"""Headline benchmark: distinct states/sec on the BASELINE.md metric
config (tlc_membership raft.cfg at Server=3, MaxTerm=3, MaxLogLen=3,
ElectionSafety checked — BASELINE.json config #2).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "states/sec", "vs_baseline": N}

``vs_baseline`` compares the TPU engine against the repo's native C++
multi-threaded checker (native/raft_checker.cc) measured on this
machine over the SAME budgeted run — the machine-measured stand-in for
the reference's "TLC -workers N" baseline (the reference publishes no
numbers — BASELINE.md).  Both engines run the same level-granular
budget and land on the identical distinct-state count (the metric
config's full space exceeds single-chip HBM at the current 620B/state
row; BASELINE.md records the exhaustive configs separately).

Correctness gate: before timing, the engine is differentially checked
against the Python oracle on a micro config; a mismatch zeroes the
score (guards against accelerator-path miscompiles).
"""

import json
import os
import sys
import time

# The budget stops the run at the end of depth 18 (2,443,370 states on
# both engines).  Depth 19 needs a >4M-row level buffer, which at the
# current 620B/state exceeds single-chip HBM alongside the frontier.
BUDGET = 2_400_000
LCAP = 1 << 21
# sized so the visited table never crosses the load bound mid-run (a
# growth would rehash + retrace the fused kernels: ~100s of remote
# compile through the tunnel)
VCAP = 1 << 24


def main():
    from raft_tla_tpu import native
    from raft_tla_tpu.cfg.parser import load_model
    from raft_tla_tpu.config import Bounds
    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.models.explore import explore

    # -- correctness gate (micro config, fast) --------------------------
    micro = load_model("/root/reference/tlc_membership/raft.cfg",
                       bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                                          max_client_requests=1))
    micro = micro.with_(n_servers=2, init_servers=(0, 1), values=(1,),
                        max_inflight_override=4)
    eng_micro = Engine(micro, chunk=256, store_states=False)
    got = eng_micro.check()
    want = explore(micro)
    gate_ok = (got.distinct_states == want.distinct_states and
               got.depth == want.depth and
               got.generated_states == want.generated_states and
               len(got.violations) == len(want.violations))
    if not gate_ok:
        print(json.dumps({
            "metric": "distinct_states_per_sec_tlc_membership_S3_T3_L3",
            "value": 0.0, "unit": "states/sec", "vs_baseline": 0.0,
            "detail": {"correctness_gate": False,
                       "micro_engine": int(got.distinct_states),
                       "micro_oracle": int(want.distinct_states)}}))
        return

    # -- metric config #2 ----------------------------------------------
    # MaxTerm=3 <=> max_timeouts=2 (MaxTerms = MaxTimeouts+1, raft.tla:27)
    cfg = load_model("/root/reference/tlc_membership/raft.cfg",
                     bounds=Bounds.make(max_log_length=3, max_timeouts=2,
                                        max_client_requests=3))
    cfg = cfg.with_(invariants=("ElectionSafety",))

    budget = int(float(sys.argv[1])) if len(sys.argv) > 1 else BUDGET

    # -- CPU baseline: the native multi-threaded checker ----------------
    threads = os.cpu_count() or 8
    nat = native.check(cfg, threads=threads, max_states=budget)
    nat_rate = nat.states_per_sec

    # -- TPU engine, same budget ----------------------------------------
    eng = Engine(cfg, chunk=2048, store_states=False, lcap=LCAP, vcap=VCAP)
    t_compile = time.time()
    eng.check(max_depth=2)                      # warm the jit caches
    t_compile = time.time() - t_compile
    t0 = time.time()
    r = eng.check(max_states=budget)
    secs = time.time() - t0
    rate = r.distinct_states / max(secs, 1e-9)

    count_ok = (r.distinct_states == nat.distinct_states and
                r.depth == nat.depth)
    gate_ok = gate_ok and count_ok

    out = {
        "metric": "distinct_states_per_sec_tlc_membership_S3_T3_L3",
        "value": round(rate if gate_ok else 0.0, 1),
        "unit": "states/sec",
        "vs_baseline": round((rate / nat_rate) if gate_ok else 0.0, 2),
        "detail": {
            "distinct_states": int(r.distinct_states),
            "depth": int(r.depth),
            "seconds": round(secs, 2),
            "compile_seconds": round(t_compile, 1),
            "violations": len(r.violations),
            "overflow_faults": int(r.overflow_faults),
            "baseline_native_states_per_sec": round(nat_rate, 1),
            "baseline_native_seconds": round(nat.seconds, 2),
            "baseline_native_threads": threads,
            "correctness_gate": bool(gate_ok),
            "counts_match_native": bool(count_ok),
            "exhausted": bool(r.distinct_states < budget),
            # the dedup-exhaustiveness claim's collision bound
            # (64-bit fingerprints; ADVICE r1, SURVEY §7.4 pt 4)
            "expected_fp_collisions": float(
                r.distinct_states ** 2 / 2.0 ** 65),
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
