"""Headline benchmark: distinct states/sec on the BASELINE.md metric
config (tlc_membership raft.cfg at Server=3, MaxTerm=3, MaxLogLen=3,
ElectionSafety checked — BASELINE.json config #2).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "states/sec", "vs_baseline": N}

``vs_baseline`` compares the TPU engine against the repo's native C++
checker (native/raft_checker.cc) measured on this machine over the
SAME depth-exact run — the machine-measured stand-in for the
reference's "TLC -workers N" baseline (the reference publishes no
numbers — BASELINE.md).  Both engines run level-exact to depth 19
(7,619,299 states — the deepest level whose buffers fit single-chip
HBM; BASELINE.md "round 3" section measures the exhaustion wall) and
must land on the identical distinct-state count.

Correctness gate: before timing, the engine is differentially checked
against the Python oracle on a micro config; a mismatch zeroes the
score (guards against accelerator-path miscompiles).

Perf floor (BENCH_FLOOR.json): rounds 1->2->3 measured 68x and 3.5x
rate swings, so a silent regression would otherwise ship green.  A run
below warn_frac x best-recorded-rate is flagged in detail.perf_floor;
below hard_frac x best (under the measured tunnel noise band) the
score is zeroed.  A new best rewrites the floor file.
"""

import json
import os
import sys
import time

# Depth-exact headline: both engines run the full space to depth 19.
# Level-20 frontiers (~25M rows) exceed single-chip HBM — BASELINE.md.
MAX_DEPTH = 19
LCAP = 3 << 21            # ≥ the 5.18M-row depth-19 level, no growth
VCAP = 1 << 25            # 7.62M keys at a 23% load factor


def perf_floor(rate, max_depth, plat, floor_path, gate_ok=True,
               allow_bump=True, key="tlc_membership_S3_T3_L3",
               headline_depth=None, bump_source="bench.py auto-bump"):
    """Perf regression floor (VERDICT r3 #5, extended to per-config
    rows in r5 — VERDICT r4 #6; tests/test_bench.py).

    Returns (floor_info dict or None, zero_score bool).  Only applies
    to the recorded run shape on the recorded machine class — a
    shallower run pays proportionally more per-level dispatch/compile
    and its rate isn't comparable.  A new best (gate passing, >2% up)
    rewrites the floor file so the floor ratchets with the engine."""
    if headline_depth is None:
        headline_depth = MAX_DEPTH
    try:
        fl = json.load(open(floor_path))[key]
    except (OSError, KeyError, ValueError):
        return None, False
    if not str(plat).upper().startswith(fl["platform_prefix"].upper()):
        return {"status": f"skipped (platform {plat!r})"}, False
    if max_depth != headline_depth:
        return {"status": "skipped (non-headline depth)"}, False
    best = float(fl["best_states_per_sec"])
    warn, hard = best * fl["warn_frac"], best * fl["hard_frac"]
    status = ("ok" if rate >= warn else
              "warn" if rate >= hard else "hard")
    info = {"best_states_per_sec": best, "warn_below": round(warn, 1),
            "hard_below": round(hard, 1), "status": status}
    if allow_bump and gate_ok and rate > best * 1.02:
        data = json.load(open(floor_path))
        data[key]["best_states_per_sec"] = round(rate, 1)
        data[key]["source"] = bump_source
        # write-then-rename: a floor file truncated by a mid-dump kill
        # would silently DISABLE the regression gate on every later run
        # (the loader treats unreadable as no-floor)
        tmp = floor_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(data, fh, indent=2)
        os.replace(tmp, floor_path)
    return info, status == "hard"


def _burst_ab(out_path):
    """Fused-dispatch A/B (tools/bench_sim.py idiom): the same micro
    space checked with the multi-level burst ON vs OFF, recording a
    dispatches-per-level metric — host level-sync round trips per BFS
    level, counting each burst device call (burst_dispatches counts
    every call, committing or bailing, as exactly one round trip) plus
    one per level the per-level driver ran.  This is the
    dispatch-floor metric the burst exists to cut (ROADMAP open items
    #3/#4: the tunneled runtime pays ~172 ms per sync).  Counts are
    correctness-gated: a mismatch labels the file failed.  On this
    CPU-only container the rows are an honest CPU fallback, exactly as
    BENCH_r06.json labels the sim figures — the dispatch COUNTS are
    platform-independent; only the seconds are not.

    Round 8: each run carries an obs SpanRecorder, and the row records
    ``phase_seconds`` (per-span totals: burst_dispatch /
    level_dispatch / harvest / archive_io / compile) so the A/B delta
    attributes to dispatch vs compute vs harvest instead of one
    end-to-end number — the file is the BENCH_r08 round."""
    import jax

    from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.obs import Obs, SpanRecorder

    micro = ModelConfig(
        n_servers=2, init_servers=(0, 1), values=(1,),
        next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
        bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                           max_client_requests=1))
    rows, counts = {}, {}
    for label, burst in (("burst_off", False), ("burst_on", True)):
        eng = Engine(micro, chunk=256, store_states=False, burst=burst)
        rec = SpanRecorder()
        obs = Obs(spans=rec)
        with obs.span("compile"):
            eng.check(max_depth=2)               # warm the jit caches
        t0 = time.perf_counter()
        r = eng.check(obs=obs)
        secs = time.perf_counter() - t0
        level_syncs = r.burst_dispatches + (r.depth - r.levels_fused)
        rows[label] = {
            "distinct_states": int(r.distinct_states),
            "depth": int(r.depth),
            "levels_fused": int(r.levels_fused),
            "burst_dispatches": int(r.burst_dispatches),
            "burst_bailouts": int(r.burst_bailouts),
            "level_syncs": int(level_syncs),
            "dispatches_per_level": round(
                level_syncs / max(r.depth, 1), 3),
            "seconds": round(secs, 2),
            "states_per_sec": round(
                r.distinct_states / max(secs, 1e-9), 1),
            # per-phase span totals (obs/spans): the A/B delta
            # attributes to dispatch vs compute vs harvest
            "phase_seconds": {nm: t["seconds"]
                              for nm, t in rec.totals().items()},
            "phase_counts": {nm: t["count"]
                             for nm, t in rec.totals().items()},
        }
        counts[label] = (r.distinct_states, r.depth,
                         tuple(r.level_sizes))
    identical = counts["burst_on"] == counts["burst_off"]
    out = {
        "bench": "fused multi-level dispatch A/B with per-phase span "
                 "totals (bench.py, BENCH_r08 round)",
        "platform": jax.default_backend(),
        "honest_label": (
            "CPU-only fallback: this container has no TPU; the "
            "dispatch/level counts are platform-independent, the "
            "seconds are XLA:CPU" if jax.default_backend() == "cpu"
            else "TPU-measured"),
        "status": ("ok" if identical else
                   "FAILED: burst counts diverge from the per-level "
                   "driver — the perf rows are meaningless"),
        "counts_identical": identical,
        "rows": rows,
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmp, out_path)
    return out


def _matmul_ab(out_path):
    """MXU-native expansion A/B (BENCH round 9): the same micro space
    checked with guard_matmul ON (guard grid as int8 matmul + one-hot
    successor einsum) vs OFF (the historical vmapped lane sweep),
    counts correctness-gated identical, each run carrying the PR-7
    span recorder so the end-to-end delta attributes per phase.

    On top of the end-to-end rows, two STANDALONE micro-phases time the
    replaced primitives directly (the engine fuses them inside one jit,
    so per-phase wall-clock needs standalone dispatch):

    - ``guard_matmul`` vs ``guard_lanes`` spans — the [B, A] guard
      grid via the packed int8 matmul vs the vmapped per-lane sweep,
      jitted, on a batch of reachable states;
    - ``dedup_kernel`` vs ``dedup_probe`` spans — the Pallas
      probe/claim-insert kernel vs the lax claim walk on a
      forced-collision key block.  Off-TPU the kernel runs through the
      Pallas INTERPRETER, so its seconds measure the fallback, not the
      TPU kernel — the row is labeled honestly, and the outcome
      equality (outcomes_identical) is the platform-independent part.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tla_tpu.config import Bounds, ModelConfig
    from raft_tla_tpu.engine.bfs import Engine, U32MAX
    from raft_tla_tpu.engine.fingerprint import probe_claim_insert_pallas
    from raft_tla_tpu.obs import Obs, SpanRecorder

    micro = ModelConfig(
        n_servers=2, init_servers=(0, 1), values=(1,),
        symmetry=True, max_inflight_override=4,
        bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                           max_client_requests=1))
    rows, counts = {}, {}
    engines = {}
    for label, gm in (("guard_matmul_off", False),
                      ("guard_matmul_on", True)):
        eng = engines[label] = Engine(micro, chunk=256,
                                      store_states=False,
                                      guard_matmul=gm)
        rec = SpanRecorder()
        obs = Obs(spans=rec)
        with obs.span("compile"):
            eng.check(max_depth=2)               # warm the jit caches
        t0 = time.perf_counter()
        r = eng.check(obs=obs)
        secs = time.perf_counter() - t0
        rows[label] = {
            "distinct_states": int(r.distinct_states),
            "depth": int(r.depth),
            "guard_matmul": int(r.guard_matmul),
            "dedup_kernel": int(r.dedup_kernel),
            "levels_fused": int(r.levels_fused),
            "seconds": round(secs, 2),
            "states_per_sec": round(
                r.distinct_states / max(secs, 1e-9), 1),
            "phase_seconds": {nm: t["seconds"]
                              for nm, t in rec.totals().items()},
            "phase_counts": {nm: t["count"]
                             for nm, t in rec.totals().items()},
        }
        counts[label] = (r.distinct_states, r.depth,
                         tuple(r.level_sizes))
    identical = counts["guard_matmul_on"] == counts["guard_matmul_off"]

    # ---- standalone guard-pass micro-phase ---------------------------
    from raft_tla_tpu.models.explore import explore
    from raft_tla_tpu.ops.codec import encode, widen
    from raft_tla_tpu.ops.layout import Layout
    lay = Layout(micro)
    st = list(explore(micro, max_states=1024,
                      keep_states=True).states.values())[:256]
    batch = widen({k: np.stack([encode(lay, sv, h)[k]
                                for sv, h in st])
                   for k in encode(lay, *st[0])})
    svT = {k: jnp.moveaxis(jnp.asarray(v), 0, -1)
           for k, v in batch.items()}
    ex_on = engines["guard_matmul_on"].expander
    ex_off = engines["guard_matmul_off"].expander
    derT = jax.jit(ex_on.derived_batch_T)(svT)
    f_on = jax.jit(ex_on.guards_T_matmul)
    f_off = jax.jit(lambda s, d: ex_off.guards_T(s, d))
    ok_a = np.asarray(f_on(svT, derT))           # warm + correctness
    ok_b = np.asarray(f_off(svT, derT))
    guards_identical = bool((ok_a == ok_b).all())
    rec2 = SpanRecorder()
    REPS = 20
    with rec2.span("guard_matmul"):
        for _ in range(REPS):
            f_on(svT, derT)[0].block_until_ready()
    with rec2.span("guard_lanes"):
        for _ in range(REPS):
            f_off(svT, derT)[0].block_until_ready()

    # ---- standalone dedup micro-phase (forced collisions) ------------
    eng = engines["guard_matmul_on"]
    W = eng.W
    rng = np.random.RandomState(11)
    VCAP, M = 1 << 12, 1 << 10
    distinct = rng.randint(0, 1 << 32, size=(M // 4, W)) \
        .astype(np.uint32)
    keys_np = distinct[rng.randint(0, M // 4, size=M)]
    keys = tuple(jnp.asarray(keys_np[:, w]) for w in range(W))
    live = jnp.ones((M,), bool)
    tbl0 = tuple(jnp.full((VCAP,), U32MAX) for _ in range(W))
    cl0 = jnp.full((VCAP,), U32MAX)
    ranks = jnp.arange(M, dtype=jnp.uint32)
    lax_fn = jax.jit(lambda t, c: eng._probe_insert_lax(
        t, c, keys, live, ranks))
    pal_fn = jax.jit(lambda t: probe_claim_insert_pallas(
        t, keys, live, max_rounds=eng._MAX_PROBE_ROUNDS,
        interpret=eng._dedup_interpret))
    outA = lax_fn(tbl0, cl0)                     # warm both
    outB = pal_fn(tbl0)
    same = bool(np.array_equal(np.asarray(outA[2]),
                               np.asarray(outB[1])) and
                all(np.array_equal(np.asarray(outA[0][w]),
                                   np.asarray(outB[0][w]))
                    for w in range(W)))
    DREPS = 5
    with rec2.span("dedup_probe"):
        for _ in range(DREPS):
            lax_fn(tbl0, cl0)[0][0].block_until_ready()
    with rec2.span("dedup_kernel"):
        for _ in range(DREPS):
            pal_fn(tbl0)[0][0].block_until_ready()
    micro_phase = {nm: {"seconds": t["seconds"], "count": t["count"]}
                   for nm, t in rec2.totals().items()}

    plat = jax.default_backend()
    out = {
        "bench": "MXU-native expansion A/B with per-phase span totals "
                 "(bench.py, BENCH_r09 round)",
        "platform": plat,
        "honest_label": (
            "CPU-only fallback: this container has no TPU — the count/"
            "outcome identities are platform-independent; the seconds "
            "are XLA:CPU, and the dedup_kernel micro-phase runs the "
            "Pallas INTERPRETER (the CPU fallback), not the compiled "
            "TPU kernel" if plat == "cpu" else "TPU-measured"),
        "status": ("ok" if identical and guards_identical and same else
                   "FAILED: guard-matmul path diverges from the lane "
                   "path — the perf rows are meaningless"),
        "counts_identical": identical,
        "guard_grid_identical": guards_identical,
        "dedup_outcomes_identical": same,
        "rows": rows,
        "micro_phase_spans": micro_phase,
        "micro_phase_note": (
            "guard_matmul/guard_lanes: 20 jitted dispatches of the "
            "[256-state x lane-grid] guard pass each; dedup_kernel/"
            "dedup_probe: 5 dispatches of a 1024-key forced-collision "
            "claim-insert against a 4096-slot table each"),
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmp, out_path)
    return out


def _delta_ab(out_path):
    """Delta-matmul successor-generation A/B (BENCH round 11, ROADMAP
    item 3): the same micro space checked with delta_matmul ON (every
    declared family applies as ONE batched scatter-as-matmul per
    family group) vs OFF (the per-family vmapped kernels), counts
    correctness-gated identical for raft AND paxos — the paxos pair
    doubles as the zero-new-kernels proof (all four families run from
    declarations alone).

    On top of the end-to-end rows, a STANDALONE expansion-phase
    micro-pair times the replaced primitive directly on config #2's
    lane mix (the engines fuse materialize inside one jit, so
    per-phase wall-clock needs standalone dispatch):

    - ``delta_apply`` — jitted ``Expander.materialize`` with the group
      delta matmul compiled (int32 einsum blocks);
    - ``delta_kernels`` — the identical call with the per-family
      kernel path.

    Off-TPU the einsum blocks run on XLA:CPU — the seconds measure the
    fallback, not the matrix unit; the row is labeled honestly and the
    candidate-buffer identity is the platform-independent part.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tla_tpu.config import Bounds, ModelConfig
    from raft_tla_tpu.cfg.parser import load_model
    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.engine.expand import Expander
    from raft_tla_tpu.obs import Obs, SpanRecorder
    from raft_tla_tpu.spec import get_spec
    from raft_tla_tpu.spec.paxos.config import PaxosConfig

    micro = ModelConfig(
        n_servers=2, init_servers=(0, 1), values=(1,),
        symmetry=True, max_inflight_override=4,
        bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                           max_client_requests=1))
    rows, counts = {}, {}
    for label, dm in (("delta_matmul_off", False),
                      ("delta_matmul_on", True)):
        eng = Engine(micro, chunk=256, store_states=False,
                     delta_matmul=dm)
        rec = SpanRecorder()
        obs = Obs(spans=rec)
        with obs.span("compile"):
            eng.check(max_depth=2)               # warm the jit caches
        t0 = time.perf_counter()
        r = eng.check(obs=obs)
        secs = time.perf_counter() - t0
        rows[label] = {
            "distinct_states": int(r.distinct_states),
            "depth": int(r.depth),
            "delta_matmul": int(r.delta_matmul),
            "seconds": round(secs, 2),
            "states_per_sec": round(
                r.distinct_states / max(secs, 1e-9), 1),
            "phase_seconds": {nm: t["seconds"]
                              for nm, t in rec.totals().items()},
        }
        counts[label] = (r.distinct_states, r.depth,
                         tuple(r.level_sizes))
    identical = counts["delta_matmul_on"] == counts["delta_matmul_off"]

    # paxos end-to-end pair: declarations-only expansion, full space
    pax_counts = {}
    for label, dm in (("off", False), ("on", True)):
        r = Engine(PaxosConfig(), chunk=128, store_states=False,
                   delta_matmul=dm).check()
        pax_counts[label] = (r.distinct_states, r.depth,
                            tuple(r.level_sizes))
    pax_identical = pax_counts["on"] == pax_counts["off"]

    # ---- standalone expansion-phase micro-pair (config #2 lane mix) --
    # the repo-local cfg twin + config #2's bounds reproduce the
    # headline config's LANE GRID exactly; the batch is depth-limited
    # reachable states (the phase timing needs the mix, not the space)
    cfg2 = load_model(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "configs",
        "tlc_membership", "raft.cfg"), bounds=Bounds.make(
        max_log_length=3, max_timeouts=2, max_client_requests=3))
    cfg2 = cfg2.with_(invariants=("ElectionSafety",))
    ir = get_spec("raft")
    lay = ir.make_layout(cfg2)
    st = list(ir.oracle_explore(cfg2, max_states=1024,
                                keep_states=True).states.values())[:256]
    enc = [ir.encode(lay, sv, h) for sv, h in st]    # encode each ONCE
    batch = ir.widen({k: np.stack([e[k] for e in enc])
                      for k in enc[0]})
    svT = {k: jnp.moveaxis(jnp.asarray(v), 0, -1)
           for k, v in batch.items()}
    ex_on = Expander(cfg2, delta_matmul=True)
    ex_off = Expander(cfg2, delta_matmul=False)
    derT = jax.jit(ex_on.derived_batch_T)(svT)
    ok = np.asarray(jax.jit(ex_on.guards_T)(svT, derT))
    B = ok.shape[0]
    okf = jnp.asarray(ok.reshape(-1))
    FCAP = int(ok.sum()) + 8
    epos = jnp.where(okf, jnp.cumsum(okf.astype(jnp.int32)) - 1, FCAP)
    caps = ex_on.default_fam_caps(B)
    f_on = jax.jit(lambda s, d: ex_on.materialize(
        s, d, okf, epos, FCAP, caps))
    f_off = jax.jit(lambda s, d: ex_off.materialize(
        s, d, okf, epos, FCAP, caps))
    c_on, _x1 = f_on(svT, derT)                  # warm + correctness
    c_off, _x2 = f_off(svT, derT)
    n_e = int(ok.sum())
    cands_identical = all(
        np.array_equal(np.asarray(c_on[k])[..., :n_e],
                       np.asarray(c_off[k])[..., :n_e])
        for k in c_on)
    rec2 = SpanRecorder()
    REPS = 10
    with rec2.span("delta_apply"):
        for _ in range(REPS):
            f_on(svT, derT)[0]["ctr"].block_until_ready()
    with rec2.span("delta_kernels"):
        for _ in range(REPS):
            f_off(svT, derT)[0]["ctr"].block_until_ready()
    micro_phase = {nm: {"seconds": t["seconds"], "count": t["count"]}
                   for nm, t in rec2.totals().items()}

    plat = jax.default_backend()
    ok_all = identical and pax_identical and cands_identical
    out = {
        "bench": "delta-matmul successor generation A/B with "
                 "expansion-phase span totals (bench.py, BENCH_r11 "
                 "round)",
        "platform": plat,
        "honest_label": (
            "CPU-only fallback: this container has no TPU — the "
            "count/candidate identities are platform-independent; the "
            "delta_apply seconds time the off-TPU lowering (static "
            "gathers + segment scatter-add, bit-identical buffers), "
            "NOT the MXU einsum blocks a TPU runs"
            if plat == "cpu" else "TPU-measured"),
        "status": ("ok" if ok_all else
                   "FAILED: delta-matmul path diverges from the "
                   "kernel path — the perf rows are meaningless"),
        "counts_identical": identical,
        "paxos_counts_identical": pax_identical,
        "paxos_zero_new_kernels": True,
        "candidates_identical": cands_identical,
        "delta_families_raft": list(ex_on.delta_family_names),
        "rows": rows,
        "expansion_phase_spans": micro_phase,
        "expansion_phase_note": (
            f"delta_apply/delta_kernels: {REPS} jitted materialize "
            f"dispatches each over a 256-state reachable batch on "
            f"config #2's lane mix ({ex_on.n_lanes} lanes, "
            f"{n_e} enabled)"),
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmp, out_path)
    return out


def _batch_ab(out_path):
    """Multi-tenant batch A/B (BENCH round 10, ROADMAP 2b): K=4 small
    jobs — the same micro config under four different depth gates, the
    serving layer's bread-and-butter repeat-tenant shape — run
    sequentially (one engine per job: K compiles, K dispatch chains)
    vs batched (ONE bucket engine, ONE job-vmapped device program,
    per-job state on a leading [J] axis).  Records compile count,
    dispatch count and wall-clock per job for both modes under the
    shared correctness gate: every per-job result must be identical
    across modes or the file is labeled FAILED and the headline gate
    trips.  On this CPU-only container the rows are an honest CPU
    fallback (the compile/dispatch COUNTS are platform-independent;
    the seconds are XLA:CPU), as in BENCH_r05-r09."""
    import jax

    from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
    from raft_tla_tpu.obs import Obs, SpanRecorder
    from raft_tla_tpu.serve import Job, run_jobs

    micro = ModelConfig(
        n_servers=2, init_servers=(0, 1), values=(1,),
        next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
        bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                           max_client_requests=1))
    DEPTHS = (3, 4, 5, 6)
    K = len(DEPTHS)

    def mk_jobs():
        return [Job(micro, max_depth=d, label=f"d{d}") for d in DEPTHS]

    rows, per_job, raw_secs = {}, {}, {}
    for label, seq in (("sequential", True), ("batched", False)):
        rec = SpanRecorder()
        t0 = time.perf_counter()
        rep = run_jobs(mk_jobs(), obs=Obs(spans=rec), sequential=seq)
        secs = raw_secs[label] = time.perf_counter() - t0
        per_job[label] = {
            o.job.label: (int(o.res.distinct_states),
                          int(o.res.generated_states),
                          int(o.res.depth),
                          tuple(int(x) for x in o.res.level_sizes))
            for o in rep.outcomes}
        device_dispatches = sum(
            int(o.res.burst_dispatches) +
            (int(o.res.depth) - int(o.res.levels_fused))
            for o in rep.outcomes) if seq else \
            rep.meta["batch_dispatches"]
        rows[label] = {
            "jobs": K,
            "engines_compiled": rep.meta["engines_compiled"],
            "device_dispatches": int(device_dispatches),
            "seconds": round(secs, 2),
            "seconds_per_job": round(secs / K, 2),
            "statuses": [o.status for o in rep.outcomes],
            "phase_seconds": {nm: t["seconds"]
                              for nm, t in rec.totals().items()},
            "phase_counts": {nm: t["count"]
                             for nm, t in rec.totals().items()},
        }
    identical = per_job["sequential"] == per_job["batched"]
    all_batched = all(s == "done"
                      for s in rows["batched"]["statuses"])
    # raw timings, not the 2-decimal display rounding in the rows
    speedup = raw_secs["sequential"] / max(raw_secs["batched"], 1e-9)
    out = {
        "bench": "multi-tenant batch A/B: K=4 small jobs sequential "
                 "vs one job-vmapped device program (bench.py, "
                 "BENCH_r10 round)",
        "platform": jax.default_backend(),
        "honest_label": (
            "CPU-only fallback: this container has no TPU; the "
            "compile/dispatch counts and result identities are "
            "platform-independent, the seconds are XLA:CPU"
            if jax.default_backend() == "cpu" else "TPU-measured"),
        "status": ("ok" if identical and all_batched else
                   "FAILED: batched per-job results diverge from the "
                   "sequential engines (or jobs fell back) — the perf "
                   "rows are meaningless"),
        "results_identical": identical,
        "all_jobs_batched": all_batched,
        "per_job_speedup": round(speedup, 2),
        "rows": rows,
        "per_job_counts": {lbl: list(v) for lbl, v in
                           per_job["batched"].items()},
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmp, out_path)
    return out


def _ceiling_ab(out_path):
    """Constant-ceiling serving A/B (BENCH round 12, ROADMAP item 1):
    K=4 raft jobs with DISTINCT value bounds (max_timeouts ×
    max_log_length at depth 13 — each job's reachable count differs,
    so the runtime-bounds machinery is provably live, not coincidence)
    run sequentially (K engines, K compiles) vs through ONE padded
    bucket ceiling (one engine, ONE ``bucket_compile``, per-job guard
    thresholds/lane masks/bounds as vmapped device data).  Before
    round 13 this exact job list compiled K separate buckets — the
    heterogeneous traffic missed the bucket cache entirely.

    Correctness gate: every job's (counts, level sizes) must be
    identical across modes AND the four jobs' counts must be four
    DIFFERENT numbers; otherwise the file is labeled FAILED and the
    headline gate trips.  CPU fallback labeling as in BENCH_r05+."""
    import jax

    from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
    from raft_tla_tpu.obs import Obs, SpanRecorder
    from raft_tla_tpu.serve import Job, run_jobs
    from raft_tla_tpu.spec import spec_of

    BOUNDS = ((1, 1), (1, 2), (2, 1), (2, 2))
    K = len(BOUNDS)
    cfgs = [ModelConfig(
        n_servers=2, init_servers=(0, 1), values=(1,),
        next_family=NEXT_ASYNC, symmetry=True,
        max_inflight_override=4,
        bounds=Bounds.make(max_log_length=m, max_timeouts=t,
                           max_client_requests=2))
        for m, t in BOUNDS]
    n_ceilings = len({repr(spec_of(c).serve_bucket(c)[0])
                      for c in cfgs})

    def mk_jobs():
        return [Job(c, max_depth=13, label=f"b{m}x{t}")
                for c, (m, t) in zip(cfgs, BOUNDS)]

    rows, per_job, raw_secs = {}, {}, {}
    for label, seq in (("sequential", True), ("bucketed", False)):
        rec = SpanRecorder()
        t0 = time.perf_counter()
        rep = run_jobs(mk_jobs(), obs=Obs(spans=rec), sequential=seq)
        secs = raw_secs[label] = time.perf_counter() - t0
        per_job[label] = {
            o.job.label: (int(o.res.distinct_states),
                          int(o.res.generated_states),
                          int(o.res.depth),
                          tuple(int(x) for x in o.res.level_sizes))
            for o in rep.outcomes}
        rows[label] = {
            "jobs": K,
            "engines_compiled": rep.meta["engines_compiled"],
            "buckets": rep.meta.get("buckets", 0),
            "seconds": round(secs, 2),
            "seconds_per_job": round(secs / K, 2),
            "statuses": [o.status for o in rep.outcomes],
            "phase_seconds": {nm: t["seconds"]
                              for nm, t in rec.totals().items()},
            "phase_counts": {nm: t["count"]
                             for nm, t in rec.totals().items()},
        }
    identical = per_job["sequential"] == per_job["bucketed"]
    counts = [v[0] for v in per_job["bucketed"].values()]
    discriminated = len(set(counts)) == K
    all_bucketed = all(s == "done"
                       for s in rows["bucketed"]["statuses"])
    one_compile = (n_ceilings == 1 and
                   rows["bucketed"]["engines_compiled"] == 1 and
                   rows["bucketed"]["phase_counts"].get(
                       "bucket_compile", 0) == 1)
    ok = identical and discriminated and all_bucketed and one_compile
    speedup = raw_secs["sequential"] / max(raw_secs["bucketed"], 1e-9)
    out = {
        "bench": "constant-ceiling serving A/B: K=4 heterogeneous-"
                 "bounds jobs sequential vs ONE padded bucket ceiling "
                 "(bench.py, BENCH_r12 round)",
        "platform": jax.default_backend(),
        "honest_label": (
            "CPU-only fallback: this container has no TPU; the "
            "compile counts, bucket-hit behavior and result "
            "identities are platform-independent, the seconds are "
            "XLA:CPU"
            if jax.default_backend() == "cpu" else "TPU-measured"),
        "status": ("ok" if ok else
                   "FAILED: padded-ceiling per-job results diverge "
                   "from the sequential engines, do not discriminate "
                   "by bounds, or compiled more than once — the perf "
                   "rows are meaningless"),
        "results_identical": identical,
        "bounds_discriminate": discriminated,
        "all_jobs_bucketed": all_bucketed,
        "one_bucket_one_compile": one_compile,
        "engines_compiled": {lbl: rows[lbl]["engines_compiled"]
                             for lbl in rows},
        "per_job_speedup": round(speedup, 2),
        "rows": rows,
        "per_job_counts": {lbl: list(v) for lbl, v in
                           per_job["bucketed"].items()},
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmp, out_path)
    return out


def _pjit_ab(out_path):
    """Pod-scale round A/B pair (BENCH_r13, round 14) under one
    correctness gate:

    (a) **sweep overlap** — SpillEngine ``--host-table`` with the
    double-buffered pre-sweep H2D staging ON (default) vs OFF: level
    k's partition-image uploads are issued at level start
    (``h2d_stage`` spans nested inside ``level_dispatch`` = the
    visible overlap; ``sweep_overlap`` marks each serialized upload a
    sweep skipped because its image already rode the link), so the
    upload cost leaves the sweep's critical path.  Counts must be
    bit-identical ON vs OFF and the ON run must record at least one
    prestage hit, or the file is FAILED.

    (b) **pjit vs mesh** — the whole-state NamedSharding engine
    (parallel/pjit_mesh: dedup exchange as in-program GSPMD
    collectives) vs the shard_map mesh engine (explicit all_to_all)
    on the same micro space, span totals attached.  Counts must be
    identical across both AND equal to (a)'s — one shared gate.

    CPU fallback labeling as in BENCH_r05+: on this container the
    device_put staging is a host memcpy and the collectives are
    XLA:CPU's, so the seconds are honest-fallback; the span/counter
    structure (overlap visible, hits > 0, identical counts) is the
    platform-independent content."""
    import jax

    from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
    from raft_tla_tpu.engine.spill import SpillEngine
    from raft_tla_tpu.obs import Obs, SpanRecorder
    from raft_tla_tpu.parallel.mesh import ShardedEngine
    from raft_tla_tpu.parallel.pjit_mesh import PjitShardedEngine

    micro = ModelConfig(
        n_servers=2, init_servers=(0, 1), values=(1,),
        next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
        bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                           max_client_requests=1))
    rows, keys = {}, {}

    def timed(label, eng, extra=None):
        eng.check(max_depth=2)                  # warm the jit caches
        rec = SpanRecorder()
        t0 = time.perf_counter()
        r = eng.check(obs=Obs(spans=rec))
        secs = time.perf_counter() - t0
        keys[label] = (int(r.distinct_states), int(r.depth),
                       tuple(int(x) for x in r.level_sizes),
                       int(r.generated_states))
        tot = rec.totals()
        rows[label] = {
            "distinct_states": int(r.distinct_states),
            "seconds": round(secs, 2),
            "states_per_sec": round(
                r.distinct_states / max(secs, 1e-9), 1),
            "phase_seconds": {nm: t["seconds"]
                              for nm, t in tot.items()},
            "phase_counts": {nm: t["count"] for nm, t in tot.items()},
            **(extra(eng) if extra else {}),
        }

    # (a) sweep overlap ON/OFF
    for label, stage in (("sweep_stage_off", False),
                         ("sweep_stage_on", True)):
        timed(label, SpillEngine(
            micro, chunk=64, store_states=False, seg=1 << 10,
            vcap=1 << 12, sync_every=2, host_table=True, partitions=4,
            part_cap=1 << 10, sweep_stage=stage),
            extra=lambda e: {
                "sweep_stage_hits": int(e.sweep_stage_hits),
                "sweep_stage_misses": int(e.sweep_stage_misses)})

    # (b) pjit vs mesh
    timed("mesh_shard_map", ShardedEngine(
        micro, chunk=64, store_states=False, lcap=1 << 12,
        vcap=1 << 15))
    timed("pjit_named_shardings", PjitShardedEngine(
        micro, chunk=64, store_states=False, lcap=1 << 12,
        vcap=1 << 15))

    identical = len(set(keys.values())) == 1
    on = rows["sweep_stage_on"]
    overlap_visible = (on["sweep_stage_hits"] > 0 and
                       on["phase_counts"].get("h2d_stage", 0) > 0 and
                       on["phase_counts"].get("sweep_overlap", 0) > 0)
    ok = identical and overlap_visible
    out = {
        "bench": "pod-scale round: host-table sweep-overlap ON/OFF + "
                 "pjit-vs-mesh engine spans (bench.py, BENCH_r13 "
                 "round)",
        "platform": jax.default_backend(),
        "honest_label": (
            "CPU-only fallback: this container has no TPU; the "
            "overlap structure (h2d_stage inside level_dispatch, "
            "prestage hits, identical counts) is platform-"
            "independent, the seconds are XLA:CPU and device_put is "
            "a host memcpy here — the DMA overlap this buys is a TPU "
            "measurement (standing carry-over)"
            if jax.default_backend() == "cpu" else "TPU-measured"),
        "status": ("ok" if ok else
                   "FAILED: sweep-stage/pjit counts diverge or the "
                   "overlap left no h2d_stage/sweep_overlap spans — "
                   "the perf rows are meaningless"),
        "counts_identical": identical,
        "overlap_visible": overlap_visible,
        "pjit_vs_mesh_seconds": {
            "mesh": rows["mesh_shard_map"]["seconds"],
            "pjit": rows["pjit_named_shardings"]["seconds"]},
        "rows": rows,
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmp, out_path)
    return out


def _canon_ab(out_path):
    """Orbit-sort canonicalization A/B (BENCH round 14 file, repo
    round 15): the config-#5 SHAPE — S=5 all-init, full S_5 symmetry,
    P=120 — checked depth-capped with ``--sym-canon sort`` (ONE
    argsorted canonical relabeling hashed per state, adjacent-
    transposition certificates, rare min-over-perms fallback) vs
    ``minperm`` (the historical P-fold min).  Counts must be
    bit-identical — the orbit partitions are provably equal, so any
    divergence is a miscompile and the file is FAILED.

    On top of the end-to-end rows, a STANDALONE fingerprint-phase
    micro-pair times the replaced primitive directly (the engine fuses
    hashing inside one jit, so per-phase wall-clock needs standalone
    dispatch): ``canon_sort`` vs ``canon_minperm`` — jitted
    ``fingerprint_batch_T`` over the same reachable 256-state batch.
    The partition induced by the two modes' values must be identical
    (the VALUES themselves differ by design: the sort hash is salted
    into a disjoint codomain so cross-mode tables can never alias).
    At P=120 the sort path does ~1 hash + 1 argsort + S-1 certificate
    probes where minperm does 120 masked hashes; the round claims
    >=3x on this phase and the file records whether the claim held.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.engine.fingerprint import Fingerprinter
    from raft_tla_tpu.models.explore import explore
    from raft_tla_tpu.obs import Obs, SpanRecorder
    from raft_tla_tpu.ops.codec import encode, widen
    from raft_tla_tpu.ops.layout import Layout

    cfg5 = ModelConfig(
        n_servers=5, init_servers=(0, 1, 2, 3, 4), values=(1,),
        next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
        bounds=Bounds.make(max_log_length=2, max_timeouts=1,
                           max_client_requests=1))
    DEPTH = 4
    rows, counts = {}, {}
    for label, mode in (("minperm", "minperm"), ("sort", "sort")):
        eng = Engine(cfg5, chunk=256, store_states=False,
                     sym_canon=mode)
        rec = SpanRecorder()
        obs = Obs(spans=rec)
        with obs.span("compile"):
            eng.check(max_depth=2)               # warm the jit caches
        t0 = time.perf_counter()
        r = eng.check(max_depth=DEPTH, obs=obs)
        secs = time.perf_counter() - t0
        rows[label] = {
            "distinct_states": int(r.distinct_states),
            "depth": int(r.depth),
            "sym_canon": int(r.sym_canon),
            "seconds": round(secs, 2),
            "states_per_sec": round(
                r.distinct_states / max(secs, 1e-9), 1),
            "phase_seconds": {nm: t["seconds"]
                              for nm, t in rec.totals().items()},
        }
        counts[label] = (r.distinct_states, r.generated_states,
                         r.depth, tuple(r.level_sizes))
    identical = counts["sort"] == counts["minperm"]
    flags_ok = (rows["sort"]["sym_canon"] == 1 and
                rows["minperm"]["sym_canon"] == 0)

    # ---- standalone fingerprint-phase micro-pair ---------------------
    lay = Layout(cfg5)
    st = list(explore(cfg5, max_states=2048,
                      keep_states=True).states.values())[:512]
    batch = widen({k: np.stack([encode(lay, sv, h)[k]
                                for sv, h in st])
                   for k in encode(lay, *st[0])})
    svT = {k: jnp.moveaxis(jnp.asarray(v), 0, -1)
           for k, v in batch.items()}
    fprs = {m: Fingerprinter(cfg5, sym_canon=m)
            for m in ("sort", "minperm")}
    fns = {m: jax.jit(f.fingerprint_batch_T) for m, f in fprs.items()}
    fp = {m: np.asarray(fn(svT)) for m, fn in fns.items()}   # warm

    def gids(a):
        """[n_streams, B] values -> first-occurrence group ids: the
        induced partition, comparable across disjoint codomains."""
        seen = {}
        return [seen.setdefault(tuple(int(a[t, b])
                                      for t in range(a.shape[0])), b)
                for b in range(a.shape[1])]

    partition_identical = gids(fp["sort"]) == gids(fp["minperm"])
    hard = fprs["sort"].sort_debug(batch)["hard"]
    rec2 = SpanRecorder()
    REPS = 20
    phase_secs = {}
    for m in ("sort", "minperm"):
        with rec2.span(f"canon_{m}"):
            for _ in range(REPS):
                fns[m](svT)[0].block_until_ready()
        phase_secs[m] = rec2.totals()[f"canon_{m}"]["seconds"]
    speedup = phase_secs["minperm"] / max(phase_secs["sort"], 1e-9)
    speedup_3x = speedup >= 3.0

    plat = jax.default_backend()
    ok = identical and flags_ok and partition_identical and speedup_3x
    out = {
        "bench": "orbit-sort canonicalization A/B: one argsorted "
                 "canonical hash vs the P=120 min-over-perms "
                 "(bench.py, BENCH_r14 round)",
        "platform": plat,
        "honest_label": (
            "CPU-only fallback: this container has no TPU — the "
            "count/partition identities are platform-independent; the "
            "canon_sort seconds time XLA:CPU's argsort+gather "
            "lowering, NOT the TPU sort/gather units, so the phase "
            "ratio is the fallback's, measured against the same "
            "fallback's 120 masked hashes"
            if plat == "cpu" else "TPU-measured"),
        "status": ("ok" if ok else
                   "FAILED: sort-mode counts/partition diverge from "
                   "min-over-perms (or the claimed fingerprint-phase "
                   "speedup did not hold) — the perf rows are "
                   "meaningless"),
        "counts_identical": identical,
        "mode_flags_stamped": flags_ok,
        "partition_identical": partition_identical,
        "perm_group_size": len(fprs["minperm"].sigmas),
        "hard_fallback_rate": round(float(np.mean(hard)), 4),
        "fingerprint_phase_seconds": {
            m: round(s, 4) for m, s in phase_secs.items()},
        "fingerprint_phase_speedup": round(speedup, 2),
        "speedup_at_least_3x": speedup_3x,
        "fingerprint_phase_note": (
            f"canon_sort/canon_minperm: {REPS} jitted "
            "fingerprint_batch_T dispatches each over the same "
            "512-state reachable batch at S=5, P=120"),
        "rows": rows,
    }
    tmp = out_path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmp, out_path)
    return out


def _wave_mesh_ab(out_path):
    """Mesh-sharded serving wave A/B (BENCH_r15, round 16): the SAME
    6-job raft wave through ``cli batch`` on ONE device vs a 4-virtual-
    device job mesh (``--wave-mesh 4``), under the shared correctness
    gate (per-job counts/level sizes bit-identical across modes, or
    the file is FAILED).

    Subprocess runs, not in-process: the job mesh needs >1 local
    device and this process's jax initialized with the default 1 —
    both runs force ``--xla_force_host_platform_device_count=4`` so
    the device count itself is identical and only ``--wave-mesh``
    differs.  Both record into one ``--registry``, so the A/B is an
    ``obs diff`` verdict (clean = identical counts) and the rows carry
    the records' ``batched_dispatch`` span totals plus per-job wall
    seconds from ``--stats-json``.

    Honest CPU-fallback label: 4 virtual CPU devices share the SAME
    physical cores, so the mesh row's seconds measure sharding
    overhead, not speedup — the throughput claim (D devices x 8 lanes
    per dispatch) is a TPU-slice measurement; what this file pins on
    every container is bit-exactness, occupancy accounting and the
    dispatch-count invariance."""
    import shutil
    import subprocess
    import tempfile

    import jax

    from raft_tla_tpu.obs.registry import RunRegistry
    from raft_tla_tpu.obs.report import diff_runs

    repo = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="wave_mesh_ab_")
    jobs_path = os.path.join(tmp, "jobs.jsonl")
    with open(jobs_path, "w") as fh:
        for d in (3, 4, 5, 6, 7, 8):
            fh.write(json.dumps({
                "spec": "raft",
                "config": "configs/tlc_membership/raft.cfg",
                "overrides": {
                    "servers": 2, "values": [1], "max_inflight": 4,
                    "next": "NextAsync",
                    "bounds": {"max_log_length": 1, "max_timeouts": 1,
                               "max_client_requests": 1}},
                "max_depth": d, "label": f"r{d}"}) + "\n")
    registry = os.path.join(tmp, "registry")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                          " --xla_force_host_platform_device_count=4"
                          ).strip())
    rows, keys, run_ids = {}, {}, {}
    try:
        for label, mesh in (("single_device", "off"),
                            ("mesh_4dev", "4")):
            stats = os.path.join(tmp, label + ".json")
            t0 = time.perf_counter()
            p = subprocess.run(
                [sys.executable, "-m", "raft_tla_tpu", "batch",
                 "--jobs", jobs_path, "--wave-mesh", mesh,
                 "--stats-json", stats, "--registry", registry],
                capture_output=True, text=True, cwd=repo, env=env,
                timeout=900)
            wall = time.perf_counter() - t0
            if p.returncode != 0:
                out = {"bench": "mesh-sharded serving wave A/B "
                                "(bench.py, BENCH_r15 round)",
                       "status": f"FAILED: cli batch --wave-mesh "
                                 f"{mesh} exited {p.returncode}: "
                                 f"{p.stderr[-500:]}"}
                tmpf = out_path + ".tmp"
                with open(tmpf, "w") as fh:
                    json.dump(out, fh, indent=1)
                os.replace(tmpf, out_path)
                return out
            with open(stats) as fh:
                payload = json.load(fh)
            summary, jrows = payload["summary"], payload["jobs"]
            keys[label] = tuple(
                (r["label"], r["distinct_states"],
                 r["generated_states"], r["depth"],
                 tuple(r["level_sizes"])) for r in jrows)
            reg = RunRegistry(registry)
            fresh = [i for i in reg.run_ids()
                     if i not in run_ids.values()]
            run_ids[label] = fresh[-1]
            rec = reg.load(run_ids[label])
            spans = rec.get("spans") or {}
            disp = spans.get("batched_dispatch") or {}
            rows[label] = {
                "run_id": run_ids[label],
                "wall_seconds": round(wall, 2),
                "wave_devices": int(summary.get("wave_devices", 0)),
                "wave_lanes": int(summary.get("wave_lanes", 0)),
                "batch_dispatches":
                    int(summary.get("batch_dispatches", 0)),
                "batched_dispatch_span": {
                    "count": int(disp.get("count", 0)),
                    "seconds": round(float(disp.get("seconds", 0.0)),
                                     4)},
                "bucket_compile_seconds": round(float(
                    (spans.get("bucket_compile") or {})
                    .get("seconds", 0.0)), 4),
                "per_job_seconds": {
                    r["label"]: round(float(r.get("seconds", 0.0)), 4)
                    for r in jrows},
            }
        reg = RunRegistry(registry)
        diff = diff_runs(reg.load(run_ids["single_device"]),
                         reg.load(run_ids["mesh_4dev"]))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    identical = len(set(keys.values())) == 1
    occupancy_ok = (rows["mesh_4dev"]["wave_devices"] == 4 and
                    rows["single_device"]["wave_devices"] == 1 and
                    rows["mesh_4dev"]["batch_dispatches"] ==
                    rows["single_device"]["batch_dispatches"])
    diff_ok = diff["verdict"] in ("clean", "mode_drift")
    ok = identical and occupancy_ok and diff_ok
    out = {
        "bench": "mesh-sharded serving wave A/B: one 6-job raft wave, "
                 "--wave-mesh off vs 4 virtual devices (bench.py, "
                 "BENCH_r15 round)",
        "platform": jax.default_backend(),
        "honest_label": (
            "CPU-only fallback: the 4 'devices' are virtual XLA:CPU "
            "devices on the SAME physical cores, so the mesh row's "
            "seconds measure GSPMD sharding overhead, not speedup — "
            "the D-devices-x-8-lanes throughput multiplier is a TPU-"
            "slice measurement; bit-exactness, wave occupancy "
            "accounting and dispatch-count invariance are the "
            "platform-independent content"
            if jax.default_backend() == "cpu" else "TPU-measured"),
        "status": ("ok" if ok else
                   "FAILED: mesh-wave counts diverge from the single-"
                   "device wave (or the occupancy/diff verdict is "
                   "wrong) — the perf rows are meaningless"),
        "counts_identical": identical,
        "occupancy_ok": occupancy_ok,
        "obs_diff_verdict": diff["verdict"],
        "registry_run_ids": run_ids,
        "rows": rows,
    }
    tmpf = out_path + ".tmp"
    with open(tmpf, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmpf, out_path)
    return out


def _wave_mesh2d_ab(out_path):
    """2-D wave-mesh A/B (BENCH_r16, round 17): one OVERSIZED tenant
    (a full-space micro raft job) plus three small fills through
    ``cli batch`` on one device vs the ``--wave-mesh 2x2`` jobs x
    state grid on 4 virtual devices, under the shared correctness
    gate (per-job counts/level sizes bit-identical across modes, or
    the file is FAILED).

    The 2x2 grid is the round-17 claim: the big tenant's visited
    slots/frontier rings split across the state axis while the fills
    pack the job axis — same wave, no eviction of the small jobs.
    Both runs record into one ``--registry`` so the A/B is an ``obs
    diff`` verdict (clean = identical counts), and the grid row must
    stamp ``wave_state_shards=2`` next to ``wave_devices=4``.

    Honest CPU-fallback label: 4 virtual CPU devices share the SAME
    physical cores, so the grid row's seconds measure GSPMD resharding
    overhead, not speedup — the per-device memory-ceiling relief
    (VCAP/S slots per device) is a TPU-slice claim; what this file
    pins on every container is bit-exactness, the state-shard
    occupancy accounting and the dispatch-count invariance."""
    import shutil
    import subprocess
    import tempfile

    import jax

    from raft_tla_tpu.obs.registry import RunRegistry
    from raft_tla_tpu.obs.report import diff_runs

    repo = os.path.dirname(os.path.abspath(__file__))
    tmp = tempfile.mkdtemp(prefix="wave_mesh2d_ab_")
    jobs_path = os.path.join(tmp, "jobs.jsonl")
    ovr = {"servers": 2, "values": [1], "max_inflight": 4,
           "next": "NextAsync",
           "bounds": {"max_log_length": 1, "max_timeouts": 1,
                      "max_client_requests": 1}}
    with open(jobs_path, "w") as fh:
        # the oversized tenant: the full micro space, deepest job in
        # the wave by far...
        fh.write(json.dumps({
            "spec": "raft",
            "config": "configs/tlc_membership/raft.cfg",
            "overrides": ovr, "max_depth": 13,
            "label": "big"}) + "\n")
        # ...plus small fills sharing its bucket's job axis
        for d in (2, 3, 4):
            fh.write(json.dumps({
                "spec": "raft",
                "config": "configs/tlc_membership/raft.cfg",
                "overrides": ovr, "max_depth": d,
                "label": f"fill{d}"}) + "\n")
    registry = os.path.join(tmp, "registry")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "") +
                          " --xla_force_host_platform_device_count=4"
                          ).strip())
    rows, keys, run_ids = {}, {}, {}
    try:
        for label, mesh in (("single_device", "off"),
                            ("grid_2x2", "2x2")):
            stats = os.path.join(tmp, label + ".json")
            t0 = time.perf_counter()
            p = subprocess.run(
                [sys.executable, "-m", "raft_tla_tpu", "batch",
                 "--jobs", jobs_path, "--wave-mesh", mesh,
                 "--stats-json", stats, "--registry", registry],
                capture_output=True, text=True, cwd=repo, env=env,
                timeout=900)
            wall = time.perf_counter() - t0
            if p.returncode != 0:
                out = {"bench": "2-D wave-mesh A/B (bench.py, "
                                "BENCH_r16 round)",
                       "status": f"FAILED: cli batch --wave-mesh "
                                 f"{mesh} exited {p.returncode}: "
                                 f"{p.stderr[-500:]}"}
                tmpf = out_path + ".tmp"
                with open(tmpf, "w") as fh:
                    json.dump(out, fh, indent=1)
                os.replace(tmpf, out_path)
                return out
            with open(stats) as fh:
                payload = json.load(fh)
            summary, jrows = payload["summary"], payload["jobs"]
            keys[label] = tuple(
                (r["label"], r["distinct_states"],
                 r["generated_states"], r["depth"],
                 tuple(r["level_sizes"])) for r in jrows)
            reg = RunRegistry(registry)
            fresh = [i for i in reg.run_ids()
                     if i not in run_ids.values()]
            run_ids[label] = fresh[-1]
            rec = reg.load(run_ids[label])
            spans = rec.get("spans") or {}
            disp = spans.get("batched_dispatch") or {}
            rows[label] = {
                "run_id": run_ids[label],
                "wall_seconds": round(wall, 2),
                "wave_devices": int(summary.get("wave_devices", 0)),
                "wave_state_shards":
                    int(summary.get("wave_state_shards", 0)),
                "wave_lanes": int(summary.get("wave_lanes", 0)),
                "batch_dispatches":
                    int(summary.get("batch_dispatches", 0)),
                "batched_dispatch_span": {
                    "count": int(disp.get("count", 0)),
                    "seconds": round(float(disp.get("seconds", 0.0)),
                                     4)},
                "bucket_compile_seconds": round(float(
                    (spans.get("bucket_compile") or {})
                    .get("seconds", 0.0)), 4),
                "per_job_seconds": {
                    r["label"]: round(float(r.get("seconds", 0.0)), 4)
                    for r in jrows},
            }
        reg = RunRegistry(registry)
        diff = diff_runs(reg.load(run_ids["single_device"]),
                         reg.load(run_ids["grid_2x2"]))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    identical = len(set(keys.values())) == 1
    occupancy_ok = (rows["grid_2x2"]["wave_devices"] == 4 and
                    rows["grid_2x2"]["wave_state_shards"] == 2 and
                    rows["single_device"]["wave_devices"] == 1 and
                    rows["grid_2x2"]["batch_dispatches"] ==
                    rows["single_device"]["batch_dispatches"])
    diff_ok = diff["verdict"] in ("clean", "mode_drift")
    ok = identical and occupancy_ok and diff_ok
    out = {
        "bench": "2-D wave-mesh A/B: one oversized micro-raft tenant "
                 "+ 3 fills, --wave-mesh off vs the 2x2 jobs x state "
                 "grid on 4 virtual devices (bench.py, BENCH_r16 "
                 "round)",
        "platform": jax.default_backend(),
        "honest_label": (
            "CPU-only fallback: the 4 'devices' are virtual XLA:CPU "
            "devices on the SAME physical cores, so the grid row's "
            "seconds measure GSPMD resharding overhead, not speedup — "
            "the per-device ceiling relief (VCAP/S visited slots per "
            "device) is a TPU-slice claim; bit-exactness, state-shard "
            "occupancy accounting and dispatch-count invariance are "
            "the platform-independent content"
            if jax.default_backend() == "cpu" else "TPU-measured"),
        "status": ("ok" if ok else
                   "FAILED: 2x2 grid counts diverge from the single-"
                   "device wave (or the occupancy/diff verdict is "
                   "wrong) — the perf rows are meaningless"),
        "correctness_gate": bool(ok),
        "counts_identical": identical,
        "occupancy_ok": occupancy_ok,
        "obs_diff_verdict": diff["verdict"],
        "registry_run_ids": run_ids,
        "rows": rows,
    }
    tmpf = out_path + ".tmp"
    with open(tmpf, "w") as fh:
        json.dump(out, fh, indent=1)
    os.replace(tmpf, out_path)
    return out


def _bench_registry_record(registry_dir, headline):
    """Append one ``cmd="bench"`` record to a run registry (ISSUE 17)
    so ``cli obs ls/diff/regress`` can query bench results next to
    check runs — the headline detail's numeric fields become the
    record's counters (the parity keys obs/report.py compares)."""
    if not registry_dir:
        return
    import time as _time

    from raft_tla_tpu.obs.registry import RunRegistry, new_run_id
    from raft_tla_tpu.obs.resources import backend_fingerprint
    detail = headline.get("detail") or {}
    counters = {k: v for k, v in detail.items()
                if isinstance(v, (int, float))
                and not isinstance(v, bool)}
    RunRegistry(registry_dir).append({
        "run_id": new_run_id(), "cmd": "bench", "status": "finished",
        "finished_ts": round(_time.time(), 3),
        "metric": headline.get("metric"),
        "value": headline.get("value"),
        "counters": counters,
        "backend": backend_fingerprint(),
        "headline": headline})


def _no_reference_fallback(registry=None):
    """Containers without the reference checkout (and without the TPU)
    cannot run the headline metric at all — emit ONE honestly-labeled
    JSON line instead of a traceback, carrying the only measurement
    that IS possible here: a correctness-gated micro A/B of the spill
    engine with the host-partitioned table OFF vs ON (ISSUE 1: the
    floor must be shown still-ok both ways; on this platform the floor
    row skips by platform_prefix, and the host table defaults OFF so
    the floor-guarded paths are untouched)."""
    import jax

    from raft_tla_tpu.config import Bounds, ModelConfig, NEXT_ASYNC
    from raft_tla_tpu.engine.spill import SpillEngine
    from raft_tla_tpu.models.explore import explore

    micro = ModelConfig(
        n_servers=2, init_servers=(0, 1), values=(1,),
        next_family=NEXT_ASYNC, symmetry=True, max_inflight_override=4,
        bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                           max_client_requests=1))
    want = explore(micro)
    plat = str(jax.devices()[0].device_kind)
    floor_path = os.path.join(os.path.dirname(os.path.abspath(
        __file__)), "BENCH_FLOOR.json")
    ab = {}
    gate_ok = True
    for label, kw in (("host_table_off", {}),
                      ("host_table_on", dict(host_table=True,
                                             partitions=4,
                                             part_cap=1 << 10))):
        eng = SpillEngine(micro, chunk=64, store_states=False,
                          seg=1 << 10, vcap=1 << 12, sync_every=2, **kw)
        eng.check(max_depth=2)                   # warm the jit caches
        t0 = time.time()
        r = eng.check()
        secs = time.time() - t0
        ok = (r.distinct_states == want.distinct_states and
              r.depth == want.depth and
              r.level_sizes == want.level_sizes)
        gate_ok = gate_ok and ok
        # the run's REAL depth, never MAX_DEPTH: a micro rate vs the
        # config-2 floor would read as a bogus 'hard' regression on
        # any TPU-prefixed host that merely lacks /root/reference —
        # the non-headline-depth guard must skip it everywhere
        floor_info, _zero = perf_floor(
            r.distinct_states / max(secs, 1e-9), int(r.depth), plat,
            floor_path, gate_ok=ok, allow_bump=False,
            key="spill_config2_depth19")
        ab[label] = {
            "distinct_states": int(r.distinct_states),
            "seconds": round(secs, 2),
            "states_per_sec": round(
                r.distinct_states / max(secs, 1e-9), 1),
            "counts_match_oracle": bool(ok),
            "perf_floor": floor_info}
    burst_ab = _burst_ab(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_r08.json"))
    # the burst A/B is correctness-gated like the spill A/B: a
    # burst≡per-level mismatch fails the shared gate, not just the file
    gate_ok = gate_ok and burst_ab["counts_identical"]
    # round 9: the MXU-path A/B (guard matmul + dedup kernel) rides the
    # SAME shared correctness gate
    matmul_ab = _matmul_ab(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_r09.json"))
    gate_ok = gate_ok and matmul_ab["status"] == "ok"
    # round 10: the multi-tenant batch A/B rides the same shared gate
    batch_ab = _batch_ab(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_r10.json"))
    gate_ok = gate_ok and batch_ab["status"] == "ok"
    # round 11: the delta-matmul successor A/B rides the same gate
    delta_ab = _delta_ab(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_r11.json"))
    gate_ok = gate_ok and delta_ab["status"] == "ok"
    # round 12: the constant-ceiling serving A/B rides the same gate
    ceiling_ab = _ceiling_ab(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_r12.json"))
    gate_ok = gate_ok and ceiling_ab["status"] == "ok"
    # round 13 file (PR 14): sweep overlap + pjit-vs-mesh, same gate
    pjit_ab = _pjit_ab(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_r13.json"))
    gate_ok = gate_ok and pjit_ab["status"] == "ok"
    # round 14 file (PR 15): orbit-sort canonicalization, same gate
    canon_ab = _canon_ab(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_r14.json"))
    gate_ok = gate_ok and canon_ab["status"] == "ok"
    # round 15 file (PR 18): mesh-sharded serving waves, same gate
    wave_mesh_ab = _wave_mesh_ab(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_r15.json"))
    gate_ok = gate_ok and wave_mesh_ab["status"] == "ok"
    # round 16 file (PR 20): the 2-D jobs x state grid, same gate
    wave_mesh2d_ab = _wave_mesh2d_ab(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "BENCH_r16.json"))
    gate_ok = gate_ok and wave_mesh2d_ab["status"] == "ok"
    out = {
        "metric": "distinct_states_per_sec_tlc_membership_S3_T3_L3",
        "value": None, "unit": "states/sec", "vs_baseline": None,
        "status": "headline skipped: /root/reference cfgs and the TPU "
                  "are absent on this container; floor rows skip by "
                  "platform_prefix and BENCH_FLOOR.json is unchanged",
        "detail": {"platform": plat, "correctness_gate": bool(gate_ok),
                   "micro_spill_ab": ab,
                   "burst_ab": {
                       "written_to": "BENCH_r08.json",
                       "counts_identical":
                           burst_ab["counts_identical"],
                       "dispatches_per_level": {
                           k: v["dispatches_per_level"]
                           for k, v in burst_ab["rows"].items()}},
                   "matmul_ab": {
                       "written_to": "BENCH_r09.json",
                       "status": matmul_ab["status"],
                       "states_per_sec": {
                           k: v["states_per_sec"]
                           for k, v in matmul_ab["rows"].items()}},
                   "batch_ab": {
                       "written_to": "BENCH_r10.json",
                       "status": batch_ab["status"],
                       "per_job_speedup": batch_ab["per_job_speedup"],
                       "engines_compiled": {
                           k: v["engines_compiled"]
                           for k, v in batch_ab["rows"].items()}},
                   "delta_ab": {
                       "written_to": "BENCH_r11.json",
                       "status": delta_ab["status"],
                       "states_per_sec": {
                           k: v["states_per_sec"]
                           for k, v in delta_ab["rows"].items()}},
                   "ceiling_ab": {
                       "written_to": "BENCH_r12.json",
                       "status": ceiling_ab["status"],
                       "per_job_speedup":
                           ceiling_ab["per_job_speedup"],
                       "engines_compiled":
                           ceiling_ab["engines_compiled"]},
                   "pjit_ab": {
                       "written_to": "BENCH_r13.json",
                       "status": pjit_ab["status"],
                       "overlap_visible": pjit_ab["overlap_visible"],
                       "pjit_vs_mesh_seconds":
                           pjit_ab["pjit_vs_mesh_seconds"]},
                   "canon_ab": {
                       "written_to": "BENCH_r14.json",
                       "status": canon_ab["status"],
                       "fingerprint_phase_speedup":
                           canon_ab["fingerprint_phase_speedup"],
                       "hard_fallback_rate":
                           canon_ab["hard_fallback_rate"]},
                   "wave_mesh_ab": {
                       "written_to": "BENCH_r15.json",
                       "status": wave_mesh_ab["status"],
                       "obs_diff_verdict":
                           wave_mesh_ab.get("obs_diff_verdict"),
                       "wall_seconds": {
                           k: v["wall_seconds"]
                           for k, v in (wave_mesh_ab.get("rows") or
                                        {}).items()}},
                   "wave_mesh2d_ab": {
                       "written_to": "BENCH_r16.json",
                       "status": wave_mesh2d_ab["status"],
                       "obs_diff_verdict":
                           wave_mesh2d_ab.get("obs_diff_verdict"),
                       "wall_seconds": {
                           k: v["wall_seconds"]
                           for k, v in (wave_mesh2d_ab.get("rows") or
                                        {}).items()}}}}
    print(json.dumps(out))
    _bench_registry_record(registry, out)


def main():
    from raft_tla_tpu import native
    from raft_tla_tpu.cfg.parser import load_model
    from raft_tla_tpu.config import Bounds
    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.models.explore import explore

    # --registry parses before the reference check: the fallback path
    # (this container) records a queryable cmd="bench" row too
    argv = sys.argv[1:]
    registry = None
    if "--registry" in argv:
        i = argv.index("--registry")
        if i + 1 >= len(argv):
            raise SystemExit("--registry needs a DIR argument")
        registry = argv[i + 1]
        del argv[i:i + 2]

    # -- correctness gate (micro config, fast) --------------------------
    if not os.path.exists("/root/reference/tlc_membership/raft.cfg"):
        _no_reference_fallback(registry)
        return
    micro = load_model("/root/reference/tlc_membership/raft.cfg",
                       bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                                          max_client_requests=1))
    micro = micro.with_(n_servers=2, init_servers=(0, 1), values=(1,),
                        max_inflight_override=4)
    eng_micro = Engine(micro, chunk=256, store_states=False)
    got = eng_micro.check()
    want = explore(micro)
    gate_ok = (got.distinct_states == want.distinct_states and
               got.depth == want.depth and
               got.generated_states == want.generated_states and
               len(got.violations) == len(want.violations))
    if not gate_ok:
        print(json.dumps({
            "metric": "distinct_states_per_sec_tlc_membership_S3_T3_L3",
            "value": 0.0, "unit": "states/sec", "vs_baseline": 0.0,
            "detail": {"correctness_gate": False,
                       "micro_engine": int(got.distinct_states),
                       "micro_oracle": int(want.distinct_states)}}))
        return

    # -- metric config #2 ----------------------------------------------
    # MaxTerm=3 <=> max_timeouts=2 (MaxTerms = MaxTimeouts+1, raft.tla:27)
    cfg = load_model("/root/reference/tlc_membership/raft.cfg",
                     bounds=Bounds.make(max_log_length=3, max_timeouts=2,
                                        max_client_requests=3))
    cfg = cfg.with_(invariants=("ElectionSafety",))

    # optional overrides: `python bench.py [--max-depth N] [--chunk C]`
    # (NOTE: the round-2 positional arg was a STATE BUDGET; the metric
    # is now depth-exact, so a bare positional number is rejected to
    # avoid silently reinterpreting old invocations).  --chunk exists
    # to let the perf-floor trip be exercised deliberately.
    max_depth, chunk = MAX_DEPTH, 2048
    while argv:
        if len(argv) >= 2 and argv[0] == "--max-depth":
            max_depth = int(argv[1])
            if not 1 <= max_depth <= 64:
                raise SystemExit(f"--max-depth {max_depth}: BFS depths "
                                 "are small (the round-2 budget arg is "
                                 "gone)")
            argv = argv[2:]
        elif len(argv) >= 2 and argv[0] == "--chunk":
            chunk = int(argv[1])
            argv = argv[2:]
        else:
            raise SystemExit("usage: python bench.py [--max-depth N] "
                             "[--chunk C] [--registry DIR]   (the "
                             "metric is depth-exact now; the old "
                             "positional state budget was removed)")

    # -- CPU baseline: the native checker, same depth-exact run ---------
    threads = os.cpu_count() or 8
    nat = native.check(cfg, threads=threads, max_depth=max_depth)
    nat_rate = nat.states_per_sec

    # -- TPU engine, same depth ----------------------------------------
    # ocap pre-sized: the early nearly-all-fresh levels outgrow the
    # default chunk*4 fresh-row buffer, and the growth replay would
    # re-run a level inside the timed window
    eng = Engine(cfg, chunk=chunk, store_states=False, lcap=LCAP,
                 vcap=VCAP, ocap=1 << 14)
    t_compile = time.time()
    eng.check(max_depth=2)                      # warm the jit caches
    t_compile = time.time() - t_compile
    t0 = time.time()
    r = eng.check(max_depth=max_depth)
    secs = time.time() - t0
    rate = r.distinct_states / max(secs, 1e-9)

    count_ok = (r.distinct_states == nat.distinct_states and
                r.depth == nat.depth)
    gate_ok = gate_ok and count_ok

    # fused-dispatch A/B rides along (file only — the stdout contract
    # stays ONE JSON line); a burst≡per-level mismatch fails the
    # headline gate and blocks the floor ratchet below
    burst_ab = _burst_ab(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r08.json"))
    gate_ok = gate_ok and burst_ab["counts_identical"]
    matmul_ab = _matmul_ab(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r09.json"))
    gate_ok = gate_ok and matmul_ab["status"] == "ok"
    batch_ab = _batch_ab(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r10.json"))
    gate_ok = gate_ok and batch_ab["status"] == "ok"
    delta_ab = _delta_ab(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r11.json"))
    gate_ok = gate_ok and delta_ab["status"] == "ok"
    ceiling_ab = _ceiling_ab(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r12.json"))
    gate_ok = gate_ok and ceiling_ab["status"] == "ok"
    pjit_ab = _pjit_ab(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r13.json"))
    gate_ok = gate_ok and pjit_ab["status"] == "ok"
    canon_ab = _canon_ab(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r14.json"))
    gate_ok = gate_ok and canon_ab["status"] == "ok"
    wave_mesh_ab = _wave_mesh_ab(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r15.json"))
    gate_ok = gate_ok and wave_mesh_ab["status"] == "ok"
    wave_mesh2d_ab = _wave_mesh2d_ab(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "BENCH_r16.json"))
    gate_ok = gate_ok and wave_mesh2d_ab["status"] == "ok"

    # -- perf regression floor (BENCH_FLOOR.json; VERDICT r3 #5) --------
    # Only meaningful for the full-depth run on the recorded machine
    # class: a shallower --max-depth pays proportionally more per-level
    # dispatch/compile and would false-trip.
    import jax
    floor_info, floor_zero = perf_floor(
        rate, max_depth, str(jax.devices()[0].device_kind),
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     "BENCH_FLOOR.json"), gate_ok=gate_ok,
        # only the default-chunk headline run may ratchet the floor — a
        # hand-tuned --chunk rate would zero future default runs
        allow_bump=(chunk == 2048))

    scored = gate_ok and not floor_zero
    out = {
        "metric": "distinct_states_per_sec_tlc_membership_S3_T3_L3",
        "value": round(rate if scored else 0.0, 1),
        "unit": "states/sec",
        "vs_baseline": round((rate / nat_rate) if scored else 0.0, 2),
        "detail": {
            "distinct_states": int(r.distinct_states),
            "depth": int(r.depth),
            "depth_exact": True,      # no budget cap: full space to depth
            "seconds": round(secs, 2),
            "compile_seconds": round(t_compile, 1),
            "violations": len(r.violations),
            "overflow_faults": int(r.overflow_faults),
            "baseline_native_states_per_sec": round(nat_rate, 1),
            "baseline_native_seconds": round(nat.seconds, 2),
            "baseline_native_threads": threads,
            "correctness_gate": bool(gate_ok),
            "counts_match_native": bool(count_ok),
            "perf_floor": floor_info,
            # the full space exceeds ~1e8 states (BASELINE.md round-3
            # exhaustion-wall measurements); depth 19 is the deepest
            # single-chip level-exact run
            "exhausted": False,
            # the dedup-exhaustiveness claim's collision bound
            # (64-bit fingerprints; fp128 parity recorded in
            # baseline_runs/round3_deep.json)
            "expected_fp_collisions": float(
                r.distinct_states ** 2 / 2.0 ** 65),
        },
    }
    out["detail"]["burst_ab_counts_identical"] = \
        bool(burst_ab["counts_identical"])
    out["detail"]["matmul_ab_status"] = matmul_ab["status"]
    out["detail"]["batch_ab_status"] = batch_ab["status"]
    out["detail"]["delta_ab_status"] = delta_ab["status"]
    out["detail"]["ceiling_ab_status"] = ceiling_ab["status"]
    out["detail"]["pjit_ab_status"] = pjit_ab["status"]
    out["detail"]["canon_ab_status"] = canon_ab["status"]
    out["detail"]["wave_mesh_ab_status"] = wave_mesh_ab["status"]
    out["detail"]["wave_mesh2d_ab_status"] = wave_mesh2d_ab["status"]
    print(json.dumps(out))
    _bench_registry_record(registry, out)


if __name__ == "__main__":
    main()
