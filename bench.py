"""Headline benchmark: distinct states/sec on the BASELINE.md metric
config (tlc_membership raft.cfg at Server=3, MaxTerm=3, MaxLogLen=3,
ElectionSafety checked — BASELINE.json config #2).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "states/sec", "vs_baseline": N}

``vs_baseline`` compares the TPU engine against the repo's native C++
checker (native/raft_checker.cc) measured on this machine over the
SAME depth-exact run — the machine-measured stand-in for the
reference's "TLC -workers N" baseline (the reference publishes no
numbers — BASELINE.md).  Both engines run level-exact to depth 19
(7,619,299 states — the deepest level whose buffers fit single-chip
HBM; BASELINE.md "round 3" section measures the exhaustion wall) and
must land on the identical distinct-state count.

Correctness gate: before timing, the engine is differentially checked
against the Python oracle on a micro config; a mismatch zeroes the
score (guards against accelerator-path miscompiles).
"""

import json
import os
import sys
import time

# Depth-exact headline: both engines run the full space to depth 19.
# Level-20 frontiers (~25M rows) exceed single-chip HBM — BASELINE.md.
MAX_DEPTH = 19
LCAP = 3 << 21            # ≥ the 5.18M-row depth-19 level, no growth
VCAP = 1 << 25            # 7.62M keys at a 23% load factor


def main():
    from raft_tla_tpu import native
    from raft_tla_tpu.cfg.parser import load_model
    from raft_tla_tpu.config import Bounds
    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.models.explore import explore

    # -- correctness gate (micro config, fast) --------------------------
    micro = load_model("/root/reference/tlc_membership/raft.cfg",
                       bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                                          max_client_requests=1))
    micro = micro.with_(n_servers=2, init_servers=(0, 1), values=(1,),
                        max_inflight_override=4)
    eng_micro = Engine(micro, chunk=256, store_states=False)
    got = eng_micro.check()
    want = explore(micro)
    gate_ok = (got.distinct_states == want.distinct_states and
               got.depth == want.depth and
               got.generated_states == want.generated_states and
               len(got.violations) == len(want.violations))
    if not gate_ok:
        print(json.dumps({
            "metric": "distinct_states_per_sec_tlc_membership_S3_T3_L3",
            "value": 0.0, "unit": "states/sec", "vs_baseline": 0.0,
            "detail": {"correctness_gate": False,
                       "micro_engine": int(got.distinct_states),
                       "micro_oracle": int(want.distinct_states)}}))
        return

    # -- metric config #2 ----------------------------------------------
    # MaxTerm=3 <=> max_timeouts=2 (MaxTerms = MaxTimeouts+1, raft.tla:27)
    cfg = load_model("/root/reference/tlc_membership/raft.cfg",
                     bounds=Bounds.make(max_log_length=3, max_timeouts=2,
                                        max_client_requests=3))
    cfg = cfg.with_(invariants=("ElectionSafety",))

    # optional override: `python bench.py --max-depth N` (NOTE: the
    # round-2 positional arg was a STATE BUDGET; the metric is now
    # depth-exact, so a bare positional number is rejected to avoid
    # silently reinterpreting old invocations)
    max_depth = MAX_DEPTH
    if len(sys.argv) > 2 and sys.argv[1] == "--max-depth":
        max_depth = int(sys.argv[2])
        if not 1 <= max_depth <= 64:
            raise SystemExit(f"--max-depth {max_depth}: BFS depths are "
                             "small (the round-2 budget arg is gone)")
    elif len(sys.argv) > 1:
        raise SystemExit("usage: python bench.py [--max-depth N]   "
                         "(the metric is depth-exact now; the old "
                         "positional state budget was removed)")

    # -- CPU baseline: the native checker, same depth-exact run ---------
    threads = os.cpu_count() or 8
    nat = native.check(cfg, threads=threads, max_depth=max_depth)
    nat_rate = nat.states_per_sec

    # -- TPU engine, same depth ----------------------------------------
    eng = Engine(cfg, chunk=2048, store_states=False, lcap=LCAP, vcap=VCAP)
    t_compile = time.time()
    eng.check(max_depth=2)                      # warm the jit caches
    t_compile = time.time() - t_compile
    t0 = time.time()
    r = eng.check(max_depth=max_depth)
    secs = time.time() - t0
    rate = r.distinct_states / max(secs, 1e-9)

    count_ok = (r.distinct_states == nat.distinct_states and
                r.depth == nat.depth)
    gate_ok = gate_ok and count_ok

    out = {
        "metric": "distinct_states_per_sec_tlc_membership_S3_T3_L3",
        "value": round(rate if gate_ok else 0.0, 1),
        "unit": "states/sec",
        "vs_baseline": round((rate / nat_rate) if gate_ok else 0.0, 2),
        "detail": {
            "distinct_states": int(r.distinct_states),
            "depth": int(r.depth),
            "depth_exact": True,      # no budget cap: full space to depth
            "seconds": round(secs, 2),
            "compile_seconds": round(t_compile, 1),
            "violations": len(r.violations),
            "overflow_faults": int(r.overflow_faults),
            "baseline_native_states_per_sec": round(nat_rate, 1),
            "baseline_native_seconds": round(nat.seconds, 2),
            "baseline_native_threads": threads,
            "correctness_gate": bool(gate_ok),
            "counts_match_native": bool(count_ok),
            # the full space exceeds ~1e8 states (BASELINE.md round-3
            # exhaustion-wall measurements); depth 19 is the deepest
            # single-chip level-exact run
            "exhausted": False,
            # the dedup-exhaustiveness claim's collision bound
            # (64-bit fingerprints; fp128 parity recorded in
            # baseline_runs/round3_deep.json)
            "expected_fp_collisions": float(
                r.distinct_states ** 2 / 2.0 ** 65),
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
