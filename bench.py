"""Headline benchmark: distinct states/sec on the BASELINE.md metric
config (tlc_membership raft.cfg at Server=3, MaxTerm=3, MaxLogLen=3,
ElectionSafety checked — BASELINE.json config #2).

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "states/sec", "vs_baseline": N}

``vs_baseline`` compares against the Python oracle BFS (the stand-in CPU
implementation measured on this machine; the reference publishes no
numbers — BASELINE.md).  Correctness gate: before timing, the engine is
differentially checked against the oracle on a micro config; a mismatch
zeroes the score (guards against accelerator-path miscompiles).
"""

import json
import sys
import time


def main():
    from raft_tla_tpu.cfg.parser import load_model
    from raft_tla_tpu.config import Bounds
    from raft_tla_tpu.engine.bfs import Engine
    from raft_tla_tpu.models.explore import explore

    # -- correctness gate (micro config, fast) --------------------------
    micro = load_model("/root/reference/tlc_membership/raft.cfg",
                       bounds=Bounds.make(max_log_length=1, max_timeouts=1,
                                          max_client_requests=1))
    micro = micro.with_(n_servers=2, init_servers=(0, 1), values=(1,),
                        max_inflight_override=4)
    eng_micro = Engine(micro, chunk=256, store_states=False)
    got = eng_micro.check()
    want = explore(micro)
    gate_ok = (got.distinct_states == want.distinct_states and
               got.depth == want.depth and
               len(got.violations) == len(want.violations))

    # -- metric config #2 ----------------------------------------------
    # MaxTerm=3 <=> max_timeouts=2 (MaxTerms = MaxTimeouts+1, raft.tla:27)
    cfg = load_model("/root/reference/tlc_membership/raft.cfg",
                     bounds=Bounds.make(max_log_length=3, max_timeouts=2,
                                        max_client_requests=3))
    cfg = cfg.with_(invariants=("ElectionSafety",))

    budget_states = int(float(sys.argv[1])) if len(sys.argv) > 1 else 150_000
    eng = Engine(cfg, chunk=2048, store_states=False)
    eng.check(max_depth=2)                      # warm the jit caches
    t0 = time.time()
    r = eng.check(max_states=budget_states)
    secs = time.time() - t0
    rate = r.distinct_states / max(secs, 1e-9)

    # -- CPU baseline: Python oracle BFS on the same config -------------
    t0 = time.time()
    want_small = explore(cfg, max_states=4000)
    base_secs = time.time() - t0
    base_rate = want_small.distinct_states / max(base_secs, 1e-9)

    out = {
        "metric": "distinct_states_per_sec_tlc_membership_S3_T3_L3",
        "value": round(rate if gate_ok else 0.0, 1),
        "unit": "states/sec",
        "vs_baseline": round((rate / base_rate) if gate_ok else 0.0, 2),
        "detail": {
            "distinct_states": int(r.distinct_states),
            "depth": int(r.depth),
            "seconds": round(secs, 2),
            "violations": len(r.violations),
            "overflow_faults": int(r.overflow_faults),
            "baseline_oracle_states_per_sec": round(base_rate, 1),
            "correctness_gate": bool(gate_ok),
            "exhausted": bool(r.distinct_states < budget_states),
        },
    }
    print(json.dumps(out))


if __name__ == "__main__":
    main()
