---- MODULE raft ----
\* Bound-constant stub of the reference apalache_no_membership/raft.tla
\* (see configs/tlc_membership/raft.tla): only the regex-scanned bound
\* constants and the MaxInFlightMessages formula shape matter to the
\* cfg front-end (cfg/parser.read_bounds_from_spec /
\* max_inflight_from_spec).

MaxLogLength == 5
MaxRestarts == 2
MaxTimeouts == 2
MaxClientRequests == 3

MaxInFlightMessages == LET card == 2 * Cardinality(Server) IN card * card

BoundedTrace == Len(globalHistory) <= 12

====
