---- MODULE raft ----
\* Bound-constant stub of the reference tlc_membership/raft.tla: the
\* cfg front-end lifts the in-spec search bounds by regex-scanning the
\* sibling .tla (cfg/parser.read_bounds_from_spec) — only these
\* definitions matter to it.  The full Next-relation semantics live in
\* raft_tla_tpu/models/raft.py (the oracle) and ops/kernels.py (the
\* device kernels), both cited line-by-line against the reference spec.

MaxLogLength == 5
MaxRestarts == 2
MaxTimeouts == 3
MaxClientRequests == 3
MaxMembershipChanges == 3

MaxInFlightMessages == LET card == Cardinality(Server) IN 2 * card * card

BoundedTrace == Len(globalHistory) <= 24

====
