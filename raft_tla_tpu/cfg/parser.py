"""TLC ``.cfg`` front-end: the operator boundary the framework preserves.

Parses the reference's model files unchanged (BASELINE.json north star:
"SPECIFICATION/INVARIANT/CONSTANTS are read unchanged") into a
ModelConfig:

  * CONSTANTS: model-value bindings (``s1 = 1``), sets (``Server =
    {s1, s2, s3}``), ints (``NumRounds = 1``); string-valued model
    constants (roles, message types, entry tags) are validated but carry
    no information for us — our codec fixes their encodings.
  * INIT / NEXT: Init must be ``Init``; NEXT selects the Next-relation
    family (raft.tla:909-943).
  * SYMMETRY perms / VIEW vars: symmetry reduction toggle; the VIEW is
    always ``vars`` semantics here (identity excludes history).  A cfg
    with no VIEW line (apalache_no_membership) would make TLC fingerprint
    the ever-growing history — divergence documented: we keep VIEW vars.
  * CONSTRAINT(S) / ACTION_CONSTRAINT(S) / INVARIANT(S): names resolved
    against the predicate registries (singular and plural forms, the
    plural introducing an indented name list, as in the reference cfgs).

In-spec search bounds (MaxLogLength etc., raft.tla:22-30) are NOT
cfg-settable in the reference — editing the spec is required — so
``read_bounds_from_spec`` lifts them by scanning the sibling ``raft.tla``
(SURVEY §5 "Config" tier b).
"""

from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, List, Optional

from ..config import (Bounds, DEFAULT_CONSTRAINTS, DEFAULT_INVARIANTS,
                      ModelConfig, NEXT_ASYNC, NEXT_ASYNC_CRASH,
                      NEXT_DYNAMIC, NEXT_FULL)
from ..models import predicates as OP

_KEYWORDS = {
    "CONSTANTS", "CONSTANT", "SYMMETRY", "VIEW", "INIT", "NEXT",
    "CONSTRAINTS", "CONSTRAINT", "ACTION_CONSTRAINTS", "ACTION_CONSTRAINT",
    "INVARIANTS", "INVARIANT", "SPECIFICATION", "PROPERTIES", "PROPERTY",
}

_NEXT_FAMILIES = {
    "NextAsync": NEXT_ASYNC,
    "NextAsyncCrash": NEXT_ASYNC_CRASH,
    "Next": NEXT_FULL,
    "NextDynamic": NEXT_DYNAMIC,
}


class CfgError(ValueError):
    pass


def _tokenize(text: str) -> List[str]:
    out = []
    for line in text.splitlines():
        line = line.split("\\*")[0]
        # split on whitespace but keep {...} sets together
        line = line.replace("{", " { ").replace("}", " } ") \
                   .replace("=", " = ").replace(",", " , ")
        out.extend(line.split())
    return out


def _parse_value(toks: List[str], pos: int):
    """Parse int | "string" | {elems} starting at pos; returns (value,
    new_pos).  Set elements are names or ints."""
    t = toks[pos]
    if t == "{":
        elems = []
        pos += 1
        while toks[pos] != "}":
            if toks[pos] != ",":
                elems.append(toks[pos])
            pos += 1
        return ("set", elems), pos + 1
    if t.startswith('"'):
        return ("str", t.strip('"')), pos + 1
    try:
        return ("int", int(t)), pos + 1
    except ValueError:
        return ("name", t), pos + 1


def parse_cfg_text(text: str) -> Dict:
    """Raw parse: returns constants, init, next, symmetry, view, and the
    constraint/action-constraint/invariant name lists."""
    toks = _tokenize(text)
    consts: Dict[str, object] = {}
    out = {"constants": consts, "init": None, "next": None,
           "symmetry": None, "view": None, "specification": None,
           "constraints": [], "action_constraints": [], "invariants": [],
           "properties": []}
    i = 0
    section = None
    while i < len(toks):
        t = toks[i]
        if t in _KEYWORDS:
            section = t
            i += 1
            if t in ("SYMMETRY", "VIEW", "INIT", "NEXT", "SPECIFICATION"):
                out[t.lower()] = toks[i]
                i += 1
                section = None
            continue
        if section in ("CONSTANTS", "CONSTANT"):
            name = t
            if i + 1 < len(toks) and toks[i + 1] == "=":
                val, i = _parse_value(toks, i + 2)
                consts[name] = val
            else:
                i += 1
            continue
        if section in ("CONSTRAINTS", "CONSTRAINT"):
            out["constraints"].append(t)
        elif section in ("ACTION_CONSTRAINTS", "ACTION_CONSTRAINT"):
            out["action_constraints"].append(t)
        elif section in ("INVARIANTS", "INVARIANT"):
            out["invariants"].append(t)
        elif section in ("PROPERTIES", "PROPERTY"):
            out["properties"].append(t)
        else:
            raise CfgError(f"unexpected token {t!r} outside any section")
        i += 1
    return out


def _resolve_set(consts: Dict, val) -> List[int]:
    kind, elems = val
    if kind != "set":
        raise CfgError(f"expected a set, got {val}")
    out = []
    for e in elems:
        try:
            out.append(int(e))
        except ValueError:
            bound = consts.get(e)
            if bound is None or bound[0] != "int":
                raise CfgError(f"model value {e!r} has no int binding")
            out.append(bound[1])
    return out


def read_bounds_from_spec(spec_path: Path,
                          default: Optional[Bounds] = None) -> Bounds:
    """Lift the in-spec bound constants (tlc raft.tla:22-30 / apalache
    raft.tla:19-22) by scanning the spec text.  Unrecognized bounds keep
    the Bounds.make defaults."""
    text = Path(spec_path).read_text()
    found = {}
    for name in ("MaxLogLength", "MaxRestarts", "MaxTimeouts",
                 "MaxClientRequests", "MaxMembershipChanges"):
        m = re.search(rf"^{name}\s*==\s*(\d+)\s*$", text, re.M)
        if m:
            found[name] = int(m.group(1))
    m = re.search(r"^BoundedTrace\s*==.*<=\s*(\d+)", text, re.M)
    base = default or Bounds()
    return Bounds.make(
        max_log_length=found.get("MaxLogLength", base.max_log_length),
        max_restarts=found.get("MaxRestarts", base.max_restarts),
        max_timeouts=found.get("MaxTimeouts", base.max_timeouts),
        max_client_requests=found.get("MaxClientRequests",
                                      base.max_client_requests),
        max_membership_changes=found.get("MaxMembershipChanges",
                                         base.max_membership_changes),
        max_trace=int(m.group(1)) if m else base.max_trace,
    )


def max_inflight_from_spec(spec_path: Path, n_servers: int) -> Optional[int]:
    """The two MaxInFlightMessages formulas in the reference family:
    tlc 2·S² (raft.tla:30) vs apalache (2S)² (raft.tla:22)."""
    text = Path(spec_path).read_text()
    if re.search(r"LET card == 2 \* Cardinality\(Server\) IN card \* card",
                 text):
        return 4 * n_servers * n_servers
    if re.search(r"LET card == Cardinality\(Server\) IN 2 \* card \* card",
                 text):
        return 2 * n_servers * n_servers
    return None


# ---------------------------------------------------------------------------
# Paxos front-ends (--spec paxos).  Two entry points share one
# validated construction path: ``paxos_config_from_obj`` (the JSON /
# inline-dict constants form the CLI and serve/jobs consume) and
# ``load_paxos_model`` (the TLC .cfg form — ROADMAP 2a leftover), which
# round-trip onto identical PaxosConfig objects (tests/test_cfg.py).
# ---------------------------------------------------------------------------

_PAXOS_ALIAS = {"acceptors": "n_servers", "servers": "n_servers",
                "ballots": "n_ballots", "values": "n_values",
                "instances": "n_instances"}
_PAXOS_INT_KEYS = ("n_servers", "n_ballots", "n_values", "n_instances")

# TLC .cfg CONSTANT names -> PaxosConfig bound (singular and plural
# forms, as the reference raft cfgs accept for their sections)
_PAXOS_CFG_CONSTS = {
    "Acceptor": "n_servers", "Acceptors": "n_servers",
    "Ballot": "n_ballots", "Ballots": "n_ballots",
    "Value": "n_values", "Values": "n_values",
    "Instance": "n_instances", "Instances": "n_instances",
    "NumInstances": "n_instances",
}


def paxos_config_from_obj(raw: Dict, where: str = "paxos config"):
    """Constants dict -> PaxosConfig, with clear errors naming the
    offending key.  Accepted keys: acceptors/servers, ballots, values,
    instances (ints), symmetry/fp128 (bools), invariants (names from
    the paxos registry)."""
    from ..spec import get_spec
    from ..spec.paxos.config import PaxosConfig
    if not isinstance(raw, dict):
        raise CfgError(
            f"{where}: paxos constants must be a JSON object "
            f"(got {type(raw).__name__})")
    kw = {}
    for k, v in raw.items():
        kk = _PAXOS_ALIAS.get(k, k)
        if kk not in _PAXOS_INT_KEYS + ("symmetry", "fp128",
                                        "invariants"):
            raise CfgError(f"{where}: unknown paxos config key {k!r}")
        if kk in ("symmetry", "fp128"):
            if not isinstance(v, bool):
                raise CfgError(
                    f"{where}: {k} must be a JSON bool (got {v!r})")
        elif kk == "invariants":
            known = get_spec("paxos").known_invariants
            bad = [nm for nm in v if nm not in known]
            if bad:
                raise CfgError(
                    f"{where}: unknown invariant(s) "
                    f"{', '.join(map(repr, bad))} for spec 'paxos'; "
                    f"known: {', '.join(sorted(known))}")
            v = tuple(v)
        elif isinstance(v, bool) or not isinstance(v, int):
            raise CfgError(
                f"{where}: {k} must be a JSON integer (got {v!r})")
        kw[kk] = v
    try:
        return PaxosConfig(**kw)
    except ValueError as e:
        raise CfgError(f"{where}: {e}") from e


def load_paxos_model(cfg_path) -> "object":
    """TLC ``.cfg`` front-end for ``--spec paxos``: CONSTANTS map onto
    PaxosConfig bounds — Acceptor/Value as model-value sets (their
    cardinality is the bound; values must be the dense 0..N-1 indices
    the packed layout uses), Ballot as a 0..N-1 set or an int count,
    Instance(s) as an int — SYMMETRY toggles acceptor canonicalization
    (a cfg with no SYMMETRY line runs symmetry-off, TLC semantics),
    and INVARIANT names resolve against the paxos registry.  Quorum
    must NOT be bound: the engine derives all majorities of Acceptor,
    the standard Paxos.tla instantiation.  Every other key errors by
    name.  Round-trips with the JSON constants path
    (``paxos_config_from_obj``); tests/test_cfg.py pins it."""
    cfg_path = Path(cfg_path)
    raw = parse_cfg_text(cfg_path.read_text())
    consts = raw["constants"]
    # names referenced inside any set binding are model values (a1 = 1)
    refd = set()
    for val in consts.values():
        if val[0] == "set":
            refd.update(val[1])
    kw: Dict[str, object] = {}
    for name, val in consts.items():
        if name in _PAXOS_CFG_CONSTS:
            key = _PAXOS_CFG_CONSTS[name]
            if val[0] == "set":
                elems = _resolve_set(consts, val)
                if key in ("n_ballots", "n_values") and \
                        sorted(elems) != list(range(len(elems))):
                    raise CfgError(
                        f"{cfg_path}: {name} must be the contiguous "
                        f"set 0..N-1 (got {sorted(elems)}) — ballots "
                        "and values are dense indices in the packed "
                        "layout")
                kw[key] = len(elems)
            elif val[0] == "int":
                kw[key] = val[1]
            else:
                raise CfgError(
                    f"{cfg_path}: {name} must be a set or an int "
                    f"(got {val[1]!r})")
        elif name == "Quorum":
            raise CfgError(
                f"{cfg_path}: Quorum is not cfg-settable — the engine "
                "derives all majorities of Acceptor (the standard "
                "Paxos.tla instantiation); remove the Quorum binding")
        elif val[0] == "int" and name in refd:
            pass          # model-value binding, consumed by the sets
        else:
            raise CfgError(
                f"{cfg_path}: unsupported paxos CONSTANT {name!r} — "
                "supported: " +
                ", ".join(sorted(set(_PAXOS_CFG_CONSTS))))
    if raw["init"] not in (None, "Init"):
        raise CfgError(f"{cfg_path}: unsupported INIT {raw['init']!r}")
    if raw["view"] is not None:
        raise CfgError(
            f"{cfg_path}: VIEW is not supported for spec 'paxos' — "
            "state identity is the full packed state; remove the "
            "VIEW line")
    if raw["specification"] not in (None, "Spec"):
        raise CfgError(
            f"{cfg_path}: unsupported SPECIFICATION "
            f"{raw['specification']!r}")
    if raw["next"] not in (None, "Next"):
        raise CfgError(
            f"{cfg_path}: unsupported NEXT {raw['next']!r} for spec "
            "'paxos' (only the full Next relation exists)")
    if raw["properties"]:
        raise CfgError(
            f"{cfg_path}: temporal PROPERTIES are not supported: "
            f"{raw['properties']}")
    if raw["constraints"] or raw["action_constraints"]:
        raise CfgError(
            f"{cfg_path}: spec 'paxos' declares no constraints / "
            "action constraints (the bounded space is finite without "
            "them)")
    # delegate invariant validation + construction to the JSON path's
    # validator, so the two front-ends share one tail and cannot drift
    kw["symmetry"] = raw["symmetry"] is not None
    if raw["invariants"]:
        kw["invariants"] = list(raw["invariants"])
    return paxos_config_from_obj(kw, where=str(cfg_path))


def load_model(cfg_path, variant: Optional[str] = None,
               bounds: Optional[Bounds] = None) -> ModelConfig:
    """cfg file -> ModelConfig.  ``variant`` = 'apalache' switches the
    live VotesGrantedInv/LeaderCompleteness to the documented-false forms
    the apalache_no_membership spec ships (SURVEY §2.7); auto-detected
    from the path when None."""
    cfg_path = Path(cfg_path)
    raw = parse_cfg_text(cfg_path.read_text())
    consts = raw["constants"]
    if variant is None:
        variant = "apalache" if "apalache" in str(cfg_path) else "tlc"

    if "Server" not in consts:
        raise CfgError("cfg binds no Server set")
    server_ids = sorted(_resolve_set(consts, consts["Server"]))
    id_map = {sid: k for k, sid in enumerate(server_ids)}
    init_ids = (sorted(_resolve_set(consts, consts["InitServer"]))
                if "InitServer" in consts else server_ids)
    values = tuple(sorted(_resolve_set(consts, consts["Value"]))) \
        if "Value" in consts else (1,)
    num_rounds = consts.get("NumRounds", ("int", 1))[1]

    if raw["init"] not in (None, "Init"):
        raise CfgError(f"unsupported INIT {raw['init']!r}")
    if raw["properties"]:
        raise CfgError(
            f"temporal PROPERTIES are not supported: {raw['properties']}")
    next_name = raw["next"]
    if next_name is None and raw["specification"] is not None:
        # SPECIFICATION Spec == Init /\ [][Next]_vars (raft.tla:947)
        if raw["specification"] != "Spec":
            raise CfgError(
                f"unsupported SPECIFICATION {raw['specification']!r}")
        next_name = "Next"
    next_name = next_name or "NextAsyncCrash"
    if next_name not in _NEXT_FAMILIES:
        raise CfgError(f"unknown NEXT family {next_name!r}")

    for nm in raw["invariants"]:
        if nm not in OP.INVARIANTS:
            raise CfgError(f"unknown invariant {nm!r}")
    # the punctuated-search prefix pins (raft.tla:1198-1234) are cfg
    # CONSTRAINTS in the reference but compile to BFS seeds here
    # (models/golden.prefix_pin_seeds) — split them out
    prefix_pins = tuple(nm for nm in raw["constraints"]
                        if nm in ("CommitWhenConcurrentLeaders_unique",
                                  "MajorityOfClusterRestarts_constraint"))
    plain_constraints = tuple(nm for nm in raw["constraints"]
                              if nm not in prefix_pins)
    for nm in plain_constraints:
        if nm not in OP.CONSTRAINTS:
            raise CfgError(f"unknown constraint {nm!r}")
    for nm in raw["action_constraints"]:
        if nm not in OP.ACTION_CONSTRAINTS:
            raise CfgError(f"unknown action constraint {nm!r}")

    spec_path = cfg_path.with_suffix(".tla")
    n = len(server_ids)
    if bounds is None and spec_path.exists():
        bounds = read_bounds_from_spec(spec_path)
    bounds = bounds or Bounds()
    inflight = (max_inflight_from_spec(spec_path, n)
                if spec_path.exists() else None)

    return ModelConfig(
        n_servers=n,
        init_servers=tuple(id_map[s] for s in init_ids),
        values=values,
        num_rounds=num_rounds,
        next_family=_NEXT_FAMILIES[next_name],
        # defaults only when the cfg listed NO constraints at all — a
        # cfg listing only prefix pins gets exactly that (an author who
        # pinned the search did not ask for the bounded-constraint set)
        constraints=(plain_constraints if raw["constraints"]
                     else DEFAULT_CONSTRAINTS),
        prefix_pins=prefix_pins,
        action_constraints=tuple(raw["action_constraints"]),
        invariants=tuple(raw["invariants"]) or DEFAULT_INVARIANTS,
        symmetry=raw["symmetry"] is not None,
        bounds=bounds,
        apalache_variant=(variant == "apalache"),
        max_inflight_override=inflight,
    )
