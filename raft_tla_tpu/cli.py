"""Command-line front-end: ``check`` and ``trace`` (SURVEY §7.2 L5).

Mirrors the two ways the reference drives TLC (SURVEY §3.1, §3.5):

  check  — exhaustive bounded model check: BFS to fixpoint, report
           distinct states / depth / states/sec and any invariant
           violations (with traces).
  trace  — scenario-trace generation: enable ONE negated-reachability
           property (raft.cfg "Test cases", §2.9) and print the witness
           trace TLC would emit as a "violation".

Engine selection: --engine tpu (default; the JAX BFS) or --engine oracle
(the plain-Python reference implementation, for cross-checking).

Spec selection: --spec raft (default; the cfg positional is a TLC .cfg
path) or --spec paxos (the cfg positional is optional — omitted or
"default" builds the stock small PaxosConfig, else a JSON file of
constants).  Every engine/oracle path below routes through the
``SpecIR`` handle, so the two specs share the whole command surface.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from .cfg.parser import load_model
from .config import Bounds


def _honor_platform_env():
    """The axon TPU plugin in this image overrides JAX_PLATFORMS during
    its sitecustomize registration; re-assert the user's choice (see
    tests/conftest.py for the same dance)."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat and plat != "axon":
        import jax
        jax.config.update("jax_platforms", plat)


def _apply_overrides(cfg, args):
    kw = {}
    if args.servers is not None:
        kw["n_servers"] = args.servers
        init = args.init_servers if args.init_servers is not None \
            else args.servers
        kw["init_servers"] = tuple(range(init))
        # MaxInFlightMessages is a FORMULA over Server in the spec
        # (2·|S|² tlc / 4·|S|² apalache, raft.tla:30); the parser lifts
        # its value at the cfg's |Server|, so a --servers override must
        # recompute it — otherwise a shrunk model keeps the big model's
        # bag capacity (e.g. K=19 at S=2, a shape the remote TPU
        # compiler chokes on for >15 min)
        old_n, new_n = cfg.n_servers, args.servers
        ov = cfg.max_inflight_override
        if ov == 2 * old_n * old_n:
            kw["max_inflight_override"] = 2 * new_n * new_n
        elif ov == 4 * old_n * old_n:
            kw["max_inflight_override"] = 4 * new_n * new_n
    elif args.init_servers is not None:
        kw["init_servers"] = tuple(range(args.init_servers))
    if args.symmetry is not None:
        kw["symmetry"] = args.symmetry
    if getattr(args, "next_family", None):
        # next-relation family override (the CLI analog of editing the
        # cfg's NEXT line — e.g. NextDynamic enables the membership
        # actions the MembershipChange* scenario targets need)
        kw["next_family"] = args.next_family
    b = cfg.bounds
    bkw = {}
    if args.max_terms is not None:
        bkw["max_terms"] = args.max_terms
    if args.max_log_length is not None:
        bkw["max_log_length"] = args.max_log_length
    if args.max_timeouts is not None:
        bkw["max_timeouts"] = args.max_timeouts
    if args.max_client_requests is not None:
        bkw["max_client_requests"] = args.max_client_requests
    if args.max_restarts is not None:
        bkw["max_restarts"] = args.max_restarts
    if bkw:
        kw["bounds"] = Bounds.make(
            max_log_length=bkw.get("max_log_length", b.max_log_length),
            max_restarts=bkw.get("max_restarts", b.max_restarts),
            max_timeouts=bkw.get("max_timeouts", b.max_timeouts),
            max_client_requests=bkw.get("max_client_requests",
                                        b.max_client_requests),
            max_membership_changes=b.max_membership_changes,
            max_terms=bkw.get("max_terms"),
            max_trace=b.max_trace)
    if args.fp128:
        kw["fp128"] = True
    # cfg-surgery equivalents of TLC's comment-toggling (raft.cfg:51-76).
    # ADDITIVE, like TLC's repeated CONSTRAINTS/INVARIANTS blocks: the
    # cfg's general bounding constraints stay in force.
    from .models import predicates as OP

    def _add(base, extra, known, what):
        for nm in extra:
            if nm not in known:
                raise SystemExit(
                    f"unknown {what} {nm!r}; known: "
                    f"{', '.join(sorted(known))}")
        return tuple(dict.fromkeys(base + tuple(extra)))
    if getattr(args, "invariants", None):
        kw["invariants"] = _add(cfg.invariants, args.invariants,
                                OP.INVARIANTS, "invariant")
    if getattr(args, "constraint_overrides", None):
        kw["constraints"] = _add(cfg.constraints, args.constraint_overrides,
                                 OP.CONSTRAINTS, "constraint")
    if getattr(args, "action_constraints", None):
        kw["action_constraints"] = _add(cfg.action_constraints,
                                        args.action_constraints,
                                        OP.ACTION_CONSTRAINTS,
                                        "action constraint")
    return cfg.with_(**kw) if kw else cfg


def _load_paxos_model(args):
    """--spec paxos config assembly: the cfg positional is optional
    (None/"default" -> the stock small model; a ``.cfg`` path -> the
    TLC CONSTANTS front-end, cfg/parser.load_paxos_model; anything
    else -> a JSON file of constants), then the generic CLI overrides
    apply (--servers = acceptors, --ballots/--paxos-values/
    --instances, --symmetry, --fp128, --invariant)."""
    import json as _json
    from .cfg.parser import (CfgError, load_paxos_model,
                             paxos_config_from_obj)
    from .spec import get_spec
    from .spec.paxos.config import PaxosConfig
    raft_only = [flag for flag, attr in (
        ("--next", "next_family"), ("--max-terms", "max_terms"),
        ("--max-log-length", "max_log_length"),
        ("--max-timeouts", "max_timeouts"),
        ("--max-client-requests", "max_client_requests"),
        ("--max-restarts", "max_restarts"),
        ("--init-servers", "init_servers"))
        if getattr(args, attr, None) is not None]
    if raft_only:
        raise SystemExit(
            f"{', '.join(raft_only)} are raft-only bounds/toggles — "
            "spec 'paxos' is bounded by --ballots/--paxos-values/"
            "--instances/--servers instead")
    if args.cfg and args.cfg != "default":
        try:
            if args.cfg.endswith(".cfg"):
                cfg = load_paxos_model(args.cfg)
            else:
                with open(args.cfg) as fh:
                    raw = _json.load(fh)
                cfg = paxos_config_from_obj(raw, where=args.cfg)
        except CfgError as e:
            raise SystemExit(str(e))
    else:
        cfg = PaxosConfig()
    kw = {}
    if args.servers is not None:
        kw["n_servers"] = args.servers
    if getattr(args, "ballots", None) is not None:
        kw["n_ballots"] = args.ballots
    if getattr(args, "paxos_values", None) is not None:
        kw["n_values"] = args.paxos_values
    if getattr(args, "instances", None) is not None:
        kw["n_instances"] = args.instances
    if args.symmetry is not None:
        kw["symmetry"] = args.symmetry
    if args.fp128:
        kw["fp128"] = True
    try:
        if kw:
            cfg = cfg.with_(**kw)
    except ValueError as e:
        raise SystemExit(f"paxos config: {e}")
    if getattr(args, "invariants", None):
        ir = get_spec("paxos")
        for nm in args.invariants:
            if nm not in ir.known_invariants:
                raise SystemExit(
                    f"unknown invariant {nm!r} for spec 'paxos'; "
                    f"known: {', '.join(sorted(ir.known_invariants))}")
        cfg = cfg.with_(invariants=tuple(dict.fromkeys(
            cfg.invariants + tuple(args.invariants))))
    if getattr(args, "constraint_overrides", None) or \
            getattr(args, "action_constraints", None):
        raise SystemExit(
            "spec 'paxos' declares no constraints / action "
            "constraints (the bounded space is finite without them)")
    return cfg


def _load_cfg(args):
    """(SpecIR handle, model config) for the selected --spec."""
    from .spec import get_spec
    ir = get_spec(args.spec)
    if args.spec == "paxos":
        return ir, _load_paxos_model(args)
    if not args.cfg:
        raise SystemExit(
            "a TLC .cfg path is required for --spec raft "
            "(only --spec paxos has a built-in default model)")
    cfg = load_model(args.cfg, bounds=None)
    return ir, _apply_overrides(cfg, args)


def _print_violation(idx, name, trace):
    print(f"\nViolation {idx}: invariant {name}")
    if trace:
        for step, (label, sv) in enumerate(trace):
            print(f"  {step:3d}  {label}")
            print(f"       {sv}")


def _load_seeds(path, ir):
    """Seed-trace file -> list of seeds (punctuated search: BFS
    explores only extensions of the pinned prefix).  Entries carry the
    active spec's oracle state/hist plus the exact non-VIEW lanes when
    emitted by the engine."""
    import json as _json
    with open(path) as fh:
        data = _json.load(fh)
    if isinstance(data, dict):
        data = [data]
    oracle_seeds, engine_seeds = [], []
    for obj in data:
        # seed files are spec-tagged (paxos state_to_obj writes a
        # "paxos" marker; untagged files are raft-era) — refuse a
        # cross-spec seed with the same clarity as checkpoint resume
        got_spec = "paxos" if obj.get("paxos") else "raft"
        if got_spec != ir.name:
            raise SystemExit(
                f"{path}: seed was emitted for spec {got_spec!r}; "
                f"this run is --spec {ir.name} — re-emit the seed "
                f"with the matching --spec")
        sv, h = ir.state_from_obj(obj)
        oracle_seeds.append((sv, h))
        engine_seeds.append((sv, h, obj.get("nonview")))
    return oracle_seeds, engine_seeds


def _engine_seed_arrays(cfg, ir, engine_seeds):
    import numpy as np
    lay = ir.make_layout(cfg)
    out = []
    for sv, h, nonview in engine_seeds:
        arrs = ir.encode(lay, sv, h)
        if nonview:
            for k, v in nonview.items():
                arrs[k] = np.asarray(v, dtype=arrs[k].dtype)
        out.append(arrs)
    return out


_OBS_ARGS = ("ledger", "heartbeat", "trace_timeline", "profile_dir",
             "registry")


def _obs_flags_set(args) -> bool:
    """Flag presence WITHOUT constructing the bundle (building it
    opens/truncates the ledger and timeline files)."""
    return any(getattr(args, nm, None) for nm in _OBS_ARGS)


def _build_obs(args, ir=None, cfg=None, cmd=None):
    """The observability bundle the flags describe (obs package);
    NULL_OBS when no flag is set.  ``ir`` stamps the active spec name
    + IR fingerprint into every ledger record; ``cfg``/``cmd`` ride
    the run-level context (ledger meta row + registry record only —
    a cfg repr is too bulky for every dispatch row)."""
    from .obs import from_flags
    meta = ({"spec": ir.name, "ir_fingerprint": ir.fingerprint()}
            if ir is not None else None)
    run_info = {}
    if cmd is not None:
        run_info["cmd"] = cmd
    if cfg is not None:
        run_info["cfg"] = repr(cfg)
    return from_flags(ledger=getattr(args, "ledger", None),
                      heartbeat=getattr(args, "heartbeat", None),
                      timeline=getattr(args, "trace_timeline", None),
                      profile_dir=getattr(args, "profile_dir", None),
                      meta=meta,
                      registry=getattr(args, "registry", None),
                      run_info=run_info or None)


def _add_obs_flags(sp):
    """--ledger/--heartbeat/--trace-timeline/--profile-dir/--registry,
    shared by check, simulate and batch (tools/deep_run.py exposes the
    same set)."""
    sp.add_argument("--ledger", default=None, metavar="FILE",
                    help="append one JSONL record per dispatch (depth, "
                         "frontier, registry counters, states/sec, "
                         "RSS, device memory) — flushed per record, so "
                         "a killed run keeps its telemetry; tail with "
                         "tools/watch.py")
    sp.add_argument("--heartbeat", default=None, metavar="FILE",
                    help="atomically rewrite a small JSON (pid, depth, "
                         "last-dispatch timestamp, states enqueued) "
                         "every dispatch so an external watchdog can "
                         "distinguish a slow level from a dead tunnel")
    sp.add_argument("--trace-timeline", default=None, metavar="FILE",
                    help="write the host span timeline (compile / "
                         "burst_dispatch / harvest / host_sweep / "
                         "archive_io / checkpoint) as Chrome-trace "
                         "JSON — load it in Perfetto "
                         "(https://ui.perfetto.dev)")
    sp.add_argument("--profile-dir", default=None, metavar="DIR",
                    help="capture an XLA device trace via "
                         "jax.profiler.trace into DIR; span names ride "
                         "along as TraceAnnotations so the device "
                         "trace lines up with --trace-timeline")
    sp.add_argument("--registry", default=None, metavar="DIR",
                    help="append one atomic schema-versioned run "
                         "record (counters, span rollups, resource "
                         "peaks, backend fingerprint, exit status, "
                         "artifact paths) under DIR at run end; query "
                         "with `cli obs ls/show/diff/regress`")


def _install_chaos(args):
    """--chaos SPEC -> the process-global schedule (resil/chaos);
    returns an error string on a malformed spec."""
    if not getattr(args, "chaos", None):
        return None
    from .resil.chaos import ChaosSpecError, install
    try:
        install(args.chaos)
    except ChaosSpecError as e:
        return str(e)
    return None


def _check_retry_flags(args):
    if getattr(args, "retries", 0) < 0:
        return f"--retries must be >= 0 (got {args.retries})"
    if getattr(args, "backoff", 1.0) <= 0:
        return f"--backoff must be positive (got {args.backoff})"
    if getattr(args, "ckpt_keep", 1) is not None and \
            getattr(args, "ckpt_keep", 1) < 1:
        return f"--ckpt-keep must be >= 1 (got {args.ckpt_keep})"
    return None


def cmd_check(args):
    ir, cfg = _load_cfg(args)
    if args.engine == "oracle" and (args.resume or args.checkpoint):
        print("--checkpoint/--resume are tpu-engine features",
              file=sys.stderr)
        return 2
    if args.resume and args.seed_trace:
        print("--resume and --seed-trace are mutually exclusive",
              file=sys.stderr)
        return 2
    if args.resume_portable and not args.resume:
        print("--resume-portable qualifies --resume: pass the "
              "checkpoint with --resume FILE", file=sys.stderr)
        return 2
    if args.resume_portable and not (args.spill or args.pjit):
        print("--resume-portable re-partitions any engine family's "
              "checkpoint onto the spill or pjit engine: add --spill "
              "or --pjit", file=sys.stderr)
        return 2
    if args.pjit and args.spill:
        print("--pjit and --spill are different engines; pick one",
              file=sys.stderr)
        return 2
    err = _check_retry_flags(args) or _install_chaos(args)
    if err:
        print(err, file=sys.stderr)
        return 2
    oracle_seeds = engine_seeds = None
    if args.seed_trace:
        oracle_seeds, raw = _load_seeds(args.seed_trace, ir)
        if args.engine == "oracle":
            # engine-emitted seeds (nonview lanes, no glob records)
            # cannot feed the oracle's record-scanning predicates: they
            # would silently evaluate against an empty history.
            needs_glob = ir.glob_dependent & (
                set(cfg.invariants) | set(cfg.constraints) |
                set(cfg.action_constraints))
            for _sv, h, nonview in raw:
                if nonview and not h.glob and needs_glob:
                    print(f"seed was emitted by the tpu engine (nonview "
                          f"lanes, no history records); the oracle "
                          f"cannot evaluate {sorted(needs_glob)} on it — "
                          f"re-emit the seed with `trace --engine oracle "
                          f"--emit-seed`", file=sys.stderr)
                    return 2
        else:
            engine_seeds = _engine_seed_arrays(cfg, ir, raw)
    if args.engine == "oracle":
        explore = ir.oracle_explore
        import time
        if _obs_flags_set(args):
            # the oracle has no dispatches to ledger/heartbeat; say so
            # instead of silently writing nothing (and do NOT build
            # the bundle — that would touch the files)
            print("--ledger/--heartbeat/--trace-timeline/--profile-dir"
                  "/--registry instrument the tpu engines; ignored "
                  "for --engine oracle", file=sys.stderr)
        t0 = time.perf_counter()
        r = explore(cfg, max_depth=args.max_depth,
                    max_states=args.max_states,
                    stop_on_violation=not args.keep_going,
                    trace_violations=True, seed_states=oracle_seeds)
        secs = time.perf_counter() - t0
        viol = [(v.invariant, v.trace) for v in r.violations]
        distinct, depth, gen = r.distinct_states, r.depth, \
            r.generated_states
    else:
        from .engine.bfs import CheckpointError, Engine
        if args.host_table and not args.spill:
            print("--host-table composes with the spill engine: add "
                  "--spill", file=sys.stderr)
            return 2
        if args.burst_levels is not None and args.burst_levels <= 0:
            # a clear error beats the jit-time shape traceback a zero
            # ring would produce
            print(f"--burst-levels must be positive (got "
                  f"{args.burst_levels}); use --no-burst to disable "
                  "the fused-level path", file=sys.stderr)
            return 2
        fam_density = None
        if args.fam_cap_density:
            from .engine.expand import parse_fam_density
            try:
                fam_density = parse_fam_density(args.fam_cap_density,
                                                ir)
            except ValueError as e:
                print(f"--fam-cap-density: {e}", file=sys.stderr)
                return 2
        burst_kw = dict(burst=args.burst, burst_levels=args.burst_levels,
                        guard_matmul=args.guard_matmul,
                        dedup_kernel=args.dedup_kernel,
                        delta_matmul=args.delta_matmul,
                        fam_density=fam_density,
                        sym_canon=args.sym_canon)

        def make_engine():
            # one fresh engine per supervised attempt — the backend-
            # reinit contract (resil/supervisor): a retry re-traces
            # against a reconnected backend instead of reusing
            # executables that may hold dead tunnel handles
            if args.spill:
                # host-spill engine: levels stream through host RAM,
                # for depths whose level buffers exceed HBM
                # (engine/spill); --host-table additionally moves the
                # visited set to fingerprint-prefix partitions in host
                # RAM, streamed through HBM per level
                # (engine/host_table) — the ceiling becomes host RAM,
                # not the chip
                from .engine.spill import SpillEngine
                eng = SpillEngine(cfg, chunk=args.chunk,
                                  store_states=not args.no_store,
                                  seg=args.seg,
                                  host_table=args.host_table,
                                  partitions=args.partitions,
                                  part_cap=args.part_cap,
                                  sweep_stage=args.sweep_stage,
                                  archive_dir=args.archive_dir,
                                  **burst_kw)
            elif args.pjit:
                # pod-scale pjit engine: the classic program under
                # named shardings spanning every host's devices
                # (parallel/pjit_mesh) — bit-identical counts/traces
                from .parallel.pjit_mesh import PjitShardedEngine
                eng = PjitShardedEngine(cfg, chunk=args.chunk,
                                        store_states=not args.no_store,
                                        archive_dir=args.archive_dir,
                                        **burst_kw)
            else:
                eng = Engine(cfg, chunk=args.chunk,
                             store_states=not args.no_store,
                             archive_dir=args.archive_dir,
                             **burst_kw)
            eng.ckpt_keep = args.ckpt_keep
            return eng
        from .resil.supervisor import RetryExhausted, supervised_check
        obs = _build_obs(args, ir, cfg=cfg, cmd="check")
        obs.start()
        done = False
        try:
            resume_image = None
            if args.resume_portable:
                from .resil.portable import load_portable_image
                resume_image = load_portable_image(args.resume)
            r, eng, _attempts = supervised_check(
                make_engine, retries=args.retries,
                backoff=args.backoff, obs=obs,
                checkpoint_path=args.checkpoint,
                resume_from=(None if args.resume_portable
                             else args.resume),
                resume_image=resume_image,
                max_depth=args.max_depth,
                max_states=args.max_states,
                stop_on_violation=not args.keep_going,
                verbose=args.verbose, seed_states=engine_seeds,
                checkpoint_every=args.checkpoint_every)
            done = True
        except (CheckpointError, FileNotFoundError) as e:
            # only checkpoint load/format problems — a mid-run error
            # after a successful resume propagates with its real trace
            if not args.resume:
                raise
            print(f"cannot resume from {args.resume}: {e}",
                  file=sys.stderr)
            return 2
        except RetryExhausted as e:
            print(str(e), file=sys.stderr)
            return 3
        finally:
            # the final heartbeat carries the run's reported depth (so
            # a watchdog sees "finished" with depth == the stats line)
            if done:
                obs.finish(depth=int(r.depth),
                           states=int(r.distinct_states),
                           counters=r.metrics.as_dict(),
                           level_sizes=list(r.level_sizes))
            else:
                obs.finish(status="failed")
        secs = r.seconds
        viol = []
        for v in r.violations[:args.max_violations]:
            if v.state_id < 0:
                # pinned-prefix interior state (models/golden): checked
                # at seed time, never entered BFS — no parent chain
                trace = [("(pinned-prefix interior state — precedes "
                          "the seeded witness end)", v.state)]
            elif not args.no_store:
                trace = eng.trace(v.state_id)
            elif v.state is not None:
                # no parent archive, but the violating state itself was
                # decoded at detection time — always show it (TLC always
                # reports at least the bad state)
                trace = [("(violating state; run without --no-store "
                          "for the full trace)", v.state)]
            else:
                trace = None
            viol.append((v.invariant, trace))
        distinct, depth, gen = r.distinct_states, r.depth, \
            r.generated_states
        if r.overflow_faults:
            print(f"FAULT: {r.overflow_faults} un-representable states "
                  f"(bounds too small for the disabled-constraint space)",
                  file=sys.stderr)
    # ONE stats assembler (obs.metrics.check_stats) generates the
    # stdout line and --stats-json from the metrics registry — same
    # keys as the historical hand-built dict (pinned by
    # tests/test_obs.py), incl. pin_interior_states only when nonzero
    # and the fingerprint/burst telemetry only for the tpu engines
    from .obs.metrics import check_stats
    if args.engine == "oracle":
        counters = dict(
            distinct_states=int(distinct), generated_states=int(gen),
            depth=int(depth),
            pin_interior_states=int(
                getattr(r, "pin_interior_states", 0) or 0))
        out = check_stats(counters, secs, len(viol),
                          spec=ir.name, ir_fp=ir.fingerprint())
    else:
        out = check_stats(r.metrics.as_dict(), secs, len(viol),
                          fp_bits=128 if args.fp128 else 64,
                          spec=ir.name, ir_fp=ir.fingerprint())
    print(json.dumps(out))
    if args.stats_json:
        # oracle runs write the same stats file (minus the
        # fingerprint/burst telemetry keys the oracle has no notion of)
        with open(args.stats_json, "w") as fh:
            json.dump(out, fh)
    for k, (name, trace) in enumerate(viol):
        if args.engine == "oracle":
            print(f"\nViolation {k}: {name}")
            if trace:
                print("  " + " -> ".join(trace))
            elif trace is None:
                # pinned-prefix interior state (models/golden): outside
                # the BFS parent map, so there is no action trace.
                # (A ROOT violation has an EMPTY trace, not None.)
                print("  (pinned-prefix interior state — precedes the "
                      "seeded witness end)")
            else:
                print("  (violation at a root state — empty trace)")
        else:
            _print_violation(k, name, trace)
    return 1 if viol else 0


def _write_seed(path, obj):
    with open(path, "w") as fh:
        json.dump(obj, fh)
    print(f"seed written to {path}", file=sys.stderr)


def _seed_obj(ir, sv, hist, arrs):
    """Witness end state -> the seed-file object `check --seed-trace`
    accepts: the active spec's oracle view (state_to_obj) plus the raw
    non-VIEW lanes, so a seeded engine resumes with identical
    constraint / scenario-predicate inputs.  ONE definition — trace
    and simulate both emit through it, so seed files cannot drift."""
    import numpy as np
    obj = ir.state_to_obj(sv, hist)
    obj["nonview"] = {k: np.asarray(arrs[k]).tolist()
                      for k in ir.nonview_keys}
    return obj


def _check_target(name, ir) -> bool:
    """Validate a --target against the active spec's scenario registry
    (SpecIR.scenario_properties — the ONE table trace, simulate and
    the help text all read, so new sim-reachable targets cannot drift
    out of the CLI).  Safety invariants are also accepted (hunting a
    real violation is a legitimate target)."""
    if name in ir.known_invariants:
        return True
    others = sorted(set(ir.known_invariants) -
                    set(ir.scenario_properties))
    print(f"unknown scenario property {name!r} for spec "
          f"{ir.name!r}; known scenario properties: "
          f"{', '.join(ir.scenario_properties)}\n"
          f"(safety invariants are accepted too: "
          f"{', '.join(others)})",
          file=sys.stderr)
    return False


def cmd_trace(args):
    ir, cfg = _load_cfg(args)
    if not _check_target(args.target, ir):
        return 2
    cfg = cfg.with_(invariants=(args.target,))
    if args.engine == "oracle":
        import time
        explore = ir.oracle_explore
        t0 = time.perf_counter()
        r = explore(cfg, max_depth=args.max_depth,
                    max_states=args.max_states, stop_on_violation=True,
                    trace_violations=True)
        if not r.violations:
            print(f"no witness found for {args.target} within bounds "
                  f"({r.distinct_states} states, depth {r.depth})")
            return 1
        print(f"witness for {args.target} at depth {r.depth} "
              f"({r.distinct_states} states explored, "
              f"{time.perf_counter() - t0:.1f}s):")
        for step, label in enumerate(r.violations[0].trace):
            print(f"  {step + 1:3d}  {label}")
        if args.emit_seed:
            v = r.violations[0]
            _write_seed(args.emit_seed,
                        ir.state_to_obj(v.state, v.hist))
        return 0
    from .engine.bfs import Engine
    eng = Engine(cfg, chunk=args.chunk, store_states=True,
                 guard_matmul=args.guard_matmul,
                 delta_matmul=args.delta_matmul,
                 sym_canon=args.sym_canon)
    r = eng.check(max_depth=args.max_depth, max_states=args.max_states,
                  stop_on_violation=True, verbose=args.verbose)
    if not r.violations:
        print(f"no witness found for {args.target} within bounds "
              f"({r.distinct_states} states, depth {r.depth})")
        return 1
    v = r.violations[0]
    print(f"witness for {args.target} at depth {r.depth} "
          f"({r.distinct_states} states explored, "
          f"{r.seconds:.1f}s):")
    for step, (label, sv) in enumerate(eng.trace(v.state_id)):
        print(f"  {step:3d}  {label}")
        if args.verbose:
            print(f"       {sv}")
    if args.emit_seed:
        arrs = eng.get_state_arrays(v.state_id)
        sv, h = ir.decode(eng.lay, arrs)
        _write_seed(args.emit_seed, _seed_obj(ir, sv, h, arrs))
    return 0


def cmd_simulate(args):
    """TLC ``-simulate`` analogue: W vmapped random walkers hunt a
    scenario property beyond the exhaustive stack's reach (sim/walker
    design notes).  Exit 0 on a witness, 1 on none within the step
    budget."""
    import time
    # a clear bounds error beats the jit-time shape traceback a
    # non-positive loop length would produce (ROADMAP sim follow-ups)
    for nm, val in (("--steps-per-dispatch", args.steps_per_dispatch),
                    ("--walkers", args.walkers),
                    ("--steps", args.steps)):
        if val <= 0:
            print(f"{nm} must be positive (got {val})",
                  file=sys.stderr)
            return 2
    ir, cfg = _load_cfg(args)
    if not _check_target(args.target, ir):
        return 2
    cfg = cfg.with_(invariants=(args.target,))
    # --max-depth doubles as the walk restart bound; the check-style
    # "unbounded" default maps to a walk-sized one
    depth = args.max_depth if args.max_depth < 10 ** 6 else 64
    import jax
    from .sim import SimEngine
    kw = dict(max_depth=depth, seed=args.seed, policy=args.policy,
              bloom_bits=args.bloom_bits,
              guard_matmul=args.guard_matmul,
              delta_matmul=args.delta_matmul,
              sym_canon=args.sym_canon)
    if args.mesh and len(jax.local_devices()) > 1:
        from .parallel.sim_mesh import ShardedSimEngine
        eng = ShardedSimEngine(cfg, walkers=args.walkers, **kw)
    else:
        eng = SimEngine(cfg, walkers=args.walkers, **kw)
    obs = _build_obs(args, ir, cfg=cfg, cmd="simulate")
    obs.start()
    t0 = time.perf_counter()
    done = False
    try:
        r = eng.run(steps=args.steps,
                    steps_per_dispatch=args.steps_per_dispatch,
                    verbose=args.verbose, obs=obs)
        done = True
    finally:
        if done:
            from .obs.metrics import sim_counters
            obs.finish(depth=int(r.steps_dispatched),
                       states=int(r.walker_steps),
                       counters=sim_counters(r))
        else:
            obs.finish(status="failed")
    # the ONE simulate stats assembler (obs.metrics.sim_stats) — same
    # keys as the historical hand-built dict
    from .obs.metrics import sim_stats
    out = sim_stats(r, target=args.target, policy=args.policy,
                    seed=args.seed, platform=jax.default_backend())
    # the active SpecIR stamp, appended last (same contract as
    # check_stats' spec/ir_fingerprint tail keys)
    out["spec"] = ir.name
    out["ir_fingerprint"] = ir.fingerprint()
    print(json.dumps(out))
    if args.stats_json:
        with open(args.stats_json, "w") as fh:
            json.dump(out, fh)
    if not r.hits:
        print(f"no witness found for {args.target} within "
              f"{r.walker_steps} walker-steps", file=sys.stderr)
        return 1
    h = eng.decode_hit(r.hits[0])
    print(f"witness for {args.target} at depth {h.depth} "
          f"(walker {h.walker}, {r.walker_steps} walker-steps, "
          f"{time.perf_counter() - t0:.1f}s):")
    for step, (label, sv) in enumerate(h.trace):
        print(f"  {step:3d}  {label}")
        if args.verbose:
            print(f"       {sv}")
    if args.trace_out:
        with open(args.trace_out, "w") as fh:
            json.dump({"target": args.target, "depth": h.depth,
                       "walker": h.walker, "seed": args.seed,
                       "labels": [label for label, _sv in h.trace]},
                      fh)
        print(f"witness trace written to {args.trace_out}",
              file=sys.stderr)
    if args.emit_seed:
        _write_seed(args.emit_seed,
                    _seed_obj(ir, h.trace[-1][1], h.hist,
                              h.state_arrs))
    return 0


def cmd_batch(args):
    """Multi-tenant batched checking (serve/): a job list from a JSONL
    file and/or repeated --job flags, grouped into shape buckets and
    run as one device program per bucket, with fingerprint-keyed
    result caching.  Prints one summary JSON line, then one report
    line per job (submission order).  Exit 0 = all clean, 1 = some job
    found violations, 2 = usage error."""
    from .cfg.parser import CfgError
    from .serve import (ResultCache, job_from_dict, load_jobs,
                        run_jobs)
    jobs = []
    if args.jobs:
        try:
            jobs.extend(load_jobs(args.jobs))
        except (OSError, ValueError, CfgError) as e:
            print(str(e), file=sys.stderr)
            return 2
    for k, text in enumerate(args.job or []):
        where = f"--job #{k + 1}"
        try:
            jobs.append(job_from_dict(json.loads(text), where=where))
        except (OSError, ValueError) as e:
            # OSError too: a missing config path is a usage error
            # (exit 2), not a violation-style exit 1
            msg = str(e) if str(e).startswith(where) \
                else f"{where}: {e}"
            print(msg, file=sys.stderr)
            return 2
    if not jobs:
        print("no jobs: pass --jobs FILE.jsonl and/or --job JSON",
              file=sys.stderr)
        return 2
    if args.cache_max_bytes is not None and args.cache_max_bytes <= 0:
        print(f"--cache-max-bytes must be positive (got "
              f"{args.cache_max_bytes}); omit it for an unbounded "
              "cache", file=sys.stderr)
        return 2
    if args.cache_max_bytes is not None and not args.cache_dir:
        print("--cache-max-bytes bounds the on-disk result cache: "
              "add --cache-dir", file=sys.stderr)
        return 2
    if args.wave_yield is not None and args.wave_yield < 1:
        print(f"--wave-yield must be >= 1 (got {args.wave_yield})",
              file=sys.stderr)
        return 2
    if args.max_wave is not None and args.max_wave < 1:
        print(f"--max-wave must be >= 1 (got {args.max_wave})",
              file=sys.stderr)
        return 2
    try:
        from .serve.batch import resolve_wave_mesh
        resolve_wave_mesh(args.wave_mesh)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.executable_cache_max_bytes is not None:
        if args.executable_cache_max_bytes <= 0:
            print(f"--executable-cache-max-bytes must be positive "
                  f"(got {args.executable_cache_max_bytes}); omit it "
                  "for an unbounded cache", file=sys.stderr)
            return 2
        if not args.executable_cache:
            print("--executable-cache-max-bytes bounds the on-disk "
                  "executable cache: add --executable-cache",
                  file=sys.stderr)
            return 2
    err = _check_retry_flags(args) or _install_chaos(args)
    if err:
        print(err, file=sys.stderr)
        return 2
    cache = ResultCache(args.cache_dir,
                        max_bytes=args.cache_max_bytes) \
        if args.cache_dir else None
    exec_cache = None
    if args.executable_cache:
        from .serve.exec_cache import ExecCache
        exec_cache = ExecCache(
            args.executable_cache,
            max_bytes=args.executable_cache_max_bytes)
    obs = _build_obs(args, cmd="batch")
    obs.start()
    done = False
    rep = None
    import time as _time
    from .resil.supervisor import RETRYABLE, backoff_delay
    attempt = 0
    try:
        while True:
            try:
                rep = run_jobs(jobs, cache=cache, obs=obs,
                               sequential=args.sequential,
                               verbose=args.verbose,
                               wave_state=args.wave_state,
                               wave_yield=args.wave_yield,
                               max_wave=args.max_wave,
                               wave_mesh=args.wave_mesh,
                               bucket_overrides=(
                                   {"sym_canon": args.sym_canon}
                                   if args.sym_canon != "auto"
                                   else None),
                               exec_cache=exec_cache)
                done = True
                break
            except RETRYABLE as e:
                # a retried batch is incremental: finished jobs answer
                # from the result cache, stragglers resume mid-BFS
                # from --wave-state
                if attempt >= args.retries:
                    print(f"batch run failed: {e}", file=sys.stderr)
                    return 3
                wait = backoff_delay(attempt, args.backoff, 60.0)
                obs.retry(attempt=attempt + 1,
                          max_attempts=args.retries + 1,
                          wait_s=wait, error=e)
                _time.sleep(wait)
                attempt += 1
    finally:
        if done:
            obs.finish(
                depth=max((int(o.report.get("depth", 0))
                           for o in rep.outcomes), default=0),
                states=sum(int(o.report.get("distinct_states", 0))
                           for o in rep.outcomes),
                # the batch summary's scalar counters (jobs, buckets,
                # cache hits, dispatches) are the run's registry record
                counters={k: v for k, v in rep.summary.items()
                          if isinstance(v, (int, float))
                          and not isinstance(v, bool)})
        else:
            obs.finish(status="failed")
    print(json.dumps(rep.summary))
    for o in rep.outcomes:
        print(json.dumps(o.report))
    if args.stats_json:
        with open(args.stats_json, "w") as fh:
            json.dump({"summary": rep.summary,
                       "jobs": [o.report for o in rep.outcomes]}, fh)
    n_viol = sum(int(o.report.get("violations", 0))
                 for o in rep.outcomes)
    return 1 if n_viol else 0


def cmd_serve(args):
    """The persistent checking daemon (serve/daemon): watch a spool
    directory (and/or tail a JSONL stream) for job submissions, drain
    claimed jobs through the shared wave scheduler, and write one
    atomic result JSON + done/ marker per submission.  Runs until
    SIGTERM/SIGINT (graceful drain, exit 0) or --max-idle-polls.
    Exit 0 = drained cleanly, 2 = usage error, 3 = a serve cycle
    exhausted its retries (the supervisor's restart signal)."""
    from .serve import Daemon, ResultCache
    if args.poll <= 0:
        print(f"--poll must be positive (got {args.poll})",
              file=sys.stderr)
        return 2
    if args.grace < 0:
        print(f"--grace must be >= 0 (got {args.grace})",
              file=sys.stderr)
        return 2
    if args.max_idle_polls is not None and args.max_idle_polls < 1:
        print(f"--max-idle-polls must be >= 1 "
              f"(got {args.max_idle_polls})", file=sys.stderr)
        return 2
    if args.wave_yield is not None and args.wave_yield < 1:
        print(f"--wave-yield must be >= 1 (got {args.wave_yield})",
              file=sys.stderr)
        return 2
    if args.max_wave is not None and args.max_wave < 1:
        print(f"--max-wave must be >= 1 (got {args.max_wave})",
              file=sys.stderr)
        return 2
    try:
        from .serve.batch import resolve_wave_mesh
        resolve_wave_mesh(args.wave_mesh)
    except ValueError as e:
        print(str(e), file=sys.stderr)
        return 2
    if args.cache_max_bytes is not None and args.cache_max_bytes <= 0:
        print(f"--cache-max-bytes must be positive (got "
              f"{args.cache_max_bytes}); omit it for an unbounded "
              "cache", file=sys.stderr)
        return 2
    if args.executable_cache_max_bytes is not None:
        if args.executable_cache_max_bytes <= 0:
            print(f"--executable-cache-max-bytes must be positive "
                  f"(got {args.executable_cache_max_bytes}); omit it "
                  "for an unbounded cache", file=sys.stderr)
            return 2
        if not args.executable_cache:
            print("--executable-cache-max-bytes bounds the on-disk "
                  "executable cache: add --executable-cache",
                  file=sys.stderr)
            return 2
    err = _check_retry_flags(args) or _install_chaos(args)
    if err:
        print(err, file=sys.stderr)
        return 2
    # the daemon is restart-proof BY DEFAULT: result cache and wave
    # state live under the spool unless pointed elsewhere
    cache_dir = args.cache_dir or os.path.join(args.spool, "cache")
    wave_dir = args.wave_state or os.path.join(args.spool, "waves")
    cache = ResultCache(cache_dir, max_bytes=args.cache_max_bytes)
    exec_cache = None
    if args.executable_cache:
        from .serve.exec_cache import ExecCache
        exec_cache = ExecCache(
            args.executable_cache,
            max_bytes=args.executable_cache_max_bytes)
    obs = _build_obs(args, cmd="serve")
    obs.start()
    daemon = Daemon(
        args.spool, cache=cache, wave_state=wave_dir,
        exec_cache=exec_cache, obs=obs, poll_s=args.poll,
        wave_yield=args.wave_yield,
        max_wave=args.max_wave, wave_mesh=args.wave_mesh,
        bucket_overrides=({"sym_canon": args.sym_canon}
                          if args.sym_canon != "auto" else None),
        retries=args.retries, backoff=args.backoff,
        max_idle_polls=args.max_idle_polls, stream=args.stream,
        grace_s=args.grace, verbose=args.verbose)
    daemon.install_signals()
    # daemon.run owns obs.finish (the drain epilogue must run on
    # every exit path, with the daemon's own counters)
    return daemon.run()


def _load_baseline_file(path, row):
    """A committed baseline for ``obs regress``: a --stats-json
    payload, a bench headline object, a registry record, or a BENCH
    A/B file with a ``rows`` map (then --baseline-row picks one)."""
    with open(path) as fh:
        obj = json.load(fh)
    if isinstance(obj, dict) and isinstance(obj.get("rows"), dict):
        if not row:
            raise SystemExit(
                f"{path} holds multiple A/B rows; pick one with "
                f"--baseline-row (known: "
                f"{', '.join(sorted(obj['rows']))})")
        if row not in obj["rows"]:
            raise SystemExit(
                f"--baseline-row {row!r} not in {path} (known: "
                f"{', '.join(sorted(obj['rows']))})")
        return obj["rows"][row]
    if row:
        raise SystemExit(f"--baseline-row given but {path} has no "
                         "'rows' map")
    return obj


def cmd_obs(args):
    """``cli obs`` — the registry's query surface (obs/report.py).

    ls      — filterable run table (newest last).
    show    — one run's full record (counters, span rollups,
              resource peaks, artifacts) as indented JSON.
    diff    — machine-readable parity verdict + per-phase span deltas
              between two runs; exit 1 on count mismatch.
    regress — a run against a prior run (--against) or a committed
              baseline file (--baseline); exit 1 on count mismatch or
              a tripped --max-span-ratio bound, 2 on usage errors.

    Run tokens: a full run id, a unique id prefix, or ``last``."""
    from .obs.registry import RunRegistry
    from .obs.report import diff_runs, regress
    reg = RunRegistry(args.registry)

    def resolve(token):
        rid = reg.resolve(token)
        if rid is None:
            ids = reg.run_ids()
            print(f"no unique run matches {token!r} in "
                  f"{args.registry} ({len(ids)} records"
                  + (f"; newest {ids[-1]}" if ids else "")
                  + ")", file=sys.stderr)
        return rid

    if args.obs_cmd == "ls":
        rows = []
        for rid, rec in reg.records():
            if args.spec and rec.get("spec") != args.spec:
                continue
            if args.cmd_filter and rec.get("cmd") != args.cmd_filter:
                continue
            if args.status and rec.get("status") != args.status:
                continue
            rows.append(rec)
        print(f"{'run_id':34s} {'cmd':9s} {'spec':6s} {'status':9s} "
              f"{'depth':>6s} {'states':>10s} {'seconds':>8s}")
        for rec in rows:
            print(f"{str(rec.get('run_id', '?')):34s} "
                  f"{str(rec.get('cmd', '?')):9s} "
                  f"{str(rec.get('spec', '-')):6s} "
                  f"{str(rec.get('status', '?')):9s} "
                  f"{str(rec.get('depth', '-')):>6s} "
                  f"{str(rec.get('distinct_states', '-')):>10s} "
                  f"{str(rec.get('seconds', '-')):>8s}")
        return 0
    if args.obs_cmd == "show":
        rid = resolve(args.run)
        if rid is None:
            return 2
        print(json.dumps(reg.load(rid), indent=1))
        return 0
    if args.obs_cmd == "diff":
        ra, rb = resolve(args.run_a), resolve(args.run_b)
        if ra is None or rb is None:
            return 2
        rep = diff_runs(reg.load(ra), reg.load(rb))
        print(json.dumps(rep))
        return 1 if rep["verdict"] == "mismatch" else 0
    if args.obs_cmd == "regress":
        if bool(args.against) == bool(args.baseline):
            print("obs regress needs exactly one of --against RUN / "
                  "--baseline FILE", file=sys.stderr)
            return 2
        rid = resolve(args.run)
        if rid is None:
            return 2
        if args.against:
            bid = resolve(args.against)
            if bid is None:
                return 2
            baseline = reg.load(bid)
        else:
            baseline = _load_baseline_file(args.baseline,
                                           args.baseline_row)
        rep, code = regress(reg.load(rid), baseline,
                            max_span_ratio=args.max_span_ratio,
                            min_seconds=args.min_seconds)
        print(json.dumps(rep))
        return code
    raise SystemExit(f"unknown obs subcommand {args.obs_cmd!r}")


def main(argv=None):
    p = argparse.ArgumentParser(
        prog="raft_tla_tpu",
        description="TPU-native explicit-state model checker for the "
                    "Raft spec family")
    sub = p.add_subparsers(dest="cmd", required=True)

    def common(sp):
        sp.add_argument("cfg", nargs="?", default=None,
                        help="model file: a TLC .cfg path (--spec "
                             "raft; required) or a TLC .cfg / JSON "
                             "constants file / 'default' (--spec "
                             "paxos; optional)")
        sp.add_argument("--spec", choices=("raft", "paxos"),
                        default="raft",
                        help="which spec frontend (SpecIR) to check: "
                             "the Raft membership-change spec "
                             "(default) or bounded single-decree/"
                             "multi-instance Paxos — same engines, "
                             "same flags, same oracle-differential "
                             "guarantees")
        sp.add_argument("--engine", choices=("tpu", "oracle"),
                        default="tpu")
        sp.add_argument("--chunk", type=int, default=512)
        sp.add_argument("--max-depth", type=int, default=10 ** 9)
        sp.add_argument("--max-states", type=int, default=10 ** 9)
        sp.add_argument("--servers", type=int, default=None,
                        help="override |Server|")
        sp.add_argument("--init-servers", type=int, default=None,
                        help="override |InitServer| (first K servers)")
        sp.add_argument("--symmetry", action=argparse.BooleanOptionalAction,
                        default=None)
        sp.add_argument("--next", dest="next_family", default=None,
                        choices=("NextAsync", "NextAsyncCrash", "Next",
                                 "NextDynamic"),
                        help="override the cfg's NEXT family (e.g. "
                             "NextDynamic enables the membership "
                             "actions the MembershipChange* scenario "
                             "targets need)")
        sp.add_argument("--max-terms", type=int, default=None)
        sp.add_argument("--max-log-length", type=int, default=None)
        sp.add_argument("--max-timeouts", type=int, default=None)
        sp.add_argument("--max-client-requests", type=int, default=None)
        sp.add_argument("--max-restarts", type=int, default=None)
        sp.add_argument("--fp128", action="store_true")
        # --spec paxos constants (ignored for raft)
        sp.add_argument("--ballots", type=int, default=None,
                        help="paxos: ballots 0..N-1 (--spec paxos)")
        sp.add_argument("--paxos-values", type=int, default=None,
                        help="paxos: values 0..N-1 (--spec paxos)")
        sp.add_argument("--instances", type=int, default=None,
                        help="paxos: independent consensus instances "
                             "(--spec paxos)")
        sp.add_argument("--guard-matmul",
                        action=argparse.BooleanOptionalAction,
                        default=True,
                        help="MXU-native expansion (default ON, "
                             "bit-exact): the guard grid runs as one "
                             "int8 matmul against the packed guard "
                             "matrix and enabled-lane materialization "
                             "as one-hot einsum blocks; --no-guard-"
                             "matmul restores the vmapped per-lane "
                             "sweep exactly")
        sp.add_argument("--delta-matmul",
                        action=argparse.BooleanOptionalAction,
                        default=True,
                        help="delta-matmul successor generation "
                             "(default ON, bit-exact): families with "
                             "declared delta algebras apply as ONE "
                             "batched scatter-as-matmul per family "
                             "group (int32 einsum blocks on the MXU); "
                             "declaration-less families keep the "
                             "per-family kernel path either way, and "
                             "--no-delta-matmul restores it for all")
        sp.add_argument("--sym-canon",
                        choices=("auto", "sort", "minperm"),
                        default="auto",
                        help="symmetry canonicalization (round 15): "
                             "'sort' hashes ONE orbit-sorted canonical "
                             "relabeling per state (equivariant "
                             "signatures + argsort; signature ties "
                             "fall back to min-over-residual-perms, "
                             "so the state partition is IDENTICAL); "
                             "'minperm' keeps the P-fold "
                             "min-over-perms; 'auto' (default) picks "
                             "sort past 6 perms.  Fingerprint VALUES "
                             "are mode-specific — checkpoints refuse "
                             "cross-mode resume")
        sp.add_argument("--verbose", "-v", action="store_true")

    pc = sub.add_parser("check", help="exhaustive bounded check")
    common(pc)
    pc.add_argument("--keep-going", action="store_true",
                    help="do not stop at the first violation")
    pc.add_argument("--spill", action="store_true",
                    help="host-spill engine: stream levels through "
                         "host RAM (TLC's disk-spill counterpart) — "
                         "required past the single-chip HBM depth wall")
    pc.add_argument("--pjit", action="store_true",
                    help="pod-scale pjit engine (parallel/pjit_mesh): "
                         "the whole BFS state lives under named "
                         "shardings on a mesh spanning every host's "
                         "devices (multi-controller runs span hosts "
                         "after jax.distributed.initialize), with the "
                         "hash-ownership dedup exchange compiled as "
                         "in-program collectives; counts/gids/traces "
                         "are bit-identical to the default engine")
    pc.add_argument("--seg", type=int, default=1 << 21,
                    help="spill segment capacity in states (--spill)")
    pc.add_argument("--host-table", action="store_true",
                    help="host-partitioned visited table (needs "
                         "--spill): the authoritative fingerprint set "
                         "lives in host RAM as fingerprint-prefix "
                         "partitions streamed through HBM per level; "
                         "the device table becomes a bounded cache — "
                         "breaks the ~2^29-slot HBM dedup ceiling "
                         "(TLC's disk-spillable fingerprint set "
                         "counterpart)")
    pc.add_argument("--sweep-stage",
                    action=argparse.BooleanOptionalAction,
                    default=True,
                    help="double-buffered pre-sweep H2D staging "
                         "(--host-table): issue the next sweep's "
                         "partition-image uploads at level start so "
                         "the DMA overlaps the level's compute "
                         "instead of serializing inside the sweep "
                         "(h2d_stage/sweep_overlap spans on the "
                         "ledger/timeline; counts are identical "
                         "either way — --no-sweep-stage is the A/B "
                         "reference)")
    pc.add_argument("--partitions", type=int, default=4, metavar="P",
                    help="host-table partition count, a power of two "
                         "(counts are P-invariant; P sizes the "
                         "largest image HBM must hold at once)")
    pc.add_argument("--part-cap", type=int, default=1 << 16,
                    metavar="N",
                    help="initial slots per host-table partition "
                         "(grows 4x on the 0.40 load bound)")
    pc.add_argument("--archive-dir", default=None, metavar="DIR",
                    help="disk-backed trace archives: stream each "
                         "level's parent/lane/state rows to memmap'd "
                         "files under DIR instead of growing host "
                         "arrays (store_states runs stay RAM-bounded; "
                         "traces replay from the memmaps)")
    pc.add_argument("--burst", action=argparse.BooleanOptionalAction,
                    default=True,
                    help="fused multi-level dispatch: run whole runs "
                         "of small BFS levels inside one device "
                         "program instead of one dispatch+sync per "
                         "level (--no-burst restores the pure "
                         "per-level driver; counts are bit-identical "
                         "either way)")
    pc.add_argument("--burst-levels", type=int, default=None,
                    metavar="K",
                    help="max levels fused per burst device call "
                         "(default 16)")
    pc.add_argument("--dedup-kernel", choices=("auto", "on", "off"),
                    default="auto",
                    help="Pallas probe/claim-insert dedup kernel "
                         "(engine/fingerprint): 'auto' engages it on "
                         "TPU only; 'on' forces it everywhere (runs "
                         "through the Pallas interpreter off-TPU — "
                         "slow, for differential testing); 'off' "
                         "keeps the lax gather/scatter sequence. "
                         "Outcomes are bit-identical in every mode")
    pc.add_argument("--fam-cap-density", default=None, metavar="SPEC",
                    help="override per-family enabled-lane density "
                         "caps as fam=k,fam2=k2 (e.g. "
                         "Receive=8,Timeout=2): cap_f = chunk * "
                         "min(lanes_f, k).  Tunes cap-overflow "
                         "replays without editing engine/expand.py; "
                         "unknown families / non-positive k are "
                         "rejected with a clear error")
    pc.add_argument("--stats-json", default=None, metavar="FILE",
                    help="write the run stats JSON (incl. "
                         "levels_fused/burst_bailouts) to FILE")
    _add_obs_flags(pc)
    pc.add_argument("--no-store", action="store_true",
                    help="do not retain states (no traces; less memory)")
    pc.add_argument("--max-violations", type=int, default=5)
    pc.add_argument("--checkpoint", default=None, metavar="FILE",
                    help="write a resumable checkpoint every "
                         "--checkpoint-every levels (tpu engine; TLC's "
                         "states/ dir counterpart)")
    pc.add_argument("--checkpoint-every", type=int, default=5,
                    metavar="N",
                    help="levels between checkpoints (each checkpoint "
                         "is a full snapshot incl. the visited set and "
                         "any trace archives — frequent checkpoints on "
                         "deep store_states runs are I/O-heavy)")
    pc.add_argument("--resume", default=None, metavar="FILE",
                    help="resume a checkpointed run (final counts are "
                         "identical to an uninterrupted run).  A torn "
                         "or corrupt head falls back to the previous "
                         "valid checkpoint in the last-K chain with a "
                         "named warning")
    pc.add_argument("--ckpt-keep", type=int, default=2, metavar="K",
                    help="checkpoint-chain depth: keep the last K "
                         "checkpoints (FILE, FILE.1, ...), each with "
                         "a sha256 integrity sidecar, so a crash "
                         "mid-write never strands the run (default 2; "
                         "1 = the historical single file)")
    pc.add_argument("--resume-portable", action="store_true",
                    help="shape-portable resume (needs --spill): "
                         "re-partition ANY engine family's checkpoint "
                         "— classic, spill, or a mesh of any device "
                         "count — onto this engine by re-inserting "
                         "the visited key set and re-routing the "
                         "frontier (resil/portable)")
    pc.add_argument("--retries", type=int, default=0, metavar="N",
                    help="supervised retry/backoff (resil/supervisor): "
                         "on a transient failure (dropped tunnel, "
                         "device error), reinit the backend and "
                         "resume from the newest valid checkpoint, up "
                         "to N times with bounded exponential backoff "
                         "+ jitter; attempts are stamped into the "
                         "ledger and heartbeat")
    pc.add_argument("--backoff", type=float, default=2.0, metavar="S",
                    help="base backoff seconds for --retries "
                         "(doubles per attempt, capped at 60s, "
                         "deterministic jitter)")
    pc.add_argument("--chaos", default=None, metavar="SPEC",
                    help="deterministic fault injection "
                         "(resil/chaos): e.g. "
                         "'dispatch:every=2;ckpt_torn:at=1' — seeded "
                         "schedule firing at named engine sites "
                         "(dispatch, ckpt_torn, ckpt_corrupt, "
                         "archive, host_table, wave_kill), so every "
                         "recovery path is testable on CPU")
    pc.add_argument("--seed-trace", default=None, metavar="FILE",
                    help="punctuated search: explore only extensions of "
                         "the seed state(s) in FILE (emitted by `trace "
                         "--emit-seed`; the engine analog of the spec's "
                         "hard-coded prefix pins, raft.tla:1198-1234)")
    # cfg toggles, check-only (trace derives its invariant from --target):
    # ADD to the cfg's lists, mirroring TLC's additive repeated blocks
    pc.add_argument("--invariant", dest="invariants",
                    action="append", default=None, metavar="NAME",
                    help="enable an extra invariant (repeatable) — the "
                         "CLI analog of uncommenting the cfg's "
                         "Test-cases block")
    pc.add_argument("--constraint", dest="constraint_overrides",
                    action="append", default=None, metavar="NAME",
                    help="enable an extra CONSTRAINT (repeatable)")
    pc.add_argument("--action-constraint", dest="action_constraints",
                    action="append", default=None, metavar="NAME",
                    help="enable an extra ACTION_CONSTRAINT (repeatable)")
    pc.set_defaults(fn=cmd_check)

    # --target help comes from the per-spec scenario registries
    # (SpecIR.scenario_properties) so new sim-reachable targets cannot
    # drift out of the help text
    from .spec import get_spec
    target_help = ("scenario property of the active --spec (raft: " +
                   ", ".join(get_spec("raft").scenario_properties) +
                   "; paxos: " +
                   ", ".join(get_spec("paxos").scenario_properties) +
                   ")")

    pt = sub.add_parser("trace", help="generate a scenario witness trace")
    common(pt)
    pt.add_argument("--target", required=True, help=target_help)
    pt.add_argument("--emit-seed", default=None, metavar="FILE",
                    help="write the witness end state to FILE as a seed "
                         "for `check --seed-trace` (punctuated search)")
    pt.set_defaults(fn=cmd_trace)

    ps = sub.add_parser(
        "simulate",
        help="random-walk scenario hunt (TLC -simulate analogue): W "
             "vmapped walkers sample enabled actions uniformly — for "
             "configs beyond the exhaustive stack's reach")
    common(ps)
    ps.add_argument("--target", required=True, help=target_help)
    ps.add_argument("--walkers", type=int, default=256,
                    help="fleet width W (one vmapped lane per walker)")
    ps.add_argument("--steps", type=int, default=10000,
                    help="synchronous fleet steps before giving up")
    ps.add_argument("--steps-per-dispatch", type=int, default=256,
                    help="walker steps fused into one device program "
                         "(the persistent-kernel loop length)")
    ps.add_argument("--seed", type=int, default=0,
                    help="PRNG seed; fixed seeds replay bit-identical "
                         "trajectories across runs and --walkers "
                         "shardings")
    ps.add_argument("--policy", choices=("punctuated", "tlc"),
                    default="punctuated",
                    help="restart policy: 'punctuated' (default) "
                         "resamples pruned successors and restarts "
                         "from per-walker scenario-ladder bases; "
                         "'tlc' is exact TLC -simulate shape (abandon "
                         "the walk on any pruned successor)")
    ps.add_argument("--bloom-bits", type=int, default=24,
                    help="log2 bits of the novelty Bloom filter behind "
                         "est_distinct_states")
    ps.add_argument("--mesh", action="store_true",
                    help="shard the fleet across all local devices "
                         "(pmapped per-device cohorts)")
    ps.add_argument("--trace-out", default=None, metavar="FILE",
                    help="write the witness trace (labels) as JSON")
    ps.add_argument("--emit-seed", default=None, metavar="FILE",
                    help="write the witness end state as a seed for "
                         "`check --seed-trace` (simulation feeds "
                         "punctuated exhaustive search)")
    ps.add_argument("--stats-json", default=None, metavar="FILE",
                    help="write the run stats JSON to FILE")
    _add_obs_flags(ps)
    ps.set_defaults(fn=cmd_simulate)

    pb = sub.add_parser(
        "batch",
        help="multi-tenant batched checking: many (spec, config) jobs "
             "packed into one device program per shape bucket, with "
             "fingerprint-keyed result caching (README 'Batch / "
             "serving' documents the JSONL job format)")
    pb.add_argument("--jobs", default=None, metavar="FILE",
                    help="JSONL job file: one job object per line "
                         "(blank lines and #-comments skipped)")
    pb.add_argument("--job", action="append", default=None,
                    metavar="JSON",
                    help="inline job object (repeatable), same schema "
                         "as a --jobs line")
    pb.add_argument("--cache-dir", default=None, metavar="DIR",
                    help="result cache: jobs whose (spec, config, "
                         "engine-options) fingerprints match a cached "
                         "result are answered with zero device "
                         "dispatches; results persist across "
                         "invocations")
    pb.add_argument("--cache-max-bytes", type=int, default=None,
                    metavar="N",
                    help="LRU-by-bytes cache bound: every completed "
                         "job's put trims the --cache-dir back under "
                         "N bytes, least-recently-used payloads "
                         "first (default: unbounded, the historical "
                         "behavior)")
    pb.add_argument("--executable-cache", default=None, metavar="DIR",
                    help="persistent AOT executable cache (serve/"
                         "exec_cache): bucket executables serialize "
                         "to DIR around .lower().compile(), so a "
                         "service restart re-loads them instead of "
                         "re-paying the 30-50s TPU compiles; on a "
                         "backend that cannot serialize executables "
                         "every entry reads as a labeled miss "
                         "(counted in the summary/ledger), never a "
                         "crash")
    pb.add_argument("--executable-cache-max-bytes", type=int,
                    default=None, metavar="N",
                    help="LRU-by-bytes bound on the executable cache "
                         "directory (entries are MBs each on TPU): "
                         "every store trims --executable-cache back "
                         "under N bytes, least-recently-USED entries "
                         "first (recency = mtime, refreshed on warm "
                         "loads; the just-stored entry is never the "
                         "victim; default: unbounded)")
    pb.add_argument("--sequential", action="store_true",
                    help="run each job on its own engine instead of "
                         "the batched path (the honest A/B reference "
                         "— N jobs pay N compiles)")
    pb.add_argument("--wave-state", default=None, metavar="DIR",
                    help="preemptible waves (serve/wavestate): "
                         "persist every live job's carry slice at "
                         "each wave boundary, so a killed run "
                         "resumes finished jobs from --cache-dir and "
                         "stragglers mid-BFS — bit-exact per job")
    pb.add_argument("--wave-yield", type=int, default=None,
                    metavar="N",
                    help="preemption: a wave yields its lanes after "
                         "N batched device calls while other jobs "
                         "wait (higher Job priority runs first); "
                         "parked jobs continue in a later wave")
    pb.add_argument("--max-wave", type=int, default=None, metavar="N",
                    help="jobs-per-wave ceiling (default: 8 per mesh "
                         "device); shrink it to force parking or to "
                         "bound wave memory")
    pb.add_argument("--wave-mesh", default="auto",
                    metavar="auto|N|JxS|off",
                    help="shard each batched wave across a 2-D "
                         "(jobs, state) mesh of local devices: 'auto' "
                         "(default) = all local devices on the job "
                         "axis (state shards kick in when a bucket's "
                         "ceiling exceeds the per-device budget), "
                         "'off' = the single-device wave, N = the "
                         "first N devices on the job axis, JxS (e.g. "
                         "4x2) = J job rows x S state shards so one "
                         "huge job's visited table/rings span S "
                         "devices; per-job results are bit-exact in "
                         "every mode")
    pb.add_argument("--retries", type=int, default=0, metavar="N",
                    help="re-run the job list up to N times on a "
                         "transient failure, with bounded exponential "
                         "backoff — incremental via --cache-dir + "
                         "--wave-state")
    pb.add_argument("--backoff", type=float, default=2.0, metavar="S",
                    help="base backoff seconds for --retries")
    pb.add_argument("--chaos", default=None, metavar="SPEC",
                    help="deterministic fault injection (resil/"
                         "chaos); 'wave_kill:at=1' is the "
                         "deterministic SIGKILL stand-in the CI "
                         "chaos smoke uses")
    pb.add_argument("--sym-canon",
                    choices=("auto", "sort", "minperm"),
                    default="auto",
                    help="symmetry canonicalization for every bucket "
                         "engine and solo fallback (see check "
                         "--sym-canon); part of the executable cache "
                         "key — sort and minperm never share a "
                         "compiled bucket")
    pb.add_argument("--stats-json", default=None, metavar="FILE",
                    help="write the batch summary + per-job reports "
                         "as one JSON file")
    pb.add_argument("--verbose", "-v", action="store_true")
    _add_obs_flags(pb)
    pb.set_defaults(fn=cmd_batch)

    pd = sub.add_parser(
        "serve",
        help="persistent checking daemon: watch a spool directory "
             "(and/or tail a JSONL stream) for job files, claim them "
             "atomically, drain them through the shared wave "
             "scheduler, and write one atomic result JSON + done/ "
             "marker per job; SIGTERM drains gracefully (README "
             "'Daemon service' documents the spool protocol)")
    pd.add_argument("--spool", required=True, metavar="DIR",
                    help="spool root: incoming/ claimed/ rejected/ "
                         "results/ done/ are created under it; "
                         "clients write-then-rename one JSON job "
                         "object per file (trailing newline) into "
                         "incoming/")
    pd.add_argument("--stream", default=None, metavar="FILE",
                    help="also tail this append-only JSONL job "
                         "stream: each complete appended line "
                         "materializes as a spool submission "
                         "(stream-<n>); the consumed offset persists "
                         "across restarts")
    pd.add_argument("--poll", type=float, default=0.5, metavar="SEC",
                    help="spool poll interval while idle "
                         "(default 0.5)")
    pd.add_argument("--grace", type=float, default=5.0, metavar="SEC",
                    help="seconds an incomplete submission (no "
                         "trailing newline — a writer mid-write) may "
                         "sit in incoming/ before it quarantines as "
                         "torn (default 5)")
    pd.add_argument("--max-idle-polls", type=int, default=None,
                    metavar="N",
                    help="drain and exit 0 after N consecutive empty "
                         "polls (default: run until SIGTERM; CI "
                         "smokes use this for bounded runs)")
    pd.add_argument("--cache-dir", default=None, metavar="DIR",
                    help="result cache directory (default: "
                         "SPOOL/cache) — duplicate submissions are "
                         "answered from it with zero device "
                         "dispatches")
    pd.add_argument("--cache-max-bytes", type=int, default=None,
                    metavar="N",
                    help="LRU-by-bytes result-cache bound (see "
                         "batch --cache-max-bytes)")
    pd.add_argument("--executable-cache", default=None, metavar="DIR",
                    help="persistent AOT executable cache: a warm "
                         "daemon restart performs ZERO bucket "
                         "compiles (see batch --executable-cache)")
    pd.add_argument("--executable-cache-max-bytes", type=int,
                    default=None, metavar="N",
                    help="LRU-by-bytes bound on the executable cache "
                         "(see batch --executable-cache-max-bytes)")
    pd.add_argument("--wave-state", default=None, metavar="DIR",
                    help="wave-state directory (default: SPOOL/waves) "
                         "— live jobs persist their carry at every "
                         "wave boundary, so a killed daemon resumes "
                         "stragglers mid-BFS bit-exact on restart")
    pd.add_argument("--wave-yield", type=int, default=None,
                    metavar="N",
                    help="fairness: a wave yields its lanes after N "
                         "batched device calls while other claimed "
                         "jobs wait (higher Job priority runs first)")
    pd.add_argument("--max-wave", type=int, default=None, metavar="N",
                    help="jobs-per-wave ceiling (default: 8 per mesh "
                         "device; see batch --max-wave)")
    pd.add_argument("--wave-mesh", default="auto",
                    metavar="auto|N|JxS|off",
                    help="2-D (jobs, state) mesh sharding for every "
                         "wave (see batch --wave-mesh); the daemon "
                         "restart matrix is portable — a restart "
                         "under ANY mesh shape (2-D included) "
                         "resumes the parked wave state bit-exact")
    pd.add_argument("--retries", type=int, default=0, metavar="N",
                    help="re-run a failed serve cycle up to N times "
                         "with bounded exponential backoff "
                         "(incremental via the result cache + wave "
                         "state); exhaustion exits 3")
    pd.add_argument("--backoff", type=float, default=2.0, metavar="S",
                    help="base backoff seconds for --retries")
    pd.add_argument("--chaos", default=None, metavar="SPEC",
                    help="deterministic fault injection (resil/"
                         "chaos); 'intake' faults the spool scan, "
                         "'wave_kill:at=1' is the deterministic "
                         "SIGKILL stand-in the daemon smoke uses")
    pd.add_argument("--sym-canon",
                    choices=("auto", "sort", "minperm"),
                    default="auto",
                    help="symmetry canonicalization for every bucket "
                         "engine (see batch --sym-canon)")
    pd.add_argument("--verbose", "-v", action="store_true")
    _add_obs_flags(pd)
    pd.set_defaults(fn=cmd_serve)

    po = sub.add_parser(
        "obs",
        help="query the run registry: ls (run table), show RUN, "
             "diff A B (parity verdict + span deltas), regress "
             "(verdict vs a prior run or committed baseline; exit "
             "nonzero on count mismatch / span-ratio regression)")
    osub = po.add_subparsers(dest="obs_cmd", required=True)

    def _reg_flag(sp):
        sp.add_argument("--registry", required=True, metavar="DIR",
                        help="the registry directory earlier runs "
                             "recorded into")

    ols = osub.add_parser("ls", help="list recorded runs (newest last)")
    _reg_flag(ols)
    ols.add_argument("--spec", default=None,
                     help="only runs of this spec frontend")
    ols.add_argument("--cmd", dest="cmd_filter", default=None,
                     help="only runs of this command (check/simulate/"
                          "batch/serve/deep_run/bench)")
    ols.add_argument("--status", default=None,
                     help="only runs with this exit status "
                          "(finished/failed, or a daemon's "
                          "done/draining)")

    oshow = osub.add_parser(
        "show", help="one run's full record (counters, span rollups, "
                     "resource peaks, artifact paths) as JSON")
    _reg_flag(oshow)
    oshow.add_argument("run", help="run id, unique prefix, or 'last'")

    odiff = osub.add_parser(
        "diff", help="machine-readable diff of two runs: count/"
                     "level-size parity verdict, per-phase span "
                     "deltas, mode-flag drift by name; exit 1 on "
                     "count mismatch")
    _reg_flag(odiff)
    odiff.add_argument("run_a", help="run id, unique prefix, or 'last'")
    odiff.add_argument("run_b", help="run id, unique prefix, or 'last'")

    oreg = osub.add_parser(
        "regress", help="regression verdict of RUN against a prior "
                        "registry run or a committed baseline file; "
                        "exit 1 on regression, 2 on usage error")
    _reg_flag(oreg)
    oreg.add_argument("run", help="run id, unique prefix, or 'last'")
    oreg.add_argument("--against", default=None, metavar="RUN",
                      help="baseline = this prior registry run")
    oreg.add_argument("--baseline", default=None, metavar="FILE",
                      help="baseline = a committed JSON file: a "
                           "--stats-json payload, a bench headline "
                           "object, or a BENCH_*.json A/B file "
                           "(then --baseline-row picks the row)")
    oreg.add_argument("--baseline-row", default=None, metavar="KEY",
                      help="row key inside a BENCH file's 'rows' map")
    oreg.add_argument("--max-span-ratio", type=float, default=None,
                      metavar="R",
                      help="also fail when a shared phase's span time "
                           "exceeds R x the baseline's (phases under "
                           "--min-seconds in the baseline are exempt "
                           "— CI wall-clock noise)")
    oreg.add_argument("--min-seconds", type=float, default=0.05,
                      metavar="S",
                      help="span-ratio floor: baseline phases shorter "
                           "than S seconds never trip (default 0.05)")
    po.set_defaults(fn=cmd_obs)

    args = p.parse_args(argv)
    _honor_platform_env()
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
