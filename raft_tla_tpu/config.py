"""Model configuration: the `raft.cfg` operator boundary, lifted.

This mirrors the two config tiers of the reference (SURVEY.md §5 "Config"):
  (a) `raft.cfg`-settable things: CONSTANTS (Server/InitServer/Value/NumRounds),
      INIT/NEXT selection, CONSTRAINTS / ACTION_CONSTRAINTS / INVARIANTS lists,
      SYMMETRY, VIEW            (reference: tlc_membership/raft.cfg:1-88)
  (b) in-spec search bounds (MaxLogLength etc., tlc_membership/raft.tla:22-30)
      which in the reference require editing the spec; here they are real
      config.  They determine static tensor shapes, so a distinct Bounds is a
      distinct JIT cache entry.

Server IDs are 0-based ints everywhere (the reference binds model values
s1..s5 = 1..5; our cfg front-end maps them down).  NIL is -1.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Tuple

NIL = -1

# Server roles (tlc_membership/raft.tla:38-44).
FOLLOWER = 0
CANDIDATE = 1
LEADER = 2

# Log entry types (tlc_membership/raft.tla:20).
VALUE_ENTRY = 0
CONFIG_ENTRY = 1

# Message types (tlc_membership/raft.tla:52-65).  0 is reserved for "empty
# bag slot" in the packed encoding, so wire types start at 1.
MT_RVREQ = 1
MT_RVRESP = 2
MT_AEREQ = 3
MT_AERESP = 4
MT_CATREQ = 5
MT_CATRESP = 6
MT_COC = 7

MSG_TYPE_NAMES = {
    MT_RVREQ: "RequestVoteRequest",
    MT_RVRESP: "RequestVoteResponse",
    MT_AEREQ: "AppendEntriesRequest",
    MT_AERESP: "AppendEntriesResponse",
    MT_CATREQ: "CatchupRequest",
    MT_CATRESP: "CatchupResponse",
    MT_COC: "CheckOldConfig",
}

# Next-relation families (tlc_membership/raft.tla:909-943).
NEXT_ASYNC = "NextAsync"
NEXT_ASYNC_CRASH = "NextAsyncCrash"
NEXT_FULL = "Next"
NEXT_DYNAMIC = "NextDynamic"

# The default-enabled constraint set (tlc_membership/raft.cfg:37-49).
DEFAULT_CONSTRAINTS = (
    "BoundedInFlightMessages",
    "BoundedRequestVote",
    "BoundedLogSize",
    "BoundedRestarts",
    "BoundedTimeouts",
    "BoundedTerms",
    "BoundedClientRequests",
    "BoundedTriedMembershipChanges",
    "BoundedMembershipChanges",
    "ElectionsUncontested",
    "CleanStartUntilFirstRequest",
    "CleanStartUntilTwoLeaders",
)

# The default-enabled safety invariants (tlc_membership/raft.cfg:79-87).
DEFAULT_INVARIANTS = (
    "LeaderVotesQuorum",
    "CandidateTermNotInLog",
    "ElectionSafety",
    "LogMatching",
    "VotesGrantedInv",
    "QuorumLogInv",
    "MoreUpToDateCorrect",
    "LeaderCompleteness",
)


@dataclass(frozen=True)
class Bounds:
    """In-spec search bounds (tlc_membership/raft.tla:22-30), lifted to config.

    Note: these bound *expansion* (TLC CONSTRAINT semantics, SURVEY.md §2.8):
    a state exceeding a bound is still generated and invariant-checked, it is
    just never expanded.  The packed representation must therefore hold one
    step beyond each bound (e.g. log length max_log_length+1 after an
    unconstrained append, and up to 2*max_log_length after a catchup splice;
    see ops/codec.py).
    """

    max_log_length: int = 5
    max_restarts: int = 2
    max_timeouts: int = 3
    max_client_requests: int = 3
    max_membership_changes: int = 3
    # Derived defaults mirror the reference (raft.tla:27,29): MaxTerms =
    # MaxTimeouts + 1, MaxTriedMembershipChanges = MaxMembershipChanges + 1.
    max_terms: int = 4
    max_tried_membership_changes: int = 4
    # BoundedTrace cap (raft.tla:1143: 24; apalache variant :776: 12)
    max_trace: int = 24

    @staticmethod
    def make(max_log_length=5, max_restarts=2, max_timeouts=3,
             max_client_requests=3, max_membership_changes=3,
             max_terms=None, max_tried_membership_changes=None,
             max_trace=24) -> "Bounds":
        return Bounds(
            max_log_length=max_log_length,
            max_restarts=max_restarts,
            max_timeouts=max_timeouts,
            max_client_requests=max_client_requests,
            max_membership_changes=max_membership_changes,
            max_terms=max_timeouts + 1 if max_terms is None else max_terms,
            max_tried_membership_changes=(
                max_membership_changes + 1
                if max_tried_membership_changes is None
                else max_tried_membership_changes),
            max_trace=max_trace,
        )


@dataclass(frozen=True)
class ModelConfig:
    """One checkable model: constants + NEXT + toggles (= one raft.cfg)."""

    # SpecIR dispatch marker (spec/ package) — class attribute, NOT a
    # dataclass field, so repr(cfg) (the checkpoint-compat key) is
    # byte-identical to every pre-IR checkpoint's
    spec = "raft"

    n_servers: int = 3                      # |Server|
    init_servers: Tuple[int, ...] = (0, 1, 2)   # InitServer ⊆ Server
    values: Tuple[int, ...] = (1, 2)        # Value
    num_rounds: int = 1                     # NumRounds (catch-up rounds)
    next_family: str = NEXT_ASYNC_CRASH     # raft.cfg:33 default
    constraints: Tuple[str, ...] = DEFAULT_CONSTRAINTS
    action_constraints: Tuple[str, ...] = ()
    invariants: Tuple[str, ...] = DEFAULT_INVARIANTS
    symmetry: bool = True                   # SYMMETRY perms (raft.cfg:29)
    bounds: Bounds = Bounds()
    # Variant switch: apalache_no_membership ships the two *_false invariant
    # forms as its live VotesGrantedInv / LeaderCompleteness (SURVEY.md §2.7
    # divergence note).  When True, those names resolve to the _false forms.
    apalache_variant: bool = False
    # Override for MaxInFlightMessages (raft.tla:30 derives 2*|Server|^2).
    # The reference requires editing the spec for this; we lift it.
    max_inflight_override: int = None
    # 128-bit fingerprints (two independent 64-bit streams).  TLC runs with
    # 64-bit fingerprints and ~1e-9 collision odds; exhaustive-parity runs
    # can opt into 128 (SURVEY §7.4 hard part 4).
    fp128: bool = False
    # Punctuated-search prefix pins from the cfg (raft.tla:1198-1234):
    # "CommitWhenConcurrentLeaders_unique" /
    # "MajorityOfClusterRestarts_constraint".  The reference evaluates
    # these as CONSTRAINTs against a hard-coded witness trace embedded in
    # the spec; the engines compile them into seed states — BFS starts at
    # the end of the pinned prefix (models/golden.prefix_pin_seeds), which
    # reproduces TLC's punctuated-search outcome (the witness extensions)
    # while skipping the prefix interior itself.
    prefix_pins: Tuple[str, ...] = ()

    @property
    def init_mask(self) -> int:
        m = 0
        for i in self.init_servers:
            m |= 1 << i
        return m

    @property
    def all_mask(self) -> int:
        return (1 << self.n_servers) - 1

    @property
    def max_inflight(self) -> int:
        # MaxInFlightMessages == 2 * |Server|^2 (raft.tla:30)
        if self.max_inflight_override is not None:
            return self.max_inflight_override
        return 2 * self.n_servers * self.n_servers

    @property
    def bag_capacity(self) -> int:
        # A state may exceed BoundedInFlightMessages by exactly one Send
        # before being pruned (constraints gate expansion, not generation).
        return self.max_inflight + 1

    @property
    def log_capacity(self) -> int:
        # Worst case representable log: catchup splice of a <=L prefix with
        # <=L caught-up entries (HandleCatchupRequest, raft.tla:734-736), or
        # an append onto a length-L log.  See Bounds docstring.
        return 2 * self.bounds.max_log_length

    def with_(self, **kw) -> "ModelConfig":
        return dataclasses.replace(self, **kw)


def popcount(x: int) -> int:
    return bin(x & ((1 << 64) - 1)).count("1")


def mask_iter(mask: int, n: int):
    for i in range(n):
        if mask >> i & 1:
            yield i
