"""Per-level trace archives: the parent/lane/state-row store behind
``store_states`` (SURVEY §7.2 L5 trace reconstruction), with two
backings:

- RAM (the historical behavior): per-level numpy arrays held in Python
  lists on the host.  Fine below ~1e7 states; a 63M-state spill run
  would hold ~21 GB of rows (BASELINE.md round-5 "remaining RAM
  ceilings").
- DISK (``archive_dir=``): each level's arrays stream to memmap'd
  ``.npy`` files under a run directory and are read back through
  ``numpy`` memory maps, so trace reconstruction and ``store_states``
  runs are bounded by the frontier working set, not the cumulative
  archive.  TLC keeps its state queue/trace files on disk the same way
  (its ``states/`` directory).

Layout under ``root``::

    meta.json                  {"level_rows": [...], "keys": [...]}
    lvl0000.parents.npy        int32 [n]  parent global ids
    lvl0000.lanes.npy          int32 [n]  action lane ids
    lvl0000.st.<key>.npy       storage-dtype [n, ...] state rows
    ...

Rows are batch-MAJOR on disk (the host archive layout the engines
already use); writers may supply batch-last parts and they are
transposed per part, so a spill engine's segment blocks stream straight
to disk without a whole-level concatenation buffer.

``meta.json`` is rewritten atomically after every level append, so a
killed run leaves a readable archive of its completed levels; resume
truncates back to the checkpointed level count (`truncate`) to keep
resumed runs bit-identical to uninterrupted ones.
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional

import numpy as np


class ArchiveError(ValueError):
    """Archive directory missing, malformed, or inconsistent with the
    run/checkpoint attaching to it."""


def _lvl(i: int) -> str:
    return f"lvl{i:04d}"


class DiskArchive:
    """Disk-backed per-level parent/lane/state archive (module
    docstring).  One instance per run directory; ``attach=True`` reopens
    an existing archive (checkpoint resume) instead of starting empty.
    """

    def __init__(self, root: str, attach: bool = False):
        self.root = root
        self._mmaps: Dict[str, np.ndarray] = {}   # read-cache per file
        if attach:
            try:
                with open(self._meta_path) as fh:
                    meta = json.load(fh)
            except (OSError, ValueError) as e:
                raise ArchiveError(
                    f"{root}: not a readable trace archive ({e})") from e
            self.level_rows: List[int] = [int(n) for n in
                                          meta["level_rows"]]
            self.keys: Optional[List[str]] = list(meta["keys"]) \
                if meta.get("keys") is not None else None
        else:
            os.makedirs(root, exist_ok=True)
            self.level_rows = []
            self.keys = None
            self._write_meta()

    # -- write path ----------------------------------------------------

    @property
    def _meta_path(self) -> str:
        return os.path.join(self.root, "meta.json")

    def _write_meta(self):
        tmp = self._meta_path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump({"level_rows": self.level_rows, "keys": self.keys},
                      fh)
        os.replace(tmp, self._meta_path)

    def _path(self, i: int, name: str) -> str:
        return os.path.join(self.root, f"{_lvl(i)}.{name}.npy")

    def append_level(self, parents: np.ndarray, lanes: np.ndarray,
                     states: Dict[str, np.ndarray]):
        """One finished level, batch-major arrays (the classic engines'
        harvest layout)."""
        self.append_level_parts([dict(
            lpar=parents, llane=lanes, rows_major=states,
            n=int(parents.shape[0]))])

    def append_level_parts(self, parts: List[dict]):
        """One finished level from spill parts, streamed part-by-part
        into the level's memmaps (no whole-level concat buffer).  Each
        part is ``dict(n=…, lpar=…, llane=…)`` plus either
        ``rows`` (batch-LAST state arrays, the spill block layout) or
        ``rows_major`` (batch-major)."""
        # chaos site: a disk I/O failure before this level's memmaps
        # are written.  meta.json still names only complete levels, so
        # a resume reattaches + truncates and re-appends bit-exact.
        from ..resil.chaos import chaos_point
        chaos_point("archive")
        i = len(self.level_rows)
        n = sum(int(p["n"]) for p in parts)
        first = parts[0]
        rows0 = first.get("rows_major") or first["rows"]
        if self.keys is None:
            self.keys = sorted(rows0.keys())
        mm_par = np.lib.format.open_memmap(
            self._path(i, "parents"), mode="w+", dtype=np.int32,
            shape=(n,))
        mm_lane = np.lib.format.open_memmap(
            self._path(i, "lanes"), mode="w+", dtype=np.int32,
            shape=(n,))
        mm_st = {}
        for k in self.keys:
            v = rows0[k]
            minor = v.shape[1:] if "rows_major" in first else v.shape[:-1]
            mm_st[k] = np.lib.format.open_memmap(
                self._path(i, f"st.{k}"), mode="w+", dtype=v.dtype,
                shape=(n,) + tuple(minor))
        off = 0
        for p in parts:
            m = int(p["n"])
            mm_par[off:off + m] = p["lpar"][:m]
            mm_lane[off:off + m] = p["llane"][:m]
            if "rows_major" in p:
                for k in self.keys:
                    mm_st[k][off:off + m] = p["rows_major"][k][:m]
            else:
                for k in self.keys:
                    mm_st[k][off:off + m] = np.moveaxis(
                        p["rows"][k][..., :m], -1, 0)
            off += m
        for mm in [mm_par, mm_lane, *mm_st.values()]:
            mm.flush()
        del mm_par, mm_lane, mm_st      # drop the write maps: RSS stays
        # bounded by the level being written, not the cumulative archive
        self.level_rows.append(n)
        self._write_meta()

    def truncate(self, n_levels: int):
        """Drop levels past ``n_levels`` (checkpoint resume: the run
        replays from the checkpointed level and re-appends them)."""
        if n_levels > len(self.level_rows):
            raise ArchiveError(
                f"{self.root}: archive has {len(self.level_rows)} "
                f"levels, checkpoint expects {n_levels} — wrong "
                "archive_dir for this checkpoint?")
        for i in range(n_levels, len(self.level_rows)):
            for name in ["parents", "lanes"] + \
                    [f"st.{k}" for k in (self.keys or [])]:
                try:
                    os.remove(self._path(i, name))
                except OSError:
                    pass
        self.level_rows = self.level_rows[:n_levels]
        self._mmaps.clear()
        self._write_meta()

    # -- read path (memmap'd; random access never loads a level) -------

    @property
    def n_levels(self) -> int:
        return len(self.level_rows)

    @property
    def total_rows(self) -> int:
        return sum(self.level_rows)

    def _map(self, i: int, name: str) -> np.ndarray:
        path = self._path(i, name)
        mm = self._mmaps.get(path)
        if mm is None:
            mm = self._mmaps[path] = np.load(path, mmap_mode="r")
        return mm

    def parents(self, i: int) -> np.ndarray:
        return self._map(i, "parents")

    def lanes(self, i: int) -> np.ndarray:
        return self._map(i, "lanes")

    def states(self, i: int) -> Dict[str, np.ndarray]:
        return {k: self._map(i, f"st.{k}") for k in self.keys or []}

    def locate(self, gid: int):
        """Global state id -> (level, row-within-level)."""
        off = 0
        for i, n in enumerate(self.level_rows):
            if gid < off + n:
                return i, gid - off
            off += n
        raise IndexError(gid)

    def state_row(self, gid: int) -> Dict[str, np.ndarray]:
        i, r = self.locate(gid)
        return {k: np.asarray(self._map(i, f"st.{k}")[r])
                for k in self.keys or []}

    def parent_lane(self, gid: int):
        i, r = self.locate(gid)
        return int(self._map(i, "parents")[r]), \
            int(self._map(i, "lanes")[r])
