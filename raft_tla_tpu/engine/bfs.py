"""Level-synchronous BFS engine: TLC's worker loop, TPU-shaped.

Replaces the reference's external checker (SURVEY §2.13: TLC's BFS +
fingerprint set + invariant eval) with a **device-resident** pipeline:
the frontier, the candidate expansion, the fingerprint set (a sorted
multi-word key array in HBM), the per-level dedup, the invariant /
constraint evaluation and the next-frontier compaction all live on
device.  Per frontier chunk the host issues ONE fused jit call
(expand + fingerprint + action constraints + intra-chunk first-seen
dedup + membership probe + scatter into the level buffer) with a
donated carry, so chunk steps pipeline asynchronously; the only
per-level synchronization is reading back a handful of scalars
(new-state count, violation count, next-frontier size).

State identity follows TLC's semantics: the visited set stores the
symmetry-canonical VIEW fingerprints (engine/fingerprint) as
``n_streams`` u32 words compared lexicographically; first-seen survivor
order matches the Python oracle (chunk-sequential, candidate-index
order within a chunk — SURVEY §7.4 pt 5).  CONSTRAINT semantics are
prune-not-reject: violating states are counted and checked but not
expanded (§2.8).  Parent pointers (state-id, lane-id) stream to the
host per level for trace reconstruction (SURVEY §7.2 L5).

Capacity model: the visited set (VCAP keys) and the per-level buffer
(LCAP states) are fixed-shape device arrays padded with an all-ones
sentinel key; when a level or the visited set outgrows its capacity the
engine doubles the cap, recompiles (one extra jit cache entry per
doubling) and — for the level buffer — replays the level from the
intact frontier (the visited set is only merged at level end, so the
replay is exact).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import CANDIDATE, ModelConfig
from ..models.raft import Hist, State, init_state
from ..ops.codec import C_GLOBLEN, C_OVERFLOW, decode, encode
from ..ops.kernels import RaftKernels
from ..ops.layout import Layout
from ..ops.vpredicates import Predicates
from .expand import Expander
from .fingerprint import Fingerprinter, combine_u64

U32MAX = jnp.uint32(0xFFFFFFFF)


def _cat(chunks: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    return {k: np.concatenate([c[k] for c in chunks]) for k in chunks[0]}


def fp_key(fp_u32: np.ndarray) -> np.ndarray:
    """[N, n_streams] u32 -> 1-D sortable dedup key covering ALL streams:
    plain u64 for the 2-stream default, a lexicographic structured array
    for fp128 (so the extra streams actually buy collision resistance)."""
    u64 = combine_u64(fp_u32)                     # [N, n_streams//2]
    if u64.shape[1] == 1:
        return u64[:, 0]
    dtype = np.dtype([(f"w{i}", "<u8") for i in range(u64.shape[1])])
    return np.ascontiguousarray(u64).view(dtype)[:, 0]


def _take(arrs: Dict[str, np.ndarray], idx) -> Dict[str, np.ndarray]:
    return {k: v[idx] for k, v in arrs.items()}


@dataclass
class Violation:
    invariant: str
    state_id: int
    state: Optional[State] = None
    hist: Optional[Hist] = None
    trace: Optional[List[str]] = None


@dataclass
class CheckResult:
    distinct_states: int
    generated_states: int
    depth: int
    violations: List[Violation] = field(default_factory=list)
    level_sizes: List[int] = field(default_factory=list)
    seconds: float = 0.0
    overflow_faults: int = 0
    phase_seconds: Dict[str, float] = field(default_factory=dict)

    @property
    def states_per_sec(self):
        return self.distinct_states / max(self.seconds, 1e-9)


def _ceil_log2(n: int) -> int:
    return max(1, int(np.ceil(np.log2(max(n, 2)))))


class Engine:
    """One compiled checker instance per (ModelConfig, chunk size).

    chunk    — frontier states expanded per fused device call.
    lcap     — initial per-level buffer capacity (states); doubles on
               overflow (the level is replayed from the intact frontier).
    vcap     — initial visited-set capacity (fingerprint keys).
    """

    def __init__(self, cfg: ModelConfig, chunk: int = 512,
                 store_states: bool = True,
                 lcap: int = 1 << 14, vcap: int = 1 << 17):
        self.cfg = cfg
        self.chunk = max(16, int(chunk))
        self.store_states = store_states
        self.lay = Layout(cfg)
        self.kern = RaftKernels(self.lay)
        self.expander = Expander(cfg)
        self.fpr = Fingerprinter(cfg)
        self.preds = Predicates(self.lay)
        self.inv_names = list(cfg.invariants)
        self.con_names = list(cfg.constraints)
        self.act_names = list(cfg.action_constraints)
        self.labels = self.expander.lane_labels()
        self.A = self.expander.n_lanes
        self.W = self.fpr.n_streams           # u32 words per dedup key
        # capacities (LCAP always a multiple of chunk)
        self.LCAP = self._round_cap(max(lcap, 4 * self.chunk))
        self.VCAP = int(vcap)
        self._phase1 = jax.jit(self._phase1_impl)
        self._phase2 = jax.jit(self._phase2_impl)
        self._step_jit = jax.jit(self._chunk_step_impl, donate_argnums=0)
        self._fin_jit = jax.jit(self._finalize_impl, donate_argnums=0)

    def _round_cap(self, n: int) -> int:
        c = self.chunk
        return ((int(n) + c - 1) // c) * c

    # ------------------------------------------------------------------
    # phase 1: expand + action constraints + fingerprint (also used by
    # the driver entry point and the sharded engine)
    # ------------------------------------------------------------------

    def _act_ok(self, parent_sv, cand_sv):
        """ACTION_CONSTRAINTS (raft.tla:1207-1210): evaluated on the
        (unprimed, primed) pair; violating transitions are not taken."""
        ok = jnp.bool_(True)
        for nm in self.act_names:
            if nm == "CommitWhenConcurrentLeaders_action_constraint":
                deep = parent_sv["ctr"][C_GLOBLEN] >= 20
                no_cand = jnp.all(cand_sv["st"] != CANDIDATE)
                ok = ok & (~deep | no_cand)
            else:
                raise KeyError(f"unknown action constraint {nm}")
        return ok

    def _phase1_impl(self, svb):
        ok, cand = self.expander._expand_impl(svb)          # [B,A], [B,A,…]

        def per_state(parent, cand_row, ok_row):
            def per_lane(c, o):
                fp = self.fpr.fingerprint(c)
                act = self._act_ok(parent, c)
                return fp, act
            return jax.vmap(per_lane)(cand_row, ok_row)

        fp, act = jax.vmap(per_state)(svb, cand, ok)
        return ok & act, cand, fp

    def _phase2_impl(self, svb):
        def one(sv):
            der = self.kern.derived(sv)
            inv = jnp.stack([self.preds.invariant_fn(nm)(sv, der)
                             for nm in self.inv_names]) \
                if self.inv_names else jnp.ones((0,), bool)
            con = jnp.bool_(True)
            for nm in self.con_names:
                con = con & self.preds.constraint_fn(nm)(sv, der)
            return inv, con
        return jax.vmap(one)(svb)

    # ------------------------------------------------------------------
    # device-resident dedup primitives
    # ------------------------------------------------------------------

    def _lower_bound(self, arrs: Tuple[jnp.ndarray, ...],
                     qs: Tuple[jnp.ndarray, ...]) -> jnp.ndarray:
        """First index where the lexicographic W-word key >= query.
        arrs: W × u32[C] sorted ascending (sentinel-padded); qs: W × u32[N].
        Branchless fixed-depth binary search (the HBM-resident analog of
        TLC's fingerprint-set probe)."""
        C = arrs[0].shape[0]
        lo = jnp.zeros(qs[0].shape, jnp.int32)
        hi = jnp.full(qs[0].shape, C, jnp.int32)
        for _ in range(_ceil_log2(C) + 1):
            mid = lo + ((hi - lo) >> 1)
            midc = jnp.clip(mid, 0, C - 1)
            less = jnp.zeros(qs[0].shape, bool)
            eq = jnp.ones(qs[0].shape, bool)
            for w in range(self.W):
                kw = arrs[w][midc]
                less = less | (eq & (kw < qs[w]))
                eq = eq & (kw == qs[w])
            lo = jnp.where(less, mid + 1, lo)
            hi = jnp.where(less, hi, mid)
        return lo

    def _member(self, arrs, qs) -> jnp.ndarray:
        C = arrs[0].shape[0]
        pos = jnp.clip(self._lower_bound(arrs, qs), 0, C - 1)
        eq = jnp.ones(qs[0].shape, bool)
        for w in range(self.W):
            eq = eq & (arrs[w][pos] == qs[w])
        return eq

    def _sorted_insert(self, arrs, ins, cap):
        """Merge `ins` (W × u32[M], sentinel for dead lanes) into the
        sorted sentinel-padded `arrs` (W × u32[cap]) via concat + sort;
        real keys must fit in cap (checked by the caller's overflow
        logic)."""
        cat = tuple(jnp.concatenate([arrs[w], ins[w]])
                    for w in range(self.W))
        merged = lax.sort(cat, num_keys=self.W)
        return tuple(merged[w][:cap] for w in range(self.W))

    # ------------------------------------------------------------------
    # fused per-chunk step (ONE device call per frontier chunk)
    # ------------------------------------------------------------------

    def _chunk_step_impl(self, carry, base):
        """Expand frontier[base:base+chunk], fingerprint, dedup
        (intra-chunk first-seen + visited + level membership) and
        scatter the fresh states into the level buffer.  Everything
        stays on device; `carry` is donated so buffers are reused."""
        B, A, W = self.chunk, self.A, self.W
        LCAP = carry["lpar"].shape[0]
        N = B * A
        sv = {k: lax.dynamic_slice_in_dim(v, base, B)
              for k, v in carry["front"].items()}
        pgids = lax.dynamic_slice_in_dim(carry["gids"], base, B)
        ok, cand, fp = self._phase1_impl(sv)
        valid = (base + jnp.arange(B, dtype=jnp.int32)) < carry["n_front"]
        okf = (ok & valid[:, None]).reshape(N)
        n_gen = carry["n_gen"] + okf.sum(dtype=jnp.int32)

        kws = tuple(jnp.where(okf, fp[..., w].reshape(N), U32MAX)
                    for w in range(W))
        idx = jnp.arange(N, dtype=jnp.int32)
        sorted_ops = lax.sort(kws + (idx,), num_keys=W, is_stable=True)
        sk, sidx = sorted_ops[:W], sorted_ops[W]
        # first of each equal-key run; stability => smallest original
        # index survives (the oracle's first-seen rule)
        diff = jnp.zeros(N, bool).at[0].set(True)
        for w in range(W):
            diff = diff | jnp.concatenate(
                [jnp.ones(1, bool), sk[w][1:] != sk[w][:-1]])
        is_sent = jnp.ones(N, bool)
        for w in range(W):
            is_sent = is_sent & (sk[w] == U32MAX)
        surv = diff & ~is_sent
        surv = surv & ~self._member(carry["vis"], sk)
        surv = surv & ~self._member(carry["lvlk"], sk)

        fresh = jnp.zeros(N, bool).at[sidx].set(surv)   # original order
        offs = jnp.cumsum(fresh.astype(jnp.int32)) - 1
        pos = jnp.where(fresh, carry["n_lvl"] + offs, LCAP)   # OOB drops
        n_fresh = fresh.sum(dtype=jnp.int32)
        ovf = carry["ovf"] | (carry["n_lvl"] + n_fresh > LCAP)

        lvl = {k: v.at[pos].set(cand[k].reshape((N,) + v.shape[1:]),
                                mode="drop")
               for k, v in carry["lvl"].items()}
        lpar = carry["lpar"].at[pos].set(pgids[idx // A], mode="drop")
        llane = carry["llane"].at[pos].set(idx % A, mode="drop")
        ins = tuple(jnp.where(surv, sk[w], U32MAX) for w in range(W))
        lvlk = self._sorted_insert(carry["lvlk"], ins, LCAP)
        return dict(carry, lvl=lvl, lpar=lpar, llane=llane, lvlk=lvlk,
                    n_lvl=jnp.minimum(carry["n_lvl"] + n_fresh, LCAP),
                    n_gen=n_gen, ovf=ovf)

    # ------------------------------------------------------------------
    # per-level finalize: invariants/constraints on the new states,
    # next-frontier compaction, visited merge — one device call
    # ------------------------------------------------------------------

    def _finalize_impl(self, carry, g_off):
        LCAP = carry["lpar"].shape[0]
        VCAP = carry["vis"][0].shape[0]
        n_lvl = carry["n_lvl"]
        validrow = jnp.arange(LCAP, dtype=jnp.int32) < n_lvl
        inv, con = self._phase2_impl(carry["lvl"])
        inv_ok = inv | ~validrow[:, None] if self.inv_names else inv
        n_viol = (~inv_ok).sum(dtype=jnp.int32)
        faults = ((carry["lvl"]["ctr"][:, C_OVERFLOW] > 0) &
                  validrow).sum(dtype=jnp.int32)
        # CONSTRAINT = checked but not expanded (SURVEY §2.8)
        expand_mask = con & validrow
        fpos = jnp.where(expand_mask,
                         jnp.cumsum(expand_mask.astype(jnp.int32)) - 1,
                         LCAP)
        front = {k: v.at[fpos].set(carry["lvl"][k], mode="drop")
                 for k, v in carry["front"].items()}
        gids = carry["gids"].at[fpos].set(
            g_off + jnp.arange(LCAP, dtype=jnp.int32), mode="drop")
        n_front = expand_mask.sum(dtype=jnp.int32)
        vis = self._sorted_insert(carry["vis"], carry["lvlk"], VCAP)
        lvlk = tuple(jnp.full((LCAP,), U32MAX) for _ in range(self.W))
        new_carry = dict(carry, vis=vis, lvlk=lvlk, front=front,
                         gids=gids, n_front=n_front,
                         n_lvl=jnp.int32(0), ovf=jnp.bool_(False))
        return new_carry, dict(inv_ok=inv_ok, n_viol=n_viol,
                               faults=faults, n_front=n_front,
                               n_lvl=n_lvl)

    # ------------------------------------------------------------------

    def _fresh_carry(self, lcap: int, vcap: int):
        one = encode(self.lay, *init_state(self.cfg))
        zeros = {k: jnp.zeros((lcap,) + v.shape, dtype=v.dtype)
                 for k, v in one.items()}
        sent = tuple(jnp.full((lcap,), U32MAX) for _ in range(self.W))
        return dict(
            vis=tuple(jnp.full((vcap,), U32MAX) for _ in range(self.W)),
            lvlk=sent,
            lvl=zeros,
            lpar=jnp.full((lcap,), -1, jnp.int32),
            llane=jnp.full((lcap,), -1, jnp.int32),
            n_lvl=jnp.int32(0),
            n_gen=jnp.int32(0),
            ovf=jnp.bool_(False),
            front={k: jnp.zeros_like(v) for k, v in zeros.items()},
            gids=jnp.full((lcap,), -1, jnp.int32),
            n_front=jnp.int32(0),
        )

    def _grow(self, carry, lcap: int, vcap: int):
        """Re-home a carry into bigger capacity buffers (visited keys and
        the frontier survive; the level buffer is reset — callers replay
        the level)."""
        old_lcap = carry["lpar"].shape[0]
        new = self._fresh_carry(lcap, vcap)
        new["vis"] = self._grow_vis(carry, vcap)["vis"]
        pad = lcap - old_lcap
        new["front"] = {k: jnp.concatenate(
            [carry["front"][k], jnp.zeros((pad,) + v.shape[1:], v.dtype)])
            for k, v in carry["front"].items()}
        new["gids"] = jnp.concatenate(
            [carry["gids"], jnp.full((pad,), -1, jnp.int32)])
        new["n_front"] = carry["n_front"]
        # n_gen stays 0: the caller replays the whole level from the
        # intact frontier, so keeping the partial count would double it
        return new

    # ------------------------------------------------------------------

    def check(self, max_depth: int = 10 ** 9, max_states: int = 10 ** 9,
              stop_on_violation: bool = False,
              seed_states: Optional[List] = None,
              verbose: bool = False) -> CheckResult:
        """seed_states entries are (State, Hist) pairs or raw SoA dicts
        (the latter preserve feature lanes exactly — engine-emitted
        seeds; punctuated search, SURVEY §2.9)."""
        t0 = time.time()
        lay = self.lay
        init_list = (seed_states if seed_states is not None
                     else [init_state(self.cfg)])
        init_arrs = _cat([
            {k: np.asarray(v)[None] for k, v in s.items()}
            if isinstance(s, dict) else
            {k: v[None] for k, v in encode(lay, *s).items()}
            for s in init_list])
        rootsb = {k: jnp.asarray(v) for k, v in init_arrs.items()}
        root_fp = np.asarray(jax.vmap(self.fpr.fingerprint)(rootsb))
        root_keys = fp_key(root_fp)
        _uniq, first_idx = np.unique(root_keys, return_index=True)
        first_idx.sort()
        roots = _take(init_arrs, first_idx)
        n_roots = len(first_idx)

        res = CheckResult(distinct_states=0, generated_states=n_roots,
                          depth=0)
        self._states: List[Dict[str, np.ndarray]] = []
        self._parents: List[np.ndarray] = []
        self._lanes: List[np.ndarray] = []

        while self.LCAP < 2 * n_roots:
            self.LCAP *= 2
        carry = self._fresh_carry(self.LCAP, self.VCAP)
        # roots enter through the same admit path as every level: place
        # them in the level buffer and finalize.
        pad = self.LCAP - n_roots
        carry["lvl"] = {k: jnp.asarray(np.concatenate(
            [roots[k], np.zeros((pad,) + roots[k].shape[1:],
                                roots[k].dtype)]))
            for k in roots}
        rk = np.asarray(root_fp[first_idx], dtype=np.uint32)
        # lexicographic row sort (np.lexsort: LAST key is primary)
        order = np.lexsort(tuple(rk[:, w]
                                 for w in range(self.W - 1, -1, -1)))
        carry["lvlk"] = tuple(jnp.asarray(np.concatenate(
            [rk[order, w], np.full(pad, 0xFFFFFFFF, np.uint32)]))
            for w in range(self.W))
        carry["n_lvl"] = jnp.int32(n_roots)
        n_states = 0
        n_vis = 0
        depth = 0
        t_dev = 0.0

        def run_finalize(carry):
            nonlocal n_vis
            need = n_vis + int(np.asarray(carry["n_lvl"]))
            if need > self.VCAP:
                while self.VCAP < need:
                    self.VCAP *= 2
                carry = self._grow_vis(carry, self.VCAP)
            return self._fin_jit(carry, jnp.int32(n_states))

        def harvest(carry, out):
            """Per-level host bookkeeping: counts, parents/lanes,
            violations, optional state store."""
            nonlocal n_states, n_vis
            n_lvl = int(np.asarray(out["n_lvl"]))
            res.distinct_states += n_lvl
            res.overflow_faults += int(np.asarray(out["faults"]))
            # slice on device, transfer only live rows
            self._parents.append(np.asarray(carry["lpar"][:n_lvl]))
            self._lanes.append(np.asarray(carry["llane"][:n_lvl]))
            if self.store_states:
                self._states.append(
                    {k: np.asarray(v[:n_lvl])
                     for k, v in carry["lvl"].items()})
            n_viol = int(np.asarray(out["n_viol"]))
            if n_viol:
                inv_ok = np.asarray(out["inv_ok"])[:n_lvl]
                rows = {k: np.asarray(v)[:n_lvl]
                        for k, v in carry["lvl"].items()}
                for j, nm in enumerate(self.inv_names):
                    for s in np.nonzero(~inv_ok[:, j])[0]:
                        vsv, vh = decode(self.lay, _take(rows, s))
                        res.violations.append(
                            Violation(nm, n_states + int(s),
                                      state=vsv, hist=vh))
            n_states += n_lvl
            n_vis += n_lvl
            # global state ids are device int32 (gids/lpar); fail loud
            # rather than wrap if a run ever approaches that scale
            if n_states >= 2 ** 31 - 1:
                raise RuntimeError(
                    "state-id space exhausted (2^31 ids): run exceeds "
                    "the engine's int32 global-id width")
            return int(np.asarray(out["n_front"]))

        carry, out = run_finalize(carry)
        n_front = harvest(carry, out)
        if stop_on_violation and res.violations:
            res.seconds = time.time() - t0
            return res

        while n_front and depth < max_depth and \
                res.distinct_states < max_states:
            depth += 1
            t1 = time.time()
            while True:
                n_chunks = (n_front + self.chunk - 1) // self.chunk
                for c in range(n_chunks):
                    carry = self._step_jit(carry, jnp.int32(c * self.chunk))
                if not bool(np.asarray(carry["ovf"])):
                    break
                # level buffer overflow: double LCAP and replay the
                # level (visited is only merged at finalize, so replay
                # from the intact frontier is exact)
                self.LCAP *= 2
                if verbose:
                    print(f"level {depth}: buffer overflow, growing "
                          f"LCAP to {self.LCAP}")
                carry = self._grow(carry, self.LCAP, self.VCAP)
            carry, out = run_finalize(carry)
            res.generated_states += int(np.asarray(carry["n_gen"]))
            carry["n_gen"] = jnp.int32(0)
            n_front = harvest(carry, out)
            t_dev += time.time() - t1
            res.level_sizes.append(n_front)
            if stop_on_violation and res.violations:
                break
            if verbose:
                n_lvl = int(np.asarray(out["n_lvl"]))
                print(f"depth {depth}: +{n_lvl} states "
                      f"(total {res.distinct_states}), "
                      f"frontier {n_front}")
        res.depth = depth
        res.seconds = time.time() - t0
        res.phase_seconds["device_levels"] = t_dev
        return res

    def _grow_vis(self, carry, vcap: int):
        ovcap = carry["vis"][0].shape[0]
        carry = dict(carry)
        carry["vis"] = tuple(
            jnp.concatenate([carry["vis"][w],
                             jnp.full((vcap - ovcap,), U32MAX)])
            for w in range(self.W))
        return carry

    # ------------------------------------------------------------------

    def get_state(self, gid: int) -> Tuple[State, Hist]:
        return decode(self.lay, self.get_state_arrays(gid))

    def get_state_arrays(self, gid: int) -> Dict[str, np.ndarray]:
        assert self.store_states, "state store disabled"
        off = 0
        for blk in self._states:
            n = len(blk["ct"])
            if gid < off + n:
                return _take(blk, gid - off)
            off += n
        raise IndexError(gid)

    def trace(self, gid: int) -> List[Tuple[str, State]]:
        parents = np.concatenate(self._parents)
        lanes = np.concatenate(self._lanes)
        chain = []
        g = gid
        while g >= 0:
            lane = lanes[g]
            label = self.labels[lane] if lane >= 0 else "Init"
            chain.append((label, self.get_state(g)[0]))
            g = parents[g]
        return list(reversed(chain))
