"""Level-synchronous BFS engine: TLC's worker loop, TPU-shaped.

Replaces the reference's external checker (SURVEY §2.13: TLC's BFS +
fingerprint set + invariant eval) with a **device-resident** pipeline:
the frontier, the candidate expansion, the fingerprint set (a sorted
multi-word key array in HBM), the per-level dedup, the invariant /
constraint evaluation and the next-frontier compaction all live on
device.  Per frontier chunk the host issues ONE fused jit call
(expand + fingerprint + action constraints + intra-chunk first-seen
dedup + membership probe + scatter into the level buffer) with a
donated carry, so chunk steps pipeline asynchronously; the only
per-level synchronization is reading back a handful of scalars
(new-state count, violation count, next-frontier size).

State identity follows TLC's semantics: the visited set stores the
symmetry-canonical VIEW fingerprints (engine/fingerprint) as
``n_streams`` u32 words compared lexicographically; first-seen survivor
order matches the Python oracle (chunk-sequential, candidate-index
order within a chunk — SURVEY §7.4 pt 5).  CONSTRAINT semantics are
prune-not-reject: violating states are counted and checked but not
expanded (§2.8).  Parent pointers (state-id, lane-id) stream to the
host per level for trace reconstruction (SURVEY §7.2 L5).

Capacity model: the visited set (VCAP keys) and the per-level buffer
(LCAP states) are fixed-shape device arrays padded with an all-ones
sentinel key; when a level or the visited set outgrows its capacity the
engine doubles the cap, recompiles (one extra jit cache entry per
doubling) and — for the level buffer — replays the level from the
intact frontier (the visited set is only merged at level end, so the
replay is exact).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import CANDIDATE, ModelConfig
from ..models.raft import Hist, State, init_state
from ..ops.codec import C_GLOBLEN, C_OVERFLOW, decode, encode
from ..ops.kernels import RaftKernels
from ..ops.layout import Layout
from ..ops.vpredicates import Predicates
from .expand import Expander
from .fingerprint import Fingerprinter, combine_u64

U32MAX = jnp.uint32(0xFFFFFFFF)

_CACHE_ENABLED = False


def enable_persistent_compilation_cache():
    """Persist XLA executables across processes (TPU compiles of the
    fused BFS kernels run 30-50s; warm loads are sub-second).  Honors a
    user-set JAX_COMPILATION_CACHE_DIR; defaults to a repo-local dir."""
    global _CACHE_ENABLED
    if _CACHE_ENABLED:
        return
    _CACHE_ENABLED = True
    import os
    path = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".jax_cache")
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:
        pass                  # older jax without the knob: run uncached


def _cat(chunks: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    return {k: np.concatenate([c[k] for c in chunks]) for k in chunks[0]}


def fp_key(fp_u32: np.ndarray) -> np.ndarray:
    """[N, n_streams] u32 -> 1-D sortable dedup key covering ALL streams:
    plain u64 for the 2-stream default, a lexicographic structured array
    for fp128 (so the extra streams actually buy collision resistance)."""
    u64 = combine_u64(fp_u32)                     # [N, n_streams//2]
    if u64.shape[1] == 1:
        return u64[:, 0]
    dtype = np.dtype([(f"w{i}", "<u8") for i in range(u64.shape[1])])
    return np.ascontiguousarray(u64).view(dtype)[:, 0]


def _take(arrs: Dict[str, np.ndarray], idx) -> Dict[str, np.ndarray]:
    return {k: v[idx] for k, v in arrs.items()}


@dataclass
class Violation:
    invariant: str
    state_id: int
    state: Optional[State] = None
    hist: Optional[Hist] = None
    trace: Optional[List[str]] = None


@dataclass
class CheckResult:
    distinct_states: int
    generated_states: int
    depth: int
    violations: List[Violation] = field(default_factory=list)
    level_sizes: List[int] = field(default_factory=list)
    seconds: float = 0.0
    overflow_faults: int = 0
    phase_seconds: Dict[str, float] = field(default_factory=dict)

    @property
    def states_per_sec(self):
        return self.distinct_states / max(self.seconds, 1e-9)


def _ceil_log2(n: int) -> int:
    return max(1, int(np.ceil(np.log2(max(n, 2)))))


def _leaf_name(key_path) -> str:
    """Stable archive name for a carry pytree leaf (shared by
    checkpoint save and load — must stay in lockstep)."""
    return "carry|" + "|".join(
        str(getattr(p, "key", getattr(p, "idx", p))) for p in key_path)


class Engine:
    """One compiled checker instance per (ModelConfig, chunk size).

    chunk    — frontier states expanded per fused device call.
    lcap     — initial per-level buffer capacity (states); doubles on
               overflow (the level is replayed from the intact frontier).
    vcap     — initial visited-set capacity (fingerprint keys).
    """

    def __init__(self, cfg: ModelConfig, chunk: int = 512,
                 store_states: bool = True,
                 lcap: int = 1 << 14, vcap: int = 1 << 17,
                 fcap: Optional[int] = None):
        enable_persistent_compilation_cache()
        self.cfg = cfg
        self.chunk = max(16, int(chunk))
        self.store_states = store_states
        self.lay = Layout(cfg)
        self.kern = RaftKernels(self.lay)
        self.expander = Expander(cfg)
        self.fpr = Fingerprinter(cfg)
        self.preds = Predicates(self.lay)
        self.inv_names = list(cfg.invariants)
        self.con_names = list(cfg.constraints)
        self.act_names = list(cfg.action_constraints)
        self.labels = self.expander.lane_labels()
        self.A = self.expander.n_lanes
        self.W = self.fpr.n_streams           # u32 words per dedup key
        # capacities (LCAP always a multiple of chunk).  FCAP bounds the
        # fresh-per-chunk compaction buffer; LCAP reserves an FCAP-sized
        # append margin (usable level capacity is LCAP - FCAP).
        self.FCAP = int(fcap) if fcap else min(
            self.chunk * self.A, max(self.chunk * 16, 1 << 13))
        self.LCAP = self._round_cap(
            max(lcap, 4 * self.chunk, 4 * self.FCAP))
        self.VCAP = int(vcap)
        self._phase1 = jax.jit(self._phase1_impl)
        self._phase2 = jax.jit(self._phase2_impl)
        self._step_jit = jax.jit(self._chunk_step_impl, donate_argnums=0)
        self._fin_jit = jax.jit(self._finalize_impl, donate_argnums=0)
        self._rootfp_jit = jax.jit(
            lambda svb: jax.vmap(self.fpr.fingerprint)(svb))

    def _round_cap(self, n: int) -> int:
        c = self.chunk
        return ((int(n) + c - 1) // c) * c

    # ------------------------------------------------------------------
    # phase 1: expand + action constraints + fingerprint (also used by
    # the driver entry point and the sharded engine)
    # ------------------------------------------------------------------

    def _act_ok(self, parent_sv, cand_sv):
        """ACTION_CONSTRAINTS (raft.tla:1207-1210): evaluated on the
        (unprimed, primed) pair; violating transitions are not taken."""
        ok = jnp.bool_(True)
        for nm in self.act_names:
            if nm == "CommitWhenConcurrentLeaders_action_constraint":
                deep = parent_sv["ctr"][C_GLOBLEN] >= 20
                no_cand = jnp.all(cand_sv["st"] != CANDIDATE)
                ok = ok & (~deep | no_cand)
            else:
                raise KeyError(f"unknown action constraint {nm}")
        return ok

    def _phase1_impl(self, svb):
        ok, cand = self.expander._expand_impl(svb)          # [B,A], [B,A,…]

        def per_state(parent, cand_row, ok_row):
            def per_lane(c, o):
                fp = self.fpr.fingerprint(c)
                act = self._act_ok(parent, c)
                return fp, act
            return jax.vmap(per_lane)(cand_row, ok_row)

        fp, act = jax.vmap(per_state)(svb, cand, ok)
        return ok & act, cand, fp

    def _phase2_impl(self, svb):
        def one(sv):
            der = self.kern.derived(sv)
            inv = jnp.stack([self.preds.invariant_fn(nm)(sv, der)
                             for nm in self.inv_names]) \
                if self.inv_names else jnp.ones((0,), bool)
            con = jnp.bool_(True)
            for nm in self.con_names:
                con = con & self.preds.constraint_fn(nm)(sv, der)
            return inv, con
        return jax.vmap(one)(svb)

    # ------------------------------------------------------------------
    # device-resident dedup primitives
    # ------------------------------------------------------------------

    def _lower_bound(self, arrs: Tuple[jnp.ndarray, ...],
                     qs: Tuple[jnp.ndarray, ...]) -> jnp.ndarray:
        """First index where the lexicographic W-word key >= query.
        arrs: W × u32[C] sorted ascending (sentinel-padded); qs: W × u32[N].
        Branchless fixed-depth binary search (the HBM-resident analog of
        TLC's fingerprint-set probe)."""
        C = arrs[0].shape[0]
        lo = jnp.zeros(qs[0].shape, jnp.int32)
        hi = jnp.full(qs[0].shape, C, jnp.int32)
        for _ in range(_ceil_log2(C) + 1):
            mid = lo + ((hi - lo) >> 1)
            midc = jnp.clip(mid, 0, C - 1)
            less = jnp.zeros(qs[0].shape, bool)
            eq = jnp.ones(qs[0].shape, bool)
            for w in range(self.W):
                kw = arrs[w][midc]
                less = less | (eq & (kw < qs[w]))
                eq = eq & (kw == qs[w])
            lo = jnp.where(less, mid + 1, lo)
            hi = jnp.where(less, hi, mid)
        return lo

    def _member(self, arrs, qs) -> jnp.ndarray:
        C = arrs[0].shape[0]
        pos = jnp.clip(self._lower_bound(arrs, qs), 0, C - 1)
        eq = jnp.ones(qs[0].shape, bool)
        for w in range(self.W):
            eq = eq & (arrs[w][pos] == qs[w])
        return eq

    def _sorted_insert(self, arrs, ins, cap):
        """Merge `ins` (W × u32[M], sentinel for dead lanes) into the
        sorted sentinel-padded `arrs` (W × u32[cap]) via concat + sort;
        real keys must fit in cap (checked by the caller's overflow
        logic)."""
        cat = tuple(jnp.concatenate([arrs[w], ins[w]])
                    for w in range(self.W))
        merged = lax.sort(cat, num_keys=self.W)
        return tuple(merged[w][:cap] for w in range(self.W))

    # ------------------------------------------------------------------
    # fused per-chunk step (ONE device call per frontier chunk)
    # ------------------------------------------------------------------

    def _chunk_step_impl(self, carry):
        """Expand frontier[base:base+chunk], fingerprint, dedup
        (intra-chunk first-seen + visited + level membership) and
        append the fresh states to the level buffer.  Everything stays
        on device; `carry` is donated so buffers are reused.

        Shaped for the TPU's strengths (profiled on hardware):

        - enabled lanes are compacted to the FCAP buffer *before*
          fingerprinting, so the expensive min-over-perms hash runs on
          ~enabled candidates instead of the full B×A lane grid
          (typically ~10× fewer — the fingerprint dominated phase 1);
        - the intra-chunk dedup sort is *unstable* with the compaction
          slot as an extra sort key (first-of-run then still has the
          smallest original lane index — the oracle's first-seen rule —
          while avoiding XLA's slow stable-sort path);
        - the level write is gather + contiguous dynamic_update_slice
          instead of a full-width scatter (TPU scatters are an order of
          magnitude slower than gathers at these shapes);
        - every phase boundary carries an optimization_barrier: without
          them XLA rematerializes the huge expansion graph into each
          consumer (measured 140ms/chunk vs ~20ms with barriers)."""
        B, A, W = self.chunk, self.A, self.W
        LCAP = carry["lpar"].shape[0]
        FCAP = carry["cidx"].shape[0]
        N = B * A
        base = carry["base"]        # device-resident chunk cursor: a
        # host-passed scalar would cost a blocking ~100ms host->device
        # transfer per chunk through the tunneled-TPU runtime
        sv = {k: lax.dynamic_slice_in_dim(v, base, B)
              for k, v in carry["front"].items()}
        fmask = lax.dynamic_slice_in_dim(carry["fmask"], base, B)
        ok, cand = lax.optimization_barrier(
            self.expander._expand_impl(sv))               # [B,A], [B,A,…]
        if self.act_names:
            act = jax.vmap(lambda p, crow: jax.vmap(
                lambda c: self._act_ok(p, c))(crow))(sv, cand)
            ok = ok & act
        # fmask carries both the live-row bound and the CONSTRAINT
        # prune-not-expand mask (SURVEY §2.8)
        valid = ((base + jnp.arange(B, dtype=jnp.int32)) <
                 carry["n_front"]) & fmask
        okf = (ok & valid[:, None]).reshape(N)
        n_gen = carry["n_gen"] + okf.sum(dtype=jnp.int32)

        # compact enabled lanes into FCAP (ascending lane index =
        # the oracle's successor enumeration order)
        idx = jnp.arange(N, dtype=jnp.int32)
        epos = jnp.where(okf, jnp.cumsum(okf.astype(jnp.int32)) - 1,
                         FCAP)                           # OOB drops
        n_e = okf.sum(dtype=jnp.int32)
        fovf = carry["fovf"] | (n_e > FCAP)
        eidx = lax.optimization_barrier(
            jnp.full((FCAP,), N, jnp.int32).at[epos].set(
                idx, mode="drop"))                       # slot -> lane
        elive = jnp.arange(FCAP, dtype=jnp.int32) < n_e
        take = jnp.clip(eidx, 0, N - 1)
        cand_c = lax.optimization_barrier(
            {k: v.reshape((N,) + v.shape[2:])[take]
             for k, v in cand.items()})                  # [FCAP, …]

        # fingerprint only the compacted candidates
        fp = lax.optimization_barrier(
            jax.vmap(self.fpr.fingerprint)(cand_c))      # [FCAP, W]
        kws = tuple(jnp.where(elive, fp[:, w], U32MAX)
                    for w in range(W))
        slot = jnp.arange(FCAP, dtype=jnp.int32)
        sorted_ops = lax.optimization_barrier(
            lax.sort(kws + (slot,), num_keys=W + 1))
        sk, sslot = sorted_ops[:W], sorted_ops[W]
        # first of each equal-key run = smallest slot (slot is the
        # final sort key), i.e. the oracle's first-seen survivor
        diff = jnp.zeros(FCAP, bool).at[0].set(True)
        for w in range(W):
            diff = diff | jnp.concatenate(
                [jnp.ones(1, bool), sk[w][1:] != sk[w][:-1]])
        is_sent = jnp.ones(FCAP, bool)
        for w in range(W):
            is_sent = is_sent & (sk[w] == U32MAX)
        surv = diff & ~is_sent
        # membership probes against the visited set and the level set
        surv = surv & ~self._member(carry["vis"], sk)
        surv = surv & ~self._member(carry["lvlk"], sk)

        surv = surv & ~self._member(carry["ltail"], sk)

        fresh = jnp.zeros(FCAP, bool).at[sslot].set(surv)  # slot order
        n_fresh = fresh.sum(dtype=jnp.int32)
        lpos = jnp.where(fresh,
                         jnp.cumsum(fresh.astype(jnp.int32)) - 1, FCAP)
        lidx, lkey = lax.optimization_barrier((
            jnp.zeros((FCAP,), jnp.int32).at[lpos].set(
                slot, mode="drop"),                      # out slot -> slot
            tuple(jnp.full((FCAP,), U32MAX).at[lpos].set(
                kws[w], mode="drop") for w in range(W))))

        # contiguous append at n_lvl: gather FCAP rows, one
        # dynamic_update_slice per array.  Rows past n_fresh are
        # garbage but live beyond the new n_lvl, so later chunks
        # overwrite them and finalize masks them by n_lvl.  The start
        # clamp only engages when the level has overflowed, in which
        # case ovf forces a replay anyway.
        start = jnp.minimum(carry["n_lvl"], LCAP - FCAP)
        ovf = carry["ovf"] | (carry["n_lvl"] + n_fresh > LCAP - FCAP)
        lane = take[lidx]                                # original lane id
        lvl = {k: lax.dynamic_update_slice_in_dim(
            v, cand_c[k][lidx], start, 0)
            for k, v in carry["lvl"].items()}
        # parent global ids are arithmetic: frontier row r has id
        # pg_off + r (the frontier IS the previous level, uncompacted)
        lpar = lax.dynamic_update_slice_in_dim(
            carry["lpar"], carry["pg_off"] + base + lane // A, start, 0)
        llane = lax.dynamic_update_slice_in_dim(
            carry["llane"], lane % A, start, 0)
        # two-tier level key set (LSM-style): fresh keys merge into the
        # small sorted tail each chunk (O(TCAP)); the tail spills into
        # the big sorted run only when nearly full, so the O(LCAP)
        # merge is amortized over many chunks instead of paid per chunk
        TCAP = carry["ltail"][0].shape[0]
        spill = carry["n_tail"] + n_fresh > TCAP

        def do_spill(ops):
            lvlk, ltail = ops
            return (self._sorted_insert(lvlk, ltail, LCAP),
                    tuple(jnp.full((TCAP,), U32MAX)
                          for _ in range(W)))

        def no_spill(ops):
            return ops

        lvlk, ltail = lax.cond(spill, do_spill, no_spill,
                               (carry["lvlk"], carry["ltail"]))
        n_tail = jnp.where(spill, 0, carry["n_tail"]) + n_fresh
        ltail = self._sorted_insert(ltail, lkey, TCAP)
        return dict(carry, lvl=lvl, lpar=lpar, llane=llane, lvlk=lvlk,
                    ltail=ltail, n_tail=n_tail,
                    n_lvl=jnp.minimum(carry["n_lvl"] + n_fresh,
                                      LCAP - FCAP),
                    n_gen=n_gen, ovf=ovf, fovf=fovf,
                    base=base + B)

    # ------------------------------------------------------------------
    # per-level finalize: invariants/constraints on the new states,
    # next-frontier compaction, visited merge — one device call
    # ------------------------------------------------------------------

    def _finalize_impl(self, carry):
        """Level finalize.  Returns (carry', outputs) where
        outputs["scal"] packs every per-level scalar the host needs —
        [n_lvl, n_viol, faults, n_front, ovf, fovf, n_gen] — into ONE
        int32 array so the level costs a single device→host round trip
        (the tunneled-TPU transfer latency is ~100ms; it used to be
        paid 5× per level).  When a chunk overflowed a buffer (ovf /
        fovf), the commit branch is skipped on device: the visited set
        and frontier stay untouched and the level buffer resets, so the
        host can grow capacities and replay the level exactly."""
        LCAP = carry["lpar"].shape[0]
        VCAP = carry["vis"][0].shape[0]
        n_lvl = carry["n_lvl"]
        g_off = carry["g_off"]
        bad = carry["ovf"] | carry["fovf"]
        validrow = jnp.arange(LCAP, dtype=jnp.int32) < n_lvl
        # barrier for the same reason as the chunk step: stop XLA from
        # rematerializing the predicate graphs into each consumer
        inv, con = lax.optimization_barrier(
            self._phase2_impl(carry["lvl"]))
        inv_ok = inv | ~validrow[:, None] if self.inv_names else inv
        n_viol = (~inv_ok).sum(dtype=jnp.int32)
        faults = ((carry["lvl"]["ctr"][:, C_OVERFLOW] > 0) &
                  validrow).sum(dtype=jnp.int32)

        def commit(carry):
            # the level buffer BECOMES the frontier (pointer swap, free
            # under donation); constraint-pruned rows stay in place and
            # are masked out of expansion by fmask (prune-not-expand,
            # SURVEY §2.8) so no LCAP-wide compaction gather is needed
            fmask = con & validrow
            vis = self._sorted_insert(
                carry["vis"],
                tuple(jnp.concatenate([carry["lvlk"][w],
                                       carry["ltail"][w]])
                      for w in range(self.W)),
                VCAP)
            return (carry["lvl"], carry["front"], fmask, n_lvl,
                    vis, g_off, g_off + n_lvl)

        def abandon(carry):
            # overflow: leave frontier/visited intact for the replay
            return (carry["front"], carry["lvl"], carry["fmask"],
                    carry["n_front"], carry["vis"], carry["pg_off"],
                    g_off)

        front, lvl, fmask, n_front, vis, pg_off, g_next = lax.cond(
            bad, abandon, commit, carry)
        lvlk = tuple(jnp.full((LCAP,), U32MAX) for _ in range(self.W))
        ltail = tuple(jnp.full((carry["ltail"][0].shape[0],), U32MAX)
                      for _ in range(self.W))
        n_expand = (con & validrow).sum(dtype=jnp.int32)
        scal = jnp.stack([
            n_lvl, n_viol, faults, n_front,
            carry["ovf"].astype(jnp.int32), carry["fovf"].astype(jnp.int32),
            carry["n_gen"], n_expand])
        new_carry = dict(carry, vis=vis, lvlk=lvlk, ltail=ltail,
                         n_tail=jnp.int32(0), front=front, lvl=lvl,
                         fmask=fmask, n_front=n_front,
                         n_lvl=jnp.int32(0), n_gen=jnp.int32(0),
                         ovf=jnp.bool_(False), fovf=jnp.bool_(False),
                         base=jnp.int32(0), pg_off=pg_off, g_off=g_next)
        return new_carry, dict(inv_ok=inv_ok, scal=scal)

    # ------------------------------------------------------------------

    def _fresh_carry(self, lcap: int, vcap: int, fcap: Optional[int] = None):
        fcap = fcap if fcap is not None else self.FCAP
        one = encode(self.lay, *init_state(self.cfg))
        zeros = {k: jnp.zeros((lcap,) + v.shape, dtype=v.dtype)
                 for k, v in one.items()}
        sent = tuple(jnp.full((lcap,), U32MAX) for _ in range(self.W))
        tcap = min(8 * fcap, lcap)
        return dict(
            vis=tuple(jnp.full((vcap,), U32MAX) for _ in range(self.W)),
            lvlk=sent,
            ltail=tuple(jnp.full((tcap,), U32MAX) for _ in range(self.W)),
            n_tail=jnp.int32(0),
            lvl=zeros,
            lpar=jnp.full((lcap,), -1, jnp.int32),
            llane=jnp.full((lcap,), -1, jnp.int32),
            cidx=jnp.zeros((fcap,), jnp.int32),   # chunk-compaction scratch
            n_lvl=jnp.int32(0),
            n_gen=jnp.int32(0),
            base=jnp.int32(0),      # chunk cursor within the frontier
            g_off=jnp.int32(0),     # global state-id offset (this level)
            pg_off=jnp.int32(0),    # global state-id offset (frontier)
            ovf=jnp.bool_(False),
            fovf=jnp.bool_(False),
            front={k: jnp.zeros_like(v) for k, v in zeros.items()},
            fmask=jnp.zeros((lcap,), bool),
            n_front=jnp.int32(0),
        )

    def _grow(self, carry, lcap: int, vcap: int):
        """Re-home a carry into bigger capacity buffers (visited keys and
        the frontier survive; the level buffer is reset — callers replay
        the level)."""
        old_lcap = carry["lpar"].shape[0]
        new = self._fresh_carry(lcap, vcap, self.FCAP)
        new["vis"] = self._grow_vis(carry, vcap)["vis"]
        pad = lcap - old_lcap
        new["front"] = {k: jnp.concatenate(
            [carry["front"][k], jnp.zeros((pad,) + v.shape[1:], v.dtype)])
            for k, v in carry["front"].items()}
        new["fmask"] = jnp.concatenate(
            [carry["fmask"], jnp.zeros((pad,), bool)])
        new["n_front"] = carry["n_front"]
        new["g_off"] = carry["g_off"]
        new["pg_off"] = carry["pg_off"]
        # n_gen stays 0: the caller replays the whole level from the
        # intact frontier, so keeping the partial count would double it
        return new

    # ------------------------------------------------------------------

    def check(self, max_depth: int = 10 ** 9, max_states: int = 10 ** 9,
              stop_on_violation: bool = False,
              seed_states: Optional[List] = None,
              checkpoint_path: Optional[str] = None,
              checkpoint_every: int = 1,
              resume_from: Optional[str] = None,
              verbose: bool = False) -> CheckResult:
        """seed_states entries are (State, Hist) pairs or raw SoA dicts
        (the latter preserve feature lanes exactly — engine-emitted
        seeds; punctuated search, SURVEY §2.9).

        checkpoint_path — write a checkpoint there every
        ``checkpoint_every`` levels; resume_from — continue a prior
        checkpointed run (final counts are identical to an
        uninterrupted run; levels are never half-resumed)."""
        t0 = time.time()
        lay = self.lay
        self._states: List[Dict[str, np.ndarray]] = []
        self._parents: List[np.ndarray] = []
        self._lanes: List[np.ndarray] = []

        if resume_from is not None:
            carry, res, meta = self._load_checkpoint(resume_from)
            n_states = meta["n_states"]
            n_vis = meta["n_vis"]
            depth = meta["depth"]
            n_front = meta["n_front"]
            resumed = True
        else:
            init_list = (seed_states if seed_states is not None
                         else [init_state(self.cfg)])
            init_arrs = _cat([
                {k: np.asarray(v)[None] for k, v in s.items()}
                if isinstance(s, dict) else
                {k: v[None] for k, v in encode(lay, *s).items()}
                for s in init_list])
            rootsb = {k: jnp.asarray(v) for k, v in init_arrs.items()}
            root_fp = np.asarray(self._rootfp_jit(rootsb))
            root_keys = fp_key(root_fp)
            _uniq, first_idx = np.unique(root_keys, return_index=True)
            first_idx.sort()
            roots = _take(init_arrs, first_idx)
            n_roots = len(first_idx)

            res = CheckResult(distinct_states=0,
                              generated_states=n_roots, depth=0)
            while self.LCAP - self.FCAP < 2 * n_roots:
                self.LCAP *= 2
            carry = self._fresh_carry(self.LCAP, self.VCAP)
            # roots enter through the same admit path as every level:
            # place them in the level buffer and finalize.
            pad = self.LCAP - n_roots
            carry["lvl"] = {k: jnp.asarray(np.concatenate(
                [roots[k], np.zeros((pad,) + roots[k].shape[1:],
                                    roots[k].dtype)]))
                for k in roots}
            rk = np.asarray(root_fp[first_idx], dtype=np.uint32)
            # lexicographic row sort (np.lexsort: LAST key is primary)
            order = np.lexsort(tuple(rk[:, w]
                                     for w in range(self.W - 1, -1, -1)))
            carry["lvlk"] = tuple(jnp.asarray(np.concatenate(
                [rk[order, w], np.full(pad, 0xFFFFFFFF, np.uint32)]))
                for w in range(self.W))
            carry["n_lvl"] = jnp.int32(n_roots)
            n_states = 0
            n_vis = 0
            depth = 0
            resumed = False
        t_dev = 0.0

        def run_finalize(carry):
            # pessimistic growth: a level can add at most LCAP - FCAP
            # keys, so growing on the bound needs no mid-level sync
            need = n_vis + self.LCAP - self.FCAP
            if need > self.VCAP:
                while self.VCAP < need:
                    self.VCAP *= 4
                carry = self._grow_vis(carry, self.VCAP)
            carry, out = self._fin_jit(carry)
            # the ONE per-level device->host sync
            return carry, out, [int(x) for x in np.asarray(out["scal"])]

        def harvest(carry, out, scal):
            """Per-level host bookkeeping: counts, parents/lanes,
            violations, optional state store."""
            nonlocal n_states, n_vis
            n_lvl, n_viol, faults, n_front, _, _, n_genl, _ = scal
            res.distinct_states += n_lvl
            res.overflow_faults += faults
            res.generated_states += n_genl
            if self.store_states:
                # after finalize the level's rows live in front (the
                # buffers swap); they are only overwritten by the
                # next-next level's chunk steps
                self._parents.append(np.asarray(carry["lpar"][:n_lvl]))
                self._lanes.append(np.asarray(carry["llane"][:n_lvl]))
                self._states.append(
                    {k: np.asarray(v[:n_lvl])
                     for k, v in carry["front"].items()})
            if n_viol:
                inv_ok = np.asarray(out["inv_ok"])[:n_lvl]
                rows = {k: np.asarray(v)[:n_lvl]
                        for k, v in carry["front"].items()}
                for j, nm in enumerate(self.inv_names):
                    for s in np.nonzero(~inv_ok[:, j])[0]:
                        vsv, vh = decode(self.lay, _take(rows, s))
                        res.violations.append(
                            Violation(nm, n_states + int(s),
                                      state=vsv, hist=vh))
            n_states += n_lvl
            n_vis += n_lvl
            # global state ids are device int32 (gids/lpar); fail loud
            # rather than wrap if a run ever approaches that scale
            if n_states >= 2 ** 31 - 1:
                raise RuntimeError(
                    "state-id space exhausted (2^31 ids): run exceeds "
                    "the engine's int32 global-id width")
            return n_front

        if not resumed:
            carry, out, scal = run_finalize(carry)
            n_front = harvest(carry, out, scal)
        if stop_on_violation and res.violations:
            res.seconds = time.time() - t0
            return res

        while n_front and depth < max_depth and \
                res.distinct_states < max_states:
            depth += 1
            t1 = time.time()
            while True:
                n_chunks = (n_front + self.chunk - 1) // self.chunk
                for _ in range(n_chunks):
                    carry = self._step_jit(carry)
                carry, out, scal = run_finalize(carry)
                ovf, fovf = bool(scal[4]), bool(scal[5])
                if not (ovf or fovf):
                    break
                # buffer overflow: the finalize skipped its commit on
                # device (frontier + visited intact), so grow and
                # replay the level exactly.  Growth is 4x — each growth
                # step recompiles the fused kernels, so fewer, larger
                # steps.
                if fovf:
                    self.FCAP *= 4
                if ovf or self.LCAP < 4 * self.FCAP:
                    self.LCAP = self._round_cap(
                        max((4 * self.LCAP) if ovf else self.LCAP,
                            4 * self.FCAP))
                if verbose:
                    print(f"level {depth}: buffer overflow "
                          f"({'level' if ovf else 'chunk'}), growing "
                          f"LCAP={self.LCAP} FCAP={self.FCAP}")
                carry = self._grow(carry, self.LCAP, self.VCAP)
            n_front = harvest(carry, out, scal)
            if scal[0] == 0 and scal[6] == 0:
                # the frontier had only constraint-pruned rows: nothing
                # was even generated, so this is not a BFS level (the
                # oracle's frontier excludes pruned rows and would not
                # have run it).  An all-duplicates level (n_gen > 0)
                # DOES count, matching the oracle.
                depth -= 1
            else:
                # post-constraint frontier size, the oracle's metric
                res.level_sizes.append(scal[7])
            t_dev += time.time() - t1
            if checkpoint_path is not None and \
                    depth % max(1, checkpoint_every) == 0:
                self._save_checkpoint(checkpoint_path, carry, res,
                                      depth, n_states, n_vis, n_front)
            if stop_on_violation and res.violations:
                break
            if verbose:
                print(f"depth {depth}: +{scal[0]} states "
                      f"(total {res.distinct_states}), "
                      f"frontier {n_front}, "
                      f"{n_chunks} chunks in {time.time() - t1:.2f}s")
        res.depth = depth
        res.seconds = time.time() - t0
        res.phase_seconds["device_levels"] = t_dev
        return res

    def _grow_vis(self, carry, vcap: int):
        ovcap = carry["vis"][0].shape[0]
        carry = dict(carry)
        carry["vis"] = tuple(
            jnp.concatenate([carry["vis"][w],
                             jnp.full((vcap - ovcap,), U32MAX)])
            for w in range(self.W))
        return carry

    # ------------------------------------------------------------------
    # checkpoint / resume (TLC checkpoints to states/ —
    # /root/reference/.gitignore:4; SURVEY §5).  A checkpoint is the
    # full BFS wavefront: {carry pytree, level counters, result-so-far,
    # and (when store_states) the parent/lane/state archives needed for
    # trace reconstruction}.  Written at level boundaries, so a resumed
    # run replays nothing and lands on bit-identical counts.
    # ------------------------------------------------------------------

    def _save_checkpoint(self, path, carry, res, depth, n_states,
                         n_vis, n_front):
        import json
        data = {}
        leaves = jax.tree_util.tree_flatten_with_path(carry)[0]
        for kp, leaf in leaves:
            data[_leaf_name(kp)] = np.asarray(leaf)
        if self.store_states:
            for i, arr in enumerate(self._parents):
                data[f"parents|{i}"] = arr
            for i, arr in enumerate(self._lanes):
                data[f"lanes|{i}"] = arr
            for i, blk in enumerate(self._states):
                for k, v in blk.items():
                    data[f"states|{i}|{k}"] = v
        data["viol_names"] = np.array(
            [v.invariant for v in res.violations])
        data["viol_ids"] = np.array(
            [v.state_id for v in res.violations], dtype=np.int64)
        data["meta"] = np.array(json.dumps(dict(
            depth=depth, n_states=n_states, n_vis=n_vis,
            n_front=n_front, LCAP=self.LCAP, VCAP=self.VCAP,
            FCAP=self.FCAP, chunk=self.chunk,
            distinct=res.distinct_states,
            generated=res.generated_states,
            faults=res.overflow_faults,
            level_sizes=res.level_sizes,
            n_levels=len(self._parents),
            store_states=self.store_states,
            cfg=repr(self.cfg))))
        import os
        tmp = path + ".tmp.npz"       # .npz suffix: savez won't append
        np.savez(tmp, **data)
        os.replace(tmp, path)

    def _load_checkpoint(self, path):
        import json
        z = np.load(path, allow_pickle=False)
        meta = json.loads(str(z["meta"]))
        if meta["cfg"] != repr(self.cfg):
            raise ValueError(
                "checkpoint was written for a different model config:\n"
                f"  checkpoint: {meta['cfg']}\n"
                f"  engine:     {self.cfg!r}")
        if meta["chunk"] != self.chunk:
            raise ValueError(
                f"checkpoint was written with chunk={meta['chunk']}; "
                f"resume with the same chunk (engine has {self.chunk} — "
                "capacities are rounded to the chunk size)")
        self.LCAP, self.VCAP, self.FCAP = (meta["LCAP"], meta["VCAP"],
                                           meta["FCAP"])
        # eval_shape: the template is only read for structure/key paths,
        # never materialized (a real _fresh_carry would transiently
        # double device memory at resume)
        template = jax.eval_shape(
            lambda: self._fresh_carry(self.LCAP, self.VCAP, self.FCAP))
        leaves, treedef = jax.tree_util.tree_flatten_with_path(template)
        vals = [jnp.asarray(z[_leaf_name(kp)]) for kp, _ in leaves]
        carry = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(template), vals)
        if self.store_states and not meta["store_states"]:
            raise ValueError(
                "checkpoint was written with store_states=False; "
                "resume with store_states=False (CLI: --no-store) — "
                "trace archives cannot be reconstructed")
        if self.store_states and meta["store_states"]:
            self._parents = [z[f"parents|{i}"]
                             for i in range(meta["n_levels"])]
            self._lanes = [z[f"lanes|{i}"]
                           for i in range(meta["n_levels"])]
            keys = list(template["lvl"].keys())
            self._states = [
                {k: z[f"states|{i}|{k}"] for k in keys}
                for i in range(meta["n_levels"])]
        res = CheckResult(
            distinct_states=meta["distinct"],
            generated_states=meta["generated"], depth=meta["depth"],
            level_sizes=list(meta["level_sizes"]),
            overflow_faults=meta["faults"])
        for nm, sid in zip(z["viol_names"], z["viol_ids"]):
            res.violations.append(Violation(str(nm), int(sid)))
        return carry, res, meta

    # ------------------------------------------------------------------

    def get_state(self, gid: int) -> Tuple[State, Hist]:
        return decode(self.lay, self.get_state_arrays(gid))

    def get_state_arrays(self, gid: int) -> Dict[str, np.ndarray]:
        assert self.store_states, "state store disabled"
        off = 0
        for blk in self._states:
            n = len(blk["ct"])
            if gid < off + n:
                return _take(blk, gid - off)
            off += n
        raise IndexError(gid)

    def trace(self, gid: int) -> List[Tuple[str, State]]:
        parents = np.concatenate(self._parents)
        lanes = np.concatenate(self._lanes)
        chain = []
        g = gid
        while g >= 0:
            lane = lanes[g]
            label = self.labels[lane] if lane >= 0 else "Init"
            chain.append((label, self.get_state(g)[0]))
            g = parents[g]
        return list(reversed(chain))
