"""Level-synchronous BFS engine: TLC's worker loop, TPU-shaped.

Replaces the reference's external checker (SURVEY §2.13: TLC's BFS +
fingerprint set + invariant eval) with a **device-resident** pipeline:
the frontier, the candidate expansion, the fingerprint set (an
open-addressing hash table in HBM), the dedup, the invariant /
constraint evaluation and the next-frontier compaction all live on
device.  Per frontier chunk the host issues ONE fused jit call
(expand + fingerprint + action constraints + claim-insert dedup +
invariant/constraint eval on the fresh rows + scatter into the level
buffer) with a donated carry, so chunk steps pipeline asynchronously;
the only per-level synchronization is reading back a handful of
scalars (new-state count, violation count, next-frontier size).

State identity follows TLC's semantics: the visited table stores the
symmetry-canonical VIEW fingerprints (engine/fingerprint) as
``n_streams`` u32 words; first-seen survivor order matches the Python
oracle (chunk-sequential, candidate-index order within a chunk —
SURVEY §7.4 pt 5) via rank-tie-broken claims.  CONSTRAINT semantics
are prune-not-reject: violating states are counted and checked but not
expanded (§2.8).  Parent pointers (state-id, lane-id) stream to the
host per level for trace reconstruction (SURVEY §7.2 L5).

Dedup design (the hot path — profiled on the tunneled TPU): a
membership query against the table costs ~1-3 dependent gathers
(quadratic probing at load factor <= _LOAD_MAX), versus the ~22-24
gather rounds per query of the sorted-array binary search this
replaced; inserts happen inside the same probe walk via a scatter-min
claim round, so there is no per-chunk sort and no per-level key merge
at all.  Each level journals its inserted slots; a level abandoned for
buffer overflow rolls the table back by clearing exactly those slots
(safe: a cleared cohort postdates every surviving key, so it cannot
sit on a surviving key's probe path — see _probe_insert).

Capacity model: the table (VCAP slots, power of two) and the per-level
buffer (LCAP states) are fixed-shape device arrays; when a level
outgrows LCAP (or the table's load bound trips) the engine grows the
cap, recompiles (one extra jit cache entry per growth), rolls back and
replays the level from the intact frontier.  The table grows by
rehashing into a larger table on device.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import ModelConfig
from ..obs import NULL_OBS
from ..obs.metrics import CHECK_COUNTER_KEYS
from ..ops.codec import C_OVERFLOW
from ..spec import spec_of
from ..utils import HOME_SALT
from ..resil.chaos import chaos_point
from ..utils import cat_arrays as _cat
from ..utils import fmix32_int as _fmix32_int
from ..utils import fp_key
from ..utils import take_arrays as _take
from . import driver
from .expand import Expander
from .fingerprint import Fingerprinter, fmix32

U32MAX = jnp.uint32(0xFFFFFFFF)

# historical name; the canonical definition lives in utils.HOME_SALT
# (shared with the host-partition images — see utils docstring)
_HOME_SALT = HOME_SALT


class CheckpointError(ValueError):
    """Checkpoint missing, malformed, or written by an incompatible
    engine version/config.  The CLI catches exactly this for its
    'cannot resume' message; unrelated mid-run ValueErrors propagate."""

_CACHE_ENABLED = False

_BARRIER_BATCH_REGISTERED = False


def _register_barrier_batching():
    """``jax.vmap`` over the burst core (the job-axis batched burst the
    serving layer runs) needs a batching rule for
    ``lax.optimization_barrier``; this jax version ships none.  The
    barrier is an identity, so the rule is dim-passthrough: bind the
    batched operands unchanged.  Registered lazily — only when the
    batched burst is actually used — and a no-op on jax versions that
    grow the rule upstream."""
    global _BARRIER_BATCH_REGISTERED
    if _BARRIER_BATCH_REGISTERED:
        return
    _BARRIER_BATCH_REGISTERED = True
    try:
        from jax._src.lax import lax as _lax_internal
        from jax.interpreters import batching as _batching
        prim = _lax_internal.optimization_barrier_p
    except (ImportError, AttributeError):
        return
    if prim not in _batching.primitive_batchers:
        def _rule(args, dims):
            return prim.bind(*args), dims
        _batching.primitive_batchers[prim] = _rule


def enable_persistent_compilation_cache():
    """Persist XLA executables across processes (TPU compiles of the
    fused BFS kernels run 30-50s; warm loads are sub-second).  Honors a
    user-set JAX_COMPILATION_CACHE_DIR; defaults to a repo-local dir."""
    global _CACHE_ENABLED
    if _CACHE_ENABLED:
        return
    _CACHE_ENABLED = True
    import os
    path = os.environ.get("JAX_COMPILATION_CACHE_DIR") or os.path.join(
        os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__)))), ".jax_cache")
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # persist even sub-second programs: the warm-start floor on the
        # tunneled runtime is per-executable round trips, and the many
        # small root-path programs otherwise recompile every process
        # (tools/compile_probe.py measured the breakdown)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
    except Exception:
        pass                  # older jax without the knob: run uncached


@dataclass
class Violation:
    invariant: str
    state_id: int
    # the spec oracle's (state, hist) pair — raft State/Hist or the
    # paxos twins, depending on the engine's SpecIR
    state: Optional[object] = None
    hist: Optional[object] = None
    trace: Optional[List[str]] = None


class CheckResult:
    """Run result whose scalar counters live in ONE
    ``obs.metrics.MetricsRegistry`` (``self.metrics``); the named
    attributes below are write-through views, so a harvest loop
    mutating ``res.levels_fused`` IS updating the registry — the
    ledger, ``--stats-json`` and checkpoint meta all read the same
    store and cannot drift apart per consumer (the PR-5
    ``levels_fused`` bug class).

    Counter notes (the registry keys, obs.metrics.CHECK_COUNTER_KEYS):

    - ``violations_global`` — total across the whole mesh; under a
      multi-controller run the ``violations`` list holds only this
      controller's shards, but this count (from the replicated scalar
      matrix) is global.
    - ``levels_fused`` / ``burst_dispatches`` / ``burst_bailouts`` —
      fused-dispatch telemetry (the multi-level burst fast path):
      levels committed inside bursts, burst device calls (each is
      exactly one host round trip, whether it committed levels or
      not), and calls that ended in a bail back to the per-level path
      (a call can both commit levels AND bail) — bench/progress lines
      read these to prove the burst engaged instead of silently
      bailing every level.
    - ``pin_interior_states`` — punctuated search from cfg prefix pins
      seeds BFS at the witness END state (models/golden docstring);
      TLC also counts the prefix interior states.  This is the number
      of distinct interior states the engine invariant-checked but did
      NOT count — the upper bound on the distinct_states divergence
      from TLC for pinned cfgs.
    """

    # the ONE canonical key tuple lives in obs.metrics — aliasing it
    # (not copying) is what makes a future counter addition a
    # single-site change
    _COUNTERS = CHECK_COUNTER_KEYS

    def __init__(self, distinct_states: int = 0,
                 generated_states: int = 0, depth: int = 0,
                 violations: Optional[List[Violation]] = None,
                 level_sizes: Optional[List[int]] = None,
                 seconds: float = 0.0, overflow_faults: int = 0,
                 phase_seconds: Optional[Dict[str, float]] = None,
                 violations_global: int = 0, levels_fused: int = 0,
                 burst_dispatches: int = 0, burst_bailouts: int = 0,
                 pin_interior_states: int = 0, guard_matmul: int = 0,
                 dedup_kernel: int = 0, delta_matmul: int = 0,
                 sym_canon: int = 0):
        from ..obs.metrics import MetricsRegistry
        init = locals()
        self.metrics = MetricsRegistry()
        for nm in self._COUNTERS:
            self.metrics.register(nm, int(init[nm]))
        self.violations: List[Violation] = list(violations or [])
        self.level_sizes: List[int] = list(level_sizes or [])
        self.seconds = float(seconds)
        self.phase_seconds: Dict[str, float] = dict(phase_seconds or {})

    def __repr__(self):
        body = ", ".join(f"{k}={v}"
                         for k, v in self.metrics.as_dict().items())
        return (f"CheckResult({body}, seconds={self.seconds:.3f}, "
                f"violations={len(self.violations)})")

    @property
    def states_per_sec(self):
        return self.distinct_states / max(self.seconds, 1e-9)

    @property
    def dedup_hit_rate(self):
        """Fraction of generated successors that were duplicates —
        TLC's 'distinct vs generated' engine metric (SURVEY §5)."""
        return 1.0 - self.distinct_states / max(self.generated_states, 1)


def _metric_view(nm: str) -> property:
    return property(lambda self: self.metrics.get(nm),
                    lambda self, v: self.metrics.set(nm, int(v)))


for _nm in CheckResult._COUNTERS:
    setattr(CheckResult, _nm, _metric_view(_nm))


def _ceil_log2(n: int) -> int:
    return max(1, int(np.ceil(np.log2(max(n, 2)))))


def _leaf_name(key_path) -> str:
    """Stable archive name for a carry pytree leaf (shared by
    checkpoint save and load — must stay in lockstep)."""
    return "carry|" + "|".join(
        str(getattr(p, "key", getattr(p, "idx", p))) for p in key_path)


# ---------------------------------------------------------------------------
# checkpoint serializer, shared by Engine and ShardedEngine (TLC
# checkpoints to states/ — /root/reference/.gitignore:4; SURVEY §5).
# A checkpoint is the full BFS wavefront: {carry pytree leaves (by
# _leaf_name), level counters, result-so-far, and (when store_states)
# the parent/lane/state archives for trace reconstruction}.  Written at
# level boundaries, so a resumed run replays nothing and lands on
# bit-identical counts.  Engine-specific capacity fields ride in the
# meta dict the callers supply.
# ---------------------------------------------------------------------------

_CKPT_BASE_KEYS = ("cfg", "chunk", "store_states", "n_levels",
                   "distinct", "generated", "depth", "level_sizes",
                   "faults", "viol_global", "n_states", "n_vis",
                   "n_front")


def ckpt_write(path, carry, store_states, parents, lanes, states, res,
               meta, keep: int = 1):
    """``keep`` > 1 keeps a last-K chain (path, path.1, ..) with the
    previous heads rotated down before the atomic publish; every
    member carries a sha256 sidecar (resil/ckpt_chain) so a torn or
    corrupt head is detected BEFORE any array is read and resume
    falls back to the newest valid predecessor."""
    import json
    import os
    data = {}
    for kp, leaf in jax.tree_util.tree_flatten_with_path(carry)[0]:
        data[_leaf_name(kp)] = np.asarray(leaf)
    if store_states:
        for i, arr in enumerate(parents):
            data[f"parents|{i}"] = arr
        for i, arr in enumerate(lanes):
            data[f"lanes|{i}"] = arr
        for i, blk in enumerate(states):
            for k, v in blk.items():
                data[f"states|{i}|{k}"] = v
    data["viol_names"] = np.array([v.invariant for v in res.violations])
    data["viol_ids"] = np.array([v.state_id for v in res.violations],
                                dtype=np.int64)
    base = dict(distinct=res.distinct_states,
                generated=res.generated_states,
                faults=res.overflow_faults,
                level_sizes=res.level_sizes,
                viol_global=res.violations_global,
                pin_interior=res.pin_interior_states,
                levels_fused=res.levels_fused,
                burst_dispatches=res.burst_dispatches,
                burst_bailouts=res.burst_bailouts,
                n_levels=len(parents), store_states=store_states)
    data["meta"] = np.array(json.dumps({**base, **meta}))
    tmp = path + ".tmp.npz"           # .npz suffix: savez won't append
    np.savez(tmp, **data)
    # rotate + publish + checksum sidecar (+ the ckpt_torn/ckpt_corrupt
    # chaos sites, applied to the fresh head only)
    from ..resil.ckpt_chain import publish
    publish(tmp, path, keep=keep)


def ckpt_read(path, cfg_repr, chunk, extra_keys, sharded, spill=False,
              expected_format=None, spec_name=None, sym_canon=None):
    """np.load + the meta validation every engine shares.  Returns
    (npz, meta) or raises CheckpointError.

    expected_format — optional (meta_key, want_value, why) triple: the
    engine's checkpoint-format gate, checked here so every engine
    versions its files one way (meta lacking the key reads as format 1
    — the pre-versioning era).

    spec_name — the resuming engine's SpecIR name: resume refuses on a
    spec mismatch (same pattern as the config-mismatch refusal below;
    meta lacking the key reads as "raft" — every pre-IR checkpoint is
    a Raft one).

    sym_canon — the resuming engine's RESOLVED canonicalization mode
    ("sort" | "minperm"): the visited table stores fingerprint VALUES,
    and orbit-sort values are a bijective remix of min-over-perms
    values (fingerprint._core_sort), so resuming across modes would
    silently re-visit every known state.  Refused by name; meta
    lacking the key reads as "minperm" — every round-14 checkpoint
    predates the sort path.

    Integrity (round 12, resil/ckpt_chain): the file's sha256 sidecar
    is verified BEFORE any array is touched — a truncated or corrupt
    file is a clear named condition, never a numpy/zipfile traceback —
    and a bad head falls back (with a ChainWarning) to the newest
    valid predecessor in the last-K chain ``path, path.1, ...``."""
    import json
    from ..resil.ckpt_chain import (IntegrityError, load_engine_npz,
                                    open_validated)
    # payload-integrity validation before ANY meta compare: the digest
    # check runs first; the structural loader catches legacy
    # no-sidecar files whose zip container or meta record is torn
    try:
        z, path = open_validated(path, load_engine_npz)
    except IntegrityError as e:
        raise CheckpointError(str(e)) from e
    meta = json.loads(str(z["meta"]))
    if spec_name is not None:
        got_spec = meta.get("spec", "raft")
        if got_spec != spec_name:
            raise CheckpointError(
                f"{path}: checkpoint was written for spec "
                f"{got_spec!r}; engine is running spec {spec_name!r} "
                f"— resume with --spec {got_spec}")
    if sym_canon is not None:
        got_mode = meta.get("sym_canon", "minperm")
        if got_mode != sym_canon:
            raise CheckpointError(
                f"{path}: checkpoint fingerprints were canonicalized "
                f"with --sym-canon {got_mode}; engine resolved "
                f"{sym_canon} — fingerprint values are mode-specific "
                f"(the visited table would miss every known state) — "
                f"resume with --sym-canon {got_mode}")
    # spill before sharded: a spill checkpoint handed to ShardedEngine
    # must name SpillEngine, not "the single-device Engine"
    if bool(meta.get("spill")) != spill:
        raise CheckpointError(
            f"{path}: host-spill checkpoint — resume it with "
            "SpillEngine" if meta.get("spill")
            else f"{path}: not a SpillEngine checkpoint — resume it "
            "with the engine that wrote it")
    if bool(meta.get("sharded")) != sharded:
        raise CheckpointError(
            f"{path}: sharded-engine checkpoint — resume it with "
            "ShardedEngine on the same mesh size" if meta.get("sharded")
            else f"{path}: single-device checkpoint — resume it with "
            "the single-device Engine")
    if expected_format is not None:
        fkey, want, why = expected_format
        got = meta.get(fkey, 1)
        if got != want:
            raise CheckpointError(
                f"{path}: checkpoint format {got!r} != {want} ({why}) "
                "— re-run without --resume")
    for key in _CKPT_BASE_KEYS + tuple(extra_keys):
        if key not in meta:
            raise CheckpointError(
                f"{path}: checkpoint written by an older engine "
                f"version (meta lacks {key!r}) — re-run without "
                "--resume")
    if meta["cfg"] != cfg_repr:
        raise CheckpointError(
            "checkpoint was written for a different model config:\n"
            f"  checkpoint: {meta['cfg']}\n"
            f"  engine:     {cfg_repr}")
    if meta["chunk"] != chunk:
        raise CheckpointError(
            f"checkpoint was written with chunk={meta['chunk']}; "
            f"resume with the same chunk (engine has {chunk} — "
            "capacities are rounded to the chunk size)")
    return z, meta


def ckpt_carry(path, z, template, to_device):
    """Rebuild the carry pytree from archived leaves; `to_device` is
    jnp.asarray for single-controller engines, the global-array builder
    for multi-controller ones."""
    leaves, _ = jax.tree_util.tree_flatten_with_path(template)
    missing = [_leaf_name(kp) for kp, _ in leaves
               if _leaf_name(kp) not in z]
    if missing:
        raise CheckpointError(
            f"{path}: checkpoint carry layout is from an "
            f"incompatible engine version (missing {missing[:3]}"
            f"{'…' if len(missing) > 3 else ''}) — re-run without "
            "--resume")
    return jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(template),
        [to_device(z[_leaf_name(kp)]) for kp, _ in leaves])


def ckpt_archives(z, meta, template, store_states):
    """(parents, lanes, states) trace archives; empty when the store is
    off."""
    if store_states and not meta["store_states"]:
        raise CheckpointError(
            "checkpoint was written with store_states=False; "
            "resume with store_states=False (CLI: --no-store) — "
            "trace archives cannot be reconstructed")
    if not (store_states and meta["store_states"]):
        return [], [], []
    parents = [z[f"parents|{i}"] for i in range(meta["n_levels"])]
    lanes = [z[f"lanes|{i}"] for i in range(meta["n_levels"])]
    keys = list(template["lvl"].keys())
    states = [{k: z[f"states|{i}|{k}"] for k in keys}
              for i in range(meta["n_levels"])]
    return parents, lanes, states


def ckpt_result(z, meta) -> "CheckResult":
    res = CheckResult(
        distinct_states=meta["distinct"],
        generated_states=meta["generated"], depth=meta["depth"],
        level_sizes=list(meta["level_sizes"]),
        overflow_faults=meta["faults"],
        violations_global=meta["viol_global"],
        # .get: round-3 checkpoints predate the field
        pin_interior_states=meta.get("pin_interior", 0),
        # .get: round-7 checkpoints predate the burst telemetry — a
        # resumed run's stats must stay cumulative, like every other
        # counter here
        levels_fused=meta.get("levels_fused", 0),
        burst_dispatches=meta.get("burst_dispatches", 0),
        burst_bailouts=meta.get("burst_bailouts", 0))
    for nm, sid in zip(z["viol_names"], z["viol_ids"]):
        res.violations.append(Violation(str(nm), int(sid)))
    return res


class Engine:
    """One compiled checker instance per (ModelConfig, chunk size).

    chunk    — frontier states expanded per fused device call.
    lcap     — initial per-level buffer capacity (states); doubles on
               overflow (the level is replayed from the intact frontier).
    vcap     — initial visited-set capacity (fingerprint keys).
    """

    def __init__(self, cfg: ModelConfig, chunk: int = 512,
                 store_states: bool = True,
                 lcap: int = 1 << 14, vcap: int = 1 << 17,
                 fcap: Optional[int] = None,
                 ocap: Optional[int] = None,
                 incremental_fp: bool = True,
                 burst: bool = True,
                 burst_levels: Optional[int] = None,
                 archive_dir: Optional[str] = None,
                 guard_matmul: bool = True,
                 dedup_kernel: str = "auto",
                 delta_matmul: bool = True,
                 delta_chunk_skip: Optional[bool] = None,
                 fam_density: Optional[Dict[str, int]] = None,
                 sym_canon: str = "auto"):
        enable_persistent_compilation_cache()
        self.cfg = cfg
        # the active spec's compiled operator surface (SpecIR): layout,
        # codec, kernels, families, predicates, fingerprints, oracle —
        # every model-specific hook below routes through this handle
        self.ir = spec_of(cfg)
        # observability bundle (obs/): check() rebinds it per run; the
        # archive/checkpoint helpers read it so their spans land on the
        # active run's timeline
        self._obs = NULL_OBS
        self.chunk = max(16, int(chunk))
        self.store_states = store_states
        # disk-backed per-level trace archives (engine/archive): with
        # store_states, parents/lanes/state rows stream to memmap'd
        # files under this run directory instead of growing host
        # arrays, so trace reconstruction is RAM-bounded.  None keeps
        # the historical in-RAM archive.
        self.archive_dir = archive_dir
        self._arch = None
        self._states: List[Dict[str, np.ndarray]] = []
        self._parents: List[np.ndarray] = []
        self._lanes: List[np.ndarray] = []
        # incremental per-action fingerprints (auto-off for big
        # symmetry groups — fingerprint.supports_incremental)
        self.incremental_fp = incremental_fp
        self.lay = self.ir.make_layout(cfg)
        self.kern = self.ir.make_kernels(self.lay)
        # MXU-native expansion (guard grid as int8 matmul + one-hot
        # einsum selection — expand.Expander docstring): default ON,
        # bit-exact by construction; guard_matmul=False restores the
        # historical vmapped-sweep program exactly
        self.guard_matmul = bool(guard_matmul)
        # delta-matmul successor generation (expand.Expander docstring):
        # families with declared delta algebras apply as ONE batched
        # scatter-as-matmul per family group; default ON, bit-exact by
        # construction, delta_matmul=False restores the per-family
        # kernel path for every family
        self.delta_matmul = bool(delta_matmul)
        # delta_chunk_skip: per-family lax.cond blocks that skip a
        # family's whole delta-group slice when a chunk enables none of
        # its lanes (None = follow the backend default: ON under the
        # TPU MXU lowering, OFF under the CPU scatter-add — see the
        # Expander docstring; bit-exact either way)
        self.expander = Expander(cfg, guard_matmul=self.guard_matmul,
                                 delta_matmul=self.delta_matmul,
                                 delta_chunk_skip=delta_chunk_skip)
        # Pallas probe/claim dedup kernel (fingerprint.py): "auto"
        # engages it on TPU only (the gather/scatter lax sequence stays
        # the CPU program — the kernel's interpret=True fallback exists
        # so CPU tier-1 and the oracle differentials can still exercise
        # it, via "on"); guard_matmul=False forces the whole MXU path
        # off, the kernel included.
        if dedup_kernel not in ("auto", "on", "off"):
            raise ValueError(
                f"dedup_kernel must be 'auto', 'on' or 'off' "
                f"(got {dedup_kernel!r})")
        self.dedup_kernel = dedup_kernel
        plat = jax.default_backend()
        self._dedup_pallas = self.guard_matmul and (
            dedup_kernel == "on" or
            (dedup_kernel == "auto" and plat == "tpu"))
        self._dedup_interpret = plat != "tpu"
        # symmetry canonicalization mode (fingerprint.resolve_sym_canon):
        # "sort" hashes ONE argsorted canonical relabeling per state,
        # "minperm" keeps the historical P-fold min-over-perms; "auto"
        # picks sort past 6 perms.  Fingerprint VALUES are mode-specific
        # (checkpoints refuse cross-mode resume) but the induced state
        # partition is identical — bench._canon_ab pins the A/B.
        self.fpr = Fingerprinter(cfg, sym_canon=sym_canon)
        self.preds = self.ir.make_predicates(self.lay)
        self.inv_names = list(cfg.invariants)
        self.con_names = list(cfg.constraints)
        self.act_names = list(cfg.action_constraints)
        self.labels = self.expander.lane_labels()
        self.A = self.expander.n_lanes
        self.W = self.fpr.n_streams           # u32 words per dedup key
        # capacities (LCAP always a multiple of chunk).  FCAP bounds the
        # fresh-per-chunk compaction buffer; LCAP reserves an FCAP-sized
        # append margin (usable level capacity is LCAP - FCAP).
        # FCAP: measured enabled-lane density on the metric config is
        # ~4 lanes/state on the widest levels but spikes past 8/state
        # on mid-depth chunks; chunk*16 avoids the fovf growth path,
        # whose mid-run recompile costs ~100s on the tunneled TPU
        self.FCAP = int(fcap) if fcap else min(
            self.chunk * self.A, max(self.chunk * 16, 1 << 13))
        # OCAP bounds the POST-DEDUP fresh-row buffer: phase2 +
        # narrow + level append run at this width, not FCAP.  Fresh
        # rows per chunk are enabled * (1 - dedup hit rate) — typically
        # ~1-4x chunk where enabled can exceed 20x chunk on the
        # membership config, so the second compaction cuts the
        # append-side work ~8x (measured 17+21 ms -> 8+11 ms per chunk
        # at FCAP=2^16 vs 2^13, tools/profile.py).  A chunk
        # whose fresh count exceeds OCAP trips oovf and the level
        # replays with OCAP grown (same discipline as FCAP/fam caps).
        self.OCAP = self._round_cap(min(self.FCAP, int(ocap) if ocap
                                        else max(4 * self.chunk,
                                                 1 << 11)))
        self.LCAP = self._round_cap(
            max(lcap, 4 * self.chunk, 4 * self.FCAP))
        # open-addressing table: power-of-two capacity (mask indexing)
        self.VCAP = 1 << _ceil_log2(int(vcap))
        if self.VCAP != int(vcap):
            import warnings
            warnings.warn(
                f"vcap {vcap} rounded up to the next power of two "
                f"({self.VCAP}) for mask indexing — the visited table "
                f"allocates {self.VCAP * self.W * 4} bytes",
                stacklevel=2)
        # per-family materialization caps (guard-first expansion);
        # static jit args so growth retraces the step.  fam_density
        # overrides the measured per-family densities (validated —
        # a bad entry raises here, not as a jit traceback)
        self.fam_density = dict(fam_density or {})
        self.FAM_CAPS = tuple(self.expander.default_fam_caps(
            self.chunk, self.fam_density))
        self._rehash_cache = {}
        self._phase1 = jax.jit(self._phase1_impl)
        self._phase2 = jax.jit(self._phase2_impl)
        # runtime-bounds twin (traced only by the padded-ceiling
        # serving path — solo checks never touch it)
        self._phase2_rt = jax.jit(self._phase2_rt_impl)
        # NOTE: a multi-chunk dispatch (K chunk steps per device call
        # via fori_loop) was tried and MEASURED SLOWER on v5e (70k ->
        # 38k states/s at K=4): XLA copies the loop-carried level/table
        # buffers at the loop boundary instead of aliasing them, which
        # outweighs the ~10ms flat dispatch cost of the tunneled
        # runtime that motivated it.
        self._step_jit = jax.jit(self._chunk_step_impl, donate_argnums=0,
                                 static_argnums=1)
        self._fin_jit = jax.jit(self._finalize_impl, donate_argnums=0)
        self._rootfp_jit = jax.jit(self.fpr.fingerprint_batch)
        # small-level burst (see _burst_core): on by default; burst=False
        # restores the pure per-level driver (the A/B is pinned by
        # tests/test_burst.py).  burst_levels caps the levels fused per
        # device call; the ring width is _burst_chunks frontier chunks.
        self.burst = burst
        if burst_levels is not None and int(burst_levels) <= 0:
            raise ValueError(
                f"burst_levels must be positive, got {burst_levels} "
                "(use burst=False to disable the fused-level path)")
        self.burst_levels = (int(burst_levels) if burst_levels
                             else self._BURST_LEVELS)
        self._burst_jit = jax.jit(self._burst_impl, donate_argnums=0,
                                  static_argnums=1)
        # job-axis batched burst (serve/batch) — built lazily by
        # burst_batched_fn, so solo checks never trace it
        self._bat_jit = None
        # checkpoint-chain depth (resil/ckpt_chain): keep the last K
        # checkpoints (path, path.1, ...) so a torn head falls back to
        # a valid predecessor; 1 restores the historical single file.
        # An attribute (not a ctor kwarg) so all four engine families
        # inherit it and the CLI sets it in one place (--ckpt-keep).
        self.ckpt_keep = 2

    def _round_cap(self, n: int) -> int:
        c = self.chunk
        return ((int(n) + c - 1) // c) * c

    def _fetch(self, x) -> np.ndarray:
        """Device array -> host numpy, engine-overridable: the harvest
        paths route every device read through here so an engine whose
        state lives under multi-host shardings (parallel/pjit_mesh) can
        gather to a replicated (every-controller-addressable) array
        first.  The base engines' arrays are process-local already."""
        return np.asarray(x)

    # ------------------------------------------------------------------
    # phase 1: expand + action constraints + fingerprint (also used by
    # the driver entry point and the sharded engine)
    # ------------------------------------------------------------------

    def _act_ok(self, parent_sv, cand_sv):
        """ACTION_CONSTRAINTS (TLC semantics): evaluated on the
        (unprimed, primed) pair; violating transitions are not taken.
        The name registry is part of the spec surface
        (preds.action_fn) — an unknown name errors naming the spec."""
        ok = jnp.bool_(True)
        for nm in self.act_names:
            ok = ok & self.preds.action_fn(nm)(parent_sv, cand_sv)
        return ok

    def _phase1_impl(self, svb):
        ok, cand = self.expander._expand_impl(svb)          # [B,A], [B,A,…]

        def per_state(parent, cand_row, ok_row):
            def per_lane(c, o):
                fp = self.fpr.fingerprint(c)
                act = self._act_ok(parent, c)
                return fp, act
            return jax.vmap(per_lane)(cand_row, ok_row)

        fp, act = jax.vmap(per_state)(svb, cand, ok)
        return ok & act, cand, fp

    def _phase2_one(self, sv, rtb=None):
        der = self.kern.derived(sv)
        inv = jnp.stack([self.preds.invariant_fn(nm)(sv, der)
                         for nm in self.inv_names]) \
            if self.inv_names else jnp.ones((0,), bool)
        con = jnp.bool_(True)
        for nm in self.con_names:
            con = con & self.preds.constraint_fn(nm)(sv, der, rtb)
        return inv, con

    def _phase2_impl(self, svb):
        """Batch-major ([B, ...]) public API: inv [B, n_inv], con [B]."""
        inv, con = self._phase2_T(
            {k: jnp.moveaxis(v, 0, -1) for k, v in svb.items()})
        return jnp.moveaxis(inv, -1, 0), con

    def _phase2_rt_impl(self, svb, rtb):
        """Batch-major twin taking a runtime-bounds vector (the padded-
        ceiling serving path's root admission — serve/batch._admit)."""
        inv, con = self._phase2_T(
            {k: jnp.moveaxis(v, 0, -1) for k, v in svb.items()}, rtb)
        return jnp.moveaxis(inv, -1, 0), con

    def _phase2_T(self, svT, rtb=None):
        """Batch-LAST hot-path twin: inv [n_inv, B], con [B] (rows
        vmapped at -1 — the tiny per-state minor dims waste TPU vector
        tiles batch-major, expand.materialize docstring).  ``rtb`` is
        an optional per-JOB runtime search-bounds vector
        (ops/vpredicates.runtime_bounds): constant across the state
        batch, so it broadcasts (in_axes=None) — under the serving
        layer's job-axis vmap it varies per job."""
        return jax.vmap(self._phase2_one, in_axes=(-1, None),
                        out_axes=-1)(svT, rtb)

    # ------------------------------------------------------------------
    # device-resident dedup primitives
    # ------------------------------------------------------------------

    # ------------------------------------------------------------------
    # device-resident open-addressing visited table.  Empty slot =
    # all-ones key (an all-ones fingerprint aliases "empty" with
    # probability 2^-64 — the same accepted-risk class as TLC's
    # fingerprint collisions; fp128 shrinks it to 2^-128).
    # ------------------------------------------------------------------

    _MAX_PROBE_ROUNDS = 4096
    _LOAD_MAX = 0.40

    def _home(self, keys, vcap: int):
        h = jnp.full(keys[0].shape, _HOME_SALT, jnp.uint32)
        for w in range(self.W):
            h = fmix32(h ^ keys[w])
        return (h & jnp.uint32(vcap - 1)).astype(jnp.int32)

    def _probe_insert(self, table, claims, keys, live, ranks):
        """Claim-insert dispatch: the Pallas probe/claim kernel
        (engine/fingerprint.probe_claim_insert_pallas — one fused
        kernel walking probe → compare → claim per lane, no XLA
        gather/scatter round trips) when the MXU dedup path is active,
        else the historical lax formulation (_probe_insert_lax).

        Contract for the kernel path: every caller passes ``ranks``
        ascending with lane index (they all pass jnp.arange), which
        makes the kernel's sequential index-order processing exactly
        the lax path's rank tie-break — bit-identical outcomes
        (tests/test_guard_matmul.py pins it on forced-collision
        fixtures)."""
        if self._dedup_pallas:
            from .fingerprint import probe_claim_insert_pallas
            with jax.named_scope("dedup_kernel"):
                table, fresh, pos, hovf = probe_claim_insert_pallas(
                    table, keys, live,
                    max_rounds=self._MAX_PROBE_ROUNDS,
                    interpret=self._dedup_interpret)
            return table, claims, fresh, pos, hovf
        return self._probe_insert_lax(table, claims, keys, live, ranks)

    def _probe_insert_lax(self, table, claims, keys, live, ranks):
        """Parallel claim-insert of `keys` (W × u32[M]; lanes with
        live=False are ignored) into the open-addressing `table`
        (W × u32[VCAP]; `claims` u32[VCAP] all-U32MAX between calls).
        Returns (table', claims', fresh, pos, hovf): fresh marks lanes
        whose key was NOT already present and won its slot; pos is each
        lane's final table slot.

        Two-phase structure, shaped by TPU op costs (scatters are an
        order of magnitude slower than gathers at these widths):

        - WALK (inner while_loop, gathers only): every active lane
          quadratic-probes (pos += ++t, full-cycle for power-of-2
          capacity) until its current slot holds its key (duplicate)
          or is empty (insertion candidate).
        - RESOLVE (one scatter round per outer iteration): insertion
          candidates claim their empty slot by scatter-min of the lane
          rank (first-seen tie-break = the oracle's enumeration order,
          since ranks ascend in candidate order); winners scatter
          their keys into the table; claims are reset by a scatter of
          the sentinel.  Losers — and duplicates of a key that just
          won — stay active and re-walk from their current position in
          the next outer iteration (equal keys walk identical probe
          paths, so a duplicate always finds its winner).

        The outer loop runs until every lane resolves — typically 2-3
        iterations (≈12 scatter ops total), versus one 4-scatter round
        per probe *step* in the naive formulation.  `hovf` reports a
        blown round budget (table too full — caller grows, rehashes,
        replays the level).

        Rollback safety (used by _finalize_impl's abandon): every slot
        on an inserted key's probe path was occupied by an *earlier*
        insert at walk time, so clearing a whole trailing cohort of
        inserts can never punch an empty hole into a surviving key's
        path — lookups after rollback still terminate correctly.
        """
        VCAP = table[0].shape[0]
        M = keys[0].shape[0]
        pos0 = self._home(keys, VCAP)

        def classify(table, pos):
            cur = [table[w][pos] for w in range(self.W)]
            iskey = jnp.ones((M,), bool)
            isempty = jnp.ones((M,), bool)
            for w in range(self.W):
                iskey &= cur[w] == keys[w]
                isempty &= cur[w] == U32MAX
            return iskey, isempty

        def outer_cond(st):
            _t, _c, _p, _tt, active, _f, rounds = st
            return active.any() & (rounds < self._MAX_PROBE_ROUNDS)

        def outer_body(st):
            table, claims, pos, t, active, fresh, rounds = st

            # ---- walk: gathers only, no table writes ----
            def walk_cond(ws):
                _p, _t, moving, steps = ws
                return moving.any() & (steps < self._MAX_PROBE_ROUNDS)

            def walk_body(ws):
                pos, t, moving, steps = ws
                iskey, isempty = classify(table, pos)
                adv = moving & ~(iskey | isempty)
                t = jnp.where(adv, t + 1, t)
                pos = jnp.where(adv, (pos + t) & (VCAP - 1), pos)
                return pos, t, adv, steps + 1

            pos, t, still_moving, _s = lax.while_loop(
                walk_cond, walk_body, (pos, t, active, jnp.int32(0)))
            iskey, isempty = classify(table, pos)
            active = active & ~iskey               # duplicate: lane dies

            # ---- resolve: one claim/insert/reset scatter round ----
            claimers = active & isempty
            cidx = jnp.where(claimers, pos, VCAP)
            claims = claims.at[cidx].min(ranks, mode="drop")
            won = claimers & (claims[pos] == ranks)
            widx = jnp.where(won, pos, VCAP)
            table = tuple(table[w].at[widx].set(keys[w], mode="drop")
                          for w in range(self.W))
            claims = claims.at[cidx].set(U32MAX, mode="drop")
            fresh = fresh | won
            active = active & ~won
            return table, claims, pos, t, active, fresh, rounds + 1

        state0 = (table, claims, pos0, jnp.zeros((M,), jnp.int32),
                  live, jnp.zeros((M,), bool), jnp.int32(0))
        table, claims, pos, _t, active, fresh, _r = lax.while_loop(
            outer_cond, outer_body, state0)
        return table, claims, fresh, pos, active.any()

    def _host_probe_assign(self, keys: np.ndarray,
                           vcap: Optional[int] = None) -> np.ndarray:
        """Sequential host twin of _probe_insert against an EMPTY table
        (root/punctuated-seed placement): same home hash and quadratic
        advance, so the device continues the table consistently.  keys
        are pre-deduped [N, W] u32."""
        vcap = vcap if vcap is not None else self.VCAP
        occupied = set()
        out = np.zeros(len(keys), np.int32)
        for i, kw in enumerate(keys):
            h = _HOME_SALT
            for w in range(self.W):
                h = _fmix32_int(h ^ int(kw[w]))
            pos, t = h & (vcap - 1), 0
            while pos in occupied:
                t += 1
                pos = (pos + t) & (vcap - 1)
            occupied.add(pos)
            out[i] = pos
        return out

    def _rehash_tables(self, table, new_vcap: int):
        """Grow the visited table: device-side rehash of every occupied
        slot into a fresh table (and fresh claims array) of `new_vcap`
        slots (one jit cache entry per (old, new) capacity pair)."""
        old_vcap = table[0].shape[0]
        fn = self._rehash_cache.get((old_vcap, new_vcap))
        if fn is None:
            def impl(table):
                allones = jnp.ones((old_vcap,), bool)
                for w in range(self.W):
                    allones &= table[w] == U32MAX
                new = tuple(jnp.full((new_vcap,), U32MAX)
                            for _ in range(self.W))
                ncl = jnp.full((new_vcap,), U32MAX)
                ranks = jnp.arange(old_vcap, dtype=jnp.uint32)
                # always the lax path: a rehash probes old_vcap lanes
                # at once — not the per-candidate hot loop the Pallas
                # kernel exists for
                new, ncl, _fresh, _pos, hv = self._probe_insert_lax(
                    new, ncl, table, ~allones, ranks)
                return new, ncl, hv
            fn = self._rehash_cache[(old_vcap, new_vcap)] = jax.jit(impl)
        new, ncl, hv = fn(table)
        if bool(np.asarray(hv)):
            raise RuntimeError("rehash did not converge — table "
                               "pathologically full; raise vcap")
        return new, ncl

    # ------------------------------------------------------------------
    # fused per-chunk step (ONE device call per frontier chunk)
    # ------------------------------------------------------------------

    def _expand_fp_chunk(self, sv, valid, fam_caps, FCAP, rt=None):
        """Shared front half of a chunk step (this engine's fused step
        and engine/spill's streamed step): guard-first expansion over
        the [B, A] lane grid, compaction of enabled lanes into the FCAP
        buffer, successor materialization, ACTION_CONSTRAINTS, and the
        symmetry-canonical fingerprint of the compacted candidates.

        Returns (cand_c [..., FCAP] batch-last, elive [FCAP], fp
        [W, FCAP], take [FCAP] flat lane ids, famx_chunk [n_fams]
        per-family enabled counts, n_e enabled total).  Callers fold
        famx/fovf into their carries.

        Fingerprints run INCREMENTALLY when the config supports it
        (fingerprint.py "Incremental per-action fingerprints"): one
        full per-term hash per PARENT, per-candidate deltas over the
        action family's touched positions — bit-identical to the
        direct path (tests/test_codec.py) at a fraction of the work on
        wide-expansion configs.

        ``rt`` — the per-job runtime-thresholds dict (guard thresholds
        + family lane mask as device data; expand.Expander docstring),
        None outside the padded-ceiling serving path."""
        B, A = valid.shape[0], self.A      # B from the caller's batch:
        # the level burst expands a whole (small) frontier as one chunk
        N = B * A
        derb = self.expander.derived_batch_T(sv)
        ok = lax.optimization_barrier(
            self.expander.guards_T(sv, derb, rt))
        okf = (ok & valid[:, None]).reshape(N)

        # compact enabled lanes into FCAP (ascending lane index =
        # the oracle's successor enumeration order)
        idx = jnp.arange(N, dtype=jnp.int32)
        epos = jnp.where(okf, jnp.cumsum(okf.astype(jnp.int32)) - 1,
                         FCAP)                           # OOB drops
        n_e = okf.sum(dtype=jnp.int32)
        incr = self.incremental_fp and self.fpr.supports_incremental()
        if incr:
            tables = lax.optimization_barrier(
                self.fpr.parent_tables(sv))
            cand_c, famx, fp = self.expander.materialize(
                sv, derb, okf, epos, FCAP, fam_caps,
                delta_fp=(self.fpr, tables))
        else:
            cand_c, famx = self.expander.materialize(
                sv, derb, okf, epos, FCAP, fam_caps)
        cand_c = lax.optimization_barrier(cand_c)        # [..., FCAP]
        elive = jnp.arange(FCAP, dtype=jnp.int32) < n_e
        eidx = lax.optimization_barrier(
            jnp.full((FCAP,), N, jnp.int32).at[epos].set(
                idx, mode="drop"))                       # slot -> lane
        take = jnp.clip(eidx, 0, N - 1)
        if self.act_names:
            # ACTION_CONSTRAINTS on the compacted (parent, successor)
            # pairs: violating transitions are killed before dedup
            par_c = {k: v[..., take // A] for k, v in sv.items()}
            act = jax.vmap(self._act_ok, in_axes=-1)(par_c, cand_c)
            elive = elive & act
        if not incr:
            # direct path: full min-over-perms hash per candidate
            fp = self.fpr.fingerprint_batch_T(cand_c)    # [W, FCAP]
        fp = lax.optimization_barrier(fp)
        return cand_c, elive, fp, take, famx, n_e

    def _chunk_step_impl(self, carry, fam_caps):
        """Expand frontier[base:base+chunk], fingerprint, dedup via the
        visited hash table (claim-insert: intra-chunk first-seen,
        cross-chunk and cross-level membership in ONE probe walk),
        evaluate invariants/constraints on the fresh rows, and append
        them to the level buffer.  Everything stays on device; `carry`
        is donated so buffers are reused.

        Shaped for the TPU's strengths (profiled on hardware):

        - enabled lanes are compacted to the FCAP buffer *before*
          fingerprinting, so the expensive min-over-perms hash runs on
          ~enabled candidates instead of the full B×A lane grid
          (typically ~10× fewer — the fingerprint dominated phase 1);
        - dedup is the open-addressing claim walk (_probe_insert):
          ~1-3 dependent gathers per candidate instead of the 60+
          binary-search gather rounds of the sorted-set design, and no
          sorts anywhere in the step;
        - the level write is gather + contiguous dynamic_update_slice
          instead of a full-width scatter (TPU scatters are an order of
          magnitude slower than gathers at these shapes);
        - invariants/constraints run here on the FCAP fresh rows, not
          on the LCAP-wide level buffer at finalize — total predicate
          work is O(distinct states), and finalize does no heavy work;
        - every phase boundary carries an optimization_barrier: without
          them XLA rematerializes the huge expansion graph into each
          consumer (measured 140ms/chunk vs ~20ms with barriers)."""
        B, A, W = self.chunk, self.A, self.W
        LCAP = carry["lpar"].shape[0]
        FCAP = carry["cidx"].shape[0]
        OCAP = carry["oidx"].shape[0]
        VCAP = carry["vis"][0].shape[0]
        N = B * A
        base = carry["base"]        # device-resident chunk cursor: a
        # host-passed scalar would cost a blocking ~100ms host->device
        # transfer per chunk through the tunneled-TPU runtime
        # Frontier rows are stored narrow (codec.narrow_dtypes) and
        # BATCH-LAST ([..., LCAP]): the tiny per-state dims (S, Lcap,
        # K) are far smaller than the TPU's (8, 128) vector tiles, so
        # keeping them off the lane axis is worth ~5x on the successor
        # materialization (expand.Expander.materialize docstring).
        sv = self.ir.widen({k: lax.dynamic_slice_in_dim(v, base, B,
                                                axis=v.ndim - 1)
                    for k, v in carry["front"].items()})
        fmask = lax.dynamic_slice_in_dim(carry["fmask"], base, B)
        # guard-first expansion + compaction + fingerprint: the shared
        # front half (_expand_fp_chunk).  fmask carries both the
        # live-row bound and the CONSTRAINT prune-not-expand mask
        # (SURVEY §2.8)
        valid = ((base + jnp.arange(B, dtype=jnp.int32)) <
                 carry["n_front"]) & fmask
        cand_c, elive, fp, take, famx_c, n_e = self._expand_fp_chunk(
            sv, valid, fam_caps, FCAP)
        famx = jnp.maximum(carry["famx"], famx_c)
        fovf = carry["fovf"] | (n_e > FCAP) | \
            jnp.any(famx > jnp.asarray(fam_caps, jnp.int32))
        n_gen = carry["n_gen"] + elive.sum(dtype=jnp.int32)
        keys = tuple(jnp.where(elive, fp[w], U32MAX)
                     for w in range(W))
        # any overflow means this level replays — stop inserting so the
        # journal stays the exact record of this level's table writes
        gate = ~(carry["ovf"] | fovf | carry["hovf"] | carry["oovf"])
        ranks = jnp.arange(FCAP, dtype=jnp.uint32)
        table, claims, fresh, pos, hv = self._probe_insert(
            carry["vis"], carry["claims"], keys, elive & gate, ranks)
        hovf = carry["hovf"] | hv
        n_fresh = fresh.sum(dtype=jnp.int32)
        # two chunk-local overflows share the revert path: level buffer
        # full (ovf — margin is OCAP, the most one chunk can append)
        # and fresh-compaction buffer blown (oovf)
        ovf_now = carry["n_lvl"] + n_fresh > LCAP - OCAP
        oovf_now = n_fresh > OCAP
        bad_now = ovf_now | oovf_now
        # revert THIS chunk's inserts on the spot (earlier chunks' stay
        # until finalize's abandon clears them via the journal), then
        # skip the append
        ridx = jnp.where(fresh & bad_now, pos, VCAP)
        table = tuple(table[w].at[ridx].set(U32MAX, mode="drop")
                      for w in range(W))
        fresh = fresh & ~bad_now
        n_fresh = jnp.where(bad_now, 0, n_fresh)
        ovf = carry["ovf"] | ovf_now
        oovf = carry["oovf"] | oovf_now

        # second compaction: FCAP candidate slots -> OCAP fresh rows.
        # Everything downstream (phase2, narrow, the level append) runs
        # at OCAP width — fresh rows are the dedup survivors, typically
        # ~8x fewer than enabled candidates on wide-grid configs
        # (tools/profile.py measured the width halves the
        # append+phase2 cost even at 8x).
        slot = jnp.arange(FCAP, dtype=jnp.int32)
        lpos = jnp.where(fresh,
                         jnp.cumsum(fresh.astype(jnp.int32)) - 1, OCAP)
        lidx = lax.optimization_barrier(
            jnp.zeros((OCAP,), jnp.int32).at[lpos].set(
                slot, mode="drop"))                # out slot -> FCAP slot

        # contiguous append at n_lvl: gather OCAP rows, one
        # dynamic_update_slice per array.  Rows past n_fresh are
        # garbage but live beyond the new n_lvl, so later chunks
        # overwrite them and finalize masks them by n_lvl.  The start
        # clamp only engages when the level has overflowed, in which
        # case ovf forces a replay anyway.
        start = jnp.minimum(carry["n_lvl"], LCAP - OCAP)
        lane = take[lidx]                                # original lane id
        rows = lax.optimization_barrier(
            {k: cand_c[k][..., lidx] for k in cand_c})   # batch-last
        # invariants + constraints on the fresh rows (garbage rows are
        # masked by n_lvl at finalize)
        inv, con = lax.optimization_barrier(self._phase2_T(rows))
        rows_n = self.ir.narrow(self.lay, rows)   # storage dtypes
        lvl = {k: lax.dynamic_update_slice_in_dim(
                   v, rows_n[k], start, v.ndim - 1)
               for k, v in carry["lvl"].items()}
        # parent global ids are arithmetic: frontier row r has id
        # pg_off + r (the frontier IS the previous level, uncompacted)
        lpar = lax.dynamic_update_slice_in_dim(
            carry["lpar"], carry["pg_off"] + base + lane // A, start, 0)
        llane = lax.dynamic_update_slice_in_dim(
            carry["llane"], lane % A, start, 0)
        jslot = lax.dynamic_update_slice_in_dim(
            carry["jslot"], pos[lidx], start, 0)
        linv = lax.dynamic_update_slice_in_dim(carry["linv"], inv,
                                               start, 1)
        lcon = lax.dynamic_update_slice_in_dim(
            carry["lcon"], con, start, 0)
        return dict(carry, vis=table, claims=claims, lvl=lvl, lpar=lpar,
                    llane=llane, jslot=jslot, linv=linv, lcon=lcon,
                    n_lvl=jnp.minimum(carry["n_lvl"] + n_fresh,
                                      LCAP - OCAP),
                    n_gen=n_gen, ovf=ovf, fovf=fovf, hovf=hovf,
                    oovf=oovf, famx=famx,
                    ofx=jnp.maximum(carry["ofx"], n_fresh),
                    base=base + B)

    # ------------------------------------------------------------------
    # per-level finalize: scalar aggregation, next-frontier swap,
    # journal rollback on overflow — one cheap device call.
    #
    # (A whole-level while_loop driver was tried and reverted for the
    # single-device engine: XLA materializes padded-layout copies of
    # the loop-carried [LCAP, S, S]-shaped buffers — (3,3) minor dims
    # tile to (4,128), a 57x blowup that OOMs HBM at LCAP=2^21 — and
    # measured host dispatch is only ~0.5 ms/chunk, so per-chunk
    # dispatch costs nothing.  The sharded engine keeps its level
    # driver: shard_map dispatch is genuinely expensive and its
    # per-device LB is D-fold smaller.)
    # ------------------------------------------------------------------

    def _finalize_impl(self, carry):
        """Level finalize.  Returns (carry', outputs) where
        outputs["scal"] packs every per-level scalar the host needs —
        [n_lvl, n_viol, faults, n_front, ovf, fovf, n_gen, n_expand,
        hovf] — into ONE int32 array so the level costs a single
        device→host round trip (the tunneled-TPU transfer latency is
        ~100ms).  Invariants/constraints were already evaluated per
        chunk (linv/lcon rows); finalize only aggregates, swaps the
        level buffer into the frontier, and — when a chunk overflowed a
        buffer (ovf/fovf/hovf) — rolls the visited table back via the
        journal instead of committing, so the host can grow capacities
        and replay the level exactly."""
        LCAP = carry["lpar"].shape[0]
        VCAP = carry["vis"][0].shape[0]
        n_lvl = carry["n_lvl"]
        g_off = carry["g_off"]
        bad = carry["ovf"] | carry["fovf"] | carry["hovf"] | \
            carry["oovf"]
        validrow = jnp.arange(LCAP, dtype=jnp.int32) < n_lvl
        inv_ok = (carry["linv"] | ~validrow[None, :]
                  if self.inv_names else carry["linv"])   # [n_inv, LCAP]
        con = carry["lcon"]
        n_viol = (~inv_ok).sum(dtype=jnp.int32)
        faults = ((carry["lvl"]["ctr"][C_OVERFLOW] > 0) &
                  validrow).sum(dtype=jnp.int32)

        def commit(carry):
            # the level buffer BECOMES the frontier (pointer swap, free
            # under donation); constraint-pruned rows stay in place and
            # are masked out of expansion by fmask (prune-not-expand,
            # SURVEY §2.8) so no LCAP-wide compaction gather is needed.
            # The level's keys are already in the visited table.
            fmask = con & validrow
            return (carry["lvl"], carry["front"], fmask, n_lvl,
                    carry["vis"], g_off, g_off + n_lvl)

        def abandon(carry):
            # overflow: roll the visited table back to the level start
            # by clearing exactly the journaled inserts (safe — see
            # _probe_insert rollback note), leave the frontier intact
            cidx = jnp.where(validrow, carry["jslot"], VCAP)
            vis = tuple(carry["vis"][w].at[cidx].set(U32MAX, mode="drop")
                        for w in range(self.W))
            return (carry["front"], carry["lvl"], carry["fmask"],
                    carry["n_front"], vis, carry["pg_off"], g_off)

        front, lvl, fmask, n_front, vis, pg_off, g_next = lax.cond(
            bad, abandon, commit, carry)
        n_expand = (con & validrow).sum(dtype=jnp.int32)
        # scal tail carries the per-family enabled-count maxima so the
        # host can grow exactly the overflowing family caps (still ONE
        # device→host transfer per level)
        scal = jnp.concatenate([jnp.stack([
            n_lvl, n_viol, faults, n_front,
            carry["ovf"].astype(jnp.int32), carry["fovf"].astype(jnp.int32),
            carry["n_gen"], n_expand, carry["hovf"].astype(jnp.int32),
            carry["oovf"].astype(jnp.int32), carry["ofx"]]),
            carry["famx"]])
        new_carry = dict(carry, vis=vis, front=front, lvl=lvl,
                         fmask=fmask, n_front=n_front,
                         n_lvl=jnp.int32(0), n_gen=jnp.int32(0),
                         ovf=jnp.bool_(False), fovf=jnp.bool_(False),
                         hovf=jnp.bool_(False), oovf=jnp.bool_(False),
                         famx=jnp.zeros_like(carry["famx"]),
                         ofx=jnp.int32(0),
                         base=jnp.int32(0), pg_off=pg_off, g_off=g_next)
        return new_carry, dict(inv_ok=inv_ok, scal=scal)

    # ------------------------------------------------------------------
    # small-level burst: run up to burst_levels whole BFS levels in ONE
    # device call while the frontier fits the burst ring
    # (_burst_chunks frontier chunks).
    #
    # Motivation (measured, round 5): the tunneled-TPU runtime costs
    # ~172 ms per synchronous dispatch+readback, so a tiny level (one
    # chunk step + finalize + scalar sync) costs ~220 ms of which the
    # device computes ~80 ms — the 12 sub-chunk levels every config #3
    # run pays before the space widens were ~2.6 s of almost pure
    # latency.  The burst folds those levels into one jit: a
    # lax.while_loop whose body is the SAME pipeline as a chunk step
    # (guard-first expand + fingerprint + claim-insert dedup + phase2)
    # plus the finalize's commit; each iteration processes one frontier
    # CHUNK and commits a level whenever the chunk cursor drains the
    # frontier, so levels up to _burst_chunks * chunk states still fuse
    # (round 5's one-chunk burst capped at `chunk`, which left config
    # #3's 2-5k-state early levels on the per-level path).  The host
    # reads back ONE stats array for the whole burst.
    #
    # The while carry holds only ring-width (_burst_chunks * chunk)
    # buffers + the visited table; the big LCAP buffers pass through
    # OUTSIDE the loop (the reverted whole-level while_loop driver died
    # on XLA padding the loop-carried [.., S, S, LCAP] buffers — see
    # the note above _finalize_impl; the burst's loop-carried state
    # stays orders of magnitude smaller).
    #
    # Overflow discipline: any overflow (enabled > FCAP, a family cap,
    # level outgrowing the ring, probe budget) BAILS: the tripping
    # chunk's table inserts are cleared on the spot and the level's
    # earlier chunks' via the in-ring journal, the pre-level frontier
    # is kept, and the host replays that level through the ordinary
    # per-level path.  Archives (parents/lanes/state rows/inv bits) are
    # recorded per level on device and fetched only when needed
    # (store_states or a violation), so a clean burst costs one small
    # D2H transfer.
    #
    # Parent ids ride an explicit per-row gid array (gd) instead of the
    # chunk step's pg_off arithmetic: the spill engine feeds this same
    # core (engine/spill) with host-compacted frontiers whose gids are
    # NOT contiguous; levels born inside the burst refresh gd
    # arithmetically, which is exactly the per-level id assignment.
    # ------------------------------------------------------------------

    _BURST_LEVELS = 16
    _BURST_CHUNKS = 4           # ring width, in frontier chunks
    _BS_N = 8                   # stats columns (see _burst_core)

    @property
    def _burst_chunks(self) -> int:
        return self._BURST_CHUNKS

    def _burst_width(self) -> int:
        """Ring width (states): the largest frontier/level the fused
        path handles before falling back to the per-level driver."""
        return self._burst_chunks * self.chunk

    def _burst_core(self, vis, claims, fr, fm, gd, nf, g0, pg0,
                    fam_caps, levels_left, states_cap, fcap=None,
                    ocap=None, rt=None):
        """The fused multi-level loop, over standalone ring-width
        buffers (no engine carry): fr/fm/gd are [..., KB]/[KB]/[KB]
        frontier rows (narrow, batch-last), membership mask and global
        ids; g0 is the next global id to assign.  Returns (stf, out):
        stf the final while state (vis/claims/fr/fm/gd/nf/g/pg), out
        the stats + per-level archives.

        out["stats"] is int32 [burst_levels + 1, _BS_N]: per-level rows
        [n_lvl, n_viol, faults, n_expand, n_gen, 0, 0, 0] and a meta
        row at index burst_levels:
        [n_levels_done, bail, n_front_out, viol_any, states_done].
        out["par"]/out["lane"] are [L_MAX, KB] int32, out["st"] the
        narrow state rows [..., L_MAX, KB], out["inv"] bool
        [n_inv, L_MAX, KB] — the per-level archives."""
        B, A, W = self.chunk, self.A, self.W
        FCAP = int(fcap) if fcap is not None else self.FCAP
        KB = fm.shape[0]
        VCAP = vis[0].shape[0]
        L_MAX = self.burst_levels
        n_inv = len(self.inv_names)
        # post-dedup compaction width (capped by the ring: a chunk can
        # never append more than KB rows anyway) — see the OCAP note in
        # the chunk step; the burst body used to run narrow/phase2 at
        # FCAP width per chunk where the per-level path compacts to
        # OCAP first, a measured ~2x per-chunk saving
        OC = min(int(ocap) if ocap is not None else self.OCAP, KB)

        st = dict(
            vis=vis, claims=claims, fr=fr, fm=fm, gd=gd, nf=nf,
            base=jnp.int32(0), nl=jnp.int32(0), gl=jnp.int32(0),
            lv={k: jnp.zeros_like(v) for k, v in fr.items()},
            lvp=jnp.full((KB,), -1, jnp.int32),
            lvlane=jnp.full((KB,), -1, jnp.int32),
            lin=jnp.ones((n_inv, KB), bool),
            lco=jnp.ones((KB,), bool),
            jsl=jnp.zeros((KB,), jnp.int32),
            li=jnp.int32(0), done=jnp.int32(0),
            g=g0, pg=pg0,
            bail=jnp.bool_(False), viol=jnp.bool_(False),
            stats=jnp.zeros((L_MAX, self._BS_N), jnp.int32),
            opar=jnp.full((L_MAX, KB), -1, jnp.int32),
            olane=jnp.full((L_MAX, KB), -1, jnp.int32),
            ost={k: jnp.zeros(v.shape[:-1] + (L_MAX, KB), v.dtype)
                 for k, v in fr.items()},
            oinv=jnp.ones((n_inv, L_MAX, KB), bool),
        )

        def cond(st):
            return (~st["bail"] & ~st["viol"] & (st["li"] < levels_left)
                    & (st["nf"] > 0) & (st["done"] < states_cap))

        def body(st):
            base, nl = st["base"], st["nl"]
            sv = self.ir.widen({k: lax.dynamic_slice_in_dim(v, base, B,
                                                    axis=v.ndim - 1)
                        for k, v in st["fr"].items()})
            fm_c = lax.dynamic_slice_in_dim(st["fm"], base, B)
            valid = ((base + jnp.arange(B, dtype=jnp.int32)) <
                     st["nf"]) & fm_c
            cand_c, elive, fp, take, famx_c, n_e = \
                self._expand_fp_chunk(sv, valid, fam_caps, FCAP, rt)
            bail = (n_e > FCAP) | jnp.any(
                famx_c > jnp.asarray(fam_caps, jnp.int32))
            keys = tuple(jnp.where(elive, fp[w], U32MAX)
                         for w in range(W))
            ranks = jnp.arange(FCAP, dtype=jnp.uint32)
            vis, claims, fresh, pos, hv = self._probe_insert(
                st["vis"], st["claims"], keys, elive & ~bail, ranks)
            bail = bail | hv
            n_fresh = fresh.sum(dtype=jnp.int32)
            bail = bail | (nl + n_fresh > KB)
            # one chunk's fresh rows outran the post-dedup compaction
            # buffer: bail to the per-level path, whose oovf growth
            # machinery owns this case
            bail = bail | (n_fresh > OC)
            # bail => this level never happened: clear THIS chunk's
            # inserts on the spot and the level's earlier chunks' via
            # the ring journal (rollback-safe — _probe_insert note)
            ridx = jnp.where(fresh & bail, pos, VCAP)
            vis = tuple(vis[w].at[ridx].set(U32MAX, mode="drop")
                        for w in range(W))
            jb = jnp.where((jnp.arange(KB, dtype=jnp.int32) < nl) & bail,
                           st["jsl"], VCAP)
            vis = tuple(vis[w].at[jb].set(U32MAX, mode="drop")
                        for w in range(W))
            fresh = fresh & ~bail
            n_fresh = jnp.where(bail, 0, n_fresh)
            n_genl = jnp.where(bail, 0, elive.sum(dtype=jnp.int32))
            gl2 = st["gl"] + n_genl
            nl2 = nl + n_fresh

            # second compaction (the chunk step's OCAP discipline,
            # folded in round 9): fresh FCAP slots compact to OC rows
            # BEFORE narrow/phase2/ring-append, so the burst body never
            # pays padded FCAP width for the append-side work.  Row
            # order is candidate-slot ascending = parent-major, lane
            # ascending — the per-level order, bit-identical appends.
            slot = jnp.arange(FCAP, dtype=jnp.int32)
            opos = jnp.where(fresh,
                             jnp.cumsum(fresh.astype(jnp.int32)) - 1,
                             OC)
            oidx = lax.optimization_barrier(
                jnp.zeros((OC,), jnp.int32).at[opos].set(
                    slot, mode="drop"))          # out row -> FCAP slot
            rows = lax.optimization_barrier(
                {k: cand_c[k][..., oidx] for k in cand_c})
            inv, con = self._phase2_T(
                rows, None if rt is None else rt["bounds"])
            rows_n = self.ir.narrow(self.lay, rows)
            # ring positions for the compacted rows: nl + row index
            oar = jnp.arange(OC, dtype=jnp.int32)
            rpos = jnp.where(oar < n_fresh, nl + oar, KB)
            lv = {k: st["lv"][k].at[..., rpos].set(rows_n[k],
                                                   mode="drop")
                  for k in st["lv"]}
            take_o = take[oidx]
            par_row = jnp.clip(base + take_o // A, 0, KB - 1)
            pgid = st["gd"][par_row]
            lvp = st["lvp"].at[rpos].set(pgid, mode="drop")
            lvlane = st["lvlane"].at[rpos].set(take_o % A, mode="drop")
            jsl = st["jsl"].at[rpos].set(pos[oidx], mode="drop")
            lin = (st["lin"].at[:, rpos].set(inv, mode="drop")
                   if n_inv else st["lin"])
            lco = st["lco"].at[rpos].set(con, mode="drop")

            new_base = base + B
            level_done = ~bail & (new_base >= st["nf"])

            # level commit (predicated — a mid-level chunk leaves the
            # frontier and archives untouched)
            validrow = jnp.arange(KB, dtype=jnp.int32) < nl2
            inv_ok = ((lin | ~validrow[None, :]) if n_inv
                      else jnp.ones((0, KB), bool))
            n_viol = (~inv_ok).sum(dtype=jnp.int32)
            faults = ((lv["ctr"][C_OVERFLOW] > 0) &
                      validrow).sum(dtype=jnp.int32)
            n_expand = (lco & validrow).sum(dtype=jnp.int32)
            li = st["li"]
            row = jnp.stack([nl2, n_viol, faults, n_expand, gl2,
                             jnp.int32(0), jnp.int32(0), jnp.int32(0)])

            new = dict(st)
            new["vis"], new["claims"] = vis, claims
            new["lv"], new["lvp"], new["lvlane"] = lv, lvp, lvlane
            new["lin"], new["lco"], new["jsl"] = lin, lco, jsl
            new["stats"] = jnp.where(
                level_done,
                lax.dynamic_update_slice(st["stats"], row[None],
                                         (li, 0)),
                st["stats"])
            new["opar"] = jnp.where(
                level_done,
                lax.dynamic_update_slice(st["opar"], lvp[None],
                                         (li, 0)),
                st["opar"])
            new["olane"] = jnp.where(
                level_done,
                lax.dynamic_update_slice(st["olane"], lvlane[None],
                                         (li, 0)),
                st["olane"])
            new["ost"] = {
                k: jnp.where(
                    level_done,
                    lax.dynamic_update_slice(
                        v, lv[k][..., None, :],
                        (0,) * (v.ndim - 2) + (li, 0)),
                    v)
                for k, v in st["ost"].items()}
            if n_inv:
                new["oinv"] = jnp.where(
                    level_done,
                    lax.dynamic_update_slice(st["oinv"],
                                             inv_ok[:, None, :],
                                             (0, li, 0)),
                    st["oinv"])
            # frontier swap only at a level boundary (bail keeps the
            # pre-level frontier so the host can replay it exactly);
            # rows past nl2 are stale but masked by nf/fm downstream
            new["fr"] = {k: jnp.where(level_done, lv[k], st["fr"][k])
                         for k in st["fr"]}
            new["fm"] = jnp.where(level_done, lco & validrow, st["fm"])
            new["nf"] = jnp.where(level_done, nl2, st["nf"])
            new["gd"] = jnp.where(
                level_done, st["g"] + jnp.arange(KB, dtype=jnp.int32),
                st["gd"])
            new["pg"] = jnp.where(level_done, st["g"], st["pg"])
            new["g"] = st["g"] + jnp.where(level_done, nl2, 0)
            new["done"] = st["done"] + jnp.where(level_done, nl2, 0)
            new["li"] = li + level_done.astype(jnp.int32)
            new["base"] = jnp.where(level_done, 0, new_base)
            new["nl"] = jnp.where(level_done, 0, nl2)
            new["gl"] = jnp.where(level_done, 0, gl2)
            new["bail"] = bail
            new["viol"] = st["viol"] | (level_done & (n_viol > 0))
            return new

        st = lax.while_loop(cond, body, st)

        meta = jnp.zeros((self._BS_N,), jnp.int32)
        meta = meta.at[0].set(st["li"])
        meta = meta.at[1].set(st["bail"].astype(jnp.int32))
        meta = meta.at[2].set(st["nf"])
        meta = meta.at[3].set(st["viol"].astype(jnp.int32))
        meta = meta.at[4].set(st["done"])
        stats = jnp.concatenate([st["stats"], meta[None]], axis=0)
        return st, dict(stats=stats, par=st["opar"], lane=st["olane"],
                        st=st["ost"], inv=st["oinv"])

    # ------------------------------------------------------------------
    # job-axis batched burst (serve/batch): _burst_core with every
    # per-job buffer riding a leading [J] axis — the multi-tenant
    # serving layer packs many small (spec, config) jobs into ONE
    # device program this way, amortizing compile and dispatch across
    # tenants exactly as the burst amortizes them across levels.
    # ------------------------------------------------------------------

    def _batched_burst_impl(self, jst, lv_left, st_cap):
        """Job-vmapped burst core.  ``jst`` stacks per-job state on a
        leading job axis: vis (W-tuple of u32[J, VCAP] tables), claims
        u32[J, VCAP], fr (narrow batch-last frontier rows [J, ..., KB]),
        fm bool[J, KB], gd int32[J, KB], nf/g/pg int32[J]; ``lv_left``
        and ``st_cap`` are per-job int32[J] depth/state gates (a
        finished job passes lv_left=0 and never re-enters the loop).

        Constant-padding ceilings (round 13): an optional ``jst["rt"]``
        carries per-job runtime data — guard thresholds int32[J, A],
        family lane masks bool[J, A], and the search-bounds vector
        int32[J, NB] — so heterogeneous small configs (differing
        MaxTerm-style bounds, paxos ballot/value/instance counts) ride
        ONE compiled ceiling program: the int8 guard matrix and delta
        matrices stay shared per shape ceiling while each job's
        thresholds/masks/bounds vmap as device data.  Absent, the
        program is the historical baked-constant one, bit-identical.

        Under vmap the burst's while_loops run until EVERY job's cond
        is false, with per-job select masking: a finished job's state
        freezes (its lanes contribute no further table writes or
        appends) while stragglers keep stepping.  Each job's trajectory
        is bit-identical to a solo burst — every op in the body is
        per-lane-independent integer/boolean work, and the select only
        ever replaces a finished job's next state with its frozen one
        (tests/test_serve.py pins batched ≡ sequential on counts, level
        sizes, violations and witness traces).

        Returns (jst', out) with out's stats matrix and per-level
        archives carrying the same leading [J] axis."""
        def one(st, lvl, cap):
            rt = st.get("rt")
            stf, out = self._burst_core(
                st["vis"], st["claims"], st["fr"], st["fm"], st["gd"],
                st["nf"], st["g"], st["pg"], self.FAM_CAPS, lvl, cap,
                rt=rt)
            nst = dict(vis=stf["vis"], claims=stf["claims"],
                       fr=stf["fr"], fm=stf["fm"], gd=stf["gd"],
                       nf=stf["nf"], g=stf["g"], pg=stf["pg"])
            if rt is not None:
                # rt is job-constant: pass it through the carry so the
                # AOT executable's output tree matches its input tree
                # (the serving layer re-feeds jst every device call)
                nst["rt"] = rt
            return nst, out
        return jax.vmap(one)(jst, lv_left, st_cap)

    def burst_batched_fn(self, donate: bool = True, sharding=None):
        """The jitted job-axis burst entry point (lazy: solo checks
        never pay for it).  The serving layer AOT-compiles it per
        (bucket, padded job count) via ``.lower(...).compile()`` so the
        compile lands in one attributable span.

        ``donate=False`` compiles WITHOUT donating the carry.  Carry
        donation bakes input->output buffer aliasing into the XLA
        executable, and on this jax version (0.4.37) an executable
        deserialized in a DIFFERENT process loses the jax-side half of
        that contract: the re-fed carry comes back silently corrupted
        (the harvest stats stay right, so nothing crashes — the wave
        state persisted at the next boundary is garbage and a resumed
        run goes wrong).  The serving layer therefore compiles the
        donation-free variant whenever a persistent executable cache
        is in play, trading one carry's worth of device memory for a
        program that round-trips serialization exactly
        (tools/daemon_smoke.py pins the kill->restart path warm).

        ``sharding`` is either None, a single job-axis
        ``NamedSharding`` (the round-16 1-D job mesh), or a dict
        ``{"carry": <tree>, "gate": <sharding>, "out": <tree>}`` of
        per-leaf sharding pytrees (the round-17 2-D jobs × state
        mesh).

        The single-sharding form applies as a pytree-prefix
        ``in_shardings``/``out_shardings`` over the whole carry: every
        leaf of ``jst`` and ``out`` leads with the [J] job axis, so
        ONE spec splits the wave across devices and GSPMD partitions
        the body with no data collectives (each lane is independent;
        only the vmapped while-loop condition reduces across jobs).

        The dict form carries full per-leaf trees because under a 2-D
        mesh the leaves shard DIFFERENTLY: per-job scalars/cursors
        stay on P("jobs") while the visited-table slots, frontier
        rings, level buffers and archive staging also shard their
        big per-job axis over "state" (serve/batch builds the trees
        from parallel/pjit_mesh's rule-matched partition specs).
        ``"carry"`` must match ``jst``'s structure, ``"gate"`` covers
        the two int32[J] gate args, ``"out"`` the stats/archive tree.
        Either way the body is UNCHANGED — the same program serves
        one device, a 1-D job mesh, or a 2-D pod slice; the dedup
        probe/claim scatter lowers to in-program GSPMD collectives
        along the state axis only."""
        if self._bat_jit is None:
            _register_barrier_batching()
            self._bat_jit = {}
        if isinstance(sharding, dict):
            # spec trees are unhashable pytrees: key the jit-variant
            # cache on (treedef, leaves) — NamedShardings hash fine
            leaves, treedef = jax.tree_util.tree_flatten(sharding)
            key = (bool(donate), treedef, tuple(leaves))
        else:
            key = (bool(donate), sharding)
        fn = self._bat_jit.get(key)
        if fn is None:
            kwargs = {}
            if donate:
                kwargs["donate_argnums"] = 0
            if isinstance(sharding, dict):
                gate = sharding["gate"]
                kwargs["in_shardings"] = (sharding["carry"], gate,
                                          gate)
                kwargs["out_shardings"] = (sharding["carry"],
                                           sharding["out"])
            elif sharding is not None:
                kwargs["in_shardings"] = (sharding, sharding, sharding)
                kwargs["out_shardings"] = sharding
            fn = jax.jit(self._batched_burst_impl, **kwargs)
            self._bat_jit[key] = fn
        return fn

    def _burst_impl(self, carry, fam_caps, levels_left, states_cap):
        """Classic-carry wrapper around _burst_core: slice the ring out
        of the LCAP buffers, run the fused loop, paste the surviving
        frontier back.  Returns (carry', out) — out as in
        _burst_core."""
        KB = self._burst_width()
        front0 = {k: lax.dynamic_slice_in_dim(v, 0, KB, axis=v.ndim - 1)
                  for k, v in carry["front"].items()}
        # classic frontiers are contiguous: row r has id pg_off + r
        gd0 = carry["pg_off"] + jnp.arange(KB, dtype=jnp.int32)
        stf, out = self._burst_core(
            carry["vis"], carry["claims"], front0,
            carry["fmask"][:KB], gd0, carry["n_front"], carry["g_off"],
            carry["pg_off"], fam_caps, levels_left, states_cap,
            fcap=carry["cidx"].shape[0], ocap=carry["oidx"].shape[0])
        fmask = jnp.zeros_like(carry["fmask"]).at[:KB].set(stf["fm"])
        front = {k: lax.dynamic_update_slice_in_dim(
                     v, stf["fr"][k], 0, axis=v.ndim - 1)
                 for k, v in carry["front"].items()}
        new_carry = dict(carry, vis=stf["vis"], claims=stf["claims"],
                         front=front, fmask=fmask, n_front=stf["nf"],
                         g_off=stf["g"], pg_off=stf["pg"])
        return new_carry, out

    # ------------------------------------------------------------------

    def _fresh_carry(self, lcap: int, vcap: int, fcap: Optional[int] = None,
                     ocap: Optional[int] = None):
        fcap = fcap if fcap is not None else self.FCAP
        ocap = ocap if ocap is not None else self.OCAP
        one = self.ir.narrow(self.lay, self.ir.encode(
            self.lay, *self.ir.init_state(self.cfg)))
        # frontier/level state buffers are BATCH-LAST ([..., lcap]) —
        # see the chunk step's layout note
        zeros = {k: jnp.zeros(v.shape + (lcap,), dtype=v.dtype)
                 for k, v in one.items()}
        n_inv = len(self.inv_names)
        return dict(
            # the open-addressing visited table + its transient claims
            vis=tuple(jnp.full((vcap,), U32MAX) for _ in range(self.W)),
            claims=jnp.full((vcap,), U32MAX),
            jslot=jnp.full((lcap,), -1, jnp.int32),  # level insert journal
            linv=jnp.ones((n_inv, lcap), bool),      # per-row invariants
            lcon=jnp.ones((lcap,), bool),            # per-row constraints
            lvl=zeros,
            lpar=jnp.full((lcap,), -1, jnp.int32),
            llane=jnp.full((lcap,), -1, jnp.int32),
            cidx=jnp.zeros((fcap,), jnp.int32),   # FCAP shape anchor
            oidx=jnp.zeros((ocap,), jnp.int32),   # OCAP shape anchor
            n_lvl=jnp.int32(0),
            n_gen=jnp.int32(0),
            famx=jnp.zeros((len(self.expander.families),), jnp.int32),
            ofx=jnp.int32(0),       # max fresh rows in any chunk
            base=jnp.int32(0),      # chunk cursor within the frontier
            g_off=jnp.int32(0),     # global state-id offset (this level)
            pg_off=jnp.int32(0),    # global state-id offset (frontier)
            ovf=jnp.bool_(False),
            fovf=jnp.bool_(False),
            hovf=jnp.bool_(False),  # probe-round budget blown
            oovf=jnp.bool_(False),  # fresh-compaction buffer blown
            front={k: jnp.zeros_like(v) for k, v in zeros.items()},
            fmask=jnp.zeros((lcap,), bool),
            n_front=jnp.int32(0),
        )

    def _grow(self, carry, lcap: int, vcap: int):
        """Re-home a carry into bigger capacity buffers (the visited
        table and the frontier survive; the level buffer is reset —
        callers replay the level).  The table must already have `vcap`
        slots (_rehash_tables handles table growth)."""
        old_lcap = carry["lpar"].shape[0]
        assert carry["vis"][0].shape[0] == vcap, \
            "grow the table via _rehash_tables first"
        new = self._fresh_carry(lcap, vcap, self.FCAP, self.OCAP)
        new["vis"] = carry["vis"]
        new["claims"] = carry["claims"]
        pad = lcap - old_lcap
        new["front"] = {k: jnp.concatenate(
            [carry["front"][k],
             jnp.zeros(v.shape[:-1] + (pad,), v.dtype)], axis=-1)
            for k, v in carry["front"].items()}
        new["fmask"] = jnp.concatenate(
            [carry["fmask"], jnp.zeros((pad,), bool)])
        new["n_front"] = carry["n_front"]
        new["g_off"] = carry["g_off"]
        new["pg_off"] = carry["pg_off"]
        # n_gen stays 0: the caller replays the whole level from the
        # intact frontier, so keeping the partial count would double it
        return new

    # ------------------------------------------------------------------

    def _stamp_mode(self, res: "CheckResult") -> "CheckResult":
        """Record which expansion/dedup program this run executed (the
        MXU-path mode flags in the metrics registry).  Stamped from the
        LIVE engine config — never serialized into checkpoints — so a
        resumed run reports the resuming engine's modes."""
        res.guard_matmul = int(self.guard_matmul)
        res.dedup_kernel = int(self._dedup_pallas)
        # 1 only when the delta program actually compiled (flag ON and
        # the spec declares at least one affine family)
        res.delta_matmul = int(self.expander.delta_active)
        # 1 = orbit-sort canonical fingerprints, 0 = min-over-perms
        # (fingerprint.resolve_sym_canon — the RESOLVED mode, so "auto"
        # runs report what they actually executed)
        res.sym_canon = int(self.fpr.sym_canon == "sort")
        return res

    def _prewarm_perlevel(self):
        """Warm the per-level step/finalize executables with one dummy
        dispatch each BEFORE the driver loop (the BENCH_r08 recompile
        leak: with burst ON the first per-level dispatch otherwise
        happens only when a burst BAILS, so its cold compile landed
        mid-run inside a level_dispatch span — 11.6 s over 9 dispatches
        vs 1.65 s over 30 in per-level mode).  The dummy carry is empty
        (n_front = 0: every lane invalid, nothing inserted) and donated
        away by the calls, so the cost is one transient carry
        allocation + two no-op dispatches; post-bail dispatches then
        reuse the warmed executable (tests/test_obs.py pins the
        compile-span/cache counts).  Capacity growth retraces, as
        ever."""
        dummy = self._fresh_carry(self.LCAP, self.VCAP)
        dummy = self._step_jit(dummy, self.FAM_CAPS)
        dummy, _out = self._fin_jit(dummy)
        del dummy

    def _dedup_roots(self, seed_states):
        """Shared root-admission front half (this engine, ShardedEngine
        and SpillEngine): cfg prefix pins compile to seeds
        (raft.tla:1198-1234; models/golden docstring), seeds encode to
        SoA rows, and first-seen fingerprint dedup picks the root set.
        Returns (roots int32 SoA [n, ...] batch-major, rk u32 [n, W]
        canonical fingerprints, pin_interiors or None)."""
        pin_interiors = None
        if seed_states is None and self.cfg.prefix_pins:
            if self.ir.prefix_pin_seeds is None:
                raise ValueError(
                    f"spec {self.ir.name!r} has no prefix-pin support")
            seed_states, pin_interiors = self.ir.prefix_pin_seeds(
                self.cfg, with_interior=True)
        init_list = (seed_states if seed_states is not None
                     else [self.ir.init_state(self.cfg)])
        init_arrs = self.ir.widen(_cat([
            {k: np.asarray(v)[None] for k, v in s.items()}
            if isinstance(s, dict) else
            {k: v[None] for k, v in self.ir.encode(self.lay, *s).items()}
            for s in init_list]))
        rootsb = {k: jnp.asarray(v) for k, v in init_arrs.items()}
        root_fp = np.asarray(self._rootfp_jit(rootsb)).astype(np.uint32)
        _uniq, first_idx = np.unique(fp_key(root_fp),
                                     return_index=True)
        first_idx.sort()
        return _take(init_arrs, first_idx), root_fp[first_idx], \
            pin_interiors

    # ------------------------------------------------------------------
    # trace-archive plumbing (engine/archive): every engine family
    # stores per-level parent/lane/state arrays either in host RAM (the
    # historical lists) or streamed to memmap'd per-level files under
    # ``archive_dir`` — one dispatch point so check loops, checkpoints
    # and trace reconstruction stay backing-agnostic.
    # ------------------------------------------------------------------

    def _init_store(self):
        self._states, self._parents, self._lanes = [], [], []
        self._arch = None
        if self.store_states and self.archive_dir:
            from .archive import DiskArchive
            self._arch = DiskArchive(self.archive_dir)

    def _archive_level(self, parents, lanes, states_major):
        with self._obs.span("archive_io"):
            if self._arch is not None:
                self._arch.append_level(parents, lanes, states_major)
            else:
                self._parents.append(parents)
                self._lanes.append(lanes)
                self._states.append(states_major)

    def _ckpt_store_args(self):
        """(parents, lanes, states, extra-meta) for ckpt_write: a disk
        archive already persists itself level-by-level, so checkpoints
        record only its level count instead of re-embedding rows."""
        if self._arch is not None:
            return [], [], [], dict(disk_archive=True,
                                    arch_levels=self._arch.n_levels)
        return self._parents, self._lanes, self._states, {}

    def _load_archives(self, path, z, meta, template):
        """Resume-side twin of _ckpt_store_args: reattach the disk
        archive (truncating levels past the checkpoint, so a resumed
        run re-appends them bit-identically) or unpack the embedded
        in-RAM archives."""
        from .archive import ArchiveError, DiskArchive
        if meta.get("disk_archive"):
            if not (self.store_states and self.archive_dir):
                raise CheckpointError(
                    f"{path}: checkpoint archives live in a disk "
                    "archive directory — resume with the same "
                    "archive_dir (CLI: --archive-dir)")
            try:
                self._arch = DiskArchive(self.archive_dir, attach=True)
                self._arch.truncate(meta["arch_levels"])
            except ArchiveError as e:
                raise CheckpointError(str(e)) from e
            self._parents, self._lanes, self._states = [], [], []
            return
        if self.store_states and self.archive_dir:
            raise CheckpointError(
                f"{path}: checkpoint holds in-RAM archives; resume "
                "without archive_dir")
        self._arch = None
        self._parents, self._lanes, self._states = ckpt_archives(
            z, meta, template, self.store_states)

    def _restore_portable_archives(self, img):
        """Shape-portable twin of _load_archives: attach the archives a
        ``resil.portable.PortableImage`` carries (the in-RAM per-level
        lists, or a disk-archive reattach+truncate).  The archive
        format is engine-agnostic — parents/lanes/state rows in global
        id order — so archives port across engine families unchanged."""
        from .archive import ArchiveError, DiskArchive
        self._arch = None
        self._parents, self._lanes, self._states = [], [], []
        if not self.store_states:
            return
        if not img.store_states:
            raise CheckpointError(
                "portable image was written with store_states=False; "
                "resume with store_states=False (CLI: --no-store) — "
                "trace archives cannot be reconstructed")
        if img.disk_archive_levels is not None:
            if not self.archive_dir:
                raise CheckpointError(
                    f"{img.source_path}: image archives live in a "
                    "disk archive directory — resume with the same "
                    "archive_dir (CLI: --archive-dir)")
            try:
                self._arch = DiskArchive(self.archive_dir, attach=True)
                self._arch.truncate(img.disk_archive_levels)
            except ArchiveError as e:
                raise CheckpointError(str(e)) from e
            return
        if self.archive_dir:
            raise CheckpointError(
                f"{img.source_path}: image holds in-RAM archives; "
                "resume without archive_dir")
        self._parents = list(img.parents)
        self._lanes = list(img.lanes)
        self._states = [dict(s) for s in img.states]

    def check(self, max_depth: int = 10 ** 9, max_states: int = 10 ** 9,
              stop_on_violation: bool = False,
              seed_states: Optional[List] = None,
              checkpoint_path: Optional[str] = None,
              checkpoint_every: int = 1,
              resume_from: Optional[str] = None,
              resume_image=None,
              verbose: bool = False, obs=None) -> CheckResult:
        """seed_states entries are (State, Hist) pairs or raw SoA dicts
        (the latter preserve feature lanes exactly — engine-emitted
        seeds; punctuated search, SURVEY §2.9).

        checkpoint_path — write a checkpoint there every
        ``checkpoint_every`` levels; resume_from — continue a prior
        checkpointed run (final counts are identical to an
        uninterrupted run; levels are never half-resumed).

        resume_image — a ``resil.portable.PortableImage`` from ANY
        engine family's checkpoint (round 12 contract): the visited
        key SET rebuilds this engine's table image and the gid-ordered
        frontier rows re-home into the level-buffer layout, so a mesh
        or spill checkpoint resumes here (and, via
        parallel/pjit_mesh's inherited override, onto a pod-spanning
        pjit mesh) landing on the exact counts of an uninterrupted
        run.

        obs — an ``obs.Obs`` bundle (spans / JSONL ledger / heartbeat /
        profiler hooks); every dispatch writes one ledger record and
        one heartbeat rewrite, so a killed run keeps its telemetry."""
        obs = self._obs = obs if obs is not None else NULL_OBS
        t0 = time.perf_counter()
        lay = self.lay
        if resume_from is not None and resume_image is not None:
            raise ValueError(
                "resume_from and resume_image are mutually exclusive")

        def prewarm(obs):
            # per-level executables warm at run start, inside a compile
            # span — never mid-run inside a level_dispatch span (the
            # BENCH_r08 burst-bailout leak).  Gated on span
            # instrumentation: every real perf/TPU run carries the obs
            # surface (ROADMAP carry-over; bench/deep_run/obs_smoke all
            # pass spans), while uninstrumented unit-test checks skip
            # the two extra dummy dispatches — on XLA:CPU the
            # persistent compile cache cannot absorb them, and tier-1
            # runs ~100 check() calls.  Called BEFORE the real carry
            # materializes where possible: the dummy carry is donated
            # away by the warm dispatches, so sequencing it first keeps
            # peak device memory at ONE carry.
            if obs.spans is not None:
                with obs.span("compile"):
                    self._prewarm_perlevel()

        if resume_from is not None:
            carry, res, meta = self._load_checkpoint(resume_from)
            # resume: the checkpointed carry is already device-resident
            # before the capacities are known, so this prewarm runs
            # beside it — a transient second carry allocation (resumes
            # are rare; a fresh start never pays it)
            prewarm(obs)
            n_states = meta["n_states"]
            n_vis = meta["n_vis"]
            depth = meta["depth"]
            n_front = meta["n_front"]
            resumed = True
        elif resume_image is not None:
            (carry, res, depth, n_states, n_vis,
             n_front) = self._resume_portable(resume_image)
            prewarm(obs)
            resumed = True
        else:
            self._init_store()
            roots, rk, pin_interiors = self._dedup_roots(seed_states)
            n_roots = len(rk)

            res = CheckResult(distinct_states=0,
                              generated_states=n_roots, depth=0)
            self._check_pin_interiors(pin_interiors, res)
            while self.LCAP - self.OCAP < 2 * n_roots:
                self.LCAP *= 2
            while n_roots + self.LCAP - self.OCAP > \
                    self._LOAD_MAX * self.VCAP:
                self.VCAP *= 4
            # capacities final; warm BEFORE the real carry allocates
            prewarm(obs)
            carry = self._fresh_carry(self.LCAP, self.VCAP)
            # roots enter through the same admit path as every level:
            # place them in the level buffer + visited table (host-side
            # probe placement — the table is empty, so the sequential
            # simulation is exact) and finalize.  Only the n_roots rows
            # cross the tunnel: the buffers stay device-resident and
            # take the rows via .at[] updates — the previous host-side
            # concatenate-then-upload shipped the WHOLE padded LCAP
            # buffer (~340 B/row x millions of rows at ~50 MB/s, tens
            # of seconds of "warm start" per check() call).
            roots_n = {k: np.moveaxis(v, 0, -1) for k, v in
                       self.ir.narrow(self.lay,
                                      self.ir.widen(roots)).items()}
            carry["lvl"] = {
                k: v.at[..., :n_roots].set(jnp.asarray(roots_n[k]))
                for k, v in carry["lvl"].items()}
            slots = self._host_probe_assign(rk)
            sl = jnp.asarray(slots)
            carry["vis"] = tuple(
                carry["vis"][w].at[sl].set(jnp.asarray(rk[:, w]))
                for w in range(self.W))
            carry["jslot"] = carry["jslot"].at[:n_roots].set(sl)
            carry["n_lvl"] = jnp.int32(n_roots)
            # invariants/constraints for the root cohort (levels get
            # theirs inside the chunk step; roots bypass it)
            inv_r, con_r = self._phase2(
                {k: jnp.asarray(roots[k]) for k in roots})
            carry["linv"] = carry["linv"].at[:, :n_roots].set(inv_r.T)
            carry["lcon"] = carry["lcon"].at[:n_roots].set(con_r)
            n_states = 0
            n_vis = 0
            depth = 0
            resumed = False
        self._stamp_mode(res)
        t_dev = 0.0

        def run_finalize(carry):
            carry, out = self._fin_jit(carry)
            # the ONE per-level device->host sync
            return carry, out, [int(x) for x in np.asarray(out["scal"])]

        def grow_table_if_needed(carry, min_add=0):
            # pessimistic load bound: a level can add at most
            # LCAP - OCAP keys (a burst up to min_add), so checking
            # before the level needs no mid-level sync
            need = n_vis + max(self.LCAP - self.OCAP, min_add)
            if need > self._LOAD_MAX * self.VCAP:
                while need > self._LOAD_MAX * self.VCAP:
                    self.VCAP *= 4
                vis, claims = self._rehash_tables(carry["vis"], self.VCAP)
                carry = dict(carry, vis=vis, claims=claims)
            return carry

        def harvest(carry, out, scal):
            """Per-level host bookkeeping: counts, parents/lanes,
            violations, optional state store."""
            nonlocal n_states, n_vis
            n_lvl, n_viol, faults, n_front = scal[:4]
            n_genl = scal[6]
            res.distinct_states += n_lvl
            res.overflow_faults += faults
            res.generated_states += n_genl
            res.violations_global += n_viol
            if self.store_states:
                # after finalize the level's rows live in front (the
                # buffers swap); they are only overwritten by the
                # next-next level's chunk steps.  Archives are stored
                # batch-major numpy (host layout) — decode/trace/_take
                # row-index them.
                self._archive_level(
                    self._fetch(carry["lpar"][:n_lvl]),
                    self._fetch(carry["llane"][:n_lvl]),
                    {k: np.moveaxis(self._fetch(v[..., :n_lvl]), -1, 0)
                     for k, v in carry["front"].items()})
            if n_viol:
                inv_ok = self._fetch(out["inv_ok"])[:, :n_lvl]
                rows = {k: np.moveaxis(self._fetch(v[..., :n_lvl]),
                                       -1, 0)
                        for k, v in carry["front"].items()}
                for j, nm in enumerate(self.inv_names):
                    for s in np.nonzero(~inv_ok[j])[0]:
                        vsv, vh = self.ir.decode(self.lay,
                                                 _take(rows, s))
                        res.violations.append(
                            Violation(nm, n_states + int(s),
                                      state=vsv, hist=vh))
            n_states += n_lvl
            n_vis += n_lvl
            # global state ids are device int32 (gids/lpar); fail loud
            # rather than wrap if a run ever approaches that scale
            driver.guard_id_space(n_states)
            return n_front

        if not resumed:
            carry, out, scal = run_finalize(carry)
            n_front = harvest(carry, out, scal)
        if stop_on_violation and res.violations:
            res.seconds = time.perf_counter() - t0
            return res

        # burst_ok gates the speculative burst entry: a burst that
        # committed levels and THEN bailed leaves the bailing level's
        # pre-level frontier intact, so re-entering the burst would
        # deterministically replay the same chunks and bail again — one
        # wasted round trip (the exact cost the burst cuts).  Skip the
        # burst for that one level; the per-level path re-arms it.
        burst_ok = True
        while n_front and depth < max_depth and \
                res.distinct_states < max_states:
            # chaos site: a dispatch-time device/tunnel error at the
            # level boundary (resil/chaos).  Raised BEFORE any device
            # work, so the last checkpoint/archives stay consistent
            # and the supervised runner resumes bit-exact.
            chaos_point("dispatch")
            if self.burst and burst_ok and \
                    n_front <= self._burst_width():
                # small-level burst: run up to burst_levels levels in
                # one device call (see _burst_core).  nlev == 0 means
                # the very first level bailed on an overflow — fall
                # through and let the per-level path (with its growth
                # machinery) run that level.
                t1 = time.perf_counter()
                with obs.span("burst_dispatch"):
                    carry = grow_table_if_needed(
                        carry,
                        min_add=self.burst_levels * self._burst_width())
                    lv_left = min(self.burst_levels, max_depth - depth)
                    st_cap = max(1,
                                 min(max_states - res.distinct_states,
                                     2 ** 31 - 1))
                    carry, bout = self._burst_jit(
                        carry, self.FAM_CAPS, jnp.int32(lv_left),
                        jnp.int32(st_cap))
                    stats = np.asarray(bout["stats"])  # the ONE burst
                    # sync
                nlev = int(stats[-1, 0])
                bailed = bool(stats[-1, 1])
                res.burst_dispatches += 1
                res.burst_bailouts += int(bailed)
                if nlev:
                    burst_ok = not bailed
                    d0 = depth
                    n_front = int(stats[-1, 2])
                    viol_any = bool(stats[-1, 3])
                    with obs.span("harvest"):
                        par_h = lane_h = st_h = inv_h = None
                        if self.store_states or viol_any:
                            par_h = self._fetch(bout["par"])
                            lane_h = self._fetch(bout["lane"])
                            st_h = {k: self._fetch(v)
                                    for k, v in bout["st"].items()}
                            inv_h = self._fetch(bout["inv"])

                        def _arch(li, n_lvl):
                            if self.store_states:
                                self._archive_level(
                                    *driver.burst_archive_slice(
                                        par_h, lane_h, st_h, li,
                                        n_lvl))

                        def _viol(li, n_lvl, gid_base):
                            driver.burst_decode_violations(
                                res, self.ir, self.lay,
                                self.inv_names, inv_h, st_h, li,
                                n_lvl, gid_base)

                        def _vis(li, n_lvl):
                            nonlocal n_vis
                            n_vis += n_lvl

                        depth, n_states = driver.harvest_fused_levels(
                            res, nlev, lambda li: stats[li, :5],
                            depth, n_states, archive=_arch,
                            violations=_viol, visited=_vis)
                    t_dev += time.perf_counter() - t1
                    if checkpoint_path is not None and \
                            driver.ckpt_due_after_burst(
                                depth, d0, checkpoint_every):
                        self._save_checkpoint(checkpoint_path, carry,
                                              res, depth, n_states,
                                              n_vis, n_front)
                    obs.dispatch(kind="burst", depth=depth,
                                 frontier=n_front,
                                 metrics=res.metrics.as_dict())
                    if stop_on_violation and res.violations:
                        break
                    if verbose:
                        print(f"burst: {nlev} levels to depth {depth} "
                              f"(total {res.distinct_states}), "
                              f"frontier {n_front}, "
                              f"{time.perf_counter() - t1:.2f}s")
                    continue
            burst_ok = True        # re-arm after a per-level level
            depth += 1
            t1 = time.perf_counter()
            _lvl_span = obs.span("level_dispatch")
            _lvl_span.__enter__()
            carry = grow_table_if_needed(carry)
            while True:
                n_chunks = (n_front + self.chunk - 1) // self.chunk
                for _ in range(n_chunks):
                    carry = self._step_jit(carry, self.FAM_CAPS)
                carry, out, scal = run_finalize(carry)
                ovf, fovf, hovf, oovf = (bool(scal[4]), bool(scal[5]),
                                         bool(scal[8]), bool(scal[9]))
                if not (ovf or fovf or hovf or oovf):
                    break
                # buffer overflow: the finalize rolled the table back
                # and skipped its commit on device (frontier intact),
                # so grow and replay the level exactly.  Growth is 4x —
                # each growth step recompiles the fused kernels, so
                # fewer, larger steps.
                old_caps = (self.LCAP, self.FCAP, self.OCAP)
                if oovf:
                    # a chunk's FRESH rows outran the post-dedup
                    # compaction buffer; the true need is unknown (the
                    # revert fired first), so double toward FCAP
                    self.OCAP = self._round_cap(
                        min(self.FCAP, 2 * self.OCAP))
                if fovf:
                    # grow exactly the overflowing family caps (famx in
                    # the scal tail); grow FCAP only if the TOTAL
                    # enabled count blew the compaction buffer
                    famx = scal[11:11 + len(self.FAM_CAPS)]
                    caps = list(self.FAM_CAPS)
                    fam_over = False
                    for fi, fam in enumerate(self.expander.families):
                        hard = fam.n_lanes * self.chunk
                        while caps[fi] < hard and famx[fi] > caps[fi]:
                            caps[fi] = min(2 * caps[fi], hard)
                            fam_over = True
                    self.FAM_CAPS = tuple(caps)
                    if not fam_over:
                        # the TOTAL enabled count blew the compaction
                        # buffer.  Grow to what the measured per-family
                        # maxima need (Σfamx bounds any chunk's n_e),
                        # not a blind 4x: an oversized FCAP widens the
                        # fingerprint/dedup/append work of EVERY later
                        # chunk (a 4x overshoot measured ~4x slower
                        # steady-state on the membership config)
                        self.FCAP = self._round_cap(min(
                            self.chunk * self.A,
                            max(2 * self.FCAP,
                                (5 * int(sum(famx))) // 4)))
                if ovf or self.LCAP < 4 * self.OCAP:
                    # the append margin is OCAP now, so the LCAP floor
                    # couples to OCAP (an FCAP growth alone no longer
                    # forces a level-buffer rebuild)
                    self.LCAP = self._round_cap(
                        max((4 * self.LCAP) if ovf else self.LCAP,
                            4 * self.OCAP))
                if hovf:
                    # probe walk blew its round budget: table too full
                    self.VCAP *= 4
                    vis, claims = self._rehash_tables(carry["vis"],
                                                      self.VCAP)
                    carry = dict(carry, vis=vis, claims=claims)
                if verbose:
                    print(f"level {depth}: buffer overflow "
                          f"(ovf={ovf} fovf={fovf} hovf={hovf} "
                          f"oovf={oovf}), LCAP={self.LCAP} "
                          f"FCAP={self.FCAP} OCAP={self.OCAP} "
                          f"VCAP={self.VCAP}")
                if (self.LCAP, self.FCAP, self.OCAP) != old_caps:
                    carry = self._grow(carry, self.LCAP, self.VCAP)
                    # the replayed level can now add up to the NEW
                    # LCAP - FCAP keys: re-check the table load bound
                    # before replaying (a full table would spin the
                    # probe walk to its round budget)
                    carry = grow_table_if_needed(carry)
            _lvl_span.__exit__(None, None, None)
            with obs.span("harvest"):
                n_front = harvest(carry, out, scal)
            # per-family enabled maxima ride the scal tail every level;
            # keep the run-wide max as cap-sizing diagnostics
            # (tools/tune_config3.py reads this to pre-size FAM_CAPS)
            self.famx_max = [max(a, b) for a, b in zip(
                getattr(self, "famx_max", [0] * len(self.FAM_CAPS)),
                scal[11:11 + len(self.FAM_CAPS)])]
            # the shared depth gate (engine/driver docstring): an
            # all-pruned pseudo-level advances no depth; a real level
            # appends the post-constraint frontier size (the oracle's
            # metric)
            depth = driver.gate_level_depth(res, depth, scal[0],
                                            scal[6], scal[7])
            t_dev += time.perf_counter() - t1
            if checkpoint_path is not None and \
                    driver.ckpt_due_at_level(depth, checkpoint_every):
                self._save_checkpoint(checkpoint_path, carry, res,
                                      depth, n_states, n_vis, n_front)
            obs.dispatch(kind="level", depth=depth, frontier=n_front,
                         metrics=res.metrics.as_dict())
            if stop_on_violation and res.violations:
                break
            if verbose:
                print(f"depth {depth}: +{scal[0]} states "
                      f"(total {res.distinct_states}), "
                      f"frontier {n_front}, {n_chunks} chunks in "
                      f"{time.perf_counter() - t1:.2f}s")
        res.depth = depth
        res.seconds = time.perf_counter() - t0
        res.phase_seconds["device_levels"] = t_dev
        return res

    def _check_pin_interiors(self, interiors, res: CheckResult):
        """Invariant-check the replayed pinned-prefix interior states.

        TLC counts and invariant-checks every prefix state; seeding at
        the witness end skips them (models/golden docstring).  The
        interiors are already materialized by replay(), so check them
        here — a violation inside the pinned prefix gets reported with
        state_id=-1 (it has no BFS id) — and record the distinct count
        in CheckResult.pin_interior_states as the divergence bound."""
        if not interiors:
            return
        arrs = self.ir.widen(_cat([
            {k: v[None] for k, v in self.ir.encode(self.lay,
                                                   *s).items()}
            for s in interiors]))
        b = {k: jnp.asarray(v) for k, v in arrs.items()}
        keys = fp_key(np.asarray(self._rootfp_jit(b)))
        _uniq, first = np.unique(keys, return_index=True)
        first.sort()
        res.pin_interior_states = len(first)
        if not self.inv_names:
            return
        inv = np.asarray(self._phase2(b)[0])       # [B, n_inv]
        for j, nm in enumerate(self.inv_names):
            for s in np.nonzero(~inv[first, j])[0]:
                sv, h = interiors[int(first[s])]
                res.violations.append(
                    Violation(nm, -1, state=sv, hist=h))
                res.violations_global += 1

    # ------------------------------------------------------------------
    # checkpoint / resume (see the module-level ckpt_* serializer)
    # ------------------------------------------------------------------

    def _save_checkpoint(self, path, carry, res, depth, n_states,
                         n_vis, n_front):
        with self._obs.span("checkpoint"):
            parents, lanes, states, arch_meta = self._ckpt_store_args()
            ckpt_write(path, carry, self.store_states, parents,
                       lanes, states, res, dict(
                           depth=depth, n_states=n_states, n_vis=n_vis,
                           n_front=n_front, LCAP=self.LCAP,
                           VCAP=self.VCAP, FCAP=self.FCAP,
                           OCAP=self.OCAP,
                           fam_caps=list(self.FAM_CAPS), **arch_meta,
                           layout=2, chunk=self.chunk,
                           spec=self.ir.name,
                           sym_canon=self.fpr.sym_canon,
                           ir_fingerprint=self.ir.fingerprint(),
                           cfg=repr(self.cfg)),
                       keep=self.ckpt_keep)

    def _load_checkpoint(self, path):
        z, meta = ckpt_read(path, repr(self.cfg), self.chunk,
                            ("LCAP", "VCAP", "FCAP", "OCAP",
                             "fam_caps"),
                            sharded=False, expected_format=(
                                "layout", 2, "this engine's batch-last/"
                                "narrow-dtype storage layout"),
                            spec_name=self.ir.name,
                            sym_canon=self.fpr.sym_canon)
        self.LCAP, self.VCAP, self.FCAP, self.OCAP = (
            meta["LCAP"], meta["VCAP"], meta["FCAP"], meta["OCAP"])
        self.FAM_CAPS = tuple(int(c) for c in meta["fam_caps"])
        # eval_shape: the template is only read for structure/key paths,
        # never materialized (a real _fresh_carry would transiently
        # double device memory at resume)
        template = jax.eval_shape(
            lambda: self._fresh_carry(self.LCAP, self.VCAP, self.FCAP,
                                      self.OCAP))
        carry = ckpt_carry(path, z, template, jnp.asarray)
        self._load_archives(path, z, meta, template)
        res = ckpt_result(z, meta)
        z.close()             # all arrays extracted; don't leak the fd
        return carry, res, meta

    # ------------------------------------------------------------------
    # shape-portable resume (resil/portable round-12 contract): any
    # engine family's checkpoint re-homes into this engine's layout —
    # the key SET rebuilds the table image (membership is a set
    # property, slot layout never matters), the gid-ordered frontier
    # rows land in the level-buffer positions their contiguous ids
    # dictate, and archives/counters attach unchanged.  The pjit mesh
    # engine inherits this wholesale and re-partitions via
    # _commit_carry.
    # ------------------------------------------------------------------

    def _commit_carry(self, carry):
        """Final placement hook for host-assembled carries: identity
        here; parallel/pjit_mesh re-partitions onto its named
        shardings."""
        return carry

    def _seed_table_from_keys(self, keys_np: np.ndarray):
        """[N, W] u32 visited keys -> a fresh (vis, claims) pair at the
        CURRENT self.VCAP via the bulk lax claim walk (the reseed
        discipline of engine/spill: whole-cohort inserts stay on the
        lax path; dedup needs membership, not the original slot
        layout)."""
        n = int(keys_np.shape[0])
        nq = 1 << max(10, _ceil_log2(max(n, 2)))
        kq = np.full((self.W, nq), np.uint32(0xFFFFFFFF), np.uint32)
        if n:
            kq[:, :n] = keys_np.T
        VCAP, W = self.VCAP, self.W
        fn = getattr(self, "_seed_table_cache", None)
        if fn is None:
            fn = self._seed_table_cache = {}
        impl = fn.get((VCAP, nq))
        if impl is None:
            def build(keys, n):
                table = tuple(jnp.full((VCAP,), U32MAX)
                              for _ in range(W))
                claims = jnp.full((VCAP,), U32MAX)
                live = jnp.arange(nq, dtype=jnp.int32) < n
                ks = tuple(keys[w] for w in range(W))
                ranks = jnp.arange(nq, dtype=jnp.uint32)
                table, claims, _f, _p, hv = self._probe_insert_lax(
                    table, claims, ks, live, ranks)
                return table, claims, hv
            impl = fn[(VCAP, nq)] = jax.jit(build)
        vis, claims, hv = impl(jnp.asarray(kq), jnp.int32(n))
        if bool(np.asarray(hv)):
            raise RuntimeError(
                "portable-resume table seed probe overflow — raise "
                "vcap")
        return vis, claims

    def _resume_portable(self, img):
        """PortableImage -> (carry, res, depth, n_states, n_vis,
        n_front).  Refuses images whose frontier gids are not
        contiguous (spill-family images drop pruned rows; this
        engine's frontier layout is the full last level under fmask)
        with a message naming the engine that can host them."""
        from ..resil.portable import validate_image
        validate_image(img, self.ir.name, repr(self.cfg), self.W)
        n_front = img.n_front
        if n_front:
            gids = np.asarray(img.gids, np.int64)
            pg_off = int(gids[0])
            if not np.array_equal(
                    gids, pg_off + np.arange(n_front, dtype=np.int64)):
                raise CheckpointError(
                    f"{img.source_path}: portable image's frontier "
                    "gids are not contiguous (a spill-family image "
                    "drops constraint-pruned rows); this engine's "
                    "frontier layout needs the full last level — "
                    "resume it with the spill engine "
                    "(check --spill --resume F --resume-portable)")
        else:
            pg_off = img.n_states
        # capacity sizing follows the fresh-start discipline
        # (capacities shape overflow replays, never counts)
        while self.LCAP - self.OCAP < 2 * max(n_front, 1):
            self.LCAP *= 2
        while img.n_vis + self.LCAP - self.OCAP > \
                self._LOAD_MAX * self.VCAP:
            self.VCAP *= 4
        self._restore_portable_archives(img)
        carry = self._fresh_carry(self.LCAP, self.VCAP)
        carry["vis"], carry["claims"] = self._seed_table_from_keys(
            img.keys)
        if n_front:
            rows_T = {k: np.moveaxis(np.asarray(v), 0, -1)
                      for k, v in img.rows.items()}
            carry["front"] = {
                k: v.at[..., :n_front].set(jnp.asarray(rows_T[k]))
                for k, v in carry["front"].items()}
            carry["fmask"] = carry["fmask"].at[:n_front].set(
                jnp.asarray(np.asarray(img.con, bool)))
        carry["n_front"] = jnp.int32(n_front)
        carry["pg_off"] = jnp.int32(pg_off)
        carry["g_off"] = jnp.int32(img.n_states)
        carry = self._commit_carry(carry)
        return (carry, img.fresh_result(), img.depth, img.n_states,
                img.n_vis, n_front)

    # ------------------------------------------------------------------

    def get_state(self, gid: int) -> Tuple:
        return self.ir.decode(self.lay, self.get_state_arrays(gid))

    def get_state_arrays(self, gid: int) -> Dict[str, np.ndarray]:
        assert self.store_states, "state store disabled"
        if self._arch is not None:
            return self._arch.state_row(gid)
        off = 0
        for blk in self._states:
            # any leaf's row count — key sets are spec-defined, so no
            # named key can be assumed here
            n = len(next(iter(blk.values())))
            if gid < off + n:
                return _take(blk, gid - off)
            off += n
        raise IndexError(gid)

    def trace(self, gid: int) -> List[Tuple]:
        if self._arch is not None:
            # memmap'd walk: each hop reads one parent/lane pair and
            # one state row — no level is ever loaded whole
            chain = []
            g = gid
            while g >= 0:
                par, lane = self._arch.parent_lane(g)
                label = self.labels[lane] if lane >= 0 else "Init"
                chain.append((label, self.get_state(g)[0]))
                g = par
            return list(reversed(chain))
        parents = np.concatenate(self._parents)
        lanes = np.concatenate(self._lanes)
        chain = []
        g = gid
        while g >= 0:
            lane = lanes[g]
            label = self.labels[lane] if lane >= 0 else "Init"
            chain.append((label, self.get_state(g)[0]))
            g = parents[g]
        return list(reversed(chain))
