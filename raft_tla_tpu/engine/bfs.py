"""Level-synchronous BFS engine: TLC's worker loop, TPU-shaped.

Replaces the reference's external checker (SURVEY §2.13: TLC's BFS +
fingerprint set + invariant eval) with a two-phase device pipeline per
frontier chunk:

  phase 1 (jit):  expand the chunk over the action grid (engine/expand),
                  evaluate ACTION_CONSTRAINTS against the parent, and
                  fingerprint every candidate (engine/fingerprint)
  host:           first-seen dedup in candidate order (stable — mirrors
                  the oracle BFS ordering) against the visited set
  phase 2 (jit):  on the *new* states only: invariant verdicts +
                  CONSTRAINT masks (prune-expansion semantics, §2.8)

The visited set is a sorted uint64 fingerprint array merged per level —
the host-side analog of TLC's fingerprint set.  Parent pointers
(state-id, lane-id) append per level for trace reconstruction
(SURVEY §7.2 L5).  Multi-device sharding wraps phase 1 (parallel/).
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import CANDIDATE, ModelConfig
from ..models.raft import Hist, State, init_state
from ..ops.codec import C_GLOBLEN, C_OVERFLOW, decode, encode
from ..ops.kernels import RaftKernels
from ..ops.layout import Layout
from ..ops.vpredicates import Predicates
from .expand import Expander
from .fingerprint import Fingerprinter, combine_u64


def _cat(chunks: List[Dict[str, np.ndarray]]) -> Dict[str, np.ndarray]:
    return {k: np.concatenate([c[k] for c in chunks]) for k in chunks[0]}


def fp_key(fp_u32: np.ndarray) -> np.ndarray:
    """[N, n_streams] u32 -> 1-D sortable dedup key covering ALL streams:
    plain u64 for the 2-stream default, a lexicographic structured array
    for fp128 (so the extra streams actually buy collision resistance)."""
    u64 = combine_u64(fp_u32)                     # [N, n_streams//2]
    if u64.shape[1] == 1:
        return u64[:, 0]
    dtype = np.dtype([(f"w{i}", "<u8") for i in range(u64.shape[1])])
    return np.ascontiguousarray(u64).view(dtype)[:, 0]


def sorted_member(sorted_arr: np.ndarray, keys: np.ndarray) -> np.ndarray:
    """Membership of keys in a sorted array via searchsorted (the host
    analog of TLC's fingerprint-set probe)."""
    idx = np.searchsorted(sorted_arr, keys)
    idx = np.minimum(idx, max(len(sorted_arr) - 1, 0))
    if len(sorted_arr) == 0:
        return np.zeros(len(keys), bool)
    return sorted_arr[idx] == keys


def sorted_merge(sorted_arr: np.ndarray, new_keys: np.ndarray) -> np.ndarray:
    """O(N+M) merge of new (unsorted, unique) keys into a sorted array."""
    new_sorted = np.sort(new_keys)
    pos = np.searchsorted(sorted_arr, new_sorted)
    return np.insert(sorted_arr, pos, new_sorted)


def _take(arrs: Dict[str, np.ndarray], idx) -> Dict[str, np.ndarray]:
    return {k: v[idx] for k, v in arrs.items()}


@dataclass
class Violation:
    invariant: str
    state_id: int
    state: Optional[State] = None
    hist: Optional[Hist] = None
    trace: Optional[List[str]] = None


@dataclass
class CheckResult:
    distinct_states: int
    generated_states: int
    depth: int
    violations: List[Violation] = field(default_factory=list)
    level_sizes: List[int] = field(default_factory=list)
    seconds: float = 0.0
    overflow_faults: int = 0

    @property
    def states_per_sec(self):
        return self.distinct_states / max(self.seconds, 1e-9)


class Engine:
    """One compiled checker instance per (ModelConfig, chunk size)."""

    def __init__(self, cfg: ModelConfig, chunk: int = 512,
                 store_states: bool = True):
        self.cfg = cfg
        self.chunk = chunk
        self.store_states = store_states
        self.lay = Layout(cfg)
        self.kern = RaftKernels(self.lay)
        self.expander = Expander(cfg)
        self.fpr = Fingerprinter(cfg)
        self.preds = Predicates(self.lay)
        self.inv_names = list(cfg.invariants)
        self.con_names = list(cfg.constraints)
        self.act_names = list(cfg.action_constraints)
        self.labels = self.expander.lane_labels()
        self.A = self.expander.n_lanes
        self._phase1 = jax.jit(self._phase1_impl)
        self._phase2 = jax.jit(self._phase2_impl)
        # fixed-size on-device row gather: only SELECTED candidates ever
        # leave the device (transferring the full [B, A, ...] candidate
        # block per chunk dominated wall time on the TPU tunnel)
        self._gather = jax.jit(
            lambda cand, idx: {
                k: v.reshape((-1,) + v.shape[2:])[idx]
                for k, v in cand.items()})

    # ------------------------------------------------------------------

    def _act_ok(self, parent_sv, cand_sv):
        """ACTION_CONSTRAINTS (raft.tla:1207-1210): evaluated on the
        (unprimed, primed) pair; violating transitions are not taken."""
        ok = jnp.bool_(True)
        for nm in self.act_names:
            if nm == "CommitWhenConcurrentLeaders_action_constraint":
                deep = parent_sv["ctr"][C_GLOBLEN] >= 20
                no_cand = jnp.all(cand_sv["st"] != CANDIDATE)
                ok = ok & (~deep | no_cand)
            else:
                raise KeyError(f"unknown action constraint {nm}")
        return ok

    def _phase1_impl(self, svb):
        ok, cand = self.expander._expand_impl(svb)          # [B,A], [B,A,…]

        def per_state(parent, cand_row, ok_row):
            def per_lane(c, o):
                fp = self.fpr.fingerprint(c)
                act = self._act_ok(parent, c)
                return fp, act
            return jax.vmap(per_lane)(cand_row, ok_row)

        fp, act = jax.vmap(per_state)(svb, cand, ok)
        return ok & act, cand, fp

    def _phase2_impl(self, svb):
        def one(sv):
            der = self.kern.derived(sv)
            inv = jnp.stack([self.preds.invariant_fn(nm)(sv, der)
                             for nm in self.inv_names]) \
                if self.inv_names else jnp.ones((0,), bool)
            con = jnp.bool_(True)
            for nm in self.con_names:
                con = con & self.preds.constraint_fn(nm)(sv, der)
            return inv, con
        return jax.vmap(one)(svb)

    # ------------------------------------------------------------------

    def _pad(self, arrs: Dict[str, np.ndarray], n: int):
        cur = len(arrs["ct"])
        if cur == n:
            return arrs, np.ones(n, bool)
        pad = n - cur
        out = {k: np.concatenate([v, np.repeat(v[:1], pad, axis=0)])
               for k, v in arrs.items()}
        return out, np.concatenate([np.ones(cur, bool), np.zeros(pad, bool)])

    def check(self, max_depth: int = 10 ** 9, max_states: int = 10 ** 9,
              stop_on_violation: bool = False,
              seed_states: Optional[List] = None,
              verbose: bool = False) -> CheckResult:
        """seed_states entries are (State, Hist) pairs or raw SoA dicts
        (the latter preserve feature lanes exactly — engine-emitted
        seeds; punctuated search, SURVEY §2.9)."""
        t0 = time.time()
        lay = self.lay
        init_list = (seed_states if seed_states is not None
                     else [init_state(self.cfg)])
        init_arrs = _cat([
            {k: np.asarray(v)[None] for k, v in s.items()}
            if isinstance(s, dict) else
            {k: v[None] for k, v in encode(lay, *s).items()}
            for s in init_list])
        # fingerprint + check the roots
        rootsb = {k: jnp.asarray(v) for k, v in init_arrs.items()}
        root_fp = fp_key(np.asarray(jax.vmap(self.fpr.fingerprint)(rootsb)))
        _uniq, first_idx = np.unique(root_fp, return_index=True)
        first_idx.sort()
        roots = _take(init_arrs, first_idx)
        n_roots = len(first_idx)

        res = CheckResult(distinct_states=0, generated_states=n_roots,
                          depth=0)
        visited = np.sort(root_fp[first_idx])
        self._states: List[Dict[str, np.ndarray]] = []
        self._parents = [np.full(n_roots, -1, np.int64)]
        self._lanes = [np.full(n_roots, -1, np.int32)]
        n_states = 0

        def admit(new_arrs):
            """Check invariants/constraints on new distinct states;
            returns (expandable subset, their global ids) — CONSTRAINT
            semantics: violating states are checked but not expanded.
            Runs phase 2 in fixed-size chunks so the jit compiles ONCE
            (variable-size padding would recompile per level)."""
            nonlocal n_states
            m = len(new_arrs["ct"])
            res.distinct_states += m
            inv_parts, con_parts = [], []
            for base in range(0, m, self.chunk):
                piece = _take(new_arrs, slice(base, base + self.chunk))
                padded, _valid = self._pad(piece, self.chunk)
                inv_p, con_p = self._phase2(
                    {k: jnp.asarray(v) for k, v in padded.items()})
                n_live = len(piece["ct"])
                inv_parts.append(np.asarray(inv_p)[:n_live])
                con_parts.append(np.asarray(con_p)[:n_live])
            inv = np.concatenate(inv_parts) if inv_parts else \
                np.ones((0, len(self.inv_names)), bool)
            con = np.concatenate(con_parts) if con_parts else \
                np.ones((0,), bool)
            res.overflow_faults += int(
                (new_arrs["ctr"][:, C_OVERFLOW] > 0).sum())
            for j, nm in enumerate(self.inv_names):
                for s in np.nonzero(~inv[:, j])[0]:
                    vsv, vh = decode(self.lay, _take(new_arrs, s))
                    res.violations.append(
                        Violation(nm, n_states + s, state=vsv, hist=vh))
            if self.store_states:
                self._states.append(new_arrs)
            keep = np.nonzero(con)[0]
            gids = n_states + keep
            n_states += m
            return _take(new_arrs, keep), gids

        frontier, front_ids = admit(roots)
        if stop_on_violation and res.violations:
            res.seconds = time.time() - t0
            res.depth = 0
            return res

        depth = 0
        while len(frontier["ct"]) and depth < max_depth and \
                res.distinct_states < max_states:
            depth += 1
            level_new: List[Dict[str, np.ndarray]] = []
            level_parents: List[np.ndarray] = []
            level_lanes: List[np.ndarray] = []
            level_fps: List[np.ndarray] = []
            level_seen = visited[:0]          # empty, same key dtype
            n_front = len(frontier["ct"])
            for base in range(0, n_front, self.chunk):
                piece = _take(frontier, slice(base, base + self.chunk))
                piece_ids = front_ids[base:base + self.chunk]
                padded, valid_b = self._pad(piece, self.chunk)
                ok, cand, fp = self._phase1(
                    {k: jnp.asarray(v) for k, v in padded.items()})
                okn = np.asarray(ok) & valid_b[:, None]          # [B, A]
                keys = fp_key(
                    np.asarray(fp).reshape(-1, self.fpr.n_streams))
                flat_ok = okn.reshape(-1)
                res.generated_states += int(flat_ok.sum())
                cand_order = np.nonzero(flat_ok)[0]
                # first occurrence in candidate order (mirrors the
                # oracle's first-seen survivor rule, SURVEY §7.4 pt 5)
                _u, first = np.unique(keys[cand_order], return_index=True)
                first.sort()
                sel = cand_order[first]
                fps_sel = keys[sel]
                fresh = ~sorted_member(visited, fps_sel) & \
                    ~sorted_member(level_seen, fps_sel)
                sel = sel[fresh]
                if len(sel) == 0:
                    continue
                pieces = []
                for b2 in range(0, len(sel), self.chunk):
                    piece_sel = sel[b2:b2 + self.chunk]
                    padded_sel = np.zeros(self.chunk, np.int32)
                    padded_sel[:len(piece_sel)] = piece_sel
                    g = self._gather(cand, jnp.asarray(padded_sel))
                    pieces.append({k: np.asarray(v)[:len(piece_sel)]
                                   for k, v in g.items()})
                new_arrs = _cat(pieces)
                level_new.append(new_arrs)
                level_fps.append(fps_sel[fresh])
                level_seen = sorted_merge(level_seen, fps_sel[fresh])
                level_parents.append(piece_ids[sel // self.A])
                level_lanes.append((sel % self.A).astype(np.int32))
            if not level_new:
                res.level_sizes.append(0)
                break
            new_arrs = _cat(level_new)
            new_fps = np.concatenate(level_fps)
            self._parents.append(np.concatenate(level_parents))
            self._lanes.append(np.concatenate(level_lanes))
            frontier, front_ids = admit(new_arrs)
            visited = sorted_merge(visited, new_fps)
            # expandable count, matching the oracle's level_sizes
            # (models/explore.py appends len(nxt) post-constraint)
            res.level_sizes.append(len(frontier["ct"]))
            if stop_on_violation and res.violations:
                break
            if verbose:
                print(f"depth {depth}: +{len(new_fps)} states "
                      f"(total {res.distinct_states}), "
                      f"frontier {len(frontier['ct'])}")
        res.depth = depth
        res.seconds = time.time() - t0
        return res

    # ------------------------------------------------------------------

    def get_state(self, gid: int) -> Tuple[State, Hist]:
        return decode(self.lay, self.get_state_arrays(gid))

    def get_state_arrays(self, gid: int) -> Dict[str, np.ndarray]:
        assert self.store_states, "state store disabled"
        off = 0
        for blk in self._states:
            n = len(blk["ct"])
            if gid < off + n:
                return _take(blk, gid - off)
            off += n
        raise IndexError(gid)

    def trace(self, gid: int) -> List[Tuple[str, State]]:
        parents = np.concatenate(self._parents)
        lanes = np.concatenate(self._lanes)
        chain = []
        g = gid
        while g >= 0:
            lane = lanes[g]
            label = self.labels[lane] if lane >= 0 else "Init"
            chain.append((label, self.get_state(g)[0]))
            g = parents[g]
        return list(reversed(chain))
