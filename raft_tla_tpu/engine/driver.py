"""Shared level-harvest/driver core (ROADMAP item 5).

The per-level host bookkeeping every engine driver runs — decode the
stats rows, accumulate counters into the ``CheckResult`` registry,
depth-gate all-pruned pseudo-levels, guard the int32 global-id space,
and decide checkpoint-crossing — lived in FIVE copies (the four
exhaustive engine drivers plus the batched-serve harvest).  One
telemetry drift (``levels_fused`` pseudo-level counting) needed three
review passes to fix everywhere; the MetricsRegistry killed the
counter-drift class but not the control-flow duplication.  This module
is the single copy: engines supply what genuinely differs per family —
how archive rows are stored, how violation rows decode out of their
array layout, and how per-device visited occupancy is tracked — as
callbacks, and everything else runs HERE.

The contract is bit-exactness: every existing engine differential
(counts, level sizes, gids, archives, traces, checkpoints) pins the
re-homed call sites against the oracle unchanged
(tests/test_driver.py adds the call-site routing reps).

Semantics notes, shared by every caller:

- **depth gate** — a level with ``n_lvl == 0`` AND ``n_gen == 0`` is
  an all-pruned pseudo-level: the frontier held only constraint-pruned
  rows, nothing was even generated, so the oracle (whose frontier
  excludes pruned rows) would not have run it — it advances no depth
  and appends no level size.  An all-duplicates level (``n_gen > 0``)
  DOES count.  ``levels_fused`` increments inside the same gate so
  ``levels_fused ≡ depth advanced`` in every engine and
  ``depth - levels_fused`` is exactly the per-level-driver level
  count.
- **id guard** — global state ids are device int32 (gids/lpar); fail
  loud rather than wrap when a run approaches 2^31 ids.
- **checkpoint crossing** — a fused burst jumps several levels per
  device call, so the burst checkpoint fires when ANY multiple of
  ``checkpoint_every`` was crossed by the jump (an exact-modulo test
  could step over every multiple); the per-level path keeps the plain
  modulo.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

from ..utils import take_arrays as _take


def guard_id_space(n_states: int) -> None:
    """Fail loud before the int32 global-id space wraps."""
    if n_states >= 2 ** 31 - 1:
        raise RuntimeError(
            "state-id space exhausted (2^31 ids): run exceeds "
            "the engine's int32 global-id width")


def ckpt_due_after_burst(depth: int, depth_before: int,
                         checkpoint_every: int) -> bool:
    """True when the burst's multi-level depth jump crossed ANY
    multiple of ``checkpoint_every`` (the exact-modulo test could skip
    every checkpoint with checkpoint_every > 1)."""
    every = max(1, checkpoint_every)
    return depth // every > depth_before // every


def ckpt_due_at_level(depth: int, checkpoint_every: int) -> bool:
    """The per-level drivers' plain modulo test."""
    return depth % max(1, checkpoint_every) == 0


def gate_level_depth(res, depth: int, n_new: int, n_gen: int,
                     level_size: int) -> int:
    """Per-level depth gate (docstring above): returns the corrected
    depth, appending ``level_size`` to ``res.level_sizes`` only for a
    real level.  Callers pre-increment depth at level entry and assign
    the return value back."""
    if n_new == 0 and n_gen == 0:
        return depth - 1
    res.level_sizes.append(level_size)
    return depth


def harvest_fused_levels(
        res, nlev: int,
        stats_of: Callable[[int], Tuple[int, int, int, int, int]],
        depth: int, n_states: int, *,
        archive: Optional[Callable[[int, int], None]] = None,
        violations: Optional[Callable[[int, int, int], None]] = None,
        visited: Optional[Callable[[int, int], None]] = None,
        id_guard: bool = True) -> Tuple[int, int]:
    """THE fused-burst harvest loop (the five-copy dedup).

    ``stats_of(li)`` returns the level's
    ``(n_lvl, n_viol, faults, n_expand, n_gen)`` — mesh engines sum
    their per-device stats matrix inside it.  Per committed level the
    loop accumulates the result counters, calls ``archive(li, n_lvl)``
    (the callback owns its own store_states / empty-level policy),
    calls ``violations(li, n_lvl, gid_base)`` only when the level saw
    violations (``gid_base`` is the level's first global id — the
    PRE-increment n_states), applies the depth gate, advances
    ``n_states``, and finally calls ``visited(li, n_lvl)`` for
    per-engine occupancy/flush bookkeeping.  Returns the advanced
    ``(depth, n_states)``.

    ``id_guard=False`` preserves the batched-serve semantics exactly
    (per-job ids never approach 2^31; the solo engines guard after
    every harvest)."""
    for li in range(nlev):
        n_lvl, n_viol, faults, n_expand, n_gen = (
            int(x) for x in stats_of(li))
        res.distinct_states += n_lvl
        res.generated_states += n_gen
        res.overflow_faults += faults
        res.violations_global += n_viol
        if archive is not None:
            archive(li, n_lvl)
        if n_viol and violations is not None:
            # a None callback means "don't decode violation rows" —
            # violations_global above still counts them
            violations(li, n_lvl, n_states)
        if n_lvl == 0 and n_gen == 0:
            pass        # all-pruned pseudo-level: not a BFS level
        else:
            depth += 1
            res.levels_fused += 1
            res.level_sizes.append(n_expand)
        n_states += n_lvl
        if visited is not None:
            visited(li, n_lvl)
    if id_guard:
        guard_id_space(n_states)
    return depth, n_states


# ---------------------------------------------------------------------------
# shared row helpers for the single-chip burst layout ([..., L_MAX, KB]
# batch-last ring archives — engine/bfs._burst_core's out arrays).  The
# mesh engines keep their own per-device decodes in their callbacks;
# bfs, spill and the batched serve share these.
# ---------------------------------------------------------------------------

def burst_archive_slice(par_h, lane_h, st_h, li: int, n_lvl: int):
    """One burst level's (parents, lanes, states batch-major) archive
    rows, copied out of the ring stack (the stack buffer is reused by
    the next burst)."""
    return (par_h[li, :n_lvl].copy(), lane_h[li, :n_lvl].copy(),
            {k: np.moveaxis(v[..., li, :n_lvl], -1, 0).copy()
             for k, v in st_h.items()})


def burst_decode_violations(res, ir, lay, inv_names, inv_h, st_h,
                            li: int, n_lvl: int, gid_base: int) -> None:
    """Decode one burst level's violating rows out of the ring stack
    into ``res.violations`` (ids = gid_base + row)."""
    from .bfs import Violation
    rows = {k: np.moveaxis(v[..., li, :n_lvl], -1, 0)
            for k, v in st_h.items()}
    for j, nm in enumerate(inv_names):
        for s in np.nonzero(~inv_h[j, li, :n_lvl])[0]:
            vsv, vh = ir.decode(lay, _take(rows, int(s)))
            res.violations.append(
                Violation(nm, gid_base + int(s), state=vsv, hist=vh))
