"""Frontier expansion: the Next-relation as one vmapped/jitted step.

The action grid mirrors the ∃-quantification TLC performs (SURVEY §3.1):
each *family* (RequestVote, Phase2a, …) is vmapped over its parameter
grid (server pairs, values, bag slots) and over the frontier batch
axis, then families concatenate into a [B, A] candidate block with
validity masks.

SPEC-AGNOSTIC since round 10: the family registry, the guard-algebra
declarations behind the int8 guard matmul, and the per-family density
caps all come from the active ``SpecIR`` (``spec/`` — raft and paxos
today).  Family order follows each spec's oracle successor enumeration
so candidate streams are comparable; a family without a declared guard
algebra fails loudly at construction, naming the spec.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..spec import spec_of


@dataclass
class Family:
    name: str
    fn: Callable            # (sv, der, *params) -> (ok, sv2)
    params: Tuple[np.ndarray, ...]   # one array per param, equal length
    labeler: Callable        # (*param_values) -> str
    # guard-algebra declaration for the MXU guard-matrix path:
    # (feature-offset table, layout, *lane params) ->
    # ([(feature_index, weight)], threshold) over the spec kernels'
    # guard_features vector.  Part of the SpecIR contract: a family
    # without one fails at Expander construction (the int8 guard
    # matmul cannot silently fall back without forking the two paths).
    guard: Optional[Callable] = None
    # delta-algebra declaration for the MXU successor path (the
    # BLEST-style scatter-as-matmul; round 11): (offset table, layout,
    # *lane params) -> [(slot, source, weight), ...] triples over the
    # packed int32 state view, meaning
    #
    #     x'[slot] = x[slot] + sum over triples of weight * psi[source]
    #
    # where x is the flat int32 view of the state (u32 lanes bitcast)
    # and psi = concat([1], x, kernels.delta_features(sv, der)).  A
    # "set" is (slot, const, v) + (slot, old-slot source, -1); u32 bit
    # sends ride a bit-clear/one-hot feature so integer add == set-OR.
    # UNLIKE guard, delta is OPTIONAL: a family without one (genuinely
    # nonlinear actions — bag inserts, log reshuffles) transparently
    # keeps the per-family kernel path; declared families are compiled
    # into ONE batched delta matmul per family group.
    delta: Optional[Callable] = None

    @property
    def n_lanes(self):
        return len(self.params[0]) if self.params else 1


def d_set(off, slot: int, value: int):
    """Delta-declaration helper: the two triples of ``x'[slot] = value``
    for a lane-constant value — the constant in, the old slot value
    out.  (State- or feature-sourced sets are spelled directly as
    triples; see the spec IRs.)"""
    return [(slot, off["_const"], int(value)),
            (slot, off["_src_x"] + slot, -1)]


# Per-family enabled-lane density caps are part of the SpecIR contract
# (cap_f = chunk * min(n_lanes_f, density); overflow trips fovf, the
# engine grows the cap and replays the level — throughput tuning, not
# correctness bounds).  Each spec owns its measured table
# (spec/raft_ir.FAMILY_DENSITY, spec/paxos/ir.FAMILY_DENSITY); the
# historical module-level name stays as the raft alias for existing
# imports.
from ..spec.raft_ir import FAMILY_DENSITY as _FAMILY_DENSITY  # noqa: E402


def validate_fam_density(density, ir=None) -> Dict[str, int]:
    """Bounds-validate a per-family density override mapping (the
    engines' ``fam_density`` kwarg / CLI ``--fam-cap-density``): known
    family name OF THE ACTIVE SPEC, integer k >= 1.  Raises ValueError
    with a message fit for the CLI — never a jit traceback.  ``ir``
    defaults to the raft frontend (the historical global table)."""
    if ir is None:
        from ..spec import get_spec
        ir = get_spec("raft")
    known = dict(ir.family_density)
    out = {}
    for name, k in dict(density or {}).items():
        if name not in known:
            raise ValueError(
                f"unknown action family {name!r} in fam-cap-density "
                f"for spec {ir.name!r}; known families: "
                f"{', '.join(sorted(known))}")
        if isinstance(k, bool) or not isinstance(k, int):
            raise ValueError(
                f"fam-cap-density {name}: k must be an integer "
                f"(got {k!r})")
        if k < 1:
            raise ValueError(
                f"fam-cap-density {name}: k must be >= 1 (got {k}) — "
                "a zero cap would drop every enabled lane of the "
                "family")
        out[name] = k
    return out


def parse_fam_density(text: str, ir=None) -> Dict[str, int]:
    """Parse the CLI form ``fam=k,fam2=k2`` (``--fam-cap-density``)
    into a validated override dict against the active spec's family
    table (``ir``; raft when omitted)."""
    out = {}
    for item in (text or "").split(","):
        item = item.strip()
        if not item:
            continue
        name, sep, val = item.partition("=")
        if not sep:
            raise ValueError(
                f"fam-cap-density entry {item!r} is not of the form "
                "fam=k (e.g. Receive=8,Timeout=2)")
        try:
            k = int(val.strip())
        except ValueError:
            raise ValueError(
                f"fam-cap-density {name.strip()}: k must be an "
                f"integer, got {val.strip()!r}") from None
        out[name.strip()] = k
    return validate_fam_density(out, ir)


class Expander:
    """Compiled expansion over a frontier batch.

    guard_matmul — the MXU-native expansion path (default ON, bit-exact
    by construction): the [states × lanes] guard grid is computed as
    one int8 matmul of per-state guard features against a packed
    signed-weight matrix (``guards_T_matmul``) instead of the vmapped
    per-lane kernel sweep, and the compacted (row, lane) selections in
    ``materialize``/``step_lanes`` become one-hot einsum blocks (the
    BLEST/tensor-core-BFS formulation: frontier expansion as low-
    precision matrix products).  OFF restores the exact historical
    gather/vmap program — tests/test_guard_matmul.py pins ON ≡ OFF.

    delta_matmul — the successor-GENERATION half of the same
    reformulation (round 11): every family whose ``Family.delta``
    algebra is declared compiles into shared one-hot delta matrices,
    and ``materialize``/``step_lanes`` apply the whole affine family
    group as ONE batched scatter-as-matmul (int32 einsum blocks:
    S' = S + P^T((L Q) ⊙ (Ψ V)) over the packed int32 state view)
    instead of one vmapped kernel per family.  Declaration-less
    families transparently keep the kernel path; OFF restores it for
    every family — tests/test_delta_matmul.py pins ON ≡ OFF."""

    def __init__(self, cfg, guard_matmul: bool = True,
                 delta_matmul: bool = True,
                 delta_chunk_skip: Optional[bool] = None):
        self.cfg = cfg
        self.ir = spec_of(cfg)
        self.lay = self.ir.make_layout(cfg)
        self.kern = self.ir.make_kernels(self.lay)
        self.families = self.ir.build_families(self.lay)
        self.keys = self.ir.all_keys
        self.n_lanes = sum(f.n_lanes for f in self.families)
        self.guard_matmul = bool(guard_matmul)
        self.delta_matmul = bool(delta_matmul)
        # P-contraction lowering: the MXU matmul on TPU, the
        # bit-identical static scatter-add off-TPU (see _delta_of)
        self._delta_mxu = jax.default_backend() == "tpu"
        # chunk skip (the ROADMAP item-3 leftover): apply the delta
        # group as per-family blocks, each under a lax.cond on the
        # chunk's enabled count, so a chunk that enables NONE of a
        # family's lanes skips that family's whole cap-wide block —
        # today's group width is the sum of declared fam caps even
        # then.  Bit-exact: an enabled family's block runs the same
        # gathers and int32 adds as the fused group, and a skipped
        # family's columns hold only compaction garbage no consumer
        # ever reads (the same garbage-unobservability the ON≡OFF
        # differentials already rest on).  Default follows the MXU
        # lowering — the cond buys back dense matmul width on TPU,
        # while off-TPU the always-apply single block keeps the
        # cheaper-to-compile graph; tests force it ON under CPU to pin
        # exactness.
        self.delta_chunk_skip = (self._delta_mxu
                                 if delta_chunk_skip is None
                                 else bool(delta_chunk_skip))
        self._gW, self._gT = self._build_guard_matrix()
        self._dgroup = self._build_delta_group() if self.delta_matmul \
            else None
        self._expand = jax.jit(self._expand_impl)

    @property
    def delta_active(self) -> bool:
        """True when the delta-matmul successor path is compiled (the
        flag is ON and at least one family declares its delta algebra)
        — what the engines stamp into the ``delta_matmul`` counter."""
        return self._dgroup is not None

    @property
    def delta_family_names(self):
        if self._dgroup is None:
            return ()
        return tuple(self.families[fi].name
                     for fi in self._dgroup["fam_idx"])

    # ---- packed guard matrix (the guard grid as int8 matmul) -------------

    def _build_guard_matrix(self):
        """(W int8 [n_features, A], T int32 [A]): lane a's enabling
        guard is exactly ``φ(s) · W[:, a] == T[a]`` over the feature
        vector of the spec kernels' ``guard_features``.

        Guards that are pure conjunctions of features select them with
        +1 weights and threshold = the conjunct count; a negated
        conjunct (raft AddNewServer's ``j ∉ config``) enters with
        weight -1 and no threshold contribution — integer arithmetic,
        so the compare is exact, never approximate.  The rows come
        from each family's ``guard`` declaration (the SpecIR contract);
        a family without one fails loudly here: new actions must
        declare their guard algebra, silently falling back would fork
        the two paths."""
        OFF = self.kern.guard_feature_offsets()
        Wm = np.zeros((OFF["total"], self.n_lanes), np.int8)
        T = np.zeros((self.n_lanes,), np.int32)
        lane = 0
        for fam in self.families:
            if fam.guard is None:
                raise KeyError(
                    f"no guard algebra declared for action family "
                    f"{fam.name!r} of spec {self.ir.name!r} — set the "
                    f"Family.guard declaration in the spec's "
                    f"build_families (spec/{self.ir.name}*)")
            for vals in zip(*fam.params) if fam.params else [()]:
                vals = tuple(int(v) for v in vals)
                pairs, thresh = fam.guard(OFF, self.lay, *vals)
                for idx, w in pairs:
                    Wm[idx, lane] = w
                T[lane] = thresh
                lane += 1
        assert lane == self.n_lanes
        return Wm, T

    # ---- packed delta matrices (successor generation as matmul) ----------
    #
    # The affine family group compiles into three shared matrices over
    # the flat int32 state view x (all state arrays in self.keys order,
    # u32 lanes bitcast, row-major) and the extended source vector
    # psi = concat([1], x, kernels.delta_features(sv, der)):
    #
    #   Q [A_g, T] int8  — triple-ownership: Q[a, t] = 1 iff triple t
    #                      belongs to group lane a (kept as the
    #                      documented matrix; _delta_of applies it as
    #                      the equivalent static gather t_lane)
    #   t_srcu/t_w [T]   — per-triple source row (into the pruned
    #                      `used` psi subset) and int32 weight (u32
    #                      bit weights wrap through two's complement,
    #                      exact under the bit-clear sourcing
    #                      contract) — the single-nonzero V matrix in
    #                      gather form
    #   P [T,   D] int8  — slot placement: P[t, slot_t] = 1
    #
    # so a compacted (row, lane) block with row one-hot R and lane
    # one-hot L applies ALL its lanes' deltas as int32 einsum blocks:
    #
    #   x'_rows = R x + P^T ((L Q) ⊙ (w · psi[src]))
    #
    # — one batched scatter-as-matmul for the whole family group
    # instead of one vmapped kernel per family (ROADMAP item 3, the
    # BLEST formulation; arXiv:2512.21967 / 2606.05081).

    def _build_delta_group(self):
        fams = [(fi, fam) for fi, fam in enumerate(self.families)
                if fam.delta is not None]
        if not fams:
            return None
        # flat state-view layout from the spec's canonical (widened)
        # encoding of the init state — shapes/dtypes only
        proto = {k: np.asarray(v) for k, v in self.ir.widen(
            self.ir.encode(self.lay,
                           *self.ir.init_state(self.cfg))).items()}
        slots, shapes, dtypes = {}, {}, {}
        D = 0
        for k in self.keys:
            a = proto[k]
            slots[k], shapes[k], dtypes[k] = D, a.shape, a.dtype
            D += int(a.size)
        foff = self.kern.delta_feature_offsets()
        nF = int(foff["total"])
        E = 1 + D + nF
        OFF = dict(slots)
        OFF["_const"] = 0            # source index of the literal 1
        OFF["_src_x"] = 1            # + flat slot -> old-value source
        OFF["_src_f"] = 1 + D        # + feature index -> feature source
        OFF["_feat"] = dict(foff)    # the spec's feature offset table
        t_lane, t_slot, t_src, t_w = [], [], [], []
        fam_idx, lane_base = [], {}
        fam_trng = {}                # fi -> the family's triple range
        lane_to_aff = np.full((self.n_lanes,), -1, np.int32)
        A_g = 0
        goff = 0                     # global lane offset
        for fi, fam in enumerate(self.families):
            nf = fam.n_lanes
            if fam.delta is not None:
                fam_idx.append(fi)
                lane_base[fi] = A_g
                t_lo = len(t_w)
                lane_to_aff[goff:goff + nf] = \
                    A_g + np.arange(nf, dtype=np.int32)
                for li, vals in enumerate(
                        zip(*fam.params) if fam.params else [()]):
                    vals = tuple(int(v) for v in vals)
                    for slot, src, w in fam.delta(OFF, self.lay, *vals):
                        if not 0 <= slot < D:
                            raise KeyError(
                                f"delta declaration of family "
                                f"{fam.name!r} (spec {self.ir.name!r}) "
                                f"writes slot {slot} outside the "
                                f"[0, {D}) state view")
                        if not 0 <= src < E:
                            raise KeyError(
                                f"delta declaration of family "
                                f"{fam.name!r} (spec {self.ir.name!r}) "
                                f"reads source {src} outside the "
                                f"[0, {E}) psi vector")
                        if not -(1 << 31) <= int(w) < (1 << 32):
                            # the deliberate wrap below covers u32 bit
                            # weights; anything wider would silently
                            # truncate — fail at build time instead
                            raise KeyError(
                                f"delta declaration of family "
                                f"{fam.name!r} (spec {self.ir.name!r}) "
                                f"uses weight {w} outside the 32-bit "
                                f"range")
                        t_lane.append(A_g + li)
                        t_slot.append(slot)
                        t_src.append(src)
                        t_w.append(int(w))
                fam_trng[fi] = (t_lo, len(t_w))
                A_g += nf
            goff += nf
        T = len(t_w)
        Q = np.zeros((A_g, T), np.int8)
        Q[np.asarray(t_lane), np.arange(T)] = 1
        # prune the source axis to the USED psi rows only: V holds one
        # nonzero per column, so restricting to the distinct sources
        # (typically tens, vs E = 1 + D + n_features in the hundreds)
        # shrinks both the traced graph and the matmul FLOPs several-
        # fold with zero semantic change — `used` gathers the rows out
        # of the full psi vector with static indices
        used = np.unique(np.asarray(t_src, np.int64))
        src_of = {int(s): u for u, s in enumerate(used)}
        # u32-bit weights (1 << 31) wrap to INT_MIN: two's-complement
        # add still sets exactly that bit when the source proves it
        # clear, so the wrap is the intended exact arithmetic
        t_wi = (np.asarray(t_w, np.int64) &
                0xFFFFFFFF).astype(np.uint32).view(np.int32)
        P = np.zeros((T, D), np.int8)
        P[np.arange(T), np.asarray(t_slot)] = 1
        return dict(fam_idx=fam_idx, lane_base=lane_base, n_lanes=A_g,
                    n_triples=T, Q=Q, P=P, slots=slots,
                    shapes=shapes, dtypes=dtypes, D=D,
                    used=used.astype(np.int32), n_feats=nF,
                    t_lane=np.asarray(t_lane, np.int32),
                    t_srcu=np.asarray([src_of[s] for s in t_src],
                                      np.int32),
                    t_slot=np.asarray(t_slot, np.int32),
                    t_w=t_wi, lane_to_aff=lane_to_aff,
                    fam_trng=fam_trng)

    def _flatten_T(self, svT) -> jnp.ndarray:
        """Batch-last state dict [..., B] -> flat int32 view [D, B]
        (u32 lanes bitcast; key order = self.keys, row-major)."""
        parts = []
        for k in self.keys:
            v = svT[k]
            if v.dtype == jnp.uint32:
                v = jax.lax.bitcast_convert_type(v, jnp.int32)
            parts.append(v.reshape((-1,) + v.shape[-1:]))
        return jnp.concatenate(parts, axis=0)

    def _unflatten_T(self, flat):
        """[D, B] flat view -> the state dict, original shapes/dtypes."""
        dg = self._dgroup
        out, pos = {}, 0
        for k in self.keys:
            shape = dg["shapes"][k]
            n = int(np.prod(shape, dtype=np.int64)) if shape else 1
            v = flat[pos:pos + n].reshape(tuple(shape) + flat.shape[-1:])
            if dg["dtypes"][k] == np.uint32:
                v = jax.lax.bitcast_convert_type(v, jnp.uint32)
            out[k] = v
            pos += n
        return out

    def _delta_of(self, psi_c, selL):
        """The group delta [D, cap] for per-row sources psi_c [U, cap]
        and group-lane one-hots selL [cap, A_g]: per-triple terms
        ``own ⊙ (w · psi[src])`` contract against the slot-placement
        matrix P — the scatter-as-matmul (an all-zero selL row applies
        no delta, so the row passes through unchanged).

        The per-triple source/ownership selections are single-nonzero
        matrices, so they apply as STATIC-index gathers (free to
        compile, and on TPU they vectorize as row broadcasts).  P's
        contraction is the one genuine summation: on TPU it is the
        int32 matmul that rides the MXU; off-TPU it lowers to the
        bit-identical static segment scatter-add (int32 addition is
        commutative/associative even under wrap, so the two lowerings
        produce equal buffers) — ANY dot embedded in the fused engine
        step costs ~1.3s of XLA:CPU compile per traced program, which
        tier-1 pays per engine instance (same fallback posture as the
        Pallas dedup kernel's interpret mode)."""
        dg = self._dgroup
        tv = psi_c[jnp.asarray(dg["t_srcu"])] * \
            jnp.asarray(dg["t_w"])[:, None]               # [T, cap]
        own = jnp.transpose(selL)[jnp.asarray(dg["t_lane"])]
        x = own * tv
        if self._delta_mxu:
            return jnp.einsum("td,tc->dc", jnp.asarray(dg["P"]), x,
                              preferred_element_type=jnp.int32)
        slots = jnp.asarray(dg["t_slot"])
        return jnp.zeros((dg["D"], x.shape[-1]),
                         jnp.int32).at[slots].add(x)

    def _delta_of_fam(self, psi_c, selL, fi: int):
        """_delta_of restricted to ONE family's triple range — the
        chunk-skip path's per-family block (delta_chunk_skip; selL is
        the family-LOCAL lane one-hot [cap, nf]).  Same sources, same
        weights, same int32 adds as the fused group, so an enabled
        family's columns are bit-identical to the single-block path."""
        dg = self._dgroup
        lo, hi = dg["fam_trng"][fi]
        tv = psi_c[jnp.asarray(dg["t_srcu"][lo:hi])] * \
            jnp.asarray(dg["t_w"][lo:hi])[:, None]        # [Tf, cap]
        own = jnp.transpose(selL)[
            jnp.asarray(dg["t_lane"][lo:hi]
                        - dg["lane_base"][fi])]
        x = own * tv
        if self._delta_mxu:
            return jnp.einsum("td,tc->dc",
                              jnp.asarray(dg["P"][lo:hi]), x,
                              preferred_element_type=jnp.int32)
        slots = jnp.asarray(dg["t_slot"][lo:hi])
        return jnp.zeros((dg["D"], x.shape[-1]),
                         jnp.int32).at[slots].add(x)

    def _psi_T(self, svT, derT, xflat):
        """The USED rows of the extended source vector
        psi = [1; x; features], in `used` (ascending-source) order —
        [U, B].  Regions are gathered with static indices; the feature
        pass is skipped entirely when no declaration sources it."""
        dg = self._dgroup
        used, D = dg["used"], dg["D"]
        B = xflat.shape[-1]
        u_x = used[(used >= 1) & (used < 1 + D)] - 1
        u_f = used[used >= 1 + D] - (1 + D)
        parts = []
        if (used < 1).any():
            parts.append(jnp.ones((1, B), jnp.int32))
        if len(u_x):
            parts.append(xflat[jnp.asarray(u_x)])
        if len(u_f):
            feats = jax.vmap(self.kern.delta_features,
                             in_axes=-1, out_axes=-1)(svT, derT)
            parts.append(feats.astype(jnp.int32)[jnp.asarray(u_f)])
        return jnp.concatenate(parts, axis=0)

    def lane_labels(self) -> List[str]:
        out = []
        for f in self.families:
            cols = [p for p in f.params]
            for vals in zip(*cols):
                out.append(f.labeler(*[int(v) for v in vals]))
        return out

    def _expand_impl(self, svb: Dict[str, jnp.ndarray]):
        """[B, ...] frontier -> (ok [B, A], cand dict of [B, A, ...])."""
        kern = self.kern

        def one_state(sv):
            der = kern.derived(sv)
            oks, cands = [], []
            for fam in self.families:
                lane = jax.vmap(fam.fn,
                                in_axes=(None, None) + (0,) * len(fam.params))
                ok, sv2 = lane(sv, der,
                               *[jnp.asarray(p) for p in fam.params])
                oks.append(ok)
                cands.append(sv2)
            ok = jnp.concatenate([o.reshape(-1) for o in oks])
            cand = {k: jnp.concatenate([c[k] for c in cands], axis=0)
                    for k in self.keys}
            return ok, cand

        return jax.vmap(one_state)(svb)

    def expand(self, svb):
        return self._expand(svb)

    # ---- guard-first expansion (the engine hot path) ---------------------
    #
    # The full [B, A] candidate materialization of _expand_impl writes
    # ~A× more successor state than survives compaction (typically ~4-8
    # of A≈90 lanes are enabled per parent).  The engines instead run a
    # cheap guard pass over the whole lane grid (XLA dead-code-eliminates
    # the successor arithmetic since only `ok` is consumed), then
    # materialize successors ONLY for enabled lanes: per family, enabled
    # (parent, lane) pairs compact into a statically-capped buffer, the
    # family kernel runs on those rows, and an index map reassembles the
    # global FCAP candidate buffer in the oracle's enumeration order.

    def default_fam_caps(self, chunk: int,
                         density=None) -> Tuple[int, ...]:
        """Per-family materialization caps: chunk × min(lanes, density).
        ``density`` overrides the spec's family_density table per
        family (the engines' ``fam_density`` kwarg /
        ``--fam-cap-density`` — validated by validate_fam_density, so
        cap-overflow replays are tunable without editing any spec
        module)."""
        d = dict(self.ir.family_density)
        d.update(validate_fam_density(density, self.ir))
        return tuple(
            chunk * min(f.n_lanes, d.get(f.name, 2))
            for f in self.families)

    def derived_batch_T(self, svT):
        """Batch-LAST derived quantities (the engines' batch-minor hot
        path — see materialize's layout note)."""
        return jax.vmap(self.kern.derived, in_axes=-1, out_axes=-1)(svT)

    def _guard_one(self, sv, der):
        oks = []
        for fam in self.families:
            lane = jax.vmap(fam.fn,
                            in_axes=(None, None) + (0,) * len(fam.params))
            ok, _sv2 = lane(sv, der,
                            *[jnp.asarray(p) for p in fam.params])
            oks.append(ok.reshape(-1))
        return jnp.concatenate(oks)

    # ---- runtime thresholds (the serving layer's constant-padding
    # bucket ceilings, round 13).  The int8 guard matrix W is shared
    # per SHAPE CEILING; what varies per job is runtime data:
    #
    #   rt["thr"]  int32 [A] — the per-lane threshold the matmul
    #              accumulator compares against (today every job's
    #              vector equals the ceiling's baked _gT — thresholds
    #              are conjunct counts — but the compare consumes it as
    #              DEVICE DATA, so a [J]-leading axis vmaps it per job
    #              with zero retrace);
    #   rt["mask"] bool [A] — the job's family lane mask: a padded
    #              ceiling enumerates MORE lanes than a small job's
    #              grid (paxos ballots/values/instances); masked lanes
    #              read disabled before compaction, so the surviving
    #              candidate stream is exactly the job's own
    #              enumeration order.
    #
    # rt=None keeps the historical baked-constant trace bit-identical.

    def runtime_thresholds(self):
        """The ceiling's (thresholds, all-enabled mask) pair as host
        arrays — the template a spec's ``serve_runtime`` hook starts
        from when building a job's rt data."""
        return (np.asarray(self._gT, np.int32).copy(),
                np.ones((self.n_lanes,), bool))

    def guards_T(self, svT, derT, rt=None) -> jnp.ndarray:
        """Batch-LAST frontier [..., B] -> ok [B, A]: every lane's
        enabling guard.  Dispatches to the MXU guard-matrix path
        (``guards_T_matmul``, default) or the historical vmapped
        per-lane sweep with the successor construction
        dead-code-eliminated (``guard_matmul=False``).  ``rt`` is the
        per-job runtime-thresholds dict above (None = baked
        constants)."""
        if self.guard_matmul:
            return self.guards_T_matmul(svT, derT, rt)
        ok = jax.vmap(self._guard_one, in_axes=-1, out_axes=-1)(svT, derT)
        ok = jnp.moveaxis(ok, -1, 0)
        if rt is not None:
            # the sweep computes guards directly (no threshold
            # compare), so only the lane mask applies here
            ok = ok & rt["mask"][None, :]
        return ok

    def guards_T_matmul(self, svT, derT, rt=None) -> jnp.ndarray:
        """The guard grid as ONE int8 matmul: φ [F, B] features (one
        elementwise extraction pass per state — the per-slot receive
        guards run once per SLOT, not once per lane) contracted against
        the packed weight matrix on the MXU with int32 accumulation,
        then the exact per-lane threshold compare.  Bit-identical to
        the lane sweep by construction (integer arithmetic, 0/±1
        weights).  With ``rt``, the thresholds are device data and the
        job's lane mask ANDs in after the compare (see the
        runtime-thresholds note above)."""
        with jax.named_scope("guard_matmul"):
            phi = jax.vmap(self.kern.guard_features,
                           in_axes=-1, out_axes=-1)(svT, derT)  # [F, B]
            acc = jax.lax.dot_general(
                phi, jnp.asarray(self._gW),
                dimension_numbers=(((0,), (0,)), ((), ())),
                preferred_element_type=jnp.int32)               # [B, A]
            if rt is None:
                return acc == jnp.asarray(self._gT)[None, :]
            return (acc == rt["thr"][None, :]) & rt["mask"][None, :]

    # ---- one-hot einsum selection (the successor-generation half of
    # the MXU path): a compacted (row, lane) index block becomes an
    # int one-hot matrix contracted against the batch — a single-1-per-
    # row matmul is EXACTLY the gather (one nonzero product per output
    # element, int32 accumulation), but it rides the MXU instead of the
    # scalar gather units.  uint32 payloads bitcast through int32.

    def _sel_rows(self, arrs, b_idx, B: int):
        sel = (b_idx[:, None] ==
               jnp.arange(B, dtype=jnp.int32)[None, :]) \
            .astype(jnp.int32)                            # [cap, B]
        out = {}
        for k, v in arrs.items():
            isu = v.dtype == jnp.uint32
            vi = jax.lax.bitcast_convert_type(v, jnp.int32) if isu else v
            r = jnp.einsum("...b,cb->...c", vi, sel,
                           preferred_element_type=jnp.int32)
            out[k] = jax.lax.bitcast_convert_type(r, jnp.uint32) \
                if isu else r
        return out

    def _sel_params(self, params, l_idx, nf: int):
        sel = (l_idx[:, None] ==
               jnp.arange(nf, dtype=jnp.int32)[None, :]) \
            .astype(jnp.int32)                            # [cap, nf]
        return [jnp.einsum("cn,n->c", sel, jnp.asarray(p, jnp.int32),
                           preferred_element_type=jnp.int32)
                for p in params]

    def materialize(self, svT, derT, okf, epos, fcap: int,
                    fam_caps, delta_fp=None) \
            -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
        """Build the compacted candidate buffer [..., fcap] from the
        guard mask.  svT/derT are BATCH-LAST ([..., B]); okf is the
        flat [B*A] enabled mask in b-major lane order, epos the global
        compaction position per flat lane (fcap = dropped).  Returns
        (cand rows batch-last in enumeration order, per-family enabled
        counts — the host grows any family whose count exceeded its cap
        and replays the level).

        delta_fp — optional (Fingerprinter, parent_tables) pair: each
        family also computes its candidates' per-permutation hashes
        incrementally from the parent tables (fingerprint.family_delta)
        and a third return value fp [n_streams, fcap] carries the
        sealed canonical fingerprints.

        Everything runs BATCH-MINOR (the row axis vmapped at -1): the
        per-state arrays have tiny minor dims (S, Lcap, K ≈ 3-20) which
        waste the TPU's (8,128) vector tiles when the batch is major —
        measured 5.6x slower than this layout on v5e."""
        B = okf.shape[0] // self.n_lanes
        A = self.n_lanes
        totc = sum(fam_caps)

        # ---- one fused compaction for ALL families -------------------
        # The per-family cumsum+scatter chains were ~2x13 serialized
        # kernel launches; instead rearrange the lane grid family-major
        # once (static permutation), run ONE cumsum, and derive every
        # family's buffer positions from it with static lookup tables.
        n_fams = len(self.families)
        perm = np.empty((B * A,), np.int64)          # grouped -> flat
        f_of = np.empty((B * A,), np.int32)
        blk_start = np.empty((n_fams,), np.int64)    # grouped offsets
        caps_np = np.asarray(fam_caps, np.int32)
        coff_np = np.concatenate([[0], np.cumsum(caps_np)[:-1]])
        fam_off = []                  # global lane offset per family
        g = 0
        off = 0
        for fi, fam in enumerate(self.families):
            nf = fam.n_lanes
            blk_start[fi] = g
            fam_off.append(off)
            bl = (np.arange(B)[:, None] * A + off +
                  np.arange(nf)[None, :]).reshape(-1)
            perm[g:g + B * nf] = bl
            f_of[g:g + B * nf] = fi
            g += B * nf
            off += nf
        okg = okf[perm]                              # [N] family-major
        cum = jnp.cumsum(okg.astype(jnp.int32))      # ONE scan
        # enabled-count per family = cum at block ends minus starts
        ends = jnp.asarray(np.concatenate([blk_start[1:], [B * A]]) - 1)
        cum_end = cum[ends]
        cum_start = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), cum_end[:-1]])
        counts = cum_end - cum_start                 # [n_fams] = famx
        # per grouped lane: position within its family's cap buffer
        wpos = cum - 1 - cum_start[jnp.asarray(f_of)]
        cap_p = jnp.asarray(caps_np)[jnp.asarray(f_of)]
        coff_p = jnp.asarray(coff_np, jnp.int32)[jnp.asarray(f_of)]
        fits = okg & (wpos < cap_p)
        target = jnp.where(fits, coff_p + wpos, totc)
        # src: concat slot -> flat lane id (ONE scatter)
        src = jnp.full((totc,), B * A, jnp.int32).at[target].set(
            jnp.asarray(perm, jnp.int32), mode="drop")
        srcc = jnp.clip(src, 0, B * A - 1)
        b_all, l_all = srcc // A, srcc % A
        # mapidx: global FCAP slot -> concat slot (ONE scatter).  Only
        # fitting lanes may write (a clip-garbage src could alias an
        # enabled lane's epos).
        epos_g = epos[perm]
        mapidx = jnp.full((fcap,), totc, jnp.int32).at[
            jnp.where(fits, epos_g, fcap)].set(
            target, mode="drop")

        # ---- affine family group: ONE batched scatter-as-matmul ------
        # Every delta-declared family's buffer slice concatenates into
        # a single (row, group-lane) block; parent-row selection, the
        # source gather and the slot scatter all run as int32 einsum
        # blocks over the flat state view (the BLEST reformulation —
        # see the delta-matrix comment above).  Declaration-less
        # families fall through to the per-family kernel loop below.
        dg = self._dgroup
        g_cand = None
        if dg is not None:
            with jax.named_scope("delta_apply"):
                # barrier the block's inputs as well as its output:
                # the compaction indices and the flat/psi views
                # otherwise fuse into the one-hot einsums and the
                # fusion search dominates compile time (~1.3s per
                # traced program on XLA:CPU) — identity ops, bit-exact
                xflat = jax.lax.optimization_barrier(
                    self._flatten_T(svT))
                psi = jax.lax.optimization_barrier(
                    self._psi_T(svT, derT, xflat))
                if self.delta_chunk_skip:
                    # chunk skip (the ROADMAP item-3 leftover): one
                    # block per family, each under a cond on the
                    # chunk's enabled count — a chunk enabling none of
                    # a family's lanes skips its whole cap-wide block
                    # instead of paying the full group width.  An
                    # enabled family's block runs the identical
                    # gathers/adds as the fused group (bit-exact); a
                    # skipped family's columns were compaction garbage
                    # no consumer reads either way.
                    out_parts, par_parts = [], []
                    for fi in dg["fam_idx"]:
                        nf = self.families[fi].n_lanes
                        lo = int(coff_np[fi])
                        cap = fam_caps[fi]
                        gb_f, gl_f = jax.lax.optimization_barrier(
                            (b_all[lo:lo + cap],
                             jnp.clip(l_all[lo:lo + cap]
                                      - fam_off[fi], 0, nf - 1)))

                        def _apply(ops, fi=fi, nf=nf):
                            xf, ps, gb, gl = ops
                            selL = (gl[:, None] ==
                                    jnp.arange(nf, dtype=jnp.int32)
                                    [None, :]).astype(jnp.int32)
                            if self._delta_mxu:
                                selB = (gb[:, None] ==
                                        jnp.arange(B, dtype=jnp.int32)
                                        [None, :]).astype(jnp.int32)
                                rows = jnp.einsum(
                                    "db,cb->dc", xf, selB,
                                    preferred_element_type=jnp.int32)
                                vals = jnp.einsum(
                                    "eb,cb->ec", ps, selB,
                                    preferred_element_type=jnp.int32)
                            else:
                                rows = xf[:, gb]
                                vals = ps[:, gb]
                            return rows, rows + self._delta_of_fam(
                                vals, selL, fi)

                        def _skip(ops, cap=cap):
                            z = jnp.zeros((dg["D"], cap), jnp.int32)
                            return z, z

                        par_f, out_f = jax.lax.cond(
                            counts[fi] > 0, _apply, _skip,
                            (xflat, psi, gb_f, gl_f))
                        par_parts.append(par_f)
                        out_parts.append(out_f)
                    out_flat = jax.lax.optimization_barrier(
                        jnp.concatenate(out_parts, axis=-1))
                    rows_flat = jnp.concatenate(par_parts, axis=-1)
                else:
                    gb_parts, gl_parts = [], []
                    for fi in dg["fam_idx"]:
                        nf = self.families[fi].n_lanes
                        lo = int(coff_np[fi])
                        cap = fam_caps[fi]
                        gb_parts.append(b_all[lo:lo + cap])
                        gl_parts.append(jnp.clip(
                            l_all[lo:lo + cap] - fam_off[fi],
                            0, nf - 1) + dg["lane_base"][fi])
                    gb, gl = jax.lax.optimization_barrier(
                        (jnp.concatenate(gb_parts),
                         jnp.concatenate(gl_parts)))
                    selL = (gl[:, None] ==
                            jnp.arange(dg["n_lanes"],
                                       dtype=jnp.int32)[None, :]) \
                        .astype(jnp.int32)                # [gcap, A_g]
                    if self._delta_mxu:
                        # row selection as one-hot matmuls (the PR-8
                        # _sel_rows trick, whole group at once)
                        selB = (gb[:, None] ==
                                jnp.arange(B, dtype=jnp.int32)
                                [None, :]).astype(jnp.int32)
                        rows_flat = jnp.einsum(
                            "db,cb->dc", xflat, selB,
                            preferred_element_type=jnp.int32)
                        vals = jnp.einsum(
                            "eb,cb->ec", psi, selB,
                            preferred_element_type=jnp.int32)
                    else:
                        # off-TPU: the bit-identical column gather
                        # (each embedded dot costs ~1s of XLA:CPU
                        # compile)
                        rows_flat = xflat[:, gb]
                        vals = psi[:, gb]
                    # the barrier stops XLA fusing the delta matmul
                    # into its ~n_keys × n_families unflatten/concat
                    # consumers — without it the fusion search costs
                    # ~1.3s of compile per traced program (same class
                    # as the phase barriers in
                    # engine/bfs._chunk_step_impl); identity, so the
                    # bit-exactness contract is untouched
                    out_flat = jax.lax.optimization_barrier(
                        rows_flat + self._delta_of(vals, selL))
                # ONE unflatten for the whole group buffer; families
                # slice their column ranges out of the shaped arrays
                # (slices are far cheaper to trace than per-family
                # reshape+bitcast cascades)
                g_all = self._unflatten_T(out_flat)
                g_par = (self._unflatten_T(rows_flat)
                         if delta_fp is not None else None)
                g_pos = {}
                pos = 0
                for fi in dg["fam_idx"]:
                    g_pos[fi] = pos
                    pos += fam_caps[fi]
                g_cand = g_pos            # membership + slice offset

        # ---- per-family successor kernels on their buffer slices -----
        outs = []
        fp_outs = []
        off = 0
        for fi, (fam, cap) in enumerate(zip(self.families, fam_caps)):
            nf = fam.n_lanes
            lo = int(coff_np[fi])
            b_idx = b_all[lo:lo + cap]
            l_idx = jnp.clip(l_all[lo:lo + cap] - off, 0, nf - 1)
            if g_cand is not None and fi in g_cand:
                # affine family: its successors came out of the group
                # delta matmul above; only the incremental-fp hook
                # still needs the per-family row/param views
                gp = g_cand[fi]
                sv2 = {k: v[..., gp:gp + cap]
                       for k, v in g_all.items()}
                outs.append(sv2)
                if delta_fp is not None:
                    prm_rows = (self._sel_params(fam.params, l_idx, nf)
                                if self.guard_matmul else
                                [jnp.asarray(p)[l_idx]
                                 for p in fam.params])
                    fpr, tables = delta_fp
                    fp_outs.append(fpr.family_delta(
                        fam.name, tables, b_idx,
                        {k: v[..., gp:gp + cap]
                         for k, v in g_par.items()}, sv2, prm_rows))
                off += nf
                continue
            if self.guard_matmul:
                # batched successor einsum: the family's compacted
                # (row, lane) block selects parent rows and lane params
                # via one-hot matmuls (exact — see _sel_rows)
                sv_rows = self._sel_rows(svT, b_idx, B)
                der_rows = self._sel_rows(derT, b_idx, B)
                prm_rows = self._sel_params(fam.params, l_idx, nf)
            else:
                sv_rows = {k: v[..., b_idx] for k, v in svT.items()}
                der_rows = {k: v[..., b_idx] for k, v in derT.items()}
                prm_rows = [jnp.asarray(p)[l_idx] for p in fam.params]
            _ok, sv2 = jax.vmap(
                fam.fn, in_axes=(-1, -1) + (0,) * len(fam.params),
                out_axes=(0, -1))(sv_rows, der_rows, *prm_rows)
            outs.append(sv2)
            if delta_fp is not None:
                fpr, tables = delta_fp
                fp_outs.append(fpr.family_delta(
                    fam.name, tables, b_idx, sv_rows, sv2, prm_rows))
            off += nf
        concat = {k: jnp.concatenate([o[k] for o in outs], axis=-1)
                  for k in self.keys}
        take = jnp.clip(mapidx, 0, totc - 1)
        cand = {k: v[..., take] for k, v in concat.items()}
        if delta_fp is None:
            return cand, counts
        h_all = jnp.concatenate(fp_outs, axis=-1)[..., take]
        return cand, counts, delta_fp[0].finish_min(h_all)

    # ---- per-walker step fusion (the sim engine's hot path) --------------
    #
    # A random walker takes ONE lane per state per step, so the full
    # [B, A] candidate materialization (or even the FCAP compaction) is
    # ~A× too much successor construction.  step_lanes instead applies
    # each family's kernel ONCE per walker with that walker's chosen
    # params (clipped to the family's grid when the walker chose another
    # family — the result is discarded by the select), then merges the
    # n_families results by lane-range selects.  Cost per step is
    # n_families (~10-14) kernel applications per walker versus
    # A (~90-370) lanes of a full expansion; the guard pass stays the
    # dead-code-eliminated guards_T grid.

    def step_lanes(self, svT, derT, lane) -> Dict[str, jnp.ndarray]:
        """Batch-last walker states [..., B] + flat lane ids [B] ->
        successor rows [..., B].  lane must be an enabled lane of its
        state (sim samples from guards_T via ops.kernels.select_enabled);
        rows whose lane is out of range (e.g. -1 = no enabled lane)
        return the state unchanged — callers mask on enabled-count.

        With the delta path compiled, every walker whose lane belongs
        to an affine family steps through ONE group delta matmul (a
        walker outside the group gets an all-zero lane one-hot, so its
        delta is exactly zero and the row passes through); only the
        declaration-less families still apply their kernels."""
        dg = self._dgroup
        if dg is not None:
            with jax.named_scope("delta_apply"):
                aff = jnp.asarray(dg["lane_to_aff"])[
                    jnp.clip(lane, 0, self.n_lanes - 1)]
                aff = jnp.where(lane >= 0, aff, jnp.int32(-1))
                selL = (aff[:, None] ==
                        jnp.arange(dg["n_lanes"],
                                   dtype=jnp.int32)[None, :]) \
                    .astype(jnp.int32)                    # [B, A_g]
                xflat = self._flatten_T(svT)
                psi = self._psi_T(svT, derT, xflat)
                out = self._unflatten_T(
                    xflat + self._delta_of(psi, selL))
        else:
            out = {k: v for k, v in svT.items()}
        off = 0
        for fam in self.families:
            nf = fam.n_lanes
            if dg is not None and fam.delta is not None:
                off += nf
                continue
            li = jnp.clip(lane - off, 0, nf - 1)
            prm = (self._sel_params(fam.params, li, nf)
                   if self.guard_matmul
                   else [jnp.asarray(p)[li] for p in fam.params])
            _ok, sv2 = jax.vmap(
                fam.fn, in_axes=(-1, -1) + (0,) * len(fam.params),
                out_axes=(0, -1))(svT, derT, *prm)
            sel = (lane >= off) & (lane < off + nf)
            out = {k: jnp.where(sel, sv2[k], out[k]) for k in out}
            off += nf
        return out

    # ---- test/debug path -------------------------------------------------
    def expand_one(self, arrs: Dict[str, np.ndarray]):
        """Single state -> [(label, sv2_arrays)] for enabled lanes."""
        svb = {k: jnp.asarray(v)[None] for k, v in arrs.items()}
        ok, cand = self.expand(svb)
        ok = np.asarray(ok)[0]
        labels = self.lane_labels()
        out = []
        for lane in np.nonzero(ok)[0]:
            sv2 = {k: np.asarray(cand[k])[0, lane] for k in self.keys}
            out.append((labels[lane], sv2))
        return out
