"""Frontier expansion: the Next-relation as one vmapped/jitted step.

The action grid mirrors the ∃-quantification TLC performs (SURVEY §3.1):
each *family* (RequestVote, Receive, …) is vmapped over its parameter grid
(server pairs, values, bag slots) and over the frontier batch axis, then
families concatenate into a [B, A] candidate block with validity masks.

Family order follows the oracle's successor enumeration
(models/raft.py successors(), itself mirroring raft.tla:909-943) so
candidate streams are comparable; receive lanes are family-major
(UpdateTerm block, CheckOldConfig-discard block, main-handler block).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import (NEXT_ASYNC_CRASH, NEXT_DYNAMIC, NEXT_FULL,
                      ModelConfig)
from ..ops.codec import ALL_KEYS
from ..ops.kernels import RaftKernels
from ..ops.layout import Layout


@dataclass
class Family:
    name: str
    fn: Callable            # (sv, der, *params) -> (ok, sv2)
    params: Tuple[np.ndarray, ...]   # one array per param, equal length
    labeler: Callable        # (*param_values) -> str

    @property
    def n_lanes(self):
        return len(self.params[0]) if self.params else 1


def build_families(lay: Layout) -> List[Family]:
    cfg = lay.cfg
    kern = RaftKernels(lay)
    S, K = lay.S, lay.K
    fams: List[Family] = []

    def grid(*ranges):
        arrs = np.meshgrid(*[np.asarray(r, np.int32) for r in ranges],
                           indexing="ij")
        return tuple(a.ravel() for a in arrs)

    ij = grid(range(S), range(S))
    ij_ne = tuple(a[ij[0] != ij[1]] for a in ij)        # i != j lanes
    iv = grid(range(S), list(cfg.values))
    i_ = grid(range(S))
    k_ = grid(range(K))

    fams.append(Family(
        "RequestVote", kern.request_vote, ij,
        lambda i, j: f"RequestVote({i},{j})"))
    fams.append(Family(
        "BecomeLeader", kern.become_leader, i_,
        lambda i: f"BecomeLeader({i})"))
    fams.append(Family(
        "ClientRequest", kern.client_request, iv,
        lambda i, v: f"ClientRequest({i},{v})"))
    fams.append(Family(
        "AdvanceCommitIndex", kern.advance_commit_index, i_,
        lambda i: f"AdvanceCommitIndex({i})"))
    fams.append(Family(
        "AppendEntries", kern.append_entries, ij_ne,
        lambda i, j: f"AppendEntries({i},{j})"))
    fams.append(Family(
        "UpdateTerm", kern.update_term, k_,
        lambda k: f"UpdateTerm[slot{k}]"))
    fams.append(Family(
        "CocDiscard", kern.coc_discard, k_,
        lambda k: f"CocDiscard[slot{k}]"))
    fams.append(Family(
        "Receive", kern.receive_main, k_,
        lambda k: f"Receive[slot{k}]"))
    fams.append(Family(
        "Timeout", kern.timeout, i_,
        lambda i: f"Timeout({i})"))
    if cfg.next_family in (NEXT_ASYNC_CRASH, NEXT_FULL, NEXT_DYNAMIC):
        fams.append(Family(
            "Restart", lambda sv, der, i: kern.restart(sv, i), i_,
            lambda i: f"Restart({i})"))
    if cfg.next_family in (NEXT_FULL, NEXT_DYNAMIC):
        fams.append(Family(
            "Duplicate", lambda sv, der, k: kern.duplicate_message(sv, k),
            k_, lambda k: f"Duplicate[slot{k}]"))
        fams.append(Family(
            "Drop", lambda sv, der, k: kern.drop_message(sv, k),
            k_, lambda k: f"Drop[slot{k}]"))
    if cfg.next_family == NEXT_DYNAMIC:
        fams.append(Family(
            "AddNewServer", kern.add_new_server, ij,
            lambda i, j: f"AddNewServer({i},{j})"))
        fams.append(Family(
            "DeleteServer", kern.delete_server, ij_ne,
            lambda i, j: f"DeleteServer({i},{j})"))
    return fams


# Expected enabled-lane density per parent state, by family (measured on
# the BASELINE configs; used to size the per-family materialization
# buffers — cap_f = chunk * min(n_lanes_f, density).  A chunk whose
# enabled count exceeds a cap trips fovf and the engine grows that
# family's cap and replays the level, so these are throughput tuning,
# not correctness bounds.  Restart/Timeout are enabled for ~every
# server in ~every state, so they get their full lane width.
_FAMILY_DENSITY = {
    "Restart": 1 << 30, "Timeout": 1 << 30,
    "RequestVote": 2, "BecomeLeader": 1, "ClientRequest": 2,
    "AdvanceCommitIndex": 2, "AppendEntries": 2,
    "UpdateTerm": 2, "CocDiscard": 1, "Receive": 4,
    "Duplicate": 4, "Drop": 4, "AddNewServer": 2, "DeleteServer": 2,
}


class Expander:
    """Compiled expansion over a frontier batch."""

    def __init__(self, cfg: ModelConfig):
        self.cfg = cfg
        self.lay = Layout(cfg)
        self.kern = RaftKernels(self.lay)
        self.families = build_families(self.lay)
        self.n_lanes = sum(f.n_lanes for f in self.families)
        self._expand = jax.jit(self._expand_impl)

    def lane_labels(self) -> List[str]:
        out = []
        for f in self.families:
            cols = [p for p in f.params]
            for vals in zip(*cols):
                out.append(f.labeler(*[int(v) for v in vals]))
        return out

    def _expand_impl(self, svb: Dict[str, jnp.ndarray]):
        """[B, ...] frontier -> (ok [B, A], cand dict of [B, A, ...])."""
        kern = self.kern

        def one_state(sv):
            der = kern.derived(sv)
            oks, cands = [], []
            for fam in self.families:
                lane = jax.vmap(fam.fn,
                                in_axes=(None, None) + (0,) * len(fam.params))
                ok, sv2 = lane(sv, der,
                               *[jnp.asarray(p) for p in fam.params])
                oks.append(ok)
                cands.append(sv2)
            ok = jnp.concatenate([o.reshape(-1) for o in oks])
            cand = {k: jnp.concatenate([c[k] for c in cands], axis=0)
                    for k in ALL_KEYS}
            return ok, cand

        return jax.vmap(one_state)(svb)

    def expand(self, svb):
        return self._expand(svb)

    # ---- guard-first expansion (the engine hot path) ---------------------
    #
    # The full [B, A] candidate materialization of _expand_impl writes
    # ~A× more successor state than survives compaction (typically ~4-8
    # of A≈90 lanes are enabled per parent).  The engines instead run a
    # cheap guard pass over the whole lane grid (XLA dead-code-eliminates
    # the successor arithmetic since only `ok` is consumed), then
    # materialize successors ONLY for enabled lanes: per family, enabled
    # (parent, lane) pairs compact into a statically-capped buffer, the
    # family kernel runs on those rows, and an index map reassembles the
    # global FCAP candidate buffer in the oracle's enumeration order.

    def default_fam_caps(self, chunk: int) -> Tuple[int, ...]:
        return tuple(
            chunk * min(f.n_lanes, _FAMILY_DENSITY.get(f.name, 2))
            for f in self.families)

    def derived_batch_T(self, svT):
        """Batch-LAST derived quantities (the engines' batch-minor hot
        path — see materialize's layout note)."""
        return jax.vmap(self.kern.derived, in_axes=-1, out_axes=-1)(svT)

    def _guard_one(self, sv, der):
        oks = []
        for fam in self.families:
            lane = jax.vmap(fam.fn,
                            in_axes=(None, None) + (0,) * len(fam.params))
            ok, _sv2 = lane(sv, der,
                            *[jnp.asarray(p) for p in fam.params])
            oks.append(ok.reshape(-1))
        return jnp.concatenate(oks)

    def guards_T(self, svT, derT) -> jnp.ndarray:
        """Batch-LAST frontier [..., B] -> ok [B, A]: every lane's
        enabling guard, with the successor construction
        dead-code-eliminated."""
        ok = jax.vmap(self._guard_one, in_axes=-1, out_axes=-1)(svT, derT)
        return jnp.moveaxis(ok, -1, 0)

    def materialize(self, svT, derT, okf, epos, fcap: int,
                    fam_caps, delta_fp=None) \
            -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
        """Build the compacted candidate buffer [..., fcap] from the
        guard mask.  svT/derT are BATCH-LAST ([..., B]); okf is the
        flat [B*A] enabled mask in b-major lane order, epos the global
        compaction position per flat lane (fcap = dropped).  Returns
        (cand rows batch-last in enumeration order, per-family enabled
        counts — the host grows any family whose count exceeded its cap
        and replays the level).

        delta_fp — optional (Fingerprinter, parent_tables) pair: each
        family also computes its candidates' per-permutation hashes
        incrementally from the parent tables (fingerprint.family_delta)
        and a third return value fp [n_streams, fcap] carries the
        sealed canonical fingerprints.

        Everything runs BATCH-MINOR (the row axis vmapped at -1): the
        per-state arrays have tiny minor dims (S, Lcap, K ≈ 3-20) which
        waste the TPU's (8,128) vector tiles when the batch is major —
        measured 5.6x slower than this layout on v5e."""
        B = okf.shape[0] // self.n_lanes
        A = self.n_lanes
        totc = sum(fam_caps)

        # ---- one fused compaction for ALL families -------------------
        # The per-family cumsum+scatter chains were ~2x13 serialized
        # kernel launches; instead rearrange the lane grid family-major
        # once (static permutation), run ONE cumsum, and derive every
        # family's buffer positions from it with static lookup tables.
        n_fams = len(self.families)
        perm = np.empty((B * A,), np.int64)          # grouped -> flat
        f_of = np.empty((B * A,), np.int32)
        blk_start = np.empty((n_fams,), np.int64)    # grouped offsets
        caps_np = np.asarray(fam_caps, np.int32)
        coff_np = np.concatenate([[0], np.cumsum(caps_np)[:-1]])
        g = 0
        off = 0
        for fi, fam in enumerate(self.families):
            nf = fam.n_lanes
            blk_start[fi] = g
            bl = (np.arange(B)[:, None] * A + off +
                  np.arange(nf)[None, :]).reshape(-1)
            perm[g:g + B * nf] = bl
            f_of[g:g + B * nf] = fi
            g += B * nf
            off += nf
        okg = okf[perm]                              # [N] family-major
        cum = jnp.cumsum(okg.astype(jnp.int32))      # ONE scan
        # enabled-count per family = cum at block ends minus starts
        ends = jnp.asarray(np.concatenate([blk_start[1:], [B * A]]) - 1)
        cum_end = cum[ends]
        cum_start = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32), cum_end[:-1]])
        counts = cum_end - cum_start                 # [n_fams] = famx
        # per grouped lane: position within its family's cap buffer
        wpos = cum - 1 - cum_start[jnp.asarray(f_of)]
        cap_p = jnp.asarray(caps_np)[jnp.asarray(f_of)]
        coff_p = jnp.asarray(coff_np, jnp.int32)[jnp.asarray(f_of)]
        fits = okg & (wpos < cap_p)
        target = jnp.where(fits, coff_p + wpos, totc)
        # src: concat slot -> flat lane id (ONE scatter)
        src = jnp.full((totc,), B * A, jnp.int32).at[target].set(
            jnp.asarray(perm, jnp.int32), mode="drop")
        srcc = jnp.clip(src, 0, B * A - 1)
        b_all, l_all = srcc // A, srcc % A
        # mapidx: global FCAP slot -> concat slot (ONE scatter).  Only
        # fitting lanes may write (a clip-garbage src could alias an
        # enabled lane's epos).
        epos_g = epos[perm]
        mapidx = jnp.full((fcap,), totc, jnp.int32).at[
            jnp.where(fits, epos_g, fcap)].set(
            target, mode="drop")

        # ---- per-family successor kernels on their buffer slices -----
        outs = []
        fp_outs = []
        off = 0
        for fi, (fam, cap) in enumerate(zip(self.families, fam_caps)):
            nf = fam.n_lanes
            lo = int(coff_np[fi])
            b_idx = b_all[lo:lo + cap]
            l_idx = jnp.clip(l_all[lo:lo + cap] - off, 0, nf - 1)
            sv_rows = {k: v[..., b_idx] for k, v in svT.items()}
            der_rows = {k: v[..., b_idx] for k, v in derT.items()}
            prm_rows = [jnp.asarray(p)[l_idx] for p in fam.params]
            _ok, sv2 = jax.vmap(
                fam.fn, in_axes=(-1, -1) + (0,) * len(fam.params),
                out_axes=(0, -1))(sv_rows, der_rows, *prm_rows)
            outs.append(sv2)
            if delta_fp is not None:
                fpr, tables = delta_fp
                fp_outs.append(fpr.family_delta(
                    fam.name, tables, b_idx, sv_rows, sv2, prm_rows))
            off += nf
        concat = {k: jnp.concatenate([o[k] for o in outs], axis=-1)
                  for k in ALL_KEYS}
        take = jnp.clip(mapidx, 0, totc - 1)
        cand = {k: v[..., take] for k, v in concat.items()}
        if delta_fp is None:
            return cand, counts
        h_all = jnp.concatenate(fp_outs, axis=-1)[..., take]
        return cand, counts, delta_fp[0].finish_min(h_all)

    # ---- per-walker step fusion (the sim engine's hot path) --------------
    #
    # A random walker takes ONE lane per state per step, so the full
    # [B, A] candidate materialization (or even the FCAP compaction) is
    # ~A× too much successor construction.  step_lanes instead applies
    # each family's kernel ONCE per walker with that walker's chosen
    # params (clipped to the family's grid when the walker chose another
    # family — the result is discarded by the select), then merges the
    # n_families results by lane-range selects.  Cost per step is
    # n_families (~10-14) kernel applications per walker versus
    # A (~90-370) lanes of a full expansion; the guard pass stays the
    # dead-code-eliminated guards_T grid.

    def step_lanes(self, svT, derT, lane) -> Dict[str, jnp.ndarray]:
        """Batch-last walker states [..., B] + flat lane ids [B] ->
        successor rows [..., B].  lane must be an enabled lane of its
        state (sim samples from guards_T via ops.kernels.select_enabled);
        rows whose lane is out of range (e.g. -1 = no enabled lane)
        return the state unchanged — callers mask on enabled-count."""
        out = {k: v for k, v in svT.items()}
        off = 0
        for fam in self.families:
            nf = fam.n_lanes
            li = jnp.clip(lane - off, 0, nf - 1)
            prm = [jnp.asarray(p)[li] for p in fam.params]
            _ok, sv2 = jax.vmap(
                fam.fn, in_axes=(-1, -1) + (0,) * len(fam.params),
                out_axes=(0, -1))(svT, derT, *prm)
            sel = (lane >= off) & (lane < off + nf)
            out = {k: jnp.where(sel, sv2[k], out[k]) for k in out}
            off += nf
        return out

    # ---- test/debug path -------------------------------------------------
    def expand_one(self, arrs: Dict[str, np.ndarray]):
        """Single state -> [(label, sv2_arrays)] for enabled lanes."""
        svb = {k: jnp.asarray(v)[None] for k, v in arrs.items()}
        ok, cand = self.expand(svb)
        ok = np.asarray(ok)[0]
        labels = self.lane_labels()
        out = []
        for lane in np.nonzero(ok)[0]:
            sv2 = {k: np.asarray(cand[k])[0, lane] for k in ALL_KEYS}
            out.append((labels[lane], sv2))
        return out
