"""Frontier expansion: the Next-relation as one vmapped/jitted step.

The action grid mirrors the ∃-quantification TLC performs (SURVEY §3.1):
each *family* (RequestVote, Receive, …) is vmapped over its parameter grid
(server pairs, values, bag slots) and over the frontier batch axis, then
families concatenate into a [B, A] candidate block with validity masks.

Family order follows the oracle's successor enumeration
(models/raft.py successors(), itself mirroring raft.tla:909-943) so
candidate streams are comparable; receive lanes are family-major
(UpdateTerm block, CheckOldConfig-discard block, main-handler block).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import (NEXT_ASYNC_CRASH, NEXT_DYNAMIC, NEXT_FULL,
                      ModelConfig)
from ..ops.codec import ALL_KEYS
from ..ops.kernels import RaftKernels
from ..ops.layout import Layout


@dataclass
class Family:
    name: str
    fn: Callable            # (sv, der, *params) -> (ok, sv2)
    params: Tuple[np.ndarray, ...]   # one array per param, equal length
    labeler: Callable        # (*param_values) -> str

    @property
    def n_lanes(self):
        return len(self.params[0]) if self.params else 1


def build_families(lay: Layout) -> List[Family]:
    cfg = lay.cfg
    kern = RaftKernels(lay)
    S, K = lay.S, lay.K
    fams: List[Family] = []

    def grid(*ranges):
        arrs = np.meshgrid(*[np.asarray(r, np.int32) for r in ranges],
                           indexing="ij")
        return tuple(a.ravel() for a in arrs)

    ij = grid(range(S), range(S))
    ij_ne = tuple(a[ij[0] != ij[1]] for a in ij)        # i != j lanes
    iv = grid(range(S), list(cfg.values))
    i_ = grid(range(S))
    k_ = grid(range(K))

    fams.append(Family(
        "RequestVote", kern.request_vote, ij,
        lambda i, j: f"RequestVote({i},{j})"))
    fams.append(Family(
        "BecomeLeader", kern.become_leader, i_,
        lambda i: f"BecomeLeader({i})"))
    fams.append(Family(
        "ClientRequest", kern.client_request, iv,
        lambda i, v: f"ClientRequest({i},{v})"))
    fams.append(Family(
        "AdvanceCommitIndex", kern.advance_commit_index, i_,
        lambda i: f"AdvanceCommitIndex({i})"))
    fams.append(Family(
        "AppendEntries", kern.append_entries, ij_ne,
        lambda i, j: f"AppendEntries({i},{j})"))
    fams.append(Family(
        "UpdateTerm", kern.update_term, k_,
        lambda k: f"UpdateTerm[slot{k}]"))
    fams.append(Family(
        "CocDiscard", kern.coc_discard, k_,
        lambda k: f"CocDiscard[slot{k}]"))
    fams.append(Family(
        "Receive", kern.receive_main, k_,
        lambda k: f"Receive[slot{k}]"))
    fams.append(Family(
        "Timeout", kern.timeout, i_,
        lambda i: f"Timeout({i})"))
    if cfg.next_family in (NEXT_ASYNC_CRASH, NEXT_FULL, NEXT_DYNAMIC):
        fams.append(Family(
            "Restart", lambda sv, der, i: kern.restart(sv, i), i_,
            lambda i: f"Restart({i})"))
    if cfg.next_family in (NEXT_FULL, NEXT_DYNAMIC):
        fams.append(Family(
            "Duplicate", lambda sv, der, k: kern.duplicate_message(sv, k),
            k_, lambda k: f"Duplicate[slot{k}]"))
        fams.append(Family(
            "Drop", lambda sv, der, k: kern.drop_message(sv, k),
            k_, lambda k: f"Drop[slot{k}]"))
    if cfg.next_family == NEXT_DYNAMIC:
        fams.append(Family(
            "AddNewServer", kern.add_new_server, ij,
            lambda i, j: f"AddNewServer({i},{j})"))
        fams.append(Family(
            "DeleteServer", kern.delete_server, ij_ne,
            lambda i, j: f"DeleteServer({i},{j})"))
    return fams


class Expander:
    """Compiled expansion over a frontier batch."""

    def __init__(self, cfg: ModelConfig):
        self.cfg = cfg
        self.lay = Layout(cfg)
        self.kern = RaftKernels(self.lay)
        self.families = build_families(self.lay)
        self.n_lanes = sum(f.n_lanes for f in self.families)
        self._expand = jax.jit(self._expand_impl)

    def lane_labels(self) -> List[str]:
        out = []
        for f in self.families:
            cols = [p for p in f.params]
            for vals in zip(*cols):
                out.append(f.labeler(*[int(v) for v in vals]))
        return out

    def _expand_impl(self, svb: Dict[str, jnp.ndarray]):
        """[B, ...] frontier -> (ok [B, A], cand dict of [B, A, ...])."""
        kern = self.kern

        def one_state(sv):
            der = kern.derived(sv)
            oks, cands = [], []
            for fam in self.families:
                lane = jax.vmap(fam.fn,
                                in_axes=(None, None) + (0,) * len(fam.params))
                ok, sv2 = lane(sv, der,
                               *[jnp.asarray(p) for p in fam.params])
                oks.append(ok)
                cands.append(sv2)
            ok = jnp.concatenate([o.reshape(-1) for o in oks])
            cand = {k: jnp.concatenate([c[k] for c in cands], axis=0)
                    for k in ALL_KEYS}
            return ok, cand

        return jax.vmap(one_state)(svb)

    def expand(self, svb):
        return self._expand(svb)

    # ---- test/debug path -------------------------------------------------
    def expand_one(self, arrs: Dict[str, np.ndarray]):
        """Single state -> [(label, sv2_arrays)] for enabled lanes."""
        svb = {k: jnp.asarray(v)[None] for k, v in arrs.items()}
        ok, cand = self.expand(svb)
        ok = np.asarray(ok)[0]
        labels = self.lane_labels()
        out = []
        for lane in np.nonzero(ok)[0]:
            sv2 = {k: np.asarray(cand[k])[0, lane] for k in ALL_KEYS}
            out.append((labels[lane], sv2))
        return out
