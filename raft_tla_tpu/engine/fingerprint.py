"""Symmetry-aware state fingerprints (VIEW + SYMMETRY semantics).

State identity follows the reference model's TLC declarations
(tlc_membership/raft.cfg:29-30): the fingerprint covers only the 10
semantic variables (``VIEW vars`` — history/features excluded, SURVEY
§2.2) and is canonical under server relabeling (``SYMMETRY perms``,
raft.tla:1281) by taking the minimum over the permutation group of a
64-bit hash of the relabeled view:

  fp(s) = min_{σ ∈ G} H(relabel(s, σ))

G is the subgroup of Permutations(Server) fixing InitServer setwise —
Permutations(Server) as the reference declares would be unsound when
InitServer ⊊ Server (models/explore.py symmetry_perms is the oracle twin).

H hashes positional fields with per-position salts and the message bag
**commutatively** (Σ over slots of count · mix(slot)), so bag slot order
— or a message split across slots — never affects identity and no
canonical bag sort exists anywhere in the engine (ops/layout.py).

Hot-path formulation (the engine fingerprints every fresh candidate, so
this dominated profiles): because the positional hash is a commutative
sum Σ_t fmix(relabeled[t] ^ salt[t]), relabeling the *state* is
equivalent to permuting the *salts*:

  Σ_t fmix(view(σ(s))[t] ^ salt[t])  =  Σ_p fmix(content_σ(s)[p] ^ salt[σ(p)])

so instead of gathering every state array through the inverse
permutation per σ (the old formulation — P gathers of the whole state
per candidate), the engine precomputes P statically-permuted salt
tables at init and hashes the state IN PLACE.  Only fields whose
*values* carry server labels still need per-σ work: votedFor, the
vote bitmasks, ConfigEntry payloads, and message src/dst/mserver.
Message slots are unpacked ONCE (perm-independent) and per σ only the
three label fields are re-packed into the header word.  The resulting
fingerprints are bit-identical to the naive relabel-then-hash form
(tests/test_codec.py asserts batch/per-state identity; the engine's
differential suites pin the semantics).

64-bit fingerprints are two independent 32-bit murmur-finalizer streams
(no jax x64 dependency); ``fp128`` doubles the streams (SURVEY §7.4
hard part 4: TLC-style collision odds vs exhaustiveness claims).
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..config import CONFIG_ENTRY, MT_COC, NIL, ModelConfig
from ..ops.kernels import RaftKernels
from ..ops.layout import Layout, get_field, put_field

U32 = jnp.uint32


def fmix32(x):
    """murmur3 finalizer on uint32 arrays (wrapping arithmetic)."""
    x = x ^ (x >> 16)
    x = x * U32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * U32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _salts(n: int, stream: int) -> np.ndarray:
    rng = np.random.RandomState(0xC0FFEE + 7919 * stream)
    return rng.randint(0, 1 << 32, size=n, dtype=np.uint32)


SYM_CANON_MODES = ("auto", "sort", "minperm")
# auto → orbit-sort once the group outgrows the trivial-cost regime.
# P ≤ 6 (S ≤ 3 full-symmetry) keeps the static min-over-perms path —
# it is already cheap there AND keeps the incremental-fp delta tables.
_AUTO_SORT_MIN_PERMS = 6


def resolve_sym_canon(cfg, sym_canon: str = "auto") -> str:
    """CLI/engine mode -> the concrete canonicalizer ("sort" or
    "minperm").  Symmetry off always resolves to minperm (the identity
    permutation; nothing to sort); "auto" picks sort when the group
    has more than ``_AUTO_SORT_MIN_PERMS`` permutations."""
    if sym_canon not in SYM_CANON_MODES:
        raise ValueError(
            f"sym_canon must be one of {SYM_CANON_MODES}, "
            f"got {sym_canon!r}")
    if not cfg.symmetry:
        return "minperm"
    if sym_canon == "auto":
        from ..spec import spec_of
        n_perms = len(spec_of(cfg).symmetry_perms(cfg))
        return "sort" if n_perms > _AUTO_SORT_MIN_PERMS else "minperm"
    return sym_canon


def Fingerprinter(cfg, sym_canon: str = "auto"):
    """Factory: the active spec's symmetry-canonical fingerprinter
    (``spec_of(cfg).make_fingerprinter`` — RaftFingerprinter below for
    raft, spec/paxos/fingerprint.PaxosFingerprinter for paxos).  Kept
    under the historical class name so every engine/tool call site
    reads unchanged.  ``sym_canon`` selects the canonicalizer (round
    15): "minperm" is the classic P-fold min-over-perms, "sort" the
    orbit-sort signature path, "auto" resolves per the group size —
    the spec hook always receives the RESOLVED mode."""
    from ..spec import spec_of
    return spec_of(cfg).make_fingerprinter(
        cfg, sym_canon=resolve_sym_canon(cfg, sym_canon))


class RaftFingerprinter:
    def __init__(self, cfg: ModelConfig, sym_canon: str = "minperm"):
        assert sym_canon in ("sort", "minperm"), sym_canon
        self.sym_canon = sym_canon
        self.cfg = cfg
        self.lay = Layout(cfg)
        self.kern = RaftKernels(self.lay)
        S, Lcap = self.lay.S, self.lay.Lcap
        self.n_streams = 4 if cfg.fp128 else 2
        # positional salt layout: ct,st,vf,ci,llen | log | vr,vg | ni,mi
        self.n_pos = 5 * S + S * Lcap + 2 * S + 2 * S * S
        self.pos_salts = [_salts(self.n_pos, t) for t in
                          range(self.n_streams)]
        self.bag_salts = [_salts(self.lay.msg_words + 1, 16 + t)
                          for t in range(self.n_streams)]
        if cfg.symmetry:
            # the spec's symmetry group (SpecIR handle — the oracle
            # twin models/explore.symmetry_perms for raft)
            from ..spec import spec_of
            perms = spec_of(cfg).symmetry_perms(cfg)
        else:
            perms = [tuple(range(S))]
        self.sigmas = np.array(perms, dtype=np.int32)           # [P, S]
        # statically permuted salt tables: psalts[p, t, i] is the salt a
        # value at original flat position i hashes against under σ_p —
        # i.e. pos_salts[t][σ_p(position i)]; per-server blocks permute
        # by σ(i), log by (σ(i), l), ni/mi by (σ(i), σ(j)).
        idx = np.empty((len(perms), self.n_pos), dtype=np.int64)
        ar = np.arange(S)
        for p, sig in enumerate(np.asarray(self.sigmas)):
            off = 0
            for _blk in range(5):                        # ct st vf ci llen
                idx[p, off:off + S] = off + sig[ar]
                off += S
            blk = (sig[ar][:, None] * Lcap +
                   np.arange(Lcap)[None, :]).reshape(-1)  # log
            idx[p, off:off + S * Lcap] = off + blk
            off += S * Lcap
            for _blk in range(2):                        # vr vg
                idx[p, off:off + S] = off + sig[ar]
                off += S
            blk2 = (sig[ar][:, None] * S + sig[ar][None, :]).reshape(-1)
            for _blk in range(2):                        # ni mi
                idx[p, off:off + S * S] = off + blk2
                off += S * S
            assert off == self.n_pos
        self.psalts = np.stack(
            [np.stack([self.pos_salts[t][idx[p]]
                       for t in range(self.n_streams)])
             for p in range(len(perms))])          # [P, n_streams, n_pos]
        if sym_canon == "sort":
            # orbit-sort precompute (round 15): static per-block server
            # index lists (every σ in the group fixes InitServer
            # setwise, so the sort must never move a server across the
            # inside/outside boundary), a per-block salt folded into
            # the signature so equal-looking servers in DIFFERENT
            # blocks never tie, per-log-slot signature salts, and a
            # per-stream salt for the final bijection that keeps
            # sort-mode fingerprint VALUES disjoint from min-perm mode
            # (the checkpoint cross-mode refusal guards a real
            # incompatibility, not a convention).
            inside = [i for i in range(S) if cfg.init_mask >> i & 1]
            outside = [i for i in range(S)
                       if not (cfg.init_mask >> i & 1)]
            self._blocks = [np.array(b, np.int32)
                            for b in (inside, outside) if b]
            bsalt = _salts(len(self._blocks), 41)
            blk = np.zeros(S, np.uint32)
            for bi, b in enumerate(self._blocks):
                blk[b] = bsalt[bi]
            self._blk_salt = blk
            self._log_sig_salts = _salts(Lcap, 42)
            self._sort_salt = _salts(self.n_streams, 49)
            from ..spec import spec_of
            self._sig_fn = spec_of(cfg).server_signature

    # ------------------------------------------------------------------

    def _perm_mask(self, m, sigma):
        out = jnp.zeros_like(m)
        for i in range(self.lay.S):
            out = out | (((m >> i) & 1) << sigma[i])
        return out

    # ------------------------------------------------------------------
    # shared hashing core.  svT holds the VIEW arrays with their
    # canonical leading axes ([S], [S,Lcap], [K,MW], [K]) and `nb`
    # trailing batch axes (0 for the per-state path, 1 for the batched
    # engine path — batch axis LAST so position reductions stay major).
    # ------------------------------------------------------------------

    def _prep(self, svT: Dict, nb: int) -> Dict:
        """Perm-independent hashing precompute (hoisted out of every
        per-σ / per-lane hash evaluation): bag header fields unpacked
        once, log/entry ConfigEntry payloads split once."""
        lay, kern = self.lay, self.kern
        K = lay.K
        hs = lay.header_shifts
        bag = svT["bag"]                                  # [K, MW, ...]
        w0 = bag[:, 0]
        mtype = get_field(w0, hs["mtype"]).astype(jnp.int32)
        src = get_field(w0, hs["msrc"]).astype(jnp.int32)
        dst = get_field(w0, hs["mdst"]).astype(jnp.int32)
        braw = get_field(w0, hs["b"]).astype(jnp.int32)   # stored +1
        clear = U32(0xFFFFFFFF) ^ U32(
            put_field(0xFFFFFFFF, hs["msrc"]) |
            put_field(0xFFFFFFFF, hs["mdst"]) |
            put_field(0xFFFFFFFF, hs["b"]))
        w0_base = w0 & clear
        empty = mtype == 0
        is_coc = mtype == MT_COC
        ebits, epw = lay.entry_bits, lay.entries_per_word
        emask = (1 << ebits) - 1
        ent = jnp.stack([
            ((bag[:, 1 + k // epw] >> (ebits * (k % epw))) & emask)
            .astype(jnp.int32)
            for k in range(lay.Lmax)], axis=1) if lay.msg_words > 1 \
            else jnp.zeros((K, 0) + w0.shape[1:], jnp.int32)  # [K,Lmax,...]
        vmask = (1 << lay.value_bits) - 1

        def split_cfg(e):
            """entry -> (is_cfg, payload-cleared base, payload)."""
            is_cfg = (kern.entry_type(e) == CONFIG_ENTRY) & (e != 0)
            return is_cfg, e & ~jnp.int32(vmask), e & vmask

        ent_cfg, ent_base, ent_pay = split_cfg(ent)
        log = svT["log"]                                  # [S, Lcap, ...]
        log_cfg, log_base, log_pay = split_cfg(log)
        const_flat = [svT["ct"], svT["st"], None, svT["ci"], svT["llen"],
                      None, None, None, svT["ni"], svT["mi"]]
        return dict(bag=bag, w0=w0, src=src, dst=dst, braw=braw,
                    w0_base=w0_base, empty=empty, is_coc=is_coc,
                    ent=ent, ent_cfg=ent_cfg, ent_base=ent_base,
                    ent_pay=ent_pay, log=log, log_cfg=log_cfg,
                    log_base=log_base, log_pay=log_pay,
                    vf=svT["vf"], cnt=svT["cnt"].astype(U32),
                    const_flat=const_flat)

    def _hash_under(self, prep: Dict, svT: Dict, nb: int,
                    sigma, psalt) -> jnp.ndarray:
        """One salted hash of the state under σ -> u32[n_streams, ...].

        σ is either a single static permutation [S] (the min-over-perms
        path vmaps this over ``sigmas``/``psalts``) or a PER-LANE
        permutation [S, B] with per-lane gathered salts ([T, n_pos, B],
        the orbit-sort path).  Value rewrites and salt lookups pick the
        gather flavor by ndim; the hash algebra is identical, so the
        two paths agree bit-for-bit whenever the permutations do."""
        lay = self.lay
        S = lay.S
        hs = lay.header_shifts
        tail = (1,) * nb

        def sub(idx):
            return (jnp.take_along_axis(sigma, idx, axis=0)
                    if sigma.ndim > 1 else sigma[idx])

        # ---- label-carrying content, relabeled under σ ----
        vf = prep["vf"]
        vfp = jnp.where(vf >= 0, sub(jnp.clip(vf, 0, S - 1)), NIL)
        vrp = self._perm_mask(svT["vr"], sigma)
        vgp = self._perm_mask(svT["vg"], sigma)
        logp = jnp.where(prep["log_cfg"],
                         prep["log_base"] |
                         self._perm_mask(prep["log_pay"], sigma),
                         prep["log"])
        pieces = list(prep["const_flat"])
        pieces[2], pieces[5], pieces[6], pieces[7] = vfp, logp, vrp, vgp
        flat = jnp.concatenate(
            [p.reshape((-1,) + p.shape[p.ndim - nb:]).astype(U32)
             for p in pieces])                            # [n_pos, ...]

        # ---- bag header/entry repack (only label fields change) --
        srcp = sub(jnp.clip(prep["src"], 0, S - 1))
        dstp = sub(jnp.clip(prep["dst"], 0, S - 1))
        bp = jnp.where(prep["is_coc"],
                       sub(jnp.clip(prep["braw"] - 1, 0, S - 1)) + 1,
                       prep["braw"])
        w0p = (prep["w0_base"] |
               put_field(srcp.astype(U32), hs["msrc"]) |
               put_field(dstp.astype(U32), hs["mdst"]) |
               put_field(bp.astype(U32), hs["b"]))
        w0p = jnp.where(prep["empty"], prep["w0"], w0p)
        entp = jnp.where(prep["ent_cfg"],
                         prep["ent_base"] |
                         self._perm_mask(prep["ent_pay"], sigma),
                         prep["ent"])
        ebits, epw = lay.entry_bits, lay.entries_per_word
        words = [w0p]
        for w in range(1, lay.msg_words):
            acc = jnp.zeros_like(prep["w0"])
            for k in range((w - 1) * epw, min(w * epw, lay.Lmax)):
                acc = acc | (entp[:, k].astype(U32)
                             << (ebits * (k % epw)))
            words.append(jnp.where(prep["empty"], prep["bag"][:, w],
                                   acc))

        # ---- per-stream reduction ----
        out = []
        for t in range(self.n_streams):
            p_t = psalt[t]
            if p_t.ndim == 1:
                p_t = p_t.reshape((self.n_pos,) + tail)
            h = jnp.sum(fmix32(flat ^ p_t), axis=0)
            bs = jnp.asarray(self.bag_salts[t])
            slot = jnp.zeros_like(prep["w0"])
            for w in range(lay.msg_words):
                slot = slot + fmix32(words[w] ^ bs[w])
            h = h + jnp.sum(prep["cnt"] * fmix32(slot ^ bs[-1]),
                            axis=0)
            out.append(h)
        return jnp.stack(out)                     # [n_streams, ...]

    def _core(self, svT: Dict, nb: int) -> jnp.ndarray:
        prep = self._prep(svT, nb)
        if self.sym_canon == "sort" and len(self.sigmas) > 1:
            assert nb == 1          # fingerprint() wraps with B=1
            return self._core_sort(prep, svT)
        hs_all = jax.vmap(
            lambda s, p: self._hash_under(prep, svT, nb, s, p))(
            jnp.asarray(self.sigmas),
            jnp.asarray(self.psalts))             # [P, n_streams, ...]
        return self._seal(self._lex_min(hs_all))

    # ------------------------------------------------------------------
    # Orbit-sort canonicalization (round 15).  Instead of hashing under
    # EVERY σ and minning (×P work per candidate, P = S! on config #5),
    # compute a permutation-EQUIVARIANT per-server signature (the
    # SpecIR ``server_signature`` hook — vectorized 1-WL color
    # refinement), stable-argsort it within each symmetry block, and
    # hash ONCE under the sorting permutation π.  Soundness:
    #   * if the sorted signatures are strictly increasing inside every
    #     block, π is the UNIQUE canonicalizing permutation up to the
    #     stabilizer of the state, and H(relabel(s, π)) is an orbit
    #     invariant outright;
    #   * signature ties leave a residual subgroup generated by the
    #     adjacent transpositions of tie runs.  For each tied adjacent
    #     pair the CERTIFICATE hashes under τ∘π (swap the two canonical
    #     slots — S-1 extra dynamic hashes worst case): if every tied
    #     transposition leaves the hash fixed, the whole residual
    #     subgroup stabilizes the canonical representative (a product
    #     of symmetric groups is generated by adjacent transpositions)
    #     and the single hash is again orbit-invariant ("soft" lane);
    #   * otherwise the lane is "hard": the signature could not
    #     separate genuinely distinct servers (1-WL-hard cases, e.g.
    #     votedFor functional-graph cycles), and the lane falls back to
    #     the exact min-over-perms value — same orbit ⟹ same min, so
    #     the partition equals min-over-perms EXACTLY (modulo the same
    #     2^-64-per-pair hash-collision class as minperm itself; a
    #     certificate-hash collision can additionally SPLIT an orbit
    #     where minperm could only merge — same odds class).
    # Hard/soft classification is itself orbit-invariant (signatures
    # are equivariant, so relabeled states sort to the SAME canonical
    # representative and tie pattern), hence lanes of one orbit never
    # disagree on which value they use.  The fallback is lax.cond-gated
    # per chunk: a chunk with zero hard lanes never pays the P-fold
    # pass.  Finally a per-stream fmix bijection over the selected
    # value keeps sort-mode fingerprints value-disjoint from min-perm
    # mode (cross-mode resume is refused, not silently corrupted).
    # ------------------------------------------------------------------

    def _sort_perm(self, sig):
        """Per-lane canonicalizing permutation π (old id -> canonical
        slot) from the signature: stable argsort WITHIN each symmetry
        block.  Returns (π [S, B] i32, ties) where ties is the static
        list of (slot_a, slot_b, eq [B]) adjacent-pair certificates —
        block boundaries never generate a tie entry."""
        S = self.lay.S
        nB = sig.shape[1]
        col = jnp.arange(nB)[None, :]
        pi = jnp.zeros((S, nB), jnp.int32)
        ties = []
        for blk in self._blocks:
            bj = jnp.asarray(blk)
            sigb = sig[blk]                       # [m, B] static gather
            order = jnp.argsort(sigb, axis=0, stable=True)
            src = bj[order]               # old ids in canonical order
            pi = pi.at[src, col].set(
                jnp.broadcast_to(bj[:, None], src.shape))
            ss = jnp.take_along_axis(sigb, order, axis=0)
            for r in range(len(blk) - 1):
                ties.append((int(blk[r]), int(blk[r + 1]),
                             ss[r] == ss[r + 1]))
        return pi, ties

    def _dyn_psalts(self, pi):
        """pos_salts gathered under a PER-LANE permutation — the jnp
        mirror of __init__'s static psalts index construction.
        pi [S, B] -> [n_streams, n_pos, B]."""
        S, Lcap = self.lay.S, self.lay.Lcap
        B = pi.shape[1:]
        parts, off = [], 0
        for _blk in range(5):                        # ct st vf ci llen
            parts.append(off + pi)
            off += S
        lg = off + pi[:, None] * Lcap + \
            jnp.arange(Lcap, dtype=jnp.int32)[None, :, None]
        parts.append(lg.reshape((S * Lcap,) + B))    # log
        off += S * Lcap
        for _blk in range(2):                        # vr vg
            parts.append(off + pi)
            off += S
        for _blk in range(2):                        # ni mi
            sq = off + pi[:, None] * S + pi[None, :]
            parts.append(sq.reshape((S * S,) + B))
            off += S * S
        idx = jnp.concatenate(parts)                 # [n_pos, B]
        return jnp.stack([jnp.asarray(self.pos_salts[t])[idx]
                          for t in range(self.n_streams)])

    def _sort_hashes(self, prep: Dict, svT: Dict):
        """Shared sort-path body: (h0 [T, B], hard [B], tie [B])."""
        sig = self._sig_fn(self, svT, prep)          # [S, B] u32
        pi, ties = self._sort_perm(sig)
        h0 = self._hash_under(prep, svT, 1, pi, self._dyn_psalts(pi))
        hard = jnp.zeros(h0.shape[1:], bool)
        tie = jnp.zeros(h0.shape[1:], bool)
        for a, b, eq in ties:
            tie = tie | eq
            pit = jnp.where(pi == a, b, jnp.where(pi == b, a, pi))
            ht = self._hash_under(prep, svT, 1, pit,
                                  self._dyn_psalts(pit))
            same = jnp.ones_like(hard)
            for t in range(self.n_streams):
                same = same & (ht[t] == h0[t])
            hard = hard | (eq & ~same)
        return h0, hard, tie

    def _core_sort(self, prep: Dict, svT: Dict) -> jnp.ndarray:
        h0, hard, _tie = self._sort_hashes(prep, svT)

        def _fallback(_):
            hs_all = jax.vmap(
                lambda s, p: self._hash_under(prep, svT, 1, s, p))(
                jnp.asarray(self.sigmas), jnp.asarray(self.psalts))
            return self._lex_min(hs_all)

        fp_min = jax.lax.cond(jnp.any(hard), _fallback,
                              lambda _: jnp.zeros_like(h0), None)
        fp = jnp.where(hard[None], fp_min, h0)
        fp = fmix32(fp ^ jnp.asarray(self._sort_salt)[:, None])
        return self._seal(fp)

    def sort_debug(self, svb: Dict) -> Dict:
        """Test/bench hook: per-state (hard, tie) masks for a batch-
        FIRST [B, ...] state dict under the sort canonicalizer."""
        assert self.sym_canon == "sort"
        svT = {k: jnp.moveaxis(jnp.asarray(v), 0, -1)
               for k, v in svb.items()}
        prep = self._prep(svT, 1)
        _h0, hard, tie = self._sort_hashes(prep, svT)
        return dict(hard=np.asarray(hard), tie=np.asarray(tie))

    def _seal(self, best):
        """The engines' visited tables use the all-ones key as the
        empty-slot sentinel; an all-ones fingerprint would alias it
        and be re-admitted as fresh on EVERY regeneration (unlike an
        ordinary fp collision, which miscounts once).  Remap it to a
        fixed alternate so the sentinel is unreachable by real keys."""
        allones = jnp.ones(best.shape[1:], bool)
        for t in range(self.n_streams):
            allones = allones & (best[t] == U32(0xFFFFFFFF))
        return best.at[self.n_streams - 1].set(
            jnp.where(allones, U32(0xFFFFFFFE), best[self.n_streams - 1]))

    def fingerprint(self, sv: Dict) -> jnp.ndarray:
        """Single state -> u32[n_streams]: the canonical hash (min over
        the symmetry group in minperm mode, the orbit-sort hash in sort
        mode — same partition either way)."""
        if self.sym_canon == "sort" and len(self.sigmas) > 1:
            svT = {k: jnp.asarray(v)[..., None] for k, v in sv.items()}
            return self._core(svT, nb=1)[..., 0]
        return self._core(sv, nb=0)

    def fingerprint_batch(self, svb: Dict) -> jnp.ndarray:
        """[B, ...] batch -> u32[B, n_streams]; bit-identical to
        vmap(fingerprint) (tests/test_codec.py asserts this) but with
        the batch axis minor so the position reduction vectorizes."""
        svT = {k: jnp.moveaxis(v, 0, -1) for k, v in svb.items()}
        return self._core(svT, nb=1).T            # [B, n_streams]

    def fingerprint_batch_T(self, svT: Dict) -> jnp.ndarray:
        """Batch-LAST twin for the engines' batch-minor hot path:
        [..., B] arrays -> u32[n_streams, B] (no transposes)."""
        return self._core(svT, nb=1)

    def _lex_min(self, hs) -> jnp.ndarray:
        """[P, n_streams, ...] -> [n_streams, ...]: lexicographic min
        over the permutation axis via iterative select (P is small).
        Shared by the per-state and batched entry points so the
        tie-break order can never diverge between them."""
        best = hs[0]
        for p in range(1, hs.shape[0]):
            cand = hs[p]
            less = jnp.zeros(best.shape[1:], bool)
            eq = jnp.ones(best.shape[1:], bool)
            for t in range(self.n_streams):
                less = less | (eq & (cand[t] < best[t]))
                eq = eq & (cand[t] == best[t])
            best = jnp.where(less, cand, best)
        return best

    # ==================================================================
    # Incremental per-action fingerprints (VERDICT r3 #2/#3).
    #
    # Because every stream is a COMMUTATIVE u32 sum of per-position /
    # per-bag-slot terms, a successor's per-permutation hash is exactly
    #
    #   h_p(s') = h_p(s) + Σ_{touched pos i} [term_p(new_i) − term_p(old_i)]
    #           + Σ_{changed slot k} [bagterm_p(new_k) − bagterm_p(old_k)]
    #
    # (u32 modular addition is associative/commutative, so this is
    # BIT-IDENTICAL to the direct sum — tests/test_codec.py pins it).
    # The engine therefore computes, ONCE per frontier chunk, a table
    # of every parent's per-position terms (one full hash per PARENT),
    # and each candidate only evaluates terms at its action family's
    # statically-known touched-position superset (unchanged positions
    # cancel exactly, so supersets are sound).  At ~4-20 enabled lanes
    # per parent this collapses the per-candidate fingerprint work —
    # the measured dominant phase on the wide membership config
    # (BASELINE.md config #3) — by ~6-10x.
    #
    # Per-family touch supersets are derived from ops/kernels.py (each
    # kernel's masked writes); the bag side is a generic <=2-changed-
    # slot diff (every action sends and/or consumes at most one
    # message each — SURVEY §2.4/§2.5).
    # ==================================================================

    # families whose kernels touch the message bag (ops/kernels.py)
    _BAG_FAMILIES = frozenset((
        "RequestVote", "AppendEntries", "CocDiscard", "Receive",
        "Duplicate", "Drop", "AddNewServer", "DeleteServer"))

    def supports_incremental(self) -> bool:
        """Parent-table memory is O(P * n_pos * B); the big-symmetry
        configs (S=5 -> P=120) blow past the win, and their direct
        salt-permutation path already measured >=1.0x vs native.  The
        orbit-sort path has no per-perm delta algebra at all (π is
        data-dependent, so a parent's terms say nothing about its
        successors'), so sort mode always takes the direct path — the
        engines' ``incremental_fp and supports_incremental()`` gate
        handles every call site."""
        if self.sym_canon == "sort":
            return False
        return len(self.sigmas) <= 24

    def _offsets(self):
        S, Lcap = self.lay.S, self.lay.Lcap
        return dict(ct=0, st=S, vf=2 * S, ci=3 * S, llen=4 * S,
                    log=5 * S, vr=5 * S + S * Lcap,
                    vg=6 * S + S * Lcap, ni=7 * S + S * Lcap,
                    mi=7 * S + S * Lcap + S * S)

    def _perm_mask_P(self, m, sig):
        """m [cap] -> [P, cap]: perm_mask under every sigma at once."""
        out = jnp.zeros((sig.shape[0],) + m.shape, jnp.int32)
        for i in range(self.lay.S):
            out = out | (((m >> i) & 1)[None] << sig[:, i][:, None])
        return out

    def parent_tables(self, svT: Dict) -> Dict:
        """Batch-last parent rows [..., B] -> per-term tables:
        posterm [P,T,n_pos,B], bagterm [P,T,K,B], h [P,T,B].  The same
        arithmetic as _core, with the per-term sums retained."""
        lay, kern = self.lay, self.kern
        S, Lcap, K = lay.S, lay.Lcap, lay.K
        hs = lay.header_shifts
        bag = svT["bag"]                                  # [K, MW, B]
        w0 = bag[:, 0]
        mtype = get_field(w0, hs["mtype"]).astype(jnp.int32)
        src = get_field(w0, hs["msrc"]).astype(jnp.int32)
        dst = get_field(w0, hs["mdst"]).astype(jnp.int32)
        braw = get_field(w0, hs["b"]).astype(jnp.int32)
        clear = U32(0xFFFFFFFF) ^ U32(
            put_field(0xFFFFFFFF, hs["msrc"]) |
            put_field(0xFFFFFFFF, hs["mdst"]) |
            put_field(0xFFFFFFFF, hs["b"]))
        w0_base = w0 & clear
        empty = mtype == 0
        is_coc = mtype == MT_COC
        ebits, epw = lay.entry_bits, lay.entries_per_word
        emask = (1 << ebits) - 1
        ent = jnp.stack([
            ((bag[:, 1 + k // epw] >> (ebits * (k % epw))) & emask)
            .astype(jnp.int32)
            for k in range(lay.Lmax)], axis=1) if lay.msg_words > 1 \
            else jnp.zeros((K, 0) + w0.shape[1:], jnp.int32)
        vmask = (1 << lay.value_bits) - 1

        def split_cfg(e):
            is_cfg = (kern.entry_type(e) == CONFIG_ENTRY) & (e != 0)
            return is_cfg, e & ~jnp.int32(vmask), e & vmask

        ent_cfg, ent_base, ent_pay = split_cfg(ent)
        log = svT["log"]
        log_cfg, log_base, log_pay = split_cfg(log)
        vf = svT["vf"]
        cnt = svT["cnt"].astype(U32)
        const_flat = [svT["ct"], svT["st"], None, svT["ci"], svT["llen"],
                      None, None, None, svT["ni"], svT["mi"]]

        def one_perm(sigma, psalt):
            vfp = jnp.where(vf >= 0,
                            sigma[jnp.clip(vf, 0, S - 1)], NIL)
            vrp = self._perm_mask(svT["vr"], sigma)
            vgp = self._perm_mask(svT["vg"], sigma)
            logp = jnp.where(log_cfg,
                             log_base | self._perm_mask(log_pay, sigma),
                             log)
            pieces = list(const_flat)
            pieces[2], pieces[5], pieces[6], pieces[7] = vfp, logp, vrp, vgp
            flat = jnp.concatenate(
                [p.reshape((-1,) + p.shape[p.ndim - 1:]).astype(U32)
                 for p in pieces])                        # [n_pos, B]
            srcp = sigma[jnp.clip(src, 0, S - 1)]
            dstp = sigma[jnp.clip(dst, 0, S - 1)]
            bp = jnp.where(is_coc,
                           sigma[jnp.clip(braw - 1, 0, S - 1)] + 1, braw)
            w0p = (w0_base |
                   put_field(srcp.astype(U32), hs["msrc"]) |
                   put_field(dstp.astype(U32), hs["mdst"]) |
                   put_field(bp.astype(U32), hs["b"]))
            w0p = jnp.where(empty, w0, w0p)
            entp = jnp.where(ent_cfg,
                             ent_base | self._perm_mask(ent_pay, sigma),
                             ent)
            words = [w0p]
            for w in range(1, lay.msg_words):
                acc = jnp.zeros_like(w0)
                for k in range((w - 1) * epw, min(w * epw, lay.Lmax)):
                    acc = acc | (entp[:, k].astype(U32)
                                 << (ebits * (k % epw)))
                words.append(jnp.where(empty, bag[:, w], acc))
            posterm, bagterm, hsum = [], [], []
            for t in range(self.n_streams):
                pt = fmix32(flat ^ psalt[t][:, None])     # [n_pos, B]
                bs = jnp.asarray(self.bag_salts[t])
                slot = jnp.zeros_like(w0)
                for w in range(lay.msg_words):
                    slot = slot + fmix32(words[w] ^ bs[w])
                bt = cnt * fmix32(slot ^ bs[-1])          # [K, B]
                posterm.append(pt)
                bagterm.append(bt)
                hsum.append(pt.sum(axis=0) + bt.sum(axis=0))
            return (jnp.stack(posterm), jnp.stack(bagterm),
                    jnp.stack(hsum))

        posterm, bagterm, h = jax.vmap(one_perm)(
            jnp.asarray(self.sigmas), jnp.asarray(self.psalts))
        return dict(posterm=posterm, bagterm=bagterm, h=h)

    def _slot_terms(self, words, cnt, sig):
        """One bag slot per candidate (words [MW, cap] u32, cnt [cap])
        -> its per-(perm, stream) bag term [P, T, cap]: the single-slot
        twin of parent_tables' bag reduction."""
        lay = self.lay
        hs = lay.header_shifts
        S = lay.S
        w0 = words[0]
        mtype = get_field(w0, hs["mtype"]).astype(jnp.int32)
        src = get_field(w0, hs["msrc"]).astype(jnp.int32)
        dst = get_field(w0, hs["mdst"]).astype(jnp.int32)
        braw = get_field(w0, hs["b"]).astype(jnp.int32)
        clear = U32(0xFFFFFFFF) ^ U32(
            put_field(0xFFFFFFFF, hs["msrc"]) |
            put_field(0xFFFFFFFF, hs["mdst"]) |
            put_field(0xFFFFFFFF, hs["b"]))
        w0_base = w0 & clear
        empty = mtype == 0
        is_coc = mtype == MT_COC
        ebits, epw = lay.entry_bits, lay.entries_per_word
        emask = (1 << ebits) - 1
        vmask = (1 << lay.value_bits) - 1
        srcp = sig[:, jnp.clip(src, 0, S - 1)]            # [P, cap]
        dstp = sig[:, jnp.clip(dst, 0, S - 1)]
        bp = jnp.where(is_coc[None],
                       sig[:, jnp.clip(braw - 1, 0, S - 1)] + 1,
                       braw[None])
        w0p = (w0_base[None] |
               put_field(srcp.astype(U32), hs["msrc"]) |
               put_field(dstp.astype(U32), hs["mdst"]) |
               put_field(bp.astype(U32), hs["b"]))
        w0p = jnp.where(empty[None], w0[None], w0p)       # [P, cap]
        wordsp = [w0p]
        if lay.msg_words > 1:
            ent = [((words[1 + k // epw] >> (ebits * (k % epw))) & emask)
                   .astype(jnp.int32) for k in range(lay.Lmax)]
            for w in range(1, lay.msg_words):
                acc = jnp.zeros_like(w0p)
                for k in range((w - 1) * epw, min(w * epw, lay.Lmax)):
                    e = ent[k]
                    is_cfg = (self.kern.entry_type(e) == CONFIG_ENTRY) \
                        & (e != 0)
                    ep = jnp.where(is_cfg[None],
                                   (e & ~jnp.int32(vmask))[None] |
                                   self._perm_mask_P(e & vmask, sig),
                                   e[None])
                    acc = acc | (ep.astype(U32) << (ebits * (k % epw)))
                wordsp.append(jnp.where(empty[None], words[w][None],
                                        acc))
        out = []
        cntu = cnt.astype(U32)
        for t in range(self.n_streams):
            bs = jnp.asarray(self.bag_salts[t])
            slot = jnp.zeros_like(w0p)
            for w in range(lay.msg_words):
                slot = slot + fmix32(wordsp[w] ^ bs[w])
            out.append(cntu[None] * fmix32(slot ^ bs[-1]))
        return jnp.stack(out, axis=1)                     # [P, T, cap]

    def family_delta(self, name: str, tables: Dict, b_idx, parT: Dict,
                     candT: Dict, params) -> jnp.ndarray:
        """Per-candidate per-permutation hashes [P, T, cap] for one
        action family's buffer rows: parent hash + touched-term deltas.
        parT/candT are batch-last [..., cap]; b_idx maps rows to the
        chunk's parent index (tables' B axis).  Touch supersets follow
        ops/kernels.py's masked writes; unchanged positions cancel."""
        lay = self.lay
        S, Lcap, K = lay.S, lay.Lcap, lay.K
        hs = lay.header_shifts
        OFF = self._offsets()
        cap = b_idx.shape[0]
        r = jnp.arange(cap)
        sig = jnp.asarray(self.sigmas)                    # [P, S]
        psal = jnp.asarray(self.psalts)                   # [P, T, n_pos]

        if name in ("UpdateTerm", "CocDiscard", "Receive",
                    "Duplicate", "Drop"):
            k = params[0]
            w0 = parT["bag"][k, 0, r]
            i = get_field(w0, hs["mdst"]).astype(jnp.int32)
            j = get_field(w0, hs["msrc"]).astype(jnp.int32)
        else:
            i = params[0]
            j = params[1] if len(params) > 1 else None

        touches = []                   # (kind, pos [cap], newval [cap])

        def t_plain(key, a, pos):
            touches.append(("plain", pos, candT[key][a, r]))

        def t_mask(key, a, pos):
            touches.append(("mask", pos, candT[key][a, r]))

        if name == "Restart":
            t_plain("st", i, OFF["st"] + i)
            t_mask("vr", i, OFF["vr"] + i)
            t_mask("vg", i, OFF["vg"] + i)
            t_plain("ci", i, OFF["ci"] + i)
            for jj in range(S):
                touches.append(("plain", OFF["ni"] + i * S + jj,
                                candT["ni"][i, jj, r]))
                touches.append(("plain", OFF["mi"] + i * S + jj,
                                candT["mi"][i, jj, r]))
        elif name == "Timeout":
            t_plain("ct", i, OFF["ct"] + i)
            t_plain("st", i, OFF["st"] + i)
            touches.append(("vf", OFF["vf"] + i, candT["vf"][i, r]))
            t_mask("vr", i, OFF["vr"] + i)
            t_mask("vg", i, OFF["vg"] + i)
        elif name == "BecomeLeader":
            t_plain("st", i, OFF["st"] + i)
            for jj in range(S):
                touches.append(("plain", OFF["ni"] + i * S + jj,
                                candT["ni"][i, jj, r]))
                touches.append(("plain", OFF["mi"] + i * S + jj,
                                candT["mi"][i, jj, r]))
        elif name == "ClientRequest":
            t_plain("llen", i, OFF["llen"] + i)
            lpos = jnp.clip(parT["llen"][i, r], 0, Lcap - 1)
            touches.append(("logent", OFF["log"] + i * Lcap + lpos,
                            candT["log"][i, lpos, r]))
        elif name == "AdvanceCommitIndex":
            t_plain("ci", i, OFF["ci"] + i)
        elif name == "AddNewServer":
            t_plain("ct", j, OFF["ct"] + j)
            touches.append(("vf", OFF["vf"] + j, candT["vf"][j, r]))
        elif name == "UpdateTerm":
            t_plain("ct", i, OFF["ct"] + i)
            t_plain("st", i, OFF["st"] + i)
            touches.append(("vf", OFF["vf"] + i, candT["vf"][i, r]))
        elif name == "Receive":
            t_plain("ct", i, OFF["ct"] + i)
            t_plain("st", i, OFF["st"] + i)
            touches.append(("vf", OFF["vf"] + i, candT["vf"][i, r]))
            t_plain("ci", i, OFF["ci"] + i)
            t_plain("llen", i, OFF["llen"] + i)
            t_mask("vr", i, OFF["vr"] + i)
            t_mask("vg", i, OFF["vg"] + i)
            jc = jnp.clip(j, 0, S - 1)
            touches.append(("plain", OFF["ni"] + i * S + jc,
                            candT["ni"][i, jc, r]))
            touches.append(("plain", OFF["mi"] + i * S + jc,
                            candT["mi"][i, jc, r]))
            for ll in range(Lcap):
                touches.append(("logent", OFF["log"] + i * Lcap + ll,
                                candT["log"][i, ll, r]))
        # RequestVote / AppendEntries / DeleteServer / CocDiscard /
        # Duplicate / Drop: bag-only

        vmask = (1 << lay.value_bits) - 1
        delta = jnp.zeros((len(self.sigmas), self.n_streams, cap), U32)
        for kind, pos, val in touches:
            pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (cap,))
            old = tables["posterm"][:, :, pos, b_idx]     # [P, T, cap]
            saltv = psal[:, :, pos]                       # [P, T, cap]
            if kind == "plain":
                newv = jnp.broadcast_to(val.astype(U32)[None],
                                        (len(self.sigmas), cap))
            elif kind == "vf":
                newv = jnp.where(val[None] >= 0,
                                 sig[:, jnp.clip(val, 0, S - 1)],
                                 NIL).astype(U32)
            elif kind == "mask":
                newv = self._perm_mask_P(val, sig).astype(U32)
            else:                                         # logent
                is_cfg = (self.kern.entry_type(val) == CONFIG_ENTRY) \
                    & (val != 0)
                newv = jnp.where(
                    is_cfg[None],
                    (val & ~jnp.int32(vmask))[None] |
                    self._perm_mask_P(val & vmask, sig),
                    val[None]).astype(U32)
            delta = delta + (fmix32(newv[:, None] ^ saltv) - old)

        if name in self._BAG_FAMILIES:
            bagp = parT["bag"]                            # [K, MW, cap]
            bagc = candT["bag"]
            diff = jnp.any(bagp != bagc, axis=1) | \
                (parT["cnt"] != candT["cnt"])             # [K, cap]
            k0 = jnp.argmax(diff, axis=0)
            d0 = diff[k0, r]
            diff2 = diff & (jnp.arange(K)[:, None] != k0[None])
            k1 = jnp.argmax(diff2, axis=0)
            d1 = diff2[k1, r]
            bag_t = jnp.moveaxis(bagc, 1, 0)              # [MW, K, cap]
            for km, dm in ((k0, d0), (k1, d1)):
                old = tables["bagterm"][:, :, km, b_idx]
                new = self._slot_terms(bag_t[:, km, r],
                                       candT["cnt"][km, r], sig)
                delta = delta + jnp.where(dm[None, None], new - old, 0)

        return tables["h"][:, :, b_idx] + delta

    def finish_min(self, h_all) -> jnp.ndarray:
        """[P, T, ...] per-perm hashes -> sealed canonical fingerprint
        [T, ...] (same lexmin + sentinel remap as the direct path)."""
        return self._seal(self._lex_min(h_all))


# ---------------------------------------------------------------------------
# Per-server signature kernel (SpecIR ``server_signature`` hook, raft
# implementation; spec/paxos/fingerprint.paxos_acceptor_signature is
# the paxos twin).  The contract: sig[S, B] u32, permutation-
# EQUIVARIANT — sig(relabel(s, σ))[σ(i)] == sig(s)[i] for every σ in
# the symmetry group — so sorting by signature commutes with
# relabeling and the sorted representative is orbit-canonical.  Every
# component below is a per-server invariant: own scalar row state,
# self/NIL classes of votedFor, popcount+own-bit of the vote masks and
# ConfigEntry payloads (the full bit pattern is NOT equivariant — bit
# j moves under σ), row/column value multisets of nextIndex /
# matchIndex, and the multiset of label-blanked message contents that
# reference the server as src / dst / CoC-subject.  Two rounds of
# 1-WL color refinement then fold NEIGHBOR colors over the label
# relations (votedFor edges both directions, vote-mask bits both
# directions, ni/mi cells keyed by value), separating servers that
# agree on local counts but differ in who they point at.  Signature
# strength is a PERFORMANCE knob only — correctness never depends on
# it (the certificate + min-over-perms fallback in _core_sort is what
# pins the partition).
# ---------------------------------------------------------------------------


def _popc(m, nbits: int):
    """Population count over the low ``nbits`` bits (static loop)."""
    pc = jnp.zeros_like(m)
    for i in range(nbits):
        pc = pc + ((m >> i) & 1)
    return pc


def _refine_colors(fpr, svT: Dict, c, rnd: int):
    """One 1-WL round: fold each server's neighbors' colors over the
    label-carrying relations, keyed by relation and direction."""
    S = fpr.lay.S
    ar0 = jnp.arange(S, dtype=jnp.int32)
    agg = fmix32(c * U32(0x9E3779B1) + U32(0x7FEB352D + 0x45D9F3B * rnd))
    vf = svT["vf"]
    tgt = jnp.take_along_axis(c, jnp.clip(vf, 0, S - 1), axis=0)
    agg = agg + jnp.where(vf >= 0, fmix32(tgt ^ U32(0x2C1B3C6D)),
                          U32(0x297A2D39))
    inm = vf[None, :, :] == ar0[:, None, None]          # [S_i, S_j, B]
    agg = agg + jnp.sum(inm.astype(U32)
                        * fmix32(c ^ U32(0xD35A2D97))[None], axis=1)
    for key, so, si in (("vr", 0x9F3B5389, 0x6F68F2CD),
                        ("vg", 0xB92E5B2B, 0x186A3C6B)):
        m = svT[key]
        bits = ((m[:, None, :] >> ar0[None, :, None]) & 1)  # bit j of m[i]
        agg = agg + jnp.sum(bits.astype(U32)
                            * fmix32(c ^ U32(so))[None], axis=1)
        agg = agg + jnp.sum(jnp.swapaxes(bits, 0, 1).astype(U32)
                            * fmix32(c ^ U32(si))[None], axis=1)
    for key, s1, s2 in (("ni", 0x8DA6B343, 0xD8163841),
                        ("mi", 0xCB1AB31F, 0x41C64E6D)):
        M = svT[key].astype(U32)
        agg = agg + jnp.sum(fmix32(c[None] ^ fmix32(M ^ U32(s1))),
                            axis=1)
        agg = agg + jnp.sum(
            fmix32(c[None] ^ fmix32(jnp.swapaxes(M, 0, 1) ^ U32(s2))),
            axis=1)
    return fmix32(agg)


def raft_server_signature(fpr, svT: Dict, prep: Dict) -> jnp.ndarray:
    """Raft ``server_signature`` hook body (docstring above): batch-
    last views + the fingerprinter's _prep dict -> sig u32[S, B]."""
    lay = fpr.lay
    S = lay.S

    def U(x):
        return x.astype(U32)

    ar1 = jnp.arange(S, dtype=jnp.int32)[:, None]        # [S, 1]
    c = fmix32(U(svT["ct"]) ^ U32(0x6B79D8A5))
    c = fmix32(c + U(svT["st"]) * U32(0x9E3779B1))
    c = fmix32(c + U(svT["ci"]) * U32(0x85EBCA77))
    c = fmix32(c + U(svT["llen"]) * U32(0xC2B2AE3D))
    vf = svT["vf"]
    c = fmix32(c + U(vf == ar1) * U32(0x27D4EB2F)
               + U(vf < 0) * U32(0x165667B1))
    for key, k1, k2 in (("vr", 0x94D049BB, 0xBF58476D),
                        ("vg", 0x2545F491, 0xD6E8FEB8)):
        m = svT[key]
        c = fmix32(c + U(_popc(m, S)) * U32(k1)
                   + U((m >> ar1) & 1) * U32(k2))
    # log: order-preserving entry fold; ConfigEntry payloads (server-
    # set bitmasks) reduce to their invariants (popcount + own bit)
    ar2 = ar1[:, None]                                   # [S, 1, 1]
    entc = jnp.where(
        prep["log_cfg"],
        U(prep["log_base"])
        + U(_popc(prep["log_pay"], S)) * U32(0xFF51AFD7)
        + U((prep["log_pay"] >> ar2) & 1) * U32(0xC4CEB9FE),
        U(prep["log"]))
    lsalt = jnp.asarray(fpr._log_sig_salts)[None, :, None]
    c = fmix32(c + jnp.sum(fmix32(entc ^ lsalt), axis=1))
    # ni/mi: row/column value multisets + the diagonal
    ar0 = jnp.arange(S)
    for key, s1, s2, s3 in (("ni", 0x0AF63B71, 0x9C06FAF1, 0x4B7F1897),
                            ("mi", 0x71D67FFF, 0xFD7046C5, 0xABA98398)):
        M = U(svT[key])                                  # [S, S, B]
        c = fmix32(c + jnp.sum(fmix32(M ^ U32(s1)), axis=1))
        c = fmix32(c + jnp.sum(fmix32(M ^ U32(s2)), axis=0))
        c = fmix32(c ^ fmix32(M[ar0, ar0] * U32(s3)))
    # message bag: each live slot's label-blanked content hash, counted
    # into the multisets of the servers it references (src / dst /
    # CoC subject).  Entry-payload MEMBERSHIP is deliberately not
    # folded — states differing only there tie and ride the fallback.
    slot = fmix32(U(prep["w0_base"]) ^ U32(0xE6546B64))
    for k in range(lay.Lmax):
        ek = jnp.where(
            prep["ent_cfg"][:, k],
            U(prep["ent_base"][:, k])
            + U(_popc(prep["ent_pay"][:, k], S)) * U32(0x5BD1E995),
            U(prep["ent"][:, k]))
        slot = fmix32(slot + ek * U32(0x38B34AE5 + 2 * k))
    term = prep["cnt"] * U(~prep["empty"])               # [K, B]
    ark = jnp.arange(S, dtype=jnp.int32)[:, None, None]  # [S, 1, 1]
    for fld, ks in ((prep["src"], 0x632BE5AB),
                    (prep["dst"], 0x85157AF5)):
        w = term * fmix32(slot ^ U32(ks))
        msk = fld[None] == ark                           # [S, K, B]
        c = fmix32(c + jnp.sum(U(msk) * w[None], axis=1))
    wb = term * U(prep["is_coc"]) * fmix32(slot ^ U32(0x3C6EF372))
    mskb = (prep["braw"] - 1)[None] == ark
    c = fmix32(c + jnp.sum(U(mskb) * wb[None], axis=1))
    # per-block salt: σ fixes the InitServer blocks, so equal-looking
    # servers in different blocks must never tie
    c = c ^ jnp.asarray(fpr._blk_salt)[:, None]
    for rnd in range(2):
        c = _refine_colors(fpr, svT, c, rnd)
    return c


# ---------------------------------------------------------------------------
# Pallas probe/claim-insert dedup kernel (the MXU-path third piece,
# round 9).  The lax formulation (engine/bfs._probe_insert_lax) round-
# trips every probe outcome through XLA gather/scatter ops — each outer
# iteration is a walk (gathers) plus a 4-scatter resolve round, with
# the whole FCAP lane vector re-materialized between them.  This kernel
# fuses the entire probe → compare → claim walk per candidate block
# into ONE device kernel: the table stays resident, each lane walks its
# quadratic probe path with scalar loads and claims an empty slot with
# an in-kernel store.
#
# Determinism/CAS note: lanes are processed in ascending index order
# inside one sequential kernel, which IS the lax path's rank tie-break
# (every engine passes ranks = jnp.arange(M)), so outcomes — fresh
# set, final slots, table contents — are bit-identical to the parallel
# claim/scatter-min formulation (the parallel loop converges to exactly
# the sequential-by-rank fixpoint; _host_probe_assign is the same
# sequential twin on host).  tests/test_guard_matmul.py pins kernel ≡
# lax on forced-collision fixtures.
#
# interpret=True is the CPU fallback: tier-1 and the oracle
# differential tests run the kernel through the Pallas interpreter
# (dedup_kernel="on" off-TPU), so the TPU path's semantics are pinned
# without TPU hardware attached.
# ---------------------------------------------------------------------------


def probe_claim_insert_pallas(table, keys, live, *, max_rounds: int,
                              interpret: bool):
    """Drop-in for the lax claim-insert (ranks == arange contract —
    see engine/bfs._probe_insert): (table W×u32[VCAP], keys W×u32[M],
    live bool[M]) -> (table', fresh bool[M], pos i32[M], hovf bool)."""
    from functools import reduce

    from jax.experimental import pallas as pl

    from ..utils import HOME_SALT

    W = len(table)
    VCAP = int(table[0].shape[0])
    M = int(keys[0].shape[0])
    tbl = jnp.stack(table)                      # [W, VCAP]
    ks = jnp.stack(keys)                        # [W, M]

    def kernel(ks_ref, live_ref, tbl_in_ref, tbl_ref, fresh_ref,
               pos_ref, hovf_ref):
        # tbl_in_ref aliases tbl_ref (input_output_aliases): all table
        # reads/writes go through the OUTPUT ref so the walk always
        # sees its own earlier claims.
        del tbl_in_ref

        def lane(m, hovf):
            lk = [ks_ref[w, m] for w in range(W)]
            h = jnp.uint32(HOME_SALT)
            for w in range(W):
                h = fmix32(h ^ lk[w])
            pos0 = (h & jnp.uint32(VCAP - 1)).astype(jnp.int32)
            is_live = live_ref[m] != 0

            def cond(st):
                pos, t, resolved, fresh, rounds = st
                return ~resolved & (rounds < max_rounds)

            def body(st):
                pos, t, resolved, fresh, rounds = st
                cur = [tbl_ref[w, pos] for w in range(W)]
                iskey = reduce(lambda a, b: a & b,
                               [cur[w] == lk[w] for w in range(W)])
                isempty = reduce(lambda a, b: a & b,
                                 [cur[w] == jnp.uint32(0xFFFFFFFF)
                                  for w in range(W)])
                claim = isempty & ~iskey

                @pl.when(claim)
                def _():
                    for w in range(W):
                        tbl_ref[w, pos] = lk[w]

                resolved2 = iskey | isempty
                adv = ~resolved2
                t2 = jnp.where(adv, t + 1, t)
                pos2 = jnp.where(adv, (pos + t2) & (VCAP - 1), pos)
                return (pos2, t2, resolved2, fresh | claim,
                        rounds + 1)

            pos, _t, resolved, fresh, _r = jax.lax.while_loop(
                cond, body,
                (pos0, jnp.int32(0), ~is_live, jnp.bool_(False),
                 jnp.int32(0)))
            fresh_ref[m] = (is_live & fresh).astype(jnp.int32)
            pos_ref[m] = pos
            # budget blown with the lane unresolved: table too full —
            # the caller grows + rehashes + replays, like the lax path
            return hovf | (is_live & ~resolved)

        hovf = jax.lax.fori_loop(0, M, lane, jnp.bool_(False))
        hovf_ref[0] = hovf.astype(jnp.int32)

    out_tbl, fresh, pos, hovf = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((W, VCAP), jnp.uint32),
            jax.ShapeDtypeStruct((M,), jnp.int32),
            jax.ShapeDtypeStruct((M,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        input_output_aliases={2: 0},
        interpret=interpret,
    )(ks, live.astype(jnp.int32), tbl)
    return (tuple(out_tbl[w] for w in range(W)), fresh != 0, pos,
            hovf[0] != 0)


# ---------------------------------------------------------------------------
# Best-effort novelty Bloom filter (sim/walker.py): the random-walk
# engine cannot afford an authoritative visited set (walkers revisit
# states by design), but a Bloom filter over the SAME symmetry-canonical
# fingerprints the exhaustive engines dedup on gives an estimated
# distinct-state coverage for ~1 bit/slot.  The k probe positions come
# straight from the fingerprint's independent u32 streams (remixed when
# k exceeds the stream count), so sim and BFS agree on state identity.
# ---------------------------------------------------------------------------

def bloom_positions(fp, m_bits: int, k: int = 2) -> jnp.ndarray:
    """Canonical fingerprints [n_streams, B] u32 -> [k, B] int32 bit
    positions into a 2^m_bits Bloom array."""
    T = fp.shape[0]
    out = []
    for j in range(k):
        h = fp[j % T]
        if j >= T:            # remix re-used streams with a round salt
            h = fmix32(h ^ U32((0x9E3779B9 * (j // T)) & 0xFFFFFFFF))
        out.append((h & U32((1 << m_bits) - 1)).astype(jnp.int32))
    return jnp.stack(out)


def bloom_estimate(bits_set: int, m_bits: int, k: int = 2) -> float:
    """Standard Bloom cardinality estimate n̂ = -(m/k)·ln(1 - X/m).
    A saturated filter (X == m) clamps to X = m-1, i.e. (m/k)·ln m —
    an arbitrary ceiling, not an estimate; callers must surface the
    saturation flag (SimResult.bloom_saturated) instead of trusting
    the number there."""
    m = float(1 << m_bits)
    x = float(min(bits_set, (1 << m_bits) - 1))
    return -(m / k) * float(np.log1p(-x / m))


# canonical dedup-key bit layout lives in utils (host helpers);
# re-exported here for back-compat with older imports
from ..utils import combine_u64  # noqa: E402,F401
