"""Symmetry-aware state fingerprints (VIEW + SYMMETRY semantics).

State identity follows the reference model's TLC declarations
(tlc_membership/raft.cfg:29-30): the fingerprint covers only the 10
semantic variables (``VIEW vars`` — history/features excluded, SURVEY
§2.2) and is canonical under server relabeling (``SYMMETRY perms``,
raft.tla:1281) by taking the minimum over the permutation group of a
64-bit hash of the relabeled view:

  fp(s) = min_{σ ∈ G} H(relabel(s, σ))

G is the subgroup of Permutations(Server) fixing InitServer setwise —
Permutations(Server) as the reference declares would be unsound when
InitServer ⊊ Server (models/explore.py symmetry_perms is the oracle twin).

H hashes positional fields with per-position salts and the message bag
**commutatively** (Σ over slots of count · mix(slot)), so bag slot order
— or a message split across slots — never affects identity and no
canonical bag sort exists anywhere in the engine (ops/layout.py).

Hot-path formulation (the engine fingerprints every fresh candidate, so
this dominated profiles): because the positional hash is a commutative
sum Σ_t fmix(relabeled[t] ^ salt[t]), relabeling the *state* is
equivalent to permuting the *salts*:

  Σ_t fmix(view(σ(s))[t] ^ salt[t])  =  Σ_p fmix(content_σ(s)[p] ^ salt[σ(p)])

so instead of gathering every state array through the inverse
permutation per σ (the old formulation — P gathers of the whole state
per candidate), the engine precomputes P statically-permuted salt
tables at init and hashes the state IN PLACE.  Only fields whose
*values* carry server labels still need per-σ work: votedFor, the
vote bitmasks, ConfigEntry payloads, and message src/dst/mserver.
Message slots are unpacked ONCE (perm-independent) and per σ only the
three label fields are re-packed into the header word.  The resulting
fingerprints are bit-identical to the naive relabel-then-hash form
(tests/test_codec.py asserts batch/per-state identity; the engine's
differential suites pin the semantics).

64-bit fingerprints are two independent 32-bit murmur-finalizer streams
(no jax x64 dependency); ``fp128`` doubles the streams (SURVEY §7.4
hard part 4: TLC-style collision odds vs exhaustiveness claims).
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..config import CONFIG_ENTRY, MT_COC, NIL, ModelConfig
from ..ops.kernels import RaftKernels
from ..ops.layout import Layout, get_field, put_field

U32 = jnp.uint32


def fmix32(x):
    """murmur3 finalizer on uint32 arrays (wrapping arithmetic)."""
    x = x ^ (x >> 16)
    x = x * U32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * U32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _salts(n: int, stream: int) -> np.ndarray:
    rng = np.random.RandomState(0xC0FFEE + 7919 * stream)
    return rng.randint(0, 1 << 32, size=n, dtype=np.uint32)


def Fingerprinter(cfg):
    """Factory: the active spec's symmetry-canonical fingerprinter
    (``spec_of(cfg).make_fingerprinter`` — RaftFingerprinter below for
    raft, spec/paxos/fingerprint.PaxosFingerprinter for paxos).  Kept
    under the historical class name so every engine/tool call site
    reads unchanged."""
    from ..spec import spec_of
    return spec_of(cfg).make_fingerprinter(cfg)


class RaftFingerprinter:
    def __init__(self, cfg: ModelConfig):
        self.cfg = cfg
        self.lay = Layout(cfg)
        self.kern = RaftKernels(self.lay)
        S, Lcap = self.lay.S, self.lay.Lcap
        self.n_streams = 4 if cfg.fp128 else 2
        # positional salt layout: ct,st,vf,ci,llen | log | vr,vg | ni,mi
        self.n_pos = 5 * S + S * Lcap + 2 * S + 2 * S * S
        self.pos_salts = [_salts(self.n_pos, t) for t in
                          range(self.n_streams)]
        self.bag_salts = [_salts(self.lay.msg_words + 1, 16 + t)
                          for t in range(self.n_streams)]
        if cfg.symmetry:
            # the spec's symmetry group (SpecIR handle — the oracle
            # twin models/explore.symmetry_perms for raft)
            from ..spec import spec_of
            perms = spec_of(cfg).symmetry_perms(cfg)
        else:
            perms = [tuple(range(S))]
        self.sigmas = np.array(perms, dtype=np.int32)           # [P, S]
        # statically permuted salt tables: psalts[p, t, i] is the salt a
        # value at original flat position i hashes against under σ_p —
        # i.e. pos_salts[t][σ_p(position i)]; per-server blocks permute
        # by σ(i), log by (σ(i), l), ni/mi by (σ(i), σ(j)).
        idx = np.empty((len(perms), self.n_pos), dtype=np.int64)
        ar = np.arange(S)
        for p, sig in enumerate(np.asarray(self.sigmas)):
            off = 0
            for _blk in range(5):                        # ct st vf ci llen
                idx[p, off:off + S] = off + sig[ar]
                off += S
            blk = (sig[ar][:, None] * Lcap +
                   np.arange(Lcap)[None, :]).reshape(-1)  # log
            idx[p, off:off + S * Lcap] = off + blk
            off += S * Lcap
            for _blk in range(2):                        # vr vg
                idx[p, off:off + S] = off + sig[ar]
                off += S
            blk2 = (sig[ar][:, None] * S + sig[ar][None, :]).reshape(-1)
            for _blk in range(2):                        # ni mi
                idx[p, off:off + S * S] = off + blk2
                off += S * S
            assert off == self.n_pos
        self.psalts = np.stack(
            [np.stack([self.pos_salts[t][idx[p]]
                       for t in range(self.n_streams)])
             for p in range(len(perms))])          # [P, n_streams, n_pos]

    # ------------------------------------------------------------------

    def _perm_mask(self, m, sigma):
        out = jnp.zeros_like(m)
        for i in range(self.lay.S):
            out = out | (((m >> i) & 1) << sigma[i])
        return out

    # ------------------------------------------------------------------
    # shared hashing core.  svT holds the VIEW arrays with their
    # canonical leading axes ([S], [S,Lcap], [K,MW], [K]) and `nb`
    # trailing batch axes (0 for the per-state path, 1 for the batched
    # engine path — batch axis LAST so position reductions stay major).
    # ------------------------------------------------------------------

    def _core(self, svT: Dict, nb: int) -> jnp.ndarray:
        lay, kern = self.lay, self.kern
        S, Lcap, K = lay.S, lay.Lcap, lay.K
        hs = lay.header_shifts
        tail = (1,) * nb                   # broadcast shape for salts

        # ---- perm-independent precompute (hoisted out of the σ loop) --
        bag = svT["bag"]                                  # [K, MW, ...]
        w0 = bag[:, 0]
        mtype = get_field(w0, hs["mtype"]).astype(jnp.int32)
        src = get_field(w0, hs["msrc"]).astype(jnp.int32)
        dst = get_field(w0, hs["mdst"]).astype(jnp.int32)
        braw = get_field(w0, hs["b"]).astype(jnp.int32)   # stored +1
        clear = U32(0xFFFFFFFF) ^ U32(
            put_field(0xFFFFFFFF, hs["msrc"]) |
            put_field(0xFFFFFFFF, hs["mdst"]) |
            put_field(0xFFFFFFFF, hs["b"]))
        w0_base = w0 & clear
        empty = mtype == 0
        is_coc = mtype == MT_COC
        ebits, epw = lay.entry_bits, lay.entries_per_word
        emask = (1 << ebits) - 1
        ent = jnp.stack([
            ((bag[:, 1 + k // epw] >> (ebits * (k % epw))) & emask)
            .astype(jnp.int32)
            for k in range(lay.Lmax)], axis=1) if lay.msg_words > 1 \
            else jnp.zeros((K, 0) + w0.shape[1:], jnp.int32)  # [K,Lmax,...]
        vmask = (1 << lay.value_bits) - 1

        def split_cfg(e):
            """entry -> (is_cfg, payload-cleared base, payload)."""
            is_cfg = (kern.entry_type(e) == CONFIG_ENTRY) & (e != 0)
            return is_cfg, e & ~jnp.int32(vmask), e & vmask

        ent_cfg, ent_base, ent_pay = split_cfg(ent)
        log = svT["log"]                                  # [S, Lcap, ...]
        log_cfg, log_base, log_pay = split_cfg(log)
        vf = svT["vf"]
        cnt = svT["cnt"].astype(U32)                      # [K, ...]
        const_flat = [svT["ct"], svT["st"], None, svT["ci"], svT["llen"],
                      None, None, None, svT["ni"], svT["mi"]]

        def one_perm(sigma, psalt):
            # ---- label-carrying content, relabeled under σ ----
            vfp = jnp.where(vf >= 0,
                            sigma[jnp.clip(vf, 0, S - 1)], NIL)
            vrp = self._perm_mask(svT["vr"], sigma)
            vgp = self._perm_mask(svT["vg"], sigma)
            logp = jnp.where(log_cfg,
                             log_base | self._perm_mask(log_pay, sigma),
                             log)
            pieces = list(const_flat)
            pieces[2], pieces[5], pieces[6], pieces[7] = vfp, logp, vrp, vgp
            flat = jnp.concatenate(
                [p.reshape((-1,) + p.shape[p.ndim - nb:]).astype(U32)
                 for p in pieces])                        # [n_pos, ...]

            # ---- bag header/entry repack (only label fields change) --
            srcp = sigma[jnp.clip(src, 0, S - 1)]
            dstp = sigma[jnp.clip(dst, 0, S - 1)]
            bp = jnp.where(is_coc,
                           sigma[jnp.clip(braw - 1, 0, S - 1)] + 1, braw)
            w0p = (w0_base |
                   put_field(srcp.astype(U32), hs["msrc"]) |
                   put_field(dstp.astype(U32), hs["mdst"]) |
                   put_field(bp.astype(U32), hs["b"]))
            w0p = jnp.where(empty, w0, w0p)
            entp = jnp.where(ent_cfg,
                             ent_base | self._perm_mask(ent_pay, sigma),
                             ent)
            words = [w0p]
            for w in range(1, lay.msg_words):
                acc = jnp.zeros_like(w0)
                for k in range((w - 1) * epw, min(w * epw, lay.Lmax)):
                    acc = acc | (entp[:, k].astype(U32)
                                 << (ebits * (k % epw)))
                words.append(jnp.where(empty, bag[:, w], acc))

            # ---- per-stream reduction ----
            out = []
            for t in range(self.n_streams):
                h = jnp.sum(fmix32(flat ^ psalt[t].reshape(
                    (self.n_pos,) + tail)), axis=0)
                bs = jnp.asarray(self.bag_salts[t])
                slot = jnp.zeros_like(w0)
                for w in range(lay.msg_words):
                    slot = slot + fmix32(words[w] ^ bs[w])
                h = h + jnp.sum(cnt * fmix32(slot ^ bs[-1]), axis=0)
                out.append(h)
            return jnp.stack(out)                 # [n_streams, ...]

        hs_all = jax.vmap(one_perm)(
            jnp.asarray(self.sigmas),
            jnp.asarray(self.psalts))             # [P, n_streams, ...]
        return self._seal(self._lex_min(hs_all))

    def _seal(self, best):
        """The engines' visited tables use the all-ones key as the
        empty-slot sentinel; an all-ones fingerprint would alias it
        and be re-admitted as fresh on EVERY regeneration (unlike an
        ordinary fp collision, which miscounts once).  Remap it to a
        fixed alternate so the sentinel is unreachable by real keys."""
        allones = jnp.ones(best.shape[1:], bool)
        for t in range(self.n_streams):
            allones = allones & (best[t] == U32(0xFFFFFFFF))
        return best.at[self.n_streams - 1].set(
            jnp.where(allones, U32(0xFFFFFFFE), best[self.n_streams - 1]))

    def fingerprint(self, sv: Dict) -> jnp.ndarray:
        """Single state -> u32[n_streams], min over the symmetry group
        (lexicographic order on the stream vector)."""
        return self._core(sv, nb=0)

    def fingerprint_batch(self, svb: Dict) -> jnp.ndarray:
        """[B, ...] batch -> u32[B, n_streams]; bit-identical to
        vmap(fingerprint) (tests/test_codec.py asserts this) but with
        the batch axis minor so the position reduction vectorizes."""
        svT = {k: jnp.moveaxis(v, 0, -1) for k, v in svb.items()}
        return self._core(svT, nb=1).T            # [B, n_streams]

    def fingerprint_batch_T(self, svT: Dict) -> jnp.ndarray:
        """Batch-LAST twin for the engines' batch-minor hot path:
        [..., B] arrays -> u32[n_streams, B] (no transposes)."""
        return self._core(svT, nb=1)

    def _lex_min(self, hs) -> jnp.ndarray:
        """[P, n_streams, ...] -> [n_streams, ...]: lexicographic min
        over the permutation axis via iterative select (P is small).
        Shared by the per-state and batched entry points so the
        tie-break order can never diverge between them."""
        best = hs[0]
        for p in range(1, hs.shape[0]):
            cand = hs[p]
            less = jnp.zeros(best.shape[1:], bool)
            eq = jnp.ones(best.shape[1:], bool)
            for t in range(self.n_streams):
                less = less | (eq & (cand[t] < best[t]))
                eq = eq & (cand[t] == best[t])
            best = jnp.where(less, cand, best)
        return best

    # ==================================================================
    # Incremental per-action fingerprints (VERDICT r3 #2/#3).
    #
    # Because every stream is a COMMUTATIVE u32 sum of per-position /
    # per-bag-slot terms, a successor's per-permutation hash is exactly
    #
    #   h_p(s') = h_p(s) + Σ_{touched pos i} [term_p(new_i) − term_p(old_i)]
    #           + Σ_{changed slot k} [bagterm_p(new_k) − bagterm_p(old_k)]
    #
    # (u32 modular addition is associative/commutative, so this is
    # BIT-IDENTICAL to the direct sum — tests/test_codec.py pins it).
    # The engine therefore computes, ONCE per frontier chunk, a table
    # of every parent's per-position terms (one full hash per PARENT),
    # and each candidate only evaluates terms at its action family's
    # statically-known touched-position superset (unchanged positions
    # cancel exactly, so supersets are sound).  At ~4-20 enabled lanes
    # per parent this collapses the per-candidate fingerprint work —
    # the measured dominant phase on the wide membership config
    # (BASELINE.md config #3) — by ~6-10x.
    #
    # Per-family touch supersets are derived from ops/kernels.py (each
    # kernel's masked writes); the bag side is a generic <=2-changed-
    # slot diff (every action sends and/or consumes at most one
    # message each — SURVEY §2.4/§2.5).
    # ==================================================================

    # families whose kernels touch the message bag (ops/kernels.py)
    _BAG_FAMILIES = frozenset((
        "RequestVote", "AppendEntries", "CocDiscard", "Receive",
        "Duplicate", "Drop", "AddNewServer", "DeleteServer"))

    def supports_incremental(self) -> bool:
        """Parent-table memory is O(P * n_pos * B); the big-symmetry
        configs (S=5 -> P=120) blow past the win, and their direct
        salt-permutation path already measured >=1.0x vs native."""
        return len(self.sigmas) <= 24

    def _offsets(self):
        S, Lcap = self.lay.S, self.lay.Lcap
        return dict(ct=0, st=S, vf=2 * S, ci=3 * S, llen=4 * S,
                    log=5 * S, vr=5 * S + S * Lcap,
                    vg=6 * S + S * Lcap, ni=7 * S + S * Lcap,
                    mi=7 * S + S * Lcap + S * S)

    def _perm_mask_P(self, m, sig):
        """m [cap] -> [P, cap]: perm_mask under every sigma at once."""
        out = jnp.zeros((sig.shape[0],) + m.shape, jnp.int32)
        for i in range(self.lay.S):
            out = out | (((m >> i) & 1)[None] << sig[:, i][:, None])
        return out

    def parent_tables(self, svT: Dict) -> Dict:
        """Batch-last parent rows [..., B] -> per-term tables:
        posterm [P,T,n_pos,B], bagterm [P,T,K,B], h [P,T,B].  The same
        arithmetic as _core, with the per-term sums retained."""
        lay, kern = self.lay, self.kern
        S, Lcap, K = lay.S, lay.Lcap, lay.K
        hs = lay.header_shifts
        bag = svT["bag"]                                  # [K, MW, B]
        w0 = bag[:, 0]
        mtype = get_field(w0, hs["mtype"]).astype(jnp.int32)
        src = get_field(w0, hs["msrc"]).astype(jnp.int32)
        dst = get_field(w0, hs["mdst"]).astype(jnp.int32)
        braw = get_field(w0, hs["b"]).astype(jnp.int32)
        clear = U32(0xFFFFFFFF) ^ U32(
            put_field(0xFFFFFFFF, hs["msrc"]) |
            put_field(0xFFFFFFFF, hs["mdst"]) |
            put_field(0xFFFFFFFF, hs["b"]))
        w0_base = w0 & clear
        empty = mtype == 0
        is_coc = mtype == MT_COC
        ebits, epw = lay.entry_bits, lay.entries_per_word
        emask = (1 << ebits) - 1
        ent = jnp.stack([
            ((bag[:, 1 + k // epw] >> (ebits * (k % epw))) & emask)
            .astype(jnp.int32)
            for k in range(lay.Lmax)], axis=1) if lay.msg_words > 1 \
            else jnp.zeros((K, 0) + w0.shape[1:], jnp.int32)
        vmask = (1 << lay.value_bits) - 1

        def split_cfg(e):
            is_cfg = (kern.entry_type(e) == CONFIG_ENTRY) & (e != 0)
            return is_cfg, e & ~jnp.int32(vmask), e & vmask

        ent_cfg, ent_base, ent_pay = split_cfg(ent)
        log = svT["log"]
        log_cfg, log_base, log_pay = split_cfg(log)
        vf = svT["vf"]
        cnt = svT["cnt"].astype(U32)
        const_flat = [svT["ct"], svT["st"], None, svT["ci"], svT["llen"],
                      None, None, None, svT["ni"], svT["mi"]]

        def one_perm(sigma, psalt):
            vfp = jnp.where(vf >= 0,
                            sigma[jnp.clip(vf, 0, S - 1)], NIL)
            vrp = self._perm_mask(svT["vr"], sigma)
            vgp = self._perm_mask(svT["vg"], sigma)
            logp = jnp.where(log_cfg,
                             log_base | self._perm_mask(log_pay, sigma),
                             log)
            pieces = list(const_flat)
            pieces[2], pieces[5], pieces[6], pieces[7] = vfp, logp, vrp, vgp
            flat = jnp.concatenate(
                [p.reshape((-1,) + p.shape[p.ndim - 1:]).astype(U32)
                 for p in pieces])                        # [n_pos, B]
            srcp = sigma[jnp.clip(src, 0, S - 1)]
            dstp = sigma[jnp.clip(dst, 0, S - 1)]
            bp = jnp.where(is_coc,
                           sigma[jnp.clip(braw - 1, 0, S - 1)] + 1, braw)
            w0p = (w0_base |
                   put_field(srcp.astype(U32), hs["msrc"]) |
                   put_field(dstp.astype(U32), hs["mdst"]) |
                   put_field(bp.astype(U32), hs["b"]))
            w0p = jnp.where(empty, w0, w0p)
            entp = jnp.where(ent_cfg,
                             ent_base | self._perm_mask(ent_pay, sigma),
                             ent)
            words = [w0p]
            for w in range(1, lay.msg_words):
                acc = jnp.zeros_like(w0)
                for k in range((w - 1) * epw, min(w * epw, lay.Lmax)):
                    acc = acc | (entp[:, k].astype(U32)
                                 << (ebits * (k % epw)))
                words.append(jnp.where(empty, bag[:, w], acc))
            posterm, bagterm, hsum = [], [], []
            for t in range(self.n_streams):
                pt = fmix32(flat ^ psalt[t][:, None])     # [n_pos, B]
                bs = jnp.asarray(self.bag_salts[t])
                slot = jnp.zeros_like(w0)
                for w in range(lay.msg_words):
                    slot = slot + fmix32(words[w] ^ bs[w])
                bt = cnt * fmix32(slot ^ bs[-1])          # [K, B]
                posterm.append(pt)
                bagterm.append(bt)
                hsum.append(pt.sum(axis=0) + bt.sum(axis=0))
            return (jnp.stack(posterm), jnp.stack(bagterm),
                    jnp.stack(hsum))

        posterm, bagterm, h = jax.vmap(one_perm)(
            jnp.asarray(self.sigmas), jnp.asarray(self.psalts))
        return dict(posterm=posterm, bagterm=bagterm, h=h)

    def _slot_terms(self, words, cnt, sig):
        """One bag slot per candidate (words [MW, cap] u32, cnt [cap])
        -> its per-(perm, stream) bag term [P, T, cap]: the single-slot
        twin of parent_tables' bag reduction."""
        lay = self.lay
        hs = lay.header_shifts
        S = lay.S
        w0 = words[0]
        mtype = get_field(w0, hs["mtype"]).astype(jnp.int32)
        src = get_field(w0, hs["msrc"]).astype(jnp.int32)
        dst = get_field(w0, hs["mdst"]).astype(jnp.int32)
        braw = get_field(w0, hs["b"]).astype(jnp.int32)
        clear = U32(0xFFFFFFFF) ^ U32(
            put_field(0xFFFFFFFF, hs["msrc"]) |
            put_field(0xFFFFFFFF, hs["mdst"]) |
            put_field(0xFFFFFFFF, hs["b"]))
        w0_base = w0 & clear
        empty = mtype == 0
        is_coc = mtype == MT_COC
        ebits, epw = lay.entry_bits, lay.entries_per_word
        emask = (1 << ebits) - 1
        vmask = (1 << lay.value_bits) - 1
        srcp = sig[:, jnp.clip(src, 0, S - 1)]            # [P, cap]
        dstp = sig[:, jnp.clip(dst, 0, S - 1)]
        bp = jnp.where(is_coc[None],
                       sig[:, jnp.clip(braw - 1, 0, S - 1)] + 1,
                       braw[None])
        w0p = (w0_base[None] |
               put_field(srcp.astype(U32), hs["msrc"]) |
               put_field(dstp.astype(U32), hs["mdst"]) |
               put_field(bp.astype(U32), hs["b"]))
        w0p = jnp.where(empty[None], w0[None], w0p)       # [P, cap]
        wordsp = [w0p]
        if lay.msg_words > 1:
            ent = [((words[1 + k // epw] >> (ebits * (k % epw))) & emask)
                   .astype(jnp.int32) for k in range(lay.Lmax)]
            for w in range(1, lay.msg_words):
                acc = jnp.zeros_like(w0p)
                for k in range((w - 1) * epw, min(w * epw, lay.Lmax)):
                    e = ent[k]
                    is_cfg = (self.kern.entry_type(e) == CONFIG_ENTRY) \
                        & (e != 0)
                    ep = jnp.where(is_cfg[None],
                                   (e & ~jnp.int32(vmask))[None] |
                                   self._perm_mask_P(e & vmask, sig),
                                   e[None])
                    acc = acc | (ep.astype(U32) << (ebits * (k % epw)))
                wordsp.append(jnp.where(empty[None], words[w][None],
                                        acc))
        out = []
        cntu = cnt.astype(U32)
        for t in range(self.n_streams):
            bs = jnp.asarray(self.bag_salts[t])
            slot = jnp.zeros_like(w0p)
            for w in range(lay.msg_words):
                slot = slot + fmix32(wordsp[w] ^ bs[w])
            out.append(cntu[None] * fmix32(slot ^ bs[-1]))
        return jnp.stack(out, axis=1)                     # [P, T, cap]

    def family_delta(self, name: str, tables: Dict, b_idx, parT: Dict,
                     candT: Dict, params) -> jnp.ndarray:
        """Per-candidate per-permutation hashes [P, T, cap] for one
        action family's buffer rows: parent hash + touched-term deltas.
        parT/candT are batch-last [..., cap]; b_idx maps rows to the
        chunk's parent index (tables' B axis).  Touch supersets follow
        ops/kernels.py's masked writes; unchanged positions cancel."""
        lay = self.lay
        S, Lcap, K = lay.S, lay.Lcap, lay.K
        hs = lay.header_shifts
        OFF = self._offsets()
        cap = b_idx.shape[0]
        r = jnp.arange(cap)
        sig = jnp.asarray(self.sigmas)                    # [P, S]
        psal = jnp.asarray(self.psalts)                   # [P, T, n_pos]

        if name in ("UpdateTerm", "CocDiscard", "Receive",
                    "Duplicate", "Drop"):
            k = params[0]
            w0 = parT["bag"][k, 0, r]
            i = get_field(w0, hs["mdst"]).astype(jnp.int32)
            j = get_field(w0, hs["msrc"]).astype(jnp.int32)
        else:
            i = params[0]
            j = params[1] if len(params) > 1 else None

        touches = []                   # (kind, pos [cap], newval [cap])

        def t_plain(key, a, pos):
            touches.append(("plain", pos, candT[key][a, r]))

        def t_mask(key, a, pos):
            touches.append(("mask", pos, candT[key][a, r]))

        if name == "Restart":
            t_plain("st", i, OFF["st"] + i)
            t_mask("vr", i, OFF["vr"] + i)
            t_mask("vg", i, OFF["vg"] + i)
            t_plain("ci", i, OFF["ci"] + i)
            for jj in range(S):
                touches.append(("plain", OFF["ni"] + i * S + jj,
                                candT["ni"][i, jj, r]))
                touches.append(("plain", OFF["mi"] + i * S + jj,
                                candT["mi"][i, jj, r]))
        elif name == "Timeout":
            t_plain("ct", i, OFF["ct"] + i)
            t_plain("st", i, OFF["st"] + i)
            touches.append(("vf", OFF["vf"] + i, candT["vf"][i, r]))
            t_mask("vr", i, OFF["vr"] + i)
            t_mask("vg", i, OFF["vg"] + i)
        elif name == "BecomeLeader":
            t_plain("st", i, OFF["st"] + i)
            for jj in range(S):
                touches.append(("plain", OFF["ni"] + i * S + jj,
                                candT["ni"][i, jj, r]))
                touches.append(("plain", OFF["mi"] + i * S + jj,
                                candT["mi"][i, jj, r]))
        elif name == "ClientRequest":
            t_plain("llen", i, OFF["llen"] + i)
            lpos = jnp.clip(parT["llen"][i, r], 0, Lcap - 1)
            touches.append(("logent", OFF["log"] + i * Lcap + lpos,
                            candT["log"][i, lpos, r]))
        elif name == "AdvanceCommitIndex":
            t_plain("ci", i, OFF["ci"] + i)
        elif name == "AddNewServer":
            t_plain("ct", j, OFF["ct"] + j)
            touches.append(("vf", OFF["vf"] + j, candT["vf"][j, r]))
        elif name == "UpdateTerm":
            t_plain("ct", i, OFF["ct"] + i)
            t_plain("st", i, OFF["st"] + i)
            touches.append(("vf", OFF["vf"] + i, candT["vf"][i, r]))
        elif name == "Receive":
            t_plain("ct", i, OFF["ct"] + i)
            t_plain("st", i, OFF["st"] + i)
            touches.append(("vf", OFF["vf"] + i, candT["vf"][i, r]))
            t_plain("ci", i, OFF["ci"] + i)
            t_plain("llen", i, OFF["llen"] + i)
            t_mask("vr", i, OFF["vr"] + i)
            t_mask("vg", i, OFF["vg"] + i)
            jc = jnp.clip(j, 0, S - 1)
            touches.append(("plain", OFF["ni"] + i * S + jc,
                            candT["ni"][i, jc, r]))
            touches.append(("plain", OFF["mi"] + i * S + jc,
                            candT["mi"][i, jc, r]))
            for ll in range(Lcap):
                touches.append(("logent", OFF["log"] + i * Lcap + ll,
                                candT["log"][i, ll, r]))
        # RequestVote / AppendEntries / DeleteServer / CocDiscard /
        # Duplicate / Drop: bag-only

        vmask = (1 << lay.value_bits) - 1
        delta = jnp.zeros((len(self.sigmas), self.n_streams, cap), U32)
        for kind, pos, val in touches:
            pos = jnp.broadcast_to(jnp.asarray(pos, jnp.int32), (cap,))
            old = tables["posterm"][:, :, pos, b_idx]     # [P, T, cap]
            saltv = psal[:, :, pos]                       # [P, T, cap]
            if kind == "plain":
                newv = jnp.broadcast_to(val.astype(U32)[None],
                                        (len(self.sigmas), cap))
            elif kind == "vf":
                newv = jnp.where(val[None] >= 0,
                                 sig[:, jnp.clip(val, 0, S - 1)],
                                 NIL).astype(U32)
            elif kind == "mask":
                newv = self._perm_mask_P(val, sig).astype(U32)
            else:                                         # logent
                is_cfg = (self.kern.entry_type(val) == CONFIG_ENTRY) \
                    & (val != 0)
                newv = jnp.where(
                    is_cfg[None],
                    (val & ~jnp.int32(vmask))[None] |
                    self._perm_mask_P(val & vmask, sig),
                    val[None]).astype(U32)
            delta = delta + (fmix32(newv[:, None] ^ saltv) - old)

        if name in self._BAG_FAMILIES:
            bagp = parT["bag"]                            # [K, MW, cap]
            bagc = candT["bag"]
            diff = jnp.any(bagp != bagc, axis=1) | \
                (parT["cnt"] != candT["cnt"])             # [K, cap]
            k0 = jnp.argmax(diff, axis=0)
            d0 = diff[k0, r]
            diff2 = diff & (jnp.arange(K)[:, None] != k0[None])
            k1 = jnp.argmax(diff2, axis=0)
            d1 = diff2[k1, r]
            bag_t = jnp.moveaxis(bagc, 1, 0)              # [MW, K, cap]
            for km, dm in ((k0, d0), (k1, d1)):
                old = tables["bagterm"][:, :, km, b_idx]
                new = self._slot_terms(bag_t[:, km, r],
                                       candT["cnt"][km, r], sig)
                delta = delta + jnp.where(dm[None, None], new - old, 0)

        return tables["h"][:, :, b_idx] + delta

    def finish_min(self, h_all) -> jnp.ndarray:
        """[P, T, ...] per-perm hashes -> sealed canonical fingerprint
        [T, ...] (same lexmin + sentinel remap as the direct path)."""
        return self._seal(self._lex_min(h_all))


# ---------------------------------------------------------------------------
# Pallas probe/claim-insert dedup kernel (the MXU-path third piece,
# round 9).  The lax formulation (engine/bfs._probe_insert_lax) round-
# trips every probe outcome through XLA gather/scatter ops — each outer
# iteration is a walk (gathers) plus a 4-scatter resolve round, with
# the whole FCAP lane vector re-materialized between them.  This kernel
# fuses the entire probe → compare → claim walk per candidate block
# into ONE device kernel: the table stays resident, each lane walks its
# quadratic probe path with scalar loads and claims an empty slot with
# an in-kernel store.
#
# Determinism/CAS note: lanes are processed in ascending index order
# inside one sequential kernel, which IS the lax path's rank tie-break
# (every engine passes ranks = jnp.arange(M)), so outcomes — fresh
# set, final slots, table contents — are bit-identical to the parallel
# claim/scatter-min formulation (the parallel loop converges to exactly
# the sequential-by-rank fixpoint; _host_probe_assign is the same
# sequential twin on host).  tests/test_guard_matmul.py pins kernel ≡
# lax on forced-collision fixtures.
#
# interpret=True is the CPU fallback: tier-1 and the oracle
# differential tests run the kernel through the Pallas interpreter
# (dedup_kernel="on" off-TPU), so the TPU path's semantics are pinned
# without TPU hardware attached.
# ---------------------------------------------------------------------------


def probe_claim_insert_pallas(table, keys, live, *, max_rounds: int,
                              interpret: bool):
    """Drop-in for the lax claim-insert (ranks == arange contract —
    see engine/bfs._probe_insert): (table W×u32[VCAP], keys W×u32[M],
    live bool[M]) -> (table', fresh bool[M], pos i32[M], hovf bool)."""
    from functools import reduce

    from jax.experimental import pallas as pl

    from ..utils import HOME_SALT

    W = len(table)
    VCAP = int(table[0].shape[0])
    M = int(keys[0].shape[0])
    tbl = jnp.stack(table)                      # [W, VCAP]
    ks = jnp.stack(keys)                        # [W, M]

    def kernel(ks_ref, live_ref, tbl_in_ref, tbl_ref, fresh_ref,
               pos_ref, hovf_ref):
        # tbl_in_ref aliases tbl_ref (input_output_aliases): all table
        # reads/writes go through the OUTPUT ref so the walk always
        # sees its own earlier claims.
        del tbl_in_ref

        def lane(m, hovf):
            lk = [ks_ref[w, m] for w in range(W)]
            h = jnp.uint32(HOME_SALT)
            for w in range(W):
                h = fmix32(h ^ lk[w])
            pos0 = (h & jnp.uint32(VCAP - 1)).astype(jnp.int32)
            is_live = live_ref[m] != 0

            def cond(st):
                pos, t, resolved, fresh, rounds = st
                return ~resolved & (rounds < max_rounds)

            def body(st):
                pos, t, resolved, fresh, rounds = st
                cur = [tbl_ref[w, pos] for w in range(W)]
                iskey = reduce(lambda a, b: a & b,
                               [cur[w] == lk[w] for w in range(W)])
                isempty = reduce(lambda a, b: a & b,
                                 [cur[w] == jnp.uint32(0xFFFFFFFF)
                                  for w in range(W)])
                claim = isempty & ~iskey

                @pl.when(claim)
                def _():
                    for w in range(W):
                        tbl_ref[w, pos] = lk[w]

                resolved2 = iskey | isempty
                adv = ~resolved2
                t2 = jnp.where(adv, t + 1, t)
                pos2 = jnp.where(adv, (pos + t2) & (VCAP - 1), pos)
                return (pos2, t2, resolved2, fresh | claim,
                        rounds + 1)

            pos, _t, resolved, fresh, _r = jax.lax.while_loop(
                cond, body,
                (pos0, jnp.int32(0), ~is_live, jnp.bool_(False),
                 jnp.int32(0)))
            fresh_ref[m] = (is_live & fresh).astype(jnp.int32)
            pos_ref[m] = pos
            # budget blown with the lane unresolved: table too full —
            # the caller grows + rehashes + replays, like the lax path
            return hovf | (is_live & ~resolved)

        hovf = jax.lax.fori_loop(0, M, lane, jnp.bool_(False))
        hovf_ref[0] = hovf.astype(jnp.int32)

    out_tbl, fresh, pos, hovf = pl.pallas_call(
        kernel,
        out_shape=[
            jax.ShapeDtypeStruct((W, VCAP), jnp.uint32),
            jax.ShapeDtypeStruct((M,), jnp.int32),
            jax.ShapeDtypeStruct((M,), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.int32),
        ],
        input_output_aliases={2: 0},
        interpret=interpret,
    )(ks, live.astype(jnp.int32), tbl)
    return (tuple(out_tbl[w] for w in range(W)), fresh != 0, pos,
            hovf[0] != 0)


# ---------------------------------------------------------------------------
# Best-effort novelty Bloom filter (sim/walker.py): the random-walk
# engine cannot afford an authoritative visited set (walkers revisit
# states by design), but a Bloom filter over the SAME symmetry-canonical
# fingerprints the exhaustive engines dedup on gives an estimated
# distinct-state coverage for ~1 bit/slot.  The k probe positions come
# straight from the fingerprint's independent u32 streams (remixed when
# k exceeds the stream count), so sim and BFS agree on state identity.
# ---------------------------------------------------------------------------

def bloom_positions(fp, m_bits: int, k: int = 2) -> jnp.ndarray:
    """Canonical fingerprints [n_streams, B] u32 -> [k, B] int32 bit
    positions into a 2^m_bits Bloom array."""
    T = fp.shape[0]
    out = []
    for j in range(k):
        h = fp[j % T]
        if j >= T:            # remix re-used streams with a round salt
            h = fmix32(h ^ U32((0x9E3779B9 * (j // T)) & 0xFFFFFFFF))
        out.append((h & U32((1 << m_bits) - 1)).astype(jnp.int32))
    return jnp.stack(out)


def bloom_estimate(bits_set: int, m_bits: int, k: int = 2) -> float:
    """Standard Bloom cardinality estimate n̂ = -(m/k)·ln(1 - X/m).
    A saturated filter (X == m) clamps to X = m-1, i.e. (m/k)·ln m —
    an arbitrary ceiling, not an estimate; callers must surface the
    saturation flag (SimResult.bloom_saturated) instead of trusting
    the number there."""
    m = float(1 << m_bits)
    x = float(min(bits_set, (1 << m_bits) - 1))
    return -(m / k) * float(np.log1p(-x / m))


# canonical dedup-key bit layout lives in utils (host helpers);
# re-exported here for back-compat with older imports
from ..utils import combine_u64  # noqa: E402,F401
