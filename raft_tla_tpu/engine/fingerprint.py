"""Symmetry-aware state fingerprints (VIEW + SYMMETRY semantics).

State identity follows the reference model's TLC declarations
(tlc_membership/raft.cfg:29-30): the fingerprint covers only the 10
semantic variables (``VIEW vars`` — history/features excluded, SURVEY
§2.2) and is canonical under server relabeling (``SYMMETRY perms``,
raft.tla:1281) by taking the minimum over the permutation group of a
64-bit hash of the relabeled view:

  fp(s) = min_{σ ∈ G} H(relabel(s, σ))

G is the subgroup of Permutations(Server) fixing InitServer setwise —
Permutations(Server) as the reference declares would be unsound when
InitServer ⊊ Server (models/explore.py symmetry_perms is the oracle twin).

H hashes positional fields with per-position salts and the message bag
**commutatively** (Σ over slots of count · mix(slot)), so bag slot order
— or a message split across slots — never affects identity and no
canonical bag sort exists anywhere in the engine (ops/layout.py).

64-bit fingerprints are two independent 32-bit murmur-finalizer streams
(no jax x64 dependency); ``fp128`` doubles the streams (SURVEY §7.4
hard part 4: TLC-style collision odds vs exhaustiveness claims).
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..config import CONFIG_ENTRY, MT_COC, NIL, ModelConfig
from ..models.explore import symmetry_perms
from ..ops.kernels import RaftKernels
from ..ops.layout import Layout

U32 = jnp.uint32


def fmix32(x):
    """murmur3 finalizer on uint32 arrays (wrapping arithmetic)."""
    x = x ^ (x >> 16)
    x = x * U32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * U32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _salts(n: int, stream: int) -> np.ndarray:
    rng = np.random.RandomState(0xC0FFEE + 7919 * stream)
    return rng.randint(0, 1 << 32, size=n, dtype=np.uint32)


class Fingerprinter:
    def __init__(self, cfg: ModelConfig):
        self.cfg = cfg
        self.lay = Layout(cfg)
        self.kern = RaftKernels(self.lay)
        S, Lcap = self.lay.S, self.lay.Lcap
        self.n_streams = 4 if cfg.fp128 else 2
        # positional salt layout: ct,st,vf,ci,llen | log | vr,vg | ni,mi
        self.n_pos = 5 * S + S * Lcap + 2 * S + 2 * S * S
        self.pos_salts = [_salts(self.n_pos, t) for t in
                          range(self.n_streams)]
        self.bag_salts = [_salts(self.lay.msg_words + 1, 16 + t)
                          for t in range(self.n_streams)]
        if cfg.symmetry:
            perms = symmetry_perms(cfg)
        else:
            perms = [tuple(range(S))]
        self.sigmas = np.array(perms, dtype=np.int32)           # [P, S]
        invs = np.zeros_like(self.sigmas)
        for p, sig in enumerate(perms):
            for i, t in enumerate(sig):
                invs[p, t] = i
        self.invs = invs

    # ------------------------------------------------------------------

    def _perm_mask(self, m, sigma):
        out = jnp.zeros_like(m)
        for i in range(self.lay.S):
            out = out | (((m >> i) & 1) << sigma[i])
        return out

    def _perm_entry(self, e, sigma):
        kern = self.kern
        is_cfg = (kern.entry_type(e) == CONFIG_ENTRY) & (e != 0)
        payload = kern.entry_payload(e)
        permuted = kern.pack_entry(kern.entry_term(e), kern.entry_type(e),
                                   self._perm_mask(payload, sigma))
        return jnp.where(is_cfg, permuted, e)

    def _relabel_view(self, sv: Dict, sigma, inv) -> List[jnp.ndarray]:
        """Permuted VIEW as a flat list: positional arrays + (bag, cnt)."""
        kern = self.kern
        vf = sv["vf"][inv]
        vf = jnp.where(vf >= 0, sigma[jnp.clip(vf, 0, self.lay.S - 1)], NIL)
        log = self._perm_entry(sv["log"][inv], sigma)
        positional = [
            sv["ct"][inv], sv["st"][inv], vf, sv["ci"][inv],
            sv["llen"][inv], log,
            self._perm_mask(sv["vr"][inv], sigma),
            self._perm_mask(sv["vg"][inv], sigma),
            sv["ni"][inv][:, inv], sv["mi"][inv][:, inv],
        ]

        def perm_slot(words):
            f = kern.msg_fields(words)
            src = sigma[jnp.clip(f["msrc"], 0, self.lay.S - 1)]
            dst = sigma[jnp.clip(f["mdst"], 0, self.lay.S - 1)]
            b = jnp.where(
                f["mtype"] == MT_COC,
                sigma[jnp.clip(f["b"], 0, self.lay.S - 1)], f["b"])
            ent = self._perm_entry(f["ent"], sigma)
            empty = f["mtype"] == 0
            repacked = kern.pack_msg(f["mtype"], f["mterm"], src, dst,
                                     a=f["a"], b=b, c=f["c"], ent=ent,
                                     entlen=f["entlen"])
            return jnp.where(empty, words, repacked)

        bag = jax.vmap(perm_slot)(sv["bag"])
        return positional, bag

    def _hash_streams(self, positional, bag, cnt) -> jnp.ndarray:
        flat = jnp.concatenate(
            [p.reshape(-1).astype(U32) for p in positional])
        out = []
        for t in range(self.n_streams):
            h = jnp.sum(fmix32(flat ^ jnp.asarray(self.pos_salts[t])))
            bs = jnp.asarray(self.bag_salts[t])
            slot = jnp.zeros((bag.shape[0],), U32)
            for w in range(self.lay.msg_words):
                slot = slot + fmix32(bag[:, w] ^ bs[w])
            h = h + jnp.sum(cnt.astype(U32) * fmix32(slot ^ bs[-1]))
            out.append(h)
        return jnp.stack(out)                        # [n_streams] u32

    def fingerprint(self, sv: Dict) -> jnp.ndarray:
        """Single state -> u32[n_streams], min over the symmetry group
        (lexicographic order on the stream vector)."""

        def one_perm(sigma, inv):
            positional, bag = self._relabel_view(sv, sigma, inv)
            return self._hash_streams(positional, bag, sv["cnt"])

        hs = jax.vmap(one_perm)(jnp.asarray(self.sigmas),
                                jnp.asarray(self.invs))   # [P, streams]
        return self._lex_min(hs)

    def _lex_min(self, hs) -> jnp.ndarray:
        """[P, n_streams, ...] -> [n_streams, ...]: lexicographic min
        over the permutation axis via iterative select (P is small).
        Shared by the per-state and batched entry points so the
        tie-break order can never diverge between them."""
        best = hs[0]
        for p in range(1, hs.shape[0]):
            cand = hs[p]
            less = jnp.zeros(best.shape[1:], bool)
            eq = jnp.ones(best.shape[1:], bool)
            for t in range(self.n_streams):
                less = less | (eq & (cand[t] < best[t]))
                eq = eq & (cand[t] == best[t])
            best = jnp.where(less, cand, best)
        return best

    def _hash_streams_cols(self, positional, bag, cnt) -> jnp.ndarray:
        """Batched twin of _hash_streams with the batch axis LAST:
        positional entries are [..., B], bag is [K, msg_words, B],
        cnt is [K, B]."""
        B = cnt.shape[-1]
        flat = jnp.concatenate(
            [p.astype(U32).reshape(-1, B) for p in positional], axis=0)
        out = []
        for t in range(self.n_streams):
            salts = jnp.asarray(self.pos_salts[t])[:, None]
            h = jnp.sum(fmix32(flat ^ salts), axis=0)
            bs = jnp.asarray(self.bag_salts[t])
            slot = jnp.zeros(cnt.shape, U32)
            for w in range(self.lay.msg_words):
                slot = slot + fmix32(bag[:, w, :] ^ bs[w])
            h = h + jnp.sum(cnt.astype(U32) * fmix32(slot ^ bs[-1]),
                            axis=0)
            out.append(h)
        return jnp.stack(out)                        # [n_streams, B]

    def fingerprint_batch(self, svb: Dict) -> jnp.ndarray:
        """[B, ...] batch -> u32[B, n_streams]; bit-identical to
        vmap(fingerprint) (tests/test_codec.py asserts this) but
        computed with the batch axis minor.  _relabel_view is
        shape-polymorphic — indexing/bit ops act on leading axes — so
        only the hash reduction needs the columns variant.  (Measured
        perf-neutral vs the vmapped form on v5e at S=3 — XLA handles
        the batch-major layout better than expected — but this is the
        engine's canonical batched entry point.)"""
        svT = {k: jnp.moveaxis(v, 0, -1) for k, v in svb.items()}

        def one_perm(sigma, inv):
            positional, bag = self._relabel_view(svT, sigma, inv)
            return self._hash_streams_cols(positional, bag, svT["cnt"])

        hs = jax.vmap(one_perm)(jnp.asarray(self.sigmas),
                                jnp.asarray(self.invs))  # [P, streams, B]
        return self._lex_min(hs).T                   # [B, n_streams]


def combine_u64(fp: np.ndarray) -> np.ndarray:
    """Host side: [N, n_streams] u32 -> [N, n_streams//2] u64 words (or a
    single u64 for the default 2-stream mode)."""
    fp = np.asarray(fp, dtype=np.uint64)
    hi = fp[:, 0::2]
    lo = fp[:, 1::2]
    return (hi << np.uint64(32)) | lo
