"""Symmetry-aware state fingerprints (VIEW + SYMMETRY semantics).

State identity follows the reference model's TLC declarations
(tlc_membership/raft.cfg:29-30): the fingerprint covers only the 10
semantic variables (``VIEW vars`` — history/features excluded, SURVEY
§2.2) and is canonical under server relabeling (``SYMMETRY perms``,
raft.tla:1281) by taking the minimum over the permutation group of a
64-bit hash of the relabeled view:

  fp(s) = min_{σ ∈ G} H(relabel(s, σ))

G is the subgroup of Permutations(Server) fixing InitServer setwise —
Permutations(Server) as the reference declares would be unsound when
InitServer ⊊ Server (models/explore.py symmetry_perms is the oracle twin).

H hashes positional fields with per-position salts and the message bag
**commutatively** (Σ over slots of count · mix(slot)), so bag slot order
— or a message split across slots — never affects identity and no
canonical bag sort exists anywhere in the engine (ops/layout.py).

Hot-path formulation (the engine fingerprints every fresh candidate, so
this dominated profiles): because the positional hash is a commutative
sum Σ_t fmix(relabeled[t] ^ salt[t]), relabeling the *state* is
equivalent to permuting the *salts*:

  Σ_t fmix(view(σ(s))[t] ^ salt[t])  =  Σ_p fmix(content_σ(s)[p] ^ salt[σ(p)])

so instead of gathering every state array through the inverse
permutation per σ (the old formulation — P gathers of the whole state
per candidate), the engine precomputes P statically-permuted salt
tables at init and hashes the state IN PLACE.  Only fields whose
*values* carry server labels still need per-σ work: votedFor, the
vote bitmasks, ConfigEntry payloads, and message src/dst/mserver.
Message slots are unpacked ONCE (perm-independent) and per σ only the
three label fields are re-packed into the header word.  The resulting
fingerprints are bit-identical to the naive relabel-then-hash form
(tests/test_codec.py asserts batch/per-state identity; the engine's
differential suites pin the semantics).

64-bit fingerprints are two independent 32-bit murmur-finalizer streams
(no jax x64 dependency); ``fp128`` doubles the streams (SURVEY §7.4
hard part 4: TLC-style collision odds vs exhaustiveness claims).
"""

from __future__ import annotations

from typing import Dict, List

import jax
import jax.numpy as jnp
import numpy as np

from ..config import CONFIG_ENTRY, MT_COC, NIL, ModelConfig
from ..models.explore import symmetry_perms
from ..ops.kernels import RaftKernels
from ..ops.layout import Layout, get_field, put_field

U32 = jnp.uint32


def fmix32(x):
    """murmur3 finalizer on uint32 arrays (wrapping arithmetic)."""
    x = x ^ (x >> 16)
    x = x * U32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * U32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return x


def _salts(n: int, stream: int) -> np.ndarray:
    rng = np.random.RandomState(0xC0FFEE + 7919 * stream)
    return rng.randint(0, 1 << 32, size=n, dtype=np.uint32)


class Fingerprinter:
    def __init__(self, cfg: ModelConfig):
        self.cfg = cfg
        self.lay = Layout(cfg)
        self.kern = RaftKernels(self.lay)
        S, Lcap = self.lay.S, self.lay.Lcap
        self.n_streams = 4 if cfg.fp128 else 2
        # positional salt layout: ct,st,vf,ci,llen | log | vr,vg | ni,mi
        self.n_pos = 5 * S + S * Lcap + 2 * S + 2 * S * S
        self.pos_salts = [_salts(self.n_pos, t) for t in
                          range(self.n_streams)]
        self.bag_salts = [_salts(self.lay.msg_words + 1, 16 + t)
                          for t in range(self.n_streams)]
        if cfg.symmetry:
            perms = symmetry_perms(cfg)
        else:
            perms = [tuple(range(S))]
        self.sigmas = np.array(perms, dtype=np.int32)           # [P, S]
        # statically permuted salt tables: psalts[p, t, i] is the salt a
        # value at original flat position i hashes against under σ_p —
        # i.e. pos_salts[t][σ_p(position i)]; per-server blocks permute
        # by σ(i), log by (σ(i), l), ni/mi by (σ(i), σ(j)).
        idx = np.empty((len(perms), self.n_pos), dtype=np.int64)
        ar = np.arange(S)
        for p, sig in enumerate(np.asarray(self.sigmas)):
            off = 0
            for _blk in range(5):                        # ct st vf ci llen
                idx[p, off:off + S] = off + sig[ar]
                off += S
            blk = (sig[ar][:, None] * Lcap +
                   np.arange(Lcap)[None, :]).reshape(-1)  # log
            idx[p, off:off + S * Lcap] = off + blk
            off += S * Lcap
            for _blk in range(2):                        # vr vg
                idx[p, off:off + S] = off + sig[ar]
                off += S
            blk2 = (sig[ar][:, None] * S + sig[ar][None, :]).reshape(-1)
            for _blk in range(2):                        # ni mi
                idx[p, off:off + S * S] = off + blk2
                off += S * S
            assert off == self.n_pos
        self.psalts = np.stack(
            [np.stack([self.pos_salts[t][idx[p]]
                       for t in range(self.n_streams)])
             for p in range(len(perms))])          # [P, n_streams, n_pos]

    # ------------------------------------------------------------------

    def _perm_mask(self, m, sigma):
        out = jnp.zeros_like(m)
        for i in range(self.lay.S):
            out = out | (((m >> i) & 1) << sigma[i])
        return out

    # ------------------------------------------------------------------
    # shared hashing core.  svT holds the VIEW arrays with their
    # canonical leading axes ([S], [S,Lcap], [K,MW], [K]) and `nb`
    # trailing batch axes (0 for the per-state path, 1 for the batched
    # engine path — batch axis LAST so position reductions stay major).
    # ------------------------------------------------------------------

    def _core(self, svT: Dict, nb: int) -> jnp.ndarray:
        lay, kern = self.lay, self.kern
        S, Lcap, K = lay.S, lay.Lcap, lay.K
        hs = lay.header_shifts
        tail = (1,) * nb                   # broadcast shape for salts

        # ---- perm-independent precompute (hoisted out of the σ loop) --
        bag = svT["bag"]                                  # [K, MW, ...]
        w0 = bag[:, 0]
        mtype = get_field(w0, hs["mtype"]).astype(jnp.int32)
        src = get_field(w0, hs["msrc"]).astype(jnp.int32)
        dst = get_field(w0, hs["mdst"]).astype(jnp.int32)
        braw = get_field(w0, hs["b"]).astype(jnp.int32)   # stored +1
        clear = U32(0xFFFFFFFF) ^ U32(
            put_field(0xFFFFFFFF, hs["msrc"]) |
            put_field(0xFFFFFFFF, hs["mdst"]) |
            put_field(0xFFFFFFFF, hs["b"]))
        w0_base = w0 & clear
        empty = mtype == 0
        is_coc = mtype == MT_COC
        ebits, epw = lay.entry_bits, lay.entries_per_word
        emask = (1 << ebits) - 1
        ent = jnp.stack([
            ((bag[:, 1 + k // epw] >> (ebits * (k % epw))) & emask)
            .astype(jnp.int32)
            for k in range(lay.Lmax)], axis=1) if lay.msg_words > 1 \
            else jnp.zeros((K, 0) + w0.shape[1:], jnp.int32)  # [K,Lmax,...]
        vmask = (1 << lay.value_bits) - 1

        def split_cfg(e):
            """entry -> (is_cfg, payload-cleared base, payload)."""
            is_cfg = (kern.entry_type(e) == CONFIG_ENTRY) & (e != 0)
            return is_cfg, e & ~jnp.int32(vmask), e & vmask

        ent_cfg, ent_base, ent_pay = split_cfg(ent)
        log = svT["log"]                                  # [S, Lcap, ...]
        log_cfg, log_base, log_pay = split_cfg(log)
        vf = svT["vf"]
        cnt = svT["cnt"].astype(U32)                      # [K, ...]
        const_flat = [svT["ct"], svT["st"], None, svT["ci"], svT["llen"],
                      None, None, None, svT["ni"], svT["mi"]]

        def one_perm(sigma, psalt):
            # ---- label-carrying content, relabeled under σ ----
            vfp = jnp.where(vf >= 0,
                            sigma[jnp.clip(vf, 0, S - 1)], NIL)
            vrp = self._perm_mask(svT["vr"], sigma)
            vgp = self._perm_mask(svT["vg"], sigma)
            logp = jnp.where(log_cfg,
                             log_base | self._perm_mask(log_pay, sigma),
                             log)
            pieces = list(const_flat)
            pieces[2], pieces[5], pieces[6], pieces[7] = vfp, logp, vrp, vgp
            flat = jnp.concatenate(
                [p.reshape((-1,) + p.shape[p.ndim - nb:]).astype(U32)
                 for p in pieces])                        # [n_pos, ...]

            # ---- bag header/entry repack (only label fields change) --
            srcp = sigma[jnp.clip(src, 0, S - 1)]
            dstp = sigma[jnp.clip(dst, 0, S - 1)]
            bp = jnp.where(is_coc,
                           sigma[jnp.clip(braw - 1, 0, S - 1)] + 1, braw)
            w0p = (w0_base |
                   put_field(srcp.astype(U32), hs["msrc"]) |
                   put_field(dstp.astype(U32), hs["mdst"]) |
                   put_field(bp.astype(U32), hs["b"]))
            w0p = jnp.where(empty, w0, w0p)
            entp = jnp.where(ent_cfg,
                             ent_base | self._perm_mask(ent_pay, sigma),
                             ent)
            words = [w0p]
            for w in range(1, lay.msg_words):
                acc = jnp.zeros_like(w0)
                for k in range((w - 1) * epw, min(w * epw, lay.Lmax)):
                    acc = acc | (entp[:, k].astype(U32)
                                 << (ebits * (k % epw)))
                words.append(jnp.where(empty, bag[:, w], acc))

            # ---- per-stream reduction ----
            out = []
            for t in range(self.n_streams):
                h = jnp.sum(fmix32(flat ^ psalt[t].reshape(
                    (self.n_pos,) + tail)), axis=0)
                bs = jnp.asarray(self.bag_salts[t])
                slot = jnp.zeros_like(w0)
                for w in range(lay.msg_words):
                    slot = slot + fmix32(words[w] ^ bs[w])
                h = h + jnp.sum(cnt * fmix32(slot ^ bs[-1]), axis=0)
                out.append(h)
            return jnp.stack(out)                 # [n_streams, ...]

        hs_all = jax.vmap(one_perm)(
            jnp.asarray(self.sigmas),
            jnp.asarray(self.psalts))             # [P, n_streams, ...]
        best = self._lex_min(hs_all)
        # the engines' visited tables use the all-ones key as the
        # empty-slot sentinel; an all-ones fingerprint would alias it
        # and be re-admitted as fresh on EVERY regeneration (unlike an
        # ordinary fp collision, which miscounts once).  Remap it to a
        # fixed alternate so the sentinel is unreachable by real keys.
        allones = jnp.ones(best.shape[1:], bool)
        for t in range(self.n_streams):
            allones = allones & (best[t] == U32(0xFFFFFFFF))
        return best.at[self.n_streams - 1].set(
            jnp.where(allones, U32(0xFFFFFFFE), best[self.n_streams - 1]))

    def fingerprint(self, sv: Dict) -> jnp.ndarray:
        """Single state -> u32[n_streams], min over the symmetry group
        (lexicographic order on the stream vector)."""
        return self._core(sv, nb=0)

    def fingerprint_batch(self, svb: Dict) -> jnp.ndarray:
        """[B, ...] batch -> u32[B, n_streams]; bit-identical to
        vmap(fingerprint) (tests/test_codec.py asserts this) but with
        the batch axis minor so the position reduction vectorizes."""
        svT = {k: jnp.moveaxis(v, 0, -1) for k, v in svb.items()}
        return self._core(svT, nb=1).T            # [B, n_streams]

    def fingerprint_batch_T(self, svT: Dict) -> jnp.ndarray:
        """Batch-LAST twin for the engines' batch-minor hot path:
        [..., B] arrays -> u32[n_streams, B] (no transposes)."""
        return self._core(svT, nb=1)

    def _lex_min(self, hs) -> jnp.ndarray:
        """[P, n_streams, ...] -> [n_streams, ...]: lexicographic min
        over the permutation axis via iterative select (P is small).
        Shared by the per-state and batched entry points so the
        tie-break order can never diverge between them."""
        best = hs[0]
        for p in range(1, hs.shape[0]):
            cand = hs[p]
            less = jnp.zeros(best.shape[1:], bool)
            eq = jnp.ones(best.shape[1:], bool)
            for t in range(self.n_streams):
                less = less | (eq & (cand[t] < best[t]))
                eq = eq & (cand[t] == best[t])
            best = jnp.where(less, cand, best)
        return best


# canonical dedup-key bit layout lives in utils (host helpers);
# re-exported here for back-compat with older imports
from ..utils import combine_u64  # noqa: E402,F401
