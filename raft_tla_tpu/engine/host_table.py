"""Fingerprint-prefix-partitioned visited table in HOST RAM (SURVEY
§7.2 L4; BASELINE.md round-5 "remaining RAM ceilings").

The HBM-resident visited table caps exhaustive runs at ~214M keys
(fp64) / ~107M keys (fp128) on a 16 GB chip next to the streaming spill
segments.  TLC never has this wall: its fingerprint set spills to disk.
This module is the host-RAM counterpart, shaped like the frontier/
bitmap tiling that scales accelerator BFS (PAPERS.md: BLEST,
arxiv 2512.21967; Graph Traversal on Tensor Cores, arxiv 2606.05081):

- the fingerprint space splits by the TOP BITS of stream 0 into ``P``
  power-of-two partitions; each partition is an open-addressing image
  (the same slot layout, home hash and quadratic walk as the device
  table in engine/bfs._probe_insert, so a partition image can be
  shipped to the device and probed by the same discipline);
- per BFS level the engine buckets the level's fresh-candidate keys by
  prefix and sweeps partition-by-partition: partition ``p``'s image
  streams into HBM while ``p+1``'s H2D staging rides the host link
  (the spill engine's double-buffering), the device walks a
  gathers-only membership probe over the level's keys in ``p``, and
  the host appends the surviving (previously-unseen) keys into its
  authoritative image;
- the DEVICE-resident table degrades to a bounded cache of recent
  levels' keys (it can only err fresh-ward — re-admitting an evicted
  key — never suppress a new state), so the exhaustive ceiling moves
  from "total distinct keys fit HBM" to "one partition image + one
  level's keys fit HBM", with total capacity bounded by host RAM at
  20-80 B/key fp64 (8 B/slot images between the 0.40 load bound and
  a fresh 4x growth; no host-side claims array).

Everything here is numpy + one jit'd membership kernel; the
device-streamed orchestration lives in engine/spill (single chip) and
parallel/spill_mesh (per-device tables composed with hash-partitioned
mesh dedup — ownership uses fingerprint stream W-1 mod D, the prefix
uses stream 0's top bits, so the two partitionings are independent and
compose).

First-seen exactness: level keys arrive already deduplicated within
the level (the device cache is complete over the running level) and in
enumeration order, so membership-against-archive is the only decision
left — the kept set and every count are bit-identical to the in-HBM
engine, differentially pinned by tests/test_host_table.py.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ..utils import HOME_SALT, fmix32_np

U32 = np.uint32(0xFFFFFFFF)
_MAX_ROUNDS = 4096


def home_np(keys: np.ndarray, cap: int) -> np.ndarray:
    """Home slots for [N, W] u32 keys in a cap-slot (power-of-two)
    table — bit-identical to engine/bfs Engine._home (same utils
    salt + finalizer, so host images and device probes share one
    probe-walk contract)."""
    h = np.full(keys.shape[0], HOME_SALT, np.uint32)
    for w in range(keys.shape[1]):
        h = fmix32_np(h ^ keys[:, w])
    return (h & np.uint32(cap - 1)).astype(np.int64)


def member_np(img: np.ndarray, keys: np.ndarray) -> np.ndarray:
    """Membership of [N, W] keys in a [W, C] open-addressing image:
    quadratic walk until the key (found) or an empty slot (absent).
    Gathers only; the host twin of the device sweep kernel."""
    N, W = keys.shape
    C = img.shape[1]
    found = np.zeros(N, bool)
    if N == 0:
        return found
    pos = home_np(keys, C)
    t = np.zeros(N, np.int64)
    active = np.ones(N, bool)
    keysT = keys.T
    for _ in range(_MAX_ROUNDS):
        if not active.any():
            break
        cur = img[:, pos]                       # [W, N]
        iskey = (cur == keysT).all(axis=0)
        isempty = (cur == U32).all(axis=0)
        found |= active & iskey
        active &= ~(iskey | isempty)
        t = np.where(active, t + 1, t)
        pos = np.where(active, (pos + t) & (C - 1), pos)
    else:
        if active.any():
            # fail LOUD like insert_np and the device sweep: a lane
            # that neither found its key nor an empty slot in the
            # budget would otherwise read as not-found — and a
            # duplicate commit would silently inflate counts
            raise RuntimeError("host partition membership walk did "
                               "not converge — image pathologically "
                               "full")
    return found


def insert_np(img: np.ndarray, keys: np.ndarray,
              ranks: Optional[np.ndarray] = None) -> None:
    """Insert [N, W] keys (unique, not present) into the image IN
    PLACE — the host twin of the device claim-insert resolve rounds:
    walk to an empty slot, claim by scatter-min of rank, winners write,
    losers re-walk.  Deterministic for a fixed key order."""
    N = keys.shape[0]
    if N == 0:
        return
    C = img.shape[1]
    if ranks is None:
        ranks = np.arange(N, dtype=np.int64)
    else:
        ranks = ranks.astype(np.int64)
    pos = home_np(keys, C)
    t = np.zeros(N, np.int64)
    active = np.ones(N, bool)
    for _ in range(_MAX_ROUNDS):
        if not active.any():
            break
        # walk every active lane to its next empty slot
        for _w in range(_MAX_ROUNDS):
            isempty = (img[:, pos] == U32).all(axis=0)
            moving = active & ~isempty
            if not moving.any():
                break
            t = np.where(moving, t + 1, t)
            pos = np.where(moving, (pos + t) & (C - 1), pos)
        else:
            raise RuntimeError("host partition probe walk did not "
                               "converge — image pathologically full")
        # claim round: min-rank wins each contested empty slot
        claims = np.full(C, np.iinfo(np.int64).max, np.int64)
        np.minimum.at(claims, pos[active], ranks[active])
        won = active & (claims[pos] == ranks)
        img[:, pos[won]] = keys[won].T
        active &= ~won
    else:
        raise RuntimeError("host partition claim rounds did not "
                           "converge — image pathologically full")


class HostPartitionedTable:
    """P prefix-partitioned open-addressing images in host RAM (module
    docstring).

    n_streams  — u32 words per key (2 for fp64, 4 for fp128).
    partitions — P, a power of two; partition id = key stream 0's top
                 log2(P) bits, so the id is a pure function of the key
                 and counts are P-invariant (tests pin P=1 ≡ 4 ≡ 8).
    part_cap   — initial slots per partition image (grows 4x on the
                 0.40 load bound, host-side rehash).
    """

    LOAD_MAX = 0.40

    def __init__(self, n_streams: int, partitions: int = 4,
                 part_cap: int = 1 << 12):
        if partitions & (partitions - 1):
            raise ValueError(f"partitions must be a power of two, "
                             f"got {partitions}")
        part_cap = max(int(part_cap), 1 << 6)
        if part_cap & (part_cap - 1):
            c = 1
            while c < part_cap:
                c *= 2
            part_cap = c
        self.W = int(n_streams)
        self.P = int(partitions)
        self.bits = self.P.bit_length() - 1
        self.imgs: List[np.ndarray] = [
            np.full((self.W, part_cap), U32, np.uint32)
            for _ in range(self.P)]
        self.counts: List[int] = [0] * self.P
        # per-partition mutation version: bumped on every rehash and
        # every commit, so a device-staged copy of an image (the spill
        # engine's double-buffered pre-sweep upload) can verify it is
        # still current before serving membership probes — an aliased
        # or stale upload is discarded, never probed
        self.vers: List[int] = [0] * self.P

    # -- key bucketing -------------------------------------------------

    def partition_ids(self, keys: np.ndarray) -> np.ndarray:
        """[N, W] u32 keys -> int64 partition ids (stream 0 top bits)."""
        if self.bits == 0:
            return np.zeros(keys.shape[0], np.int64)
        return (keys[:, 0] >> np.uint32(32 - self.bits)).astype(np.int64)

    @property
    def n_keys(self) -> int:
        return sum(self.counts)

    @property
    def nbytes(self) -> int:
        return sum(img.nbytes for img in self.imgs)

    def cap(self, p: int) -> int:
        return self.imgs[p].shape[1]

    # -- growth --------------------------------------------------------

    def reserve(self, p: int, add: int) -> bool:
        """Grow partition ``p`` so it can take ``add`` more keys under
        the load bound; returns True when a rehash happened.  Called
        BEFORE a sweep uploads the image, so the device never sees an
        image past its probe budget."""
        cap = self.cap(p)
        need = self.counts[p] + int(add)
        if need <= self.LOAD_MAX * cap:
            return False
        while need > self.LOAD_MAX * cap:
            cap *= 4
        old = self.imgs[p]
        occ = ~(old == U32).all(axis=0)
        keys = old[:, occ].T.copy()              # slot order: stable
        self.imgs[p] = np.full((self.W, cap), U32, np.uint32)
        insert_np(self.imgs[p], keys)
        self.vers[p] += 1
        return True

    # -- host-side sweep (mesh composition + differential tests) -------

    def member(self, keys: np.ndarray) -> np.ndarray:
        """[N, W] keys -> bool[N] already-archived (any partition)."""
        out = np.zeros(keys.shape[0], bool)
        pids = self.partition_ids(keys)
        for p in np.unique(pids):
            sel = pids == p
            out[sel] = member_np(self.imgs[int(p)], keys[sel])
        return out

    def commit(self, keys: np.ndarray, fresh: np.ndarray) -> None:
        """Append ``keys[fresh]`` (unique, verified-absent by a member
        pass) into their partitions, growing under the load bound."""
        keys = keys[fresh]
        pids = self.partition_ids(keys)
        for p in np.unique(pids):
            sel = pids == p
            kp = keys[sel]
            self.reserve(int(p), kp.shape[0])
            insert_np(self.imgs[int(p)], kp)
            self.counts[int(p)] += int(kp.shape[0])
            self.vers[int(p)] += 1

    def sweep(self, keys: np.ndarray) -> np.ndarray:
        """Level sweep, host path: returns keep = ~member and commits
        the kept keys.  ``keys`` must be unique (the engines' device
        cache guarantees level-local uniqueness) and in enumeration
        order."""
        # chaos site: host-partition loss (the partitions live with the
        # host process — a killed host loses them; recovery rebuilds
        # them from the checkpoint's sparse images or, shape-portably,
        # by re-sweeping the visited key set)
        from ..resil.chaos import chaos_point
        chaos_point("host_table")
        seen = self.member(keys)
        self.commit(keys, ~seen)
        return ~seen

    # -- checkpoint serialization (sparse, exact-image restore) --------

    def state_dict(self, prefix: str = "hpt") -> Dict[str, np.ndarray]:
        """Occupied slots + keys per partition: a resume rebuilds the
        EXACT images (no rehash drift), so resumed runs stay
        bit-identical."""
        out = {f"{prefix}|shape": np.array(
            [self.P, self.W] + [self.cap(p) for p in range(self.P)],
            np.int64)}
        for p in range(self.P):
            occ = ~(self.imgs[p] == U32).all(axis=0)
            idx = np.nonzero(occ)[0].astype(np.int64)
            out[f"{prefix}|idx{p}"] = idx
            out[f"{prefix}|keys{p}"] = np.ascontiguousarray(
                self.imgs[p][:, idx])
        return out

    @classmethod
    def from_state(cls, get, prefix: str = "hpt"
                   ) -> "HostPartitionedTable":
        """Rebuild from ``state_dict`` arrays; ``get(name)`` returns the
        stored array (an npz indexer)."""
        shape = np.asarray(get(f"{prefix}|shape"))
        P, W = int(shape[0]), int(shape[1])
        tbl = cls(W, partitions=P, part_cap=int(shape[2]))
        for p in range(P):
            cap = int(shape[2 + p])
            idx = np.asarray(get(f"{prefix}|idx{p}"))
            keys = np.asarray(get(f"{prefix}|keys{p}"))
            img = np.full((W, cap), U32, np.uint32)
            img[:, idx] = keys
            tbl.imgs[p] = img
            tbl.counts[p] = int(idx.shape[0])
        return tbl
