"""Host-spill BFS engine: levels stream through host RAM, breaking the
single-chip HBM exhaustion wall (SURVEY §7.2 L4 "spill/compact to host";
VERDICT r3 #1).

The classic Engine (engine/bfs) keeps the frontier and the level buffer
device-resident, which caps level-exact runs at the deepest level whose
~340 B/state buffers fit HBM next to the visited table (measured:
depth 19 on BASELINE config #2, depth 21 on #1 — BASELINE.md
"exhaustion wall").  TLC never has this wall: its fingerprint set and
state queue spill to disk (`states/`, /root/reference/.gitignore:4).

This engine is the TPU counterpart, shaped by the tunneled-runtime's
transfer economics (big transfers amortize the ~100 ms round trip;
per-chunk scalar syncs do not):

- HBM holds ONLY the visited table (12 B/key at fp64 — the one
  structure whose random-access probes need device residency) plus two
  SEGMENT buffers: a frontier segment being expanded and a level
  segment being filled.
- The frontier lives in host RAM as a list of narrow batch-last
  blocks; segments upload whole (one big H2D per ~SEG states).
- Fresh states append to the level segment on device; when it fills
  (or the level ends) it spills whole to the host (one big D2H),
  becoming both the next-frontier source and the trace archive.
- The host syncs ONE small summary vector every `sync_every` chunks
  (not per chunk): JAX only transfers what is forced, so the
  intermediate summaries are never fetched.

Overflow recovery is CHUNK-local (the classic engine's whole-level
journal replay is impossible once earlier segments have spilled): a
chunk that trips any overflow — level segment full (ovf), family/
compaction caps (fovf), probe-round budget (hovf) — reverts its own
table inserts in-step and leaves no trace; every later chunk in the
sync window sees the sticky flag and does nothing.  The host then
fixes the cause (spill the segment / grow caps / grow+rehash the
table), resets the flags, and resumes from the recorded trip chunk —
enumeration order is exactly preserved, so counts and first-seen
survivors match the classic engine and the oracle bit-for-bit.

Constraint semantics stay prune-not-expand (SURVEY §2.8): pruned rows
are counted, invariant-checked and archived, then dropped on host when
the next frontier is assembled (the classic engine keeps them device-
side under an fmask instead — same reachable set, differentially
tested in tests/test_spill.py).

What this buys: the depth wall moves from "level buffers fit HBM"
(~8.5 GB at depth 20 on config #2) to "visited table fits HBM" —
~12 B/key lets ~400M distinct states on a 16 GB chip, with level
buffers bounded by the 125 GB host.  The native C++ checker OOMs the
same host at ~65 GB RSS (~650 B/state) long before that — BASELINE.md
round-4 records the beyond-the-wall rows this engine produced.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import ModelConfig
from ..ops.codec import C_OVERFLOW
from ..obs import NULL_OBS
from . import driver
from .bfs import (CheckResult, CheckpointError, Engine, U32MAX,
                  _HOME_SALT, Violation, ckpt_read, ckpt_result,
                  ckpt_write)
from .fingerprint import fmix32
from .host_table import HostPartitionedTable, insert_np
from ..resil.chaos import chaos_point

# summary vector layout (int32): the per-window device->host sync
(S_NLVL, S_NGEN, S_OVF, S_FOVF, S_HOVF, S_OOVF, S_TRIP, S_OFX,
 S_LEN) = range(9)


class SpillEngine(Engine):
    """Engine whose frontier/level buffers stream through host RAM.

    chunk      — frontier states expanded per fused device call.
    seg        — level/frontier segment capacity (states); HBM holds
                 ~2 segments x ~340 B/state next to the visited table.
    vcap       — initial visited-table slots (grows by device rehash).
    sync_every — chunks between summary syncs (each sync costs one
                 tunneled round trip; a trip replays at most this many
                 chunks).
    """

    def __init__(self, cfg: ModelConfig, chunk: int = 2048,
                 store_states: bool = False, seg: int = 1 << 21,
                 vcap: int = 1 << 22, fcap: Optional[int] = None,
                 ocap: Optional[int] = None, sync_every: int = 8,
                 host_table: bool = False, partitions: int = 4,
                 part_cap: int = 1 << 12,
                 dev_keys: Optional[int] = None,
                 sweep_stage: bool = True,
                 burst: bool = True,
                 burst_levels: Optional[int] = None,
                 archive_dir: Optional[str] = None,
                 guard_matmul: bool = True,
                 dedup_kernel: str = "auto",
                 delta_matmul: bool = True,
                 fam_density: Optional[Dict[str, int]] = None,
                 sym_canon: str = "auto"):
        # burst (fused multi-level dispatch) is ON by default since
        # round 8 — the tiny early levels of a deep spill run pay the
        # same tunneled dispatch floor as the classic engine's; pass
        # burst=False to force the pure per-level/segment driver
        # (tests/test_burst.py pins the A/B)
        super().__init__(cfg, chunk=chunk, store_states=store_states,
                         lcap=seg, vcap=vcap, fcap=fcap, ocap=ocap,
                         burst=burst, burst_levels=burst_levels,
                         archive_dir=archive_dir,
                         guard_matmul=guard_matmul,
                         dedup_kernel=dedup_kernel,
                         delta_matmul=delta_matmul,
                         fam_density=fam_density,
                         sym_canon=sym_canon)
        self.SEGL = self.LCAP          # level segment rows (can grow)
        self.SEGF = self.LCAP          # frontier segment rows (fixed)
        self.sync_every = max(1, int(sync_every))
        # host-partitioned visited table (VERDICT r4 missing #1;
        # engine/host_table module docstring): the authoritative
        # visited set lives in host RAM as P fingerprint-prefix
        # partitions, swept through HBM partition-by-partition at level
        # boundaries; the HBM table degrades to a bounded CACHE of
        # recent levels' keys.  The cache is complete over the running
        # level (it grows mid-level if it must), so level keys reach
        # the sweep already unique and in enumeration order; the cache
        # can only err fresh-ward (an evicted key re-admitted), never
        # suppress a truly-new state, so the sweep's membership verdict
        # keeps counts EXACT — no collision class is added beyond the
        # fingerprints themselves.  The exhaustive ceiling moves from
        # "total distinct keys fit the HBM table" (~214M fp64 on
        # 16 GB) to "one partition image + one level's keys fit it",
        # bounded by host RAM at 20-80 B/key fp64 (8 B/slot images
        # between the 0.40 load bound and a fresh 4x growth).
        # TLC's disk-spillable fingerprint set is the reference
        # behavior (SURVEY §5).
        self.host_table = bool(host_table)
        self.partitions = int(partitions)
        self.part_cap = int(part_cap)
        self.VCAP0 = self.VCAP         # reseed resets the cache here
        # cache budget: past this occupancy at a level boundary the
        # device table resets and reseeds with the frontier's keys
        # (the only keys the next level's expansion re-generates at
        # high rate); everything older answers from the host sweep
        self.dev_keys = (int(dev_keys) if dev_keys
                         else int(self._LOAD_MAX * self.VCAP))
        self.hpt = None                # built per check()/resume
        # double-buffered pre-sweep H2D staging (round 14): the next
        # level's partition-image uploads are ISSUED at level start, so
        # the DMA rides the host link while the level's chunks compute
        # instead of serializing after them inside the sweep
        # (_stage_sweep_images; h2d_stage/sweep_overlap spans make the
        # overlap visible in the PR-7 ledger/timeline).  At most
        # _SWEEP_STAGE_DEPTH images are in flight (double-buffering —
        # HBM holds the staged image next to the sweep's own working
        # set); a staged image serves a sweep only when its partition's
        # mutation version still matches (host_table.vers), so growth
        # or commit can never hand the device a stale membership image.
        self.sweep_stage = bool(sweep_stage)
        self._sweep_staged = {}        # partition -> (dev_img, version)
        self.sweep_stage_hits = 0      # sweeps served from a prestage
        self.sweep_stage_misses = 0    # inline (serialized) uploads
        self._paste_cache = {}         # upload-paste jit per block size
        self._slice_cache = {}         # spill-slice jit per block size
        self._ckpt_sparse_cache = {}   # sparse-table jit per size
        self._seed_cache = {}          # table-reseed jit per size
        self._member_cache = {}        # sweep-membership jit per shape
        self._sstep_jit = jax.jit(self._spill_step_impl,
                                  donate_argnums=0, static_argnums=1)
        # spill-aware fused multi-level burst (engine/bfs._burst_core
        # over standalone ring buffers — the spill carry's segment
        # shapes never enter the loop).  fcap rides as a static arg:
        # unlike the classic wrapper there is no carry-shape anchor, so
        # an FCAP growth must force a retrace explicitly.
        self._spill_burst_jit = jax.jit(self._spill_burst_call,
                                        donate_argnums=(0, 1),
                                        static_argnums=(7, 8, 9))

    # ------------------------------------------------------------------
    # fused per-chunk step (spill twin of Engine._chunk_step_impl)
    # ------------------------------------------------------------------

    def _spill_step_impl(self, carry, fam_caps):
        """One frontier chunk: expand + fingerprint (shared front half
        _expand_fp_chunk) + claim-insert dedup + invariant/constraint
        eval + append to the level segment.  Returns (carry', summary).

        Chunk-local overflow discipline (module docstring): a chunk
        that trips ovf/fovf/hovf reverts its own inserts and commits
        nothing; `trip_base` records the first tripping chunk's frontier
        cursor so the host can resume exactly there after fixing."""
        B, A, W = self.chunk, self.A, self.W
        SEGL = carry["lpar"].shape[0]
        FCAP = carry["cidx"].shape[0]
        OCAP = carry["oidx"].shape[0]
        VCAP = carry["vis"][0].shape[0]
        base = carry["base"]
        sv = self.ir.widen({k: lax.dynamic_slice_in_dim(v, base, B,
                                                axis=v.ndim - 1)
                    for k, v in carry["front"].items()})
        # no fmask: constraint-pruned rows never enter the frontier
        # (host compacts them away — prune-not-expand is host-side)
        valid = (base + jnp.arange(B, dtype=jnp.int32)) < carry["n_front"]
        cand_c, elive, fp, take, famx_c, n_e = self._expand_fp_chunk(
            sv, valid, fam_caps, FCAP)
        famx = jnp.maximum(carry["famx"], famx_c)
        fovf_now = (n_e > FCAP) | \
            jnp.any(famx_c > jnp.asarray(fam_caps, jnp.int32))
        gate = ~(carry["ovf"] | carry["fovf"] | carry["hovf"] |
                 carry["oovf"])
        live = elive & gate & ~fovf_now

        keys = tuple(jnp.where(live, fp[w], U32MAX) for w in range(W))
        ranks = jnp.arange(FCAP, dtype=jnp.uint32)
        table, claims, fresh, pos, hovf_now = self._probe_insert(
            carry["vis"], carry["claims"], keys, live, ranks)
        n_fresh = fresh.sum(dtype=jnp.int32)
        ovf_now = gate & (carry["n_lvl"] + n_fresh > SEGL - OCAP)
        oovf_now = gate & (n_fresh > OCAP)
        bad_now = gate & (fovf_now | hovf_now | ovf_now | oovf_now)
        # revert THIS chunk's inserts on any trip — the chunk leaves no
        # trace, so the host replay re-runs it bit-identically
        ridx = jnp.where(fresh & bad_now, pos, VCAP)
        table = tuple(table[w].at[ridx].set(U32MAX, mode="drop")
                      for w in range(W))
        fresh = fresh & ~bad_now
        n_fresh = jnp.where(bad_now, 0, n_fresh)
        commit = gate & ~bad_now
        n_gen = carry["n_gen"] + \
            jnp.where(commit, elive.sum(dtype=jnp.int32), 0)
        trip_base = jnp.where(gate & bad_now, base, carry["trip_base"])

        # contiguous append of the fresh rows, post-dedup-compacted to
        # OCAP width (engine/bfs layout + second-compaction notes)
        slot = jnp.arange(FCAP, dtype=jnp.int32)
        lpos = jnp.where(fresh,
                         jnp.cumsum(fresh.astype(jnp.int32)) - 1, OCAP)
        lidx = lax.optimization_barrier(
            jnp.zeros((OCAP,), jnp.int32).at[lpos].set(
                slot, mode="drop"))
        start = jnp.minimum(carry["n_lvl"], SEGL - OCAP)
        lane = take[lidx]
        rows = lax.optimization_barrier(
            {k: cand_c[k][..., lidx] for k in cand_c})
        inv, con = lax.optimization_barrier(self._phase2_T(rows))
        rows_n = self.ir.narrow(self.lay, rows)
        lvl = {k: lax.dynamic_update_slice_in_dim(
                   v, rows_n[k], start, v.ndim - 1)
               for k, v in carry["lvl"].items()}
        # parent ids come from the uploaded per-row global ids (the
        # host-compacted frontier breaks the classic engine's
        # pg_off+row arithmetic)
        lpar = lax.dynamic_update_slice_in_dim(
            carry["lpar"], carry["gids"][base + lane // A], start, 0)
        llane = lax.dynamic_update_slice_in_dim(
            carry["llane"], lane % A, start, 0)
        linv = lax.dynamic_update_slice_in_dim(carry["linv"], inv,
                                               start, 1)
        lcon = lax.dynamic_update_slice_in_dim(
            carry["lcon"], con, start, 0)
        extra = {}
        if self.host_table:
            # the appended rows' fingerprints ride the spill (8 B/state
            # fp64): they feed the host archive check and the device-
            # table reseed at level boundaries
            extra["lfp"] = lax.dynamic_update_slice(
                carry["lfp"], fp[:, lidx], (0, start))
        n_lvl = jnp.minimum(carry["n_lvl"] + n_fresh, SEGL - OCAP)
        ovf = carry["ovf"] | ovf_now
        fovf = carry["fovf"] | (gate & fovf_now)
        hovf = carry["hovf"] | (gate & hovf_now)
        oovf = carry["oovf"] | oovf_now
        ofx = jnp.maximum(carry["ofx"], n_fresh)
        summary = jnp.concatenate([jnp.stack([
            n_lvl, n_gen, ovf.astype(jnp.int32), fovf.astype(jnp.int32),
            hovf.astype(jnp.int32), oovf.astype(jnp.int32),
            trip_base, ofx]), famx])
        new_carry = dict(carry, vis=table, claims=claims, lvl=lvl,
                         lpar=lpar, llane=llane, linv=linv, lcon=lcon,
                         n_lvl=n_lvl, n_gen=n_gen, famx=famx, ovf=ovf,
                         fovf=fovf, hovf=hovf, oovf=oovf, ofx=ofx,
                         trip_base=trip_base, base=base + B, **extra)
        return new_carry, summary

    # ------------------------------------------------------------------

    def _fresh_spill_carry(self):
        one = self.ir.narrow(self.lay, self.ir.encode(
            self.lay, *self.ir.init_state(self.cfg)))
        lvl = {k: jnp.zeros(v.shape + (self.SEGL,), dtype=v.dtype)
               for k, v in one.items()}
        front = {k: jnp.zeros(v.shape + (self.SEGF,), dtype=v.dtype)
                 for k, v in one.items()}
        n_inv = len(self.inv_names)
        extra = {}
        if self.host_table:
            extra["lfp"] = jnp.full((self.W, self.SEGL), U32MAX)
        return dict(
            vis=tuple(jnp.full((self.VCAP,), U32MAX)
                      for _ in range(self.W)),
            claims=jnp.full((self.VCAP,), U32MAX),
            lvl=lvl,
            **extra,
            lpar=jnp.full((self.SEGL,), -1, jnp.int32),
            llane=jnp.full((self.SEGL,), -1, jnp.int32),
            linv=jnp.ones((n_inv, self.SEGL), bool),
            lcon=jnp.ones((self.SEGL,), bool),
            front=front,
            gids=jnp.full((self.SEGF,), -1, jnp.int32),
            cidx=jnp.zeros((self.FCAP,), jnp.int32),  # FCAP anchor
            oidx=jnp.zeros((self.OCAP,), jnp.int32),  # OCAP anchor
            n_front=jnp.int32(0),
            base=jnp.int32(0),
            n_lvl=jnp.int32(0),
            n_gen=jnp.int32(0),
            famx=jnp.zeros((len(self.expander.families),), jnp.int32),
            ovf=jnp.bool_(False),
            fovf=jnp.bool_(False),
            hovf=jnp.bool_(False),
            oovf=jnp.bool_(False),
            ofx=jnp.int32(0),       # max fresh rows in any chunk
            trip_base=jnp.int32(-1),
        )

    def _reset_lvl_buffers(self, carry):
        """Fresh level-segment buffers at the CURRENT self.SEGL/FCAP
        (used after a cap growth changed shapes; plain n_lvl reset
        suffices otherwise)."""
        one = self.ir.narrow(self.lay, self.ir.encode(
            self.lay, *self.ir.init_state(self.cfg)))
        carry["lvl"] = {k: jnp.zeros(v.shape + (self.SEGL,),
                                     dtype=v.dtype)
                        for k, v in one.items()}
        carry["lpar"] = jnp.full((self.SEGL,), -1, jnp.int32)
        carry["llane"] = jnp.full((self.SEGL,), -1, jnp.int32)
        carry["linv"] = jnp.ones((len(self.inv_names), self.SEGL), bool)
        carry["lcon"] = jnp.ones((self.SEGL,), bool)
        carry["cidx"] = jnp.zeros((self.FCAP,), jnp.int32)
        carry["oidx"] = jnp.zeros((self.OCAP,), jnp.int32)
        carry["n_lvl"] = jnp.int32(0)
        if self.host_table:
            carry["lfp"] = jnp.full((self.W, self.SEGL), U32MAX)
        return carry

    def _prewarm_perlevel(self):
        """Spill twin of Engine._prewarm_perlevel: one dummy streamed
        chunk step on an empty spill carry warms the executable the
        segment driver falls back to when a burst bails."""
        dummy = self._fresh_spill_carry()
        dummy, _s = self._sstep_jit(dummy, self.FAM_CAPS)
        del dummy

    # ------------------------------------------------------------------
    # host-side level plumbing
    # ------------------------------------------------------------------

    def _spill_segment(self, carry, n_lvl: int):
        """Start an ASYNC fetch of the filled rows of the level segment
        and reset the device cursor.  Returns (carry, blk) where blk is
        a PENDING block: its arrays are device-side copies with
        copy_to_host_async in flight — the device keeps crunching the
        next chunks while the DMA drains; _materialize_blk turns it
        into host numpy (cheap once the DMA lands).

        Slice lengths quantize up to _spill_quantum multiples: a
        python-int slice compiles one executable per distinct length,
        and the tunneled backend pays seconds per compile — quantizing
        bounds the shape set to ~8 per SEGL.  The device-side slice is
        a real copy op sequenced BEFORE later donated steps overwrite
        the segment buffer, so the async host copy reads stable data."""
        blk = None
        if n_lvl:
            nq = self._quantize(n_lvl, self.SEGL)
            fn = self._slice_cache.get(nq)
            if fn is None:
                # a jit'd slice (not donated) ALWAYS yields fresh
                # buffers — a bare v[..., :nq] at nq == SEGL is an
                # identity view of the live segment buffer, which the
                # next donated step would delete out from under the
                # pending async copy
                def impl(lvl, lpar, llane, linv, lcon, lfp=None,
                         nq=nq):
                    out = dict(
                        rows={k: lax.slice_in_dim(v, 0, nq, axis=v.ndim - 1)
                              for k, v in lvl.items()},
                        lpar=lax.slice_in_dim(lpar, 0, nq, axis=0),
                        llane=lax.slice_in_dim(llane, 0, nq, axis=0),
                        linv=lax.slice_in_dim(linv, 0, nq, axis=1),
                        lcon=lax.slice_in_dim(lcon, 0, nq, axis=0))
                    if lfp is not None:
                        # the rows' fingerprints ride the spill: they
                        # feed the host-partition sweep and the cache
                        # reseed (host-table mode only)
                        out["lfp"] = lax.slice_in_dim(lfp, 0, nq,
                                                      axis=1)
                    return out
                fn = self._slice_cache[nq] = jax.jit(impl)
            dev = fn(carry["lvl"], carry["lpar"], carry["llane"],
                     carry["linv"], carry["lcon"],
                     carry["lfp"] if self.host_table else None)
            for leaf in jax.tree_util.tree_leaves(dev):
                try:
                    leaf.copy_to_host_async()
                except AttributeError:
                    pass        # older jax: np.asarray below still works
            blk = dict(_dev=dev, n=n_lvl)
        carry["n_lvl"] = jnp.int32(0)
        return carry, blk

    @staticmethod
    def _quantize(n: int, cap: int, floor: int = 1 << 12) -> int:
        """Round a row count up to a power of two in [floor, cap]:
        transfer/slice programs compile once per SIZE, and the tunnel
        moves ~50 MB/s — a 7-row early-level segment must not ship (or
        slice) the full multi-GB buffer (measured 30-70 s per tiny
        level when it did)."""
        q = floor
        while q < n:
            q *= 2
        return min(q, cap)

    @staticmethod
    def _materialize_blk(blk):
        """Resolve a pending spill block to host numpy, trimming the
        quantization padding with real copies — a view would pin the
        up-to-2x-padded base arrays in host RAM for as long as the
        block lives in the next frontier (the deep runs this engine
        exists for are host-RAM bound); idempotent."""
        if blk is None or "_dev" not in blk:
            return blk
        dev = blk.pop("_dev")
        n = blk["n"]

        def trim(v, axis):
            a = np.asarray(v)
            if a.shape[axis] == n:
                return a
            return np.ascontiguousarray(
                a[(slice(None),) * axis + (slice(0, n),)])
        blk["rows"] = {k: trim(v, v.ndim - 1)
                       for k, v in dev["rows"].items()}
        blk["lpar"] = trim(dev["lpar"], 0)
        blk["llane"] = trim(dev["llane"], 0)
        blk["linv"] = trim(dev["linv"], 1)
        blk["lcon"] = trim(dev["lcon"], 0)
        if "lfp" in dev:
            blk["lfp"] = trim(dev["lfp"], 1)
        return blk

    def _stage_segment(self, seg_rows: Dict[str, np.ndarray],
                       seg_gids: np.ndarray):
        """Issue the H2D transfers for a frontier segment NOW (padded
        to the next size QUANTUM, not to SEGF — a tiny early-level
        segment must not ship the full multi-GB buffer over the ~50
        MB/s tunnel) without touching the carry: called one segment
        AHEAD, so the DMA rides the tunnel while the device crunches
        the current segment (the double-buffering half of VERDICT r4
        #4)."""
        n = int(seg_gids.shape[0])
        nq = self._quantize(n, self.SEGF)
        pad = nq - n
        blocks = {}
        for k, v in seg_rows.items():
            if pad:
                v = np.concatenate(
                    [v, np.zeros(v.shape[:-1] + (pad,), v.dtype)],
                    axis=-1)
            blocks[k] = jax.device_put(v)
        gids = np.full((nq,), -1, np.int32)
        gids[:n] = seg_gids
        return dict(blocks=blocks, gids=jax.device_put(gids), n=n,
                    nq=nq)

    def _swap_in_segment(self, carry, staged):
        """Paste the staged (already device-resident) quantized block
        into the persistent SEGF-shaped frontier buffers — one small
        donated-DUS program per block size, cached.  Rows past n_front
        are stale garbage from earlier segments; the step's valid mask
        bounds them."""
        nq = staged["nq"]
        fn = self._paste_cache.get(nq)
        if fn is None:
            def impl(front, gids, blocks, bg):
                front = {k: lax.dynamic_update_slice_in_dim(
                    v, blocks[k], 0, v.ndim - 1)
                    for k, v in front.items()}
                return front, lax.dynamic_update_slice_in_dim(
                    gids, bg, 0, 0)
            fn = self._paste_cache[nq] = jax.jit(
                impl, donate_argnums=(0, 1))
        carry["front"], carry["gids"] = fn(
            carry["front"], carry["gids"], staged["blocks"],
            staged["gids"])
        carry["n_front"] = jnp.int32(staged["n"])
        carry["base"] = jnp.int32(0)
        return carry, staged["n"]

    @staticmethod
    def _resegment(blocks: List, seg: int):
        """Yield (rows, gids) segments of <= seg rows from frontier
        blocks [(rows dict batch-last, gids)], concatenating across
        block boundaries."""
        buf_rows, buf_gids, have = [], [], 0
        for rows, gids in blocks:
            n = int(gids.shape[0])
            off = 0
            while off < n:
                take_n = min(seg - have, n - off)
                buf_rows.append({k: v[..., off:off + take_n]
                                 for k, v in rows.items()})
                buf_gids.append(gids[off:off + take_n])
                have += take_n
                off += take_n
                if have == seg:
                    yield SpillEngine._cat_seg(buf_rows, buf_gids)
                    buf_rows, buf_gids, have = [], [], 0
        if have:
            yield SpillEngine._cat_seg(buf_rows, buf_gids)

    @staticmethod
    def _cat_seg(buf_rows, buf_gids):
        if len(buf_rows) == 1:
            return buf_rows[0], buf_gids[0]
        keys = buf_rows[0].keys()
        return ({k: np.concatenate([b[k] for b in buf_rows], axis=-1)
                 for k in keys}, np.concatenate(buf_gids))

    # ------------------------------------------------------------------
    # host-partitioned table: the per-level partition sweep and the
    # device-cache reseed (engine/host_table module docstring)
    # ------------------------------------------------------------------

    def _member_fn(self, cap: int, nq: int):
        """Jit'd gathers-only membership probe of nq keys against a
        cap-slot partition image (one cache entry per shape pair):
        the device half of the sweep — same home hash and quadratic
        walk as _probe_insert, no writes."""
        fn = self._member_cache.get((cap, nq))
        if fn is None:
            W = self.W
            MAXR = self._MAX_PROBE_ROUNDS

            def impl(img, keys, n):
                live = jnp.arange(nq, dtype=jnp.int32) < n
                h = jnp.full((nq,), _HOME_SALT, jnp.uint32)
                for w in range(W):
                    h = fmix32(h ^ keys[w])
                pos = (h & jnp.uint32(cap - 1)).astype(jnp.int32)

                def classify(pos):
                    iskey = jnp.ones((nq,), bool)
                    isempty = jnp.ones((nq,), bool)
                    for w in range(W):
                        cur = img[w, pos]
                        iskey &= cur == keys[w]
                        isempty &= cur == U32MAX
                    return iskey, isempty

                def cond(st):
                    _p, _t, act, _f, r = st
                    return act.any() & (r < MAXR)

                def body(st):
                    pos, t, act, found, r = st
                    iskey, isempty = classify(pos)
                    found = found | (act & iskey)
                    act = act & ~(iskey | isempty)
                    t = jnp.where(act, t + 1, t)
                    pos = jnp.where(act, (pos + t) & (cap - 1), pos)
                    return pos, t, act, found, r + 1

                st = (pos, jnp.zeros((nq,), jnp.int32), live,
                      jnp.zeros((nq,), bool), jnp.int32(0))
                _p, _t, act, found, _r = lax.while_loop(cond, body, st)
                return found, act.any()
            fn = self._member_cache[(cap, nq)] = jax.jit(impl)
        return fn

    def _sweep_level_keys(self, keys: np.ndarray) -> np.ndarray:
        """One level's partition sweep: bucket the level's keys (u32
        [N, W], unique within the level, enumeration order) by
        fingerprint prefix, stream each partition's image through the
        device for the membership probe — partition p+1's H2D staging
        is issued before p's verdict is forced, so the upload rides the
        host link while the device probes (the spill engine's
        double-buffering discipline) — then commit the fresh keys into
        the host partitions.  Returns keep = not-seen-before [N]."""
        # chaos site: host-partition loss (this device-streamed sweep
        # is the single-chip twin of HostPartitionedTable.sweep, which
        # carries the same site for the mesh composition)
        chaos_point("host_table")
        with self._obs.span("host_sweep"):
            return self._sweep_level_keys_impl(keys)

    _SWEEP_STAGE_DEPTH = 2

    def _stage_sweep_images(self):
        """Issue async H2D uploads of the NEXT sweep's first partition
        images (ascending partition order — the sweep's plan order) up
        to the double-buffer depth.  Called at level start inside the
        level_dispatch window: the ``h2d_stage`` span then visibly
        overlaps the level's compute spans on the timeline, which is
        the point — the upload cost leaves the sweep's critical path.
        device_put returns immediately (the transfer drains in the
        background); the version tag recorded here is what lets the
        sweep trust (or discard) the image later."""
        if not (self.sweep_stage and self.host_table
                and self.hpt is not None):
            return
        if getattr(self, "_staged_for", None) is not self.hpt:
            # a fresh/resumed check rebuilt the partitions: any staged
            # images belong to the OLD table object — drop them (the
            # version counters of a new table restart at 0 and could
            # alias)
            self._sweep_staged = {}
            self._staged_for = self.hpt
        todo = [p for p in range(self.hpt.P)
                if p not in self._sweep_staged]
        room = self._SWEEP_STAGE_DEPTH - len(self._sweep_staged)
        if room <= 0 or not todo:
            return
        with self._obs.span("h2d_stage"):
            for p in todo[:room]:
                self._sweep_staged[p] = (
                    jax.device_put(self.hpt.imgs[p]),
                    self.hpt.vers[p])

    def _sweep_level_keys_impl(self, keys: np.ndarray) -> np.ndarray:
        n_all = keys.shape[0]
        keep = np.ones(n_all, bool)
        if n_all == 0:
            return keep
        hpt = self.hpt
        pids = hpt.partition_ids(keys)
        plan = []
        for p in range(hpt.P):
            idx = np.nonzero(pids == p)[0]
            if idx.size:
                plan.append((p, idx))
        staged = {}

        def stage(j):
            if j < len(plan):
                p, idx = plan[j]
                # grow BEFORE the upload so the device image honors the
                # probe-budget load bound even after this level commits
                grew = hpt.reserve(p, int(idx.size))
                pre = self._sweep_staged.pop(p, None)
                if pre is not None and not grew and \
                        pre[1] == hpt.vers[p]:
                    # the image was prestaged during the level's
                    # compute (and is provably current): its H2D
                    # already rode the link — the sweep_overlap span
                    # marks the serialized upload this sweep skipped
                    with self._obs.span("sweep_overlap"):
                        staged[j] = pre[0]
                    self.sweep_stage_hits += 1
                else:
                    staged[j] = jax.device_put(hpt.imgs[p])
                    if self.sweep_stage:
                        self.sweep_stage_misses += 1

        stage(0)
        pending = []
        for j, (p, idx) in enumerate(plan):
            img = staged.pop(j)
            n = int(idx.size)
            nq = self._quantize(n, 1 << 30, floor=1 << 8)
            kq = np.full((self.W, nq), np.uint32(0xFFFFFFFF),
                         np.uint32)
            kq[:, :n] = keys[idx].T
            fn = self._member_fn(int(img.shape[1]), nq)
            found, hovf = fn(img, jax.device_put(kq), jnp.int32(n))
            stage(j + 1)        # next partition's H2D rides now
            pending.append((idx, found, hovf))
        for idx, found, hovf in pending:
            if bool(np.asarray(hovf)):
                raise RuntimeError(
                    "host-partition sweep probe walk did not converge "
                    "— partition image pathologically full")
            keep[idx] = ~np.asarray(found)[:idx.size]
        hpt.commit(keys, keep)
        return keep

    def _reseed_dev_table(self, carry, fkeys: np.ndarray):
        """Reset the device cache to the frontier's keys at (near) the
        initial capacity: the frontier cohort is what the next level
        re-generates at high rate; everything older answers from the
        host sweep.  Only ever called at a level boundary — the cache
        must stay complete over a running level."""
        n = int(fkeys.shape[0])
        self.VCAP = self.VCAP0
        while n + self.SEGL - self.OCAP > self._LOAD_MAX * self.VCAP:
            self.VCAP *= 4
        nq = self._quantize(max(n, 1), 1 << 30, floor=1 << 8)
        kq = np.full((self.W, nq), np.uint32(0xFFFFFFFF), np.uint32)
        if n:
            kq[:, :n] = fkeys.T
        fn = self._seed_cache.get((self.VCAP, nq))
        if fn is None:
            VCAP = self.VCAP

            def impl(keys, n):
                table = tuple(jnp.full((VCAP,), U32MAX)
                              for _ in range(self.W))
                claims = jnp.full((VCAP,), U32MAX)
                live = jnp.arange(nq, dtype=jnp.int32) < n
                ks = tuple(keys[w] for w in range(self.W))
                ranks = jnp.arange(nq, dtype=jnp.uint32)
                # lax path unconditionally: the reseed bulk-inserts a
                # whole frontier cohort at once — not the per-candidate
                # hot loop the sequential Pallas kernel exists for
                # (same discipline as the rehash sites)
                table, claims, _f, _p, hv = self._probe_insert_lax(
                    table, claims, ks, live, ranks)
                return table, claims, hv
            fn = self._seed_cache[(self.VCAP, nq)] = jax.jit(impl)
        vis, claims, hv = fn(jnp.asarray(kq), jnp.int32(n))
        if bool(np.asarray(hv)):
            raise RuntimeError(
                "cache reseed probe overflow — raise vcap")
        return dict(carry, vis=vis, claims=claims), n

    # ------------------------------------------------------------------
    # spill-aware fused multi-level burst: while the whole frontier
    # fits the burst ring (engine/bfs burst notes) and no host-table
    # sweep is due (host_table mode sweeps EVERY level, so it keeps the
    # per-level path), run whole levels on device — one dispatch + one
    # small stats readback per burst instead of the
    # upload/window/spill round trips of the segment driver.  The
    # moment a level outgrows the ring, any cap trips, or the space
    # widens past the ring, the burst bails with the pre-level frontier
    # intact and the segment driver takes over — a spill flush or
    # segment boundary can therefore never be needed INSIDE a burst
    # (the ring is far smaller than a segment).
    # ------------------------------------------------------------------

    def _spill_burst_call(self, vis, claims, fr, fm, gd, nf, g0,
                          fam_caps, fcap, ocap, levels_left,
                          states_cap):
        stf, out = self._burst_core(vis, claims, fr, fm, gd, nf, g0,
                                    g0, fam_caps, levels_left,
                                    states_cap, fcap=fcap, ocap=ocap)
        return (stf["vis"], stf["claims"], stf["fr"], stf["fm"],
                stf["gd"], stf["nf"], out)

    def _burst_spill_levels(self, carry, frontier_blocks, res, depth,
                            n_states, n_vis, max_depth, max_states,
                            verbose):
        """One fused multi-level device call on a tiny frontier.
        Harvests every committed level (counts, archives, violations)
        and rebuilds the host frontier blocks from the surviving ring.
        Returns (carry, frontier_blocks, depth, n_states, n_vis,
        fused, bailed) — fused=False means the first level bailed
        (caps/ring overflow) and the segment driver must run it
        instead; bailed=True means the call ended in a bail (even
        after committing levels), so re-entering the burst on the
        unchanged frontier would deterministically bail again."""
        t1 = time.perf_counter()
        lay = self.lay
        with self._obs.span("burst_dispatch"):
            KB = self._burst_width()
            n_front = sum(int(g.shape[0]) for _r, g in frontier_blocks)
            rows_cat, gids_cat = self._cat_seg(
                [r for r, _g in frontier_blocks],
                [g for _r, g in frontier_blocks])
            one = self.ir.narrow(lay, self.ir.encode(
                lay, *self.ir.init_state(self.cfg)))
            fr_np = {k: np.zeros(v.shape + (KB,), v.dtype)
                     for k, v in one.items()}
            for k in fr_np:
                fr_np[k][..., :n_front] = rows_cat[k]
            gd_np = np.full((KB,), -1, np.int32)
            gd_np[:n_front] = gids_cat
            fm_np = np.zeros((KB,), bool)
            fm_np[:n_front] = True
            carry = self._grow_table_if_needed(
                carry, n_vis, min_add=self.burst_levels * KB)
            lv_left = min(self.burst_levels, max_depth - depth)
            st_cap = max(1, min(max_states - res.distinct_states,
                                2 ** 31 - 1))
            vis, claims, frd, fmd, gdd, _nfd, out = \
                self._spill_burst_jit(
                    carry["vis"], carry["claims"],
                    {k: jnp.asarray(v) for k, v in fr_np.items()},
                    jnp.asarray(fm_np), jnp.asarray(gd_np),
                    jnp.int32(n_front), jnp.int32(n_states),
                    self.FAM_CAPS, self.FCAP, self.OCAP,
                    jnp.int32(lv_left), jnp.int32(st_cap))
            carry = dict(carry, vis=vis, claims=claims)
            stats = np.asarray(out["stats"])      # the ONE burst sync
        nlev = int(stats[-1, 0])
        bailed = bool(stats[-1, 1])
        res.burst_dispatches += 1
        res.burst_bailouts += int(bailed)
        if nlev == 0:
            return (carry, frontier_blocks, depth, n_states, n_vis,
                    False, bailed)
        viol_any = bool(stats[-1, 3])
        with self._obs.span("harvest"):
            par_h = lane_h = st_h = inv_h = None
            if self.store_states or viol_any:
                par_h = np.asarray(out["par"])
                lane_h = np.asarray(out["lane"])
                st_h = {k: np.asarray(v) for k, v in out["st"].items()}
                inv_h = np.asarray(out["inv"])

            def _arch(li, n_lvl):
                if self.store_states and n_lvl:
                    # n_lvl == 0 appends nothing: the spill archive's
                    # gid->row mapping is cumulative, not per-level
                    # (flush_archives skips empty levels the same way)
                    self._archive_level(*driver.burst_archive_slice(
                        par_h, lane_h, st_h, li, n_lvl))

            def _viol(li, n_lvl, gid_base):
                driver.burst_decode_violations(
                    res, self.ir, lay, self.inv_names, inv_h, st_h,
                    li, n_lvl, gid_base)

            def _vis(li, n_lvl):
                nonlocal n_vis
                n_vis += n_lvl

            depth, n_states = driver.harvest_fused_levels(
                res, nlev, lambda li: stats[li, :5], depth, n_states,
                archive=_arch, violations=_viol, visited=_vis)
        # rebuild the host frontier from the surviving ring: pruned
        # rows drop here (prune-not-expand stays host-side outside the
        # burst, exactly as if the level had spilled)
        nf = int(stats[-1, 2])
        frontier_blocks = []
        if nf:
            keep = np.nonzero(np.asarray(fmd)[:nf])[0]
            if len(keep):
                fr_h = {k: np.ascontiguousarray(
                            np.asarray(v)[..., keep])
                        for k, v in frd.items()}
                frontier_blocks = [
                    (fr_h, np.asarray(gdd)[keep].astype(np.int32))]
        self._obs.dispatch(kind="burst", depth=depth, frontier=nf,
                           metrics=res.metrics.as_dict())
        if verbose:
            print(f"burst: {nlev} levels to depth {depth} "
                  f"(total {res.distinct_states}), frontier "
                  f"{sum(int(g.shape[0]) for _r, g in frontier_blocks)}, "
                  f"{time.perf_counter() - t1:.2f}s", flush=True)
        return (carry, frontier_blocks, depth, n_states, n_vis, True,
                bailed)

    # ------------------------------------------------------------------

    def check(self, max_depth: int = 10 ** 9, max_states: int = 10 ** 9,
              stop_on_violation: bool = False,
              seed_states: Optional[List] = None,
              checkpoint_path: Optional[str] = None,
              checkpoint_every: int = 1,
              resume_from: Optional[str] = None,
              resume_image=None,
              verbose: bool = False, obs=None) -> CheckResult:
        """``resume_image`` — a ``resil.portable.PortableImage`` from
        ANY engine family's checkpoint: the visited key set rebuilds
        this engine's table image (and host partitions) and the
        frontier rows become one spill block, so a mesh or classic
        checkpoint resumes here after a shape change (ROADMAP item-2
        elastic resume)."""
        obs = self._obs = obs if obs is not None else NULL_OBS
        t0 = time.perf_counter()
        lay = self.lay
        frontier_keys: List[np.ndarray] = []   # host-table mode only
        if resume_from is not None and resume_image is not None:
            raise ValueError(
                "resume_from and resume_image are mutually exclusive")

        def prewarm():
            # the segment driver's streamed step warms at run start so
            # a burst BAIL never pays its cold compile mid-run inside a
            # dispatch span (the BENCH_r08 leak — engine/bfs check()'s
            # prewarm note for the span gate and the peak-memory
            # sequencing)
            if obs.spans is not None:
                with obs.span("compile"):
                    self._prewarm_perlevel()

        if resume_from is not None:
            (carry, res, frontier_blocks, frontier_keys, n_states,
             n_vis, depth) = self._load_spill_checkpoint(resume_from)
            prewarm()        # beside the loaded carry (resume-only)
            root_blk = None
        elif resume_image is not None:
            (carry, res, frontier_blocks, frontier_keys, n_states,
             n_vis, depth) = self._resume_portable(resume_image)
            prewarm()
            root_blk = None
        else:
            self._init_store()
            if self.host_table:
                self.hpt = HostPartitionedTable(
                    self.W, partitions=self.partitions,
                    part_cap=self.part_cap)
                self._sweep_staged = {}
            # ---- roots (shared admit path: engine/bfs._dedup_roots) --
            roots, rk, pin_interiors = self._dedup_roots(seed_states)
            n_roots = len(rk)

            res = CheckResult(distinct_states=0,
                              generated_states=n_roots, depth=0)
            self._check_pin_interiors(pin_interiors, res)

            # warm BEFORE the real carry allocates (the dummy is
            # donated away, so peak device memory stays ONE carry)
            prewarm()
            carry = self._fresh_spill_carry()
            slots = self._host_probe_assign(rk, vcap=self.VCAP)
            sl = jnp.asarray(slots)
            carry["vis"] = tuple(
                carry["vis"][w].at[sl].set(jnp.asarray(rk[:, w]))
                for w in range(self.W))
            inv_r, con_r = (np.asarray(a) for a in self._phase2(
                {k: jnp.asarray(v) for k, v in roots.items()}))
            roots_T = {k: np.moveaxis(v, 0, -1)
                       for k, v in self.ir.narrow(lay,
                                                  roots).items()}
            root_blk = dict(rows=roots_T,
                            lpar=np.full((n_roots,), -1, np.int32),
                            llane=np.full((n_roots,), -1, np.int32),
                            linv=inv_r.T, lcon=con_r, n=n_roots)
            if self.host_table:
                root_blk["lfp"] = np.ascontiguousarray(
                    rk.T.astype(np.uint32))

            n_states = 0       # running global id offset
            n_vis = n_roots
            depth = 0
            frontier_blocks = []

        self._stamp_mode(res)

        def harvest_block(blk, keep=None):
            """Counts, violations, archives, next-frontier rows for one
            spilled block; returns (rows, gids, fkeys) for the frontier
            (fkeys None outside host-table mode).  ``keep`` is the
            host-partition sweep's verdict: False rows were seen in an
            earlier level (the device cache only errs fresh-ward) and
            are dropped before any counting — exactly the rows the
            in-HBM engine would never have admitted."""
            nonlocal n_states
            if keep is not None and not keep.all():
                kidx = np.nonzero(keep)[0]
                sub = dict(
                    rows={k: np.ascontiguousarray(v[..., kidx])
                          for k, v in blk["rows"].items()},
                    lpar=blk["lpar"][kidx], llane=blk["llane"][kidx],
                    linv=blk["linv"][:, kidx], lcon=blk["lcon"][kidx],
                    n=len(kidx))
                if "lfp" in blk:
                    sub["lfp"] = np.ascontiguousarray(
                        blk["lfp"][:, kidx])
                blk = sub
            n = blk["n"]
            res.distinct_states += n
            # C_OVERFLOW representability faults (engine/bfs finalize
            # counts the same lane per level)
            res.overflow_faults += int(
                (blk["rows"]["ctr"][C_OVERFLOW] > 0).sum())
            gids = np.arange(n_states, n_states + n, dtype=np.int32)
            inv_ok = blk["linv"]
            if inv_ok.size and not inv_ok.all():
                bad = np.nonzero(~inv_ok)
                res.violations_global += len(bad[0])
                for j, s in zip(*bad):
                    vsv, vh = self.ir.decode(
                        lay, _take_last(blk["rows"], s))
                    res.violations.append(Violation(
                        self.inv_names[j], int(gids[s]),
                        state=vsv, hist=vh))
            if self.store_states:
                self._lvl_parts[-1].append(blk)
            n_states += n
            driver.guard_id_space(n_states)
            con = blk["lcon"].astype(bool)
            if con.all():
                fk = (np.ascontiguousarray(blk["lfp"].T)
                      if "lfp" in blk else None)
                return blk["rows"], gids, fk
            cidx = np.nonzero(con)[0]
            if not len(cidx):
                return None
            fk = (np.ascontiguousarray(blk["lfp"][:, cidx].T)
                  if "lfp" in blk else None)
            return ({k: v[..., cidx] for k, v in blk["rows"].items()},
                    gids[cidx], fk)

        def _take_last(rows, i):
            return {k: np.asarray(v[..., i]) for k, v in rows.items()}

        def flush_archives():
            """store_states: merge this level's spilled parts into the
            per-level archive — streamed to the disk archive's memmaps
            under ``archive_dir`` (host RSS stays level-bounded), or
            concatenated into the classic in-RAM batch-major arrays
            otherwise (trace()/get_state are inherited unchanged)."""
            if not self.store_states:
                return
            parts = self._lvl_parts[-1]
            if not parts:
                return
            with obs.span("archive_io"):
                if self._arch is not None:
                    self._arch.append_level_parts(parts)
                else:
                    self._parents.append(np.concatenate(
                        [p["lpar"] for p in parts]))
                    self._lanes.append(np.concatenate(
                        [p["llane"] for p in parts]))
                    keys = parts[0]["rows"].keys()
                    self._states.append(
                        {k: np.moveaxis(np.concatenate(
                            [p["rows"][k] for p in parts], axis=-1),
                            -1, 0)
                         for k in keys})
            # the archive holds its own copies/files now; dropping the
            # part refs keeps host RSS frontier-bounded
            self._lvl_parts[-1] = []

        self._lvl_parts: List[List] = [[]]
        if root_blk is not None:
            rkeep = None
            if self.host_table:
                # roots enter the host partitions through the same
                # sweep as every level (all fresh by construction)
                rkeep = self._sweep_level_keys(
                    np.ascontiguousarray(root_blk["lfp"].T))
            out = harvest_block(root_blk, rkeep)
            flush_archives()
            if out is not None:
                rows_r, gids_r, fk_r = out
                frontier_blocks.append((rows_r, gids_r))
                if fk_r is not None:
                    frontier_keys.append(fk_r)
            res.generated_states = n_roots
        if stop_on_violation and res.violations:
            res.seconds = time.perf_counter() - t0
            return res

        # ---- level loop ---------------------------------------------
        # Double-buffered (VERDICT r4 #4): the next frontier segment's
        # H2D transfers are issued while the device crunches the
        # current one; level spills ride D2H asynchronously (pending
        # blocks, harvested in FIFO later); and window summaries are
        # fetched ONE WINDOW LATE so the device always has a dispatched
        # window in flight instead of idling on the tunnel's ~100 ms
        # summary round trip.  Late detection is safe: a trip gates
        # every later chunk into a no-op (sticky flags), and the spill
        # floor reserves margin for the extra in-flight window.
        # burst_ok: a burst that committed levels then bailed keeps the
        # bailing level's frontier intact — re-entering would replay
        # the identical chunks and bail again (one wasted round trip),
        # so skip the burst for that level; the segment driver re-arms
        burst_ok = True
        while frontier_blocks and depth < max_depth and \
                res.distinct_states < max_states:
            # chaos site: dispatch-time device/tunnel error at the
            # level boundary (resil/chaos) — before any device work,
            # so the last checkpoint stays the exact resume point
            chaos_point("dispatch")
            if (self.burst and burst_ok and not self.host_table and
                    sum(int(g.shape[0]) for _r, g in frontier_blocks)
                    <= self._burst_width()):
                d0 = depth
                (carry, frontier_blocks, depth, n_states, n_vis,
                 fused, bailed) = self._burst_spill_levels(
                    carry, frontier_blocks, res, depth, n_states,
                    n_vis, max_depth, max_states, verbose)
                if fused:
                    burst_ok = not bailed
                    if checkpoint_path is not None and \
                            driver.ckpt_due_after_burst(
                                depth, d0, checkpoint_every):
                        self._save_spill_checkpoint(
                            checkpoint_path, carry, res,
                            frontier_blocks, frontier_keys, depth,
                            n_states, n_vis)
                    if stop_on_violation and res.violations:
                        break
                    continue
                # first level bailed: the segment driver (with its
                # growth machinery) runs it below
            burst_ok = True        # re-arm after a per-level level
            depth += 1
            t1 = time.perf_counter()
            self._lvl_parts.append([])
            level_new = 0
            level_gen = 0
            next_blocks: List = []
            next_keys: List = []
            level_blks: List = []      # host-table: sweep at level end
            pending_blks: List = []

            def drain_gen():
                # drain the device generated-counter into the host's
                # Python ints each segment: it is an int32, and a whole
                # beyond-the-wall run generates ~4e9 successors — kept
                # monotone on device it would wrap negative
                nonlocal level_gen, carry
                g = int(np.asarray(carry["n_gen"]))
                res.generated_states += g
                level_gen += g
                carry = dict(carry, n_gen=jnp.int32(0))

            def settle_blk(blk):
                """Immediate int bookkeeping for a fresh pending spill
                block; the numpy materialization + harvest run later
                (FIFO) so the D2H DMA overlaps further chunk work.
                n_vis tracks DEVICE-table occupancy either way; under
                the host table, level_new waits for the sweep verdict
                (a device-fresh row may be an older level's key)."""
                nonlocal n_vis, level_new
                if blk is not None:
                    n_vis += blk["n"]
                    if not self.host_table:
                        level_new += blk["n"]
                    pending_blks.append(blk)

            def drain_blks():
                nonlocal pending_blks
                if not pending_blks:
                    return
                with obs.span("harvest"):
                    for blk in pending_blks:
                        blk = self._materialize_blk(blk)
                        if self.host_table:
                            # harvest defers to the level-end sweep:
                            # the host partitions judge the whole
                            # level's keys at once, in enumeration
                            # order
                            level_blks.append(blk)
                            continue
                        out = harvest_block(blk)
                        if out is not None:
                            next_blocks.append(out[:2])
                    pending_blks = []

            _lvl_span = obs.span("level_dispatch")
            _lvl_span.__enter__()
            if self.host_table:
                # issue the level-end sweep's first partition uploads
                # NOW: the H2D DMA overlaps this level's chunk compute
                # (tentpole-c double-buffering; h2d_stage span nested
                # inside this level_dispatch span = the visible
                # overlap)
                self._stage_sweep_images()
            seg_iter = self._resegment(frontier_blocks, self.SEGF)
            staged = next(seg_iter, None)
            staged_dev = (self._stage_segment(*staged)
                          if staged is not None else None)
            while staged_dev is not None:
                carry = self._grow_table_if_needed(carry, n_vis)
                carry, n_seg = self._swap_in_segment(carry, staged_dev)
                staged = next(seg_iter, None)
                # prefetch the NEXT segment now: its H2D DMA rides the
                # tunnel while this segment's windows run
                staged_dev = (self._stage_segment(*staged)
                              if staged is not None else None)
                n_chunks = (n_seg + self.chunk - 1) // self.chunk
                k = 0
                inflight = None
                while k < n_chunks or inflight is not None:
                    cur = None
                    if k < n_chunks:
                        win_end = min(k + self.sync_every, n_chunks)
                        while k < win_end:
                            carry, cur = self._sstep_jit(carry,
                                                         self.FAM_CAPS)
                            k += 1
                    if inflight is not None:
                        s = np.asarray(inflight)    # one window stale
                        # floor margin covers the in-flight window
                        # dispatched above (2x sync_every, not 1x)
                        spill_floor = self.SEGL - self.OCAP * (
                            2 * self.sync_every + 3)
                        tripped = s[S_OVF] or s[S_FOVF] or \
                            s[S_HOVF] or s[S_OOVF]
                        if tripped or int(s[S_NLVL]) >= spill_floor:
                            if cur is not None:
                                # sync the in-flight window too: its
                                # summary is the freshest view of the
                                # sticky flags / famx / n_lvl (trip
                                # chunks are gated no-ops, so nothing
                                # was committed past the trip)
                                s = np.asarray(cur)
                                cur = None
                            if s[S_OVF] or s[S_FOVF] or s[S_HOVF] or \
                                    s[S_OOVF]:
                                # a fresh pending block may be created
                                # inside; older ones harvest first
                                drain_blks()
                                carry, blk, k = self._handle_trip(
                                    carry, s, n_vis, verbose)
                                settle_blk(blk)
                            else:
                                drain_blks()
                                carry, blk = self._spill_segment(
                                    carry, int(s[S_NLVL]))
                                settle_blk(blk)
                            # re-check the load bound now that n_vis
                            # moved: a dense segment can spill several
                            # SEGL's worth of fresh keys before the
                            # next segment-boundary check
                            carry = self._grow_table_if_needed(carry,
                                                               n_vis)
                    inflight = cur
                drain_gen()
                # final spill for this segment epoch happens lazily —
                # rows stay on device and keep accumulating across
                # frontier segments until the floor trips or the level
                # ends (fewer, larger transfers)

            # level end: spill the remainder
            n_rem = int(np.asarray(carry["n_lvl"]))
            carry, blk = self._spill_segment(carry, n_rem)
            settle_blk(blk)
            drain_gen()
            _lvl_span.__exit__(None, None, None)
            drain_blks()
            if self.host_table and level_blks:
                # the level's keys — unique (device cache is complete
                # over the level) and in enumeration order — meet the
                # host partitions: rows whose key an earlier level
                # archived are dropped everywhere at once
                lkeys = np.concatenate(
                    [np.ascontiguousarray(b["lfp"].T)
                     for b in level_blks])
                lkeep = self._sweep_level_keys(lkeys)
                with obs.span("harvest"):
                    off = 0
                    for b in level_blks:
                        nb = b["n"]
                        kb = lkeep[off:off + nb]
                        off += nb
                        level_new += int(kb.sum())
                        out = harvest_block(b, kb)
                        if out is not None:
                            rows_b, gids_b, fk_b = out
                            next_blocks.append((rows_b, gids_b))
                            next_keys.append(fk_b)
            flush_archives()
            # shared depth gate (engine/driver): a pruned-only frontier
            # cannot occur here (host drops pruned rows), but the
            # empty-frontier guard keeps depth semantics aligned
            depth = driver.gate_level_depth(
                res, depth, level_new, level_gen,
                sum(int(g.shape[0]) for _r, g in next_blocks))
            frontier_blocks = next_blocks   # the expanded level's
            # blocks are freed here (rebind) unless archived
            frontier_keys = next_keys
            if self.host_table and n_vis > self.dev_keys:
                # level boundary: the cache outgrew its HBM budget —
                # reseed it with just the frontier's keys (the host
                # partitions already hold everything archived)
                fkeys = (np.concatenate(frontier_keys) if frontier_keys
                         else np.zeros((0, self.W), np.uint32))
                carry, n_vis = self._reseed_dev_table(carry, fkeys)
            if checkpoint_path is not None and \
                    driver.ckpt_due_at_level(depth, checkpoint_every):
                self._save_spill_checkpoint(
                    checkpoint_path, carry, res, frontier_blocks,
                    frontier_keys, depth, n_states, n_vis)
            obs.dispatch(
                kind="level", depth=depth,
                frontier=sum(int(g.shape[0])
                             for _r, g in frontier_blocks),
                metrics=res.metrics.as_dict())
            if stop_on_violation and res.violations:
                break
            if verbose:
                print(f"depth {depth}: +{level_new} states "
                      f"(total {res.distinct_states}), "
                      f"frontier {sum(int(g.shape[0]) for _r, g in frontier_blocks)}, "
                      f"{time.perf_counter() - t1:.2f}s", flush=True)
        res.depth = depth
        res.seconds = time.perf_counter() - t0
        return res

    # ------------------------------------------------------------------
    # checkpoint / resume (VERDICT r4 #2): at a level boundary the whole
    # wavefront is host-reachable — the visited table is the ONLY device
    # state that matters (level segment empty, frontier segment stale:
    # both rebuild from the host frontier blocks at resume), and the
    # frontier blocks + counters + archives are already host numpy.
    # Reuses the engine-family ckpt_* serializer (engine/bfs), with the
    # frontier blocks riding inside the carry pytree; ckpt_read's
    # spill=True flag keeps classic/sharded engines from resuming these
    # files and vice versa.  TLC checkpoints its disk queue + fingerprint
    # set the same way (/root/reference/.gitignore:4).
    #
    # Each checkpoint is a full (not incremental) snapshot; under
    # store_states=True the cumulative archives rewrite every time, so
    # long trace-hunting runs should raise checkpoint_every.  The deep
    # beyond-the-wall runs this exists for run store_states=False, where
    # a snapshot is the sparse table + the current frontier only.
    # ------------------------------------------------------------------

    _SPILL_EXTRA_KEYS = ("SEGL", "SEGF", "VCAP", "FCAP", "OCAP",
                         "fam_caps", "n_fblk")

    def _save_spill_checkpoint(self, path, carry, res, frontier_blocks,
                               frontier_keys, depth, n_states, n_vis):
        with self._obs.span("checkpoint"):
            return self._save_spill_checkpoint_impl(
                path, carry, res, frontier_blocks, frontier_keys,
                depth, n_states, n_vis)

    def _save_spill_checkpoint_impl(self, path, carry, res,
                                    frontier_blocks, frontier_keys,
                                    depth, n_states, n_vis):
        # the table serializes SPARSE (occupied slot indices + keys),
        # and the sparsification runs ON DEVICE: deep runs pre-allocate
        # VCAP for the final level (2^28 slots = 4 GB of streams at
        # fp128), and fetching the dense table over the ~50 MB/s
        # tunnel cost ~80 s per checkpoint (measured — it throttled
        # every early level of the depth-21 fp128 run).  The device
        # compacts occupied slots into a buffer quantized to the
        # host-tracked occupancy (n_vis counts exactly the admitted
        # keys), so the transfer is O(occupied).  An all-ones key
        # aliases "empty" and would drop out — the same 2^-64/2^-128
        # accepted-risk class as the probe walk (engine/bfs table
        # docstring).
        VCAP = self.VCAP
        nq = self._quantize(max(n_vis, 1), VCAP)
        fn = self._ckpt_sparse_cache.get((nq, VCAP))
        if fn is None:
            def impl(vis, nq=nq, VCAP=VCAP):
                empty = vis[0] == U32MAX
                for t in vis[1:]:
                    empty &= t == U32MAX
                idx = jnp.nonzero(~empty, size=nq,
                                  fill_value=VCAP)[0]
                safe = jnp.clip(idx, 0, VCAP - 1)
                keys = jnp.stack([
                    jnp.where(idx < VCAP, t[safe], U32MAX)
                    for t in vis])
                return idx.astype(jnp.int64), keys
            fn = self._ckpt_sparse_cache[(nq, VCAP)] = jax.jit(impl)
        idx_np, keys_np = (np.asarray(a) for a in fn(carry["vis"]))
        live = idx_np < VCAP
        occ_idx = idx_np[live]
        ckpt = dict(
            vis_idx=occ_idx,
            vis_keys=np.ascontiguousarray(keys_np[:, live]),
            fblk=[dict(g=np.asarray(g),
                       r={k: np.asarray(v) for k, v in rows.items()})
                  for rows, g in frontier_blocks])
        if self.host_table:
            # the authoritative visited set: sparse per-partition
            # images (exact-image restore — no rehash drift) plus the
            # frontier key blocks the reseed path needs
            ckpt.update(self.hpt.state_dict())
            ckpt["fkey"] = [np.asarray(fk) for fk in frontier_keys]
        n_front = sum(int(g.shape[0]) for _r, g in frontier_blocks)
        parents, lanes, states, arch_meta = self._ckpt_store_args()
        ckpt_write(path, ckpt, self.store_states, parents,
                   lanes, states, res, dict(
                       spill=True, depth=depth, n_states=n_states,
                       n_vis=n_vis, n_front=n_front,
                       n_fblk=len(frontier_blocks),
                       SEGL=self.SEGL, SEGF=self.SEGF, VCAP=self.VCAP,
                       FCAP=self.FCAP, OCAP=self.OCAP,
                       fam_caps=list(self.FAM_CAPS),
                       host_table=self.host_table,
                       partitions=self.partitions, **arch_meta,
                       layout=2, chunk=self.chunk,
                       spec=self.ir.name,
                       sym_canon=self.fpr.sym_canon,
                       ir_fingerprint=self.ir.fingerprint(),
                       cfg=repr(self.cfg)),
                   keep=self.ckpt_keep)

    def _resume_portable(self, img):
        """Rebuild this engine's level-boundary state from a
        ``resil.portable.PortableImage`` (any source engine family /
        shape): the visited key set re-inserts into a fresh table
        image via the host claim-insert twin (engine/host_table
        ``insert_np`` — same home hash and probe walk as the device),
        the frontier rows become one spill block, and under
        ``host_table`` the host partitions rebuild by re-sweeping the
        whole key set (a re-partition: ANY --partitions works)."""
        from ..resil.portable import validate_image
        validate_image(img, self.ir.name, repr(self.cfg), self.W)
        self._restore_portable_archives(img)
        keys = img.keys.astype(np.uint32)
        rows, gids = img.expandable()
        frontier_blocks = []
        if gids.shape[0]:
            frontier_blocks.append((
                {k: np.ascontiguousarray(np.moveaxis(v, 0, -1))
                 for k, v in rows.items()}, gids))
        frontier_keys: List[np.ndarray] = []
        if self.host_table:
            # the authoritative set re-partitions into fresh host
            # images (chunked sweeps — every key is fresh by
            # construction); the device table reseeds with just the
            # frontier's keys, exactly the reseed-at-boundary state
            self.hpt = HostPartitionedTable(
                self.W, partitions=self.partitions,
                part_cap=self.part_cap)
            step = 1 << 16
            for i in range(0, keys.shape[0], step):
                self.hpt.sweep(np.ascontiguousarray(keys[i:i + step]))
            if gids.shape[0]:
                b = {k: jnp.asarray(v)
                     for k, v in self.ir.widen(rows).items()}
                fkeys = np.asarray(self._rootfp_jit(b)).astype(
                    np.uint32)
                frontier_keys.append(fkeys)
            else:
                fkeys = np.zeros((0, self.W), np.uint32)
            self.VCAP = self.VCAP0
            while fkeys.shape[0] + self.SEGL > \
                    self._LOAD_MAX * self.VCAP:
                self.VCAP *= 4
            tbl = np.full((self.W, self.VCAP), np.uint32(0xFFFFFFFF),
                          np.uint32)
            insert_np(tbl, fkeys)
            n_vis = int(fkeys.shape[0])
        else:
            while keys.shape[0] + self.SEGL > \
                    self._LOAD_MAX * self.VCAP:
                self.VCAP *= 4
            tbl = np.full((self.W, self.VCAP), np.uint32(0xFFFFFFFF),
                          np.uint32)
            insert_np(tbl, keys)
            n_vis = int(keys.shape[0])
        carry = self._fresh_spill_carry()
        carry["vis"] = tuple(jnp.asarray(tbl[w])
                             for w in range(self.W))
        return (carry, img.fresh_result(), frontier_blocks, frontier_keys,
                img.n_states, n_vis, img.depth)

    def _load_spill_checkpoint(self, path):
        z, meta = ckpt_read(path, repr(self.cfg), self.chunk,
                            self._SPILL_EXTRA_KEYS,
                            sharded=False, spill=True, expected_format=(
                                "layout", 2, "this engine's batch-last/"
                                "narrow-dtype storage layout"),
                            spec_name=self.ir.name,
                            sym_canon=self.fpr.sym_canon)
        if meta["SEGF"] != self.SEGF:
            # frontier re-segmentation is count-preserving (first-seen
            # is parent-order invariant), but a resumed run should be
            # bit-identical in every observable — including archive
            # block boundaries — so hold the segment shape fixed
            raise CheckpointError(
                f"checkpoint was written with seg={meta['SEGF']}; "
                f"resume with the same seg (engine has {self.SEGF})")
        self.SEGL, self.VCAP, self.FCAP, self.OCAP = (
            meta["SEGL"], meta["VCAP"], meta["FCAP"], meta["OCAP"])
        self.FAM_CAPS = tuple(int(c) for c in meta["fam_caps"])
        carry = self._fresh_spill_carry()
        if "carry|vis_idx" not in z or "carry|vis_keys" not in z:
            raise CheckpointError(
                f"{path}: checkpoint lacks the sparse visited-table "
                "records — written by an incompatible engine version; "
                "re-run without --resume")
        occ_idx = jnp.asarray(z["carry|vis_idx"])
        keys = z["carry|vis_keys"]
        if keys.shape[0] != self.W:
            raise CheckpointError(
                f"{path}: checkpoint has {keys.shape[0]} fingerprint "
                f"streams; engine expects {self.W} (fp64 vs fp128 "
                "mismatch)")
        carry["vis"] = tuple(
            carry["vis"][w].at[occ_idx].set(jnp.asarray(keys[w]))
            for w in range(self.W))
        row_keys = list(carry["lvl"].keys())
        frontier_blocks = []
        for i in range(meta["n_fblk"]):
            gids = z[f"carry|fblk|{i}|g"]
            rows = {k: z[f"carry|fblk|{i}|r|{k}"] for k in row_keys}
            frontier_blocks.append((rows, gids))
        if bool(meta.get("host_table")) != self.host_table:
            raise CheckpointError(
                f"{path}: checkpoint was written with host_table="
                f"{bool(meta.get('host_table'))}; resume with the "
                "same setting")
        frontier_keys = []
        if self.host_table:
            if meta.get("partitions") != self.partitions:
                raise CheckpointError(
                    f"{path}: checkpoint has {meta.get('partitions')} "
                    f"host-table partitions; engine has "
                    f"{self.partitions} — resume with the same "
                    "--partitions (counts are P-invariant, but the "
                    "serialized images are not)")
            self.hpt = HostPartitionedTable.from_state(
                lambda nm: z["carry|" + nm])
            frontier_keys = [np.asarray(z[f"carry|fkey|{i}"])
                             for i in range(meta["n_fblk"])]
        template = {"lvl": carry["lvl"]}       # archive key template
        self._load_archives(path, z, meta, template)
        res = ckpt_result(z, meta)
        z.close()             # all arrays extracted; don't leak the fd
        return (carry, res, frontier_blocks, frontier_keys,
                meta["n_states"], meta["n_vis"], meta["depth"])

    # ------------------------------------------------------------------

    def _grow_table_if_needed(self, carry, n_vis: int, min_add: int = 0):
        """Proactive load check, run at segment boundaries AND after
        every mid-segment spill/trip (n_vis moves there too): the table
        can take at most SEGL - FCAP more keys before the next check
        (``min_add`` raises that bound — the fused burst can admit up
        to burst_levels ring-widths before its next host sync).
        A rehash here is safe mid-segment — the cursor and frontier
        segment ride in the carry untouched — and far cheaper than the
        reactive hovf trip+replay it preempts."""
        need = n_vis + max(self.SEGL - self.OCAP, min_add)
        if need > self._LOAD_MAX * self.VCAP:
            while need > self._LOAD_MAX * self.VCAP:
                self.VCAP *= 4
            vis, claims = self._rehash_tables(carry["vis"], self.VCAP)
            carry = dict(carry, vis=vis, claims=claims)
        return carry

    def _handle_trip(self, carry, s, n_vis: int, verbose: bool):
        """Fix whatever tripped (segment full / caps / table), reset
        the sticky flags, and point the cursor back at the tripped
        chunk.  The tripped chunk left no trace (step docstring), so
        resuming there preserves enumeration order exactly."""
        trip_base = int(s[S_TRIP])
        assert trip_base >= 0, "trip flags set but no trip_base"
        blk = None
        old_shapes = (self.FCAP, self.OCAP, self.SEGL)
        if s[S_OVF]:
            carry, blk = self._spill_segment(carry, int(s[S_NLVL]))
        if s[S_OOVF]:
            # a chunk's fresh rows outran the post-dedup compaction
            # buffer (engine/bfs second-compaction note): double toward
            # FCAP, the hard bound on fresh per chunk
            self.OCAP = self._round_cap(min(self.FCAP, 2 * self.OCAP))
        if s[S_FOVF]:
            famx = [int(x) for x in s[S_LEN:S_LEN + len(self.FAM_CAPS)]]
            caps = list(self.FAM_CAPS)
            fam_over = False
            for fi, fam in enumerate(self.expander.families):
                hard = fam.n_lanes * self.chunk
                while caps[fi] < hard and famx[fi] > caps[fi]:
                    caps[fi] = min(2 * caps[fi], hard)
                    fam_over = True
            self.FAM_CAPS = tuple(caps)
            if not fam_over:
                self.FCAP = self._round_cap(min(
                    self.chunk * self.A,
                    max(2 * self.FCAP, (5 * int(sum(famx))) // 4)))
        if self.SEGL < 4 * self.OCAP:
            # the level segment keeps an OCAP-sized append margin
            self.SEGL = self._round_cap(4 * self.OCAP)
        if (self.FCAP, self.OCAP, self.SEGL) != old_shapes:
            # buffer shapes change: spill the committed rows FIRST
            # (a reset would drop them), then rebuild
            if blk is None:
                carry, blk = self._spill_segment(carry,
                                                 int(s[S_NLVL]))
            carry = self._reset_lvl_buffers(dict(carry))
        # FAM_CAPS-only growth retraces via the static jit arg —
        # no buffer rebuild needed
        if s[S_HOVF]:
            self.VCAP *= 4
            vis, claims = self._rehash_tables(carry["vis"], self.VCAP)
            carry = dict(carry, vis=vis, claims=claims)
        if verbose:
            print(f"trip at base {trip_base}: ovf={int(s[S_OVF])} "
                  f"fovf={int(s[S_FOVF])} hovf={int(s[S_HOVF])} "
                  f"oovf={int(s[S_OOVF])} "
                  f"-> FCAP={self.FCAP} OCAP={self.OCAP} "
                  f"SEGL={self.SEGL} "
                  f"VCAP={self.VCAP} fam_caps={self.FAM_CAPS}",
                  flush=True)
        carry["ovf"] = jnp.bool_(False)
        carry["fovf"] = jnp.bool_(False)
        carry["hovf"] = jnp.bool_(False)
        carry["oovf"] = jnp.bool_(False)
        carry["trip_base"] = jnp.int32(-1)
        carry["famx"] = jnp.zeros((len(self.expander.families),),
                                  jnp.int32)
        carry["base"] = jnp.int32(trip_base)
        return carry, blk, trip_base // self.chunk
