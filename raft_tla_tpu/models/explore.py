"""Oracle-side explicit-state BFS: the executable semantics of TLC's worker
loop (SURVEY §3.1) in plain Python.

This is deliberately the *simple, trustworthy* implementation: the TPU engine
in engine/ is differentially tested against it (same distinct-state counts,
same invariant verdicts, same reachable sets on small configs).

TLC semantics replicated here:
  * Fingerprint identity = VIEW = the 10 semantic vars, NOT history
    (raft.cfg:30, SURVEY §2.2); first-seen state keeps its history.
  * SYMMETRY: canonicalization under server permutations (raft.cfg:29).
    When InitServer ⊊ Server we restrict to the subgroup that fixes
    InitServer setwise — Permutations(Server) as the reference declares
    would be unsound there (InitServer is a constant; see SURVEY §2.10).
  * CONSTRAINT: violating states are checked but not expanded.
  * ACTION_CONSTRAINT: violating transitions are not generated.
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..config import CONFIG_ENTRY, NIL, ModelConfig
from . import predicates
from .raft import (Hist, State, init_state, successors,
                   _SRC_DST, MT_RVRESP, MT_AEREQ, MT_CATREQ, MT_COC)


# ---------------------------------------------------------------------------
# Symmetry canonicalization (raft.tla:1281, raft.cfg:29)
# ---------------------------------------------------------------------------

def symmetry_perms(cfg: ModelConfig) -> List[Tuple[int, ...]]:
    """Permutations of 0..n-1 fixing InitServer setwise (sound subgroup of
    the reference's Permutations(Server); identical when Server=InitServer)."""
    n = cfg.n_servers
    inside = [i for i in range(n) if cfg.init_mask >> i & 1]
    outside = [i for i in range(n) if not (cfg.init_mask >> i & 1)]
    perms = []
    for pi in itertools.permutations(inside):
        for po in itertools.permutations(outside):
            sigma = [0] * n
            for a, b in zip(inside, pi):
                sigma[a] = b
            for a, b in zip(outside, po):
                sigma[a] = b
            perms.append(tuple(sigma))
    return perms


def _perm_mask(mask: int, sigma, n: int) -> int:
    out = 0
    for i in range(n):
        if mask >> i & 1:
            out |= 1 << sigma[i]
    return out


def _perm_entry(e, sigma, n):
    term, etype, payload = e
    if etype == CONFIG_ENTRY:
        payload = _perm_mask(payload, sigma, n)
    return (term, etype, payload)


def _perm_entries(es, sigma, n):
    return tuple(_perm_entry(e, sigma, n) for e in es)


def _perm_msg(m, sigma, n):
    t = m[0]
    m = list(m)
    si, di = _SRC_DST[t]
    m[si] = sigma[m[si]]
    m[di] = sigma[m[di]]
    if t == MT_RVRESP:
        m[3] = _perm_entries(m[3], sigma, n)     # mlog
    elif t in (MT_AEREQ, MT_CATREQ):
        m[3 if t == MT_CATREQ else 4] = _perm_entries(
            m[3 if t == MT_CATREQ else 4], sigma, n)
    elif t == MT_COC:
        m[3] = sigma[m[3]]                        # mserver
    return tuple(m)


def relabel(sv: State, sigma, cfg: ModelConfig) -> State:
    """Apply server relabeling sigma (old id -> new id) to every lane of the
    state, including inside packed messages and set bitmasks (SURVEY §7.4
    hard part 1)."""
    n = cfg.n_servers
    inv = [0] * n
    for i in range(n):
        inv[sigma[i]] = i

    def pt(t):                   # permute a per-server tuple
        return tuple(t[inv[k]] for k in range(n))

    return State(
        ct=pt(sv.ct),
        st=pt(sv.st),
        vf=tuple(NIL if sv.vf[inv[k]] == NIL else sigma[sv.vf[inv[k]]]
                 for k in range(n)),
        log=tuple(_perm_entries(sv.log[inv[k]], sigma, n) for k in range(n)),
        ci=pt(sv.ci),
        vr=tuple(_perm_mask(sv.vr[inv[k]], sigma, n) for k in range(n)),
        vg=tuple(_perm_mask(sv.vg[inv[k]], sigma, n) for k in range(n)),
        ni=tuple(tuple(sv.ni[inv[k]][inv[l]] for l in range(n))
                 for k in range(n)),
        mi=tuple(tuple(sv.mi[inv[k]][inv[l]] for l in range(n))
                 for k in range(n)),
        msgs=tuple(sorted((_perm_msg(m, sigma, n), c) for m, c in sv.msgs)),
    )


def canonicalize(sv: State, perms, cfg: ModelConfig) -> State:
    """Min-over-permutations canonical representative.  States are plain
    nested tuples of ints (the absent-mcommitIndex field is the int -1), so
    the natural tuple order is total."""
    return min(relabel(sv, s, cfg) for s in perms)


# ---------------------------------------------------------------------------
# BFS driver
# ---------------------------------------------------------------------------

@dataclass
class Violation:
    invariant: str
    state: State
    hist: Hist
    trace: Optional[List[str]] = None


@dataclass
class ExploreResult:
    distinct_states: int
    generated_states: int
    depth: int
    violations: List[Violation] = field(default_factory=list)
    level_sizes: List[int] = field(default_factory=list)
    # key -> (State, Hist); only retained if keep_states=True
    states: Optional[Dict] = None
    # distinct pinned-prefix interior states invariant-checked but not
    # counted (TLC counts them; engine/bfs.CheckResult twin field)
    pin_interior_states: int = 0


def explore(cfg: ModelConfig, max_depth: int = 10 ** 9,
            max_states: int = 10 ** 9, keep_states: bool = False,
            stop_on_violation: bool = False,
            trace_violations: bool = False,
            seed_states=None) -> ExploreResult:
    """Level-synchronous BFS from Init (SURVEY §3.1), or from
    ``seed_states`` [(sv, h), ...] for punctuated search (the pinned-
    prefix technique of raft.tla:1198-1234 as replay-then-explore)."""
    perms = symmetry_perms(cfg) if cfg.symmetry else None
    inv_fns = [(nm, predicates.resolve_invariant(nm, cfg))
               for nm in cfg.invariants]
    con_fns = [predicates.CONSTRAINTS[nm] for nm in cfg.constraints]
    act_fns = [predicates.ACTION_CONSTRAINTS[nm]
               for nm in cfg.action_constraints]

    def key_of(sv: State):
        if perms:
            sv = canonicalize(sv, perms, cfg)
        return sv

    pin_interiors = None
    if seed_states is None and cfg.prefix_pins:
        # cfg-declared punctuated-search pins compile to seeds
        # (raft.tla:1198-1234; models/golden docstring)
        from .golden import prefix_pin_seeds
        seed_states, pin_interiors = prefix_pin_seeds(
            cfg, with_interior=True)
    roots = (seed_states if seed_states is not None
             else [init_state(cfg)])
    seen: Dict = {}
    parent: Dict = {}
    result = ExploreResult(distinct_states=0, generated_states=0, depth=0)
    if pin_interiors:
        # TLC counts + checks the prefix interior states; seeding at
        # the witness end skips them — invariant-check them here and
        # record the count divergence bound (models/golden docstring)
        int_seen = set()
        for sv, h in pin_interiors:
            k = key_of(sv)
            if k in int_seen:
                continue
            int_seen.add(k)
            result.pin_interior_states += 1
            for nm, fn in inv_fns:
                if not fn(sv, h, cfg):
                    result.violations.append(Violation(nm, sv, h))

    def check(sv, h, k):
        for nm, fn in inv_fns:
            if not fn(sv, h, cfg):
                v = Violation(nm, sv, h)
                if trace_violations:
                    v.trace = _trace_to(k, parent)
                result.violations.append(v)
                if stop_on_violation:
                    return False
        return True

    frontier = []
    for sv0, h0 in roots:
        k0 = key_of(sv0)
        if k0 in seen:
            continue
        seen[k0] = (sv0, h0)
        parent[k0] = (None, None)
        result.generated_states += 1
        if not check(sv0, h0, k0) and stop_on_violation:
            result.distinct_states = len(seen)
            result.states = seen if keep_states else None
            return result
        if all(f(sv0, h0, cfg) for f in con_fns):
            frontier.append((sv0, h0, k0))
    if stop_on_violation and result.violations:
        # a pinned-prefix interior state violated: stop after the root
        # level, exactly like the engines (engine/bfs.check)
        result.distinct_states = len(seen)
        result.states = seen if keep_states else None
        return result
    depth = 0
    while frontier and depth < max_depth and len(seen) < max_states:
        depth += 1
        nxt = []
        for sv, h, k in frontier:
            for label, sv2, h2 in successors(sv, h, cfg):
                if act_fns and not all(f(sv, h, sv2, h2, cfg)
                                       for f in act_fns):
                    continue
                result.generated_states += 1
                k2 = key_of(sv2)
                if k2 in seen:
                    continue
                seen[k2] = (sv2, h2)
                parent[k2] = (k, label)
                if not check(sv2, h2, k2) and stop_on_violation:
                    result.distinct_states = len(seen)
                    result.depth = depth
                    result.states = seen if keep_states else None
                    return result
                if all(f(sv2, h2, cfg) for f in con_fns):
                    nxt.append((sv2, h2, k2))
        result.level_sizes.append(len(nxt))
        frontier = nxt
    result.distinct_states = len(seen)
    result.depth = depth
    result.states = seen if keep_states else None
    return result


# ---------------------------------------------------------------------------
# Random-walk twin (TLC -simulate; oracle of sim/walker.SimEngine)
# ---------------------------------------------------------------------------

@dataclass
class WalkResult:
    steps: int                    # transitions actually taken
    restarts: int
    deadlocks: int
    sampled: int = 0              # successors drawn (incl. pruned
                                  # redraws — the engine's sampled_steps)
    hits: List[Violation] = field(default_factory=list)
    # labels of the walk that hit (root -> witness end), if any
    hit_trace: Optional[List[str]] = None
    hit_state: Optional[State] = None
    hit_hist: Optional[Hist] = None
    distinct_states: int = 0      # exact (set-based) distinct visited


def random_walk(cfg: ModelConfig, steps: int, max_depth: int = 64,
                seed: int = 0, stop_on_hit: bool = True,
                resample_pruned: bool = False) -> WalkResult:
    """Plain-Python uniform random walk — the executable oracle of the
    TPU sim engine (sim/walker.py) and of TLC's ``-simulate`` mode:

      * uniform choice over the enabled successor transitions of the
        current state (the same surface the engine's enabled-lane
        sampling draws from — tests/test_sim.py pins the per-step
        enabled COUNTS against the engine's lane grid);
      * CONSTRAINT semantics prune-not-reject: a violating successor is
        invariant-checked but never extended — the walk restarts from
        the root (``resample_pruned=False``, TLC parity) or redraws
        uniformly among the remaining enabled successors
        (``resample_pruned=True``, the engine's 'punctuated' prune
        handling: rejection sampling = uniform over the extendable
        subset);
      * bounded-depth restart at ``max_depth``; deadlock restarts.

    The RNG streams are NOT shared with the engine (python Random vs
    jax.random) — differential tests replay the ENGINE's recorded
    choices through the oracle transition relation instead
    (oracle_validates_walk)."""
    import random as _random
    rng = _random.Random(seed)
    inv_fns = [(nm, predicates.resolve_invariant(nm, cfg))
               for nm in cfg.invariants]
    con_fns = [predicates.CONSTRAINTS[nm] for nm in cfg.constraints]
    root = init_state(cfg)
    sv, h = root
    depth = 0
    labels: List[str] = []
    res = WalkResult(steps=0, restarts=0, deadlocks=0)
    seen = {_walk_key(root[0])}
    # depth-0 check: the engine checks the root once up front too
    for nm, fn in inv_fns:
        if not fn(root[0], root[1], cfg):
            res.hits.append(Violation(nm, root[0], root[1]))
            if res.hit_trace is None:
                res.hit_trace = []
                res.hit_state, res.hit_hist = root
    if res.hits and stop_on_hit:
        return _walk_finish(res, seen)
    for _ in range(steps):
        succ = walk_enabled(sv, h, cfg)      # the ONE sampling surface
        if not succ:
            res.deadlocks += 1
            res.restarts += 1
            sv, h = root
            depth = 0
            labels = []
            continue
        remaining = list(succ)

        def check(sv2, h2):
            ok = True
            for nm, fn in inv_fns:
                if not fn(sv2, h2, cfg):
                    res.hits.append(Violation(nm, sv2, h2))
                    if res.hit_trace is None:
                        res.hit_trace = list(labels)
                        res.hit_state, res.hit_hist = sv2, h2
                    ok = False
            return ok

        pruned_out = False
        while True:
            k = rng.randrange(len(remaining))
            label, sv2, h2 = remaining.pop(k)
            res.sampled += 1
            seen.add(_walk_key(sv2))
            labels.append(label)
            hit = not check(sv2, h2)
            if hit and stop_on_hit:
                return _walk_finish(res, seen)
            if all(f(sv2, h2, cfg) for f in con_fns):
                res.steps += 1           # accepted transition
                break
            labels.pop()
            if not resample_pruned or not remaining:
                pruned_out = True
                break
        depth += 1
        if pruned_out or depth >= max_depth:
            res.restarts += 1
            sv, h = root
            depth = 0
            labels = []
        else:
            sv, h = sv2, h2
    return _walk_finish(res, seen)


def _walk_finish(res: "WalkResult", seen) -> "WalkResult":
    res.distinct_states = len(seen)
    return res


def _walk_key(sv: State):
    return sv._replace(msgs=tuple(sorted(sv.msgs)))


def walk_enabled(sv: State, h: Hist, cfg: ModelConfig):
    """The enabled successor transitions the walk samples from (action
    constraints applied — the sampling surface)."""
    succ = successors(sv, h, cfg)
    act_fns = [predicates.ACTION_CONSTRAINTS[nm]
               for nm in cfg.action_constraints]
    if act_fns:
        succ = [(lb, s2, h2) for (lb, s2, h2) in succ
                if all(f(sv, h, s2, h2, cfg) for f in act_fns)]
    return succ


def oracle_validates_walk(cfg: ModelConfig, states: List[State]
                          ) -> List[str]:
    """Replay an engine-decoded state chain through the oracle
    transition relation: every consecutive pair must be one oracle
    transition (state equality modulo message-bag order — slot order is
    not part of state identity, ops/layout.py).  Returns the oracle's
    labels for the walk; raises ValueError at the first step the oracle
    cannot take.  This is the 'oracle replays it as a valid behavior'
    check the sim witness traces are accepted under."""
    sv, h = init_state(cfg)
    if _walk_key(states[0]) != _walk_key(sv):
        raise ValueError("walk does not start at Init")
    out: List[str] = []
    for t, nxt in enumerate(states[1:]):
        want = _walk_key(nxt)
        matches = [(lb, s2, h2) for (lb, s2, h2) in successors(sv, h, cfg)
                   if _walk_key(s2) == want]
        if not matches:
            raise ValueError(
                f"step {t + 1}: engine state is not an oracle successor")
        lb, sv, h = matches[0]
        out.append(lb)
    return out


def _trace_to(k, parent) -> List[str]:
    out = []
    while True:
        pk, label = parent[k]
        if pk is None:
            break
        out.append(label)
        k = pk
    return list(reversed(out))
