"""The reference's embedded punctuated-search witness traces.

`tlc_membership/raft.tla` pins deep scenario hunts to two hard-coded
history prefixes (SURVEY §2.9 "punctuated search"; Michaels et al,
Eurosys 2019):

  * a 20-record ConcurrentLeaders witness inside
    ``CommitWhenConcurrentLeaders_unique``       (raft.tla:1198-1204)
  * a 28-record CommitWhenConcurrentLeaders witness inside
    ``MajorityOfClusterRestarts_constraint``     (raft.tla:1228-1234)

Both constraints are ``∃ s1,s2,s3 distinct: IsPrefix(witness(s1,s2,s3),
history.global)`` — exploration is pinned to the witness for its length
and free afterwards.  Here the witnesses are expressed as oracle
successor-label sequences (the reference's s1,s2,s3 → ids 0,1,2); one
top-level step can emit 0, 1 or 2 history records (``UpdateTerm``
consumes nothing and logs nothing, raft.tla:826-832; a Reply logs
Receive + Send, raft.tla:308-314), so 18 labels produce the 20-record
trace and 9 more labels produce records 21-28.

``prefix_pin_seeds`` compiles a cfg's pins into BFS seed states: replay
the witness to its end state and seed the search there.  With SYMMETRY
on (the reference cfg always is) one assignment suffices — the pinned
reachable set is closed under relabeling, so the canonical exploration
from one assignment covers the ∃; without symmetry the seed set is the
witness end state under every injective (s1,s2,s3) assignment.
Divergence from TLC, documented: TLC also counts/checks the prefix
*interior* states (≤ the witness length); seeding at the end skips
those, but every extension state — the point of the technique — is
explored identically (tests/test_golden.py pins the witness hunts).
"""

from __future__ import annotations

import itertools
import re
from typing import List, Tuple

from ..config import ModelConfig
from .raft import init_state, successors

# --- records 1-20: two elections ending with concurrent leaders --------
# r2/r3: s1 sends RVReq to s2 first, then to itself (golden record order).
# r8/r9 and r18/r19: the remote vote response is received before the
# self-response.
CONCURRENT_LEADERS_LABELS = [
    "Timeout(0)",           # r1
    "RequestVote(0,1)",     # r2   Send RVReq 0->1
    "RequestVote(0,0)",     # r3   Send RVReq 0->0
    "HandleRVReq(0<-0)",    # r4,r5   Receive + Send RVResp (self grant)
    "UpdateTerm(1)",        # (no record; non-consuming, raft.tla:831)
    "HandleRVReq(1<-0)",    # r6,r7
    "HandleRVResp(0<-1)",   # r8
    "HandleRVResp(0<-0)",   # r9
    "BecomeLeader(0)",      # r10  leaders={0}
    "Timeout(1)",           # r11
    "RequestVote(1,1)",     # r12  Send RVReq 1->1 (self first, golden)
    "RequestVote(1,2)",     # r13
    "HandleRVReq(1<-1)",    # r14,r15
    "UpdateTerm(2)",        # (no record)
    "HandleRVReq(2<-1)",    # r16,r17
    "HandleRVResp(1<-2)",   # r18
    "HandleRVResp(1<-1)",   # r19
    "BecomeLeader(1)",      # r20  leaders={0,1}
]

# --- records 21-28: both leaders replicate; commit under 2 leaders -----
# ClientRequest bumps hadNumClientRequests but logs no record
# (raft.tla:488-497); AENoConflict appends without reply or record
# (raft.tla:668-672) — the success reply comes from the *second* receive
# of the same request (AlreadyDone, raft.tla:639-655).
CWCL_EXTENSION_LABELS = [
    "ClientRequest(0,1)",       # log[0] = [(2, Value, 1)]
    "AppendEntries(0,1)",       # r21  Send AEReq 0->1 (entry term 2)
    "ClientRequest(1,2)",       # log[1] = [(3, Value, 2)]
    "AppendEntries(1,2)",       # r22  Send AEReq 1->2 (entry term 3)
    "AENoConflict(2)",          # (no record) s2 appends the entry
    "AEAlreadyDone(2)",         # r23,r24  Receive + Send success reply
    "HandleAEResp(1<-2)",       # r25  matchIndex[1][2] := 1
    "AdvanceCommitIndex(1)",    # r26  CommitEntry (term 3, value 2)
    "RejectAEReq(1)",           # r27,r28  stale-term AEReq from s1
]

GOLDEN_20_KINDS = [
    "Timeout", "Send", "Send", "Receive", "Send", "Receive", "Send",
    "Receive", "Receive", "BecomeLeader",
    "Timeout", "Send", "Send", "Receive", "Send", "Receive", "Send",
    "Receive", "Receive", "BecomeLeader",
]

GOLDEN_28_KINDS = GOLDEN_20_KINDS + [
    "Send", "Send", "Receive", "Send", "Receive", "CommitEntry",
    "Receive", "Send",
]

# the two cfg-visible pin names (tlc_membership/raft.cfg:53-55)
PIN_LABELS = {
    "CommitWhenConcurrentLeaders_unique": CONCURRENT_LEADERS_LABELS,
    "MajorityOfClusterRestarts_constraint":
        CONCURRENT_LEADERS_LABELS + CWCL_EXTENSION_LABELS,
}

# which "(...)" argument positions of a golden label are server ids
# (ClientRequest's second argument is a client VALUE, raft.tla:488)
_SERVER_ARGS = {
    "Timeout": (0,), "RequestVote": (0, 1), "HandleRVReq": (0, 1),
    "UpdateTerm": (0,), "HandleRVResp": (0, 1), "BecomeLeader": (0,),
    "ClientRequest": (0,), "AppendEntries": (0, 1), "AENoConflict": (0,),
    "AEAlreadyDone": (0,), "HandleAEResp": (0, 1),
    "AdvanceCommitIndex": (0,), "RejectAEReq": (0,),
}

_LBL_RE = re.compile(r"^(\w+)\((.*)\)$")


def relabel_label(label: str, assign) -> str:
    """Map the server ids inside a golden label through ``assign``
    (0,1,2 -> the chosen s1,s2,s3)."""
    m = _LBL_RE.match(label)
    name, args = m.group(1), m.group(2)
    sep = "<-" if "<-" in args else ","
    parts = args.split(sep)
    roles = _SERVER_ARGS[name]
    parts = [str(assign[int(p)]) if k in roles else p
             for k, p in enumerate(parts)]
    return f"{name}({sep.join(parts)})"


def apply_label(sv, h, cfg: ModelConfig, label: str):
    matches = [(s2, h2) for lbl, s2, h2 in successors(sv, h, cfg)
               if lbl == label]
    if not matches:
        raise ValueError(f"no successor labelled {label!r}")
    if len(matches) > 1:
        raise ValueError(f"ambiguous label {label!r}")
    return matches[0]


def replay(labels: List[str], cfg: ModelConfig, start=None):
    """Replay a label sequence from Init (or ``start``); returns every
    intermediate (State, Hist) including the start."""
    sv, h = start if start is not None else init_state(cfg)
    states = [(sv, h)]
    for lbl in labels:
        sv, h = apply_label(sv, h, cfg, lbl)
        states.append((sv, h))
    return states


def prefix_pin_seeds(cfg: ModelConfig, with_interior: bool = False):
    """cfg.prefix_pins -> BFS seed states (oracle (State, Hist) pairs),
    or None when the cfg has no pins.  Multiple pins resolve to the
    longest witness (the 28-record trace extends the 20-record one, so
    the conjunction of both constraints IS the longer prefix).

    with_interior=True additionally returns the replayed prefix
    *interior* states (everything before each witness end, including
    Init) so callers can invariant-check them and report the
    distinct-state divergence from TLC — TLC counts and checks those
    states; seeding at the end skips them (module docstring)."""
    if not cfg.prefix_pins:
        return (None, None) if with_interior else None
    for nm in cfg.prefix_pins:
        if nm not in PIN_LABELS:
            raise KeyError(f"unknown prefix pin {nm!r}")
    labels = max((PIN_LABELS[nm] for nm in cfg.prefix_pins), key=len)
    if cfg.n_servers < 3:
        raise ValueError(
            "the punctuated-search witnesses quantify over 3 distinct "
            f"servers (raft.tla:1199); Server has {cfg.n_servers}")
    if cfg.symmetry:
        assigns = [(0, 1, 2)]
    else:
        assigns = list(itertools.permutations(range(cfg.n_servers), 3))
    seeds = []
    interiors = []
    for a in assigns:
        states = replay([relabel_label(l, a) for l in labels], cfg)
        seeds.append(states[-1])
        interiors.extend(states[:-1])
    return (seeds, interiors) if with_interior else seeds
