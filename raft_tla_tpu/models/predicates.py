"""Constraints, safety invariants, and scenario properties — oracle versions.

Literal transcriptions of tlc_membership/raft.tla:949-1278 over the Python
State/Hist representation.  The oracle versions may be slow (they enumerate
Quorum sets literally, walk the global history, etc.) — that is the point:
they are the semantics the vectorized predicates in ops/ are differentially
tested against.

TLC semantics reminders (SURVEY §2.8):
  * CONSTRAINT: a state violating it is still generated and invariant-checked
    but never *expanded*.
  * ACTION_CONSTRAINT: a transition violating it is not taken at all.
  * "Test case" INVARIANTS are negated reachability properties: a violation
    is the product (a witness trace).
"""

from __future__ import annotations

from typing import Callable, Dict

from ..config import (CANDIDATE, CONFIG_ENTRY, LEADER, NIL, ModelConfig,
                      popcount, mask_iter)
from .raft import (Hist, State, committed, get_config, is_prefix, last_term,
                   quorums)


# ---------------------------------------------------------------------------
# Constraints (raft.tla:1105-1137)
# ---------------------------------------------------------------------------

def bounded_in_flight_messages(sv, h, cfg):
    """BagCardinality(messages) <= MaxInFlightMessages (raft.tla:1105)."""
    return sum(c for _m, c in sv.msgs) <= cfg.max_inflight


def bounded_request_vote(sv, h, cfg):
    """<=1 copy of each RequestVoteRequest (raft.tla:1108-1110)."""
    from ..config import MT_RVREQ
    return all(c <= 1 for m, c in sv.msgs if m[0] == MT_RVREQ)


def bounded_log_size(sv, h, cfg):
    return all(len(l) <= cfg.bounds.max_log_length for l in sv.log)


def bounded_restarts(sv, h, cfg):
    return all(r <= cfg.bounds.max_restarts for r in h.restarted)


def bounded_timeouts(sv, h, cfg):
    return all(t <= cfg.bounds.max_timeouts for t in h.timeout)


def bounded_terms(sv, h, cfg):
    return all(t <= cfg.bounds.max_terms for t in sv.ct)


def bounded_client_requests(sv, h, cfg):
    return h.nreq <= cfg.bounds.max_client_requests


def bounded_tried_membership_changes(sv, h, cfg):
    return h.ntried <= cfg.bounds.max_tried_membership_changes


def bounded_membership_changes(sv, h, cfg):
    return h.nmc <= cfg.bounds.max_membership_changes


def elections_uncontested(sv, h, cfg):
    """<=1 concurrent Candidate (raft.tla:1126)."""
    return sum(1 for s in sv.st if s == CANDIDATE) <= 1


def clean_start_until_first_request(sv, h, cfg):
    """raft.tla:1128-1132."""
    if h.nleaders < 1 and h.nreq < 1:
        return (all(r == 0 for r in h.restarted) and
                sum(h.timeout) <= 1 and
                elections_uncontested(sv, h, cfg))
    return True


def clean_start_until_two_leaders(sv, h, cfg):
    """raft.tla:1134-1137."""
    if h.nleaders < 2:
        return sum(h.restarted) <= 1 and sum(h.timeout) <= 2
    return True


def clean_first_leader_election(sv, h, cfg):
    """CleanFirstLeaderElection (apalache_no_membership/raft.tla:766-770):
    until the first leader, no restarts and at most one candidate."""
    if h.nleaders < 1:
        return (all(r == 0 for r in h.restarted) and
                elections_uncontested(sv, h, cfg))
    return True


def commit_when_concurrent_leaders_constraint(sv, h, cfg):
    """CommitWhenConcurrentLeaders_constraint (raft.tla:1182-1186) — the
    WEAK punctuated-search pruning: by step >= 20 the history must
    contain a BecomeLeader with >= 2 simultaneous leaders (the comment at
    raft.tla:1188-1191 measures >1.2M length-20 traces still satisfy
    this; the strong prefix pin is our --seed-trace mode instead)."""
    if len(h.glob) < 20:
        return True
    return any(r[0] == "BecomeLeader" and popcount(r[2]) >= 2
               for r in h.glob)


CONSTRAINTS: Dict[str, Callable] = {
    "BoundedInFlightMessages": bounded_in_flight_messages,
    "BoundedRequestVote": bounded_request_vote,
    "BoundedLogSize": bounded_log_size,
    "BoundedRestarts": bounded_restarts,
    "BoundedTimeouts": bounded_timeouts,
    "BoundedTerms": bounded_terms,
    "BoundedClientRequests": bounded_client_requests,
    "BoundedTriedMembershipChanges": bounded_tried_membership_changes,
    "BoundedMembershipChanges": bounded_membership_changes,
    "ElectionsUncontested": elections_uncontested,
    "CleanStartUntilFirstRequest": clean_start_until_first_request,
    "CleanStartUntilTwoLeaders": clean_start_until_two_leaders,
    "CleanFirstLeaderElection": clean_first_leader_election,
    "CommitWhenConcurrentLeaders_constraint":
        commit_when_concurrent_leaders_constraint,
}


# ---------------------------------------------------------------------------
# Safety invariants (raft.tla:988-1099)
# ---------------------------------------------------------------------------

def leader_votes_quorum(sv, h, cfg):
    """LeaderVotesQuorum (raft.tla:988-993), guarded on no membership
    changes."""
    if h.nmc != 0:
        return True
    n = cfg.n_servers
    for i in range(n):
        if sv.st[i] != LEADER:
            continue
        voters = 0
        for j in range(n):
            if (sv.ct[j] > sv.ct[i] or
                    (sv.ct[j] == sv.ct[i] and sv.vf[j] == i)):
                voters |= 1 << j
        if voters not in quorums(get_config(sv, i, cfg), n):
            return False
    return True


def candidate_term_not_in_log(sv, h, cfg):
    """CandidateTermNotInLog (raft.tla:997-1004)."""
    if h.nmc != 0:
        return True
    n = cfg.n_servers
    for i in range(n):
        if sv.st[i] != CANDIDATE:
            continue
        voters = 0
        for j in range(n):
            if sv.ct[j] == sv.ct[i] and sv.vf[j] in (i, NIL):
                voters |= 1 << j
        if voters not in quorums(get_config(sv, i, cfg), n):
            continue
        for j in range(n):
            for e in sv.log[j]:
                if e[0] == sv.ct[i]:
                    return False
    return True


def election_safety(sv, h, cfg):
    """ElectionSafety (raft.tla:1009-1014)."""
    n = cfg.n_servers

    def max_or_zero(slog, term):
        idxs = [k + 1 for k, e in enumerate(slog) if e[0] == term]
        return max(idxs) if idxs else 0

    for i in range(n):
        if sv.st[i] != LEADER:
            continue
        mine = max_or_zero(sv.log[i], sv.ct[i])
        for j in range(n):
            if mine < max_or_zero(sv.log[j], sv.ct[i]):
                return False
    return True


def log_matching(sv, h, cfg):
    """LogMatching (raft.tla:1017-1021)."""
    n = cfg.n_servers
    for i in range(n):
        for j in range(n):
            upto = min(len(sv.log[i]), len(sv.log[j]))
            for k in range(upto):
                if (sv.log[i][k][0] == sv.log[j][k][0] and
                        sv.log[i][:k + 1] != sv.log[j][:k + 1]):
                    return False
    return True


def votes_granted_inv(sv, h, cfg):
    """VotesGrantedInv, corrected form (raft.tla:1048-1052)."""
    n = cfg.n_servers
    for i in range(n):
        j = sv.vf[i]
        if j != NIL and not is_prefix(committed(sv, i), sv.log[j]):
            return False
    return True


def votes_granted_inv_false(sv, h, cfg):
    """VotesGrantedInv_false — Ricketts' original, documented as violated
    (raft.tla:1038-1046); live in the apalache variant (SURVEY §2.7)."""
    n = cfg.n_servers
    for i in range(n):
        for j in mask_iter(sv.vg[i], n):
            if sv.ct[i] == sv.ct[j]:
                if not is_prefix(committed(sv, j), sv.log[i]):
                    return False
    return True


def quorum_log_inv(sv, h, cfg):
    """QuorumLogInv (raft.tla:1056-1060)."""
    n = cfg.n_servers
    for i in range(n):
        comm = committed(sv, i)
        for q in quorums(get_config(sv, i, cfg), n):
            if not any(is_prefix(comm, sv.log[j])
                       for j in mask_iter(q, n)):
                return False
    return True


def more_up_to_date_correct(sv, h, cfg):
    """MoreUpToDateCorrect (raft.tla:1066-1071)."""
    n = cfg.n_servers
    for i in range(n):
        for j in range(n):
            more = (last_term(sv.log[i]) > last_term(sv.log[j]) or
                    (last_term(sv.log[i]) == last_term(sv.log[j]) and
                     len(sv.log[i]) >= len(sv.log[j])))
            if more and not is_prefix(committed(sv, j), sv.log[i]):
                return False
    return True


def leader_completeness(sv, h, cfg):
    """LeaderCompleteness, corrected form (raft.tla:1089-1099).  An index
    beyond a leader's log length counts as a violation (TLC would raise an
    evaluation error on log[l][idx] there)."""
    n = cfg.n_servers
    leaders = [l for l in range(n) if sv.st[l] == LEADER]
    for i in range(n):
        comm = committed(sv, i)
        for idx in range(1, len(comm) + 1):
            entry = sv.log[i][idx - 1]
            for l in leaders:
                if sv.ct[l] > entry[0]:
                    if len(sv.log[l]) < idx or sv.log[l][idx - 1] != entry:
                        return False
    return True


def leader_completeness_false(sv, h, cfg):
    """LeaderCompleteness_false (raft.tla:1079-1083) — violated under
    concurrent leaders; live in the apalache variant."""
    n = cfg.n_servers
    for i in range(n):
        if sv.st[i] != LEADER:
            continue
        for j in range(n):
            if not is_prefix(committed(sv, j), sv.log[i]):
                return False
    return True


def one_at_a_time_membership_change_ok(sv, h, cfg):
    """OneAtATimeMembershipChangeOK — OURS, not the reference's.

    BASELINE.json names this invariant but no such operator exists in the
    reference (SURVEY.md preamble, phantom-name warning).  The one-at-a-time
    discipline is enforced operationally by HandleCheckOldConfig's gate
    `GetMaxConfigIndex(i) <= commitIndex[i]` (raft.tla:800).  We state the
    induced state property: every log suffix beyond a server's commitIndex
    contains at most one ConfigEntry."""
    n = cfg.n_servers
    for i in range(n):
        uncommitted_configs = sum(
            1 for e in sv.log[i][sv.ci[i]:] if e[1] == CONFIG_ENTRY)
        if uncommitted_configs > 1:
            return False
    return True


# ---------------------------------------------------------------------------
# Scenario ("test case") properties (raft.tla:1143-1278) — negated
# reachability; oracle versions read the full global history.
# ---------------------------------------------------------------------------

def _current_leaders(sv):
    m = 0
    for k, s in enumerate(sv.st):
        if s == LEADER:
            m |= 1 << k
    return m


def bounded_trace(sv, h, cfg):
    return len(h.glob) <= cfg.bounds.max_trace


def first_become_leader(sv, h, cfg):
    return not any(r[0] == "BecomeLeader" for r in h.glob)


def first_commit(sv, h, cfg):
    return not any(c > 0 for c in sv.ci)


def first_restart(sv, h, cfg):
    return not any(r >= 2 for r in h.restarted)


def leadership_change(sv, h, cfg):
    return h.nleaders < 2


def membership_change(sv, h, cfg):
    return h.nmc < 1


def multiple_membership_changes(sv, h, cfg):
    return h.nmc < 2


def concurrent_leaders(sv, h, cfg):
    return popcount(_current_leaders(sv)) < 2


def entry_committed(sv, h, cfg):
    return not any(r[0] == "CommitEntry" for r in h.glob)


def commit_when_concurrent_leaders(sv, h, cfg):
    """CommitWhenConcurrentLeaders (raft.tla:1165-1176)."""
    if popcount(_current_leaders(sv)) < 2:
        return True
    seen_bl2 = False
    for k, r in enumerate(h.glob):          # k is 0-based; spec is 1-based
        if r[0] == "BecomeLeader" and popcount(r[2]) >= 2:
            seen_bl2 = True
        elif r[0] == "CommitEntry" and seen_bl2:
            # need Len(glob) >= (k+1) + 2 in 1-based terms
            if len(h.glob) >= k + 3:
                return False
    return True


def majority_of_cluster_restarts(sv, h, cfg):
    """MajorityOfClusterRestarts (raft.tla:1212-1226)."""
    n = cfg.n_servers
    nontrivial = any(
        i != j and len(sv.log[i]) >= 2 and len(sv.log[j]) >= 1
        for i in range(n) for j in range(n))
    if not nontrivial:
        return True
    full = (1 << n) - 1
    maj_restarted = any(
        all(h.restarted[i] >= 1 for i in mask_iter(q, n))
        for q in quorums(full, n))
    if not maj_restarted:
        return True
    restart_positions = [k for k, r in enumerate(h.glob)
                         if r[0] == "Restart"]
    for a in range(len(restart_positions)):
        for b in range(a + 1, len(restart_positions)):
            if restart_positions[b] - restart_positions[a] < 6:
                return True     # activity-gap condition fails => no witness
    return False


def add_successful(sv, h, cfg):
    """AddSucessful [sic] (raft.tla:1236-1237)."""
    return not any(r[0] == "AddServer" for r in h.glob)


def membership_change_commits(sv, h, cfg):
    return not any(r[0] == "CommitMembershipChange" for r in h.glob)


def multiple_membership_changes_commit(sv, h, cfg):
    return sum(1 for r in h.glob
               if r[0] == "CommitMembershipChange") < 2


def add_commits(sv, h, cfg):
    """AddCommits (raft.tla:1248-1256)."""
    added_so_far = 0
    for r in h.glob:
        if r[0] == "AddServer":
            added_so_far |= 1 << r[2]
        elif r[0] == "CommitMembershipChange" and (r[2] & added_so_far):
            return False
    return True


def newly_joined_become_leader(sv, h, cfg):
    """NewlyJoinedBecomeLeader (raft.tla:1258-1266)."""
    added_so_far = 0
    for r in h.glob:
        if r[0] == "AddServer":
            added_so_far |= 1 << r[2]
        elif r[0] == "BecomeLeader" and (added_so_far >> r[1] & 1):
            return False
    return True


def leader_changes_during_conf_change(sv, h, cfg):
    """LeaderChangesDuringConfChange (raft.tla:1268-1278)."""
    open_add = False
    for r in h.glob:
        if r[0] == "AddServer":
            open_add = True
        elif r[0] == "CommitMembershipChange":
            open_add = False
        elif r[0] == "BecomeLeader" and open_add:
            return False
    return True


INVARIANTS: Dict[str, Callable] = {
    # Safety
    "LeaderVotesQuorum": leader_votes_quorum,
    "CandidateTermNotInLog": candidate_term_not_in_log,
    "ElectionSafety": election_safety,
    "LogMatching": log_matching,
    "VotesGrantedInv": votes_granted_inv,
    "VotesGrantedInv_false": votes_granted_inv_false,
    "QuorumLogInv": quorum_log_inv,
    "MoreUpToDateCorrect": more_up_to_date_correct,
    "LeaderCompleteness": leader_completeness,
    "LeaderCompleteness_false": leader_completeness_false,
    "OneAtATimeMembershipChangeOK": one_at_a_time_membership_change_ok,
    # Scenario / trace generation
    "BoundedTrace": bounded_trace,
    "FirstBecomeLeader": first_become_leader,
    "FirstCommit": first_commit,
    "FirstRestart": first_restart,
    "LeadershipChange": leadership_change,
    "MembershipChange": membership_change,
    "MultipleMembershipChanges": multiple_membership_changes,
    "ConcurrentLeaders": concurrent_leaders,
    "EntryCommitted": entry_committed,
    "CommitWhenConcurrentLeaders": commit_when_concurrent_leaders,
    "MajorityOfClusterRestarts": majority_of_cluster_restarts,
    "AddSucessful": add_successful,
    "MembershipChangeCommits": membership_change_commits,
    "MultipleMembershipChangesCommit": multiple_membership_changes_commit,
    "AddCommits": add_commits,
    "NewlyJoinedBecomeLeader": newly_joined_become_leader,
    "LeaderChangesDuringConfChange": leader_changes_during_conf_change,
}


def resolve_invariant(name: str, cfg: ModelConfig) -> Callable:
    """apalache_no_membership knowingly ships the *_false forms as its live
    VotesGrantedInv / LeaderCompleteness (SURVEY §2.7 divergence)."""
    if cfg.apalache_variant and name in ("VotesGrantedInv",
                                         "LeaderCompleteness"):
        return INVARIANTS[name + "_false"]
    return INVARIANTS[name]


# ---------------------------------------------------------------------------
# Action constraints (raft.tla:1207-1210)
# ---------------------------------------------------------------------------

def commit_when_concurrent_leaders_action_constraint(sv, h, sv2, h2, cfg):
    """After step 20, no transition may produce a Candidate
    (raft.tla:1207-1210).  `Len(history.global)` is evaluated on the
    unprimed state; state' on the primed one."""
    if len(h.glob) >= 20:
        return all(s != CANDIDATE for s in sv2.st)
    return True


ACTION_CONSTRAINTS: Dict[str, Callable] = {
    "CommitWhenConcurrentLeaders_action_constraint":
        commit_when_concurrent_leaders_action_constraint,
}


# Properties whose oracle evaluation scans the glob *record sequence*
# (not just the counters).  A seed emitted by the tpu engine carries no
# records (decode reconstructs counters only, ops/codec.py), so the
# oracle cannot evaluate these faithfully on such a seed — the CLI
# refuses that combination (cli.py cmd_check).
GLOB_DEPENDENT = frozenset({
    "BoundedTrace", "FirstBecomeLeader", "EntryCommitted",
    "CommitWhenConcurrentLeaders", "MajorityOfClusterRestarts",
    "AddSucessful", "MembershipChangeCommits",
    "MultipleMembershipChangesCommit", "AddCommits",
    "NewlyJoinedBecomeLeader", "LeaderChangesDuringConfChange",
    "CommitWhenConcurrentLeaders_constraint",
    "CommitWhenConcurrentLeaders_action_constraint",
})
