"""Plain-Python executable reference model of the Raft spec family (the oracle).

This is layer L0 of the build plan (SURVEY.md §7.2): a dict/tuple-based,
*painfully literal* transcription of the semantics of
`/root/reference/tlc_membership/raft.tla` (line references in each function).
It exists so the vectorized JAX kernels have a ground truth to be
differentially tested against: same successor sets, same distinct-state
counts, same invariant verdicts.

Deliberate literalism notes (all cited):
  * `HandleCheckOldConfig`'s first branch guard is
    `state[i] /= Leader \\/ m.mterm = currentTerm[i]` (raft.tla:796) — for a
    Leader at the message's term this makes the discard branch *and* the
    process branch both enabled (two successors), and a stale-term message at
    a Leader permanently unreceivable.  We reproduce this exactly.
  * `UpdateTerm` (raft.tla:826-832) overlaps `HandleCatchupRequest`'s
    `m.mterm >= currentTerm[i]` branch (raft.tla:729) and
    `HandleCheckOldConfig`'s discard branch: one message can yield several
    successors.
  * `HandleCatchupRequest` replies with `mmatchIndex |-> Len(log[i])` using
    the *unprimed* log (raft.tla:740) — i.e. the pre-splice length.
  * `HandleCatchupResponse`'s follow-up CatchupRequest (raft.tla:762-771)
    reads the *unprimed* nextIndex and omits the `mcommitIndex` field that
    `AddNewServer`'s CatchupRequest has (raft.tla:551); records with
    different field sets are distinct TLA+ values, so the omission is part
    of message identity.  We encode "absent" as mcommit = -1 (real
    mcommitIndex values are >= 0, and an int keeps messages orderable for
    the canonical sorted-bag representation).
  * `ConflictAppendEntriesRequest` / `NoConflictAppendEntriesRequest` /
    `ReturnToFollowerState` do **not** consume the message and do not touch
    history (raft.tla:632-636, 658-672).
  * `ClientRequest` bumps hadNumClientRequests but appends **no** global
    history record (raft.tla:488-497).

Servers are 0-based ints; Nil is -1; sets of servers are int bitmasks.
A log entry is a tuple ``(term, etype, payload)`` where payload is the client
value for VALUE_ENTRY and a server bitmask for CONFIG_ENTRY.
"""

from __future__ import annotations

import itertools
from collections import namedtuple
from typing import List, Tuple

from ..config import (
    CANDIDATE, CONFIG_ENTRY, FOLLOWER, LEADER, MT_AEREQ, MT_AERESP, MT_CATREQ,
    MT_CATRESP, MT_COC, MT_RVREQ, MT_RVRESP, NEXT_ASYNC, NEXT_ASYNC_CRASH,
    NEXT_DYNAMIC, NEXT_FULL, NIL, VALUE_ENTRY, ModelConfig, popcount,
    mask_iter,
)

# ---------------------------------------------------------------------------
# State representation
# ---------------------------------------------------------------------------

# The 10 semantic variables = the VIEW (raft.tla:193, raft.cfg:30).
State = namedtuple("State", [
    "ct",    # currentTerm : tuple[int]          (raft.tla:136-138)
    "st",    # state       : tuple[int]          (raft.tla:140-142)
    "vf",    # votedFor    : tuple[int], NIL=-1  (raft.tla:144-147)
    "log",   # log         : tuple[tuple[entry]] (raft.tla:153-155)
    "ci",    # commitIndex : tuple[int]          (raft.tla:157-159)
    "vr",    # votesResponded : tuple[int bitmask] (raft.tla:165-167)
    "vg",    # votesGranted   : tuple[int bitmask] (raft.tla:170-172)
    "ni",    # nextIndex   : tuple[tuple[int]]   (raft.tla:178-180)
    "mi",    # matchIndex  : tuple[tuple[int]]   (raft.tla:183-185)
    "msgs",  # messages bag: tuple[(msg, count)], sorted (raft.tla:114-123)
])

# The history variable (raft.tla:127-131, 379-386). Excluded from the VIEW.
Hist = namedtuple("Hist", [
    "restarted",  # tuple[int] per server
    "timeout",    # tuple[int] per server
    "nleaders",   # hadNumLeaders
    "nreq",       # hadNumClientRequests
    "ntried",     # hadNumTriedMembershipChanges
    "nmc",        # hadNumMembershipChanges
    "glob",       # tuple of action records (see below)
])

# Global-history action records, mirroring raft.tla's ACTION values:
#   ("Send", executedOn, msg)             SendDirect     raft.tla:248
#   ("Receive", executedOn, msg)          Discard/Reply  raft.tla:281,311
#   ("Restart", i)                                       raft.tla:410
#   ("Timeout", i)                                       raft.tla:426
#   ("BecomeLeader", i, leaders_mask)                    raft.tla:483
#   ("CommitEntry", i, entry)                            raft.tla:537
#   ("CommitMembershipChange", i, config_mask)           raft.tla:534
#   ("TryAddServer", i, added)                           raft.tla:251
#   ("TryRemoveServer", i, removed)                      raft.tla:253
#   ("AddServer", i, added)                              raft.tla:802
#   ("RemoveServer", i, removed)                         raft.tla:803

# Message tuples (type tag first; field order mirrors the packed codec):
#   (MT_RVREQ,   term, lastLogTerm, lastLogIndex, src, dst)     raft.tla:434-439
#   (MT_RVRESP,  term, granted, mlog, src, dst)                 raft.tla:588-596
#   (MT_AEREQ,   term, prevIdx, prevTerm, entries, mcommit, src, dst) :460-467
#   (MT_AERESP,  term, success, matchIdx, src, dst)             raft.tla:648-654
#   (MT_CATREQ,  term, logLen, entries, mcommit, src, dst, rounds)    :547-554
#                 (mcommit is -1 ["field absent"] for the follow-up requests of
#                  HandleCatchupResponse, raft.tla:762-771)
#   (MT_CATRESP, term, success, matchIdx, src, dst, roundsLeft) raft.tla:720-744
#   (MT_COC,     term, madd, mserver, src, dst)                 raft.tla:563-568

_SRC_DST = {
    MT_RVREQ: (4, 5), MT_RVRESP: (4, 5), MT_AEREQ: (6, 7), MT_AERESP: (4, 5),
    MT_CATREQ: (5, 6), MT_CATRESP: (4, 5), MT_COC: (4, 5),
}


def msg_src(m):
    return m[_SRC_DST[m[0]][0]]


def msg_dst(m):
    return m[_SRC_DST[m[0]][1]]


def msg_term(m):
    return m[1]


# ---------------------------------------------------------------------------
# JSON-able (de)serialization — the seed-trace file format for punctuated
# search (`check --seed-trace`, the equivalent of the spec's hard-coded
# prefix pins at raft.tla:1198-1234).
# ---------------------------------------------------------------------------

def _deep_tuple(x):
    if isinstance(x, list):
        return tuple(_deep_tuple(e) for e in x)
    return x


def _deep_list(x):
    if isinstance(x, tuple):
        return [_deep_list(e) for e in x]
    return x


def state_to_obj(sv: "State", h: "Hist") -> dict:
    return {"state": [_deep_list(f)
                      for f in (sv.ct, sv.st, sv.vf, sv.log, sv.ci, sv.vr,
                                sv.vg, sv.ni, sv.mi, sv.msgs)],
            "hist": [_deep_list(h.restarted), _deep_list(h.timeout),
                     h.nleaders, h.nreq, h.ntried, h.nmc,
                     _deep_list(h.glob)]}


def state_from_obj(obj: dict):
    f = [_deep_tuple(x) for x in obj["state"]]
    sv = State(ct=f[0], st=f[1], vf=f[2], log=f[3], ci=f[4], vr=f[5],
               vg=f[6], ni=f[7], mi=f[8], msgs=f[9])
    hh = obj["hist"]
    h = Hist(restarted=_deep_tuple(hh[0]), timeout=_deep_tuple(hh[1]),
             nleaders=hh[2], nreq=hh[3], ntried=hh[4], nmc=hh[5],
             glob=_deep_tuple(hh[6]))
    return sv, h


# ---------------------------------------------------------------------------
# Small helpers
# ---------------------------------------------------------------------------

def tup_set(t, i, v):
    return t[:i] + (v,) + t[i + 1:]


def row_set(mat, i, row):
    return mat[:i] + (row,) + mat[i + 1:]


def cell_set(mat, i, j, v):
    return row_set(mat, i, tup_set(mat[i], j, v))


def last_term(log):
    """LastTerm (raft.tla:221)."""
    return log[-1][0] if log else 0


def get_config_of_log(slog, cfg: ModelConfig) -> int:
    """GetHistoricalConfig on one log (raft.tla:346-360): the value of the
    latest ConfigEntry, committed or not; InitServer if none."""
    for k in range(len(slog) - 1, -1, -1):
        if slog[k][1] == CONFIG_ENTRY:
            return slog[k][2]
    return cfg.init_mask


def get_config(sv: State, i: int, cfg: ModelConfig) -> int:
    return get_config_of_log(sv.log[i], cfg)


def max_config_index(slog) -> int:
    """GetMaxConfigIndex (raft.tla:346-351), 1-based; 0 if none."""
    for k in range(len(slog) - 1, -1, -1):
        if slog[k][1] == CONFIG_ENTRY:
            return k + 1
    return 0


def in_quorum(set_mask: int, config_mask: int) -> bool:
    """set ∈ Quorum(config) (raft.tla:217): subset of config + majority."""
    if set_mask & ~config_mask:
        return False
    return 2 * popcount(set_mask) > popcount(config_mask)


def quorums(config_mask: int, n: int) -> List[int]:
    """Literal Quorum(config) enumeration — oracle-only (kernels use the
    popcount test; differential tests tie them together)."""
    members = list(mask_iter(config_mask, n))
    out = []
    for r in range(len(members) + 1):
        for sub in itertools.combinations(members, r):
            m = 0
            for s in sub:
                m |= 1 << s
            if 2 * len(sub) > len(members):
                out.append(m)
    return out


def is_prefix(a, b) -> bool:
    """IsPrefix(a, b) (SequencesExt.tla:134-140)."""
    return len(a) <= len(b) and tuple(b[:len(a)]) == tuple(a)


def committed(sv: State, i: int):
    """Committed(i) == SubSeq(log[i], 1, commitIndex[i]) (raft.tla:969).

    commitIndex can exceed Len(log[i]) after a catchup splice shortens the
    log (HandleCatchupRequest, raft.tla:734-736, leaves commitIndex
    UNCHANGED); TLC would raise an evaluation error there.  We clamp, which
    only matters on states TLC could not check at all."""
    return sv.log[i][:min(sv.ci[i], len(sv.log[i]))]


def bag_add(msgs, m):
    """WithMessage (raft.tla:226): bag count +1."""
    d = dict(msgs)
    d[m] = d.get(m, 0) + 1
    return tuple(sorted(d.items()))


def bag_remove(msgs, m):
    """WithoutMessage (raft.tla:231) via TypedBags (-) (TypedBags.tla:59-69):
    zero-count elements are removed from the domain."""
    d = dict(msgs)
    c = d.get(m, 0)
    if c <= 1:
        d.pop(m, None)
    else:
        d[m] = c - 1
    return tuple(sorted(d.items()))


# ---------------------------------------------------------------------------
# Send / Discard / Reply family (raft.tla:247-328, Direct variants)
# ---------------------------------------------------------------------------

def _send(sv: State, h: Hist, m) -> Tuple[State, Hist]:
    """SendDirect (raft.tla:247-263): Catchup/CheckOldConfig sends also log a
    TryAddServer/TryRemoveServer record and bump hadNumTriedMembershipChanges."""
    glob = h.glob
    ntried = h.ntried
    if m[0] == MT_CATREQ:
        glob = glob + (("TryAddServer", msg_src(m), msg_dst(m)),)
        ntried += 1
    elif m[0] == MT_COC:
        glob = glob + (("TryRemoveServer", msg_src(m), m[3]),)  # m.mserver
        ntried += 1
    glob = glob + (("Send", msg_src(m), m),)
    return sv._replace(msgs=bag_add(sv.msgs, m)), h._replace(glob=glob,
                                                             ntried=ntried)


def _discard(sv: State, h: Hist, m) -> Tuple[State, Hist]:
    """DiscardDirect (raft.tla:280-283)."""
    glob = h.glob + (("Receive", msg_dst(m), m),)
    return sv._replace(msgs=bag_remove(sv.msgs, m)), h._replace(glob=glob)


def _discard_with_mc(sv, h, m, extra) -> Tuple[State, Hist]:
    """DiscardDirectWithMembershipChange (raft.tla:285-290)."""
    glob = h.glob + (("Receive", msg_dst(m), m), extra)
    return (sv._replace(msgs=bag_remove(sv.msgs, m)),
            h._replace(glob=glob, nmc=h.nmc + 1))


def _reply(sv: State, h: Hist, resp, req) -> Tuple[State, Hist]:
    """ReplyDirect (raft.tla:308-314): add response, remove request, log
    Receive-then-Send."""
    msgs = bag_remove(bag_add(sv.msgs, resp), req)
    glob = h.glob + (("Receive", msg_dst(req), req),
                     ("Send", msg_src(resp), resp))
    return sv._replace(msgs=msgs), h._replace(glob=glob)


# ---------------------------------------------------------------------------
# Initial state (raft.tla:367-393)
# ---------------------------------------------------------------------------

def init_state(cfg: ModelConfig) -> Tuple[State, Hist]:
    n = cfg.n_servers
    sv = State(
        ct=(1,) * n,
        st=(FOLLOWER,) * n,
        vf=(NIL,) * n,
        log=((),) * n,
        ci=(0,) * n,
        vr=(0,) * n,
        vg=(0,) * n,
        ni=tuple((1,) * n for _ in range(n)),
        mi=tuple((0,) * n for _ in range(n)),
        msgs=(),
    )
    h = Hist(restarted=(0,) * n, timeout=(0,) * n, nleaders=0, nreq=0,
             ntried=0, nmc=0, glob=())
    return sv, h


# ---------------------------------------------------------------------------
# Top-level actions (SURVEY §2.4)
# ---------------------------------------------------------------------------

def restart(sv, h, i, cfg):
    """Restart(i) (raft.tla:401-411): keeps currentTerm, votedFor, log."""
    n = cfg.n_servers
    sv2 = sv._replace(
        st=tup_set(sv.st, i, FOLLOWER),
        vr=tup_set(sv.vr, i, 0),
        vg=tup_set(sv.vg, i, 0),
        ni=row_set(sv.ni, i, (1,) * n),
        mi=row_set(sv.mi, i, (0,) * n),
        ci=tup_set(sv.ci, i, 0),
    )
    h2 = h._replace(restarted=tup_set(h.restarted, i, h.restarted[i] + 1),
                    glob=h.glob + (("Restart", i),))
    return [(f"Restart({i})", sv2, h2)]


def timeout(sv, h, i, cfg):
    """Timeout(i) (raft.tla:415-427)."""
    if sv.st[i] not in (FOLLOWER, CANDIDATE):
        return []
    if not (get_config(sv, i, cfg) >> i & 1):
        return []
    sv2 = sv._replace(
        st=tup_set(sv.st, i, CANDIDATE),
        ct=tup_set(sv.ct, i, sv.ct[i] + 1),
        vf=tup_set(sv.vf, i, NIL),
        vr=tup_set(sv.vr, i, 0),
        vg=tup_set(sv.vg, i, 0),
    )
    h2 = h._replace(timeout=tup_set(h.timeout, i, h.timeout[i] + 1),
                    glob=h.glob + (("Timeout", i),))
    return [(f"Timeout({i})", sv2, h2)]


def request_vote(sv, h, i, j, cfg):
    """RequestVote(i, j) (raft.tla:431-440); includes the j = i self-send."""
    if sv.st[i] != CANDIDATE:
        return []
    if not ((get_config(sv, i, cfg) & ~sv.vr[i]) >> j & 1):
        return []
    m = (MT_RVREQ, sv.ct[i], last_term(sv.log[i]), len(sv.log[i]), i, j)
    sv2, h2 = _send(sv, h, m)
    return [(f"RequestVote({i},{j})", sv2, h2)]


def append_entries(sv, h, i, j, cfg):
    """AppendEntries(i, j) (raft.tla:446-468): up to one entry."""
    if i == j or sv.st[i] != LEADER:
        return []
    if not (get_config(sv, i, cfg) >> j & 1):
        return []
    nij = sv.ni[i][j]
    prev_idx = nij - 1
    prev_term = (sv.log[i][prev_idx - 1][0]
                 if 0 < prev_idx <= len(sv.log[i]) else 0)
    last_entry = min(len(sv.log[i]), nij)
    entries = sv.log[i][nij - 1:last_entry]          # SubSeq(log, nij, last)
    m = (MT_AEREQ, sv.ct[i], prev_idx, prev_term, entries,
         min(sv.ci[i], last_entry), i, j)
    sv2, h2 = _send(sv, h, m)
    return [(f"AppendEntries({i},{j})", sv2, h2)]


def become_leader(sv, h, i, cfg):
    """BecomeLeader(i) (raft.tla:472-484)."""
    if sv.st[i] != CANDIDATE:
        return []
    if not in_quorum(sv.vg[i], get_config(sv, i, cfg)):
        return []
    n = cfg.n_servers
    leaders = 1 << i
    for k in range(n):
        if sv.st[k] == LEADER:
            leaders |= 1 << k
    sv2 = sv._replace(
        st=tup_set(sv.st, i, LEADER),
        ni=row_set(sv.ni, i, (len(sv.log[i]) + 1,) * n),
        mi=row_set(sv.mi, i, (0,) * n),
    )
    h2 = h._replace(nleaders=h.nleaders + 1,
                    glob=h.glob + (("BecomeLeader", i, leaders),))
    return [(f"BecomeLeader({i})", sv2, h2)]


def client_request(sv, h, i, v, cfg):
    """ClientRequest(i, v) (raft.tla:488-497).  No global history record."""
    if sv.st[i] != LEADER:
        return []
    entry = (sv.ct[i], VALUE_ENTRY, v)
    sv2 = sv._replace(log=row_set(sv.log, i, sv.log[i] + (entry,)))
    h2 = h._replace(nreq=h.nreq + 1)
    return [(f"ClientRequest({i},{v})", sv2, h2)]


def advance_commit_index(sv, h, i, cfg):
    """AdvanceCommitIndex(i) (raft.tla:504-539)."""
    if sv.st[i] != LEADER:
        return []
    config = get_config(sv, i, cfg)
    agree_indexes = []
    for idx in range(1, len(sv.log[i]) + 1):
        agree = 1 << i
        for k in mask_iter(config, cfg.n_servers):
            if sv.mi[i][k] >= idx:
                agree |= 1 << k
        if in_quorum(agree, config):
            agree_indexes.append(idx)
    new_ci = sv.ci[i]
    if agree_indexes and sv.log[i][max(agree_indexes) - 1][0] == sv.ct[i]:
        new_ci = max(agree_indexes)
    did_commit = new_ci > sv.ci[i]
    sv2 = sv._replace(ci=tup_set(sv.ci, i, new_ci))
    h2 = h
    if did_commit:
        entry = sv.log[i][new_ci - 1]
        is_mc = (entry[1] == CONFIG_ENTRY and
                 entry[2] != get_config_of_log(sv.log[i][:new_ci - 1], cfg))
        if is_mc:
            h2 = h._replace(glob=h.glob +
                            (("CommitMembershipChange", i, entry[2]),))
        else:
            h2 = h._replace(glob=h.glob + (("CommitEntry", i, entry),))
    return [(f"AdvanceCommitIndex({i})", sv2, h2)]


def add_new_server(sv, h, i, j, cfg):
    """AddNewServer(i, j) (raft.tla:542-555): resets j's term/votedFor (a
    modeling shortcut — the leader writes another server's state) and sends
    the first CatchupRequest."""
    if sv.st[i] != LEADER:
        return []
    if get_config(sv, i, cfg) >> j & 1:
        return []
    sv1 = sv._replace(ct=tup_set(sv.ct, j, 1), vf=tup_set(sv.vf, j, NIL))
    m = (MT_CATREQ, sv.ct[i], sv.mi[i][j],
         sv.log[i][sv.ni[i][j] - 1:sv.ci[i]],   # SubSeq(log, ni, ci)
         sv.ci[i], i, j, cfg.num_rounds)
    sv2, h2 = _send(sv1, h, m)
    return [(f"AddNewServer({i},{j})", sv2, h2)]


def delete_server(sv, h, i, j, cfg):
    """DeleteServer(i, j) (raft.tla:558-569): self-addressed CheckOldConfig."""
    if sv.st[i] != LEADER or sv.st[j] not in (FOLLOWER, CANDIDATE):
        return []
    if not (get_config(sv, i, cfg) >> j & 1) or j == i:
        return []
    m = (MT_COC, sv.ct[i], 0, j, i, i)
    sv2, h2 = _send(sv, h, m)
    return [(f"DeleteServer({i},{j})", sv2, h2)]


def duplicate_message(sv, h, m, cfg):
    """DuplicateMessage(m) (raft.tla:892-896); count==1 guard lives in
    NextUnreliable (raft.tla:926-928).  No history record."""
    return [(f"Duplicate({m})", sv._replace(msgs=bag_add(sv.msgs, m)), h)]


def drop_message(sv, h, m, cfg):
    """DropMessage(m) (raft.tla:900-904); count==1 guard in NextUnreliable."""
    return [(f"Drop({m})", sv._replace(msgs=bag_remove(sv.msgs, m)), h)]


# ---------------------------------------------------------------------------
# Message handlers (SURVEY §2.5); each returns a list of successors — the
# disjunct structure of ReceiveDirect (raft.tla:842-863) is preserved, so
# overlapping guards yield multiple successors.
# ---------------------------------------------------------------------------

def update_term(sv, h, m, cfg):
    """UpdateTerm (raft.tla:826-832): message is NOT consumed."""
    i = msg_dst(m)
    if msg_term(m) <= sv.ct[i]:
        return []
    sv2 = sv._replace(ct=tup_set(sv.ct, i, msg_term(m)),
                      st=tup_set(sv.st, i, FOLLOWER),
                      vf=tup_set(sv.vf, i, NIL))
    return [(f"UpdateTerm({i})", sv2, h)]


def handle_rv_req(sv, h, m, cfg):
    """HandleRequestVoteRequest (raft.tla:578-597)."""
    i, j = msg_dst(m), msg_src(m)
    mterm, llt, lli = m[1], m[2], m[3]
    if mterm > sv.ct[i]:
        return []
    log_ok = (llt > last_term(sv.log[i]) or
              (llt == last_term(sv.log[i]) and lli >= len(sv.log[i])))
    grant = (mterm == sv.ct[i] and log_ok and sv.vf[i] in (NIL, j))
    sv1 = sv._replace(vf=tup_set(sv.vf, i, j)) if grant else sv
    resp = (MT_RVRESP, sv.ct[i], int(grant), sv.log[i], i, j)
    sv2, h2 = _reply(sv1, h, resp, m)
    return [(f"HandleRVReq({i}<-{j})", sv2, h2)]


def handle_rv_resp(sv, h, m, cfg):
    """DropStaleResponse / HandleRequestVoteResponse (raft.tla:836-839,
    602-614)."""
    i, j = msg_dst(m), msg_src(m)
    mterm, granted = m[1], m[2]
    if mterm < sv.ct[i]:
        sv2, h2 = _discard(sv, h, m)
        return [(f"DropStaleRVResp({i})", sv2, h2)]
    if mterm != sv.ct[i]:
        return []
    sv1 = sv._replace(vr=tup_set(sv.vr, i, sv.vr[i] | 1 << j))
    if granted:
        sv1 = sv1._replace(vg=tup_set(sv1.vg, i, sv1.vg[i] | 1 << j))
    sv2, h2 = _discard(sv1, h, m)
    return [(f"HandleRVResp({i}<-{j})", sv2, h2)]


def handle_ae_req(sv, h, m, cfg):
    """HandleAppendEntriesRequest (raft.tla:690-700) and its branch family
    (raft.tla:617-683).  The three accept sub-cases and the reject/return
    branches are mutually exclusive, but we evaluate each guard separately
    to mirror the disjunction."""
    i, j = msg_dst(m), msg_src(m)
    mterm, prev_idx, prev_term, entries, mcommit = m[1], m[2], m[3], m[4], m[5]
    if mterm > sv.ct[i]:
        return []
    log_ok = (prev_idx == 0 or
              (0 < prev_idx <= len(sv.log[i]) and
               prev_term == sv.log[i][prev_idx - 1][0]))
    out = []
    # RejectAppendEntriesRequest (raft.tla:617-629)
    if (mterm < sv.ct[i] or
            (mterm == sv.ct[i] and sv.st[i] == FOLLOWER and not log_ok)):
        resp = (MT_AERESP, sv.ct[i], 0, 0, i, j)
        sv2, h2 = _reply(sv, h, resp, m)
        out.append((f"RejectAEReq({i})", sv2, h2))
    # ReturnToFollowerState (raft.tla:632-636): message NOT consumed.
    if mterm == sv.ct[i] and sv.st[i] == CANDIDATE:
        sv2 = sv._replace(st=tup_set(sv.st, i, FOLLOWER))
        out.append((f"ReturnToFollower({i})", sv2, h))
    # AcceptAppendEntriesRequest (raft.tla:675-683)
    if mterm == sv.ct[i] and sv.st[i] == FOLLOWER and log_ok:
        index = prev_idx + 1
        # AppendEntriesAlreadyDone (raft.tla:639-655): commitIndex may
        # decrease (comment at raft.tla:644-646).
        if (entries == () or
                (len(sv.log[i]) >= index and
                 sv.log[i][index - 1][0] == entries[0][0])):
            sv1 = sv._replace(ci=tup_set(sv.ci, i, mcommit))
            resp = (MT_AERESP, sv.ct[i], 1, prev_idx + len(entries), i, j)
            sv2, h2 = _reply(sv1, h, resp, m)
            out.append((f"AEAlreadyDone({i})", sv2, h2))
        # ConflictAppendEntriesRequest (raft.tla:658-665): truncate exactly
        # one tail entry; message NOT consumed, no reply.
        if (entries != () and len(sv.log[i]) >= index and
                sv.log[i][index - 1][0] != entries[0][0]):
            sv2 = sv._replace(log=row_set(sv.log, i, sv.log[i][:-1]))
            out.append((f"AEConflict({i})", sv2, h))
        # NoConflictAppendEntriesRequest (raft.tla:668-672): append one
        # entry; message NOT consumed, no reply.
        if entries != () and len(sv.log[i]) == prev_idx:
            sv2 = sv._replace(log=row_set(sv.log, i, sv.log[i] + (entries[0],)))
            out.append((f"AENoConflict({i})", sv2, h))
    return out


def handle_ae_resp(sv, h, m, cfg):
    """DropStaleResponse / HandleAppendEntriesResponse (raft.tla:705-715)."""
    i, j = msg_dst(m), msg_src(m)
    mterm, success, midx = m[1], m[2], m[3]
    if mterm < sv.ct[i]:
        sv2, h2 = _discard(sv, h, m)
        return [(f"DropStaleAEResp({i})", sv2, h2)]
    if mterm != sv.ct[i]:
        return []
    if success:
        sv1 = sv._replace(ni=cell_set(sv.ni, i, j, midx + 1))
        sv1 = sv1._replace(mi=cell_set(sv1.mi, i, j, midx))
    else:
        sv1 = sv._replace(ni=cell_set(sv.ni, i, j, max(sv.ni[i][j] - 1, 1)))
    sv2, h2 = _discard(sv1, h, m)
    return [(f"HandleAEResp({i}<-{j})", sv2, h2)]


def handle_cat_req(sv, h, m, cfg):
    """HandleCatchupRequest (raft.tla:718-745).  NOTE: the success reply's
    mmatchIndex is Len of the *unprimed* (pre-splice) log (raft.tla:740),
    and its mterm is m.mterm (the adopted term)."""
    i, j = msg_dst(m), msg_src(m)
    mterm, mloglen, entries = m[1], m[2], m[3]
    rounds = m[7]
    out = []
    if mterm < sv.ct[i]:
        resp = (MT_CATRESP, sv.ct[i], 0, 0, i, j, 0)
        sv2, h2 = _reply(sv, h, resp, m)
        out.append((f"CatReqStale({i})", sv2, h2))
    if mterm >= sv.ct[i]:
        old_len = len(sv.log[i])
        if sv.log[i] == ():
            new_log = tuple(entries)
        else:
            new_log = sv.log[i][:min(mloglen, old_len)] + tuple(entries)
        sv1 = sv._replace(ct=tup_set(sv.ct, i, mterm),
                          log=row_set(sv.log, i, new_log))
        resp = (MT_CATRESP, mterm, 1, old_len, i, j, rounds - 1)
        sv2, h2 = _reply(sv1, h, resp, m)
        out.append((f"CatReqOk({i})", sv2, h2))
    return out


def handle_cat_resp(sv, h, m, cfg):
    """HandleCatchupResponse (raft.tla:748-792).  The follow-up
    CatchupRequest uses the *unprimed* nextIndex (raft.tla:764-767) and has
    no mcommitIndex field (encoded as -1)."""
    i, j = msg_dst(m), msg_src(m)
    mterm, success, midx, rounds_left = m[1], m[2], m[3], m[6]
    config = get_config(sv, i, cfg)
    out = []
    accept = (success and
              ((midx != sv.ci[i] and midx != sv.mi[i][j]) or
               midx == sv.ci[i]) and
              sv.st[i] == LEADER and mterm == sv.ct[i] and
              not (config >> j & 1))
    if accept:
        old_nij = sv.ni[i][j]
        sv1 = sv._replace(ni=cell_set(sv.ni, i, j, midx + 1))
        sv1 = sv1._replace(mi=cell_set(sv1.mi, i, j, midx))
        if rounds_left != 0:
            req = (MT_CATREQ, sv.ct[i], old_nij - 1,
                   sv.log[i][old_nij - 1:sv.ci[i]], -1, i, j, rounds_left)
            sv2, h2 = _reply(sv1, h, req, m)
            out.append((f"CatRespMore({i})", sv2, h2))
        else:
            req = (MT_COC, sv.ct[i], 1, j, i, i)
            sv2, h2 = _reply(sv1, h, req, m)
            out.append((f"CatRespDone({i})", sv2, h2))
    reject = (not success or
              ((midx == sv.ci[i] or midx == sv.mi[i][j]) and
               midx != sv.ci[i]) or
              sv.st[i] != LEADER or mterm != sv.ct[i] or
              bool(config >> j & 1))
    if reject:
        sv2, h2 = _discard(sv, h, m)
        out.append((f"CatRespReject({i})", sv2, h2))
    return out


def handle_coc(sv, h, m, cfg):
    """HandleCheckOldConfig (raft.tla:795-822).

    Faithful quirk: the discard branch's guard is
    `state[i] /= Leader \\/ m.mterm = currentTerm[i]` (raft.tla:796), so for
    a Leader at the message's term BOTH branches are enabled (discard or
    process), and a stale-term message at a Leader is stuck forever."""
    i = msg_dst(m)
    mterm, madd, mserver = m[1], m[2], m[3]
    out = []
    if sv.st[i] != LEADER or mterm == sv.ct[i]:
        sv2, h2 = _discard(sv, h, m)
        out.append((f"CocDiscard({i})", sv2, h2))
    if sv.st[i] == LEADER and mterm == sv.ct[i]:
        if max_config_index(sv.log[i]) <= sv.ci[i]:
            config = get_config(sv, i, cfg)
            new_config = (config | 1 << mserver) if madd else \
                (config & ~(1 << mserver))
            changed = new_config != config
            if changed:
                entry = (sv.ct[i], CONFIG_ENTRY, new_config)
                sv1 = sv._replace(log=row_set(sv.log, i, sv.log[i] + (entry,)))
                extra = (("AddServer", i, mserver) if madd
                         else ("RemoveServer", i, mserver))
                sv2, h2 = _discard_with_mc(sv1, h, m, extra)
            else:
                sv2, h2 = _discard(sv, h, m)
            out.append((f"CocApply({i})", sv2, h2))
        else:
            # One-at-a-time gate not yet satisfied: re-send to self (retry
            # loop, raft.tla:813-821).
            resend = (MT_COC, sv.ct[i], madd, mserver, i, i)
            sv2, h2 = _reply(sv, h, resend, m)
            out.append((f"CocRetry({i})", sv2, h2))
    return out


_HANDLERS = {
    MT_RVREQ: handle_rv_req,
    MT_RVRESP: handle_rv_resp,
    MT_AEREQ: handle_ae_req,
    MT_AERESP: handle_ae_resp,
    MT_CATREQ: handle_cat_req,
    MT_CATRESP: handle_cat_resp,
    MT_COC: handle_coc,
}


def receive(sv, h, m, cfg):
    """ReceiveDirect (raft.tla:842-863): UpdateTerm ∨ per-type handler."""
    return update_term(sv, h, m, cfg) + _HANDLERS[m[0]](sv, h, m, cfg)


# ---------------------------------------------------------------------------
# Next-relation families (raft.tla:909-943)
# ---------------------------------------------------------------------------

def successors(sv: State, h: Hist, cfg: ModelConfig):
    """All successors of (sv, h) under cfg.next_family, as
    (label, sv', h') triples.  Mirrors the ∃-expansion TLC performs
    (SURVEY §3.1)."""
    n = cfg.n_servers
    fam = cfg.next_family
    out = []
    # NextAsync (raft.tla:909-916)
    for i in range(n):
        for j in range(n):
            out += request_vote(sv, h, i, j, cfg)
    for i in range(n):
        out += become_leader(sv, h, i, cfg)
    for i in range(n):
        for v in cfg.values:
            out += client_request(sv, h, i, v, cfg)
    for i in range(n):
        out += advance_commit_index(sv, h, i, cfg)
    for i in range(n):
        for j in range(n):
            out += append_entries(sv, h, i, j, cfg)
    for m, _cnt in sv.msgs:
        out += receive(sv, h, m, cfg)
    for i in range(n):
        out += timeout(sv, h, i, cfg)
    # NextCrash (raft.tla:918)
    if fam in (NEXT_ASYNC_CRASH, NEXT_FULL, NEXT_DYNAMIC):
        for i in range(n):
            out += restart(sv, h, i, cfg)
    # NextUnreliable (raft.tla:924-932): only single-copy messages.
    if fam in (NEXT_FULL, NEXT_DYNAMIC):
        for m, cnt in sv.msgs:
            if cnt == 1:
                out += duplicate_message(sv, h, m, cfg)
        for m, cnt in sv.msgs:
            if cnt == 1:
                out += drop_message(sv, h, m, cfg)
    # Membership (raft.tla:940-943)
    if fam == NEXT_DYNAMIC:
        for i in range(n):
            for j in range(n):
                out += add_new_server(sv, h, i, j, cfg)
        for i in range(n):
            for j in range(n):
                out += delete_server(sv, h, i, j, cfg)
    return out
