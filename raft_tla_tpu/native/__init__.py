"""ctypes binding for the native C++ checker (native/raft_checker.cc).

Builds the shared object on demand with g++ -O3 (no pip deps) and
exposes ``check(cfg, ...)`` with the same counting semantics as the
Python oracle and the TPU engine — the framework's CPU runtime and the
machine-measured stand-in for the reference's "TLC -workers N" baseline
(BASELINE.md).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from dataclasses import dataclass
from pathlib import Path
from typing import List, Optional

import numpy as np

from ..config import (NEXT_ASYNC, NEXT_ASYNC_CRASH, NEXT_DYNAMIC,
                      NEXT_FULL, ModelConfig)
from ..models.explore import symmetry_perms
from ..ops.layout import Layout

# keep in sync with raft_checker.cc ConBit / InvBit
CONSTRAINT_ORDER = (
    "BoundedInFlightMessages", "BoundedRequestVote", "BoundedLogSize",
    "BoundedRestarts", "BoundedTimeouts", "BoundedTerms",
    "BoundedClientRequests", "BoundedTriedMembershipChanges",
    "BoundedMembershipChanges", "ElectionsUncontested",
    "CleanStartUntilFirstRequest", "CleanStartUntilTwoLeaders",
    "CleanFirstLeaderElection",
)
INVARIANT_ORDER = (
    "LeaderVotesQuorum", "CandidateTermNotInLog", "ElectionSafety",
    "LogMatching", "VotesGrantedInv", "VotesGrantedInv_false",
    "QuorumLogInv", "MoreUpToDateCorrect", "LeaderCompleteness",
    "LeaderCompleteness_false", "OneAtATimeMembershipChangeOK",
)
_FAMILY = {NEXT_ASYNC: 0, NEXT_ASYNC_CRASH: 1, NEXT_FULL: 2,
           NEXT_DYNAMIC: 3}

_lock = threading.Lock()
_lib = None


def _build() -> Path:
    """Compile the checker into a cache path keyed on a content hash of
    the source (never committed; a stale or foreign-built object can
    never be picked up).  -march=native is attempted first for speed and
    dropped automatically on toolchains/microarchitectures that reject
    it."""
    import hashlib
    src = Path(__file__).parent / "raft_checker.cc"
    digest = hashlib.sha256(src.read_bytes()).hexdigest()[:16]
    so = Path(__file__).parent / f"raft_checker.{digest}.so"
    if so.exists():
        return so
    # build into a process-unique temp and rename atomically so
    # concurrent builders (e.g. parallel pytest workers) never unlink or
    # half-overwrite an object another process is about to CDLL
    tmp = so.with_suffix(f".tmp{os.getpid()}")
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
            "-o", str(tmp), str(src), "-lpthread"]
    try:
        subprocess.run(base[:2] + ["-march=native"] + base[2:],
                       check=True, capture_output=True, text=True)
    except subprocess.CalledProcessError:
        subprocess.run(base, check=True, capture_output=True, text=True)
    os.replace(tmp, so)
    for stale in so.parent.glob("raft_checker*.so"):
        if stale != so:
            stale.unlink(missing_ok=True)
    return so


def _load():
    global _lib
    with _lock:
        if _lib is None:
            lib = ctypes.CDLL(str(_build()))
            lib.raft_check.restype = ctypes.c_int64
            lib.raft_check.argtypes = [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64)]
            _lib = lib
    return _lib


@dataclass
class NativeResult:
    distinct_states: int
    generated_states: int
    depth: int
    violations: List[str]
    overflow_faults: int
    seconds: float = 0.0

    @property
    def states_per_sec(self):
        return self.distinct_states / max(self.seconds, 1e-9)


def _pack_cfg(cfg: ModelConfig, threads: int, max_depth: int,
              max_states: int, stop_on_violation: bool) -> np.ndarray:
    lay = Layout(cfg)
    for nm in cfg.invariants:
        if nm not in INVARIANT_ORDER:
            raise ValueError(
                f"invariant {nm!r} is python-side only (scenario "
                f"properties run on the oracle/TPU engines)")
    for nm in cfg.constraints:
        if nm not in CONSTRAINT_ORDER:
            raise ValueError(f"constraint {nm!r} unsupported natively")
    con_mask = sum(1 << CONSTRAINT_ORDER.index(nm)
                   for nm in cfg.constraints)
    inv_mask = 0
    for nm in cfg.invariants:
        if cfg.apalache_variant and nm in ("VotesGrantedInv",
                                           "LeaderCompleteness"):
            nm = nm + "_false"
        inv_mask |= 1 << INVARIANT_ORDER.index(nm)
    perms = (symmetry_perms(cfg) if cfg.symmetry
             else [tuple(range(cfg.n_servers))])
    b = cfg.bounds
    head = [
        cfg.n_servers, len(cfg.values),
        *list(cfg.values) + [0] * (8 - len(cfg.values)),
        cfg.init_mask, cfg.num_rounds, _FAMILY[cfg.next_family],
        b.max_log_length, cfg.log_capacity, cfg.bag_capacity,
        b.max_restarts, b.max_timeouts, b.max_terms,
        b.max_client_requests, b.max_membership_changes,
        b.max_tried_membership_changes, cfg.max_inflight, b.max_trace,
        con_mask, inv_mask, int(cfg.symmetry), threads,
        max_depth, max_states, int(stop_on_violation), lay.value_bits,
        len(perms),
    ]
    flat = [x for p in perms for x in p]
    return np.array(head + flat, dtype=np.int64)


def check(cfg: ModelConfig, threads: int = os.cpu_count() or 8,
          max_depth: int = 2 ** 60, max_states: int = 2 ** 60,
          stop_on_violation: bool = False) -> NativeResult:
    """``max_states`` is a level-granular budget, matching the TPU
    engine's semantics: expansion stops at the first level boundary at
    or past the cap, so the returned count may exceed it by up to one
    level's worth of states."""
    import time
    lib = _load()
    arr = _pack_cfg(cfg, threads, max_depth, max_states,
                    stop_on_violation)
    out = np.zeros(8, dtype=np.int64)
    t0 = time.time()
    rc = lib.raft_check(
        arr.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    secs = time.time() - t0
    if rc != 0:
        raise RuntimeError(
            f"native checker rejected the model dims (rc={rc}): "
            f"S<=6, K<=72, Lcap<=16, Lmax<=8, |values|<=8 required")
    violations = [nm for k, nm in enumerate(INVARIANT_ORDER)
                  if out[3] >> k & 1]
    return NativeResult(
        distinct_states=int(out[0]), generated_states=int(out[1]),
        depth=int(out[2]), violations=violations,
        overflow_faults=int(out[4]), seconds=secs)
