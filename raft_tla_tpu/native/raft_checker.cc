// Native multi-threaded explicit-state checker for the Raft spec family.
//
// This is the framework's CPU runtime: a C++ twin of the Python oracle
// (raft_tla_tpu/models/raft.py, which cites tlc_membership/raft.tla
// line-by-line) running a level-synchronous multi-worker BFS — the role
// TLC's Java engine plays for the reference (SURVEY §2.13), and the
// machine-local baseline the TPU engine is benchmarked against
// (BASELINE.md: "TLC -workers 8 on CPU", measured here by us).
//
// Semantics notes mirrored from the oracle:
//   * state identity = the 10 semantic vars (VIEW vars, raft.cfg:30),
//     canonical under server relabeling (SYMMETRY, raft.cfg:29) via
//     min-over-permutations of a 64-bit field-stream hash; history
//     counters ride along but are excluded from identity.
//   * the message bag hashes commutatively (sum over slots of
//     count * mix(msg)), so bag representation order never matters.
//   * CONSTRAINT = don't-expand (state still checked); first-seen
//     survivor per level in frontier order.
//   * UpdateTerm / ReturnToFollowerState / Conflict / NoConflict do not
//     consume the message; HandleCheckOldConfig's discard and process
//     branches overlap for a Leader at the message term.
//
// Exposed C ABI (ctypes): raft_build_config-free — the Python side
// passes a flat int64 config array; see native/__init__.py.

#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstring>
#include <mutex>
#include <thread>
#include <unordered_set>
#include <vector>

namespace {

constexpr int SMAX = 6;     // servers
constexpr int LMAX = 8;     // max entries in one message
constexpr int LCAPMAX = 16; // max representable log (2 * MaxLogLength)
constexpr int KMAX = 72;    // bag slots
constexpr int VMAX = 8;     // client values
constexpr int PMAX = 720;   // symmetry permutations (<= 6!)

enum Role { FOLLOWER = 0, CANDIDATE = 1, LEADER = 2 };
enum EType { VALUE_ENTRY = 0, CONFIG_ENTRY = 1 };
enum MType {
  MT_NONE = 0, MT_RVREQ, MT_RVRESP, MT_AEREQ, MT_AERESP,
  MT_CATREQ, MT_CATRESP, MT_COC
};
enum Family { FAM_ASYNC = 0, FAM_ASYNC_CRASH, FAM_FULL, FAM_DYNAMIC };
constexpr int8_t NIL = -1;

// Constraint bit order — must match native/__init__.py CONSTRAINT_ORDER.
enum ConBit {
  CB_INFLIGHT = 0, CB_RVREQ, CB_LOGSIZE, CB_RESTARTS, CB_TIMEOUTS,
  CB_TERMS, CB_CLIENTREQ, CB_TRIEDMC, CB_MC, CB_UNCONTESTED,
  CB_CLEANFIRSTREQ, CB_CLEANTWOLEADERS, CB_CLEANFIRSTELECTION,
  CB_COUNT
};
// Invariant bit order — must match native/__init__.py INVARIANT_ORDER.
enum InvBit {
  IB_LEADERVOTESQUORUM = 0, IB_CANDTERMNOTINLOG, IB_ELECTIONSAFETY,
  IB_LOGMATCHING, IB_VOTESGRANTED, IB_VOTESGRANTED_FALSE, IB_QUORUMLOG,
  IB_MOREUPTODATE, IB_LEADERCOMPLETE, IB_LEADERCOMPLETE_FALSE,
  IB_ONEATATIME, IB_COUNT
};

struct Cfg {
  int S, nvals, init_mask, num_rounds, family;
  int vals[VMAX];
  int L, Lcap, K;
  int max_restarts, max_timeouts, max_terms, max_client_requests;
  int max_mc, max_tried, max_inflight, max_trace;
  uint32_t con_mask, inv_mask;
  int symmetry, threads;
  int64_t max_depth, max_states;
  int stop_on_violation;
  // derived
  int value_bits, entry_bits;
  int n_perms;
  int8_t perms[PMAX][SMAX];   // sigma: old -> new
};

struct Msg {
  uint8_t type;
  int16_t term, src, dst, a, b, c;
  uint8_t entlen;
  uint16_t ent[LMAX];
  // memset-based init so struct PADDING is zeroed: operator== compares
  // raw bytes, and indeterminate padding would stop equal messages
  // merging in bag_put (splitting slots breaks the count==1 guards of
  // Duplicate/Drop, raft.tla:926-932).
  Msg() {
    std::memset(this, 0, sizeof(Msg));
    a = b = c = -1;
  }
  bool operator==(const Msg &o) const {
    return std::memcmp(this, &o, sizeof(Msg)) == 0;
  }
};

struct State {
  // VIEW (identity)
  int16_t ct[SMAX];
  int8_t st[SMAX], vf[SMAX];
  int16_t ci[SMAX], llen[SMAX];
  uint16_t log[SMAX][LCAPMAX];
  uint8_t vr[SMAX], vg[SMAX];
  int16_t ni[SMAX][SMAX], mi[SMAX][SMAX];
  Msg bag[KMAX];
  uint8_t cnt[KMAX];
  // non-VIEW (history counters; constraint inputs)
  uint8_t restarted[SMAX], timeoutc[SMAX];
  int16_t nleaders, nreq, ntried, nmc;
  int32_t globlen;
  uint8_t overflow;
};

inline uint16_t pack_entry(const Cfg &c, int term, int etype, int payload) {
  return (uint16_t)((term << (1 + c.value_bits)) |
                    (etype << c.value_bits) | payload);
}
inline int entry_term(const Cfg &c, uint16_t e) {
  return e >> (1 + c.value_bits);
}
inline int entry_type(const Cfg &c, uint16_t e) {
  return (e >> c.value_bits) & 1;
}
inline int entry_payload(const Cfg &c, uint16_t e) {
  return e & ((1 << c.value_bits) - 1);
}

inline int popcount(uint32_t x) { return __builtin_popcount(x); }

// GetConfig (raft.tla:354-360): latest ConfigEntry else InitServer.
inline int get_config(const Cfg &c, const State &s, int i) {
  for (int k = s.llen[i] - 1; k >= 0; --k)
    if (entry_type(c, s.log[i][k]) == CONFIG_ENTRY)
      return entry_payload(c, s.log[i][k]);
  return c.init_mask;
}
// GetMaxConfigIndex (raft.tla:346-351), 1-based.
inline int max_config_index(const Cfg &c, const State &s, int i) {
  for (int k = s.llen[i] - 1; k >= 0; --k)
    if (entry_type(c, s.log[i][k]) == CONFIG_ENTRY) return k + 1;
  return 0;
}
inline int last_term(const Cfg &c, const State &s, int i) {
  return s.llen[i] ? entry_term(c, s.log[i][s.llen[i] - 1]) : 0;
}
// set ∈ Quorum(config) (raft.tla:217): subset + strict majority.
inline bool in_quorum(uint32_t votes, uint32_t config) {
  if (votes & ~config) return false;
  return 2 * popcount(votes) > popcount(config);
}

// ---------------------------------------------------------------------
// Bag ops (TypedBags (+)/(-), raft.tla:226-231)
// ---------------------------------------------------------------------

inline void bag_put(const Cfg &c, State &s, const Msg &m) {
  int empty = -1;
  for (int k = 0; k < c.K; ++k) {
    if (s.cnt[k] && s.bag[k] == m) { s.cnt[k]++; return; }
    if (!s.cnt[k] && empty < 0) empty = k;
  }
  if (empty < 0) { s.overflow = 1; return; }
  s.bag[empty] = m;
  s.cnt[empty] = 1;
}

inline void bag_del(State &s, int k) {
  if (--s.cnt[k] == 0) s.bag[k] = Msg{};
}

// ---------------------------------------------------------------------
// Hashing: canonical under symmetry, commutative over the bag
// ---------------------------------------------------------------------

inline uint64_t mix64(uint64_t x) {
  x += 0x9E3779B97F4A7C15ull;
  x = (x ^ (x >> 30)) * 0xBF58476D1CE4E5B9ull;
  x = (x ^ (x >> 27)) * 0x94D049BB133111EBull;
  return x ^ (x >> 31);
}

inline uint32_t perm_mask(uint32_t m, const int8_t *sigma, int S) {
  uint32_t out = 0;
  for (int i = 0; i < S; ++i)
    if (m >> i & 1) out |= 1u << sigma[i];
  return out;
}

inline uint16_t perm_entry(const Cfg &c, uint16_t e, const int8_t *sigma) {
  if (!e || entry_type(c, e) != CONFIG_ENTRY) return e;
  return pack_entry(c, entry_term(c, e), CONFIG_ENTRY,
                    perm_mask(entry_payload(c, e), sigma, c.S));
}

inline uint64_t hash_msg(const Cfg &c, const Msg &m, const int8_t *sigma) {
  uint64_t h = 0x51ED270B0B0B0B0Bull;
  h = mix64(h ^ m.type);
  h = mix64(h ^ (uint64_t)(uint16_t)m.term);
  h = mix64(h ^ (uint64_t)sigma[m.src]);
  h = mix64(h ^ (uint64_t)sigma[m.dst]);
  h = mix64(h ^ (uint64_t)(uint16_t)(m.a + 1));
  int b = (m.type == MT_COC) ? sigma[m.b] : m.b;
  h = mix64(h ^ (uint64_t)(uint16_t)(b + 1));
  h = mix64(h ^ (uint64_t)(uint16_t)(m.c + 1));
  h = mix64(h ^ m.entlen);
  for (int k = 0; k < m.entlen; ++k)
    h = mix64(h ^ perm_entry(c, m.ent[k], sigma));
  return h;
}

inline uint64_t hash_perm(const Cfg &c, const State &s, const int8_t *sigma) {
  int S = c.S;
  int8_t inv[SMAX];
  for (int i = 0; i < S; ++i) inv[sigma[i]] = (int8_t)i;
  uint64_t h = 0;
  uint64_t pos = 1;
  auto put = [&](uint64_t v) { h += mix64(v + 0x1000003 * (pos++)); };
  for (int k = 0; k < S; ++k) {
    int i = inv[k];
    put(s.ct[i]);
    put(s.st[i]);
    put(s.vf[i] == NIL ? (uint64_t)S : (uint64_t)sigma[(int)s.vf[i]]);
    put(s.ci[i]);
    put(s.llen[i]);
    for (int p = 0; p < c.Lcap; ++p) put(perm_entry(c, s.log[i][p], sigma));
    put(perm_mask(s.vr[i], sigma, S));
    put(perm_mask(s.vg[i], sigma, S));
    for (int l = 0; l < S; ++l) put(s.ni[i][inv[l]]);
    for (int l = 0; l < S; ++l) put(s.mi[i][inv[l]]);
  }
  uint64_t bag = 0;
  for (int k = 0; k < c.K; ++k)
    if (s.cnt[k]) bag += (uint64_t)s.cnt[k] * hash_msg(c, s.bag[k], sigma);
  return h + mix64(bag);
}

inline uint64_t fingerprint(const Cfg &c, const State &s) {
  uint64_t best = ~0ull;
  for (int p = 0; p < c.n_perms; ++p)
    best = std::min(best, hash_perm(c, s, c.perms[p]));
  return best;
}

// ---------------------------------------------------------------------
// Actions (oracle: models/raft.py; spec: tlc_membership/raft.tla §2.4-2.5)
// ---------------------------------------------------------------------

using Emit = void (*)(void *, const State &);

struct Ctx {
  const Cfg *c;
  void *sink;
  Emit emit;
};

inline void restart(Ctx &x, const State &s, int i) {  // raft.tla:401-411
  const Cfg &c = *x.c;
  State t = s;
  t.st[i] = FOLLOWER;
  t.vr[i] = t.vg[i] = 0;
  for (int j = 0; j < c.S; ++j) { t.ni[i][j] = 1; t.mi[i][j] = 0; }
  t.ci[i] = 0;
  t.restarted[i]++;
  t.globlen++;
  x.emit(x.sink, t);
}

inline void timeout(Ctx &x, const State &s, int i) {  // raft.tla:415-427
  const Cfg &c = *x.c;
  if (s.st[i] == LEADER) return;
  if (!(get_config(c, s, i) >> i & 1)) return;
  State t = s;
  t.st[i] = CANDIDATE;
  if (t.ct[i] + 1 > c.max_terms + 1) t.overflow = 1; else t.ct[i]++;
  t.vf[i] = NIL;
  t.vr[i] = t.vg[i] = 0;
  t.timeoutc[i]++;
  t.globlen++;
  x.emit(x.sink, t);
}

inline void request_vote(Ctx &x, const State &s, int i, int j) {  // :431-440
  const Cfg &c = *x.c;
  if (s.st[i] != CANDIDATE) return;
  if (!((get_config(c, s, i) & ~s.vr[i]) >> j & 1)) return;
  State t = s;
  Msg m;
  m.type = MT_RVREQ; m.term = s.ct[i]; m.src = (int16_t)i; m.dst = (int16_t)j;
  m.a = (int16_t)last_term(c, s, i); m.b = s.llen[i];
  bag_put(c, t, m);
  t.globlen++;
  x.emit(x.sink, t);
}

inline void append_entries(Ctx &x, const State &s, int i, int j) { // :446-468
  const Cfg &c = *x.c;
  if (i == j || s.st[i] != LEADER) return;
  if (!(get_config(c, s, i) >> j & 1)) return;
  int nij = s.ni[i][j];
  int prev_idx = nij - 1;
  int prev_term = (prev_idx > 0 && prev_idx <= s.llen[i])
                      ? entry_term(c, s.log[i][prev_idx - 1]) : 0;
  int last_entry = std::min<int>(s.llen[i], nij);
  State t = s;
  Msg m;
  m.type = MT_AEREQ; m.term = s.ct[i]; m.src = (int16_t)i;
  m.dst = (int16_t)j;
  m.a = (int16_t)prev_idx; m.b = (int16_t)prev_term;
  m.c = (int16_t)std::min<int>(s.ci[i], last_entry);
  if (nij <= last_entry) { m.entlen = 1; m.ent[0] = s.log[i][nij - 1]; }
  bag_put(c, t, m);
  t.globlen++;
  x.emit(x.sink, t);
}

inline void become_leader(Ctx &x, const State &s, int i) {  // :472-484
  const Cfg &c = *x.c;
  if (s.st[i] != CANDIDATE) return;
  if (!in_quorum(s.vg[i], get_config(c, s, i))) return;
  State t = s;
  t.st[i] = LEADER;
  for (int j = 0; j < c.S; ++j) {
    t.ni[i][j] = (int16_t)(s.llen[i] + 1);
    t.mi[i][j] = 0;
  }
  t.nleaders++;
  t.globlen++;
  x.emit(x.sink, t);
}

inline void client_request(Ctx &x, const State &s, int i, int v) { // :488-497
  const Cfg &c = *x.c;
  if (s.st[i] != LEADER) return;
  State t = s;
  if (s.llen[i] >= c.Lcap) t.overflow = 1;
  else {
    t.log[i][s.llen[i]] = pack_entry(c, s.ct[i], VALUE_ENTRY, v);
    t.llen[i]++;
  }
  t.nreq++;   // no global record (raft.tla:488-497)
  x.emit(x.sink, t);
}

inline void advance_commit_index(Ctx &x, const State &s, int i) { // :504-539
  const Cfg &c = *x.c;
  if (s.st[i] != LEADER) return;
  uint32_t config = get_config(c, s, i);
  int max_agree = 0;
  for (int idx = 1; idx <= s.llen[i]; ++idx) {
    uint32_t agree = 1u << i;
    for (int k = 0; k < c.S; ++k)
      if ((config >> k & 1) && s.mi[i][k] >= idx) agree |= 1u << k;
    if (in_quorum(agree, config)) max_agree = idx;
  }
  State t = s;
  int new_ci = s.ci[i];
  if (max_agree > 0 &&
      entry_term(c, s.log[i][max_agree - 1]) == s.ct[i])
    new_ci = max_agree;
  t.ci[i] = (int16_t)new_ci;
  // CommitEntry vs CommitMembershipChange (raft.tla:522-538) both append
  // one record; the distinction feeds feature lanes (python-side only).
  if (new_ci > s.ci[i]) t.globlen++;
  x.emit(x.sink, t);
}

inline void add_new_server(Ctx &x, const State &s, int i, int j) { // :542-555
  const Cfg &c = *x.c;
  if (s.st[i] != LEADER) return;
  if (get_config(c, s, i) >> j & 1) return;
  State t = s;
  t.ct[j] = 1;
  t.vf[j] = NIL;
  Msg m;
  m.type = MT_CATREQ; m.term = s.ct[i]; m.src = (int16_t)i;
  m.dst = (int16_t)j;
  m.a = s.mi[i][j];                       // mlogLen (raft.tla:549)
  m.b = s.ci[i];                          // mcommitIndex
  m.c = (int16_t)c.num_rounds;
  int nij = s.ni[i][j];
  int n = std::max(0, std::min<int>(s.ci[i] - nij + 1, LMAX));
  if (s.ci[i] - nij + 1 > LMAX) t.overflow = 1;
  for (int k = 0; k < n; ++k) m.ent[k] = s.log[i][nij - 1 + k];
  m.entlen = (uint8_t)n;
  bag_put(c, t, m);
  t.ntried++;
  t.globlen += 2;                         // TryAddServer + Send
  x.emit(x.sink, t);
}

inline void delete_server(Ctx &x, const State &s, int i, int j) { // :558-569
  const Cfg &c = *x.c;
  if (s.st[i] != LEADER || s.st[j] == LEADER || i == j) return;
  if (!(get_config(c, s, i) >> j & 1)) return;
  State t = s;
  Msg m;
  m.type = MT_COC; m.term = s.ct[i]; m.src = (int16_t)i; m.dst = (int16_t)i;
  m.a = 0; m.b = (int16_t)j;
  bag_put(c, t, m);
  t.ntried++;
  t.globlen += 2;                         // TryRemoveServer + Send
  x.emit(x.sink, t);
}

inline void duplicate_message(Ctx &x, const State &s, int k) {  // :892-896
  if (s.cnt[k] != 1) return;
  State t = s;
  t.cnt[k]++;
  x.emit(x.sink, t);
}

inline void drop_message(Ctx &x, const State &s, int k) {       // :900-904
  if (s.cnt[k] != 1) return;
  State t = s;
  bag_del(t, k);
  x.emit(x.sink, t);
}

// Receive (raft.tla:842-863): UpdateTerm lane + per-type handlers.
inline void receive(Ctx &x, const State &s, int k) {
  const Cfg &c = *x.c;
  if (!s.cnt[k]) return;
  const Msg &m = s.bag[k];
  int i = m.dst, j = m.src;

  // UpdateTerm (raft.tla:826-832): msg NOT consumed.
  if (m.term > s.ct[i]) {
    State t = s;
    t.ct[i] = m.term;
    t.st[i] = FOLLOWER;
    t.vf[i] = NIL;
    x.emit(x.sink, t);
  }

  switch (m.type) {
    case MT_RVREQ: {                      // raft.tla:578-597
      if (m.term > s.ct[i]) break;
      int lt = last_term(c, s, i);
      bool log_ok = m.a > lt || (m.a == lt && m.b >= s.llen[i]);
      bool grant = m.term == s.ct[i] && log_ok &&
                   (s.vf[i] == NIL || s.vf[i] == j);
      State t = s;
      if (grant) t.vf[i] = (int8_t)j;
      Msg r;
      r.type = MT_RVRESP; r.term = s.ct[i]; r.src = (int16_t)i;
      r.dst = (int16_t)j;
      r.a = grant ? 1 : 0;
      r.entlen = (uint8_t)std::min<int>(s.llen[i], LMAX);  // mlog :591-593
      for (int p = 0; p < r.entlen; ++p) r.ent[p] = s.log[i][p];
      if (s.llen[i] > LMAX) t.overflow = 1;
      bag_del(t, k);
      bag_put(c, t, r);
      t.globlen += 2;
      x.emit(x.sink, t);
      break;
    }
    case MT_RVRESP: {                     // raft.tla:836-839, 602-614
      if (m.term > s.ct[i]) break;
      State t = s;
      if (m.term == s.ct[i]) {
        t.vr[i] |= 1u << j;
        if (m.a == 1) t.vg[i] |= 1u << j;
      }
      bag_del(t, k);
      t.globlen++;
      x.emit(x.sink, t);
      break;
    }
    case MT_AEREQ: {                      // raft.tla:617-700
      if (m.term > s.ct[i]) break;
      bool eq = m.term == s.ct[i];
      int prev_idx = m.a;
      bool log_ok = prev_idx == 0 ||
                    (prev_idx > 0 && prev_idx <= s.llen[i] &&
                     m.b == entry_term(c, s.log[i][prev_idx - 1]));
      if (m.term < s.ct[i] || (eq && s.st[i] == FOLLOWER && !log_ok)) {
        State t = s;                      // Reject :617-629
        Msg r;
        r.type = MT_AERESP; r.term = s.ct[i]; r.src = (int16_t)i;
        r.dst = (int16_t)j; r.a = 0; r.b = 0;
        bag_del(t, k);
        bag_put(c, t, r);
        t.globlen += 2;
        x.emit(x.sink, t);
      } else if (eq && s.st[i] == CANDIDATE) {
        State t = s;                      // ReturnToFollower :632-636
        t.st[i] = FOLLOWER;               // msg NOT consumed
        x.emit(x.sink, t);
      } else if (eq && s.st[i] == FOLLOWER && log_ok) {
        int index = prev_idx + 1;
        bool have_at = s.llen[i] >= index;
        bool term_match =
            have_at && m.entlen &&
            entry_term(c, s.log[i][index - 1]) == entry_term(c, m.ent[0]);
        if (m.entlen == 0 || (have_at && term_match)) {
          State t = s;                    // AlreadyDone :639-655
          t.ci[i] = m.c;                  // can DECREASE (comment :644)
          Msg r;
          r.type = MT_AERESP; r.term = s.ct[i]; r.src = (int16_t)i;
          r.dst = (int16_t)j; r.a = 1;
          r.b = (int16_t)(prev_idx + m.entlen);
          bag_del(t, k);
          bag_put(c, t, r);
          t.globlen += 2;
          x.emit(x.sink, t);
        } else if (m.entlen && have_at && !term_match) {
          State t = s;                    // Conflict :658-665 (no reply)
          t.log[i][s.llen[i] - 1] = 0;
          t.llen[i]--;
          x.emit(x.sink, t);
        } else if (m.entlen && s.llen[i] == prev_idx) {
          State t = s;                    // NoConflict :668-672 (no reply)
          if (s.llen[i] >= c.Lcap) t.overflow = 1;
          else { t.log[i][s.llen[i]] = m.ent[0]; t.llen[i]++; }
          x.emit(x.sink, t);
        }
      }
      break;
    }
    case MT_AERESP: {                     // raft.tla:705-715
      if (m.term > s.ct[i]) break;
      State t = s;
      if (m.term == s.ct[i]) {
        if (m.a == 1) {
          t.ni[i][j] = (int16_t)(m.b + 1);
          t.mi[i][j] = m.b;
        } else {
          t.ni[i][j] = (int16_t)std::max(s.ni[i][j] - 1, 1);
        }
      }
      bag_del(t, k);
      t.globlen++;
      x.emit(x.sink, t);
      break;
    }
    case MT_CATREQ: {                     // raft.tla:718-745
      if (m.term < s.ct[i]) {
        State t = s;
        Msg r;
        r.type = MT_CATRESP; r.term = s.ct[i]; r.src = (int16_t)i;
        r.dst = (int16_t)j; r.a = 0; r.b = 0; r.c = 0;
        bag_del(t, k);
        bag_put(c, t, r);
        t.globlen += 2;
        x.emit(x.sink, t);
      } else {
        State t = s;
        int old_len = s.llen[i];
        int prefix = std::min<int>(m.a, old_len);
        int new_len = prefix + m.entlen;
        if (new_len > c.Lcap) t.overflow = 1;
        else {
          for (int p = 0; p < m.entlen; ++p)
            t.log[i][prefix + p] = m.ent[p];
          for (int p = new_len; p < old_len; ++p) t.log[i][p] = 0;
          t.llen[i] = (int16_t)new_len;
        }
        t.ct[i] = m.term;                 // adopt (raft.tla:737)
        Msg r;                            // mmatchIndex = PRE-splice len
        r.type = MT_CATRESP; r.term = m.term; r.src = (int16_t)i;
        r.dst = (int16_t)j; r.a = 1; r.b = (int16_t)old_len;
        r.c = (int16_t)(m.c - 1);
        bag_del(t, k);
        bag_put(c, t, r);
        t.globlen += 2;
        x.emit(x.sink, t);
      }
      break;
    }
    case MT_CATRESP: {                    // raft.tla:748-792
      bool progress = (m.b != s.ci[i] && m.b != s.mi[i][j]) ||
                      m.b == s.ci[i];
      bool accept = m.a == 1 && progress && s.st[i] == LEADER &&
                    m.term == s.ct[i] &&
                    !(get_config(c, s, i) >> j & 1);
      State t = s;
      if (accept) {
        int old_nij = s.ni[i][j];
        t.ni[i][j] = (int16_t)(m.b + 1);
        t.mi[i][j] = m.b;
        Msg r;
        if (m.c != 0) {                   // follow-up CatchupRequest
          r.type = MT_CATREQ; r.term = s.ct[i]; r.src = (int16_t)i;
          r.dst = (int16_t)j;
          r.a = (int16_t)(old_nij - 1);   // unprimed nextIndex :764-767
          r.b = -1;                       // mcommitIndex ABSENT :762-771
          r.c = m.c;
          int n = std::max(0, std::min<int>(s.ci[i] - old_nij + 1, LMAX));
          if (s.ci[i] - old_nij + 1 > LMAX) t.overflow = 1;
          for (int p = 0; p < n; ++p) r.ent[p] = s.log[i][old_nij - 1 + p];
          r.entlen = (uint8_t)n;
        } else {                          // CheckOldConfig to self
          r.type = MT_COC; r.term = s.ct[i]; r.src = (int16_t)i;
          r.dst = (int16_t)i; r.a = 1; r.b = (int16_t)j;
        }
        bag_del(t, k);
        bag_put(c, t, r);
        t.globlen += 2;
      } else {
        bag_del(t, k);
        t.globlen++;
      }
      x.emit(x.sink, t);
      break;
    }
    case MT_COC: {                        // raft.tla:795-822
      // discard branch (guard :796 — OVERLAPS the process branch)
      if (s.st[i] != LEADER || m.term == s.ct[i]) {
        State t = s;
        bag_del(t, k);
        t.globlen++;
        x.emit(x.sink, t);
      }
      if (s.st[i] == LEADER && m.term == s.ct[i]) {
        if (max_config_index(c, s, i) <= s.ci[i]) {
          uint32_t config = get_config(c, s, i);
          uint32_t nc = m.a ? (config | 1u << m.b)
                            : (config & ~(1u << m.b));
          State t = s;
          if (nc != config) {
            if (s.llen[i] >= c.Lcap) t.overflow = 1;
            else {
              t.log[i][s.llen[i]] =
                  pack_entry(c, s.ct[i], CONFIG_ENTRY, (int)nc);
              t.llen[i]++;
            }
            t.nmc++;
            bag_del(t, k);
            t.globlen += 2;               // Receive + Add/RemoveServer
          } else {
            bag_del(t, k);
            t.globlen++;
          }
          x.emit(x.sink, t);
        } else {                          // retry loop :813-821
          State t = s;
          Msg r = m;                      // re-send same COC to self
          bag_del(t, k);
          bag_put(c, t, r);
          t.globlen += 2;
          x.emit(x.sink, t);
        }
      }
      break;
    }
    default:
      break;
  }
}

// Successor enumeration in the oracle's order (models/raft.py
// successors(); raft.tla:909-943).
inline void successors(Ctx &x, const State &s) {
  const Cfg &c = *x.c;
  for (int i = 0; i < c.S; ++i)
    for (int j = 0; j < c.S; ++j) request_vote(x, s, i, j);
  for (int i = 0; i < c.S; ++i) become_leader(x, s, i);
  for (int i = 0; i < c.S; ++i)
    for (int v = 0; v < c.nvals; ++v) client_request(x, s, i, c.vals[v]);
  for (int i = 0; i < c.S; ++i) advance_commit_index(x, s, i);
  for (int i = 0; i < c.S; ++i)
    for (int j = 0; j < c.S; ++j) append_entries(x, s, i, j);
  for (int k = 0; k < c.K; ++k) receive(x, s, k);
  for (int i = 0; i < c.S; ++i) timeout(x, s, i);
  if (c.family >= FAM_ASYNC_CRASH)
    for (int i = 0; i < c.S; ++i) restart(x, s, i);
  if (c.family >= FAM_FULL) {
    for (int k = 0; k < c.K; ++k) duplicate_message(x, s, k);
    for (int k = 0; k < c.K; ++k) drop_message(x, s, k);
  }
  if (c.family == FAM_DYNAMIC) {
    for (int i = 0; i < c.S; ++i)
      for (int j = 0; j < c.S; ++j) add_new_server(x, s, i, j);
    for (int i = 0; i < c.S; ++i)
      for (int j = 0; j < c.S; ++j) delete_server(x, s, i, j);
  }
}

// ---------------------------------------------------------------------
// Constraints (raft.tla:1105-1137) and invariants (:988-1099)
// ---------------------------------------------------------------------

inline bool constraints_ok(const Cfg &c, const State &s) {
  uint32_t m = c.con_mask;
  if (m >> CB_INFLIGHT & 1) {
    int total = 0;
    for (int k = 0; k < c.K; ++k) total += s.cnt[k];
    if (total > c.max_inflight) return false;
  }
  if (m >> CB_RVREQ & 1)
    for (int k = 0; k < c.K; ++k)
      if (s.bag[k].type == MT_RVREQ && s.cnt[k] > 1) return false;
  if (m >> CB_LOGSIZE & 1)
    for (int i = 0; i < c.S; ++i)
      if (s.llen[i] > c.L) return false;
  if (m >> CB_RESTARTS & 1)
    for (int i = 0; i < c.S; ++i)
      if (s.restarted[i] > c.max_restarts) return false;
  if (m >> CB_TIMEOUTS & 1)
    for (int i = 0; i < c.S; ++i)
      if (s.timeoutc[i] > c.max_timeouts) return false;
  if (m >> CB_TERMS & 1)
    for (int i = 0; i < c.S; ++i)
      if (s.ct[i] > c.max_terms) return false;
  if (m >> CB_CLIENTREQ & 1 && s.nreq > c.max_client_requests) return false;
  if (m >> CB_TRIEDMC & 1 && s.ntried > c.max_tried) return false;
  if (m >> CB_MC & 1 && s.nmc > c.max_mc) return false;
  int ncand = 0, sum_to = 0, sum_rs = 0;
  bool any_restart = false;
  for (int i = 0; i < c.S; ++i) {
    ncand += s.st[i] == CANDIDATE;
    sum_to += s.timeoutc[i];
    sum_rs += s.restarted[i];
    any_restart |= s.restarted[i] != 0;
  }
  if (m >> CB_UNCONTESTED & 1 && ncand > 1) return false;
  if (m >> CB_CLEANFIRSTREQ & 1 && s.nleaders < 1 && s.nreq < 1)
    if (any_restart || sum_to > 1 || ncand > 1) return false;
  if (m >> CB_CLEANTWOLEADERS & 1 && s.nleaders < 2)
    if (sum_rs > 1 || sum_to > 2) return false;
  if (m >> CB_CLEANFIRSTELECTION & 1 && s.nleaders < 1)
    if (any_restart || ncand > 1) return false;
  return true;
}

// IsPrefix(Committed(i), log[j])  (raft.tla:969; committed clamps)
inline bool prefix_ok(const Cfg &c, const State &s, int i, int j) {
  int n = std::min<int>(s.ci[i], s.llen[i]);
  if (n > s.llen[j]) return false;
  for (int k = 0; k < n; ++k)
    if (s.log[i][k] != s.log[j][k]) return false;
  return true;
}

// Returns a bitmask of VIOLATED invariants.
inline uint32_t check_invariants(const Cfg &c, const State &s) {
  uint32_t viol = 0;
  uint32_t m = c.inv_mask;
  int S = c.S;

  if (m >> IB_LEADERVOTESQUORUM & 1 && s.nmc == 0) {  // :988-993
    for (int i = 0; i < S; ++i) {
      if (s.st[i] != LEADER) continue;
      uint32_t voters = 0;
      for (int j = 0; j < S; ++j)
        if (s.ct[j] > s.ct[i] || (s.ct[j] == s.ct[i] && s.vf[j] == i))
          voters |= 1u << j;
      if (!in_quorum(voters, get_config(c, s, i)))
        viol |= 1u << IB_LEADERVOTESQUORUM;
    }
  }
  if (m >> IB_CANDTERMNOTINLOG & 1 && s.nmc == 0) {   // :997-1004
    for (int i = 0; i < S; ++i) {
      if (s.st[i] != CANDIDATE) continue;
      uint32_t voters = 0;
      for (int j = 0; j < S; ++j)
        if (s.ct[j] == s.ct[i] && (s.vf[j] == i || s.vf[j] == NIL))
          voters |= 1u << j;
      if (!in_quorum(voters, get_config(c, s, i))) continue;
      for (int j = 0; j < S; ++j)
        for (int k = 0; k < s.llen[j]; ++k)
          if (entry_term(c, s.log[j][k]) == s.ct[i])
            viol |= 1u << IB_CANDTERMNOTINLOG;
    }
  }
  if (m >> IB_ELECTIONSAFETY & 1) {                   // :1009-1014
    for (int i = 0; i < S; ++i) {
      if (s.st[i] != LEADER) continue;
      int mine = 0;
      for (int k = 0; k < s.llen[i]; ++k)
        if (entry_term(c, s.log[i][k]) == s.ct[i]) mine = k + 1;
      for (int j = 0; j < S; ++j) {
        int other = 0;
        for (int k = 0; k < s.llen[j]; ++k)
          if (entry_term(c, s.log[j][k]) == s.ct[i]) other = k + 1;
        if (other > mine) viol |= 1u << IB_ELECTIONSAFETY;
      }
    }
  }
  if (m >> IB_LOGMATCHING & 1) {                      // :1017-1021
    for (int i = 0; i < S; ++i)
      for (int j = 0; j < S; ++j) {
        int upto = std::min<int>(s.llen[i], s.llen[j]);
        bool pref_eq = true;
        for (int k = 0; k < upto; ++k) {
          pref_eq = pref_eq && s.log[i][k] == s.log[j][k];
          if (entry_term(c, s.log[i][k]) == entry_term(c, s.log[j][k]) &&
              !pref_eq)
            viol |= 1u << IB_LOGMATCHING;
        }
      }
  }
  if (m >> IB_VOTESGRANTED & 1) {                     // :1048-1052
    for (int i = 0; i < S; ++i)
      if (s.vf[i] != NIL && !prefix_ok(c, s, i, s.vf[i]))
        viol |= 1u << IB_VOTESGRANTED;
  }
  if (m >> IB_VOTESGRANTED_FALSE & 1) {               // :1038-1046
    for (int i = 0; i < S; ++i)
      for (int j = 0; j < S; ++j)
        if ((s.vg[i] >> j & 1) && s.ct[i] == s.ct[j] &&
            !prefix_ok(c, s, j, i))
          viol |= 1u << IB_VOTESGRANTED_FALSE;
  }
  if (m >> IB_QUORUMLOG & 1) {                        // :1056-1060
    for (int i = 0; i < S; ++i) {
      uint32_t config = get_config(c, s, i), good = 0;
      for (int j = 0; j < S; ++j)
        if (prefix_ok(c, s, i, j)) good |= 1u << j;
      uint32_t bad = config & ~good;
      if (2 * popcount(bad) > popcount(config))
        viol |= 1u << IB_QUORUMLOG;
    }
  }
  if (m >> IB_MOREUPTODATE & 1) {                     // :1066-1071
    for (int i = 0; i < S; ++i)
      for (int j = 0; j < S; ++j) {
        int li = last_term(c, s, i), lj = last_term(c, s, j);
        bool more = li > lj || (li == lj && s.llen[i] >= s.llen[j]);
        if (more && !prefix_ok(c, s, j, i))
          viol |= 1u << IB_MOREUPTODATE;
      }
  }
  if (m >> IB_LEADERCOMPLETE & 1) {                   // :1089-1099
    for (int i = 0; i < S; ++i) {
      int n = std::min<int>(s.ci[i], s.llen[i]);
      for (int k = 0; k < n; ++k)
        for (int l = 0; l < S; ++l)
          if (s.st[l] == LEADER &&
              s.ct[l] > entry_term(c, s.log[i][k]) &&
              (s.llen[l] <= k || s.log[l][k] != s.log[i][k]))
            viol |= 1u << IB_LEADERCOMPLETE;
    }
  }
  if (m >> IB_LEADERCOMPLETE_FALSE & 1) {             // :1079-1083
    for (int i = 0; i < S; ++i)
      if (s.st[i] == LEADER)
        for (int j = 0; j < S; ++j)
          if (!prefix_ok(c, s, j, i))
            viol |= 1u << IB_LEADERCOMPLETE_FALSE;
  }
  if (m >> IB_ONEATATIME & 1) {                       // ours (SURVEY)
    for (int i = 0; i < S; ++i) {
      int n = 0;
      for (int k = s.ci[i]; k < s.llen[i]; ++k)
        n += entry_type(c, s.log[i][k]) == CONFIG_ENTRY;
      if (n > 1) viol |= 1u << IB_ONEATATIME;
    }
  }
  return viol;
}

// ---------------------------------------------------------------------
// Multi-threaded level-synchronous BFS
// ---------------------------------------------------------------------

constexpr int NSHARD = 64;

struct VisitedSet {
  std::unordered_set<uint64_t> shard[NSHARD];
  std::mutex mu[NSHARD];
  // returns true if newly inserted
  bool insert(uint64_t fp) {
    int sh = fp & (NSHARD - 1);
    std::lock_guard<std::mutex> g(mu[sh]);
    return shard[sh].insert(fp).second;
  }
  size_t size() {
    size_t n = 0;
    for (auto &s : shard) n += s.size();
    return n;
  }
};

struct Stats {
  int64_t distinct = 0, generated = 0, depth = 0, overflow = 0;
  uint32_t violated = 0;   // union of violated invariant bits
};

struct WorkerSink {
  const Cfg *c;
  VisitedSet *visited;
  std::vector<State> next;
  int64_t generated = 0, overflow = 0, distinct = 0;
  uint32_t violated = 0;
};

void worker_emit(void *sink_, const State &t) {
  auto *w = static_cast<WorkerSink *>(sink_);
  w->generated++;
  uint64_t fp = fingerprint(*w->c, t);
  if (!w->visited->insert(fp)) return;
  w->distinct++;
  if (t.overflow) w->overflow++;
  w->violated |= check_invariants(*w->c, t);
  if (constraints_ok(*w->c, t)) w->next.push_back(t);
}

}  // namespace

extern "C" {

// cfg_arr layout — keep in sync with native/__init__.py _pack_cfg():
//  [0]=S [1]=nvals [2..9]=vals [10]=init_mask [11]=num_rounds [12]=family
//  [13]=L [14]=Lcap [15]=K [16]=max_restarts [17]=max_timeouts
//  [18]=max_terms [19]=max_client_requests [20]=max_mc [21]=max_tried
//  [22]=max_inflight [23]=max_trace [24]=con_mask [25]=inv_mask
//  [26]=symmetry [27]=threads [28]=max_depth [29]=max_states
//  [30]=stop_on_violation [31]=value_bits
//  [32]=n_perms [33...]=perms flattened (n_perms * S entries)
// out: [0]=distinct [1]=generated [2]=depth [3]=violated_mask [4]=overflow
int64_t raft_check(const int64_t *a, int64_t *out) {
  Cfg c{};
  c.S = (int)a[0];
  c.nvals = (int)a[1];
  for (int v = 0; v < c.nvals; ++v) c.vals[v] = (int)a[2 + v];
  c.init_mask = (int)a[10];
  c.num_rounds = (int)a[11];
  c.family = (int)a[12];
  c.L = (int)a[13];
  c.Lcap = (int)a[14];
  c.K = (int)a[15];
  c.max_restarts = (int)a[16];
  c.max_timeouts = (int)a[17];
  c.max_terms = (int)a[18];
  c.max_client_requests = (int)a[19];
  c.max_mc = (int)a[20];
  c.max_tried = (int)a[21];
  c.max_inflight = (int)a[22];
  c.max_trace = (int)a[23];
  c.con_mask = (uint32_t)a[24];
  c.inv_mask = (uint32_t)a[25];
  c.symmetry = (int)a[26];
  c.threads = (int)a[27];
  int64_t max_depth = a[28];
  int64_t max_states = a[29];
  // a[30] stop_on_violation: BFS stops at the level a violation appears
  c.value_bits = (int)a[31];
  c.entry_bits = 0;
  c.n_perms = (int)a[32];
  if (c.S > SMAX || c.K > KMAX || c.Lcap > LCAPMAX ||
      c.nvals > VMAX || c.n_perms > PMAX || c.L > LMAX)
    return -1;
  for (int p = 0; p < c.n_perms; ++p)
    for (int i = 0; i < c.S; ++i)
      c.perms[p][i] = (int8_t)a[33 + p * c.S + i];

  // Init (raft.tla:367-393)
  State init{};
  for (int i = 0; i < c.S; ++i) {
    init.ct[i] = 1;
    init.st[i] = FOLLOWER;
    init.vf[i] = NIL;
    for (int j = 0; j < c.S; ++j) init.ni[i][j] = 1;
  }

  Stats st;
  VisitedSet visited;
  visited.insert(fingerprint(c, init));
  st.distinct = 1;
  st.generated = 1;
  st.violated |= check_invariants(c, init);
  std::vector<State> frontier;
  if (constraints_ok(c, init)) frontier.push_back(init);

  int nthreads = std::max(1, c.threads);
  while (!frontier.empty() && st.depth < max_depth &&
         st.distinct < max_states) {
    st.depth++;
    std::vector<WorkerSink> sinks(nthreads);
    std::vector<std::thread> threads;
    std::atomic<size_t> cursor{0};
    const size_t grain = 64;
    for (int t = 0; t < nthreads; ++t) {
      sinks[t].c = &c;
      sinks[t].visited = &visited;
      threads.emplace_back([&, t]() {
        Ctx x{&c, &sinks[t], worker_emit};
        for (;;) {
          size_t base = cursor.fetch_add(grain);
          if (base >= frontier.size()) break;
          size_t end = std::min(frontier.size(), base + grain);
          for (size_t q = base; q < end; ++q) successors(x, frontier[q]);
        }
      });
    }
    for (auto &t : threads) t.join();
    std::vector<State> next;
    for (auto &w : sinks) {
      st.generated += w.generated;
      st.distinct += w.distinct;
      st.overflow += w.overflow;
      st.violated |= w.violated;
      next.insert(next.end(), w.next.begin(), w.next.end());
    }
    frontier.swap(next);
    if (a[30] && st.violated) break;
  }

  out[0] = st.distinct;
  out[1] = st.generated;
  out[2] = st.depth;
  out[3] = (int64_t)st.violated;
  out[4] = st.overflow;
  return 0;
}

}  // extern "C"
