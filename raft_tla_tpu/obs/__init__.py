"""Unified observability layer for every engine driver.

One ``Obs`` bundle rides through a run and fans out to four sinks,
each optional and individually cheap enough to leave on:

- **spans** (`obs/spans.py`) — nested phase timers on
  ``time.perf_counter()``, emitted as Chrome-trace JSON
  (``--trace-timeline``, loads in Perfetto);
- **ledger** (`obs/ledger.py`) — one JSONL record per dispatch
  (``--ledger``): depth, frontier, the full metrics-registry snapshot,
  states/sec, dedup hit rate, RSS, device memory — flushed per record
  so a killed run keeps its telemetry;
- **heartbeat** (`obs/heartbeat.py`) — a small JSON atomically
  rewritten every dispatch (``--heartbeat``) so a watchdog can tell a
  slow level from a dead tunnel;
- **profiler** — opt-in ``jax.profiler.trace`` capture
  (``--profile-dir``) with ``TraceAnnotation`` names matching the span
  names, so the XLA device trace lines up with the host timeline.

ISSUE 17 adds the cross-run half: every bundle carries a **run id**
(stamped into every ledger row and heartbeat, so interleaved/resumed
runs demultiplex), a **resource sampler** (`obs/resources.py` — RSS /
device-memory peaks + compile wall-clock, sampled at every dispatch),
and an optional **registry** (`obs/registry.py`, ``--registry DIR``)
that receives one atomic schema-versioned record per run at
``finish()`` — counters, span rollups, resource peaks, backend
fingerprint, exit status, artifact paths.  ``cli obs`` queries it.

Engines take ``obs=None`` in ``check()``/``run()`` and default to
``NULL_OBS`` (every hook a no-op); the CLI builds a real bundle from
the flags via ``from_flags`` and owns its lifecycle
(``start``/``finish``).  The counters themselves live in
``obs/metrics.py``'s registry — see that module for why.
"""

from __future__ import annotations

import contextlib
import os
import time
from typing import Dict, Optional

from .heartbeat import Heartbeat
from .ledger import RunLedger, device_memory_stats, rss_bytes
from .metrics import (BURST_COUNTER_KEYS, CHECK_COUNTER_KEYS,
                      MXU_COUNTER_KEYS, SIM_COUNTER_KEYS,
                      SIM_DISPATCH_KEYS, MetricsRegistry, check_stats,
                      sim_counters, sim_stats)
from .registry import RunRegistry, new_run_id
from .resources import ResourceSampler, backend_fingerprint
from .spans import SpanRecorder

__all__ = [
    "Obs", "NULL_OBS", "from_flags", "SpanRecorder", "RunLedger",
    "Heartbeat", "MetricsRegistry", "RunRegistry", "ResourceSampler",
    "check_stats", "sim_stats", "sim_counters", "rss_bytes",
    "device_memory_stats", "backend_fingerprint", "new_run_id",
    "CHECK_COUNTER_KEYS", "BURST_COUNTER_KEYS", "MXU_COUNTER_KEYS",
    "SIM_COUNTER_KEYS", "SIM_DISPATCH_KEYS",
]

_NULL_CTX = contextlib.nullcontext()


class Obs:
    """Per-run observability bundle (see module docstring).  With no
    sinks configured every hook is a no-op — the engines call
    ``span``/``dispatch`` unconditionally."""

    def __init__(self, spans: Optional[SpanRecorder] = None,
                 ledger: Optional[RunLedger] = None,
                 heartbeat: Optional[Heartbeat] = None,
                 profile_dir: Optional[str] = None,
                 meta: Optional[Dict] = None,
                 registry: Optional[RunRegistry] = None,
                 run_info: Optional[Dict] = None):
        self.spans = spans
        self.ledger = ledger
        self.heartbeat = heartbeat
        self.profile_dir = profile_dir
        self.registry = registry
        # run-constant stamp merged into every ledger record (the CLI
        # passes the active spec name + IR fingerprint here, so every
        # dispatch line names the frontend that compiled the run)
        self.meta = dict(meta or {})
        # run-level context for the meta row + registry record ONLY
        # (cmd name, cfg repr — too bulky to ride every dispatch row)
        self.run_info = dict(run_info or {})
        self._profiling = False
        self._t0 = time.perf_counter()
        self._started_ts = time.time()
        self._n_dispatch = 0
        self._last_jobs = None
        self._last_slo = None
        self._last_wave = None
        self._last_daemon = None
        self._last_metrics: Optional[Dict] = None
        # one id per run, stamped into every ledger row (RunLedger's
        # stamp), the heartbeat, and the registry record, so
        # interleaved/resumed runs demultiplex and a registry record
        # cross-links its artifact files
        self.run_id = new_run_id() if (
            ledger is not None or heartbeat is not None
            or registry is not None) else None
        if self.run_id is not None:
            if ledger is not None:
                ledger.stamp["run_id"] = self.run_id
            if heartbeat is not None:
                heartbeat.run_id = self.run_id
        # resource sampler (obs/resources): fed at every dispatch,
        # surfaced on heartbeats, as throttled kind="resource" ledger
        # rows, and as the registry record's rollup
        self._resources = ResourceSampler(spans=spans) if (
            ledger is not None or heartbeat is not None
            or registry is not None) else None
        if profile_dir and spans is not None:
            # device traces only line up with the host timeline if the
            # TraceAnnotation names match the span names
            spans.annotate = True

    @property
    def enabled(self) -> bool:
        return (self.spans is not None or self.ledger is not None
                or self.heartbeat is not None
                or self.profile_dir is not None
                or self.registry is not None)

    # -- hooks the engines call ---------------------------------------

    def span(self, name: str):
        if self.spans is None:
            return _NULL_CTX
        return self.spans.span(name)

    def dispatch(self, *, kind: str, depth: int, frontier: int = 0,
                 metrics: Optional[Dict] = None,
                 states: Optional[int] = None,
                 jobs: Optional[Dict] = None,
                 slo: Optional[Dict] = None,
                 wave: Optional[Dict] = None):
        """One record per dispatch (burst device call / per-level round
        trip / sim dispatch / batched multi-job call): ledger line +
        heartbeat rewrite.  ``jobs`` is the serving layer's per-job
        status map ({label: {depth, distinct, status}}): it rides the
        heartbeat so ``tools/watch.py`` renders one line per job, and
        the ledger record carries its live/total counts (full per-job
        rows land as separate kind="job" records at job completion).
        ``slo`` is the serving layer's SLO snapshot (queue depth,
        wait/service-seconds histograms, exec-cache counters): it
        rides the heartbeat next to the job map — watch renders the
        queue line — and the ledger record carries queue_depth.
        ``wave`` (round 16) is the batched wave's occupancy snapshot
        ({devices, lanes, filled, pad, jobs_per_device}): the ledger
        record gets ``wave_devices``/``wave_lanes``/``wave_pad`` and
        the heartbeat carries the full block for watch's ``pad N/M``
        line."""
        self._n_dispatch += 1
        metrics = metrics or {}
        if metrics:
            self._last_metrics = dict(metrics)
        if states is None:
            states = int(metrics.get("distinct_states",
                                     metrics.get("walker_steps", 0)))
        res_snap = None
        if self._resources is not None:
            res_snap = self._resources.sample()
            if self.ledger is not None and self._resources.due():
                # the resource row precedes the dispatch row: the
                # ledger's FINAL record stays the final dispatch record
                # (obs_smoke pins that contract)
                rrec = dict(self.meta)
                rrec["kind"] = "resource"
                rrec["depth"] = int(depth)
                rrec.update(res_snap)
                self.ledger.record(rrec)
        if self.ledger is not None:
            secs = time.perf_counter() - self._t0
            # counters first, header fields second: the registry's
            # `depth` counter is only finalized at run end, so the
            # dispatch-passed depth must win
            rec = dict(metrics)
            rec.update(self.meta)
            rec["kind"] = kind
            rec["depth"] = int(depth)
            rec["frontier"] = int(frontier)
            rec["dispatch"] = self._n_dispatch
            rec["seconds"] = round(secs, 3)
            rec["states_per_sec"] = round(states / max(secs, 1e-9), 1)
            gen = int(metrics.get("generated_states", 0) or 0)
            if gen:
                rec["dedup_hit_rate"] = round(
                    1.0 - int(metrics["distinct_states"]) / gen, 4)
            rec["rss_bytes"] = rss_bytes()
            dev = device_memory_stats()
            if dev:
                rec["device_memory"] = dev
            if jobs is not None:
                rec["jobs_total"] = len(jobs)
                rec["jobs_live"] = sum(
                    1 for j in jobs.values()
                    if j.get("status") == "running")
            if slo is not None and "queue_depth" in slo:
                rec["queue_depth"] = int(slo["queue_depth"])
            if wave is not None:
                rec["wave_devices"] = int(wave.get("devices", 1))
                rec["wave_lanes"] = int(wave.get("lanes", 0))
                rec["wave_pad"] = int(wave.get("pad", 0))
                rec["wave_state_shards"] = int(
                    wave.get("state_shards", 1))
            self.ledger.record(rec)
        if jobs is not None:
            self._last_jobs = jobs
        if slo is not None:
            self._last_slo = dict(slo)
        if wave is not None:
            self._last_wave = dict(wave)
        if self.heartbeat is not None:
            extra = {}
            if jobs is not None:
                extra["jobs"] = jobs
            if slo is not None:
                extra["slo"] = dict(slo)
            if wave is not None:
                extra["wave"] = dict(wave)
            if res_snap is not None:
                extra["resources"] = res_snap
            if self._last_daemon is not None:
                # a daemon's in-wave dispatch beats keep the daemon
                # block visible — watch's daemon view never flickers
                # away while a wave is running
                extra["daemon"] = self._last_daemon
            self.heartbeat.beat(depth=depth, states=states,
                                extra=extra or None)

    def set_jobs(self, jobs: Dict, slo: Optional[Dict] = None):
        """Update the per-job status map (and optionally the SLO
        snapshot) the final heartbeat carries (the serving layer
        records cache hits and fallback/sequential jobs here — they
        finish outside any batched dispatch)."""
        self._last_jobs = dict(jobs)
        if slo is not None:
            self._last_slo = dict(slo)

    def daemon_beat(self, *, status: str, stats: Dict):
        """One daemon lifecycle beat (serve/daemon): heartbeat status
        ``idle|serving|draining`` plus the ``daemon`` block (queue
        depths, cycle/done/rejected counters, per-tenant rollups)
        tools/watch.py renders as the daemon view.  The block is also
        remembered so every subsequent dispatch beat carries it."""
        self._last_daemon = dict(stats)
        if self.heartbeat is None:
            return
        extra = {"daemon": self._last_daemon}
        if self._last_jobs is not None:
            extra["jobs"] = self._last_jobs
        if self._last_slo is not None:
            extra["slo"] = self._last_slo
        self.heartbeat.beat(depth=self.heartbeat.last_depth,
                            states=self.heartbeat.last_states,
                            status=status, extra=extra)

    def retry(self, *, attempt: int, max_attempts: int, wait_s: float,
              error):
        """One supervised-retry event (resil/supervisor): a
        ``kind="retry"`` ledger record plus a ``status="backoff"``
        heartbeat rewrite carrying the attempt counters, so a watchdog
        (tools/watch.py) shows a RETRYING run instead of a silent gap
        between dispatches."""
        retry_info = {"attempt": int(attempt),
                      "max_attempts": int(max_attempts),
                      "wait_s": round(float(wait_s), 3),
                      "error": str(error)[:300]}
        if self.ledger is not None:
            rec = dict(self.meta)
            rec["kind"] = "retry"
            rec.update(retry_info)
            self.ledger.record(rec)
        if self.heartbeat is not None:
            self.heartbeat.beat(depth=self.heartbeat.last_depth,
                                states=self.heartbeat.last_states,
                                status="backoff",
                                extra={"retry": retry_info})

    # -- lifecycle (the CLI owns it) ----------------------------------

    def start(self):
        self._t0 = time.perf_counter()
        self._started_ts = time.time()
        if self.ledger is not None:
            # ONE kind="meta" row at run start: run id (ledger stamp),
            # spec + IR fingerprint (meta), pid, cmd/cfg context and
            # the shared backend fingerprint — every ledger names the
            # process and backend that produced it
            rec = dict(self.meta)
            rec.update(self.run_info)
            rec["kind"] = "meta"
            rec["pid"] = os.getpid()
            rec["backend"] = backend_fingerprint()
            self.ledger.record(rec)
        if self.profile_dir:
            import jax
            jax.profiler.start_trace(self.profile_dir)
            self._profiling = True
        return self

    def finish(self, depth: Optional[int] = None,
               states: Optional[int] = None, status: str = "finished",
               counters: Optional[Dict] = None,
               level_sizes=None, extra: Optional[Dict] = None):
        """``extra`` (the daemon's drain epilogue): merged into both
        the final heartbeat's extra payload and the registry record's
        top level — e.g. ``{"daemon": {...}, "drain_reason": ...}``.
        A ``status`` key in it overrides the REGISTRY record's status
        only (the daemon records ``draining`` when it exits with work
        still parked) — the heartbeat keeps the ``status`` argument,
        so watch always sees the terminal done/failed.  Callers own
        the remaining key hygiene (don't shadow core fields)."""
        if self._profiling:
            import jax
            try:
                jax.profiler.stop_trace()
            finally:
                self._profiling = False
        if self.heartbeat is not None:
            # a terminal status without fresh numbers (the CLI's
            # failure path passes depth=None) still stamps the file —
            # a watchdog must see "failed", not an eternal "running"
            self.heartbeat.beat(
                depth=depth if depth is not None
                else self.heartbeat.last_depth,
                states=int(states if states is not None
                           else self.heartbeat.last_states),
                status=status,
                # a batch run's final beat keeps the per-job map (and
                # the SLO snapshot), so watch renders the job + queue
                # lines next to FINISHED
                extra=(({"jobs": self._last_jobs}
                        if self._last_jobs is not None else {}) |
                       ({"slo": self._last_slo}
                        if self._last_slo is not None else {}) |
                       ({"wave": self._last_wave}
                        if self._last_wave is not None else {}) |
                       ({"resources": self._resources.sample()}
                        if self._resources is not None else {}) |
                       ({"daemon": self._last_daemon}
                        if self._last_daemon is not None else {}) |
                       ({k: v for k, v in extra.items()
                         if k != "status"} if extra else {})) or
                None)
        if self.registry is not None:
            # ONE atomic schema-versioned record per run — the
            # cross-run half of the obs layer (obs/registry).
            # ``counters`` is the final metrics snapshot when the
            # caller has it (r.metrics.as_dict()); otherwise the last
            # dispatched snapshot stands in (its `depth` counter may
            # lag — the top-level depth field is authoritative)
            rec = dict(self.meta)
            rec.update(self.run_info)
            rec["run_id"] = self.run_id
            rec["status"] = status
            rec["started_ts"] = round(self._started_ts, 3)
            rec["finished_ts"] = round(time.time(), 3)
            rec["seconds"] = round(time.perf_counter() - self._t0, 3)
            if depth is not None:
                rec["depth"] = int(depth)
            if states is not None:
                rec["distinct_states"] = int(states)
            rec["counters"] = dict(counters if counters is not None
                                   else self._last_metrics or {})
            if level_sizes is not None:
                rec["level_sizes"] = [int(x) for x in level_sizes]
            rec["spans"] = (self.spans.totals()
                            if self.spans is not None else {})
            rec["resources"] = (self._resources.rollup()
                                if self._resources is not None else {})
            rec["backend"] = backend_fingerprint()
            rec["artifacts"] = {
                k: v for k, v in (
                    ("ledger", getattr(self.ledger, "path", None)),
                    ("heartbeat",
                     getattr(self.heartbeat, "path", None)),
                    ("timeline", getattr(self.spans, "path", None)),
                    ("profile_dir", self.profile_dir)) if v}
            if extra:
                rec.update(extra)
            self.registry.append(rec)
        if self.ledger is not None:
            self.ledger.close()
        if self.spans is not None:
            self.spans.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.finish()


NULL_OBS = Obs()


def from_flags(ledger: Optional[str] = None,
               heartbeat: Optional[str] = None,
               timeline: Optional[str] = None,
               profile_dir: Optional[str] = None,
               meta: Optional[Dict] = None,
               registry: Optional[str] = None,
               run_info: Optional[Dict] = None) -> Obs:
    """Build the bundle the CLI flags describe (NULL_OBS when none are
    set, so callers can pass the result unconditionally).  A registry
    without a timeline still gets an in-memory SpanRecorder: the run
    record's span rollups (and the sampler's compile seconds) must
    exist whether or not a trace file was requested."""
    if not (ledger or heartbeat or timeline or profile_dir
            or registry):
        return NULL_OBS
    return Obs(
        spans=SpanRecorder(timeline)
        if (timeline or profile_dir or registry) else None,
        ledger=RunLedger(ledger) if ledger else None,
        heartbeat=Heartbeat(heartbeat) if heartbeat else None,
        profile_dir=profile_dir, meta=meta,
        registry=RunRegistry(registry) if registry else None,
        run_info=run_info)
