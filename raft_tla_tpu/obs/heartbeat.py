"""Heartbeat file: atomically rewritten every dispatch.

A watchdog tailing a long tunneled-TPU run could not previously
distinguish "depth 20 is just a big level" from "the tunnel died an
hour ago" — rounds 4-5 lost multi-hour runs exactly that way.  The
engines now rewrite a small JSON (pid, depth, last-dispatch wall
timestamp, states enqueued) via write-then-rename on every dispatch,
so an external process (``tools/watch.py``, or any cron) can compare
``last_dispatch_ts`` against the clock and the pid against the process
table without touching the run.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional


class Heartbeat:
    def __init__(self, path: str):
        self.path = path
        self._pid = os.getpid()
        self._started = time.time()
        self._beats = 0
        # the owning run's id (Obs sets it): cross-links the heartbeat
        # to the ledger rows and registry record of the same run
        self.run_id: Optional[str] = None
        # last-known progress, so a terminal "failed" beat (which has
        # no fresher numbers) can still stamp the file
        self.last_depth = 0
        self.last_states = 0

    def beat(self, depth: int, states: int, status: str = "running",
             extra: Optional[Dict] = None):
        self._beats += 1
        self.last_depth = int(depth)
        self.last_states = int(states)
        obj = {
            "pid": self._pid,
            "status": status,
            "depth": int(depth),
            "states_enqueued": int(states),
            "last_dispatch_ts": round(time.time(), 3),
            "started_ts": round(self._started, 3),
            "beats": self._beats,
        }
        if self.run_id is not None:
            obj["run_id"] = self.run_id
        if extra:
            obj.update(extra)
        # write-then-rename: a reader never sees a torn file, and a
        # run killed mid-beat leaves the previous complete heartbeat
        tmp = self.path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(obj, fh)
        os.replace(tmp, self.path)


def read_heartbeat(path: str) -> Dict:
    """Load + sanity-check a heartbeat file (tools/watch.py and the CI
    smoke validation share this)."""
    with open(path) as fh:
        obj = json.load(fh)
    for key in ("pid", "depth", "last_dispatch_ts", "states_enqueued"):
        if key not in obj:
            raise ValueError(f"{path}: not a heartbeat file "
                             f"(missing {key!r})")
    return obj
