"""JSONL run ledger: one record per dispatch, written incrementally.

Rounds 4-5 lost multi-hour tunneled-TPU runs with nothing to show for
them: the stats existed only as in-process counters, so a dropped
connection destroyed the whole run's telemetry.  The ledger appends
one JSON line per dispatch (burst device call, per-level round trip,
or sim dispatch) and flushes it immediately, so a killed run leaves a
complete record up to the last dispatch — depth, frontier size,
cumulative registry counters, throughput, host RSS and device memory
(``jax.local_devices()[0].memory_stats()`` where the backend reports
it).  ``tools/watch.py`` tails it for live progress.
"""

from __future__ import annotations

import itertools
import json
import os
import time
from typing import Dict, Optional

# monotonic per-PROCESS record sequence (ISSUE 17 satellite): shared
# across every RunLedger in the process, so records of interleaved
# runs (or one run appending after a resume) order deterministically
# even when two ledgers target the same file; readers pair it with
# the per-run ``run_id`` stamp to demultiplex.  Old rows without the
# keys still parse — readers use .get().
_SEQ = itertools.count(1)


def rss_bytes() -> int:
    """Current process resident set size (bytes); 0 if unknowable."""
    try:
        with open("/proc/self/status") as fh:
            for line in fh:
                if line.startswith("VmRSS:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        import resource
        import sys
        ru = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # ru_maxrss units are platform-defined: bytes on macOS,
        # KiB everywhere else that matters here
        return int(ru) * (1 if sys.platform == "darwin" else 1024)
    except Exception:
        return 0


def device_memory_stats() -> Optional[Dict[str, int]]:
    """``memory_stats()`` of device 0, trimmed to the interesting
    gauges; None where the backend (e.g. XLA:CPU) reports nothing."""
    try:
        import jax
        stats = jax.local_devices()[0].memory_stats()
    except Exception:
        return None
    if not stats:
        return None
    keep = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")
    out = {k: int(stats[k]) for k in keep if k in stats}
    return out or None


class RunLedger:
    """Append-only JSONL writer; every record carries a wall-clock
    timestamp (for correlating with external logs) and a monotonic
    one (for durations)."""

    def __init__(self, path: str):
        self.path = path
        # run-constant keys applied to EVERY record via setdefault
        # (Obs installs {"run_id": ...} here, so rows recorded
        # directly by the serving layer carry it too)
        self.stamp: Dict = {}
        # append, never truncate: a resumed run (--resume after a
        # dropped tunnel) must extend the pre-crash telemetry, which is
        # exactly the record the ledger exists to preserve
        self._fh = open(path, "a")
        self._t0 = time.perf_counter()

    def record(self, rec: Dict):
        rec = dict(rec)
        for k, v in self.stamp.items():
            rec.setdefault(k, v)
        rec.setdefault("seq", next(_SEQ))
        rec.setdefault("ts", round(time.time(), 3))
        rec.setdefault("t_mono", round(time.perf_counter() - self._t0, 6))
        self._fh.write(json.dumps(rec) + "\n")
        # flush per record: the OS has the line even if the process is
        # killed mid-run (the whole point of the ledger)
        self._fh.flush()

    def close(self):
        if self._fh is not None:
            self._fh.close()
            self._fh = None
