"""Metrics registry: the ONE store for a run's scalar counters.

Before this module the counters lived four lives — mutated as ad-hoc
``CheckResult`` fields by each engine's harvest loop, hand-copied into
the CLI's ``--stats-json`` dict, re-copied into checkpoint meta, and
re-derived by bench/deep_run — and the copies drifted (the
``levels_fused`` pseudo-level bug needed three review passes to fix in
every copy).  Now:

- ``MetricsRegistry`` holds the counters; ``engine.bfs.CheckResult``
  exposes them as write-through attribute views, so a driver mutating
  ``res.levels_fused`` IS updating the registry — there is no second
  store to fall out of sync;
- ``check_stats`` / ``sim_stats`` are the single assemblers of the
  ``--stats-json`` payloads (cli, the run ledger and the tests all call
  them), with the pre-registry key order pinned by
  ``tests/test_obs.py`` for byte-compatibility.

Keys are registered once (``register``) and unknown-key writes raise —
a typo'd counter fails loudly instead of forking a new silent copy.
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional

# the canonical counter set every exhaustive-check engine accumulates
# (bfs / spill / mesh / spill_mesh all share CheckResult, so the set is
# structurally identical across them — tests/test_obs.py pins it)
CHECK_COUNTER_KEYS = (
    "distinct_states", "generated_states", "depth", "overflow_faults",
    "violations_global", "levels_fused", "burst_dispatches",
    "burst_bailouts", "pin_interior_states", "guard_matmul",
    "dedup_kernel", "delta_matmul", "sym_canon")

# the MXU-path mode flags (0/1): which expansion/dedup program this
# run executed — BENCH rounds 9/11 read these next to the
# guard_matmul / dedup_kernel / delta_apply span totals so the A/B
# attributes per phase AND records which mode produced each row.
# Stamped LIVE by every engine's _stamp_mode (never serialized into
# checkpoints — a resumed run reports the resuming engine's modes).
MXU_COUNTER_KEYS = ("guard_matmul", "dedup_kernel", "delta_matmul",
                    # 1 = orbit-sort canonical fingerprints (round 15),
                    # 0 = min-over-perms; the resolved --sym-canon mode
                    "sym_canon")

# the burst telemetry triple that must agree between the ledger,
# --stats-json and checkpoint meta (the PR-5 drift class)
BURST_COUNTER_KEYS = ("levels_fused", "burst_dispatches",
                      "burst_bailouts")

# the sim engine's canonical counter set (SimResult fields surfaced by
# sim_stats and the simulate ledger's final record)
SIM_COUNTER_KEYS = (
    "walkers", "steps_dispatched", "walker_steps", "sampled_steps",
    "restarts", "deadlocks", "promotions", "hits",
    "est_distinct_states", "bloom_saturated", "bloom_canonical")

# the per-dispatch subset knowable without a device bloom fetch
# (sim/walker.dispatch_counters emits exactly these)
SIM_DISPATCH_KEYS = (
    "walkers", "steps_dispatched", "walker_steps", "sampled_steps",
    "restarts", "deadlocks", "promotions", "hits")


class MetricsRegistry:
    """A named-counter store with explicit registration.

    ``register`` declares a counter once; ``set``/``inc`` update it and
    raise ``KeyError`` on undeclared names, so every counter any code
    path reports must appear in the declared set — new telemetry is
    added in exactly one place and shows up in every consumer
    (ledger, stats JSON, checkpoint meta) automatically.
    """

    __slots__ = ("_vals",)

    def __init__(self, initial: Optional[Mapping] = None):
        self._vals: Dict[str, object] = {}
        if initial:
            for k, v in initial.items():
                self.register(k, v)

    def register(self, name: str, value=0):
        if name in self._vals:
            raise ValueError(f"metric {name!r} already registered")
        self._vals[name] = value

    def set(self, name: str, value):
        if name not in self._vals:
            raise KeyError(
                f"metric {name!r} not registered (known: "
                f"{', '.join(sorted(self._vals))})")
        self._vals[name] = value

    def inc(self, name: str, delta=1):
        self.set(name, self._vals[name] + delta)

    def get(self, name: str):
        return self._vals[name]

    def __contains__(self, name: str) -> bool:
        return name in self._vals

    def keys(self):
        return tuple(self._vals.keys())

    def as_dict(self) -> Dict[str, object]:
        """Snapshot in registration order (dict order is insertion
        order, so consumers emit a stable key sequence)."""
        return dict(self._vals)


def check_stats(counters: Mapping, seconds: float, n_violations: int,
                fp_bits: Optional[int] = None,
                spec: Optional[str] = None,
                ir_fp: Optional[str] = None) -> Dict[str, object]:
    """The ``check`` stats payload (stdout line and ``--stats-json``),
    assembled from a counter mapping (``CheckResult.metrics.as_dict()``
    for the engines; a hand-built dict for the oracle, which has no
    registry).  ONE definition — cli, the run ledger's final record and
    the tests all call this, so the key set cannot drift per caller.

    Key order and presence match the pre-registry CLI output exactly
    (tests/test_obs.py pins both): the fingerprint/burst telemetry
    keys appear only when ``fp_bits`` is given (the oracle has no
    notion of them), ``pin_interior_states`` only when nonzero.
    """
    distinct = int(counters["distinct_states"])
    gen = int(counters["generated_states"])
    out = {
        "distinct_states": distinct,
        "generated_states": gen,
        "depth": int(counters["depth"]),
        "seconds": round(float(seconds), 3),
        "states_per_sec": round(distinct / max(seconds, 1e-9), 1),
        "dedup_hit_rate": round(1.0 - distinct / max(gen, 1), 4),
        "violations": int(n_violations),
    }
    if int(counters.get("pin_interior_states", 0) or 0):
        out["pin_interior_states"] = int(counters["pin_interior_states"])
    if fp_bits is not None:
        # dedup is fingerprint-based (TLC semantics): surface the
        # expected-collision bound the exhaustiveness claim rests on
        # (ADVICE r1; SURVEY §7.4 pt 4).  E[collisions] <= n^2/2^(b+1)
        out["fp_bits"] = int(fp_bits)
        out["expected_fp_collisions"] = float(
            distinct * distinct / 2.0 ** (fp_bits + 1))
        # fused-dispatch telemetry: proves the multi-level burst
        # engaged (levels_fused > 0) instead of silently bailing every
        # level (burst_bailouts ~ depth with levels_fused 0)
        for k in BURST_COUNTER_KEYS:
            out[k] = int(counters[k])
        # MXU-path mode flags (guard-matmul expansion / Pallas dedup
        # kernel) — .get: pre-round-9 counter dicts lack them
        for k in MXU_COUNTER_KEYS:
            out[k] = int(counters.get(k, 0) or 0)
    if spec is not None:
        # the active SpecIR name + structure fingerprint (spec/
        # package) — appended last so the pre-IR key prefix stays
        # byte-identical; present for the oracle engine too (the spec
        # is a frontend property, not an engine one)
        out["spec"] = spec
        if ir_fp is not None:
            out["ir_fingerprint"] = ir_fp
    return out


def sim_counters(res) -> Dict[str, object]:
    """A SimResult's canonical counter snapshot (SIM_COUNTER_KEYS
    order) — the simulate ledger records and sim_stats share it."""
    return {
        "walkers": int(res.walkers),
        "steps_dispatched": int(res.steps_dispatched),
        "walker_steps": int(res.walker_steps),
        "sampled_steps": int(res.sampled_steps),
        "restarts": int(res.restarts),
        "deadlocks": int(res.deadlocks),
        "promotions": int(res.promotions),
        "hits": len(res.hits),
        "est_distinct_states": round(float(res.est_distinct_states), 1),
        "bloom_saturated": bool(res.bloom_saturated),
        "bloom_canonical": bool(res.bloom_canonical),
    }


def sim_stats(res, target: str, policy: str, seed: int,
              platform: str) -> Dict[str, object]:
    """The ``simulate`` stats payload — same single-assembler contract
    as check_stats (key order matches the pre-registry CLI output)."""
    c = sim_counters(res)
    return {
        "target": target,
        "policy": policy,
        "walkers": c["walkers"],
        "steps_dispatched": c["steps_dispatched"],
        "walker_steps": c["walker_steps"],
        "sampled_steps": c["sampled_steps"],
        "walker_steps_per_sec": round(res.walker_steps_per_sec, 1),
        "restarts": c["restarts"],
        "deadlocks": c["deadlocks"],
        "promotions": c["promotions"],
        "seconds": round(float(res.seconds), 3),
        "est_distinct_states": c["est_distinct_states"],
        "bloom_saturated": c["bloom_saturated"],
        "bloom_canonical": c["bloom_canonical"],
        "hits": c["hits"],
        "platform": platform,
        "seed": seed,
    }
