"""Persistent run registry: one atomic JSON record per invocation.

The PR-7/PR-13 obs surface was write-only — every run's spans/ledger/
heartbeat landed in ad-hoc files with nothing persisting a cross-run
record, so two ledgers never turned into a verdict.  With
``--registry DIR``, every ``check``/``simulate``/``batch``/
``deep_run``/``bench`` invocation appends ONE schema-versioned record
under DIR:

- **naming** — ``<run_id>.json`` where ``run_id`` =
  ``r<YYYYmmdd-HHMMSS>-<pid>-<6 hex>`` (``new_run_id``): lexically ≈
  chronological, collision-free across interleaved processes, and the
  SAME id is stamped into every ledger row and heartbeat of the run,
  so a dropped tunnel no longer orphans telemetry — the record's
  ``artifacts`` paths cross-link them.
- **atomicity** — write-tmp-then-``os.replace``, the repo-wide publish
  pattern: a reader never sees a torn record, and a crash mid-write
  leaves no ``<run_id>.json`` at all (the ledger still has the run).
- **tolerance** — ``records()`` skips corrupt/foreign files with ONE
  stderr warning each instead of failing the whole listing: a registry
  shared by many runs must survive one bad writer.

``obs/report.py`` and the ``cli obs`` subcommands are the query half.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Dict, Iterator, List, Optional, Tuple

__all__ = ["SCHEMA", "RunRegistry", "new_run_id"]

# bump on any backwards-incompatible record change; readers keep
# accepting older schemas (the fields they read are append-only)
SCHEMA = 1


def new_run_id() -> str:
    """``r20260806-141530-3406-a1b2c3``: sortable timestamp prefix +
    pid + random suffix (collision-free when two runs start the same
    second in the same process tree)."""
    return (f"r{time.strftime('%Y%m%d-%H%M%S')}-{os.getpid()}-"
            f"{os.urandom(3).hex()}")


class RunRegistry:
    """Directory of one atomic ``<run_id>.json`` record per run."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def path_for(self, run_id: str) -> str:
        return os.path.join(self.root, run_id + ".json")

    def append(self, rec: Dict) -> str:
        """Publish one run record atomically; returns its path.
        ``rec`` must carry ``run_id``; ``schema`` is stamped here."""
        rec = dict(rec)
        run_id = rec.get("run_id")
        if not run_id:
            raise ValueError("registry record lacks run_id")
        rec.setdefault("schema", SCHEMA)
        path = self.path_for(run_id)
        tmp = path + ".tmp"
        with open(tmp, "w") as fh:
            json.dump(rec, fh, default=str)
        os.replace(tmp, path)
        return path

    def run_ids(self) -> List[str]:
        """All recorded run ids, sorted (≈ chronological)."""
        try:
            names = os.listdir(self.root)
        except OSError:
            return []
        return sorted(nm[:-5] for nm in names
                      if nm.endswith(".json") and nm.startswith("r"))

    def load(self, run_id: str) -> Dict:
        with open(self.path_for(run_id)) as fh:
            return json.load(fh)

    def records(self) -> Iterator[Tuple[str, Dict]]:
        """Yield ``(run_id, record)`` for every parseable record;
        corrupt files are skipped with one named stderr warning each
        (never fail the whole listing over one bad writer)."""
        for run_id in self.run_ids():
            try:
                rec = self.load(run_id)
            except (OSError, ValueError) as e:
                print(f"registry: skipping corrupt record "
                      f"{self.path_for(run_id)}: {e}", file=sys.stderr)
                continue
            if not isinstance(rec, dict):
                print(f"registry: skipping corrupt record "
                      f"{self.path_for(run_id)}: not a JSON object",
                      file=sys.stderr)
                continue
            yield run_id, rec

    def resolve(self, token: str) -> Optional[str]:
        """Run token -> run id: ``last`` (newest record), an exact id,
        or a unique id prefix; None when nothing (or more than one
        thing) matches."""
        ids = self.run_ids()
        if not ids:
            return None
        if token == "last":
            return ids[-1]
        if token in ids:
            return token
        hits = [rid for rid in ids if rid.startswith(token)]
        return hits[0] if len(hits) == 1 else None
