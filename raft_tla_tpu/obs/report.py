"""Report engine: turn run records into verdicts (``cli obs``).

The query half of the registry (ISSUE 17): pure functions over run
records — no engine imports, so ``cli obs`` verdicts run anywhere in
milliseconds.

- ``diff_runs(a, b)`` — machine-readable comparison of two runs: a
  count/level-size **parity verdict** (``clean`` / ``mode_drift`` /
  ``mismatch``), per-phase span deltas (seconds + ratio), mode-flag
  drift called out BY NAME (the ``MXU_COUNTER_KEYS`` flags: guard
  matmul, dedup kernel, delta matmul, sym canon), and resource-peak
  deltas.  Counts-equal-but-flags-differ is the repo's A/B shape —
  that is ``mode_drift``, not ``mismatch``.
- ``regress(run, baseline, ...)`` — a run against a committed
  BENCH_*.json baseline row, a ``--stats-json`` payload, or a prior
  registry run: nonzero on count mismatch, and (opt-in, because CI
  wall-clock is noisy) on a configurable per-phase span-time ratio.
- ``extract(rec)`` — shape normalizer: registry records, flat stats
  dicts, bench headline objects (``detail``) and BENCH A/B rows
  (``phase_seconds``/``phase_counts``) all reduce to the same
  ``{counters, level_sizes, spans, resources}`` view.
- ``format_span_totals`` — the one span-rollup formatter
  (``tools/profile.py`` prints through it instead of its private
  aggregation).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .metrics import MXU_COUNTER_KEYS

__all__ = ["extract", "diff_runs", "regress", "format_span_totals",
           "PARITY_KEYS"]

# the count keys whose equality defines run parity (violations rides
# along when both sides carry it)
PARITY_KEYS = ("distinct_states", "generated_states", "depth")


def format_span_totals(totals: Dict[str, Dict]) -> str:
    """``compile=6.10s/1  harvest=0.52s/12`` — the shared rendering of
    ``SpanRecorder.totals()``-shaped rollups."""
    return "  ".join(f"{nm}={t['seconds']:.2f}s/{t['count']}"
                     for nm, t in sorted(totals.items()))


def extract(rec: Dict) -> Dict:
    """Normalize any supported record shape to
    ``{counters, level_sizes, spans, resources, info}``.

    Accepted shapes: a registry record (``counters`` dict), a flat
    stats payload (``--stats-json``: counts at top level), a bench
    headline object (descend into ``detail``), and a BENCH A/B row
    (``phase_seconds``/``phase_counts`` become span totals)."""
    if not isinstance(rec, dict):
        raise ValueError("run record is not a JSON object")
    if "detail" in rec and isinstance(rec["detail"], dict) \
            and "counters" not in rec \
            and "distinct_states" not in rec:
        rec = rec["detail"]
    if isinstance(rec.get("counters"), dict):
        counters = dict(rec["counters"])
    else:
        counters = {k: rec[k] for k in rec
                    if isinstance(rec[k], (int, float))
                    and not isinstance(rec[k], bool)}
    # registry records also carry depth/distinct at top level (from
    # finish()); let those fill counter gaps, never override
    for k in PARITY_KEYS + ("violations",):
        if k not in counters and isinstance(rec.get(k), (int, float)):
            counters[k] = rec[k]
    if "distinct" in rec and "distinct_states" not in counters:
        counters["distinct_states"] = rec["distinct"]   # deep_run rows
    spans = dict(rec.get("spans") or {})
    if not spans and isinstance(rec.get("phase_seconds"), dict):
        pc = rec.get("phase_counts") or {}
        spans = {nm: {"count": int(pc.get(nm, 0)),
                      "seconds": float(s)}
                 for nm, s in rec["phase_seconds"].items()}
    ls = rec.get("level_sizes")
    return {
        "counters": counters,
        "level_sizes": list(ls) if ls is not None else None,
        "spans": spans,
        "resources": dict(rec.get("resources") or {}),
        "info": {k: rec.get(k) for k in
                 ("run_id", "cmd", "spec", "status", "cfg")
                 if rec.get(k) is not None},
    }


def _count_parity(a: Dict, b: Dict) -> Tuple[Dict, bool]:
    counts = {}
    equal = True
    keys = [k for k in PARITY_KEYS + ("violations",)
            if k in a["counters"] or k in b["counters"]]
    for k in keys:
        va, vb = a["counters"].get(k), b["counters"].get(k)
        ok = va == vb and va is not None
        counts[k] = {"a": va, "b": vb, "equal": ok}
        # a key only one side carries (oracle vs engine payloads) is
        # reported but does not break parity
        if va is not None and vb is not None and not ok:
            equal = False
    ls_eq = None
    if a["level_sizes"] is not None and b["level_sizes"] is not None:
        ls_eq = list(a["level_sizes"]) == list(b["level_sizes"])
        if not ls_eq:
            equal = False
    return {"counts": counts, "level_sizes_equal": ls_eq}, equal


def _mode_drift(a: Dict, b: Dict) -> List[str]:
    """The program-shaping mode flags that differ, BY NAME."""
    return [k for k in MXU_COUNTER_KEYS
            if a["counters"].get(k) != b["counters"].get(k)
            and (k in a["counters"] or k in b["counters"])]


def _span_deltas(a: Dict, b: Dict) -> Dict:
    out = {}
    for nm in sorted(set(a["spans"]) | set(b["spans"])):
        sa = float(a["spans"].get(nm, {}).get("seconds", 0.0))
        sb = float(b["spans"].get(nm, {}).get("seconds", 0.0))
        out[nm] = {"a_seconds": round(sa, 6), "b_seconds": round(sb, 6),
                   "delta_seconds": round(sb - sa, 6),
                   "ratio": round(sb / sa, 3) if sa > 0 else None}
    return out


def _resource_deltas(a: Dict, b: Dict) -> Dict:
    out = {}
    for k in ("rss_peak_bytes", "device_peak_bytes_in_use",
              "compile_seconds"):
        va, vb = a["resources"].get(k), b["resources"].get(k)
        if va is not None or vb is not None:
            out[k] = {"a": va, "b": vb}
    return out


def diff_runs(a_rec: Dict, b_rec: Dict) -> Dict:
    """Machine-readable diff of two run records (any ``extract``-able
    shape).  ``verdict``: ``clean`` (counts + level sizes identical,
    same mode flags), ``mode_drift`` (counts identical under DIFFERENT
    named flags — the A/B shape), ``mismatch`` (counts differ)."""
    a, b = extract(a_rec), extract(b_rec)
    parity, equal = _count_parity(a, b)
    drift = _mode_drift(a, b)
    verdict = "mismatch" if not equal else \
        ("mode_drift" if drift else "clean")
    return {
        "verdict": verdict,
        "run_a": a["info"], "run_b": b["info"],
        "parity": parity,
        "mode_drift": drift,
        "spans": _span_deltas(a, b),
        "resources": _resource_deltas(a, b),
    }


def regress(run_rec: Dict, baseline_rec: Dict,
            max_span_ratio: Optional[float] = None,
            min_seconds: float = 0.05) -> Tuple[Dict, int]:
    """Regression verdict of ``run`` against ``baseline``; returns
    ``(report, exit_code)`` with code 0 ok / 1 regression.

    Count mismatch (PARITY_KEYS both sides carry, or level sizes) is
    always a regression.  Span-time ratios are opt-in
    (``max_span_ratio``): a shared phase whose baseline took at least
    ``min_seconds`` and whose run/baseline ratio exceeds the bound
    trips — short phases are excluded because their wall-clock is
    noise on shared CI hosts."""
    run, base = extract(run_rec), extract(baseline_rec)
    parity, equal = _count_parity(run, base)
    failures = []
    if not equal:
        bad = [k for k, v in parity["counts"].items()
               if v["a"] is not None and v["b"] is not None
               and not v["equal"]]
        if bad:
            failures.append("count mismatch vs baseline: "
                            + ", ".join(bad))
        if parity["level_sizes_equal"] is False:
            failures.append("level_sizes mismatch vs baseline")
    spans = _span_deltas(base, run)   # a=baseline, b=run
    if max_span_ratio is not None:
        for nm, d in spans.items():
            if d["a_seconds"] >= min_seconds and \
                    d["ratio"] is not None and \
                    d["ratio"] > max_span_ratio:
                failures.append(
                    f"span {nm!r} regressed {d['ratio']:.2f}x "
                    f"({d['a_seconds']:.2f}s -> "
                    f"{d['b_seconds']:.2f}s > "
                    f"{max_span_ratio:.2f}x bound)")
    report = {
        "verdict": "ok" if not failures else "regression",
        "run": run["info"], "baseline": base["info"],
        "parity": parity,
        "mode_drift": _mode_drift(base, run),
        "failures": failures,
        "spans": spans,
    }
    return report, (0 if not failures else 1)
