"""Resource telemetry: backend identity + a low-overhead sampler.

Two things live here (ISSUE 17):

- ``backend_fingerprint()`` — the platform/device-kind/device-count/
  jax-version identity of this process' backend.  It started life in
  ``serve/exec_cache.py`` as part of the executable cache key; the obs
  layer stamps the SAME dict on every ledger meta row and registry
  record, so it is hoisted here as the single shared helper
  (exec_cache re-exports it for its cache keys).

- ``ResourceSampler`` — sampled at level/burst dispatch boundaries by
  ``Obs.dispatch`` (so every engine driver is covered without per-
  driver hooks): host RSS (+ running peak), jax device memory stats
  (HBM in-use/peak where the backend reports them — XLA:CPU reports
  nothing), and per-executable compile wall-clock read from the span
  recorder's ``compile``/``bucket_compile`` totals.  Samples surface
  three ways: as the ``resources`` field on every heartbeat, as
  throttled ``kind="resource"`` ledger rows (first dispatch
  immediately, then at most one per ``interval_s``), and as the
  ``resources`` rollup of the run's registry record.  This directly
  serves the ROADMAP carry-over items "archive run at depth 21+ with
  bounded RSS" and "30-50 s TPU compile": both are now measured fields
  of every run instead of scrollback folklore.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

from .ledger import device_memory_stats, rss_bytes

__all__ = ["backend_fingerprint", "ResourceSampler"]


def backend_fingerprint() -> Dict[str, str]:
    """The identity of this process' backend: platform, device kind,
    device count, jax version.  ONE definition — the executable cache
    keys on it (an executable serialized on one backend never loads on
    another) and the obs layer stamps it on every ledger meta row and
    registry record (a run record without its backend is not
    comparable)."""
    import jax
    devs = jax.devices()
    return {
        "platform": jax.default_backend(),
        "device_kind": str(devs[0].device_kind) if devs else "none",
        "n_devices": str(len(devs)),
        "jax": jax.__version__,
    }


# span names whose totals count as executable-compile wall-clock (the
# classic engines warm under "compile"; the serving layer AOT-compiles
# under "bucket_compile")
_COMPILE_SPANS = ("compile", "bucket_compile")


class ResourceSampler:
    """Peak-tracking sampler, driven by ``Obs.dispatch``.

    spans      — optional SpanRecorder; its compile-span totals become
                 the ``compile_seconds``/``compile_count`` fields.
    interval_s — minimum spacing of ``kind="resource"`` ledger rows
                 (``due()``); heartbeats carry every sample regardless.
    """

    def __init__(self, spans=None, interval_s: float = 30.0):
        self.spans = spans
        self.interval_s = float(interval_s)
        self._last_emit: Optional[float] = None
        self._n_samples = 0
        self._rss_peak = 0
        self._dev_peak_in_use = 0
        self._dev_peak = 0          # backend-reported peak_bytes_in_use

    def sample(self) -> Dict:
        """One sample: current RSS + running peak, device memory where
        reported, compile totals so far.  Cheap enough for every
        dispatch (one /proc read + one memory_stats call)."""
        self._n_samples += 1
        rss = rss_bytes()
        self._rss_peak = max(self._rss_peak, rss)
        snap = {"rss_bytes": rss, "rss_peak_bytes": self._rss_peak}
        dev = device_memory_stats()
        if dev:
            self._dev_peak_in_use = max(self._dev_peak_in_use,
                                        int(dev.get("bytes_in_use", 0)))
            self._dev_peak = max(self._dev_peak,
                                 int(dev.get("peak_bytes_in_use", 0)))
            snap["device_memory"] = dev
        snap.update(self._compile_totals())
        return snap

    def _compile_totals(self) -> Dict:
        secs, count = 0.0, 0
        if self.spans is not None:
            tot = self.spans.totals()
            for nm in _COMPILE_SPANS:
                if nm in tot:
                    secs += float(tot[nm]["seconds"])
                    count += int(tot[nm]["count"])
        return {"compile_seconds": round(secs, 3),
                "compile_count": count}

    def due(self) -> bool:
        """Throttle for ledger rows: True on the first call and then
        at most once per ``interval_s`` (a tiny CI run gets exactly
        one resource row; a days-scale run gets a bounded stream)."""
        now = time.perf_counter()
        if self._last_emit is not None and \
                now - self._last_emit < self.interval_s:
            return False
        self._last_emit = now
        return True

    def rollup(self) -> Dict:
        """The registry record's resources summary: sample count,
        peaks, compile totals."""
        out = {"samples": self._n_samples,
               "rss_peak_bytes": self._rss_peak}
        out.update(self._compile_totals())
        if self._dev_peak_in_use or self._dev_peak:
            out["device_peak_bytes_in_use"] = max(
                self._dev_peak, self._dev_peak_in_use)
        return out
