"""Span/timeline recorder: nested named phases on monotonic clocks.

Every engine driver brackets its phases — ``compile``,
``burst_dispatch``, ``level_dispatch``, ``host_sweep``, ``harvest``,
``archive_io``, ``checkpoint`` — with ``SpanRecorder.span(name)``.
Round 9 adds the MXU-path micro-phase names ``guard_matmul`` /
``guard_lanes`` and ``dedup_kernel`` / ``dedup_probe``; round 11 adds
``delta_apply`` / ``delta_kernels`` (the group scatter-as-matmul vs
the per-family successor kernels): inside a fused engine step these
exist as ``jax.named_scope`` annotations (visible in an XLA
``--profile-dir`` trace), and bench.py times them as standalone host
spans in the BENCH_r09/r11 A/Bs so the win attributes per phase.
Clocks are ``time.perf_counter()`` (monotonic: NTP steps on long
tunneled runs corrupted the old ``time.time()`` deltas), and completed
spans are emitted as Chrome-trace "complete" events (``ph": "X"`` with
``ts``/``dur`` in microseconds), so a ``--trace-timeline`` file loads
directly in Perfetto / chrome://tracing next to an XLA device trace
captured with matching ``jax.profiler.TraceAnnotation`` names
(``--profile-dir``).

The on-disk format is the catapult JSON *array* form, streamed: the
file is valid the moment each span closes (the trailing ``]`` is
optional per the trace-event spec and appended on a clean close), so a
killed run still leaves a loadable timeline up to its last dispatch.
"""

from __future__ import annotations

import contextlib
import json
import os
import time
from typing import Dict, List, Optional, Tuple


class SpanRecorder:
    """Nested span timer + Chrome-trace-event emitter.

    path     — optional trace file, streamed incrementally (see module
               docstring); ``close()`` finishes the JSON array.
    annotate — mirror every span as a ``jax.profiler.TraceAnnotation``
               so XLA device traces (``--profile-dir``) line up with
               the host timeline by name.
    """

    def __init__(self, path: Optional[str] = None,
                 annotate: bool = False):
        self.path = path
        self.annotate = annotate
        self._t0 = time.perf_counter()
        self._pid = os.getpid()
        self._stack: List[Tuple[str, float]] = []
        self._totals: Dict[str, List[float]] = {}   # name -> [n, secs]
        self.events: List[dict] = []
        self._fh = None
        self._n_written = 0
        if path:
            self._fh = open(path, "w")
            self._fh.write("[")
            self._fh.flush()

    # -- recording -----------------------------------------------------

    @contextlib.contextmanager
    def span(self, name: str):
        ann = None
        if self.annotate:
            try:
                import jax
                ann = jax.profiler.TraceAnnotation(name)
                ann.__enter__()
            except Exception:
                ann = None
        t0 = time.perf_counter()
        self._stack.append((name, t0))
        try:
            yield self
        finally:
            t1 = time.perf_counter()
            self._stack.pop()
            if ann is not None:
                ann.__exit__(None, None, None)
            self._emit(name, t0, t1)

    def _emit(self, name: str, t0: float, t1: float):
        tot = self._totals.setdefault(name, [0, 0.0])
        tot[0] += 1
        tot[1] += t1 - t0
        ev = {
            "name": name, "cat": "obs", "ph": "X",
            "ts": round((t0 - self._t0) * 1e6, 3),
            "dur": round((t1 - t0) * 1e6, 3),
            "pid": self._pid, "tid": 0,
        }
        if self._fh is None:
            # in-memory mode only: when streaming, the file IS the
            # record — retaining a second copy would grow RAM without
            # bound on days-scale runs (totals() reads _totals)
            self.events.append(ev)
        else:
            # never a trailing comma: a killed run's file stays
            # parseable (only the closing ] is missing, which the
            # trace-event spec makes optional)
            prefix = "\n" if self._n_written == 0 else ",\n"
            self._fh.write(prefix + json.dumps(ev))
            self._fh.flush()
            self._n_written += 1

    # -- reading back --------------------------------------------------

    def totals(self) -> Dict[str, Dict[str, float]]:
        """Per-span-name inclusive totals:
        ``{name: {count, seconds}}`` — bench.py records these per phase
        so A/B deltas attribute to dispatch vs compute vs harvest
        instead of one end-to-end number."""
        return {nm: {"count": n, "seconds": round(s, 6)}
                for nm, (n, s) in sorted(self._totals.items())}

    # -- lifecycle -----------------------------------------------------

    def close(self):
        if self._fh is not None:
            self._fh.write("\n]\n")
            self._fh.close()
            self._fh = None
