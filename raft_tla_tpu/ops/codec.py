"""Host-side codec: oracle (State, Hist) ↔ device struct-of-arrays.

The device state is a dict of numpy/jnp arrays (leading batch axis added by
the engine).  Fields:

  VIEW region (state identity, raft.cfg:30 ``VIEW vars``; SURVEY §2.2):
    ct, st, vf, ci, llen : i32[S]       per-server scalars
    log                  : i32[S, Lcap] packed entries (0 = empty slot)
    vr, vg               : i32[S]       vote-set bitmasks
    ni, mi               : i32[S, S]    nextIndex / matchIndex
    bag                  : u32[K, MW]   packed messages (all-zero = empty)
    cnt                  : i32[K]       bag copy counts (0 = empty slot)

  non-VIEW region (history counters + scenario features — inputs to
  constraints and scenario predicates, excluded from identity; SURVEY §2.2
  and §5 "Tracing"):
    restarted, timeout   : i32[S]
    ctr                  : i32[NCTR]    [nleaders, nreq, ntried, nmc,
                                         globlen, overflow, 0, 0]
    feat                 : i32[NFEAT]   derived scenario features (below)

`overflow` is the fault lane for un-representable growth (log beyond Lcap,
bag beyond K): the reference *constrains* those away, so with the stock
constraint set it stays 0; if a user disables the bounds we fault instead
of silently wrapping (SURVEY §7.4 hard part 3).

Scenario feature lanes (computed incrementally by kernels; recomputed from
the oracle history here for encoding mid-trace states):
    F_COMMIT_SEEN      any CommitEntry record            (raft.tla:1160-1163)
    F_BL2_SEEN         any BecomeLeader with ≥2 leaders  (raft.tla:1165-1176)
    F_CWCL_POS         1-based glob position of the first CommitEntry after
                       a BL2 record; 0 = none            (raft.tla:1165-1176)
    F_LAST_RESTART_POS 1-based position of last Restart  (raft.tla:1212-1226)
    F_MIN_RESTART_GAP  min gap between consecutive Restart records
    F_ADDED_SET        mask of servers in AddServer records (raft.tla:1248+)
    F_OPEN_ADD         AddServer seen, no CommitMembershipChange since
    F_NJBL             BecomeLeader by a previously-added server
    F_LCDCC            BecomeLeader while F_OPEN_ADD      (raft.tla:1268-1278)
    F_ADD_COMMITS      CommitMembershipChange ∩ addedSet  (raft.tla:1248-1256)
    F_PREFIX_MASK      RESERVED, always -1.  The punctuated-search prefix
                       pins (raft.tla:1198-1204) compile into BFS seed
                       states instead (cfg prefix_pins ->
                       models/golden.prefix_pin_seeds), so no per-state
                       prefix tracking is needed; the lane is kept so a
                       future in-flight IsPrefix mask has a home without
                       a layout change
    F_MC_COMMITS       count of CommitMembershipChange records — feeds
                       MembershipChangeCommits / MultipleMembership-
                       ChangesCommit (raft.tla:1239-1246)
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

from ..config import (MT_AEREQ, MT_AERESP, MT_CATREQ, MT_CATRESP, MT_COC,
                      MT_RVREQ, MT_RVRESP, popcount)
from ..models.raft import Hist, State
from .layout import (Layout, MSG_FIELDS, get_field, pack_entry,
                     put_field_checked, unpack_entry)

# the shared cross-spec ctr-lane contract now lives in the spec
# package (every SpecIR's encoded state carries the same ctr vector);
# aliased here for the historical import path
from ..spec import (C_GLOBLEN, C_NLEADERS, C_NMC, C_NREQ,   # noqa: F401
                    C_NTRIED, C_OVERFLOW, NCTR)

NFEAT = 12
(F_COMMIT_SEEN, F_BL2_SEEN, F_CWCL_POS, F_LAST_RESTART_POS,
 F_MIN_RESTART_GAP, F_ADDED_SET, F_OPEN_ADD, F_NJBL, F_LCDCC,
 F_ADD_COMMITS, F_PREFIX_MASK, F_MC_COMMITS) = range(NFEAT)

NO_GAP = 1 << 20  # "no restart pair yet" sentinel for F_MIN_RESTART_GAP

VIEW_KEYS = ("ct", "st", "vf", "ci", "llen", "log", "vr", "vg", "ni", "mi",
             "bag", "cnt")
NONVIEW_KEYS = ("restarted", "timeout", "ctr", "feat")
ALL_KEYS = VIEW_KEYS + NONVIEW_KEYS


# ---------------------------------------------------------------------------
# Storage dtypes.  Kernels/oracle/tests speak int32 SoA (encode's output);
# the ENGINES store frontier/level/archive buffers narrowed to the
# smallest dtype the configured bounds fit (VERDICT r2: the int32 rows
# cost ~620 B/state; terms <= 5, masks <= 2^S, indices <= Lcap all fit
# int8/int16, a 2-3x HBM capacity + bandwidth win), widening per chunk
# before the kernels run.  `bag` stays u32 (packed words); `ctr`/`feat`
# stay int32 (C_GLOBLEN grows with trace length; NO_GAP sentinel).
# ---------------------------------------------------------------------------

def _int_dtype_for(maxval: int) -> np.dtype:
    if maxval <= 127:
        return np.dtype(np.int8)
    if maxval <= 32767:
        return np.dtype(np.int16)
    return np.dtype(np.int32)


def narrow_dtypes(lay: Layout) -> Dict[str, np.dtype]:
    b = lay.cfg.bounds
    i32 = np.dtype(np.int32)
    mx = {
        "ct": b.max_terms + 1, "st": 2, "vf": lay.S, "ci": lay.Lcap,
        "llen": lay.Lcap, "log": (1 << lay.entry_bits) - 1,
        "vr": (1 << lay.S) - 1, "vg": (1 << lay.S) - 1,
        "ni": lay.Lcap + 1, "mi": lay.Lcap,
        # counters can outrun their Bounded* budgets when a cfg disables
        # the constraint, so give them int16 headroom regardless
        "restarted": 32000, "timeout": 32000, "cnt": 32000,
    }
    out = {k: _int_dtype_for(v) for k, v in mx.items()}
    out["bag"] = np.dtype(np.uint32)
    out["ctr"] = i32
    out["feat"] = i32
    return out


def narrow(lay: Layout, arrs):
    """int32 SoA rows -> storage dtypes (numpy or jnp, shape-agnostic)."""
    dts = narrow_dtypes(lay)
    return {k: v.astype(dts[k]) for k, v in arrs.items()}


def widen(arrs):
    """Storage rows -> the kernels' int32/uint32 SoA contract (key-based
    so it also normalizes e.g. int64 arrays from JSON-loaded seeds)."""
    return {k: v.astype(np.uint32) if k == "bag" else v.astype(np.int32)
            for k, v in arrs.items()}


# ---------------------------------------------------------------------------
# Message packing
# ---------------------------------------------------------------------------

def pack_msg(lay: Layout, m: tuple) -> np.ndarray:
    """Oracle message tuple -> u32[msg_words].  Generic fields a/b/c are
    stored +1 so an absent field (-1; the follow-up CatchupRequest's missing
    mcommitIndex, raft.tla:762-771) packs as 0 and field-set identity is
    preserved."""
    hs = lay.header_shifts
    f = MSG_FIELDS[m[0]]
    ent = m[f["ent"]] if f["ent"] is not None else ()

    def gf(key):
        idx = f[key]
        return (m[idx] if idx is not None else -1) + 1

    w0 = (put_field_checked(m[0], hs["mtype"], "mtype") |
          put_field_checked(m[1], hs["mterm"], "mterm") |
          put_field_checked(m[f["src"]], hs["msrc"], "msrc") |
          put_field_checked(m[f["dst"]], hs["mdst"], "mdst") |
          put_field_checked(gf("a"), hs["a"], "a") |
          put_field_checked(gf("b"), hs["b"], "b") |
          put_field_checked(gf("c"), hs["c"], "c") |
          put_field_checked(len(ent), hs["entlen"], "entlen"))
    words = np.zeros(lay.msg_words, dtype=np.uint32)
    words[0] = w0 & 0xFFFFFFFF
    epw = lay.entries_per_word
    for k, e in enumerate(ent):
        packed = pack_entry(lay, e[0], e[1], e[2])
        words[1 + k // epw] |= np.uint32(packed << (lay.entry_bits *
                                                    (k % epw)))
    return words


def unpack_msg(lay: Layout, words) -> tuple:
    """u32[msg_words] -> oracle message tuple (exact field order/set)."""
    hs = lay.header_shifts
    w0 = int(words[0])
    mtype = get_field(w0, hs["mtype"])
    term = get_field(w0, hs["mterm"])
    src = get_field(w0, hs["msrc"])
    dst = get_field(w0, hs["mdst"])
    a = get_field(w0, hs["a"]) - 1
    b = get_field(w0, hs["b"]) - 1
    c = get_field(w0, hs["c"]) - 1
    nent = get_field(w0, hs["entlen"])
    epw = lay.entries_per_word
    mask = (1 << lay.entry_bits) - 1
    ent = tuple(
        unpack_entry(lay, (int(words[1 + k // epw]) >>
                           (lay.entry_bits * (k % epw))) & mask)
        for k in range(nent))
    if mtype == MT_RVREQ:
        return (mtype, term, a, b, src, dst)
    if mtype == MT_RVRESP:
        return (mtype, term, a, ent, src, dst)
    if mtype == MT_AEREQ:
        return (mtype, term, a, b, ent, c, src, dst)
    if mtype == MT_AERESP:
        return (mtype, term, a, b, src, dst)
    if mtype == MT_CATREQ:
        return (mtype, term, a, ent, b, src, dst, c)
    if mtype == MT_CATRESP:
        return (mtype, term, a, b, src, dst, c)
    if mtype == MT_COC:
        return (mtype, term, a, b, src, dst)
    raise ValueError(f"bad message type {mtype}")


# ---------------------------------------------------------------------------
# Scenario features from an oracle history (mirrors what kernels maintain)
# ---------------------------------------------------------------------------

def features_from_hist(h: Hist) -> np.ndarray:
    feat = np.zeros(NFEAT, dtype=np.int32)
    feat[F_PREFIX_MASK] = -1
    bl2_seen = False
    open_add = False
    added = 0
    last_restart = 0
    min_gap = NO_GAP
    for k, r in enumerate(h.glob):
        pos = k + 1  # 1-based, matching the spec's Len-based indexing
        kind = r[0]
        if kind == "CommitEntry":
            feat[F_COMMIT_SEEN] = 1
            if bl2_seen and feat[F_CWCL_POS] == 0:
                feat[F_CWCL_POS] = pos
        elif kind == "BecomeLeader":
            if popcount(r[2]) >= 2:
                bl2_seen = True
            if (added >> r[1]) & 1:
                feat[F_NJBL] = 1
            if open_add:
                feat[F_LCDCC] = 1
        elif kind == "Restart":
            if last_restart:
                min_gap = min(min_gap, pos - last_restart)
            last_restart = pos
        elif kind == "AddServer":
            added |= 1 << r[2]
            open_add = True
        elif kind == "CommitMembershipChange":
            if r[2] & added:
                feat[F_ADD_COMMITS] = 1
            open_add = False
            feat[F_MC_COMMITS] += 1
    feat[F_BL2_SEEN] = int(bl2_seen)
    feat[F_LAST_RESTART_POS] = last_restart
    feat[F_MIN_RESTART_GAP] = min_gap
    feat[F_ADDED_SET] = added
    feat[F_OPEN_ADD] = int(open_add)
    return feat


# ---------------------------------------------------------------------------
# State encode / decode
# ---------------------------------------------------------------------------

def encode(lay: Layout, sv: State, h: Hist) -> Dict[str, np.ndarray]:
    S, Lcap, K, MW = lay.S, lay.Lcap, lay.K, lay.msg_words
    out = {
        "ct": np.array(sv.ct, dtype=np.int32),
        "st": np.array(sv.st, dtype=np.int32),
        "vf": np.array(sv.vf, dtype=np.int32),
        "ci": np.array(sv.ci, dtype=np.int32),
        "llen": np.array([len(l) for l in sv.log], dtype=np.int32),
        "vr": np.array(sv.vr, dtype=np.int32),
        "vg": np.array(sv.vg, dtype=np.int32),
        "ni": np.array(sv.ni, dtype=np.int32),
        "mi": np.array(sv.mi, dtype=np.int32),
    }
    log = np.zeros((S, Lcap), dtype=np.int32)
    for i, slog in enumerate(sv.log):
        assert len(slog) <= Lcap, "log overflow: un-representable state"
        for k, e in enumerate(slog):
            log[i, k] = pack_entry(lay, e[0], e[1], e[2])
    out["log"] = log
    bag = np.zeros((K, MW), dtype=np.uint32)
    cnt = np.zeros(K, dtype=np.int32)
    assert len(sv.msgs) <= K, "bag overflow: un-representable state"
    for slot, (m, c) in enumerate(sv.msgs):
        bag[slot] = pack_msg(lay, m)
        cnt[slot] = c
    out["bag"] = bag
    out["cnt"] = cnt
    out["restarted"] = np.array(h.restarted, dtype=np.int32)
    out["timeout"] = np.array(h.timeout, dtype=np.int32)
    ctr = np.zeros(NCTR, dtype=np.int32)
    ctr[C_NLEADERS], ctr[C_NREQ] = h.nleaders, h.nreq
    ctr[C_NTRIED], ctr[C_NMC] = h.ntried, h.nmc
    ctr[C_GLOBLEN] = len(h.glob)
    out["ctr"] = ctr
    out["feat"] = features_from_hist(h)
    return out


def decode(lay: Layout, arrs: Dict[str, np.ndarray]) -> Tuple[State, Hist]:
    """Device arrays -> (State, Hist).  The global history *sequence* is not
    reconstructible from counters (it lives host-side, SURVEY §5); the
    returned Hist carries the counters and an empty glob."""
    a = {k: np.asarray(v) for k, v in arrs.items()}
    S = lay.S
    log = []
    for i in range(S):
        n = int(a["llen"][i])
        log.append(tuple(unpack_entry(lay, int(a["log"][i, k]))
                         for k in range(n)))
    msgs = {}
    for slot in range(lay.K):
        c = int(a["cnt"][slot])
        if c > 0:
            m = unpack_msg(lay, a["bag"][slot])
            msgs[m] = msgs.get(m, 0) + c   # split slots merge here
    sv = State(
        ct=tuple(int(x) for x in a["ct"]),
        st=tuple(int(x) for x in a["st"]),
        vf=tuple(int(x) for x in a["vf"]),
        log=tuple(log),
        ci=tuple(int(x) for x in a["ci"]),
        vr=tuple(int(x) for x in a["vr"]),
        vg=tuple(int(x) for x in a["vg"]),
        ni=tuple(tuple(int(x) for x in row) for row in a["ni"]),
        mi=tuple(tuple(int(x) for x in row) for row in a["mi"]),
        msgs=tuple(sorted(msgs.items())),
    )
    ctr = a["ctr"]
    h = Hist(
        restarted=tuple(int(x) for x in a["restarted"]),
        timeout=tuple(int(x) for x in a["timeout"]),
        nleaders=int(ctr[C_NLEADERS]), nreq=int(ctr[C_NREQ]),
        ntried=int(ctr[C_NTRIED]), nmc=int(ctr[C_NMC]),
        glob=(),
    )
    return sv, h


def stack(states):
    """List of single-state dicts -> batched dict (leading axis)."""
    return {k: np.stack([s[k] for s in states]) for k in states[0]}


def unstack(batch, idx):
    return {k: np.asarray(v)[idx] for k, v in batch.items()}
