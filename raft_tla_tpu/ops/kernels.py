"""Vectorizable action kernels: the Next-relation as pure jnp functions.

Each kernel maps a *single* state (the SoA dict of ops/codec.py) plus
static-shaped parameters to ``(ok, state')`` — ``ok`` is the action's
enabling guard; when False the returned state is garbage and the engine
masks it out.  The engine vmaps kernels over the frontier axis and over
parameter grids (SURVEY §7.2 L1/L2).

Semantics contract: models/raft.py (the oracle), which cites the reference
spec line-by-line; every kernel here names its oracle twin.  Differential
tests (tests/test_kernels.py) assert successor-set equality on reachable
states.

Control-flow style: no data-dependent Python branching — guards become
masks, the AppendEntries branch family (raft.tla:617-683) becomes nested
``jnp.where`` selects (the branches are mutually exclusive, SURVEY §2.5),
and variable-length log/bag ops become masked gathers/scatters over static
Lcap/K extents.  History counters and scenario feature lanes are updated
in-kernel (they are inputs to constraints, SURVEY §2.2); the global history
*sequence* lives host-side and only its length rides along.
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from ..config import (CANDIDATE, CONFIG_ENTRY, FOLLOWER, LEADER, MT_AEREQ,
                      MT_AERESP, MT_CATREQ, MT_CATRESP, MT_COC, MT_RVREQ,
                      MT_RVRESP, NIL, VALUE_ENTRY)
from .codec import (C_GLOBLEN, C_NLEADERS, C_NMC, C_NREQ, C_NTRIED,
                    C_OVERFLOW, F_ADD_COMMITS, F_ADDED_SET, F_BL2_SEEN,
                    F_COMMIT_SEEN, F_CWCL_POS, F_LAST_RESTART_POS, F_LCDCC,
                    F_MC_COMMITS, F_MIN_RESTART_GAP, F_NJBL, F_OPEN_ADD,
                    NO_GAP)
from . import layout as layout_mod
from .layout import Layout, get_field, put_field

State = Dict[str, jnp.ndarray]


def popcount(x, nbits):
    """Popcount over the low ``nbits`` of small server bitmasks."""
    x = jnp.asarray(x)
    total = jnp.zeros_like(x)
    for k in range(nbits):
        total = total + ((x >> k) & 1)
    return total


def select_enabled(ok, u):
    """The ``u``-th enabled lane of a flat guard mask (0-based), or -1
    when no lane is enabled.

    This is the random-walk engine's sampling kernel (sim/walker.py):
    ``ok`` is one state's [A] enabling-guard vector over the expander's
    lane grid and ``u`` a uniform draw in [0, sum(ok)), so picking the
    u-th set bit IS the uniform choice over enabled (action, server,
    param) lanes — the same successor surface TLC's ``-simulate`` mode
    samples uniformly.  One cumsum + argmax, no data-dependent shapes,
    so it vmaps over walker fleets."""
    csum = jnp.cumsum(ok.astype(jnp.int32))
    idx = jnp.argmax(csum > u).astype(jnp.int32)
    return jnp.where(csum[-1] > 0, idx, jnp.int32(-1))


class RaftKernels:
    """Kernel family bound to one (Layout, ModelConfig)."""

    def __init__(self, lay: Layout):
        self.lay = lay
        self.cfg = lay.cfg
        self.S = lay.S
        self.Lmax = lay.Lmax
        self.Lcap = lay.Lcap
        self.K = lay.K

    @property
    def term_cap(self) -> int:
        """The term REPRESENTABILITY clamp: the packing holds
        max_terms + 1 (the one unconstrained step past BoundedTerms).
        This is a property of the LAYOUT's bounds, deliberately NOT of
        any per-job runtime bound: under a padded serving ceiling
        (spec serve_bucket, round 13) the job's BoundedTerms rides the
        runtime-bounds vector while this clamp stays at the ceiling's
        width — exact, because constraint-pruned states are never
        expanded, so an in-bounds job can never reach the clamp in
        either layout."""
        return self.cfg.bounds.max_terms + 1

    # ------------------------------------------------------------------
    # Derived per-state quantities (recomputed once per expansion)
    # ------------------------------------------------------------------

    def derived(self, sv: State) -> State:
        lay = self.lay
        log = sv["log"]                                   # [S, Lcap]
        etype = (log >> lay.value_bits) & 1
        occupied = log != 0
        is_cfg = (etype == CONFIG_ENTRY) & occupied
        pos = jnp.arange(1, self.Lcap + 1, dtype=jnp.int32)
        # GetMaxConfigIndex (raft.tla:346-351), 1-based, 0 if none
        maxcfg = jnp.max(jnp.where(is_cfg, pos, 0), axis=1)
        payload = log & ((1 << lay.value_bits) - 1)
        cfg_payload = jnp.take_along_axis(
            payload, jnp.maximum(maxcfg - 1, 0)[:, None], axis=1)[:, 0]
        # GetConfig (raft.tla:354-360): latest ConfigEntry else InitServer
        config = jnp.where(maxcfg > 0, cfg_payload,
                           jnp.int32(self.cfg.init_mask))
        lastterm = jnp.where(
            sv["llen"] > 0,
            self.entry_term(jnp.take_along_axis(
                log, jnp.maximum(sv["llen"] - 1, 0)[:, None], axis=1)[:, 0]),
            0)
        leaders = jnp.sum(
            jnp.where(sv["st"] == LEADER,
                      jnp.int32(1) << jnp.arange(self.S), 0))
        return {"config": config, "maxcfg": maxcfg, "lastterm": lastterm,
                "leaders": leaders}

    def guard_feature_offsets(self) -> Dict[str, int]:
        """The SpecIR kernels contract: the flat layout of this spec's
        ``guard_features`` vector (module-level table below)."""
        return guard_feature_offsets(self.lay)

    # ------------------------------------------------------------------
    # Entry / message packing helpers (device side)
    # ------------------------------------------------------------------

    # single source of truth for the entry bit layout: ops/layout.py
    def entry_term(self, e):
        return layout_mod.entry_term(self.lay, e)

    def entry_type(self, e):
        return layout_mod.entry_type(self.lay, e)

    def entry_payload(self, e):
        return layout_mod.entry_payload(self.lay, e)

    def pack_entry(self, term, etype, payload):
        return layout_mod.pack_entry(self.lay, term, etype, payload)

    def pack_msg(self, mtype, mterm, msrc, mdst, a=-1, b=-1, c=-1,
                 ent=None, entlen=0):
        """Build u32[msg_words].  a/b/c use the +1 absent-field offset
        (codec.pack_msg is the host twin)."""
        lay = self.lay
        hs = lay.header_shifts
        w0 = (put_field(jnp.int32(mtype), hs["mtype"]) |
              put_field(mterm, hs["mterm"]) |
              put_field(msrc, hs["msrc"]) | put_field(mdst, hs["mdst"]) |
              put_field(jnp.asarray(a, jnp.int32) + 1, hs["a"]) |
              put_field(jnp.asarray(b, jnp.int32) + 1, hs["b"]) |
              put_field(jnp.asarray(c, jnp.int32) + 1, hs["c"]) |
              put_field(jnp.asarray(entlen, jnp.int32), hs["entlen"]))
        words = [w0.astype(jnp.uint32)]
        epw = lay.entries_per_word
        for w in range(1, lay.msg_words):
            acc = jnp.uint32(0)
            for k in range((w - 1) * epw, min(w * epw, self.Lmax)):
                e = ent[k] if ent is not None else jnp.int32(0)
                live = jnp.asarray(k < entlen, jnp.uint32)
                acc = acc | (live * e.astype(jnp.uint32)
                             << (lay.entry_bits * (k % epw)))
            words.append(acc)
        return jnp.stack(words)

    def msg_fields(self, words):
        """u32[msg_words] -> dict of i32 header fields + ent[Lmax]."""
        lay = self.lay
        hs = lay.header_shifts
        w0 = words[0]
        f = {name: get_field(w0, hs[name]).astype(jnp.int32)
             for name in ("mtype", "mterm", "msrc", "mdst", "entlen")}
        for name in ("a", "b", "c"):
            f[name] = get_field(w0, hs[name]).astype(jnp.int32) - 1
        epw = lay.entries_per_word
        mask = (1 << lay.entry_bits) - 1
        ent = [((words[1 + k // epw] >> (lay.entry_bits * (k % epw)))
                & mask).astype(jnp.int32) for k in range(self.Lmax)]
        f["ent"] = jnp.stack(ent) if ent else jnp.zeros(0, jnp.int32)
        return f

    # ------------------------------------------------------------------
    # Bag ops (TypedBags (+)/(-), raft.tla:226-231; commutative-hash
    # identity means slot order is free — see ops/layout.py docstring)
    # ------------------------------------------------------------------

    def bag_put(self, sv: State, words) -> State:
        """WithMessage: +1 count, merging into an existing slot for the
        same message, else the first empty slot; overflow faults."""
        bag, cnt = sv["bag"], sv["cnt"]
        same = jnp.all(bag == words[None, :], axis=1) & (cnt > 0)
        exists = jnp.any(same)
        empty = cnt == 0
        first_empty = jnp.argmax(empty)            # 0 if none; guarded below
        target = jnp.where(exists, jnp.argmax(same), first_empty)
        overflow = (~exists) & (~jnp.any(empty))
        sv2 = dict(sv)
        sv2["bag"] = jnp.where(overflow, bag,
                               bag.at[target].set(words))
        sv2["cnt"] = jnp.where(overflow, cnt,
                               cnt.at[target].add(1))
        sv2["ctr"] = sv["ctr"].at[C_OVERFLOW].add(overflow.astype(jnp.int32))
        return sv2

    def bag_del_slot(self, sv: State, slot) -> State:
        """WithoutMessage on a known slot: -1 count, zero the slot at 0
        (TypedBags (-) removes zero-count elements, TypedBags.tla:59-69)."""
        cnt2 = sv["cnt"].at[slot].add(-1)
        gone = cnt2[slot] == 0
        sv2 = dict(sv)
        sv2["cnt"] = cnt2
        sv2["bag"] = jnp.where(gone,
                               sv["bag"].at[slot].set(0), sv["bag"])
        return sv2

    # ------------------------------------------------------------------
    # History / feature helpers
    # ------------------------------------------------------------------

    def _bump(self, sv: State, ctr_idx: int, n=1) -> State:
        sv2 = dict(sv)
        sv2["ctr"] = sv2["ctr"].at[ctr_idx].add(n)
        return sv2

    def _glob(self, sv: State, n) -> State:
        return self._bump(sv, C_GLOBLEN, n)

    # ------------------------------------------------------------------
    # Top-level actions (oracle: models/raft.py; SURVEY §2.4)
    # ------------------------------------------------------------------

    def restart(self, sv: State, i) -> Tuple[jnp.ndarray, State]:
        """Oracle restart(); raft.tla:401-411."""
        sv2 = dict(sv)
        sv2["st"] = sv["st"].at[i].set(FOLLOWER)
        sv2["vr"] = sv["vr"].at[i].set(0)
        sv2["vg"] = sv["vg"].at[i].set(0)
        sv2["ni"] = sv["ni"].at[i].set(jnp.ones(self.S, jnp.int32))
        sv2["mi"] = sv["mi"].at[i].set(jnp.zeros(self.S, jnp.int32))
        sv2["ci"] = sv["ci"].at[i].set(0)
        sv2["restarted"] = sv["restarted"].at[i].add(1)
        # Restart record position feeds MajorityOfClusterRestarts
        # (raft.tla:1212-1226)
        pos = sv["ctr"][C_GLOBLEN] + 1
        last = sv["feat"][F_LAST_RESTART_POS]
        gap = jnp.where(last > 0, pos - last, jnp.int32(NO_GAP))
        feat = sv["feat"].at[F_LAST_RESTART_POS].set(pos)
        feat = feat.at[F_MIN_RESTART_GAP].min(gap)
        sv2["feat"] = feat
        sv2 = self._glob(sv2, 1)
        return jnp.bool_(True), sv2

    def timeout(self, sv: State, der, i) -> Tuple[jnp.ndarray, State]:
        """Oracle timeout(); raft.tla:415-427."""
        ok = ((sv["st"][i] == FOLLOWER) | (sv["st"][i] == CANDIDATE)) \
            & (((der["config"][i] >> i) & 1) == 1)
        sv2 = dict(sv)
        sv2["st"] = sv["st"].at[i].set(CANDIDATE)
        # term-width capacity guard: packing holds max_terms + 1 (the one
        # unconstrained step past BoundedTerms); beyond that, fault AND
        # clamp so the state stays representable (the sibling overflow
        # guards' contract) — reachable only when BoundedTerms is disabled
        # (e.g. the apalache variant cfg) with too small a Bounds.max_terms
        cap = self.term_cap
        overflow = sv["ct"][i] + 1 > cap
        sv2["ct"] = sv["ct"].at[i].set(
            jnp.minimum(sv["ct"][i] + 1, cap))
        sv2["vf"] = sv["vf"].at[i].set(NIL)
        sv2["vr"] = sv["vr"].at[i].set(0)
        sv2["vg"] = sv["vg"].at[i].set(0)
        sv2["timeout"] = sv["timeout"].at[i].add(1)
        sv2["ctr"] = sv2["ctr"].at[C_OVERFLOW].add(overflow.astype(jnp.int32))
        sv2 = self._glob(sv2, 1)
        return ok, sv2

    def request_vote(self, sv: State, der, i, j) -> Tuple[jnp.ndarray, State]:
        """Oracle request_vote(); raft.tla:431-440 (includes j = i)."""
        ok = (sv["st"][i] == CANDIDATE) & \
            ((((der["config"][i] & ~sv["vr"][i]) >> j) & 1) == 1)
        words = self.pack_msg(MT_RVREQ, sv["ct"][i], i, j,
                              a=der["lastterm"][i], b=sv["llen"][i])
        sv2 = self.bag_put(sv, words)
        sv2 = self._glob(sv2, 1)
        return ok, sv2

    def append_entries(self, sv: State, der, i, j) \
            -> Tuple[jnp.ndarray, State]:
        """Oracle append_entries(); raft.tla:446-468 (≤1 entry)."""
        ok = (sv["st"][i] == LEADER) & \
            (((der["config"][i] >> j) & 1) == 1)       # i != j is static
        nij = sv["ni"][i, j]
        prev_idx = nij - 1
        in_range = (prev_idx > 0) & (prev_idx <= sv["llen"][i])
        prev_term = jnp.where(
            in_range,
            self.entry_term(sv["log"][i, jnp.clip(prev_idx - 1, 0,
                                                  self.Lcap - 1)]),
            0)
        last_entry = jnp.minimum(sv["llen"][i], nij)
        has_entry = nij <= last_entry
        ent = jnp.zeros(self.Lmax, jnp.int32).at[0].set(
            sv["log"][i, jnp.clip(nij - 1, 0, self.Lcap - 1)])
        words = self.pack_msg(
            MT_AEREQ, sv["ct"][i], i, j, a=prev_idx, b=prev_term,
            c=jnp.minimum(sv["ci"][i], last_entry),
            ent=ent, entlen=has_entry.astype(jnp.int32))
        sv2 = self.bag_put(sv, words)
        sv2 = self._glob(sv2, 1)
        return ok, sv2

    def in_quorum(self, votes, config):
        """set ∈ Quorum(config) (raft.tla:217) as the counting test
        (SURVEY §3.1 hot spot b): subset + strict majority."""
        subset = (votes & ~config) == 0
        return subset & (2 * popcount(votes, self.S) >
                         popcount(config, self.S))

    def become_leader(self, sv: State, der, i) -> Tuple[jnp.ndarray, State]:
        """Oracle become_leader(); raft.tla:472-484."""
        ok = (sv["st"][i] == CANDIDATE) & \
            self.in_quorum(sv["vg"][i], der["config"][i])
        sv2 = dict(sv)
        sv2["st"] = sv["st"].at[i].set(LEADER)
        sv2["ni"] = sv["ni"].at[i].set(
            jnp.full(self.S, 1, jnp.int32) + sv["llen"][i])
        sv2["mi"] = sv["mi"].at[i].set(jnp.zeros(self.S, jnp.int32))
        sv2 = self._bump(sv2, C_NLEADERS)
        # BecomeLeader record features (raft.tla:480-483; scenario
        # predicates §2.9)
        leaders2 = der["leaders"] | (jnp.int32(1) << i)
        feat = sv["feat"]
        bl2 = popcount(leaders2, self.S) >= 2
        feat = feat.at[F_BL2_SEEN].max(bl2.astype(jnp.int32))
        njbl = ((feat[F_ADDED_SET] >> i) & 1) == 1
        feat = feat.at[F_NJBL].max(njbl.astype(jnp.int32))
        feat = feat.at[F_LCDCC].max(feat[F_OPEN_ADD])
        sv2["feat"] = feat
        sv2 = self._glob(sv2, 1)
        return ok, sv2

    def client_request(self, sv: State, der, i, v) \
            -> Tuple[jnp.ndarray, State]:
        """Oracle client_request(); raft.tla:488-497.  No global record."""
        ok = sv["st"][i] == LEADER
        entry = self.pack_entry(sv["ct"][i], VALUE_ENTRY, jnp.int32(v))
        overflow = sv["llen"][i] >= self.Lcap
        sv2 = dict(sv)
        sv2["log"] = sv["log"].at[i, jnp.clip(sv["llen"][i], 0,
                                              self.Lcap - 1)].set(
            jnp.where(overflow, sv["log"][i, self.Lcap - 1], entry))
        sv2["llen"] = sv["llen"].at[i].add(
            jnp.where(overflow, 0, 1))
        sv2["ctr"] = sv["ctr"].at[C_NREQ].add(1) \
                              .at[C_OVERFLOW].add(overflow.astype(jnp.int32))
        return ok, sv2

    def advance_commit_index(self, sv: State, der, i) \
            -> Tuple[jnp.ndarray, State]:
        """Oracle advance_commit_index(); raft.tla:504-539."""
        ok = sv["st"][i] == LEADER
        config = der["config"][i]
        # Agree(index) = {i} ∪ {k ∈ config : matchIndex[i][k] ≥ index}
        # (raft.tla:507); agreeIndexes via the counting quorum test
        idxs = jnp.arange(1, self.Lcap + 1, dtype=jnp.int32)   # [Lcap]
        kbit = jnp.int32(1) << jnp.arange(self.S)              # [S]
        match_ge = sv["mi"][i][None, :] >= idxs[:, None]       # [Lcap, S]
        agree = (jnp.int32(1) << i) | jnp.sum(
            jnp.where(match_ge & (((config >> jnp.arange(self.S)) & 1) == 1),
                      kbit[None, :], 0), axis=1)               # [Lcap]
        in_q = self.in_quorum(agree, config) & (idxs <= sv["llen"][i])
        max_agree = jnp.max(jnp.where(in_q, idxs, 0))
        term_ok = self.entry_term(
            sv["log"][i, jnp.clip(max_agree - 1, 0, self.Lcap - 1)]) \
            == sv["ct"][i]
        new_ci = jnp.where((max_agree > 0) & term_ok, max_agree, sv["ci"][i])
        did_commit = new_ci > sv["ci"][i]
        sv2 = dict(sv)
        sv2["ci"] = sv["ci"].at[i].set(new_ci)
        # CommitEntry vs CommitMembershipChange (raft.tla:522-538): compare
        # committed entry's config against the config of the log prefix
        entry = sv["log"][i, jnp.clip(new_ci - 1, 0, self.Lcap - 1)]
        is_cfg_entry = self.entry_type(entry) == CONFIG_ENTRY
        # config of log[i][1..new_ci-1] (GetHistoricalConfig on the prefix)
        pos = jnp.arange(1, self.Lcap + 1, dtype=jnp.int32)
        etypes = self.entry_type(sv["log"][i])
        prefix_cfg_pos = jnp.max(jnp.where(
            (etypes == CONFIG_ENTRY) & (sv["log"][i] != 0) &
            (pos < new_ci), pos, 0))
        prefix_cfg = jnp.where(
            prefix_cfg_pos > 0,
            self.entry_payload(sv["log"][i, jnp.clip(prefix_cfg_pos - 1, 0,
                                                     self.Lcap - 1)]),
            jnp.int32(self.cfg.init_mask))
        is_mc = did_commit & is_cfg_entry & \
            (self.entry_payload(entry) != prefix_cfg)
        is_ce = did_commit & ~is_mc
        feat = sv["feat"]
        pos_rec = sv["ctr"][C_GLOBLEN] + 1
        feat = feat.at[F_COMMIT_SEEN].max(is_ce.astype(jnp.int32))
        cwcl_hit = is_ce & (feat[F_BL2_SEEN] == 1) & (feat[F_CWCL_POS] == 0)
        feat = feat.at[F_CWCL_POS].set(
            jnp.where(cwcl_hit, pos_rec, feat[F_CWCL_POS]))
        add_hit = is_mc & ((self.entry_payload(entry) &
                            feat[F_ADDED_SET]) != 0)
        feat = feat.at[F_ADD_COMMITS].max(add_hit.astype(jnp.int32))
        feat = feat.at[F_OPEN_ADD].set(
            jnp.where(is_mc, 0, feat[F_OPEN_ADD]))
        feat = feat.at[F_MC_COMMITS].add(is_mc.astype(jnp.int32))
        sv2["feat"] = feat
        sv2 = self._glob(sv2, did_commit.astype(jnp.int32))
        return ok, sv2

    def add_new_server(self, sv: State, der, i, j) \
            -> Tuple[jnp.ndarray, State]:
        """Oracle add_new_server(); raft.tla:542-555 — the leader resets
        j's term/votedFor (modeling shortcut) and sends CatchupRequest."""
        ok = (sv["st"][i] == LEADER) & \
            (((der["config"][i] >> j) & 1) == 0)
        sv2 = dict(sv)
        sv2["ct"] = sv["ct"].at[j].set(1)
        sv2["vf"] = sv["vf"].at[j].set(NIL)
        # mentries = SubSeq(log, nextIndex[i][j], commitIndex[i]) :550
        nij = sv["ni"][i, j]
        nent_raw = jnp.maximum(sv["ci"][i] - nij + 1, 0)
        nent = jnp.minimum(nent_raw, self.Lmax)
        gather_idx = jnp.clip(nij - 1 + jnp.arange(self.Lmax), 0,
                              self.Lcap - 1)
        ent = sv["log"][i][gather_idx]
        words = self.pack_msg(MT_CATREQ, sv["ct"][i], i, j,
                              a=sv["mi"][i, j], b=sv["ci"][i],
                              c=jnp.int32(self.cfg.num_rounds),
                              ent=ent, entlen=nent)
        sv2 = self.bag_put(sv2, words)
        sv2["ctr"] = sv2["ctr"].at[C_OVERFLOW].add(
            (nent_raw > self.Lmax).astype(jnp.int32))
        sv2 = self._bump(sv2, C_NTRIED)        # TryAddServer (raft.tla:249)
        sv2 = self._glob(sv2, 2)
        return ok, sv2

    def delete_server(self, sv: State, der, i, j) \
            -> Tuple[jnp.ndarray, State]:
        """Oracle delete_server(); raft.tla:558-569 (self-addressed
        CheckOldConfig; j != i is static)."""
        ok = (sv["st"][i] == LEADER) & \
            ((sv["st"][j] == FOLLOWER) | (sv["st"][j] == CANDIDATE)) & \
            (((der["config"][i] >> j) & 1) == 1)
        words = self.pack_msg(MT_COC, sv["ct"][i], i, i, a=0, b=j)
        sv2 = self.bag_put(sv, words)
        sv2 = self._bump(sv2, C_NTRIED)      # TryRemoveServer (raft.tla:253)
        sv2 = self._glob(sv2, 2)
        return ok, sv2

    def duplicate_message(self, sv: State, k) -> Tuple[jnp.ndarray, State]:
        """Oracle duplicate_message(); raft.tla:892-896 with the count==1
        guard of NextUnreliable (raft.tla:926-928).  No history."""
        ok = sv["cnt"][k] == 1
        sv2 = dict(sv)
        sv2["cnt"] = sv["cnt"].at[k].add(1)
        return ok, sv2

    def drop_message(self, sv: State, k) -> Tuple[jnp.ndarray, State]:
        """Oracle drop_message(); raft.tla:900-904."""
        ok = sv["cnt"][k] == 1
        sv2 = dict(sv)
        sv2["cnt"] = sv["cnt"].at[k].set(0)
        sv2["bag"] = sv["bag"].at[k].set(0)
        return ok, sv2

    # ------------------------------------------------------------------
    # Receive lanes (oracle receive(); raft.tla:842-863).  Three lanes per
    # bag slot: UpdateTerm (non-consuming), the main per-type handler
    # (branches within a type are mutually exclusive -> selects), and the
    # CheckOldConfig discard branch (which OVERLAPS the process branch,
    # models/raft.py handle_coc docstring).
    # ------------------------------------------------------------------

    def update_term(self, sv: State, der, k) -> Tuple[jnp.ndarray, State]:
        """Oracle update_term(); raft.tla:826-832 — msg NOT consumed."""
        f = self.msg_fields(sv["bag"][k])
        i = f["mdst"]
        ok = (sv["cnt"][k] > 0) & (f["mterm"] > sv["ct"][i])
        sv2 = dict(sv)
        sv2["ct"] = sv["ct"].at[i].set(f["mterm"])
        sv2["st"] = sv["st"].at[i].set(FOLLOWER)
        sv2["vf"] = sv["vf"].at[i].set(NIL)
        return ok, sv2

    def coc_discard(self, sv: State, der, k) -> Tuple[jnp.ndarray, State]:
        """HandleCheckOldConfig discard branch (raft.tla:796): guard
        ``state[i] /= Leader \\/ m.mterm = currentTerm[i]`` — overlaps the
        process branch for a Leader at the message's term."""
        f = self.msg_fields(sv["bag"][k])
        i = f["mdst"]
        ok = (sv["cnt"][k] > 0) & (f["mtype"] == MT_COC) & \
            ((sv["st"][i] != LEADER) | (f["mterm"] == sv["ct"][i]))
        sv2 = self.bag_del_slot(sv, k)
        sv2 = self._glob(sv2, 1)
        return ok, sv2

    def receive_main(self, sv: State, der, k) -> Tuple[jnp.ndarray, State]:
        """Main handler lane: per-type dispatch via selects.  Oracle twins:
        handle_rv_req / handle_rv_resp / handle_ae_req / handle_ae_resp /
        handle_cat_req / handle_cat_resp / handle_coc (process branch)."""
        lay = self.lay
        f = self.msg_fields(sv["bag"][k])
        i, j, mterm, mtype = f["mdst"], f["msrc"], f["mterm"], f["mtype"]
        has = sv["cnt"][k] > 0
        ct_i = sv["ct"][i]
        st_i = sv["st"][i]
        llen_i = sv["llen"][i]
        log_i = sv["log"][i]

        # --- per-type guards ------------------------------------------
        is_rvreq = mtype == MT_RVREQ
        is_rvresp = mtype == MT_RVRESP
        is_aereq = mtype == MT_AEREQ
        is_aeresp = mtype == MT_AERESP
        is_catreq = mtype == MT_CATREQ
        is_catresp = mtype == MT_CATRESP
        is_coc = mtype == MT_COC

        # ==============================================================
        # RVREQ (raft.tla:578-597)
        # ==============================================================
        lt = der["lastterm"][i]
        rv_logok = (f["a"] > lt) | ((f["a"] == lt) & (f["b"] >= llen_i))
        rv_grant = (mterm == ct_i) & rv_logok & \
            ((sv["vf"][i] == NIL) | (sv["vf"][i] == j))
        rvreq_ok = is_rvreq & (mterm <= ct_i)
        # mlog carries the full log (proof artifact, raft.tla:591-593);
        # llen > Lmax is only reachable with stock constraints disabled —
        # fault rather than silently truncate mlog
        rv_of = is_rvreq & (llen_i > self.Lmax)
        rv_resp = self.pack_msg(
            MT_RVRESP, ct_i, i, j, a=rv_grant.astype(jnp.int32),
            ent=log_i[:self.Lmax], entlen=jnp.minimum(llen_i, self.Lmax))

        # ==============================================================
        # RVRESP (raft.tla:836-839, 602-614)
        # ==============================================================
        rvresp_stale = mterm < ct_i
        rvresp_ok = is_rvresp & (mterm <= ct_i)
        rv_vr = sv["vr"][i] | (jnp.int32(1) << j)
        rv_vg = sv["vg"][i] | jnp.where(f["a"] == 1, jnp.int32(1) << j, 0)

        # ==============================================================
        # AEREQ branch family (raft.tla:617-700)
        # ==============================================================
        prev_idx = f["a"]
        ae_in_range = (prev_idx > 0) & (prev_idx <= llen_i)
        ae_logok = (prev_idx == 0) | (
            ae_in_range &
            (f["b"] == self.entry_term(
                log_i[jnp.clip(prev_idx - 1, 0, self.Lcap - 1)])))
        eq = mterm == ct_i
        ae_reject = (mterm < ct_i) | (eq & (st_i == FOLLOWER) & ~ae_logok)
        ae_rtf = eq & (st_i == CANDIDATE)
        ae_accept = eq & (st_i == FOLLOWER) & ae_logok
        index = prev_idx + 1
        e0 = f["ent"][0]
        have_at = llen_i >= index
        term_match = self.entry_term(
            log_i[jnp.clip(index - 1, 0, self.Lcap - 1)]) \
            == self.entry_term(e0)
        ae_already = ae_accept & ((f["entlen"] == 0) | (have_at & term_match))
        ae_conflict = ae_accept & (f["entlen"] > 0) & have_at & ~term_match
        ae_noconf = ae_accept & (f["entlen"] > 0) & (llen_i == prev_idx)
        aereq_ok = is_aereq & (ae_reject | ae_rtf | ae_already |
                               ae_conflict | ae_noconf)
        ae_resp_reject = self.pack_msg(MT_AERESP, ct_i, i, j, a=0, b=0)
        ae_resp_done = self.pack_msg(MT_AERESP, ct_i, i, j, a=1,
                                     b=prev_idx + f["entlen"])

        # ==============================================================
        # AERESP (raft.tla:705-715)
        # ==============================================================
        aeresp_stale = mterm < ct_i
        aeresp_ok = is_aeresp & (mterm <= ct_i)
        ae_succ = f["a"] == 1

        # ==============================================================
        # CATREQ (raft.tla:718-745)
        # ==============================================================
        cat_stale = mterm < ct_i
        catreq_ok = is_catreq
        # splice: prefix(min(mlogLen, Len)) ++ mentries (raft.tla:734-736)
        prefix_len = jnp.minimum(f["a"], llen_i)
        new_len = prefix_len + f["entlen"]
        cat_overflow = new_len > self.Lcap
        pos0 = jnp.arange(self.Lcap, dtype=jnp.int32)           # 0-based
        ent_idx = jnp.clip(pos0 - prefix_len, 0, self.Lmax - 1)
        spliced = jnp.where(
            pos0 < prefix_len, log_i,
            jnp.where(pos0 < new_len, f["ent"][ent_idx], 0))
        cat_resp_stale = self.pack_msg(MT_CATRESP, ct_i, i, j, a=0, b=0, c=0)
        # success reply: mterm adopted, mmatchIndex = PRE-splice length,
        # roundsLeft = mrounds - 1 (raft.tla:738-744)
        cat_resp_ok = self.pack_msg(MT_CATRESP, mterm, i, j, a=1, b=llen_i,
                                    c=f["c"] - 1)

        # ==============================================================
        # CATRESP (raft.tla:748-792); accept == NOT reject exactly
        # ==============================================================
        ci_i = sv["ci"][i]
        mi_ij = sv["mi"][i, j]
        progress = ((f["b"] != ci_i) & (f["b"] != mi_ij)) | (f["b"] == ci_i)
        cat_accept = (f["a"] == 1) & progress & (st_i == LEADER) & \
            (mterm == ct_i) & (((der["config"][i] >> j) & 1) == 0)
        catresp_ok = is_catresp
        old_nij = sv["ni"][i, j]
        more = f["c"] != 0
        # follow-up CatchupRequest (raft.tla:762-771): unprimed nextIndex,
        # NO mcommitIndex field (b=-1 = absent)
        nent2_raw = jnp.maximum(ci_i - old_nij + 1, 0)
        nent2 = jnp.minimum(nent2_raw, self.Lmax)
        cat_more_of = is_catresp & cat_accept & more & \
            (nent2_raw > self.Lmax)
        gather2 = jnp.clip(old_nij - 1 + jnp.arange(self.Lmax), 0,
                           self.Lcap - 1)
        cat_req_more = self.pack_msg(MT_CATREQ, ct_i, i, j,
                                     a=old_nij - 1, b=-1, c=f["c"],
                                     ent=log_i[gather2], entlen=nent2)
        coc_req_done = self.pack_msg(MT_COC, ct_i, i, i, a=1, b=j)

        # ==============================================================
        # COC process branch (raft.tla:795-822)
        # ==============================================================
        coc_ok = is_coc & (st_i == LEADER) & (mterm == ct_i)
        gate = der["maxcfg"][i] <= ci_i
        cfgmask = der["config"][i]
        madd = f["a"] == 1
        coc_new = jnp.where(madd, cfgmask | (jnp.int32(1) << f["b"]),
                            cfgmask & ~(jnp.int32(1) << f["b"]))
        coc_changed = coc_new != cfgmask
        coc_entry = self.pack_entry(ct_i, CONFIG_ENTRY, coc_new)
        coc_resend = self.pack_msg(MT_COC, ct_i, i, i, a=f["a"], b=f["b"])

        # ==============================================================
        # Combine: ok, then construct the successor by masked writes.
        # ==============================================================
        ok = has & (rvreq_ok | rvresp_ok | aereq_ok | aeresp_ok |
                    catreq_ok | catresp_ok | coc_ok)

        sv2 = dict(sv)

        # ---- votedFor (RVREQ grant)
        sv2["vf"] = sv["vf"].at[i].set(
            jnp.where(is_rvreq & rvreq_ok & rv_grant, j, sv["vf"][i]))
        # ---- vote sets (RVRESP non-stale)
        rvresp_live = is_rvresp & rvresp_ok & ~rvresp_stale
        sv2["vr"] = sv["vr"].at[i].set(
            jnp.where(rvresp_live, rv_vr, sv["vr"][i]))
        sv2["vg"] = sv["vg"].at[i].set(
            jnp.where(rvresp_live, rv_vg, sv["vg"][i]))
        # ---- role change (AEREQ ReturnToFollowerState)
        sv2["st"] = sv["st"].at[i].set(
            jnp.where(is_aereq & ae_rtf, FOLLOWER, sv["st"][i]))
        # ---- commitIndex (AEREQ AlreadyDone: can DECREASE, raft.tla:644)
        sv2["ci"] = sv["ci"].at[i].set(
            jnp.where(is_aereq & ae_already, f["c"], sv["ci"][i]))
        # ---- log edits
        new_log_i, new_llen_i = log_i, llen_i
        # AEREQ Conflict: truncate exactly one tail entry (raft.tla:658-665)
        trunc = is_aereq & ae_conflict
        new_log_i = jnp.where(
            trunc,
            log_i.at[jnp.clip(llen_i - 1, 0, self.Lcap - 1)].set(0),
            new_log_i)
        new_llen_i = jnp.where(trunc, llen_i - 1, new_llen_i)
        # AEREQ NoConflict: append one entry (raft.tla:668-672)
        app = is_aereq & ae_noconf
        new_log_i = jnp.where(
            app,
            log_i.at[jnp.clip(llen_i, 0, self.Lcap - 1)].set(
                jnp.where(llen_i >= self.Lcap, log_i[self.Lcap - 1], e0)),
            new_log_i)
        new_llen_i = jnp.where(app & (llen_i < self.Lcap),
                               llen_i + 1, new_llen_i)
        # CATREQ splice
        cat_live = is_catreq & ~cat_stale
        new_log_i = jnp.where(cat_live, jnp.where(cat_overflow, log_i,
                                                  spliced), new_log_i)
        new_llen_i = jnp.where(cat_live & ~cat_overflow, new_len,
                               new_llen_i)
        # COC append ConfigEntry
        coc_app = coc_ok & gate & coc_changed
        coc_of = llen_i >= self.Lcap
        new_log_i = jnp.where(
            coc_app,
            log_i.at[jnp.clip(llen_i, 0, self.Lcap - 1)].set(
                jnp.where(coc_of, log_i[self.Lcap - 1], coc_entry)),
            new_log_i)
        new_llen_i = jnp.where(coc_app & ~coc_of, llen_i + 1, new_llen_i)
        sv2["log"] = sv["log"].at[i].set(new_log_i)
        sv2["llen"] = sv["llen"].at[i].set(new_llen_i)
        # ---- currentTerm adopt (CATREQ success branch, raft.tla:737)
        sv2["ct"] = sv["ct"].at[i].set(
            jnp.where(cat_live, jnp.maximum(mterm, ct_i), sv["ct"][i]))
        # ---- next/match updates (AERESP, CATRESP-accept)
        ni_new = jnp.where(
            is_aeresp & aeresp_ok & ~aeresp_stale,
            jnp.where(ae_succ, f["b"] + 1,
                      jnp.maximum(sv["ni"][i, j] - 1, 1)),
            jnp.where(is_catresp & cat_accept, f["b"] + 1,
                      sv["ni"][i, j]))
        mi_new = jnp.where(
            (is_aeresp & aeresp_ok & ~aeresp_stale & ae_succ) |
            (is_catresp & cat_accept),
            f["b"], sv["mi"][i, j])
        sv2["ni"] = sv["ni"].at[i, j].set(ni_new)
        sv2["mi"] = sv["mi"].at[i, j].set(mi_new)
        # ---- membership-change counter + features (COC apply)
        sv2["ctr"] = sv2["ctr"].at[C_NMC].add(
            (coc_app).astype(jnp.int32))
        feat = sv2["feat"]
        add_rec = coc_app & madd
        feat = feat.at[F_ADDED_SET].set(
            jnp.where(add_rec, feat[F_ADDED_SET] | (jnp.int32(1) << f["b"]),
                      feat[F_ADDED_SET]))
        feat = feat.at[F_OPEN_ADD].max(add_rec.astype(jnp.int32))
        sv2["feat"] = feat
        sv2["ctr"] = sv2["ctr"].at[C_OVERFLOW].add(
            ((cat_live & cat_overflow) | (coc_app & coc_of) |
             rv_of | cat_more_of).astype(jnp.int32))

        # ---- bag update: consume request? send reply?
        consume = (is_rvreq & rvreq_ok) | rvresp_live | \
            (is_rvresp & rvresp_ok & rvresp_stale) | \
            (is_aereq & (ae_reject | ae_already)) | \
            (is_aeresp & aeresp_ok) | is_catreq | is_catresp | coc_ok
        # (ReturnToFollower / Conflict / NoConflict do NOT consume,
        # raft.tla:632-672)
        reply_words = jnp.where(
            is_rvreq, rv_resp,
            jnp.where(is_aereq & ae_reject, ae_resp_reject,
            jnp.where(is_aereq & ae_already, ae_resp_done,
            jnp.where(is_catreq & cat_stale, cat_resp_stale,
            jnp.where(is_catreq, cat_resp_ok,
            jnp.where(is_catresp & cat_accept & more, cat_req_more,
            jnp.where(is_catresp & cat_accept, coc_req_done,
                      coc_resend)))))))
        has_reply = (is_rvreq & rvreq_ok) | \
            (is_aereq & (ae_reject | ae_already)) | is_catreq | \
            (is_catresp & cat_accept) | (coc_ok & ~gate)
        sv3 = self.bag_del_slot(sv2, k)
        sv3 = {key: jnp.where(consume, sv3[key], sv2[key])
               if key in ("bag", "cnt") else sv3[key] for key in sv3}
        sv4 = self.bag_put(sv3, reply_words)
        sv_final = {key: jnp.where(has_reply, sv4[key], sv3[key])
                    if key in ("bag", "cnt", "ctr") else sv4[key]
                    for key in sv4}
        # ---- history record count: Reply=2, Discard=1, silent=0;
        # DiscardDirectWithMembershipChange appends Receive + the
        # AddServer/RemoveServer record = 2 (raft.tla:285-290)
        n_rec = jnp.where(has_reply | coc_app, 2,
                          jnp.where(consume, 1, 0)).astype(jnp.int32)
        sv_final["ctr"] = sv_final["ctr"].at[C_GLOBLEN].add(n_rec)
        return ok, sv_final

    # ------------------------------------------------------------------
    # Guard-only twins (the MXU guard-matrix path, engine/expand).
    #
    # The packed guard matrix reduces every lane's enabling guard to a
    # thresholded int8 dot product against a per-state FEATURE vector;
    # the message-slot families' guards are data-dependent per slot, so
    # they ARE the features — computed here once per (state, slot)
    # instead of once per (state, lane) by the vmapped kernel sweep.
    # Each guard_* below must stay in lockstep with its kernel twin's
    # ``ok`` (update_term / coc_discard / receive_main above); the
    # matmul≡lane differential tests (tests/test_guard_matmul.py) and
    # every engine's oracle differential pin the equivalence.
    # ------------------------------------------------------------------

    def guard_update_term(self, sv: State, k) -> jnp.ndarray:
        """update_term's ``ok`` without the successor (header-only)."""
        hs = self.lay.header_shifts
        w0 = sv["bag"][k, 0]
        i = get_field(w0, hs["mdst"]).astype(jnp.int32)
        mterm = get_field(w0, hs["mterm"]).astype(jnp.int32)
        return (sv["cnt"][k] > 0) & (mterm > sv["ct"][i])

    def guard_coc_discard(self, sv: State, k) -> jnp.ndarray:
        """coc_discard's ``ok`` without the successor (header-only)."""
        hs = self.lay.header_shifts
        w0 = sv["bag"][k, 0]
        i = get_field(w0, hs["mdst"]).astype(jnp.int32)
        mterm = get_field(w0, hs["mterm"]).astype(jnp.int32)
        mtype = get_field(w0, hs["mtype"]).astype(jnp.int32)
        return (sv["cnt"][k] > 0) & (mtype == MT_COC) & \
            ((sv["st"][i] != LEADER) | (mterm == sv["ct"][i]))

    def guard_receive(self, sv: State, k) -> jnp.ndarray:
        """receive_main's ``ok`` without the successor construction:
        exactly the guard sub-expressions of the main-handler lane (the
        AEREQ branch family needs the log probe and ent[0], nothing
        else — note rv_logok/rv_grant affect only the REPLY, not the
        guard, so ``der`` is not needed here)."""
        f = self.msg_fields(sv["bag"][k])
        i, mterm, mtype = f["mdst"], f["mterm"], f["mtype"]
        has = sv["cnt"][k] > 0
        ct_i = sv["ct"][i]
        st_i = sv["st"][i]
        llen_i = sv["llen"][i]
        log_i = sv["log"][i]
        rvreq_ok = (mtype == MT_RVREQ) & (mterm <= ct_i)
        rvresp_ok = (mtype == MT_RVRESP) & (mterm <= ct_i)
        # AEREQ branch family (raft.tla:617-700): guard = any branch
        prev_idx = f["a"]
        ae_in_range = (prev_idx > 0) & (prev_idx <= llen_i)
        ae_logok = (prev_idx == 0) | (
            ae_in_range &
            (f["b"] == self.entry_term(
                log_i[jnp.clip(prev_idx - 1, 0, self.Lcap - 1)])))
        eq = mterm == ct_i
        ae_reject = (mterm < ct_i) | (eq & (st_i == FOLLOWER) & ~ae_logok)
        ae_rtf = eq & (st_i == CANDIDATE)
        ae_accept = eq & (st_i == FOLLOWER) & ae_logok
        index = prev_idx + 1
        have_at = llen_i >= index
        term_match = self.entry_term(
            log_i[jnp.clip(index - 1, 0, self.Lcap - 1)]) \
            == self.entry_term(f["ent"][0])
        ae_already = ae_accept & ((f["entlen"] == 0) |
                                  (have_at & term_match))
        ae_conflict = ae_accept & (f["entlen"] > 0) & have_at & ~term_match
        ae_noconf = ae_accept & (f["entlen"] > 0) & (llen_i == prev_idx)
        aereq_ok = (mtype == MT_AEREQ) & \
            (ae_reject | ae_rtf | ae_already | ae_conflict | ae_noconf)
        aeresp_ok = (mtype == MT_AERESP) & (mterm <= ct_i)
        catreq_ok = mtype == MT_CATREQ
        catresp_ok = mtype == MT_CATRESP
        coc_ok = (mtype == MT_COC) & (st_i == LEADER) & (mterm == ct_i)
        return has & (rvreq_ok | rvresp_ok | aereq_ok | aeresp_ok |
                      catreq_ok | catresp_ok | coc_ok)

    def guard_features(self, sv: State, der: State) -> jnp.ndarray:
        """Per-state guard-feature vector φ(s), int8 [n_guard_features].

        Every family's enabling guard is a signed-weight threshold over
        these features (engine/expand builds the weight matrix), so the
        whole [states × lanes] guard grid becomes ONE int8 matmul
        φ @ W compared against the per-lane thresholds — exact by
        construction (0/±1 weights, integer accumulation).  Layout is
        ``guard_feature_offsets``; the two must move together."""
        S = self.S
        st = sv["st"]
        leader = st == LEADER
        cand = st == CANDIDATE
        folc = (st == FOLLOWER) | cand
        # BecomeLeader's quorum test, per server (vectorized in_quorum)
        blq = self.in_quorum(sv["vg"], der["config"])
        jj = jnp.arange(S)
        cfgb = ((der["config"][:, None] >> jj[None, :]) & 1) == 1
        nv = (((der["config"] & ~sv["vr"])[:, None]
               >> jj[None, :]) & 1) == 1
        ks = jnp.arange(self.K)
        ut = jax.vmap(lambda k: self.guard_update_term(sv, k))(ks)
        cocd = jax.vmap(lambda k: self.guard_coc_discard(sv, k))(ks)
        recv = jax.vmap(lambda k: self.guard_receive(sv, k))(ks)
        cnt1 = sv["cnt"] == 1
        return jnp.concatenate([
            leader, cand, folc, blq, cfgb.reshape(-1), nv.reshape(-1),
            ut, cocd, recv, cnt1]).astype(jnp.int8)

    # ------------------------------------------------------------------
    # Delta features (the value-source half of the delta-matmul
    # successor path, engine/expand delta-matrix comment; round 11).
    #
    # Every affine family's state delta is a weighted sum of these
    # per-state int32 sources (plus the constant 1 and the flat state
    # view itself), so successor generation for the declared families
    # runs as one batched scatter-as-matmul.  The features fold the
    # few data-dependent pieces the raft affine actions need:
    #
    # - BecomeLeader's three feat-lane max-updates, pre-differenced
    #   (max(old, x) - old), so the matmul ADD lands the max exactly;
    # - Timeout's term-capacity clamp (ct < cap room / its overflow);
    # - ClientRequest's append machinery: the one-hot of the append
    #   position (llen), the same one-hot scaled by the entry's term
    #   and by the old log word (so set == add with the old value
    #   cancelled), and the llen-room / overflow flags;
    # - UpdateTerm's message-indexed set-updates (round 17): per bag
    #   slot, the dst one-hot scaled by (new - old) for each of the
    #   three per-server writes, so set == add exactly and non-dst
    #   servers get zero;
    # - Restart's min-gap feature, pre-differenced the same way
    #   (min(old, gap) - old) — the nonlinear min/where pair folds
    #   into the feature, leaving the action's writes affine.
    #
    # Layout is ``delta_feature_offsets`` below; the two must move
    # together (same single-definition rule as guard_features).
    # ------------------------------------------------------------------

    def delta_features(self, sv: State, der: State) -> jnp.ndarray:
        S, Lcap = self.S, self.Lcap
        feat = sv["feat"]
        ii = jnp.arange(S)
        # BecomeLeader feat deltas, per candidate server i
        leaders2 = der["leaders"] | (jnp.int32(1) << ii)
        bl2 = (popcount(leaders2, S) >= 2).astype(jnp.int32)
        d_bl2 = jnp.maximum(feat[F_BL2_SEEN], bl2) - feat[F_BL2_SEEN]
        njbl = (feat[F_ADDED_SET] >> ii) & 1
        d_njbl = jnp.maximum(feat[F_NJBL], njbl) - feat[F_NJBL]
        d_lcdcc = (jnp.maximum(feat[F_LCDCC], feat[F_OPEN_ADD]) -
                   feat[F_LCDCC])[None]
        # Timeout's clamped term bump: room == the exact increment
        # (term_cap: the layout's representability clamp, never a
        # per-job runtime bound — see the property's docstring)
        cap = self.term_cap
        ctroom = (sv["ct"] < cap).astype(jnp.int32)
        # ClientRequest append: llen room + the append-position one-hot
        crroom = (sv["llen"] < Lcap).astype(jnp.int32)
        pos = jnp.arange(Lcap, dtype=jnp.int32)
        croh = (sv["llen"][:, None] == pos[None, :]) \
            .astype(jnp.int32)                            # [S, Lcap]
        crohct = croh * sv["ct"][:, None]
        crohold = croh * sv["log"]

        # UpdateTerm's per-slot writes, dst-one-hot scaled and
        # pre-differenced (new - old): ct[dst]=mterm, st[dst]=FOLLOWER,
        # vf[dst]=NIL land as exact matmul ADDs, zero off the dst
        def ut_row(k):
            f = self.msg_fields(sv["bag"][k])
            oh = (f["mdst"] == ii).astype(jnp.int32)          # [S]
            return (oh * (f["mterm"] - sv["ct"]),
                    oh * (jnp.int32(FOLLOWER) - sv["st"]),
                    oh * (jnp.int32(NIL) - sv["vf"]))
        utdct, utdst, utdvf = jax.vmap(ut_row)(
            jnp.arange(self.K))                               # [K, S]
        # Restart's min-gap update, pre-differenced — gap computed
        # exactly as restart() does (same pos/last/NO_GAP dance)
        pos = sv["ctr"][C_GLOBLEN] + 1
        last = feat[F_LAST_RESTART_POS]
        gap = jnp.where(last > 0, pos - last, jnp.int32(NO_GAP))
        rgap = (jnp.minimum(feat[F_MIN_RESTART_GAP], gap) -
                feat[F_MIN_RESTART_GAP])[None]
        return jnp.concatenate([
            d_bl2, d_njbl, d_lcdcc, ctroom, crroom,
            croh.reshape(-1), crohct.reshape(-1),
            crohold.reshape(-1), utdct.reshape(-1),
            utdst.reshape(-1), utdvf.reshape(-1),
            rgap]).astype(jnp.int32)

    def delta_feature_offsets(self) -> Dict[str, int]:
        """The SpecIR kernels contract: flat layout of this spec's
        ``delta_features`` vector (module-level table below)."""
        return delta_feature_offsets(self.lay)


def guard_feature_offsets(lay: Layout) -> Dict[str, int]:
    """Flat layout of ``RaftKernels.guard_features``: per-server role
    blocks (leader / candidate / follower-or-candidate / become-leader
    quorum), the two [S, S] config-bit grids (cfg[i,j], needvote[i,j],
    row-major), then the four per-slot blocks (update_term /
    coc_discard / receive / count==1).  The weight builder in
    engine/expand indexes through THIS table only, so feature order has
    a single definition."""
    S, K = lay.S, lay.K
    off = dict(leader=0, cand=S, folc=2 * S, blq=3 * S, cfg=4 * S,
               needvote=4 * S + S * S)
    base = 4 * S + 2 * S * S
    off.update(ut=base, cocd=base + K, recv=base + 2 * K,
               cnt1=base + 3 * K)
    off["total"] = base + 4 * K
    return off


def delta_feature_offsets(lay: Layout) -> Dict[str, int]:
    """Flat layout of ``RaftKernels.delta_features``: the BecomeLeader
    feat-delta blocks (bl2 / njbl per server, the scalar lcdcc), the
    Timeout term-room block, the ClientRequest append blocks (llen
    room, and the three [S, Lcap] one-hot grids: position, position ×
    term, position × old log word), the three UpdateTerm [K, S]
    dst-one-hot set-difference grids (ct / st / vf, row-major), and
    the scalar Restart min-gap difference."""
    S, Lcap, K = lay.S, lay.Lcap, lay.K
    off = dict(bl2=0, njbl=S, lcdcc=2 * S, ctroom=2 * S + 1,
               crroom=3 * S + 1, croh=4 * S + 1,
               crohct=4 * S + 1 + S * Lcap,
               crohold=4 * S + 1 + 2 * S * Lcap)
    base = 4 * S + 1 + 3 * S * Lcap
    off.update(utdct=base, utdst=base + K * S,
               utdvf=base + 2 * K * S, rgap=base + 3 * K * S)
    off["total"] = base + 3 * K * S + 1
    return off
