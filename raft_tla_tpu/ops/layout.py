"""Packed-state layout: field widths and packing spec, computed from bounds.

Device representation (SURVEY.md §7.1, revised for SoA):  a state is a
struct-of-arrays pytree rather than one bit-packed word vector — XLA
vectorizes per-field int32 arrays well and the kernels stay readable —
with bit-packing used exactly where it is load-bearing:

  * **log entries** pack to one small int each (``entry_bits`` ≤ 16):
    ``term | etype | payload`` — so entry equality (LogMatching, the
    AppendEntries conflict test) is a single integer compare
    (reference entry schema: tlc_membership/raft.tla:115, 153-155).
  * **messages** pack to ``msg_words`` uint32 words per bag slot: a
    header word (type/term/src/dst/3 generic fields/entry-count) plus
    entry words.  Field-set identity (the follow-up CatchupRequest's
    *absent* mcommitIndex, raft.tla:762-771) is preserved by storing
    every generic field with a +1 offset so "absent" = -1 = stored 0.

State *identity* (VIEW semantics, raft.cfg:30) is established by a
64/128-bit fingerprint, not by canonical bytes:  the message bag is
hashed **commutatively** (sum over slots of ``count * mix(words)``), so
slot order — and even a message split across two slots — never affects
identity, and no canonical bag sort is required anywhere (the TypedBags
(+)/(-) semantics of raft.tla:226-231 are then free).  Symmetry
(raft.cfg:29) is the min of the fingerprint over server relabelings.

All widths derive from ModelConfig bounds; tests assert round-trip
identity against the oracle representation.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property

import numpy as np

from ..config import (MT_AEREQ, MT_AERESP, MT_CATREQ, MT_CATRESP, MT_COC,
                      MT_RVREQ, MT_RVRESP, ModelConfig)


def bits_for(maxval: int) -> int:
    """Bits needed to store values 0..maxval."""
    b = 1
    while (1 << b) <= maxval:
        b += 1
    return b


# Generic message-field mapping (type tag -> which oracle tuple positions
# land in generic fields a, b, c).  src/dst positions come from the oracle's
# own table (models/raft.py _SRC_DST) so there is one source of truth.
#   RVREQ   (t, term, lastLogTerm, lastLogIndex, src, dst)       a=llt b=lli
#   RVRESP  (t, term, granted, mlog, src, dst)                   a=granted
#   AEREQ   (t, term, prevIdx, prevTerm, entries, mcommit, s, d) a=pi b=pt c=mc
#   AERESP  (t, term, success, matchIdx, src, dst)               a=succ b=mi
#   CATREQ  (t, term, logLen, entries, mcommit, src, dst, rnds)  a=ll b=mc c=r
#   CATRESP (t, term, success, matchIdx, src, dst, roundsLeft)   a=s b=mi c=rl
#   COC     (t, term, madd, mserver, src, dst)                   a=madd b=msrv
_ABC_ENT = {
    MT_RVREQ:   dict(a=2, b=3, c=None, ent=None),
    MT_RVRESP:  dict(a=2, b=None, c=None, ent=3),
    MT_AEREQ:   dict(a=2, b=3, c=5, ent=4),
    MT_AERESP:  dict(a=2, b=3, c=None, ent=None),
    MT_CATREQ:  dict(a=2, b=4, c=7, ent=3),
    MT_CATRESP: dict(a=2, b=3, c=6, ent=None),
    MT_COC:     dict(a=2, b=3, c=None, ent=None),
}


def _msg_fields():
    from ..models.raft import _SRC_DST
    return {mt: dict(src=_SRC_DST[mt][0], dst=_SRC_DST[mt][1], **abc)
            for mt, abc in _ABC_ENT.items()}


MSG_FIELDS = _msg_fields()


@dataclass(frozen=True)
class Layout:
    cfg: ModelConfig

    # ---- dimensions -----------------------------------------------------
    @cached_property
    def S(self):
        return self.cfg.n_servers

    @cached_property
    def Lmax(self):
        """Max entries carried in one message (mentries/mlog ≤ one log:
        raft.tla:444 comment limits AE to ≤1; catchup sends SubSeq of a
        frontier log, ≤ MaxLogLength; RVResp mlog likewise)."""
        return self.cfg.bounds.max_log_length

    @cached_property
    def Lcap(self):
        """Max representable per-server log: catchup splice of a ≤L prefix
        with ≤L entries (HandleCatchupRequest raft.tla:734-736) = 2L; such
        states are generated+checked but never expanded (CONSTRAINT
        semantics, SURVEY §2.8)."""
        return self.cfg.log_capacity

    @cached_property
    def K(self):
        """Bag slots: distinct messages ≤ BagCardinality ≤ MaxInFlight,
        +1 headroom for the Send that overruns the bound before pruning."""
        return self.cfg.bag_capacity

    # ---- scalar field widths -------------------------------------------
    @cached_property
    def term_bits(self):
        # terms reach max_terms + 1 (Timeout from a max_terms state is
        # generated, then pruned by BoundedTerms)
        return bits_for(self.cfg.bounds.max_terms + 1)

    @cached_property
    def server_bits(self):
        return bits_for(max(self.S - 1, 1))

    @cached_property
    def value_bits(self):
        # entry payload: raw client value (raft.cfg:11 binds small ints)
        # or a config bitmask (S bits)
        return max(bits_for(max(self.cfg.values)), self.S)

    @cached_property
    def entry_bits(self):
        # term | etype(1) | payload ; 0 == "no entry" (real terms ≥ 1)
        return self.term_bits + 1 + self.value_bits

    @cached_property
    def field_bits(self):
        # generic message fields a/b/c, stored with +1 offset (absent=-1→0):
        # values span log indices (≤ Lcap+1), terms, server ids, rounds
        fmax = max(self.Lcap + 1, self.cfg.bounds.max_terms + 1, self.S,
                   self.cfg.num_rounds)
        return bits_for(fmax + 1)

    @cached_property
    def entlen_bits(self):
        return bits_for(self.Lmax)

    # ---- message word packing ------------------------------------------
    # word0 (header): mtype | mterm | msrc | mdst | a | b | c | entlen
    # word1..      : packed entries, entries_per_word per word
    @cached_property
    def header_shifts(self):
        shifts = {}
        cur = 0
        for name, width in (("mtype", 3), ("mterm", self.term_bits),
                            ("msrc", self.server_bits),
                            ("mdst", self.server_bits),
                            ("a", self.field_bits), ("b", self.field_bits),
                            ("c", self.field_bits),
                            ("entlen", self.entlen_bits)):
            shifts[name] = (cur, width)
            cur += width
        if cur > 32:
            raise ValueError(
                f"message header needs {cur} bits > 32; bounds too large "
                f"for the single-header-word packing (split packing TBD)")
        return shifts

    @cached_property
    def entries_per_word(self):
        return 32 // self.entry_bits

    @cached_property
    def msg_words(self):
        return 1 + (self.Lmax + self.entries_per_word - 1) \
            // self.entries_per_word

    # ---- fingerprint salts ---------------------------------------------
    @cached_property
    def n_hash_streams(self):
        return 2 if self.cfg.fp128 else 1

    def describe(self) -> str:
        return (f"Layout(S={self.S}, Lmax={self.Lmax}, Lcap={self.Lcap}, "
                f"K={self.K}, entry_bits={self.entry_bits}, "
                f"msg_words={self.msg_words})")

    def __post_init__(self):
        # packed entries live in int32 log lanes: 31 usable bits
        if self.entry_bits > 31:
            raise ValueError(
                f"entry_bits={self.entry_bits} exceeds the int32 log lane")
        _ = self.header_shifts  # validate eagerly


# ---------------------------------------------------------------------------
# Generic (numpy / jnp polymorphic) bit-field helpers.  All shift amounts
# and masks are static Python ints, so these trace cleanly under jit.
# ---------------------------------------------------------------------------

def get_field(word, shift_width):
    shift, width = shift_width
    return (word >> shift) & ((1 << width) - 1)


def put_field(val, shift_width):
    shift, width = shift_width
    return (val & ((1 << width) - 1)) << shift


def put_field_checked(val, shift_width, name="field"):
    """Host-side fail-loud variant: a value outside the field width means
    the state is un-representable under the configured bounds (possible if
    a user disables the stock constraints) — fault, don't alias."""
    shift, width = shift_width
    if not 0 <= val < (1 << width):
        raise OverflowError(
            f"message {name}={val} exceeds {width}-bit packing; state is "
            f"un-representable under the configured bounds")
    return val << shift


def pack_entry(lay: Layout, term, etype, payload):
    vb = lay.value_bits
    return (term << (1 + vb)) | (etype << vb) | payload


def unpack_entry(lay: Layout, e):
    vb = lay.value_bits
    return e >> (1 + vb), (e >> vb) & 1, e & ((1 << vb) - 1)


def entry_term(lay: Layout, e):
    return e >> (1 + lay.value_bits)


def entry_type(lay: Layout, e):
    return (e >> lay.value_bits) & 1


def entry_payload(lay: Layout, e):
    return e & ((1 << lay.value_bits) - 1)


def hash_salts(lay: Layout, n_words: int, stream: int = 0) -> np.ndarray:
    """Deterministic per-position 64-bit salts for the fingerprint mix."""
    rng = np.random.RandomState(0xC0FFEE + 7919 * stream)
    lo = rng.randint(0, 1 << 32, size=n_words, dtype=np.uint64)
    hi = rng.randint(0, 1 << 32, size=n_words, dtype=np.uint64)
    return (hi << np.uint64(32)) | lo
