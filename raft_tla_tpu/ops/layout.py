"""Packed-state layout: field widths and word offsets, computed from bounds.

The packed state is a vector of ``n_words`` uint32 lanes per state
(SURVEY §7.1).  All field widths are derived from the ModelConfig bounds so
the layout is provably wide enough; tests assert round-trip identity against
the oracle representation.

Layout (word offsets in order):
  [VIEW region — hashed for the fingerprint, raft.cfg:30 `VIEW vars`]
    server words   : S words   — term | role | votedFor | commitIndex | logLen
    vote words     : S words   — votesResponded mask | votesGranted mask
    next/match     : ceil(S*S/2) words — (nextIndex, matchIndex) byte pairs
    log entries    : S * ceil(Lcap/2) words — u16 entries, 2 per word
    bag slots      : K * msg_words words — packed messages, slots sorted
                     by packed value so the (unordered) bag has a unique
                     representation (SURVEY §7.1 "load-bearing for dedup")
    bag counts     : ceil(K/4) words — u8 copy counts per slot
  [NON-VIEW region — history counters & scenario features, SURVEY §2.2:
   part of the successor computation and of constraint/scenario predicates,
   but excluded from state identity]
    history words  : per-server restarted|timeout nibbles, hadNum* nibbles
    feature words  : globalLen, scenario flags, restart positions …

A log entry packs as  term | etype | payload  in ``entry_bits`` (payload is
the value *index* for ValueEntry, the config bitmask for ConfigEntry —
raft.tla:20, 115).

A message packs into ``msg_words`` u32 words:
  word layout: mtype(3) | mterm | msource | mdest | type-specific fields,
  then up to Lmax log entries (mentries / mlog).  Absent optional fields
  (the follow-up CatchupRequest's missing mcommitIndex, raft.tla:762-771)
  get a dedicated presence bit so field-set identity is preserved.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import cached_property

from ..config import ModelConfig


def bits_for(maxval: int) -> int:
    b = 1
    while (1 << b) <= maxval:
        b += 1
    return b


@dataclass(frozen=True)
class Layout:
    cfg: ModelConfig

    # ---- scalar field widths -------------------------------------------
    @cached_property
    def S(self):
        return self.cfg.n_servers

    @cached_property
    def Lmax(self):
        # max entries ever carried in a message / appended at once
        return self.cfg.bounds.max_log_length

    @cached_property
    def Lcap(self):
        # max representable per-server log (post-splice, pre-pruning)
        return self.cfg.log_capacity

    @cached_property
    def K(self):
        return self.cfg.bag_capacity

    @cached_property
    def term_bits(self):
        # terms reach max_terms + 1 before BoundedTerms prunes expansion
        return bits_for(self.cfg.bounds.max_terms + 1)

    @cached_property
    def server_bits(self):
        # votedFor needs Nil: encode Nil as S (so range is 0..S)
        return bits_for(self.S)

    @cached_property
    def index_bits(self):
        # log indices / commitIndex / nextIndex / matchIndex: up to Lcap+1
        return bits_for(self.Lcap + 1)

    @cached_property
    def value_bits(self):
        # payload: value index (0..V-1) or config bitmask (S bits)
        return max(bits_for(max(len(self.cfg.values) - 1, 1)), self.S)

    @cached_property
    def entry_bits(self):
        return self.term_bits + 1 + self.value_bits

    @cached_property
    def count_bits(self):
        # bag copy count <= total cardinality <= K
        return bits_for(self.K)

    @cached_property
    def rounds_bits(self):
        return bits_for(max(self.cfg.num_rounds, 1))

    # ---- message packing ------------------------------------------------
    # Per-type payload bit budgets (header = type+term+src+dst is shared).
    @cached_property
    def msg_header_bits(self):
        return 3 + self.term_bits + self.server_bits + self.server_bits

    @cached_property
    def msg_payload_bits(self):
        tb, ib, eb, rb = (self.term_bits, self.index_bits, self.entry_bits,
                          self.rounds_bits)
        nbits = bits_for(self.Lmax)          # mentries length field
        per_type = {
            # RVReq: mlastLogTerm, mlastLogIndex            (raft.tla:434-439)
            "rvreq": tb + ib,
            # RVResp: granted, |mlog|, mlog                  (raft.tla:588-596)
            "rvresp": 1 + nbits + self.Lmax * eb,
            # AEReq: prevIdx, prevTerm, nentries(0/1), entry, commitIdx
            "aereq": ib + tb + 1 + eb + ib,
            # AEResp: success, matchIdx                      (raft.tla:648-654)
            "aeresp": 1 + ib,
            # CatReq: logLen, nentries, entries, commit+presence, rounds
            "catreq": ib + nbits + self.Lmax * eb + ib + 1 + rb,
            # CatResp: success, matchIdx, roundsLeft         (raft.tla:720-744)
            "catresp": 1 + ib + rb,
            # COC: madd, mserver                             (raft.tla:563-568)
            "coc": 1 + self.server_bits,
        }
        return per_type

    @cached_property
    def msg_bits(self):
        return self.msg_header_bits + max(self.msg_payload_bits.values())

    @cached_property
    def msg_words(self):
        return (self.msg_bits + 31) // 32

    # ---- word offsets ---------------------------------------------------
    @cached_property
    def off_server(self):
        return 0

    @cached_property
    def off_votes(self):
        return self.off_server + self.S

    @cached_property
    def off_nextmatch(self):
        return self.off_votes + self.S

    @cached_property
    def nextmatch_words(self):
        return (self.S * self.S + 1) // 2     # one u16 (next|match) per pair

    @cached_property
    def off_log(self):
        return self.off_nextmatch + self.nextmatch_words

    @cached_property
    def log_words_per_server(self):
        return (self.Lcap + 1) // 2           # u16 entries, 2 per word

    @cached_property
    def off_bag(self):
        return self.off_log + self.S * self.log_words_per_server

    @cached_property
    def off_counts(self):
        return self.off_bag + self.K * self.msg_words

    @cached_property
    def counts_words(self):
        return (self.K + 3) // 4

    @cached_property
    def n_view_words(self):
        return self.off_counts + self.counts_words

    # non-VIEW: history counters + scenario features
    @cached_property
    def off_hist(self):
        return self.n_view_words

    @cached_property
    def hist_words(self):
        # per-server restarted(4b)+timeout(4b) packed 4 servers/word,
        # + 1 word of hadNum{Leaders,ClientRequests,Tried,MC} bytes
        return (self.S + 3) // 4 + 1

    @cached_property
    def off_feat(self):
        return self.off_hist + self.hist_words

    # feature lanes (see ops/features.py): globalLen u16 | flags u16,
    # lastRestartPos u16 | minRestartGap u16, addedSet u8 | reserved
    @cached_property
    def feat_words(self):
        return 3

    @cached_property
    def n_words(self):
        return self.off_feat + self.feat_words

    def describe(self) -> str:
        return (f"Layout(S={self.S}, Lcap={self.Lcap}, K={self.K}, "
                f"msg_words={self.msg_words}, view={self.n_view_words}w, "
                f"total={self.n_words}w = {4 * self.n_words}B/state)")

    def __post_init__(self):
        assert self.entry_bits <= 16, "log entry must fit u16"
        assert self.term_bits + 2 + self.server_bits + 2 * self.index_bits \
            <= 32, "server word overflow"
        assert 2 * self.index_bits <= 16, "next/match pair must fit u16"
        assert self.count_bits <= 8, "bag count must fit u8"
