"""Vectorized invariants, constraints, and scenario properties.

Device twins of models/predicates.py (the oracle forms, which cite
tlc_membership/raft.tla line-by-line).  Each predicate maps a single SoA
state to a bool ("holds"); the engine vmaps them over batches of newly
discovered states.  Quantifier structure becomes broadcasting:

  * ∀ server pairs / log positions  -> [S, S, Lcap] masks + jnp.all
  * ∃ quorum ⊆ config with property P -> the counting closed form
    2·|config ∩ P| > |config| (no SUBSET enumeration; QuorumLogInv's
    "every quorum contains a good server" dualizes to "the bad set
    cannot itself contain a quorum")

TLC semantics: CONSTRAINT = don't-expand (not reject), ACTION_CONSTRAINT
= don't-generate (SURVEY §2.8); the engine applies them accordingly.
"""

from __future__ import annotations

from typing import Callable, Dict

import jax.numpy as jnp

import numpy as np

from ..config import (CANDIDATE, CONFIG_ENTRY, LEADER, MT_RVREQ, NIL,
                      ModelConfig)
from .codec import (C_GLOBLEN, C_NLEADERS, C_NMC, C_NREQ, C_NTRIED,
                    F_ADD_COMMITS, F_ADDED_SET, F_BL2_SEEN, F_COMMIT_SEEN,
                    F_CWCL_POS, F_LCDCC, F_MC_COMMITS, F_MIN_RESTART_GAP,
                    F_NJBL)
from .kernels import RaftKernels, popcount
from .layout import Layout, get_field


# ---------------------------------------------------------------------------
# Runtime search bounds (the serving layer's constant-padding ceilings,
# round 13).  Every Bounded* constraint compares a state quantity
# against ONE scalar from the model config; under a padded bucket
# ceiling those scalars become per-job device data so heterogeneous
# configs share one compiled program.  This table is the canonical
# layout of that vector: ``runtime_bounds(cfg)`` packs a config's
# bounds in RUNTIME_BOUND_KEYS order, and each Bounded* predicate reads
# index RB_* when handed an ``rtb`` vector (None keeps the historical
# baked-constant trace, bit-identical program).
# ---------------------------------------------------------------------------

RUNTIME_BOUND_KEYS = (
    "max_inflight", "max_log_length", "max_restarts", "max_timeouts",
    "max_terms", "max_client_requests", "max_tried_membership_changes",
    "max_membership_changes", "max_trace")
(RB_INFLIGHT, RB_LOGLEN, RB_RESTARTS, RB_TIMEOUTS, RB_TERMS, RB_NREQ,
 RB_TRIED, RB_NMC, RB_TRACE) = range(len(RUNTIME_BOUND_KEYS))


def runtime_bounds(cfg) -> np.ndarray:
    """A config's search bounds as the int32 vector the runtime-bounds
    predicates consume (RUNTIME_BOUND_KEYS order)."""
    b = cfg.bounds
    return np.array([
        cfg.max_inflight, b.max_log_length, b.max_restarts,
        b.max_timeouts, b.max_terms, b.max_client_requests,
        b.max_tried_membership_changes, b.max_membership_changes,
        b.max_trace], np.int32)


def _rb(rtb, idx: int, static):
    """One bound: the runtime vector's lane when present, else the
    config constant (the historical trace, unchanged)."""
    return static if rtb is None else rtb[idx]


class Predicates:
    """Predicate family bound to one (Layout, ModelConfig)."""

    def __init__(self, lay: Layout):
        self.lay = lay
        self.cfg = lay.cfg
        self.kern = RaftKernels(lay)
        self.S, self.Lcap = lay.S, lay.Lcap

    # ------------------------------------------------------------------
    # Shared derived quantities
    # ------------------------------------------------------------------

    def _prefix_ok(self, sv):
        """prefix_ok[i, j] == IsPrefix(Committed(i), log[j])
        (raft.tla:969, SequencesExt.tla:134-140).  commitIndex clamps to
        the log length, mirroring the oracle's committed()."""
        comm_len = jnp.minimum(sv["ci"], sv["llen"])          # [S]
        eq = sv["log"][:, None, :] == sv["log"][None, :, :]   # [S, S, Lcap]
        pos = jnp.arange(self.Lcap)
        within = pos[None, None, :] < comm_len[:, None, None]
        all_eq = jnp.all(eq | ~within, axis=2)
        return all_eq & (comm_len[:, None] <= sv["llen"][None, :])

    def _in_quorum(self, votes, config):
        return self.kern.in_quorum(votes, config)

    def _bits(self):
        return jnp.int32(1) << jnp.arange(self.S)

    # ------------------------------------------------------------------
    # Safety invariants (raft.tla:988-1099; oracle: models/predicates.py)
    # ------------------------------------------------------------------

    def leader_votes_quorum(self, sv, der):
        guard = sv["ctr"][C_NMC] != 0
        ct, vf = sv["ct"], sv["vf"]
        support = (ct[None, :] > ct[:, None]) | \
            ((ct[None, :] == ct[:, None]) &
             (vf[None, :] == jnp.arange(self.S)[:, None]))    # [i, j]
        voters = jnp.sum(jnp.where(support, self._bits()[None, :], 0),
                         axis=1)
        ok = ~(sv["st"] == LEADER) | self._in_quorum(voters, der["config"])
        return guard | jnp.all(ok)

    def candidate_term_not_in_log(self, sv, der):
        guard = sv["ctr"][C_NMC] != 0
        ct, vf = sv["ct"], sv["vf"]
        support = (ct[None, :] == ct[:, None]) & \
            ((vf[None, :] == jnp.arange(self.S)[:, None]) |
             (vf[None, :] == NIL))
        voters = jnp.sum(jnp.where(support, self._bits()[None, :], 0),
                         axis=1)
        electable = (sv["st"] == CANDIDATE) & \
            self._in_quorum(voters, der["config"])
        terms = self.kern.entry_term(sv["log"])               # [S, Lcap]
        occ = sv["log"] != 0
        term_in_log = jnp.any(
            occ[None, :, :] & (terms[None, :, :] == ct[:, None, None]),
            axis=(1, 2))                                      # [i]
        return guard | jnp.all(~electable | ~term_in_log)

    def election_safety(self, sv, der):
        terms = self.kern.entry_term(sv["log"])               # [S, Lcap]
        occ = sv["log"] != 0
        pos = jnp.arange(1, self.Lcap + 1)
        # maxidx[i, j] = MaxOrZero index in log[j] with term currentTerm[i]
        hit = occ[None, :, :] & \
            (terms[None, :, :] == sv["ct"][:, None, None])
        maxidx = jnp.max(jnp.where(hit, pos[None, None, :], 0), axis=2)
        mine = jnp.diagonal(maxidx)                           # [i]
        ok = ~(sv["st"] == LEADER)[:, None] | \
            (maxidx <= mine[:, None])
        return jnp.all(ok)

    def log_matching(self, sv, der):
        log = sv["log"]
        terms = self.kern.entry_term(log)
        pos = jnp.arange(self.Lcap)
        within = (pos[None, None, :] < sv["llen"][:, None, None]) & \
            (pos[None, None, :] < sv["llen"][None, :, None])
        term_eq = (terms[:, None, :] == terms[None, :, :]) & within
        entry_eq = log[:, None, :] == log[None, :, :]
        prefix_eq = jnp.cumprod(entry_eq | ~within, axis=2).astype(bool)
        return ~jnp.any(term_eq & ~prefix_eq)

    def votes_granted_inv(self, sv, der):
        """Corrected form (raft.tla:1048-1052)."""
        pref = self._prefix_ok(sv)
        vf = sv["vf"]
        my_pref = jnp.take_along_axis(
            pref, jnp.clip(vf, 0, self.S - 1)[:, None], axis=1)[:, 0]
        return jnp.all((vf == NIL) | my_pref)

    def votes_granted_inv_false(self, sv, der):
        """Ricketts' original, documented-violated (raft.tla:1038-1046);
        live in the apalache variant (SURVEY §2.7)."""
        pref = self._prefix_ok(sv)                            # [j, i]
        granted = ((sv["vg"][:, None] >> jnp.arange(self.S)[None, :])
                   & 1) == 1                                  # [i, j]
        same_term = sv["ct"][:, None] == sv["ct"][None, :]
        need = granted & same_term
        return ~jnp.any(need & ~pref.T)

    def quorum_log_inv(self, sv, der):
        """Every quorum has a member with my committed prefix — dual: the
        bad set must not itself contain a quorum (raft.tla:1056-1060)."""
        pref = self._prefix_ok(sv)                            # [i, j]
        good = jnp.sum(jnp.where(pref, self._bits()[None, :], 0), axis=1)
        bad = der["config"] & ~good
        cfg_n = popcount(der["config"], self.S)
        return jnp.all(~(2 * popcount(bad, self.S) > cfg_n))

    def more_up_to_date_correct(self, sv, der):
        lt = der["lastterm"]
        more = (lt[:, None] > lt[None, :]) | \
            ((lt[:, None] == lt[None, :]) &
             (sv["llen"][:, None] >= sv["llen"][None, :]))    # [i, j]
        pref = self._prefix_ok(sv)                            # [j, i]
        return ~jnp.any(more & ~pref.T)

    def leader_completeness(self, sv, der):
        """Corrected form (raft.tla:1089-1099): a committed entry appears
        at the same position in every higher-term current leader's log."""
        log = sv["log"]
        terms = self.kern.entry_term(log)
        comm_len = jnp.minimum(sv["ci"], sv["llen"])
        pos = jnp.arange(self.Lcap)
        committed = pos[None, :] < comm_len[:, None]          # [i, k]
        # [i, l, k]: leader l with ct[l] > entry term must hold the entry
        higher = sv["ct"][None, :, None] > terms[:, None, :]
        is_leader = (sv["st"] == LEADER)[None, :, None]
        same = log[None, :, :] == log[:, None, :]             # [i, l, k]
        within_l = pos[None, None, :] < sv["llen"][None, :, None]
        ok = ~(committed[:, None, :] & is_leader & higher) | \
            (within_l & same)
        return jnp.all(ok)

    def leader_completeness_false(self, sv, der):
        """Original form, violated under concurrent leaders
        (raft.tla:1079-1083); live in the apalache variant."""
        pref = self._prefix_ok(sv)                            # [j, i]
        is_leader = (sv["st"] == LEADER)[None, :]             # [j, i]
        return ~jnp.any(is_leader & ~pref)

    def one_at_a_time_membership_change_ok(self, sv, der):
        """OURS (SURVEY preamble phantom-name warning): at most one
        uncommitted ConfigEntry per log suffix."""
        etypes = self.kern.entry_type(sv["log"])
        occ = sv["log"] != 0
        pos = jnp.arange(self.Lcap)
        beyond = pos[None, :] >= sv["ci"][:, None]
        n_uncommitted = jnp.sum(
            (occ & (etypes == CONFIG_ENTRY) & beyond), axis=1)
        return jnp.all(n_uncommitted <= 1)

    # ------------------------------------------------------------------
    # Scenario ("test case") properties (raft.tla:1143-1278) — negated
    # reachability, read from counter/feature lanes
    # ------------------------------------------------------------------

    def bounded_trace(self, sv, der, rtb=None):
        return sv["ctr"][C_GLOBLEN] <= \
            _rb(rtb, RB_TRACE, self.cfg.bounds.max_trace)

    def first_become_leader(self, sv, der):
        return sv["ctr"][C_NLEADERS] < 1

    def first_commit(self, sv, der):
        return jnp.all(sv["ci"] == 0)

    def first_restart(self, sv, der):
        return jnp.all(sv["restarted"] < 2)

    def leadership_change(self, sv, der):
        return sv["ctr"][C_NLEADERS] < 2

    def membership_change(self, sv, der):
        return sv["ctr"][C_NMC] < 1

    def multiple_membership_changes(self, sv, der):
        return sv["ctr"][C_NMC] < 2

    def concurrent_leaders(self, sv, der):
        return popcount(der["leaders"], self.S) < 2

    def entry_committed(self, sv, der):
        return sv["feat"][F_COMMIT_SEEN] == 0

    def commit_when_concurrent_leaders(self, sv, der):
        """raft.tla:1165-1176 via the F_CWCL_POS feature lane."""
        two_now = popcount(der["leaders"], self.S) >= 2
        p = sv["feat"][F_CWCL_POS]
        witness = (p > 0) & (sv["ctr"][C_GLOBLEN] >= p + 2)
        return ~(two_now & witness)

    def majority_of_cluster_restarts(self, sv, der):
        """raft.tla:1212-1226 via restart-position feature lanes."""
        llen = sv["llen"]
        nontrivial = jnp.any(
            (llen[:, None] >= 2) & (llen[None, :] >= 1) &
            (jnp.arange(self.S)[:, None] != jnp.arange(self.S)[None, :]))
        restarted_set = jnp.sum(
            jnp.where(sv["restarted"] >= 1, self._bits(), 0))
        maj = 2 * popcount(restarted_set, self.S) > self.S
        gaps_ok = sv["feat"][F_MIN_RESTART_GAP] >= 6
        return ~(nontrivial & maj & gaps_ok)

    def add_successful(self, sv, der):
        return sv["feat"][F_ADDED_SET] == 0

    def membership_change_commits(self, sv, der):
        return sv["feat"][F_MC_COMMITS] < 1

    def multiple_membership_changes_commit(self, sv, der):
        return sv["feat"][F_MC_COMMITS] < 2

    def add_commits(self, sv, der):
        return sv["feat"][F_ADD_COMMITS] == 0

    def newly_joined_become_leader(self, sv, der):
        return sv["feat"][F_NJBL] == 0

    def leader_changes_during_conf_change(self, sv, der):
        return sv["feat"][F_LCDCC] == 0

    # ------------------------------------------------------------------
    # Constraints (raft.tla:1105-1137) — expansion gates
    # ------------------------------------------------------------------

    def bounded_in_flight_messages(self, sv, der, rtb=None):
        return jnp.sum(sv["cnt"]) <= \
            _rb(rtb, RB_INFLIGHT, self.cfg.max_inflight)

    def bounded_request_vote(self, sv, der):
        mtype = get_field(sv["bag"][:, 0],
                          self.lay.header_shifts["mtype"]).astype(jnp.int32)
        return jnp.all(~((mtype == MT_RVREQ) & (sv["cnt"] > 1)))

    def bounded_log_size(self, sv, der, rtb=None):
        return jnp.all(sv["llen"] <=
                       _rb(rtb, RB_LOGLEN,
                           self.cfg.bounds.max_log_length))

    def bounded_restarts(self, sv, der, rtb=None):
        return jnp.all(sv["restarted"] <=
                       _rb(rtb, RB_RESTARTS,
                           self.cfg.bounds.max_restarts))

    def bounded_timeouts(self, sv, der, rtb=None):
        return jnp.all(sv["timeout"] <=
                       _rb(rtb, RB_TIMEOUTS,
                           self.cfg.bounds.max_timeouts))

    def bounded_terms(self, sv, der, rtb=None):
        return jnp.all(sv["ct"] <=
                       _rb(rtb, RB_TERMS, self.cfg.bounds.max_terms))

    def bounded_client_requests(self, sv, der, rtb=None):
        return sv["ctr"][C_NREQ] <= \
            _rb(rtb, RB_NREQ, self.cfg.bounds.max_client_requests)

    def bounded_tried_membership_changes(self, sv, der, rtb=None):
        return sv["ctr"][C_NTRIED] <= \
            _rb(rtb, RB_TRIED,
                self.cfg.bounds.max_tried_membership_changes)

    def bounded_membership_changes(self, sv, der, rtb=None):
        return sv["ctr"][C_NMC] <= \
            _rb(rtb, RB_NMC, self.cfg.bounds.max_membership_changes)

    def elections_uncontested(self, sv, der):
        return jnp.sum((sv["st"] == CANDIDATE).astype(jnp.int32)) <= 1

    def clean_start_until_first_request(self, sv, der):
        pre = (sv["ctr"][C_NLEADERS] < 1) & (sv["ctr"][C_NREQ] < 1)
        cond = jnp.all(sv["restarted"] == 0) & \
            (jnp.sum(sv["timeout"]) <= 1) & \
            (jnp.sum((sv["st"] == CANDIDATE).astype(jnp.int32)) <= 1)
        return ~pre | cond

    def clean_start_until_two_leaders(self, sv, der):
        pre = sv["ctr"][C_NLEADERS] < 2
        cond = (jnp.sum(sv["restarted"]) <= 1) & \
            (jnp.sum(sv["timeout"]) <= 2)
        return ~pre | cond

    def clean_first_leader_election(self, sv, der):
        """apalache_no_membership/raft.tla:766-770."""
        pre = sv["ctr"][C_NLEADERS] < 1
        cond = jnp.all(sv["restarted"] == 0) & \
            (jnp.sum((sv["st"] == CANDIDATE).astype(jnp.int32)) <= 1)
        return ~pre | cond

    def commit_when_concurrent_leaders_constraint(self, sv, der):
        """Weak punctuated-search pruning (raft.tla:1182-1186) via the
        F_BL2_SEEN feature lane."""
        return (sv["ctr"][C_GLOBLEN] < 20) | (sv["feat"][F_BL2_SEEN] == 1)

    # ------------------------------------------------------------------
    # Registries (cfg-name -> callable), mirroring models/predicates.py
    # ------------------------------------------------------------------

    def invariant_fn(self, name: str) -> Callable:
        if self.cfg.apalache_variant and name in (
                "VotesGrantedInv", "LeaderCompleteness"):
            name = name + "_false"
        return INVARIANTS[name].__get__(self)

    def constraint_fn(self, name: str) -> Callable:
        """Every returned callable is uniformly ``(sv, der, rtb=None)``:
        bound-comparing constraints read the runtime-bounds vector when
        one is passed (the padded-ceiling serving path), the rest
        ignore it — so engine call sites thread ``rtb``
        unconditionally."""
        fn = CONSTRAINTS[name].__get__(self)
        try:
            import inspect
            takes_rtb = "rtb" in inspect.signature(fn).parameters
        except (TypeError, ValueError):       # pragma: no cover
            takes_rtb = False
        if takes_rtb:
            return fn
        return lambda sv, der, rtb=None: fn(sv, der)

    def action_fn(self, name: str) -> Callable:
        """ACTION_CONSTRAINT device form: (parent_sv, cand_sv) -> ok
        (raft.tla:1207-1210 semantics — a violating transition is not
        generated).  Moved here from the engines' hard-wired _act_ok so
        the name registry is part of the spec surface."""
        try:
            return ACTION_CONSTRAINTS_V[name].__get__(self)
        except KeyError:
            raise KeyError(
                f"unknown action constraint {name!r} for spec 'raft'; "
                f"known: {', '.join(sorted(ACTION_CONSTRAINTS_V))}"
            ) from None

    def commit_when_concurrent_leaders_action_constraint(
            self, parent_sv, cand_sv):
        """raft.tla:1207-1210: past trace length 20, kill transitions
        that leave any candidate alive (punctuated-search pruning)."""
        deep = parent_sv["ctr"][C_GLOBLEN] >= 20
        no_cand = jnp.all(cand_sv["st"] != CANDIDATE)
        return ~deep | no_cand


INVARIANTS: Dict[str, Callable] = {
    "LeaderVotesQuorum": Predicates.leader_votes_quorum,
    "CandidateTermNotInLog": Predicates.candidate_term_not_in_log,
    "ElectionSafety": Predicates.election_safety,
    "LogMatching": Predicates.log_matching,
    "VotesGrantedInv": Predicates.votes_granted_inv,
    "VotesGrantedInv_false": Predicates.votes_granted_inv_false,
    "QuorumLogInv": Predicates.quorum_log_inv,
    "MoreUpToDateCorrect": Predicates.more_up_to_date_correct,
    "LeaderCompleteness": Predicates.leader_completeness,
    "LeaderCompleteness_false": Predicates.leader_completeness_false,
    "OneAtATimeMembershipChangeOK":
        Predicates.one_at_a_time_membership_change_ok,
    "BoundedTrace": Predicates.bounded_trace,
    "FirstBecomeLeader": Predicates.first_become_leader,
    "FirstCommit": Predicates.first_commit,
    "FirstRestart": Predicates.first_restart,
    "LeadershipChange": Predicates.leadership_change,
    "MembershipChange": Predicates.membership_change,
    "MultipleMembershipChanges": Predicates.multiple_membership_changes,
    "ConcurrentLeaders": Predicates.concurrent_leaders,
    "EntryCommitted": Predicates.entry_committed,
    "CommitWhenConcurrentLeaders":
        Predicates.commit_when_concurrent_leaders,
    "MajorityOfClusterRestarts": Predicates.majority_of_cluster_restarts,
    "AddSucessful": Predicates.add_successful,
    "MembershipChangeCommits": Predicates.membership_change_commits,
    "MultipleMembershipChangesCommit":
        Predicates.multiple_membership_changes_commit,
    "AddCommits": Predicates.add_commits,
    "NewlyJoinedBecomeLeader": Predicates.newly_joined_become_leader,
    "LeaderChangesDuringConfChange":
        Predicates.leader_changes_during_conf_change,
}

# The scenario ("Test cases") properties of raft.cfg:51-76 — negated
# reachability targets, the subset of INVARIANTS whose "violation" is a
# wanted witness rather than a bug.  This is the ONE registry the CLI
# surfaces (`trace`/`simulate` --target help + validation) and the sim
# engine samples toward: a predicate added here is automatically
# advertised and targetable, so the help text cannot drift from the
# implementation (it used to be a hand-kept string).
SCENARIO_PROPERTIES = (
    "BoundedTrace",
    "FirstBecomeLeader",
    "FirstCommit",
    "FirstRestart",
    "LeadershipChange",
    "MembershipChange",
    "MultipleMembershipChanges",
    "ConcurrentLeaders",
    "EntryCommitted",
    "CommitWhenConcurrentLeaders",
    "MajorityOfClusterRestarts",
    "AddSucessful",
    "MembershipChangeCommits",
    "MultipleMembershipChangesCommit",
    "AddCommits",
    "NewlyJoinedBecomeLeader",
    "LeaderChangesDuringConfChange",
)

for _nm in SCENARIO_PROPERTIES:
    assert _nm in INVARIANTS, \
        f"scenario property {_nm!r} has no device predicate"

ACTION_CONSTRAINTS_V: Dict[str, Callable] = {
    "CommitWhenConcurrentLeaders_action_constraint":
        Predicates.commit_when_concurrent_leaders_action_constraint,
}

CONSTRAINTS: Dict[str, Callable] = {
    "BoundedInFlightMessages": Predicates.bounded_in_flight_messages,
    "BoundedRequestVote": Predicates.bounded_request_vote,
    "BoundedLogSize": Predicates.bounded_log_size,
    "BoundedRestarts": Predicates.bounded_restarts,
    "BoundedTimeouts": Predicates.bounded_timeouts,
    "BoundedTerms": Predicates.bounded_terms,
    "BoundedClientRequests": Predicates.bounded_client_requests,
    "BoundedTriedMembershipChanges":
        Predicates.bounded_tried_membership_changes,
    "BoundedMembershipChanges": Predicates.bounded_membership_changes,
    "ElectionsUncontested": Predicates.elections_uncontested,
    "CleanStartUntilFirstRequest":
        Predicates.clean_start_until_first_request,
    "CleanStartUntilTwoLeaders":
        Predicates.clean_start_until_two_leaders,
    "CleanFirstLeaderElection":
        Predicates.clean_first_leader_election,
    "CommitWhenConcurrentLeaders_constraint":
        Predicates.commit_when_concurrent_leaders_constraint,
}
